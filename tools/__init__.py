"""Operator/CI tooling (runnable scripts; importable from the repo
root for bench.py and the test suite)."""
