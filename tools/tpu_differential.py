"""On-TPU differential: cached-valset kernel vs the ZIP-215 oracle.

The authoritative correctness check for ops.ed25519_cached on real
hardware (the Pallas-interpret CPU path is compile-prohibitive for
this kernel — see tests/test_ed25519_cached.py). Covers valid rows,
tampered sig/msg, S>=L malleability, bad pubkey, small-order identity,
the -0 sign encoding, non-canonical y, and an off-curve R.

Run: python tools/tpu_differential.py   (needs the TPU; ~2 min cold)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import numpy as np
from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_cached as ec

cases = []
for i in range(8):
    seed = bytes([i + 1]) * 32
    pk = ed.pubkey_from_seed(seed)
    m = b"msg-%d" % i
    s = ed.sign(seed, m)
    cases.append((pk, m, s))
# tampered sig / msg / malleable S / bad pubkey
pk, m, s = cases[2]; cases[2] = (pk, m, s[:10] + bytes([s[10] ^ 1]) + s[11:])
pk, m, s = cases[5]; cases[5] = (pk, m + b"t", s)
pk, m, s = cases[6]
cases[6] = (pk, m, s[:32] + int.to_bytes(
    int.from_bytes(s[32:], "little") + ed.L, 32, "little"))
cases[7] = (b"\xff" * 32, b"m", cases[7][2])
# small-order / zero-s / noncanonical-R edges
ident = ed.pt_compress(ed.IDENT)
cases.append((ident, b"m", ident + b"\x00" * 32))
ident_neg = ident[:31] + bytes([ident[31] | 0x80])
cases.append((ident, b"m", ident_neg + b"\x00" * 32))
for y in range(2, 60):
    u, v = (y * y - 1) % ed.P, (ed.D * y * y + 1) % ed.P
    ok, x = ed._sqrt_ratio(u, v)
    if ok:
        enc_nc = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32, "little")
        break
seed0 = bytes(32)
pk0 = ed.pubkey_from_seed(seed0)
s0 = ed.sign(seed0, b"x")
cases.append((pk0, b"x", enc_nc + s0[32:]))
cases.append((pk0, b"x", int.to_bytes(2, 32, "little") + s0[32:]))  # off-curve R

pubs, msgs, sigs = (list(z) for z in zip(*cases))
got = ec.verify_batch_cached(pubs, msgs, sigs)
exp = np.asarray([ed.verify(p, m, s) for p, m, s in cases])
print("got:", got.astype(int))
print("exp:", exp.astype(int))
assert (got == exp).all(), np.nonzero(got != exp)
print("CACHED KERNEL: all", len(cases), "cases match oracle")
