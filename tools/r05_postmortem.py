"""The r05 regression post-mortem's reproducible instrument run.

BENCH_r05 regressed every streaming config (cfg2 8.6->15.2 ms, cfg3
110->416 ms, cfg4 121k->54k sigs/sec) while the headline improved.
POSTMORTEM_r05.md holds the findings; this tool generates the
measured half of the evidence on ANY host, TPU or not:

1. **Per-flush fixed-overhead bound** — the r05 suspect-#1 question
   ("did flush-path instrumentation eat the streaming configs?")
   answered by measurement: the always-on ledger + disabled trace
   hooks cost microseconds per flush (bench.disabled_flush_bookkeeping_us),
   orders of magnitude under the ms-scale regressions.
2. **Stage-delta tables from real traces** — two traced runs of the
   verify plane's flush pipeline (host path, so it runs in the CPU
   container) with IDENTICAL flush composition: "r05-repro" carries a
   controlled 2 ms/flush overhead injected through the
   `verifyplane.dispatch` failpoint (the exact regression an
   instrumentation bug on the flush path would cause), "fixed" is the
   shipped code. `trace_report.diff_report` aligns them — the same
   tables `--diff` produces for cfg2/cfg4 traces on the TPU host —
   pinpointing the overhead to the pack stage and showing the
   recovery; the flush ledger summarizes both runs.
3. **Per-flush amortization bound** — the same workload serialized
   (one flush per row) vs coalesced (one flush per burst), bounding
   the whole per-flush fixed cost from real traces.

Usage:
    python tools/r05_postmortem.py [--sigs 64] [--json] \
        [--trace-out PREFIX]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import disabled_flush_bookkeeping_us  # noqa: E402
from tools import trace_report  # noqa: E402


def _plane_run(n_sigs: int, serialized: bool,
               inject_per_flush_s: float = 0.0):
    """One traced verify-plane run (host path). Returns (trace events,
    ledger summary, wall_ms)."""
    import time

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.libs import failpoints as fp
    from cometbft_tpu.libs import tracing
    from cometbft_tpu.verifyplane import VerifyPlane

    keys = [PrivKey.generate((8100 + i).to_bytes(4, "big") + b"\x77" * 28)
            for i in range(n_sigs)]
    subs = [(k.pub_key(), b"pm-%d" % i, k.sign(b"pm-%d" % i))
            for i, k in enumerate(keys)]
    plane = VerifyPlane(window_ms=2.0, use_device=False)
    plane.start()
    tracing.enable(capacity=1 << 16)
    if inject_per_flush_s:
        # the r05-repro regime: a controlled per-flush overhead on the
        # dispatch path — exactly what a flush-path instrumentation
        # regression would add. delay (unlike raise) keeps the verdict
        # path identical; only the per-flush cost moves. The ARMED/
        # FIRED warnings are deliberate instrumentation here, not a
        # fault under debug — keep the output to the tables.
        import logging

        logging.getLogger("cometbft_tpu.libs.failpoints").setLevel(
            logging.ERROR)
        fp.arm("verifyplane.dispatch", "delay", inject_per_flush_s)
    try:
        t0 = time.perf_counter()
        if serialized:
            # one flush per row: per-flush fixed costs paid n_sigs
            # times (the amplification regime)
            for p, m, s in subs:
                assert plane.submit(p, m, s).result(10) == (True,)
        else:
            # the window coalesces the burst into few flushes
            futs = [plane.submit(p, m, s) for p, m, s in subs]
            assert all(all(f.result(10)) for f in futs)
        wall_ms = (time.perf_counter() - t0) * 1e3
        events = tracing.export_chrome()["traceEvents"]
    finally:
        if inject_per_flush_s:
            fp.disarm("verifyplane.dispatch")
        tracing.disable()
        plane.stop()
    return events, plane.ledger.summary(), wall_ms


def run(n_sigs: int = 64, trace_out: str = "",
        inject_ms: float = 2.0) -> dict:
    # (1) r05-repro vs fixed: identical workload + flush composition,
    # the repro side carrying inject_ms of per-flush overhead
    ev_r, led_r, wall_r = _plane_run(n_sigs, serialized=True,
                                     inject_per_flush_s=inject_ms / 1e3)
    ev_f, led_f, wall_f = _plane_run(n_sigs, serialized=True)
    # (2) amortization bound: the same work coalesced into few flushes
    ev_c, led_c, wall_c = _plane_run(n_sigs, serialized=False)
    if trace_out:
        for tag, ev in (("r05repro", ev_r), ("fixed", ev_f),
                        ("coalesced", ev_c)):
            with open(f"{trace_out}.{tag}.trace.json", "w") as f:
                json.dump({"traceEvents": ev}, f)
    rep_r = trace_report.stage_report(ev_r)
    rep_f = trace_report.stage_report(ev_f)
    rep_c = trace_report.stage_report(ev_c)
    # A = r05-repro, B = fixed: the recovery table ("where did the ms
    # go" — the pack stage gives inject_ms back per flush); the reverse
    # direction is what the regression looked like when it landed
    diff_recovery = trace_report.diff_report(rep_r, rep_f)
    diff_regression = trace_report.diff_report(rep_f, rep_r)

    def per_flush(rep, led):
        tot = sum(r["total_ms"] for r in rep["stages"]
                  if r["stage"].startswith("plane."))
        return round(tot / max(1, led["flushes"]), 4)

    return {
        "workload": {"sigs": n_sigs, "path": "host (no accelerator)",
                     "injected_per_flush_ms": inject_ms,
                     "wall_ms_r05repro": round(wall_r, 1),
                     "wall_ms_fixed": round(wall_f, 1),
                     "wall_ms_coalesced": round(wall_c, 1)},
        "hook_cost_us": disabled_flush_bookkeeping_us(k=5000),
        "stage_tables": {"r05repro": rep_r["stages"],
                         "fixed": rep_f["stages"],
                         "coalesced": rep_c["stages"]},
        "diff_recovery": diff_recovery,
        "diff_regression": diff_regression,
        "ledger": {"r05repro": led_r, "fixed": led_f,
                   "coalesced": led_c},
        "per_flush_host_ms": {"fixed_serialized": per_flush(rep_f,
                                                            led_f),
                              "coalesced": per_flush(rep_c, led_c)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="r05 post-mortem instrument run (host-path)")
    ap.add_argument("--sigs", type=int, default=64)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--trace-out", default="",
                    help="also write PREFIX.{r05repro,fixed,coalesced}"
                         ".trace.json")
    ap.add_argument("--inject-ms", type=float, default=2.0,
                    help="per-flush overhead injected into the "
                         "r05-repro run (default 2.0)")
    args = ap.parse_args(argv)
    doc = run(args.sigs, args.trace_out, args.inject_ms)
    if args.json:
        print(json.dumps(doc, indent=1))
        return 0
    hc = doc["hook_cost_us"]
    print(f"workload: {doc['workload']}")
    print(f"suspect-#1 hook cost (tracing disabled): "
          f"{hc['ledger_bookkeeping_us_per_flush']} us ledger + "
          f"{hc['disabled_span_us_per_call']} us span, per flush")
    print(f"per-flush host cost (fixed code): "
          f"{doc['per_flush_host_ms']['fixed_serialized']} ms at "
          f"{doc['ledger']['fixed']['flushes']} flushes (1 row each) "
          f"vs {doc['per_flush_host_ms']['coalesced']} ms at "
          f"{doc['ledger']['coalesced']['flushes']} flush(es) "
          f"coalesced")
    print()
    print(trace_report.format_diff(doc["diff_recovery"], "r05-repro",
                                   "fixed"))
    print()
    regs = doc["diff_regression"]["regressions"]
    print(f"reverse direction (fixed -> r05-repro) flags: {regs}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
