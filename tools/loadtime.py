"""loadtime: open-loop transaction load generator (test/loadtime analog).

Open-loop means HONEST: txs are injected at fixed target times derived
only from the configured rate — never gated on the previous response —
so the measured latencies include queueing delay under overload instead
of the generator politely slowing down to whatever the node can absorb
(closed-loop generators hide exactly the collapse this tool exists to
measure; see test/loadtime in the reference repo).

Three modes:

  * in-process (default): a LocalNetwork of real Nodes (kvstore app,
    fast timeouts, admission control + sigtx verification on) floods
    node 0's broadcast_tx path while the net commits blocks — reports
    offered/accepted txs/sec, commits/sec, CheckTx latency percentiles,
    and every overload verdict observed;
  * --rpc URL: drive a LIVE node's JSON-RPC broadcast_tx_sync with the
    same open-loop discipline (urllib, thread pool sized to the rate);
  * --smoke: tier-1 mode — mempool + admission + host verify plane
    only (no consensus, NO jax import), tiny rates, finishes in a few
    seconds; exists so CI catches loadtime rot and keeps the
    overload verdict path (explicit OVERLOADED codes with retry hints)
    continuously exercised.

Every mode prints one JSON document on stdout.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _percentiles(xs):
    from cometbft_tpu.libs.quantiles import wait_summary_ms

    return wait_summary_ms(xs)


class OpenLoopRun:
    """Aggregates one open-loop run's per-tx outcomes."""

    def __init__(self):
        self._lock = threading.Lock()
        self.offered = 0
        self.codes: dict = {}
        self.lat_ms = []
        self.overload_logs = []
        self.late = 0  # injections that missed their target slot >50ms

    def record(self, code, lat_ms: float, log: str = "") -> None:
        with self._lock:
            self.offered += 1
            self.codes[code] = self.codes.get(code, 0) + 1
            self.lat_ms.append(lat_ms)
            if code == 1001 and len(self.overload_logs) < 8:
                self.overload_logs.append(log)

    def report(self, wall_s: float, extra=None) -> dict:
        from cometbft_tpu.abci import types as abci

        accepted = self.codes.get(abci.CODE_TYPE_OK, 0)
        overloaded = self.codes.get(abci.CODE_TYPE_OVERLOADED, 0)
        out = {
            "offered": self.offered,
            "accepted": accepted,
            "overloaded": overloaded,
            "rejected_other": self.offered - accepted - overloaded,
            "offered_tx_per_s": round(self.offered / wall_s, 1)
            if wall_s else 0.0,
            "accepted_tx_per_s": round(accepted / wall_s, 1)
            if wall_s else 0.0,
            "checktx_latency": _percentiles(self.lat_ms),
            "codes": {str(k): v for k, v in sorted(self.codes.items())},
            "late_injections": self.late,
            "overload_log_samples": self.overload_logs,
            "wall_s": round(wall_s, 2),
        }
        if extra:
            out.update(extra)
        return out


def open_loop(rate: float, duration: float, make_tx, submit,
              run: OpenLoopRun, workers: int = 4) -> float:
    """Fire `rate * duration` submissions at fixed target times on a
    small worker pool (a slow response must not stall the schedule —
    that is the whole point). Returns the wall seconds elapsed."""
    import queue as _q

    count = int(round(rate * duration))
    q: "_q.Queue" = _q.Queue()
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            try:
                k, tx = q.get(timeout=0.2)
            except _q.Empty:
                continue
            t = time.perf_counter()
            try:
                code, log = submit(tx)
            except Exception as e:  # noqa: BLE001 - counted, not fatal
                code, log = -1, repr(e)[:120]
            run.record(code, (time.perf_counter() - t) * 1000, log)
            q.task_done()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, workers))]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    for k in range(count):
        target = t0 + k / rate
        lag = target - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        elif lag < -0.05:
            run.late += 1
        q.put((k, make_tx(k)))
    q.join()
    stop.set()
    return time.perf_counter() - t0


# --------------------------------------------------------------------------
# tx builders
# --------------------------------------------------------------------------


def make_tx_builder(signed: bool, size: int, tag: str = "lt"):
    if not signed:
        return lambda k: (b"%s-%d=" % (tag.encode(), k)).ljust(size, b"x")
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.mempool import sigtx

    priv = PrivKey.generate(b"loadtime-sigtx-key" + b"\x00" * 14)

    def build(k: int) -> bytes:
        payload = (b"%s-%d=" % (tag.encode(), k)).ljust(size, b"x")
        return sigtx.wrap(priv, payload)

    return build


# --------------------------------------------------------------------------
# --smoke: mempool + admission + host verify plane, no consensus, no jax
# --------------------------------------------------------------------------


def run_smoke(rate: float = 400.0, duration: float = 2.0,
              pool_size: int = 64) -> dict:
    """Host-only miniature: floods a Mempool (kvstore app, admission
    control, sigtx verification through a host-path verify plane) past
    its watermarks, so BOTH outcomes are exercised: accepted txs AND
    explicit OVERLOADED verdicts with retry hints. Asserts jax was
    never imported — this is the tier-1 guard's contract."""
    jax_loaded_before = "jax" in sys.modules

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config.config import MempoolConfig
    from cometbft_tpu.mempool.mempool import Mempool
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    mcfg = MempoolConfig(size=pool_size, high_watermark=0.5,
                         low_watermark=0.3, max_inflight_checktx=8,
                         retry_after_ms=100.0)
    mp = Mempool(KVStoreApplication(), max_txs=mcfg.size,
                 verify_sigs=True)
    mp.admission = mcfg.build_admission(fill_fn=mp.fill_fraction)
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=100.0)
    plane.start()
    set_global_plane(plane)
    run = OpenLoopRun()
    try:
        wall = open_loop(rate, duration,
                         make_tx_builder(True, 32, tag="smoke"),
                         lambda tx: _submit_mempool(mp, tx), run,
                         workers=8)
    finally:
        set_global_plane(None)
        plane.stop()
    pstats = plane.stats()
    rep = run.report(wall, extra={
        "mode": "smoke (mempool+plane only, no consensus, no jax)",
        "plane": {"lane_rows": pstats["lane_rows"],
                  "sheds": pstats["sheds"],
                  "lane_waits": plane.lane_wait_stats()},
        "admission": mp.admission.stats(),
        # already-loaded jax (a test process that ran device suites
        # first) is not OUR import — the contract is that the smoke
        # path itself never pulls it in
        "jax_imported": "jax" in sys.modules and not jax_loaded_before,
    })
    # smoke contract: the flood must overfill the tiny pool, so the
    # overload path really ran — and jax must never load
    assert rep["accepted"] > 0, "smoke flood accepted nothing"
    assert rep["overloaded"] > 0, \
        "smoke flood never tripped admission/shedding"
    assert all("retry_after_ms=" in s for s in rep["overload_log_samples"])
    assert not rep["jax_imported"], "--smoke must not import jax"
    return rep


def _submit_mempool(mp, tx: bytes):
    resp = mp.check_tx(tx)
    return resp.code, resp.log


# --------------------------------------------------------------------------
# in-process full-node mode
# --------------------------------------------------------------------------


def run_inprocess(rate: float, duration: float, n_nodes: int = 4,
                  signed: bool = True, size: int = 32,
                  plane: bool = True) -> dict:
    """A real LocalNetwork committing blocks while node 0 is flooded
    through broadcast_tx — the sustained-consensus-throughput shape
    (ROADMAP item 5) without the TCP stack in the way."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config.config import MempoolConfig
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    fast = TimeoutParams(propose=0.4, propose_delta=0.1,
                         prevote=0.2, prevote_delta=0.1,
                         precommit=0.2, precommit_delta=0.1,
                         commit=0.05)
    privs = [PrivKey.generate(bytes([i + 1]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("loadtime-chain", vals)
    net = LocalNetwork()
    nodes = []
    mcfg = MempoolConfig()
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast, mempool_config=mcfg)
        net.add(node)
        nodes.append(node)
    vplane = None
    if plane:
        vplane = VerifyPlane(window_ms=1.0, use_device=False,
                             bulk_deadline_ms=250.0)
        vplane.start()
        set_global_plane(vplane)
    for n in nodes:
        n.start()
    run = OpenLoopRun()
    try:
        h0 = nodes[0].height()
        wall = open_loop(rate, duration,
                         make_tx_builder(signed, size),
                         lambda tx: _submit_mempool(nodes[0].mempool, tx),
                         run, workers=8)
        h1 = max(n.height() for n in nodes)
        commits = h1 - h0
    finally:
        if vplane is not None:
            set_global_plane(None)
        for n in nodes:
            n.stop()
        if vplane is not None:
            vplane.stop()
    extra = {
        "mode": f"in-process LocalNetwork x{n_nodes}",
        "commits": commits,
        "commits_per_s": round(commits / wall, 2) if wall else 0.0,
        "admission": nodes[0].mempool.admission.stats()
        if nodes[0].mempool.admission else None,
    }
    # per-height commit-latency attribution from node 0's always-on
    # height ledger (trimmed: the bench evidence file must not carry
    # 512 full records) — cfg9 embeds the height_report table so the
    # sustained-load commit latency is baseline-comparable
    try:
        from tools import height_report

        hd = nodes[0].consensus.height_ledger.dump()
        hd["heights"] = hd["heights"][-64:]
        rep = height_report.stage_report(hd)
        extra["height_dump"] = hd
        extra["height_stage_table"] = rep["stages"]
        extra["commit_p50_ms"] = rep["commit_p50_ms"]
        extra["commit_p99_ms"] = rep["commit_p99_ms"]
    except Exception as e:  # noqa: BLE001 - report, don't kill the run
        extra["height_dump_error"] = repr(e)[:200]
    if vplane is not None:
        ps = vplane.stats()
        extra["plane"] = {"lane_rows": ps["lane_rows"],
                          "sheds": ps["sheds"],
                          "lane_waits": vplane.lane_wait_stats()}
    return run.report(wall, extra=extra)


# --------------------------------------------------------------------------
# --rpc mode: flood a live node over JSON-RPC
# --------------------------------------------------------------------------


def run_rpc(url: str, rate: float, duration: float,
            signed: bool = False, size: int = 32) -> dict:
    import base64
    import urllib.request

    def submit(tx: bytes):
        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "broadcast_tx_sync",
            "params": {"tx": base64.b64encode(tx).decode()},
        }).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.loads(r.read().decode())
        res = doc.get("result") or {}
        log = res.get("log", "")
        if "retry_after_ms" in res and "retry_after_ms=" not in log:
            log += f" retry_after_ms={res['retry_after_ms']}"
        return res.get("code", -1), log

    run = OpenLoopRun()
    wall = open_loop(rate, duration, make_tx_builder(signed, size),
                     submit, run, workers=16)
    return run.report(wall, extra={"mode": f"rpc {url}"})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop tx load generator (test/loadtime analog)")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="offered tx rate per second (open-loop)")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds of sustained offered load")
    ap.add_argument("--nodes", type=int, default=4,
                    help="in-process mode: LocalNetwork size")
    ap.add_argument("--size", type=int, default=32,
                    help="tx payload bytes")
    ap.add_argument("--unsigned", action="store_true",
                    help="plain txs (skip the sigtx envelope)")
    ap.add_argument("--rpc", default="",
                    help="flood a live node's JSON-RPC URL instead of "
                         "an in-process net")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 mode: mempool+plane only, no "
                         "consensus, no jax import, ~3 s")
    args = ap.parse_args(argv)
    if args.smoke:
        rep = run_smoke()
    elif args.rpc:
        rep = run_rpc(args.rpc, args.rate, args.duration,
                      signed=not args.unsigned, size=args.size)
    else:
        rep = run_inprocess(args.rate, args.duration, args.nodes,
                            signed=not args.unsigned, size=args.size)
    print(json.dumps(rep, indent=1))
    return 0


if __name__ == "__main__":
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    raise SystemExit(main())
