"""Turn a /dump_tenants document into per-tenant occupancy and QoS
tables — and DIFF two of them.

The multi-tenant sibling of tools/controller_report.py, device_report,
height_report and peer_report: where those decompose the LOOP, the
DEVICE, a BLOCK and the GOSSIP, this decomposes the POD — per tenant:
verified rows (per lane), quota sheds, warm skips, cold-table
evictions, HBM residency (bytes + tables), verify-wait percentiles,
and the configured quotas; plus the registry-level figures (size,
evictions, the retired-totals accumulator). Feed it a saved
``curl $NODE/dump_tenants`` file or a bench --json-out evidence file
with an embedded ``tenants_dump``.

Differencing mirrors controller_report --diff: figure delta rows with
REGRESSED/improved flags past BOTH a relative and an absolute
threshold, and ``--fail-on-regression`` for CI gates (requires --diff
— a gate wired without a comparison must error, not read permanently
green). Flags: shed growth (quotas started biting — or a neighbor got
noisy), warm-skip growth (residency budgets rejecting prefetches),
cold-eviction churn, and per-tenant verify-wait p99 growth (the
fair-share drain stopped being fair).

Usage:
    python tools/tenant_report.py dump.json [--json]
    python tools/tenant_report.py --diff A.json B.json \
        [--json] [--threshold-pct 25] [--threshold-abs 4] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_directional, run_cli)

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_THRESHOLD_ABS = 4.0


def load_tenants(path: str) -> dict:
    """Extract a tenant dump from any supported shape: a /dump_tenants
    document, a bench --json-out evidence file carrying
    ``extra.tenants_dump``, or a bare {"tenants": ...} object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "tenants" in doc \
            and "registry_size" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            td = extra.get("tenants_dump")
            if td and td.get("tenants") is not None:
                return td
    raise ValueError(
        f"{path}: no tenant records found (want a /dump_tenants "
        f"document or a bench --json-out file with an embedded "
        f"tenants_dump)")


def tenant_report(dump: dict) -> dict:
    """Aggregate a tenant dump into the tables the text report prints
    and the diff compares."""
    tenants = []
    for name, t in (dump.get("tenants") or {}).items():
        res = t.get("residency") or {}
        wait = t.get("wait_ms") or {}
        tenants.append({
            "tenant": name,
            "rows": t.get("rows", 0),
            "lane_rows": dict(t.get("lane_rows", {})),
            "sheds": t.get("sheds", 0),
            "warm_skips": t.get("warm_skips", 0),
            "cold_evictions": t.get("cold_evictions", 0),
            "row_quota": t.get("row_quota", 0),
            "residency_budget": t.get("residency_budget", 0),
            "resident_bytes": res.get("bytes", 0),
            "resident_tables": res.get("tables", 0),
            "wait_p99_ms": wait.get("p99_ms", 0.0),
            "wait_n": wait.get("n", 0),
            # ISSUE 20 device chargeback columns (0.0 on dumps from
            # builds predating the split — the report stays readable)
            "device_ms": t.get("device_ms", 0.0),
            "comp_ms": t.get("comp_ms", 0.0),
            "h2d_ms": t.get("h2d_ms", 0.0),
            "delta_bytes": t.get("delta_bytes", 0),
        })
    tenants.sort(key=lambda r: (-r["rows"], r["tenant"]))
    retired = dict(dump.get("retired", {}))
    return {
        "registry_size": dump.get("registry_size", 0),
        "evicted": dump.get("evicted", 0),
        "owner_keys": dump.get("owner_keys", 0),
        "retired": retired,
        "tenants": tenants,
        "rows_total": sum(r["rows"] for r in tenants)
        + retired.get("rows", 0),
        "sheds_total": sum(r["sheds"] for r in tenants)
        + retired.get("sheds", 0),
        "warm_skips_total": sum(r["warm_skips"] for r in tenants)
        + retired.get("warm_skips", 0),
        "cold_evictions_total": sum(r["cold_evictions"]
                                    for r in tenants)
        + retired.get("cold_evictions", 0),
        "resident_bytes_total": sum(r["resident_bytes"]
                                    for r in tenants),
        "wait_p99_worst_ms": max(
            (r["wait_p99_ms"] for r in tenants), default=0.0),
        "device_ms_total": round(
            sum(r["device_ms"] for r in tenants)
            + retired.get("device_us", 0) / 1000.0, 3),
        "comp_ms_total": round(
            sum(r["comp_ms"] for r in tenants)
            + retired.get("comp_us", 0) / 1000.0, 3),
    }


# --------------------------------------------------------------------------
# differencing (controller_report --diff's shape, over the pod figures)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_abs: float = DEFAULT_THRESHOLD_ABS) -> dict:
    """Pod-figure delta rows (A = before, B = after). Growth is bad
    for sheds, warm skips, cold-eviction churn and the worst per-
    tenant wait p99; a figure REGRESSED past BOTH thresholds."""

    def flag_of(a: float, b: float,
                abs_floor: float = threshold_abs) -> str:
        return flag_directional(a, b, threshold_pct=threshold_pct,
                                abs_floor=abs_floor)

    def row(metric: str, abs_floor: float = threshold_abs) -> dict:
        a, b = rep_a[metric], rep_b[metric]
        return {"metric": metric, "a": a, "b": b,
                "delta": round(b - a, 4),
                "flag": flag_of(a, b, abs_floor)}

    rows = [
        row("sheds_total"),
        row("warm_skips_total"),
        row("cold_evictions_total"),
        row("wait_p99_worst_ms", abs_floor=max(threshold_abs, 10.0)),
        # compile ms charged to tenants growing means the pod started
        # paying recompiles for someone — a regression signal
        row("comp_ms_total", abs_floor=max(threshold_abs, 10.0)),
        # total device ms is workload-following, informational only
        {"metric": "device_ms_total", "a": rep_a["device_ms_total"],
         "b": rep_b["device_ms_total"],
         "delta": round(rep_b["device_ms_total"]
                        - rep_a["device_ms_total"], 4),
         "flag": ""},
        {"metric": "rows_total", "a": rep_a["rows_total"],
         "b": rep_b["rows_total"],
         "delta": round(rep_b["rows_total"] - rep_a["rows_total"], 4),
         "flag": ""},
        {"metric": "registry_size", "a": rep_a["registry_size"],
         "b": rep_b["registry_size"],
         "delta": rep_b["registry_size"] - rep_a["registry_size"],
         "flag": ""},
    ]

    notes = []
    by_a = {r["tenant"]: r for r in rep_a["tenants"]}
    # device-share growth: a tenant taking a materially bigger slice
    # of the pod's device time than before (>= 10 percentage points
    # on a non-trivial total) is the noisy-neighbor chargeback signal
    tot_a = max(rep_a["device_ms_total"], 1e-9)
    tot_b = max(rep_b["device_ms_total"], 1e-9)
    if rep_b["device_ms_total"] >= 1.0:
        for r in rep_b["tenants"]:
            share_b = r["device_ms"] / tot_b
            before = by_a.get(r["tenant"])
            share_a = (before["device_ms"] / tot_a) if before else 0.0
            if share_b - share_a >= 0.10:
                notes.append(
                    f"tenant {r['tenant']!r} device-share growth: "
                    f"{share_a * 100.0:.1f}% -> {share_b * 100.0:.1f}% "
                    f"of pod device time ({r['device_ms']} ms) — pull "
                    f"/dump_devices cost_surfaces for its flush "
                    f"family and /dump_flushes for WHO queued the "
                    f"rows")
    for r in rep_b["tenants"]:
        before = by_a.get(r["tenant"])
        if before is None:
            notes.append(f"tenant {r['tenant']!r} is new in B "
                         f"({r['rows']} rows)")
            continue
        d = r["sheds"] - before["sheds"]
        if d >= threshold_abs and (before["sheds"] == 0 or
                                   d / before["sheds"] * 100.0
                                   >= threshold_pct):
            notes.append(
                f"tenant {r['tenant']!r} shed growth: "
                f"{before['sheds']} -> {r['sheds']} — its quota "
                f"started biting; check row_quota sizing and whether "
                f"a neighbor's drain share starved it")
    for name in by_a:
        if name not in {r["tenant"] for r in rep_b["tenants"]}:
            notes.append(f"tenant {name!r} gone in B (evicted or "
                         f"retired into the _retired accumulator)")

    regressions = [r["metric"] for r in rows
                   if r["flag"] == "REGRESSED"]
    return {"rows": rows, "regressions": regressions, "notes": notes}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    ret = rep["retired"]
    lines = [
        f"registry: {rep['registry_size']} tenants "
        f"({rep['evicted']} evicted, retired rows "
        f"{ret.get('rows', 0)}), {rep['owner_keys']} owned table "
        f"keys; {rep['rows_total']} rows verified, "
        f"{rep['sheds_total']} quota sheds, "
        f"{rep['resident_bytes_total']} resident bytes"]
    if rep["tenants"]:
        lines += ["", f"{'tenant':<22}{'rows':>10}{'sheds':>7}"
                      f"{'wskip':>7}{'cevict':>7}{'resKB':>8}"
                      f"{'tables':>7}{'p99ms':>9}{'quota':>7}"
                      f"{'dev_ms':>10}{'comp_ms':>9}"]
        for r in rep["tenants"]:
            lines.append(
                f"{r['tenant']:<22}{r['rows']:>10}{r['sheds']:>7}"
                f"{r['warm_skips']:>7}{r['cold_evictions']:>7}"
                f"{r['resident_bytes'] // 1024:>8}"
                f"{r['resident_tables']:>7}{r['wait_p99_ms']:>9}"
                f"{r['row_quota'] or '-':>7}"
                f"{r['device_ms']:>10}{r['comp_ms']:>9}")
        lines.append(
            f"device time charged: {rep['device_ms_total']} ms "
            f"(compile {rep['comp_ms_total']} ms), retired included")
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A",
                path_b: str = "B") -> str:
    lines = [f"tenant-plane delta: {path_a} -> {path_b}",
             "", f"{'metric':<24}{'A':>12}{'B':>12}{'Δ':>12}  flag"]
    for r in diff["rows"]:
        lines.append(f"{r['metric']:<24}{r['a']:>12}{r['b']:>12}"
                     f"{r['delta']:>+12}  {r['flag']}")
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"]
                   else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "per-tenant occupancy and QoS tables from a /dump_tenants "
        "document, or a pod-figure delta diff of two of them",
        operand_help="tenant dump file(s); two with --diff",
        diff_help="diff two dumps: pod-figure delta table with "
                  "regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_ABS)
    return run_cli(argv, parser=ap, load=load_tenants,
                   report=tenant_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
