"""Render the bench trajectory r01 -> rNN from the checked-in
BENCH_r*.json files.

The driver records one BENCH_rNN.json per round (its ``tail`` holds the
bench's JSON-line stdout, truncated at the head — early configs of old
rounds may be missing; they render as ``—``, never guessed). This tool
lines the rounds up per config so "did cfg4 ever recover" is one look
at one table instead of five ``python -m json.tool`` sessions.

Usage:
    python tools/bench_history.py                 # table to stdout
    python tools/bench_history.py --json          # machine-readable
    python tools/bench_history.py --dir path/to/repo --glob 'BENCH_r*.json'
"""
from __future__ import annotations

import argparse
import glob as globmod
import json
import os
import re
import sys

# direct script invocation puts tools/ on sys.path, not the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import FULL_CONFIG_NAMES, load_bench_results  # noqa: E402

# stable column order: the headline first, then the numbered configs
_CFG_ORDER = re.compile(r"cfg(\d+)")

# configs that embed the height ledger's commit-latency attribution
# (bench extra.commit_p50_ms/commit_p99_ms via tools/height_report) —
# each gets a "cfgN commit p50/p99" sub-row, all-'—' before the first
# round that recorded it (the cfg10–13 precedent)
_COMMIT_LATENCY_CFGS = ("cfg9", "cfg13")

# configs that embed the device observatory's figures: cfg15 gets a
# "cfg15 device" sub-row (cold compiles / steady recompiles — steady
# must stay 0, the round-5 class), and the mesh configs get a "util"
# sub-row from the rows-x-cost utilization model (extra.util_big /
# extra.util_est.p50) — all-'—' before their first recorded round
_DEVICE_CFGS = ("cfg15",)
_UTIL_CFGS = {"cfg11": "util_big", "cfg12": "util_est"}

# cfg16 embeds the closed-loop controller dump: a "cfg16 loop" sub-row
# tracks decisions-per-round and accrued SLO-violation seconds (the
# loop's one job is keeping the latter at 0) — '—' before its first
# recorded round, same as the device/commit sub-rows
_CONTROLLER_CFGS = ("cfg16",)

# cfg17 embeds the multi-tenant pod figures: a "cfg17 pod" sub-row
# tracks coalesced flushes and the shared-vs-split speedup (the
# subsystem's one job is serving K chains from one drain cycle) —
# '—' before its first recorded round, same as the other sub-rows
_TENANT_CFGS = ("cfg17",)

# cfg18 embeds the catch-up firehose figures: a "cfg18 replay" sub-row
# tracks valset boundaries crossed vs warm-ahead requests issued (the
# plane's one job is warming every epoch table before the replay
# cursor reaches it) — '—' before its first recorded round. Host-only
# machinery rounds carry the figures inside extra.machinery instead of
# at the top level, so the sub-row falls back there.
_CATCHUP_CFGS = ("cfg18",)

# cfg20 embeds the cost observatory's figures: a "cfg20 cost" sub-row
# tracks learned cost-surface cells and the largest bucket's marginal
# ms-per-row (the capacity-planning slope device_report renders) —
# '—' before its first recorded round, same as the other sub-rows
_COST_CFGS = ("cfg20",)


def _cfg_key(name: str):
    if name == "headline":
        return (0, 0, name)
    m = _CFG_ORDER.match(name)
    # a "cfgN commit p50/p99" sub-row sorts right after its cfgN row
    return (1, int(m.group(1)) if m else 99, name)


def collect(directory: str, pattern: str) -> dict:
    """{round_tag: {cfg: result_dict}} for every matching BENCH file."""
    rounds = {}
    for path in sorted(globmod.glob(os.path.join(directory, pattern))):
        tag = os.path.splitext(os.path.basename(path))[0]
        tag = tag.replace("BENCH_", "")
        try:
            rounds[tag] = load_bench_results(path)
        except (OSError, ValueError) as e:
            rounds[tag] = {"_error": {"metric": "load failed",
                                      "value": None, "unit": "",
                                      "extra": {"error": repr(e)}}}
    return rounds


def history(rounds: dict) -> dict:
    """Per-config series across rounds + headline deltas.

    Rows are the union of what the BENCH files recorded and the
    CURRENT bench's full config set (bench.FULL_CONFIG_NAMES) — a
    config added this round (cfg9, cfg10, ...) renders as an all-'—'
    row immediately, so its trajectory is trackable from the next
    bench round onward instead of silently absent until the first
    recording."""
    configs = sorted({c for r in rounds.values() for c in r
                      if not c.startswith("_")}
                     | set(FULL_CONFIG_NAMES), key=_cfg_key)
    series = {}
    for cfg in configs:
        pts = []
        for tag in rounds:
            res = rounds[tag].get(cfg)
            pts.append({
                "round": tag,
                "value": res.get("value") if res else None,
                "unit": (res.get("unit") or "") if res else "",
                "vs_baseline": res.get("vs_baseline") if res else None,
            })
        series[cfg] = pts
        if cfg in _DEVICE_CFGS:
            dpts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                cold = extra.get("cold_compiles")
                steady = extra.get("steady_compiles")
                dpts.append({
                    "round": tag,
                    "value": (f"{cold}c/{steady}s"
                              if cold is not None and steady is not None
                              else None),
                    "unit": "cold/steady compiles",
                    "vs_baseline": None,
                })
            series[f"{cfg} device"] = dpts
        if cfg in _UTIL_CFGS:
            upts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                u = extra.get(_UTIL_CFGS[cfg])
                if isinstance(u, dict):  # cfg12 embeds the pcts block
                    u = u.get("p50")
                upts.append({
                    "round": tag,
                    "value": (f"{u:g}" if u is not None else None),
                    "unit": "util p50",
                    "vs_baseline": None,
                })
            series[f"{cfg} util"] = upts
        if cfg in _CONTROLLER_CFGS:
            lpts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                n = extra.get("decisions_total")
                st = (extra.get("controller_dump") or {}).get(
                    "state") or {}
                viol = st.get("slo_violation_s")
                lpts.append({
                    "round": tag,
                    "value": (f"{n}d/{viol:g}s"
                              if n is not None and viol is not None
                              else None),
                    "unit": "decisions/violation",
                    "vs_baseline": None,
                })
            series[f"{cfg} loop"] = lpts
        if cfg in _TENANT_CFGS:
            tpts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                co = extra.get("coalesced_flushes")
                sp = extra.get("speedup_vs_split")
                tpts.append({
                    "round": tag,
                    "value": (f"{co}co/{sp:g}x"
                              if co is not None and sp is not None
                              else None),
                    "unit": "coalesced/speedup",
                    "vs_baseline": None,
                })
            series[f"{cfg} pod"] = tpts
        if cfg in _CATCHUP_CFGS:
            rpts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                src = extra if "boundaries" in extra \
                    else (extra.get("machinery") or {})
                bo = src.get("boundaries")
                wr = src.get("warm_requests")
                rpts.append({
                    "round": tag,
                    "value": (f"{bo}b/{wr}w"
                              if bo is not None and wr is not None
                              else None),
                    "unit": "boundaries/warms",
                    "vs_baseline": None,
                })
            series[f"{cfg} replay"] = rpts
        if cfg in _COST_CFGS:
            spts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                cells = (extra.get("cost_counters") or {}).get("cells")
                margs = [r.get("marginal_ms_per_row")
                         for r in (extra.get("cost_surfaces") or [])
                         if r.get("marginal_ms_per_row") is not None]
                spts.append({
                    "round": tag,
                    "value": (f"{cells}c/{margs[-1]:g}ms"
                              if cells is not None and margs
                              else None),
                    "unit": "cells/marginal-per-row",
                    "vs_baseline": None,
                })
            series[f"{cfg} cost"] = spts
        if cfg in _COMMIT_LATENCY_CFGS:
            cpts = []
            for tag in rounds:
                extra = (rounds[tag].get(cfg) or {}).get("extra") or {}
                p50_v = extra.get("commit_p50_ms")
                p99_v = extra.get("commit_p99_ms")
                cpts.append({
                    "round": tag,
                    "value": (f"{p50_v:g}/{p99_v:g}"
                              if p50_v is not None and p99_v is not None
                              else None),
                    "unit": "ms p50/p99",
                    "vs_baseline": None,
                })
            series[f"{cfg} commit"] = cpts
    deltas = []
    prev = None
    for tag in rounds:
        res = rounds[tag].get("headline") or {}
        v = res.get("value")
        if v is not None and prev is not None:
            deltas.append({"from": prev[0], "to": tag,
                           "delta_pct": round((v - prev[1]) / prev[1]
                                              * 100.0, 1)})
        if v is not None:
            prev = (tag, v)
    return {"rounds": list(rounds), "series": series,
            "headline_deltas": deltas}


def _fmt_val(pt: dict) -> str:
    v = pt["value"]
    if v is None:
        return "—"
    if isinstance(v, str):  # pre-rendered (commit p50/p99 sub-rows)
        return f"{v}{(' ' + pt['unit']) if pt['unit'] else ''}"
    if isinstance(v, float) and v >= 1000:
        v = round(v)
    return f"{v:g}{(' ' + pt['unit']) if pt['unit'] else ''}"


def render(hist: dict) -> str:
    tags = hist["rounds"]
    lines = []
    width = max((len(c) for c in hist["series"]), default=8) + 2
    colw = max(14, max((len(_fmt_val(p)) for pts in
                        hist["series"].values() for p in pts),
                       default=10) + 2)
    lines.append("".join(["config".ljust(width)]
                         + [t.ljust(colw) for t in tags]))
    for cfg, pts in hist["series"].items():
        lines.append("".join(
            [cfg.ljust(width)] + [_fmt_val(p).ljust(colw) for p in pts]))
    if hist["headline_deltas"]:
        steps = ", ".join(f"{d['from']}->{d['to']}: "
                          f"{d['delta_pct']:+.1f}%"
                          for d in hist["headline_deltas"])
        lines.append(f"headline trend: {steps}")
    lines.append("('—' = config missing from that round's recorded "
                 "tail — old tails are head-truncated, values are "
                 "never guessed)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench trajectory from checked-in BENCH files")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="directory holding the "
        "BENCH files (default: the repo root)")
    ap.add_argument("--glob", default="BENCH_r*.json")
    ap.add_argument("--json", action="store_true",
                    help="emit the history as JSON instead of a table")
    args = ap.parse_args(argv)
    rounds = collect(args.dir, args.glob)
    if not rounds:
        print(f"no files match {args.glob} under {args.dir}",
              file=sys.stderr)
        return 2
    hist = history(rounds)
    if args.json:
        print(json.dumps(hist, indent=1))
    else:
        print(render(hist))
    return 0


if __name__ == "__main__":
    sys.exit(main())
