"""Shared --diff/--fail-on-regression plumbing for the report tools.

Seven report tools (trace, height, peer, device, controller, catchup,
tenant) grew the same CLI shape one PR at a time: positional dump
file(s), ``--diff`` for an A->B delta table, a relative + absolute
threshold pair, ``--json``, and a ``--fail-on-regression`` CI gate
that must ERROR when wired without ``--diff`` (a gate without a
comparison reads permanently green). This module is that shape, once —
the per-tool files keep what is genuinely theirs (dump loading, figure
aggregation, which metrics flag in which direction, table rendering).

Three flag styles exist in the fleet and all three live here:

  * :func:`flag_directional` — growth (or shrink, ``bad_dir=-1``) is
    the bad direction; improvement needs only the absolute floor while
    a regression needs BOTH floors, and ``any_growth=True`` waives the
    relative floor (the steady-recompile / SLO-violation rule: one is
    a bug no matter the baseline). Used by tenant/controller/device.
  * :func:`flag_symmetric` — both directions flag past both floors:
    bigger is REGRESSED, smaller is improved. Used by the ms-based
    stage tables (height/trace) and the peer health counters.
  * :func:`flag_directed` — symmetric thresholds but an explicit
    ``bad_when`` ("up"/"down") names the bad direction, so a drop in
    blocks/s flags REGRESSED while a drop in verify_ms flags improved.
    Used by catchup's throughput-vs-latency mix.

Behavior-identical by construction: each function is the verbatim
closure it replaced, with the thresholds as keyword arguments instead
of captured cells; the argparse error strings are unchanged (the
synthetic-regression smokes in tests/test_z*_smoke.py pin them).
"""
from __future__ import annotations

import argparse
import json


def flag_directional(a: float, b: float, *, threshold_pct: float,
                     abs_floor: float, bad_dir: int = 1,
                     any_growth: bool = False) -> str:
    """One-sided flag: movement in ``bad_dir`` is bad. A regression
    must clear the absolute floor AND (unless ``any_growth``) the
    relative floor; an improvement needs only the absolute floor."""
    d = (b - a) * bad_dir
    if d <= 0:
        return "improved" if d < 0 and abs(d) >= abs_floor else ""
    if d < abs_floor:
        return ""
    if not any_growth and a > 0 and d / abs(a) * 100.0 < threshold_pct:
        return ""
    return "REGRESSED"


def flag_directed(a: float, b: float, *, bad_when: str,
                  threshold_pct: float, abs_floor: float) -> str:
    """Two-sided flag with an explicit bad direction: past both
    floors, movement toward ``bad_when`` ("up"/"down") is REGRESSED
    and the opposite movement is improved."""
    d = b - a
    bad = d > 0 if bad_when == "up" else d < 0
    if abs(d) < abs_floor:
        return ""
    if a > 0 and abs(d) / abs(a) * 100.0 < threshold_pct:
        return ""
    return "REGRESSED" if bad else "improved"


def flag_symmetric(a: float, b: float, *, threshold_pct: float,
                   abs_floor: float) -> str:
    """Two-sided flag where growth is bad: past both floors, up is
    REGRESSED and down is improved."""
    return flag_directed(a, b, bad_when="up",
                         threshold_pct=threshold_pct,
                         abs_floor=abs_floor)


def build_parser(description: str, *, operand: str = "dumps",
                 operand_help: str, diff_help: str,
                 default_pct: float, default_abs: float,
                 pct_help: str = "relative regression floor (%%)",
                 abs_flag: str = "--threshold-abs",
                 abs_help: str = "absolute regression floor "
                                 "(count / value)"
                 ) -> argparse.ArgumentParser:
    """The shared CLI surface. ``abs_flag`` lets the ms-based tools
    keep their ``--threshold-ms`` spelling; either way the value parses
    into ``args.threshold_abs`` so run_cli passes one tuple shape."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument(operand, nargs="+", help=operand_help)
    ap.add_argument("--diff", action="store_true", help=diff_help)
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    ap.add_argument("--threshold-pct", type=float, default=default_pct,
                    help=pct_help)
    ap.add_argument(abs_flag, type=float, default=default_abs,
                    dest="threshold_abs", help=abs_help)
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when the diff flags any regression")
    return ap


def run_cli(argv, *, parser: argparse.ArgumentParser, load, report,
            diff, fmt_report, fmt_diff, operand: str = "dumps",
            noun: str = "dump") -> int:
    """The shared main() body: parse, gate-check, then either the
    single-file report or the two-file diff (exit 1 when the gate is
    armed and the diff flags regressions)."""
    args = parser.parse_args(argv)
    paths = getattr(args, operand)
    if args.fail_on_regression and not args.diff:
        # only a diff can flag regressions; a gate wired without --diff
        # would be permanently green
        parser.error("--fail-on-regression requires --diff")
    if args.diff:
        if len(paths) != 2:
            parser.error(f"--diff needs exactly two {noun} files")
        rep_a = report(load(paths[0]))
        rep_b = report(load(paths[1]))
        d = diff(rep_a, rep_b, args.threshold_pct, args.threshold_abs)
        print(json.dumps(d) if args.json
              else fmt_diff(d, paths[0], paths[1]))
        return 1 if args.fail_on_regression and d["regressions"] else 0
    if len(paths) != 1:
        parser.error(f"exactly one {noun} file (or use --diff A B)")
    rep = report(load(paths[0]))
    print(json.dumps(rep) if args.json else fmt_report(rep))
    return 0
