"""Turn a /dump_catchup document into a replay throughput report —
and DIFF two of them.

The bootstrap-plane sibling of tools/tenant_report.py and
controller_report.py: where those decompose the POD and the LOOP, this
decomposes a REPLAY — per fused flush: heights covered, signatures
verified, read/verify/apply time, valset-boundary and warm-ahead
flags, resume-skip counts — plus the run figures (blocks/sec,
sigs/sec, boundary count, warm requests, resumes, and the time split
between reading history, verifying commits, and applying blocks).
Feed it a saved ``curl $NODE/dump_catchup`` file or a bench
--json-out evidence file with an embedded ``catchup_dump``.

Differencing mirrors tenant_report --diff: figure delta rows with
REGRESSED/improved flags past BOTH a relative and an absolute
threshold, and ``--fail-on-regression`` for CI gates (requires --diff
— a gate wired without a comparison must error, not read permanently
green). Flags: blocks/sec or sigs/sec decay (the firehose got
slower), verify-time growth (cold epoch tables — check the warm-ahead
column), and re-verified blocks appearing where a resume should have
skipped them.

Usage:
    python tools/catchup_report.py dump.json [--json]
    python tools/catchup_report.py --diff A.json B.json \
        [--json] [--threshold-pct 25] [--threshold-abs 4] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_directed, run_cli)

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_THRESHOLD_ABS = 4.0


def load_catchup(path: str) -> dict:
    """Extract a catch-up dump from any supported shape: a
    /dump_catchup document, a bench --json-out evidence file carrying
    ``extra.catchup_dump``, or a bare {"records": ...} object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "records" in doc \
            and "counters" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            cd = extra.get("catchup_dump")
            if cd and cd.get("records") is not None:
                return cd
    raise ValueError(
        f"{path}: no catch-up records found (want a /dump_catchup "
        f"document or a bench --json-out file with an embedded "
        f"catchup_dump)")


def catchup_report(dump: dict) -> dict:
    """Aggregate a catch-up dump into the figures the text report
    prints and the diff compares."""
    recs = list(dump.get("records") or [])
    counters = dict(dump.get("counters") or {})
    summary = dict(dump.get("summary") or {})
    read_ms = sum(r.get("read_ms", 0.0) for r in recs)
    verify_ms = sum(r.get("verify_ms", 0.0) for r in recs)
    apply_ms = sum(r.get("apply_ms", 0.0) for r in recs)
    busy_ms = read_ms + verify_ms + apply_ms
    return {
        "flushes": counters.get("flushes", len(recs)),
        "blocks_applied": counters.get("blocks_applied", 0),
        "blocks_verified": counters.get("blocks_verified", 0),
        "blocks_skipped": counters.get("blocks_skipped", 0),
        "sigs_verified": counters.get("sigs_verified", 0),
        "boundaries": counters.get("boundaries", 0),
        "warm_requests": counters.get("warm_requests", 0),
        "resumes": counters.get("resumes", 0),
        "blocks_per_s": summary.get("blocks_per_s", 0.0),
        "sigs_per_s": summary.get("sigs_per_s", 0.0),
        "read_ms": round(read_ms, 3),
        "verify_ms": round(verify_ms, 3),
        "apply_ms": round(apply_ms, 3),
        "verify_frac": round(verify_ms / busy_ms, 3) if busy_ms else 0.0,
        "records": recs,
    }


# --------------------------------------------------------------------------
# differencing (tenant_report --diff's shape, over the replay figures)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_abs: float = DEFAULT_THRESHOLD_ABS) -> dict:
    """Replay-figure delta rows (A = before, B = after). DECAY is bad
    for the rate figures; GROWTH is bad for verify time and for
    re-verified blocks a resume should have skipped. A figure flags
    REGRESSED only past BOTH thresholds."""

    def flag(a: float, b: float, bad_when: str,
             abs_floor: float = threshold_abs) -> str:
        return flag_directed(a, b, bad_when=bad_when,
                             threshold_pct=threshold_pct,
                             abs_floor=abs_floor)

    def row(metric: str, bad_when: str,
            abs_floor: float = threshold_abs) -> dict:
        a, b = rep_a[metric], rep_b[metric]
        return {"metric": metric, "a": a, "b": b,
                "delta": round(b - a, 4),
                "flag": flag(a, b, bad_when, abs_floor)}

    rows = [
        row("blocks_per_s", bad_when="down"),
        row("sigs_per_s", bad_when="down"),
        row("verify_ms", bad_when="up",
            abs_floor=max(threshold_abs, 50.0)),
        row("blocks_verified", bad_when="up"),
        {"metric": "blocks_applied", "a": rep_a["blocks_applied"],
         "b": rep_b["blocks_applied"],
         "delta": rep_b["blocks_applied"] - rep_a["blocks_applied"],
         "flag": ""},
        {"metric": "boundaries", "a": rep_a["boundaries"],
         "b": rep_b["boundaries"],
         "delta": rep_b["boundaries"] - rep_a["boundaries"],
         "flag": ""},
    ]

    notes = []
    if rep_b["resumes"] > rep_a["resumes"] \
            and rep_b["blocks_skipped"] <= rep_a["blocks_skipped"]:
        notes.append(
            "B resumed from a cursor but skipped no additional "
            "blocks — the resume re-verified work the cursor should "
            "have covered; check the cursor file survived the restart")
    if rep_b["boundaries"] and not rep_b["warm_requests"]:
        notes.append(
            "B crossed valset boundaries with ZERO warm-ahead "
            "requests — every epoch paid a cold table build; check "
            "the warmer was mounted")

    regressions = [r["metric"] for r in rows
                   if r["flag"] == "REGRESSED"]
    return {"rows": rows, "regressions": regressions, "notes": notes}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    lines = [
        f"catch-up: {rep['blocks_applied']} blocks applied in "
        f"{rep['flushes']} fused flushes ({rep['blocks_verified']} "
        f"verified, {rep['blocks_skipped']} resume-skipped, "
        f"{rep['sigs_verified']} sigs); "
        f"{rep['blocks_per_s']} blocks/s, {rep['sigs_per_s']} sigs/s",
        f"time split: read {rep['read_ms']}ms, verify "
        f"{rep['verify_ms']}ms ({rep['verify_frac']:.0%} of busy), "
        f"apply {rep['apply_ms']}ms; {rep['boundaries']} valset "
        f"boundaries, {rep['warm_requests']} warm-ahead requests, "
        f"{rep['resumes']} resumes"]
    if rep["records"]:
        lines += ["", f"{'seq':>5}{'first':>9}{'last':>9}{'blks':>6}"
                      f"{'sigs':>8}{'skip':>6}{'read':>8}{'vrfy':>8}"
                      f"{'appl':>8}  flags"]
        for r in rep["records"][-24:]:
            flags = ("B" if r.get("boundary") else "") \
                + ("W" if r.get("warmed") else "")
            lines.append(
                f"{r['seq']:>5}{r['first']:>9}{r['last']:>9}"
                f"{r['blocks']:>6}{r['sigs']:>8}{r['skipped']:>6}"
                f"{r['read_ms']:>8}{r['verify_ms']:>8}"
                f"{r['apply_ms']:>8}  {flags}")
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A",
                path_b: str = "B") -> str:
    lines = [f"catch-up delta: {path_a} -> {path_b}",
             "", f"{'metric':<20}{'A':>12}{'B':>12}{'Δ':>12}  flag"]
    for r in diff["rows"]:
        lines.append(f"{r['metric']:<20}{r['a']:>12}{r['b']:>12}"
                     f"{r['delta']:>+12}  {r['flag']}")
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"]
                   else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "replay throughput report from a /dump_catchup document, or "
        "a replay-figure delta diff of two of them",
        operand_help="catch-up dump file(s); two with --diff",
        diff_help="diff two dumps: replay-figure delta table with "
                  "regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_ABS)
    return run_cli(argv, parser=ap, load=load_catchup,
                   report=catchup_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
