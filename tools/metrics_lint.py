"""Metric naming-convention lint, wired into a fast tier-1 test.

Prometheus conventions this build commits to (and the lint enforces
over the REGISTERED metric set, so a drive-by metric addition fails CI
before it ships an unscrapable name):

  * counters end in ``_total``;
  * histograms carry a base-unit suffix: ``_seconds``, ``_bytes``, or
    ``_rows`` (the one dimensionless unit this system measures);
  * gauges must NOT end in ``_total`` (that suffix promises a counter);
  * no duplicate metric names in one registry (duplicate families
    render /metrics unparseable);
  * every metric has non-empty HELP text.

Usage:
    python tools/metrics_lint.py   # lint NodeMetrics; exit 1 on violations
"""
from __future__ import annotations

from typing import List

HISTOGRAM_UNITS = ("_seconds", "_bytes", "_rows")


def lint_registry(registry) -> List[str]:
    """Violations for every metric registered in a libs.metrics
    Registry; empty list = clean."""
    out: List[str] = []
    seen = set()
    with registry._lock:
        metrics = list(registry._metrics)
    for m in metrics:
        if m.name in seen:
            out.append(f"duplicate registration: {m.name}")
        seen.add(m.name)
        if not m.help:
            out.append(f"{m.name}: empty HELP text")
        if m.type == "counter" and not m.name.endswith("_total"):
            out.append(f"{m.name}: counter must end _total")
        if m.type == "gauge" and m.name.endswith("_total"):
            out.append(f"{m.name}: gauge must not end _total")
        if m.type == "histogram" and \
                not m.name.endswith(HISTOGRAM_UNITS):
            out.append(
                f"{m.name}: histogram must carry a base unit suffix "
                f"{HISTOGRAM_UNITS}"
            )
    return out


def lint_sample_coverage() -> List[str]:
    """Cross-check NodeMetrics._sample against a LIVE registry expose:
    every ``self.<attr>`` the sampler touches must be a registered
    metric whose family actually appears in expose_text(). Catches the
    drive-by failure mode the naming lint cannot: a scrape-time sampler
    writing into an attribute that was never declared in __init__ (the
    AttributeError would be swallowed by _sample's per-group fault
    isolation, so the family would silently never scrape)."""
    return _sample_coverage(None)


def _sample_coverage(src) -> List[str]:
    """Inner body of :func:`lint_sample_coverage`; `src` overrides the
    inspected _sample source (tests inject a synthetic sampler body to
    prove the undeclared-family detection actually detects)."""
    import inspect
    import re

    from cometbft_tpu.libs.metrics import NodeMetrics

    nm = NodeMetrics()
    exposed = nm.expose_text()  # runs _sample() against live modules
    if src is None:
        src = inspect.getsource(NodeMetrics._sample)
    out: List[str] = []
    for attr in sorted(set(re.findall(r"self\.(\w+)\.", src))):
        m = getattr(nm, attr, None)
        if m is None:
            out.append(f"_sample writes self.{attr}: never declared "
                       f"in NodeMetrics.__init__")
            continue
        name = getattr(m, "name", None)
        if name is None:
            out.append(f"_sample writes self.{attr}: not a Metric")
            continue
        if f"\n{name}" not in exposed and not exposed.startswith(name):
            out.append(f"{name}: sampled by _sample but absent from a "
                       f"live registry expose")
    return out


def lint_node_metrics() -> List[str]:
    """Lint the full node metric set (the registry every node serves):
    naming conventions + sampler/registry coverage."""
    from cometbft_tpu.libs.metrics import NodeMetrics

    return lint_registry(NodeMetrics().registry) + lint_sample_coverage()


def main() -> int:
    violations = lint_node_metrics()
    for v in violations:
        print(f"metrics-lint: {v}")
    if not violations:
        print("metrics-lint: NodeMetrics clean")
    return min(len(violations), 1)


if __name__ == "__main__":
    # direct script invocation puts tools/ on sys.path, not the repo
    # root — bootstrap it so `from cometbft_tpu...` resolves
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    raise SystemExit(main())
