#!/usr/bin/env python
"""Randomized long-schedule simnet fuzzing with seed replay.

Generates seeded random fault schedules (partitions, link faults,
kill/restart, per-node failpoints, byzantine actors, txs — and, with
--extra > 0, proportional epoch rotations interleaved with the rest),
runs each through the deterministic simnet, and asserts safety + (when
a quorum survives) liveness + evidence commitment for equivocation
schedules. Any failure prints the exact `{"seed": ..., "schedule":
[...]}` blob; rerun it byte-for-byte with --replay. Blobs carry the
network shape (nodes, horizon, extra) too, so the election state —
a pure function of (seed, extra, epoch-op order) — replays exactly.

Usage:
    python tools/simnet_fuzz.py --iters 10 --nodes 4 --seed 0
    python tools/simnet_fuzz.py --iters 10 --extra 12   # + epoch churn
    python tools/simnet_fuzz.py --replay '<json blob from a failure>'

Tier-1 never runs this (it is the long tail); CI or a soak box does.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cometbft_tpu.simnet import (  # noqa: E402
    Simnet,
    SimnetFailure,
    random_schedule,
)


def run_one(seed: int, schedule, n_nodes: int, horizon: float,
            verbose: bool, extra: int = 0) -> None:
    with tempfile.TemporaryDirectory(prefix="simnet-fuzz-") as d:
        # node power dwarfs the passive tail's stake so epoch churn
        # can never cost quorum (SimNetwork enforces the ratio)
        kw = ({"power": 100_000, "extra_validators": extra}
              if extra else {})
        with Simnet(n_nodes, seed=seed, basedir=d, **kw) as sim:
            sim.run(schedule, max_time=horizon)
            sim.assert_safety()
            # every epoch op either elected (txs recorded) or loudly
            # explained why not — silent no-op rotations hide bugs
            for rec in sim.epoch_results:
                assert "error" not in rec, rec
            alive = [n for n in sim.net.nodes if n.alive]
            if 3 * len(alive) > 2 * len(sim.net.nodes):
                sim.assert_liveness(min_new_heights=2, max_time=30.0)
                from cometbft_tpu.types.evidence import (
                    DuplicateVoteEvidence,
                )

                # equivocation oracle: the conflicting vote is a
                # one-shot send (retransmission resends only REAL
                # votes), so under partitions/drops no honest node may
                # ever hold both votes — only require commitment when
                # some live node actually DETECTED the equivocation
                # (pending evidence must then reach a block)
                detected = any(
                    isinstance(e, DuplicateVoteEvidence)
                    for n in sim.net.nodes if n.alive
                    for e in n.node.evidence_pool.pending_evidence()
                )
                if detected:
                    sim.assert_evidence_committed(
                        predicate=lambda e: isinstance(
                            e, DuplicateVoteEvidence),
                        max_time=60.0,
                    )
                sim.assert_safety()
            if verbose:
                heights = {n.idx: n.height() for n in sim.net.nodes}
                print(f"    heights={heights} sim_t={sim.net.now:.1f}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed; iteration i uses seed+i")
    ap.add_argument("--horizon", type=float, default=20.0,
                    help="schedule horizon in simulated seconds")
    ap.add_argument("--ops", type=int, default=6,
                    help="random ops per schedule")
    ap.add_argument("--extra", type=int, default=0,
                    help="passive tail validators; > 0 adds the "
                         "epoch-rotation op to the schedule pool")
    ap.add_argument("--replay", type=str, default=None,
                    help="JSON blob from a failure: run exactly that")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.replay:
        blob = json.loads(args.replay)
        # blobs printed by this tool carry nodes/horizon; harness-level
        # blobs (seed+schedule only) fall back to the CLI flags
        nodes = int(blob.get("nodes", args.nodes))
        horizon = float(blob.get("horizon", args.horizon))
        extra = int(blob.get("extra", args.extra))
        print(f"replaying seed={blob['seed']} nodes={nodes} "
              f"horizon={horizon} extra={extra} "
              f"({len(blob['schedule'])} ops)")
        try:
            run_one(blob["seed"], blob["schedule"], nodes, horizon,
                    True, extra=extra)
        except SimnetFailure as e:
            print(f"REPRODUCED:\n{e}")
            return 1
        print("replay passed (fixed, or environment-dependent?)")
        return 0

    failures = 0
    for i in range(args.iters):
        seed = args.seed + i
        schedule = random_schedule(random.Random(seed), args.nodes,
                                   horizon=args.horizon, n_ops=args.ops,
                                   epochs=args.extra > 0)
        t0 = time.time()
        print(f"[{i + 1}/{args.iters}] seed={seed} "
              f"ops={[op['op'] for op in schedule]}")
        replay_blob = json.dumps(
            {"seed": seed, "schedule": schedule, "nodes": args.nodes,
             "horizon": args.horizon, "extra": args.extra},
            sort_keys=True)
        try:
            run_one(seed, schedule, args.nodes, args.horizon,
                    args.verbose, extra=args.extra)
        except SimnetFailure as e:
            failures += 1
            print(f"  FAILURE:\n{e}\n  replay (self-contained): "
                  f"{replay_blob}", file=sys.stderr)
        except Exception:  # noqa: BLE001 - harness bug: replay blob too
            failures += 1
            import traceback

            traceback.print_exc()
            print("  HARNESS ERROR; replay: " + replay_blob,
                  file=sys.stderr)
        else:
            print(f"  ok ({time.time() - t0:.1f}s)")
    print(f"{args.iters - failures}/{args.iters} schedules passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
