"""Turn Chrome trace files (libs/tracing.py export) into per-stage
critical-path tables — and DIFF two of them.

The perf loop's before/after instrument: run a workload with tracing on
(``bench.py --trace-out``, ``[tracing] enable``, or
``curl $NODE/dump_traces``), feed the file here, and read where the
wall time went per stage — pack vs device flight vs collect vs settle
for the verify plane, per-step time for consensus, fsync cost for the
WAL. BENCH_*.json embeds the same table via ``stage_report``.

Differencing is the regression instrument (ISSUE 6 / ROADMAP open item
1): ``--diff A.trace.json B.trace.json`` aligns the two stage tables
and emits stage-delta and overlap-delta rows with regression flags, so
"where did cfg2's 6.6 ms go" is one command instead of an eyeballing
exercise.

Traces with no verify-plane spans (blocksync-/consensus-only runs)
fall back to a consensus-step table derived from the ``consensus.step``
instants, and the report says so.

Usage:
    python tools/trace_report.py trace.json [--json]
    python tools/trace_report.py --diff A.trace.json B.trace.json \
        [--json] [--threshold-pct 10] [--threshold-ms 0.05] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_symmetric, run_cli)

# verify-plane flush pipeline, in submission order: the critical-path
# section reports these stages first and computes pack/flight overlap
PLANE_STAGES = ("plane.pack", "plane.flight", "plane.collect",
                "plane.verify", "plane.settle")

# diff thresholds: a stage only flags when it moved by BOTH the
# relative and the absolute floor (one guards noise on tiny stages, the
# other on huge-but-stable ones)
DEFAULT_THRESHOLD_PCT = 10.0
DEFAULT_THRESHOLD_MS = 0.05


def load(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def _pct(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def _flight_intervals(events: List[dict]) -> List[tuple]:
    """(ts_begin, ts_end) per async flight id, from b/e event pairs."""
    begun: Dict[str, float] = {}
    out = []
    for e in events:
        if e.get("ph") == "b":
            begun[e.get("id", "")] = e["ts"]
        elif e.get("ph") == "e":
            t0 = begun.pop(e.get("id", ""), None)
            if t0 is not None:
                out.append((t0, e["ts"]))
    return out


def _merge_intervals(intervals: List[tuple]) -> List[tuple]:
    """Union of (lo, hi) intervals as disjoint sorted intervals. The
    deck keeps several flights airborne at once, so overlap math MUST
    run against the union — summing raw per-flight overlaps counted
    the same pack microsecond once per concurrent flight (fractions
    over 1.0 with two flights airborne)."""
    out: List[list] = []
    for lo, hi in sorted(intervals):
        if out and lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [tuple(p) for p in out]


def _overlap_us(span: tuple, intervals: List[tuple]) -> float:
    """Time `span` spends inside `intervals` — exact only when the
    intervals are disjoint (pass them through _merge_intervals)."""
    lo, hi = span
    return sum(max(0.0, min(hi, b) - max(lo, a))
               for a, b in intervals if b > lo and a < hi)


def _deck_occupancy(intervals: List[tuple]) -> dict:
    """Concurrency sweep over the flight intervals: how long >=1 and
    >=2 flights were airborne, and the deepest the deck got — the
    pipelined-halves instrument (one airborne flight at a time means
    the deck never overlapped; ge2 time is chips on BOTH halves busy)."""
    events = sorted([(lo, 1) for lo, hi in intervals]
                    + [(hi, -1) for lo, hi in intervals])
    depth = 0
    ge1 = ge2 = 0.0
    deepest = 0
    prev = None
    for t, d in events:
        if prev is not None and depth >= 1:
            ge1 += t - prev
            if depth >= 2:
                ge2 += t - prev
        depth += d
        deepest = max(deepest, depth)
        prev = t
    return {"ge1_us": ge1, "ge2_us": ge2, "max_airborne": deepest}


def _consensus_step_durations(events: List[dict]) -> Dict[str, List[float]]:
    """Per-step dwell times (us) reconstructed from ``consensus.step``
    instants: each instant marks ENTERING a step, so a step's duration
    is the gap to the next step instant on the same thread. The open
    tail (last instant per thread) has no end and is dropped."""
    by_tid: Dict[int, List[tuple]] = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") == "consensus.step":
            step = (e.get("args") or {}).get("step", "?")
            by_tid.setdefault(e.get("tid", 0), []).append(
                (e["ts"], str(step)))
    out: Dict[str, List[float]] = {}
    for seq in by_tid.values():
        seq.sort(key=lambda p: p[0])
        for (t0, step), (t1, _) in zip(seq, seq[1:]):
            out.setdefault(f"step.{step}", []).append(t1 - t0)
    return out


def _row(name: str, durs: List[float]) -> dict:
    return {
        "stage": name,
        "count": len(durs),
        "total_ms": round(sum(durs) / 1000.0, 3),
        "mean_ms": round(sum(durs) / len(durs) / 1000.0, 4)
        if durs else 0.0,
        "p50_ms": round(_pct(durs, 0.5) / 1000.0, 4),
        "max_ms": round(max(durs) / 1000.0, 4) if durs else 0.0,
    }


def stage_report(events: List[dict]) -> dict:
    """Aggregate a trace into {stages, instants, plane} — the table the
    bench embeds and main() pretty-prints.

    stages: per span name, count + total/mean/p50/max ms.
    instants: per instant name, count.
    plane: flush-pipeline extras — flight count/total from the async
    b/e pairs, the fraction of pack time hidden behind an airborne
    flight (computed against the UNION of flight intervals, so several
    concurrent deck flights never double-count a pack microsecond),
    and the deck occupancy sweep: fraction of trace wall time with >=1
    and >=2 flights airborne (the pipelined-halves instrument — a
    healthy deck shows ge2 occupancy, not just a boolean overlap).
    fallback: set (with a human note) when the trace holds no
    verify-plane spans and the stage table was derived from the
    consensus-step instants instead.
    """
    spans: Dict[str, List[float]] = {}
    instants: Dict[str, int] = {}
    pack_spans = []
    t_lo = t_hi = None
    for e in events:
        ph = e.get("ph")
        ts = e.get("ts")
        if ts is not None:
            end = ts + e.get("dur", 0.0)
            t_lo = ts if t_lo is None else min(t_lo, ts)
            t_hi = end if t_hi is None else max(t_hi, end)
        if ph == "X":
            spans.setdefault(e["name"], []).append(e.get("dur", 0.0))
            if e["name"] == "plane.pack":
                pack_spans.append((e["ts"], e["ts"] + e.get("dur", 0.0)))
        elif ph == "i":
            instants[e["name"]] = instants.get(e["name"], 0) + 1
    flights = _flight_intervals(events)

    fallback = None
    if not any(n in spans for n in PLANE_STAGES):
        # consensus-/blocksync-only trace: no flush pipeline to report.
        # Fall back to the per-step dwell table so the report is never
        # empty on a trace that plainly recorded consensus activity.
        steps = _consensus_step_durations(events)
        if steps:
            for name, durs in steps.items():
                spans.setdefault(name, durs)
            fallback = ("no verify-plane spans in this trace; stage "
                        "table includes consensus-step dwell times "
                        "derived from consensus.step instants")

    # plane stages first (pipeline order), then everything else by
    # total time descending — the critical path reads top-down
    ordered = [n for n in PLANE_STAGES if n in spans]
    rest = sorted((n for n in spans if n not in PLANE_STAGES),
                  key=lambda n: -sum(spans[n]))
    stages = [_row(n, spans[n]) for n in ordered + rest]

    plane: Optional[dict] = None
    if flights or pack_spans:
        flight_total = sum(b - a for a, b in flights)
        pack_total = sum(b - a for a, b in pack_spans)
        # union first: with the deck, pack(k+2) can overlap TWO
        # airborne flights — per-flight sums would count it twice
        merged = _merge_intervals(flights)
        overlapped = sum(_overlap_us(p, merged) for p in pack_spans)
        occ = _deck_occupancy(flights)
        wall = (t_hi - t_lo) if (t_lo is not None and t_hi > t_lo) \
            else 0.0
        plane = {
            "flights": len(flights),
            "flight_total_ms": round(flight_total / 1000.0, 3),
            "pack_total_ms": round(pack_total / 1000.0, 3),
            "pack_overlapped_ms": round(overlapped / 1000.0, 3),
            "pack_overlap_frac": round(overlapped / pack_total, 3)
            if pack_total else 0.0,
            # fused flushes that paid a valset table build/patch inline
            # (plane.cold_table instants): a steady stream should show
            # 0 — nonzero localizes a post-rotation stall the next-
            # epoch warmer should have absorbed
            "cold_tables": instants.get("plane.cold_table", 0),
            "deck": {
                "max_airborne": occ["max_airborne"],
                "airborne_ge1_ms": round(occ["ge1_us"] / 1000.0, 3),
                "airborne_ge2_ms": round(occ["ge2_us"] / 1000.0, 3),
                "occupancy_ge1": round(occ["ge1_us"] / wall, 3)
                if wall else 0.0,
                "occupancy_ge2": round(occ["ge2_us"] / wall, 3)
                if wall else 0.0,
            },
        }
    return {"stages": stages, "instants": instants, "plane": plane,
            "events": len(events), "fallback": fallback}


# --------------------------------------------------------------------------
# differencing
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_ms: float = DEFAULT_THRESHOLD_MS) -> dict:
    """Align two stage_report outputs (A = before, B = after) into
    stage-delta rows + an overlap-delta block with regression flags.

    A stage REGRESSED when its mean grew by more than BOTH thresholds
    (relative + absolute); it improved when it shrank by the same
    margin. Stages present on only one side are flagged too (appeared
    = new cost, vanished = cost removed or stage renamed)."""
    a_by = {r["stage"]: r for r in rep_a.get("stages", [])}
    b_by = {r["stage"]: r for r in rep_b.get("stages", [])}
    order = [r["stage"] for r in rep_a.get("stages", [])]
    order += [s for s in (r["stage"] for r in rep_b.get("stages", []))
              if s not in a_by]

    def flag_of(ma: float, mb: float) -> str:
        return flag_symmetric(ma, mb, threshold_pct=threshold_pct,
                              abs_floor=threshold_ms)

    rows = []
    for name in order:
        ra, rb = a_by.get(name), b_by.get(name)
        if ra is None or rb is None:
            rows.append({
                "stage": name,
                "flag": "appeared" if ra is None else "vanished",
                "count_a": ra["count"] if ra else 0,
                "count_b": rb["count"] if rb else 0,
                "mean_ms_a": ra["mean_ms"] if ra else 0.0,
                "mean_ms_b": rb["mean_ms"] if rb else 0.0,
                "total_ms_a": ra["total_ms"] if ra else 0.0,
                "total_ms_b": rb["total_ms"] if rb else 0.0,
                "delta_mean_ms": round(
                    (rb["mean_ms"] if rb else 0.0)
                    - (ra["mean_ms"] if ra else 0.0), 4),
                "delta_total_ms": round(
                    (rb["total_ms"] if rb else 0.0)
                    - (ra["total_ms"] if ra else 0.0), 3),
                "delta_pct": None,
            })
            continue
        d_mean = rb["mean_ms"] - ra["mean_ms"]
        rows.append({
            "stage": name,
            "flag": flag_of(ra["mean_ms"], rb["mean_ms"]),
            "count_a": ra["count"], "count_b": rb["count"],
            "mean_ms_a": ra["mean_ms"], "mean_ms_b": rb["mean_ms"],
            "total_ms_a": ra["total_ms"], "total_ms_b": rb["total_ms"],
            "delta_mean_ms": round(d_mean, 4),
            "delta_total_ms": round(rb["total_ms"] - ra["total_ms"], 3),
            "delta_pct": round(d_mean / ra["mean_ms"] * 100.0, 1)
            if ra["mean_ms"] else None,
        })

    overlap = None
    pa, pb = rep_a.get("plane"), rep_b.get("plane")
    if pa or pb:
        fa = (pa or {}).get("pack_overlap_frac", 0.0)
        fb = (pb or {}).get("pack_overlap_frac", 0.0)
        da = (pa or {}).get("deck") or {}
        db = (pb or {}).get("deck") or {}
        overlap = {
            "pack_overlap_frac_a": fa,
            "pack_overlap_frac_b": fb,
            "delta": round(fb - fa, 3),
            # deck occupancy deltas: losing ge2 time means the halves
            # stopped flying concurrently (informational — the flag
            # below still keys on pack overlap + flights vanishing)
            "occupancy_ge2_a": da.get("occupancy_ge2", 0.0),
            "occupancy_ge2_b": db.get("occupancy_ge2", 0.0),
            "max_airborne_a": da.get("max_airborne", 0),
            "max_airborne_b": db.get("max_airborne", 0),
            "flights_a": (pa or {}).get("flights", 0),
            "flights_b": (pb or {}).get("flights", 0),
            "flight_total_ms_a": (pa or {}).get("flight_total_ms", 0.0),
            "flight_total_ms_b": (pb or {}).get("flight_total_ms", 0.0),
            # losing overlap means pack time stopped hiding behind the
            # device — the double buffer stopped paying. Flights
            # vanishing entirely is the worst case of that (the plane
            # degraded to synchronous/host flushes).
            "flag": "REGRESSED"
            if (fb < fa - 0.05
                or ((pa or {}).get("flights", 0) > 0
                    and not (pb or {}).get("flights", 0)))
            else ("improved" if fb > fa + 0.05 else ""),
        }

    # an appeared stage is only a REGRESSION when its new cost clears
    # the absolute threshold — a trivial span the before-run happened
    # not to hit must not fail a --fail-on-regression CI gate
    regressions = [r["stage"] for r in rows
                   if r["flag"] == "REGRESSED"
                   or (r["flag"] == "appeared"
                       and r["mean_ms_b"] >= threshold_ms)]
    if overlap and overlap["flag"] == "REGRESSED":
        regressions.append("pack_overlap_frac")
    notes = [n for n in (rep_a.get("fallback"), rep_b.get("fallback"))
             if n]
    return {"stages": rows, "overlap": overlap,
            "regressions": regressions, "notes": notes,
            "events_a": rep_a.get("events", 0),
            "events_b": rep_b.get("events", 0)}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    lines = [f"{rep['events']} trace events"]
    if rep.get("fallback"):
        lines.append(f"NOTE: {rep['fallback']}")
    lines += ["", f"{'stage':<26}{'count':>7}{'total ms':>11}"
                  f"{'mean ms':>10}{'p50 ms':>10}{'max ms':>10}"]
    for r in rep["stages"]:
        lines.append(f"{r['stage']:<26}{r['count']:>7}"
                     f"{r['total_ms']:>11.3f}{r['mean_ms']:>10.4f}"
                     f"{r['p50_ms']:>10.4f}{r['max_ms']:>10.4f}")
    if rep["plane"]:
        p = rep["plane"]
        lines += ["",
                  f"verify-plane flights: {p['flights']} "
                  f"({p['flight_total_ms']} ms airborne); "
                  f"pack {p['pack_total_ms']} ms, "
                  f"{p['pack_overlapped_ms']} ms "
                  f"({p['pack_overlap_frac']:.0%}) hidden behind flights"]
        if p.get("cold_tables"):
            lines.append(
                f"COLD TABLES: {p['cold_tables']} fused flush(es) paid "
                f"a valset table build inline (post-rotation stall — "
                f"check the next-epoch warmer)")
        d = p.get("deck")
        if d:
            lines.append(
                f"deck occupancy: >=1 flight {d['occupancy_ge1']:.0%} "
                f"of wall ({d['airborne_ge1_ms']} ms), >=2 flights "
                f"{d['occupancy_ge2']:.0%} ({d['airborne_ge2_ms']} ms),"
                f" max airborne {d['max_airborne']}")
    if rep["instants"]:
        lines += ["", "instants: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(rep["instants"].items()))]
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A", path_b: str = "B") -> str:
    lines = [f"stage-delta: {path_a} ({diff['events_a']} events) -> "
             f"{path_b} ({diff['events_b']} events)"]
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", f"{'stage':<22}{'cnt A':>6}{'cnt B':>6}"
                  f"{'mean A':>9}{'mean B':>9}{'Δ ms':>9}{'Δ %':>8}"
                  f"  {'flag'}"]
    for r in diff["stages"]:
        pct = f"{r['delta_pct']:+.1f}" if r["delta_pct"] is not None \
            else "-"
        lines.append(
            f"{r['stage']:<22}{r['count_a']:>6}{r['count_b']:>6}"
            f"{r['mean_ms_a']:>9.4f}{r['mean_ms_b']:>9.4f}"
            f"{r['delta_mean_ms']:>+9.4f}{pct:>8}  {r['flag']}")
    if diff["overlap"]:
        o = diff["overlap"]
        lines += ["",
                  f"overlap-delta: pack_overlap_frac "
                  f"{o['pack_overlap_frac_a']:.3f} -> "
                  f"{o['pack_overlap_frac_b']:.3f} (Δ {o['delta']:+.3f})"
                  f" flights {o['flights_a']}->{o['flights_b']}"
                  f" deck-ge2 {o['occupancy_ge2_a']:.3f}->"
                  f"{o['occupancy_ge2_b']:.3f}"
                  + (f"  {o['flag']}" if o["flag"] else "")]
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"] else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "per-stage critical-path table from a Chrome trace, or a "
        "stage-delta diff of two traces",
        operand="traces",
        operand_help="trace file(s) (libs/tracing export); two files "
                     "with --diff",
        diff_help="diff two traces: stage-delta + overlap-delta "
                  "tables with regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_MS,
        pct_help="relative regression floor (mean ms, %%)",
        abs_flag="--threshold-ms",
        abs_help="absolute regression floor (mean ms)")
    return run_cli(argv, parser=ap, load=load, report=stage_report,
                   diff=diff_report, fmt_report=format_report,
                   fmt_diff=format_diff, operand="traces",
                   noun="trace")



if __name__ == "__main__":
    raise SystemExit(main())
