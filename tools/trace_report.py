"""Turn a Chrome trace file (libs/tracing.py export) into a per-stage
critical-path table.

The perf loop's before/after instrument: run a workload with tracing on
(``bench.py --trace-out``, ``[tracing] enable``, or
``curl $NODE/dump_traces``), feed the file here, and read where the
wall time went per stage — pack vs device flight vs collect vs settle
for the verify plane, per-step time for consensus, fsync cost for the
WAL. BENCH_*.json embeds the same table via ``stage_report``.

Usage:
    python tools/trace_report.py trace.json [--json]
"""
from __future__ import annotations

import argparse
import json
from typing import Dict, List, Optional

# verify-plane flush pipeline, in submission order: the critical-path
# section reports these stages first and computes pack/flight overlap
PLANE_STAGES = ("plane.pack", "plane.flight", "plane.collect",
                "plane.settle")


def load(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def _pct(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def _flight_intervals(events: List[dict]) -> List[tuple]:
    """(ts_begin, ts_end) per async flight id, from b/e event pairs."""
    begun: Dict[str, float] = {}
    out = []
    for e in events:
        if e.get("ph") == "b":
            begun[e.get("id", "")] = e["ts"]
        elif e.get("ph") == "e":
            t0 = begun.pop(e.get("id", ""), None)
            if t0 is not None:
                out.append((t0, e["ts"]))
    return out


def _overlap_us(span: tuple, intervals: List[tuple]) -> float:
    lo, hi = span
    return sum(max(0.0, min(hi, b) - max(lo, a))
               for a, b in intervals if b > lo and a < hi)


def stage_report(events: List[dict]) -> dict:
    """Aggregate a trace into {stages, instants, plane} — the table the
    bench embeds and main() pretty-prints.

    stages: per span name, count + total/mean/p50/max ms.
    instants: per instant name, count.
    plane: flush-pipeline extras — flight count/total from the async
    b/e pairs and the fraction of pack time hidden behind an airborne
    flight (the double-buffer overlap the dispatcher exists to win).
    """
    spans: Dict[str, List[float]] = {}
    instants: Dict[str, int] = {}
    pack_spans = []
    for e in events:
        ph = e.get("ph")
        if ph == "X":
            spans.setdefault(e["name"], []).append(e.get("dur", 0.0))
            if e["name"] == "plane.pack":
                pack_spans.append((e["ts"], e["ts"] + e.get("dur", 0.0)))
        elif ph == "i":
            instants[e["name"]] = instants.get(e["name"], 0) + 1
    flights = _flight_intervals(events)

    def row(name: str, durs: List[float]) -> dict:
        return {
            "stage": name,
            "count": len(durs),
            "total_ms": round(sum(durs) / 1000.0, 3),
            "mean_ms": round(sum(durs) / len(durs) / 1000.0, 4)
            if durs else 0.0,
            "p50_ms": round(_pct(durs, 0.5) / 1000.0, 4),
            "max_ms": round(max(durs) / 1000.0, 4) if durs else 0.0,
        }

    # plane stages first (pipeline order), then everything else by
    # total time descending — the critical path reads top-down
    ordered = [n for n in PLANE_STAGES if n in spans]
    rest = sorted((n for n in spans if n not in PLANE_STAGES),
                  key=lambda n: -sum(spans[n]))
    stages = [row(n, spans[n]) for n in ordered + rest]

    plane: Optional[dict] = None
    if flights or pack_spans:
        flight_total = sum(b - a for a, b in flights)
        pack_total = sum(b - a for a, b in pack_spans)
        overlapped = sum(_overlap_us(p, flights) for p in pack_spans)
        plane = {
            "flights": len(flights),
            "flight_total_ms": round(flight_total / 1000.0, 3),
            "pack_total_ms": round(pack_total / 1000.0, 3),
            "pack_overlapped_ms": round(overlapped / 1000.0, 3),
            "pack_overlap_frac": round(overlapped / pack_total, 3)
            if pack_total else 0.0,
        }
    return {"stages": stages, "instants": instants, "plane": plane,
            "events": len(events)}


def format_report(rep: dict) -> str:
    lines = [f"{rep['events']} trace events",
             "", f"{'stage':<26}{'count':>7}{'total ms':>11}"
                 f"{'mean ms':>10}{'p50 ms':>10}{'max ms':>10}"]
    for r in rep["stages"]:
        lines.append(f"{r['stage']:<26}{r['count']:>7}"
                     f"{r['total_ms']:>11.3f}{r['mean_ms']:>10.4f}"
                     f"{r['p50_ms']:>10.4f}{r['max_ms']:>10.4f}")
    if rep["plane"]:
        p = rep["plane"]
        lines += ["",
                  f"verify-plane flights: {p['flights']} "
                  f"({p['flight_total_ms']} ms airborne); "
                  f"pack {p['pack_total_ms']} ms, "
                  f"{p['pack_overlapped_ms']} ms "
                  f"({p['pack_overlap_frac']:.0%}) hidden behind flights"]
    if rep["instants"]:
        lines += ["", "instants: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(rep["instants"].items()))]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-stage critical-path table from a Chrome trace")
    ap.add_argument("trace", help="trace file (libs/tracing export)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)
    rep = stage_report(load(args.trace))
    print(json.dumps(rep) if args.json else format_report(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
