"""Turn a /dump_devices document into compile/residency/utilization
tables — and DIFF two of them.

The device-plane sibling of tools/trace_report.py, height_report.py,
and peer_report.py: where those decompose a FLUSH, a BLOCK, and the
GOSSIP, this decomposes the DEVICE — per compile site: count, total
ms, steady-state recompiles (the round-5 regression class),
persistent-cache hits; per family x device: resident bytes, pinned
valset slots, headroom against the 65536-slot/chip budget; plus the
flush ledger's device-time split (comp/h2d/dev ms, utilization) when
the dump carries it. Feed it a saved ``curl $NODE/dump_devices`` file
or a bench --json-out evidence file with an embedded ``device_dump``.

Differencing mirrors trace_report --diff: counter/figure delta rows
with REGRESSED/improved flags past BOTH a relative and an absolute
threshold, and ``--fail-on-regression`` for CI gates (requires --diff
— a gate wired without a comparison must error, not read permanently
green). Flags: compile-count and compile-seconds growth, ANY
steady-state recompile growth (absolute threshold 0 — one is a bug),
residency growth, headroom shrink, and utilization collapse.

Usage:
    python tools/device_report.py dump.json [--json]
    python tools/device_report.py --diff A.json B.json \
        [--json] [--threshold-pct 25] [--threshold-abs 8] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_directional, run_cli)

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_THRESHOLD_ABS = 8.0


def load_devices(path: str) -> dict:
    """Extract a device dump from any supported shape: a /dump_devices
    document, a bench --json-out evidence file carrying
    ``extra.device_dump``, or a bare {"summary": ..., "compiles": ...}
    object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "compiles" in doc \
            and "summary" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            dd = extra.get("device_dump")
            if dd and dd.get("compiles") is not None:
                return dd
    raise ValueError(
        f"{path}: no device records found (want a /dump_devices "
        f"document or a bench --json-out file with an embedded "
        f"device_dump)")


def device_report(dump: dict) -> dict:
    """Aggregate a device dump into the tables the text report prints
    and the diff compares."""
    summary = dict(dump.get("summary", {}))
    compiles = list(dump.get("compiles", []))
    sites: dict = {}
    for c in compiles:
        site = c.get("site") or "?"
        row = sites.setdefault(site, {"site": site, "compiles": 0,
                                      "ms": 0.0, "steady": 0,
                                      "pcache": 0})
        if c.get("pcache_hit"):
            row["pcache"] += 1
        else:
            row["compiles"] += 1
            row["ms"] = round(row["ms"] + c.get("dur_ms", 0.0), 3)
        if c.get("steady"):
            row["steady"] += 1
    res_rows = []
    for fam, devs in sorted((dump.get("residency") or {}).items()):
        for dev, slot in sorted(devs.items()):
            res_rows.append({"family": fam, "dev": dev,
                             "bytes": slot.get("bytes", 0),
                             "slots": slot.get("slots", 0)})
    head = {str(k): v
            for k, v in (dump.get("headroom_rows") or {}).items()}
    fl = dump.get("flushes") or {}
    return {
        "compiles": summary.get("compiles", 0),
        "compile_s": summary.get("compile_s", 0.0),
        "pcache_hits": summary.get("pcache_hits", 0),
        "steady_compiles": summary.get("steady_compiles", 0),
        "steady": summary.get("steady", False),
        "sites": sorted(sites.values(),
                        key=lambda r: -(r["ms"] + r["pcache"])),
        "resident_bytes": summary.get("resident_bytes", 0),
        "families": summary.get("families", {}),
        "residency_rows": res_rows,
        "headroom_min": min(head.values()) if head else None,
        "headroom": head,
        "util_p50": (fl.get("util") or {}).get("p50", 0.0),
        "dev_ms_p50": (fl.get("dev_ms") or {}).get("p50", 0.0),
        "flush_comp_ms": fl.get("comp_ms", 0.0),
        "reconcile": dump.get("reconcile", {}),
        # ISSUE 20 kernel cost surfaces (absent on dumps from builds
        # predating the recorder)
        "cost_surfaces": list(dump.get("cost_surfaces") or []),
        "cost_counters": dict(dump.get("cost_counters") or {}),
    }


# --------------------------------------------------------------------------
# differencing (trace_report --diff's shape, over the device figures)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_abs: float = DEFAULT_THRESHOLD_ABS) -> dict:
    """Device-figure delta rows (A = before, B = after). Growth is bad
    for compiles/residency, shrink is bad for headroom/util; a figure
    REGRESSED past BOTH thresholds — except steady_compiles, where ANY
    growth flags (one steady recompile is the round-5 bug class)."""

    def flag_of(a: float, b: float, bad_dir: int = +1,
                abs_floor: float = threshold_abs,
                any_growth: bool = False) -> str:
        # any_growth: the relative threshold is waived — one more
        # steady recompile flags no matter how big the baseline is
        return flag_directional(a, b, threshold_pct=threshold_pct,
                                abs_floor=abs_floor, bad_dir=bad_dir,
                                any_growth=any_growth)

    rows = [
        {"metric": "compiles", "a": rep_a["compiles"],
         "b": rep_b["compiles"],
         "flag": flag_of(rep_a["compiles"], rep_b["compiles"])},
        {"metric": "compile_s", "a": rep_a["compile_s"],
         "b": rep_b["compile_s"],
         "flag": flag_of(rep_a["compile_s"], rep_b["compile_s"],
                         abs_floor=1.0)},
        # one steady-state recompile is a bug: ANY growth flags — no
        # relative threshold can excuse the round-5 class
        {"metric": "steady_compiles", "a": rep_a["steady_compiles"],
         "b": rep_b["steady_compiles"],
         "flag": flag_of(rep_a["steady_compiles"],
                         rep_b["steady_compiles"], abs_floor=1.0,
                         any_growth=True)},
        {"metric": "resident_bytes", "a": rep_a["resident_bytes"],
         "b": rep_b["resident_bytes"],
         "flag": flag_of(rep_a["resident_bytes"],
                         rep_b["resident_bytes"],
                         abs_floor=max(threshold_abs, 1 << 16))},
    ]
    for r in rows:
        r["delta"] = round(r["b"] - r["a"], 3)
    ha, hb = rep_a["headroom_min"], rep_b["headroom_min"]
    if ha is not None or hb is not None:
        ha = 0 if ha is None else ha
        hb = 0 if hb is None else hb
        rows.append({"metric": "headroom_rows_min", "a": ha, "b": hb,
                     "delta": hb - ha,
                     "flag": flag_of(ha, hb, bad_dir=-1,
                                     abs_floor=128)})
    ua, ub = rep_a["util_p50"], rep_b["util_p50"]
    if ua or ub:
        rows.append({"metric": "util_p50", "a": ua, "b": ub,
                     "delta": round(ub - ua, 4),
                     "flag": flag_of(ua, ub, bad_dir=-1,
                                     abs_floor=0.05)})
    # kernel cost surfaces: a cell whose marginal ms-per-row grew past
    # both thresholds is a MARGINAL-COST REGRESSION — the same jit
    # family at the same shape charging more per row than it used to
    cs_a = {(r["family"], r["rows_bucket"], r["n_dev"]): r
            for r in rep_a["cost_surfaces"]}
    for r in rep_b["cost_surfaces"]:
        key = (r["family"], r["rows_bucket"], r["n_dev"])
        before = cs_a.get(key)
        if before is None:
            continue
        ma = before.get("marginal_ms_per_row")
        mb = r.get("marginal_ms_per_row")
        if ma is None or mb is None:
            continue
        fl = flag_of(ma, mb, abs_floor=0.001)
        if fl:
            fam, bucket, n_dev = key
            rows.append({
                "metric": f"marginal_ms_per_row"
                          f"[{fam}@{bucket}x{n_dev}]",
                "a": ma, "b": mb, "delta": round(mb - ma, 6),
                "flag": fl})

    notes = []
    sites_b = {r["site"]: r for r in rep_b["sites"]}
    sites_a = {r["site"]: r for r in rep_a["sites"]}
    for site, row in sites_b.items():
        grew = row["compiles"] - sites_a.get(
            site, {"compiles": 0})["compiles"]
        if row["steady"] and grew > 0:
            notes.append(
                f"steady-state recompiles at {site}: "
                f"{row['steady']} steady / {grew} new compiles — the "
                f"round-5 class; pull /dump_incidents for a "
                f"compile_storm snapshot and /dump_flushes comp_ms "
                f"for the flushes that paid")
    da, db = rep_a["reconcile"], rep_b["reconcile"]
    if db.get("table_drift") or da.get("table_drift"):
        notes.append(
            f"residency accounting drift: "
            f"{da.get('table_drift', 0)} -> {db.get('table_drift', 0)} "
            f"bytes (the per-device split and the cache truth "
            f"disagree — neither number is trustworthy)")

    regressions = [r["metric"] for r in rows if r["flag"] == "REGRESSED"]
    return {"rows": rows, "regressions": regressions, "notes": notes}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    lines = [
        f"compiles: {rep['compiles']} backend "
        f"({rep['compile_s']} s), {rep['pcache_hits']} pcache hits, "
        f"{rep['steady_compiles']} STEADY-STATE"
        + (" (steady declared)" if rep["steady"] else
           " (steady never declared)")]
    if rep["sites"]:
        lines += ["", f"{'site':<26}{'compiles':>9}{'ms':>10}"
                      f"{'steady':>7}{'pcache':>7}"]
        for r in rep["sites"]:
            lines.append(f"{r['site']:<26}{r['compiles']:>9}"
                         f"{r['ms']:>10.1f}{r['steady']:>7}"
                         f"{r['pcache']:>7}")
    if rep["residency_rows"]:
        lines += ["", f"{'family':<16}{'dev':>6}{'bytes':>14}"
                      f"{'slots':>9}"]
        for r in rep["residency_rows"]:
            lines.append(f"{r['family']:<16}{r['dev']:>6}"
                         f"{r['bytes']:>14}{r['slots']:>9}")
        lines.append(
            f"resident total: {rep['resident_bytes']} B; per-chip "
            f"headroom min {rep['headroom_min']} of 65536 valset "
            f"slots")
    if rep["util_p50"] or rep["dev_ms_p50"]:
        lines.append(
            f"flush device split: util p50 {rep['util_p50']}, dev_ms "
            f"p50 {rep['dev_ms_p50']}, compile ms charged to flushes "
            f"{rep['flush_comp_ms']}")
    if rep["cost_surfaces"]:
        cc = rep["cost_counters"]
        lines += ["", f"cost surfaces ({cc.get('observed', 0)} flush "
                      f"observations, {cc.get('cells', 0)} cells):",
                  f"{'family':<22}{'rows<=':>8}{'ndev':>5}{'n':>5}"
                  f"{'dev p50':>9}{'dev p95':>9}{'h2d p50':>9}"
                  f"{'ms/row':>10}"]
        for r in rep["cost_surfaces"]:
            marg = r.get("marginal_ms_per_row")
            lines.append(
                f"{r['family']:<22}{r['rows_bucket']:>8}"
                f"{r['n_dev']:>5}{r['n']:>5}{r['dev_ms_p50']:>9}"
                f"{r['dev_ms_p95']:>9}{r['h2d_ms_p50']:>9}"
                f"{marg if marg is not None else '-':>10}")
    rc = rep["reconcile"]
    if rc:
        drift = rc.get("table_drift", 0)
        lines.append(
            f"accounting cross-check: split {rc.get('table_bytes_split')}"
            f" vs cache {rc.get('table_bytes_cache')} "
            + ("(exact)" if not drift else f"DRIFT {drift} B"))
    if rep["steady_compiles"]:
        lines.append(
            f"STEADY-STATE RECOMPILES: {rep['steady_compiles']} — the "
            f"round-5 regression class; check /dump_incidents for a "
            f"compile_storm snapshot and the site table above for WHO")
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A", path_b: str = "B") -> str:
    lines = [f"device-plane delta: {path_a} -> {path_b}",
             "", f"{'metric':<20}{'A':>12}{'B':>12}{'Δ':>12}  flag"]
    for r in diff["rows"]:
        lines.append(f"{r['metric']:<20}{r['a']:>12}{r['b']:>12}"
                     f"{r['delta']:>+12}  {r['flag']}")
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"] else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "compile/residency/utilization tables from a /dump_devices "
        "document, or a device-figure delta diff of two of them",
        operand_help="device dump file(s); two files with --diff",
        diff_help="diff two dumps: device-figure delta table with "
                  "regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_ABS,
        abs_help="absolute regression floor (count / bytes)")
    return run_cli(argv, parser=ap, load=load_devices,
                   report=device_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
