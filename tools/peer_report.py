"""Turn a /dump_peers document into a per-peer traffic/health table —
and DIFF two of them.

The p2p-level sibling of tools/trace_report.py and
tools/height_report.py: where those decompose a FLUSH and a BLOCK,
this decomposes the GOSSIP PLANE — per peer: msgs/bytes each way, send
queue high-water, blocked puts, full-queue drops, throttle stalls,
link drops, injected-fault attribution, ping RTT, and duplicate-vote
receipts. Feed it a saved ``curl $NODE/dump_peers`` file or any JSON
holding a ``peers`` list.

Differencing mirrors trace_report --diff: health-counter delta rows
with REGRESSED/improved flags past BOTH a relative and an absolute
threshold, and ``--fail-on-regression`` for CI gates (requires --diff
— a gate wired without a comparison must error, not read permanently
green). Counters here are cumulative-by-construction, so the diff
compares the two windows' TOTALS: growth in drops/stalls/RTT between
two captures of the same node is a real health change.

Usage:
    python tools/peer_report.py dump.json [--json]
    python tools/peer_report.py --diff A.json B.json \
        [--json] [--threshold-pct 25] [--threshold-abs 8] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_symmetric, run_cli)

# aggregate health counters the diff flags on: bigger = sicker
HEALTH_KEYS = ("blocked_puts", "full_drops", "throttle_stalls",
               "link_drops", "inj_drops", "inj_delays", "dup_votes")

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_THRESHOLD_ABS = 8.0


def load_peers(path: str) -> dict:
    """Extract {summary, peers, events} from any supported shape: a
    /dump_peers document, a bench --json-out evidence file carrying
    ``extra.peer_dump``, or a bare {"peers": [...]} object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "peers" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            pd = extra.get("peer_dump")
            if pd and pd.get("peers") is not None:
                return pd
    raise ValueError(
        f"{path}: no peer records found (want a /dump_peers document "
        f"or a bench --json-out file with an embedded peer_dump)")


def peer_report(dump: dict) -> dict:
    """Aggregate a peer dump into the table + totals the text report
    prints and the diff compares."""
    peers = list(dump.get("peers", []))
    summary = dict(dump.get("summary", {}))
    rows = []
    for p in peers:
        rows.append({
            "peer": p.get("peer", "?"),
            "dir": p.get("dir", "?"),
            "state": p.get("state", "?")
            + (f"({p['reason']})" if p.get("reason") else ""),
            "msgs_tx": p.get("msgs_tx", 0),
            "bytes_tx": p.get("bytes_tx", 0),
            "msgs_rx": p.get("msgs_rx", 0),
            "bytes_rx": p.get("bytes_rx", 0),
            "q_hiwater": p.get("q_hiwater", 0),
            "blocked_puts": p.get("blocked_puts", 0),
            "full_drops": p.get("full_drops", 0),
            "throttle_stalls": p.get("throttle_stalls", 0),
            "link_drops": p.get("link_drops", 0),
            "inj": p.get("inj_drops", 0) + p.get("inj_delays", 0),
            "rtt_ms": p.get("rtt_ms", 0.0),
            "dup_votes": p.get("dup_votes", 0),
        })
    # prefer the dump's summary totals: they fold in ring-evicted
    # records, so they stay monotone across captures (the per-peer
    # rows are only the retained window); fall back to summing rows
    # for bare {"peers": [...]} inputs
    totals = {k: int(summary.get(k, sum(p.get(k, 0) for p in peers)))
              for k in HEALTH_KEYS}
    rtts = sorted(p.get("rtt_ms", 0.0) for p in peers
                  if p.get("pings", 0))
    return {
        "peers": len(peers),
        "peers_live": summary.get("peers_live", 0),
        "peers_dropped": summary.get("peers_dropped", 0),
        "rows": rows,
        "totals": totals,
        "msgs_tx": summary.get("msgs_tx", 0),
        "msgs_rx": summary.get("msgs_rx", 0),
        "bytes_tx": summary.get("bytes_tx", 0),
        "bytes_rx": summary.get("bytes_rx", 0),
        "rtt_p50_ms": rtts[len(rtts) // 2] if rtts else 0.0,
        "rtt_max_ms": rtts[-1] if rtts else 0.0,
        "q_hiwater": max((p.get("q_hiwater", 0) for p in peers),
                         default=0),
        "votes": summary.get("votes", {}),
        "events": len(dump.get("events", [])),
    }


# --------------------------------------------------------------------------
# differencing (trace_report --diff's shape, over the health totals)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_abs: float = DEFAULT_THRESHOLD_ABS) -> dict:
    """Health-counter delta rows (A = before, B = after): a counter
    REGRESSED when it grew past BOTH the relative and absolute
    thresholds (relative guards big-but-stable counters, absolute
    guards noise on tiny ones); RTT p50 diffs as its own row."""

    def flag_of(a: float, b: float) -> str:
        return flag_symmetric(a, b, threshold_pct=threshold_pct,
                              abs_floor=threshold_abs)

    rows = []
    for key in HEALTH_KEYS:
        a = rep_a["totals"].get(key, 0)
        b = rep_b["totals"].get(key, 0)
        rows.append({"metric": key, "a": a, "b": b, "delta": b - a,
                     "flag": flag_of(a, b)})
    a_rtt, b_rtt = rep_a["rtt_p50_ms"], rep_b["rtt_p50_ms"]
    rows.append({"metric": "rtt_p50_ms", "a": a_rtt, "b": b_rtt,
                 "delta": round(b_rtt - a_rtt, 3),
                 "flag": flag_of(a_rtt, b_rtt)})
    a_q, b_q = rep_a["q_hiwater"], rep_b["q_hiwater"]
    rows.append({"metric": "q_hiwater", "a": a_q, "b": b_q,
                 "delta": b_q - a_q, "flag": flag_of(a_q, b_q)})

    notes = []
    if rep_b["peers_dropped"] > rep_a["peers_dropped"]:
        notes.append(
            f"peer churn grew: {rep_a['peers_dropped']} -> "
            f"{rep_b['peers_dropped']} dropped peers (check the "
            f"lifecycle events for the drop reasons)")
    dup_a = rep_a.get("votes", {}).get("dups", 0)
    dup_b = rep_b.get("votes", {}).get("dups", 0)
    if dup_b > max(2 * dup_a, dup_a + threshold_abs):
        notes.append(
            f"duplicate vote deliveries grew: {dup_a} -> {dup_b} "
            f"(lack-based gossip healing is lagging)")

    regressions = [r["metric"] for r in rows if r["flag"] == "REGRESSED"]
    return {"rows": rows, "regressions": regressions, "notes": notes,
            "peers_a": rep_a["peers"], "peers_b": rep_b["peers"]}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    lines = [f"{rep['peers']} peers in the ledger window "
             f"({rep['peers_live']} live, {rep['peers_dropped']} "
             f"dropped, {rep['events']} lifecycle events)"]
    lines += ["", f"{'peer':<14}{'dir':>4}{'state':>18}"
                  f"{'tx msgs/B':>14}{'rx msgs/B':>14}{'q_hi':>6}"
                  f"{'blkd':>6}{'drop':>6}{'thr':>5}{'link':>6}"
                  f"{'inj':>5}{'rtt ms':>8}{'dupV':>6}"]
    for r in rep["rows"]:
        lines.append(
            f"{r['peer']:<14}{r['dir']:>4}{r['state']:>18}"
            f"{str(r['msgs_tx']) + '/' + str(r['bytes_tx']):>14}"
            f"{str(r['msgs_rx']) + '/' + str(r['bytes_rx']):>14}"
            f"{r['q_hiwater']:>6}{r['blocked_puts']:>6}"
            f"{r['full_drops']:>6}{r['throttle_stalls']:>5}"
            f"{r['link_drops']:>6}{r['inj']:>5}"
            f"{r['rtt_ms']:>8.3f}{r['dup_votes']:>6}")
    t = rep["totals"]
    lines += ["",
              f"totals: {rep['msgs_tx']} msgs/{rep['bytes_tx']} B out, "
              f"{rep['msgs_rx']} msgs/{rep['bytes_rx']} B in; "
              f"blocked={t['blocked_puts']} full_drops={t['full_drops']} "
              f"throttle={t['throttle_stalls']} "
              f"link_drops={t['link_drops']} "
              f"injected={t['inj_drops']}d/{t['inj_delays']}s "
              f"dup_votes={t['dup_votes']}"]
    if rep["rtt_p50_ms"] or rep["rtt_max_ms"]:
        lines.append(f"ping RTT p50/max: {rep['rtt_p50_ms']}/"
                     f"{rep['rtt_max_ms']} ms")
    v = rep.get("votes") or {}
    if v.get("seen"):
        lines.append(
            f"vote routes: {v['seen']} first-seen, {v['dups']} "
            f"duplicate receipts, {v['relayed']} relays "
            f"({v.get('tracked', 0)} tracked now)")
    if t["full_drops"] or t["blocked_puts"]:
        lines.append(
            f"STARVATION: {t['full_drops']} full-queue drops / "
            f"{t['blocked_puts']} blocked puts — check /dump_incidents "
            f"for a peer_starvation snapshot and the per-peer rows "
            f"above for WHICH queue")
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A", path_b: str = "B") -> str:
    lines = [f"peer-health delta: {path_a} ({diff['peers_a']} peers) "
             f"-> {path_b} ({diff['peers_b']} peers)"]
    lines += ["", f"{'metric':<18}{'A':>10}{'B':>10}{'Δ':>10}  flag"]
    for r in diff["rows"]:
        lines.append(f"{r['metric']:<18}{r['a']:>10}{r['b']:>10}"
                     f"{r['delta']:>+10}  {r['flag']}")
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"] else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "per-peer traffic/health table from a /dump_peers document, "
        "or a health delta diff of two of them",
        operand_help="peer dump file(s); two files with --diff",
        diff_help="diff two dumps: health-counter delta table with "
                  "regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_ABS,
        abs_help="absolute regression floor (count / ms)")
    return run_cli(argv, parser=ap, load=load_peers,
                   report=peer_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
