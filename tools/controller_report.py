"""Turn a /dump_controller document into a decision timeline and
per-actuator travel tables — and DIFF two of them.

The control-plane sibling of tools/device_report.py, trace_report.py,
height_report.py and peer_report.py: where those decompose the DEVICE,
a FLUSH, a BLOCK, and the GOSSIP, this decomposes the LOOP — per
actuator: configured base, clamp bounds, current value, displacement
from base, move count, tighten/relax split; plus the decision timeline
(who moved, which direction, what the trigger sensors read) and the
SLO-violation accrual. Feed it a saved ``curl $NODE/dump_controller``
file or a bench --json-out evidence file with an embedded
``controller_dump``.

Differencing mirrors device_report --diff: figure delta rows with
REGRESSED/improved flags past BOTH a relative and an absolute
threshold, and ``--fail-on-regression`` for CI gates (requires --diff
— a gate wired without a comparison must error, not read permanently
green). Flags: SLO-violation growth (the loop stopped holding the
target), decision-count blowup (a flapping loop — hysteresis or
cooldown miswired), and residual displacement growth (actuators parked
off base at the trough means the loop stopped relaxing).

Usage:
    python tools/controller_report.py dump.json [--json]
    python tools/controller_report.py --diff A.json B.json \
        [--json] [--threshold-pct 25] [--threshold-abs 4] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_directional, run_cli)

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_THRESHOLD_ABS = 4.0


def load_controller(path: str) -> dict:
    """Extract a controller dump from any supported shape: a
    /dump_controller document, a bench --json-out evidence file
    carrying ``extra.controller_dump``, or a bare {"decisions": ...,
    "actuators": ...} object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "decisions" in doc \
            and "actuators" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            cd = extra.get("controller_dump")
            if cd and cd.get("decisions") is not None:
                return cd
    raise ValueError(
        f"{path}: no controller records found (want a "
        f"/dump_controller document or a bench --json-out file with "
        f"an embedded controller_dump)")


def controller_report(dump: dict) -> dict:
    """Aggregate a controller dump into the tables the text report
    prints and the diff compares."""
    state = dict(dump.get("state", {}))
    decisions = list(dump.get("decisions", []))
    acts: dict = {}
    for name, a in (dump.get("actuators") or {}).items():
        acts[name] = {
            "actuator": name,
            "value": a.get("value", 0.0),
            "base": a.get("base", 0.0),
            "min": a.get("min", 0.0),
            "max": a.get("max", 0.0),
            "moves": a.get("moves", 0),
            # displacement from base, normalized by the clamp span —
            # the "how far off the configured static point is the loop
            # parked" figure the diff watches
            "displacement": round(
                abs(a.get("value", 0.0) - a.get("base", 0.0)), 4),
            "tightens": 0,
            "relaxes": 0,
        }
    timeline = []
    for d in decisions:
        row = acts.get(d.get("actuator"))
        if row is not None:
            if d.get("relax"):
                row["relaxes"] += 1
            else:
                row["tightens"] += 1
        timeline.append({
            "seq": d.get("seq"), "at_ms": d.get("at_ms"),
            "height": d.get("height"), "actuator": d.get("actuator"),
            "direction": d.get("direction"), "old": d.get("old"),
            "new": d.get("new"), "relax": bool(d.get("relax")),
            "trigger": d.get("trigger", {}),
        })
    displaced = sorted((r["actuator"] for r in acts.values()
                        if r["displacement"] > 0))
    return {
        "decisions_total": state.get("decisions_total", 0),
        "evals": state.get("evals", 0),
        "pokes": state.get("pokes", 0),
        "pressed": bool(state.get("pressed", False)),
        "slo": dict(dump.get("slo", {})),
        "slo_violation_s": state.get("slo_violation_s", 0.0),
        "actuators": sorted(acts.values(),
                            key=lambda r: (-r["moves"], r["actuator"])),
        "displacement_total": round(
            sum(r["displacement"] for r in acts.values()), 4),
        "displaced": displaced,
        "timeline": timeline,
    }


# --------------------------------------------------------------------------
# differencing (device_report --diff's shape, over the loop figures)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_abs: float = DEFAULT_THRESHOLD_ABS) -> dict:
    """Loop-figure delta rows (A = before, B = after). Growth is bad
    for violation seconds, decision count and residual displacement; a
    figure REGRESSED past BOTH thresholds — except slo_violation_s,
    where ANY growth flags (the loop exists to keep it at zero)."""

    def flag_of(a: float, b: float, abs_floor: float = threshold_abs,
                any_growth: bool = False) -> str:
        return flag_directional(a, b, threshold_pct=threshold_pct,
                                abs_floor=abs_floor,
                                any_growth=any_growth)

    rows = [
        # holding the SLO is the loop's one job: any violation growth
        # flags, no relative threshold can excuse it
        {"metric": "slo_violation_s", "a": rep_a["slo_violation_s"],
         "b": rep_b["slo_violation_s"],
         "flag": flag_of(rep_a["slo_violation_s"],
                         rep_b["slo_violation_s"], abs_floor=0.001,
                         any_growth=True)},
        {"metric": "decisions_total", "a": rep_a["decisions_total"],
         "b": rep_b["decisions_total"],
         "flag": flag_of(rep_a["decisions_total"],
                         rep_b["decisions_total"])},
        {"metric": "displacement_total",
         "a": rep_a["displacement_total"],
         "b": rep_b["displacement_total"],
         "flag": flag_of(rep_a["displacement_total"],
                         rep_b["displacement_total"],
                         abs_floor=0.01)},
        {"metric": "evals", "a": rep_a["evals"], "b": rep_b["evals"],
         "flag": ""},
    ]
    for r in rows:
        r["delta"] = round(r["b"] - r["a"], 4)

    notes = []
    acts_a = {r["actuator"]: r for r in rep_a["actuators"]}
    for row in rep_b["actuators"]:
        before = acts_a.get(row["actuator"],
                            {"moves": 0, "displacement": 0.0})
        if row["displacement"] > 0 and row["displacement"] \
                > before["displacement"]:
            notes.append(
                f"{row['actuator']} parked off base: "
                f"{row['value']} vs base {row['base']} "
                f"(was off by {before['displacement']}) — the loop "
                f"stopped relaxing; check the timeline's last relax "
                f"and the hysteresis thresholds")
        if before["moves"] and row["moves"] > 4 * before["moves"]:
            notes.append(
                f"{row['actuator']} move count blew up: "
                f"{before['moves']} -> {row['moves']} — a flapping "
                f"loop; check cooldown and the enter/exit spread")
    if rep_b["pressed"] and not rep_a["pressed"]:
        notes.append(
            "run B ended still PRESSED — pressure never released "
            "before the dump; trough assertions read tightened values")

    regressions = [r["metric"] for r in rows
                   if r["flag"] == "REGRESSED"]
    return {"rows": rows, "regressions": regressions, "notes": notes}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    slo = rep["slo"]
    lines = [
        f"decisions: {rep['decisions_total']} over {rep['evals']} "
        f"evaluations ({rep['pokes']} pokes), "
        + ("PRESSED" if rep["pressed"] else "unpressed")
        + f"; SLO commit p99 {slo.get('commit_p99_ms', '?')} ms, "
          f"violation accrued {rep['slo_violation_s']} s"]
    if rep["actuators"]:
        lines += ["", f"{'actuator':<26}{'value':>10}{'base':>10}"
                      f"{'min':>9}{'max':>9}{'moves':>7}"
                      f"{'tight':>7}{'relax':>7}"]
        for r in rep["actuators"]:
            lines.append(
                f"{r['actuator']:<26}{r['value']:>10}{r['base']:>10}"
                f"{r['min']:>9}{r['max']:>9}{r['moves']:>7}"
                f"{r['tightens']:>7}{r['relaxes']:>7}")
        if rep["displaced"]:
            lines.append(
                f"off base: {', '.join(rep['displaced'])} "
                f"(total displacement {rep['displacement_total']})")
        else:
            lines.append("all actuators at their configured base")
    if rep["timeline"]:
        lines += ["", "decision timeline (oldest first):"]
        for d in rep["timeline"]:
            trig = d["trigger"]
            why = ", ".join(
                f"{k}={trig[k]}" for k in ("p99_ms", "fill",
                                           "shed_delta", "util_p50",
                                           "compile_storms")
                if k in trig and trig[k] not in (None, 0, 0.0))
            lines.append(
                f"  #{d['seq']:<4} h={d['height']:<6} "
                f"{d['actuator']:<26} {d['direction']:<5}"
                f"{d['old']} -> {d['new']}"
                + (" (relax)" if d["relax"] else "")
                + (f"  [{why}]" if why else ""))
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A",
                path_b: str = "B") -> str:
    lines = [f"control-plane delta: {path_a} -> {path_b}",
             "", f"{'metric':<22}{'A':>12}{'B':>12}{'Δ':>12}  flag"]
    for r in diff["rows"]:
        lines.append(f"{r['metric']:<22}{r['a']:>12}{r['b']:>12}"
                     f"{r['delta']:>+12}  {r['flag']}")
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"]
                   else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "decision timeline and per-actuator travel tables from a "
        "/dump_controller document, or a loop-figure delta diff of "
        "two of them",
        operand_help="controller dump file(s); two with --diff",
        diff_help="diff two dumps: loop-figure delta table with "
                  "regression flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_ABS)
    return run_cli(argv, parser=ap, load=load_controller,
                   report=controller_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
