"""Turn a /dump_heights document into a per-stage commit-latency table
with a late-signer section — and DIFF two of them.

The consensus-level sibling of tools/trace_report.py: where the trace
report decomposes a FLUSH, this decomposes a BLOCK — proposal
propagation vs prevote quorum vs precommit quorum vs persist vs apply,
per height, percentile-summarized, with the verify-plane join and the
chronically-late-signer table the DCN round reads. Feed it a saved
``curl $NODE/dump_heights`` file, a bench ``--json-out`` evidence file
(cfg9/cfg13 embed a trimmed dump under ``extra.height_dump``), or any
JSON holding a ``heights`` list.

Differencing mirrors trace_report --diff: stage-delta rows with
REGRESSED/improved/appeared/vanished flags on mean ms past BOTH a
relative and an absolute threshold, and ``--fail-on-regression`` for
CI gates (requires --diff — a gate wired without a comparison must
error, not read permanently green).

Usage:
    python tools/height_report.py dump.json [--json]
    python tools/height_report.py --diff A.json B.json \
        [--json] [--threshold-pct 10] [--threshold-ms 1.0] \
        [--fail-on-regression]
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._report_common import (  # noqa: E402 - after sys.path fix
    build_parser, flag_symmetric, run_cli)

# per-record STAGE DELTAS derived from the cumulative timeline: each
# row is "time spent inside this stage", so the table sums to the
# commit latency instead of repeating cumulative prefixes
STAGE_BOUNDS = [
    ("proposal", None, "proposal_ms"),
    ("prevote_quorum", "proposal_ms", "prevote_quorum_ms"),
    ("precommit_quorum", "prevote_quorum_ms", "precommit_quorum_ms"),
    ("commit_wait", "precommit_quorum_ms", "commit_ms"),
    ("persist_apply", "commit_ms", "apply_ms"),
]

DEFAULT_THRESHOLD_PCT = 10.0
DEFAULT_THRESHOLD_MS = 1.0


def load_heights(path: str) -> dict:
    """Extract {heights, late_signers, summary} from any supported
    shape: a /dump_heights document, a bench --json-out evidence file
    (first config carrying extra.height_dump wins), or a bare
    {"heights": [...]} object."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "heights" in doc:
        return doc
    if isinstance(doc, dict) and "results" in doc:
        for cfg in sorted(doc["results"]):
            extra = (doc["results"][cfg] or {}).get("extra") or {}
            hd = extra.get("height_dump")
            if hd and hd.get("heights"):
                return hd
    raise ValueError(
        f"{path}: no height records found (want a /dump_heights "
        f"document or a bench --json-out file with an embedded "
        f"height_dump)")


def _pct(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def _row(name: str, durs: List[float]) -> dict:
    n = len(durs)
    return {
        "stage": name,
        "count": n,
        "total_ms": round(sum(durs), 3),
        "mean_ms": round(sum(durs) / n, 4) if n else 0.0,
        "p50_ms": round(_pct(durs, 0.5), 4),
        "p99_ms": round(_pct(durs, 0.99), 4),
        "max_ms": round(max(durs), 4) if n else 0.0,
    }


def stage_report(dump: dict) -> dict:
    """Aggregate a height dump into the per-stage table + the
    late-signer and attribution extras the text report prints and the
    bench embeds."""
    recs = [r for r in dump.get("heights", [])]
    # only heights with a complete monotone timeline contribute to the
    # per-stage deltas (catch-up pushes and clock-domain-swapped
    # heights carry zeros; their totals would poison the means)
    staged = []
    for r in recs:
        ts = [r.get(k, 0.0) for _, _, k in STAGE_BOUNDS]
        if r.get("via") == "consensus" and all(t > 0 for t in ts) \
                and ts == sorted(ts):
            staged.append(r)
    stage_durs: Dict[str, List[float]] = {}
    for name, lo_key, hi_key in STAGE_BOUNDS:
        durs = []
        for r in staged:
            lo = r.get(lo_key, 0.0) if lo_key else 0.0
            durs.append(max(0.0, r.get(hi_key, 0.0) - lo))
        stage_durs[name] = durs
    commit_lat = [r["apply_ms"] for r in staged]
    stages = [_row(name, stage_durs[name]) for name, _, _ in STAGE_BOUNDS]
    stages.append(_row("total_commit", commit_lat))

    plane_ms = [r.get("plane_ms", 0.0) for r in staged]
    fsync_ms = [r.get("wal_fsync_ms", 0.0) for r in staged]
    return {
        "heights": len(recs),
        "staged_heights": len(staged),
        "skipped_heights": len(recs) - len(staged),
        "stages": stages,
        "commit_p50_ms": round(_pct(commit_lat, 0.5), 3),
        "commit_p99_ms": round(_pct(commit_lat, 0.99), 3),
        "rounds_max": max((r.get("rounds", 0) for r in recs), default=0),
        "multi_round_heights": sum(
            1 for r in recs if r.get("rounds", 0) > 0),
        "plane_ms_mean": round(sum(plane_ms) / len(plane_ms), 3)
        if plane_ms else 0.0,
        "plane_flushes": int(sum(r.get("plane_flushes", 0)
                                 for r in recs)),
        "cold_table_heights": sum(
            1 for r in recs if r.get("cold_tables", 0)),
        "wal_fsync_ms_mean": round(sum(fsync_ms) / len(fsync_ms), 3)
        if fsync_ms else 0.0,
        "catchup_heights": sum(
            1 for r in recs if r.get("via") == "catchup"),
        "late_votes": int(sum(len(r.get("late", [])) for r in recs)),
        # the network-vs-crypto split over every late arrival (rows are
        # [vidx, off, net, sign, via]; pre-ISSUE-14 dumps carry 2-elem
        # rows and contribute zeros)
        "late_net_ms": round(sum(
            row[2] for r in recs for row in r.get("late", [])
            if len(row) >= 4), 3),
        "late_sign_ms": round(sum(
            row[3] for r in recs for row in r.get("late", [])
            if len(row) >= 4), 3),
        "absent_votes": int(sum(r.get("absent", 0) for r in recs)),
        "late_signers": list(dump.get("late_signers", []))[:16],
    }


# --------------------------------------------------------------------------
# differencing (trace_report --diff's shape, over stage mean ms)
# --------------------------------------------------------------------------


def diff_report(rep_a: dict, rep_b: dict,
                threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                threshold_ms: float = DEFAULT_THRESHOLD_MS) -> dict:
    """Stage-delta rows (A = before, B = after) with REGRESSED/
    improved flags: a stage regressed when its mean grew past BOTH the
    relative and absolute thresholds (one guards noise on tiny stages,
    the other on huge-but-stable ones)."""
    a_by = {r["stage"]: r for r in rep_a.get("stages", [])}
    b_by = {r["stage"]: r for r in rep_b.get("stages", [])}
    order = [r["stage"] for r in rep_a.get("stages", [])]
    order += [s for s in b_by if s not in a_by]

    def flag_of(ma: float, mb: float) -> str:
        return flag_symmetric(ma, mb, threshold_pct=threshold_pct,
                              abs_floor=threshold_ms)

    rows = []
    for name in order:
        ra, rb = a_by.get(name), b_by.get(name)
        if ra is None or rb is None:
            rows.append({
                "stage": name,
                "flag": "appeared" if ra is None else "vanished",
                "count_a": ra["count"] if ra else 0,
                "count_b": rb["count"] if rb else 0,
                "mean_ms_a": ra["mean_ms"] if ra else 0.0,
                "mean_ms_b": rb["mean_ms"] if rb else 0.0,
                "p99_ms_a": ra["p99_ms"] if ra else 0.0,
                "p99_ms_b": rb["p99_ms"] if rb else 0.0,
                "delta_mean_ms": round(
                    (rb["mean_ms"] if rb else 0.0)
                    - (ra["mean_ms"] if ra else 0.0), 4),
                "delta_pct": None,
            })
            continue
        d = rb["mean_ms"] - ra["mean_ms"]
        rows.append({
            "stage": name,
            "flag": flag_of(ra["mean_ms"], rb["mean_ms"]),
            "count_a": ra["count"], "count_b": rb["count"],
            "mean_ms_a": ra["mean_ms"], "mean_ms_b": rb["mean_ms"],
            "p99_ms_a": ra["p99_ms"], "p99_ms_b": rb["p99_ms"],
            "delta_mean_ms": round(d, 4),
            "delta_pct": round(d / ra["mean_ms"] * 100.0, 1)
            if ra["mean_ms"] else None,
        })

    # attribution deltas worth a flag of their own: cold tables
    # appearing (the warmer stopped absorbing rotations) and round
    # escalation appearing (quorum health changed)
    notes = []
    if rep_b.get("cold_table_heights", 0) \
            > rep_a.get("cold_table_heights", 0):
        notes.append(
            f"cold tables grew: {rep_a.get('cold_table_heights', 0)} "
            f"-> {rep_b.get('cold_table_heights', 0)} heights paid an "
            f"inline valset table build (check the next-epoch warmer)")
    if rep_b.get("multi_round_heights", 0) \
            > rep_a.get("multi_round_heights", 0):
        notes.append(
            f"round escalation grew: "
            f"{rep_a.get('multi_round_heights', 0)} -> "
            f"{rep_b.get('multi_round_heights', 0)} multi-round "
            f"heights")

    regressions = [r["stage"] for r in rows
                   if r["flag"] == "REGRESSED"
                   or (r["flag"] == "appeared"
                       and r["mean_ms_b"] >= threshold_ms)]
    return {"stages": rows, "regressions": regressions, "notes": notes,
            "commit_p99_ms_a": rep_a.get("commit_p99_ms", 0.0),
            "commit_p99_ms_b": rep_b.get("commit_p99_ms", 0.0),
            "heights_a": rep_a.get("heights", 0),
            "heights_b": rep_b.get("heights", 0)}


# --------------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------------


def format_report(rep: dict) -> str:
    lines = [f"{rep['heights']} heights in the ledger window "
             f"({rep['staged_heights']} with a full stage timeline"
             + (f", {rep['skipped_heights']} skipped: catch-up or "
                f"partial stamps" if rep["skipped_heights"] else "")
             + ")"]
    lines += ["", f"{'stage':<20}{'count':>7}{'mean ms':>10}"
                  f"{'p50 ms':>10}{'p99 ms':>10}{'max ms':>10}"]
    for r in rep["stages"]:
        lines.append(f"{r['stage']:<20}{r['count']:>7}"
                     f"{r['mean_ms']:>10.3f}{r['p50_ms']:>10.3f}"
                     f"{r['p99_ms']:>10.3f}{r['max_ms']:>10.3f}")
    lines += ["",
              f"commit latency p50/p99: {rep['commit_p50_ms']}/"
              f"{rep['commit_p99_ms']} ms; "
              f"verify-plane {rep['plane_ms_mean']} ms/height over "
              f"{rep['plane_flushes']} joined flushes; "
              f"WAL fsync {rep['wal_fsync_ms_mean']} ms/height"]
    if rep["multi_round_heights"]:
        lines.append(
            f"ROUND ESCALATION: {rep['multi_round_heights']} height(s) "
            f"needed extra rounds (max round {rep['rounds_max']})")
    if rep["cold_table_heights"]:
        lines.append(
            f"COLD TABLES: {rep['cold_table_heights']} height(s) "
            f"joined a flush that paid an inline valset table build "
            f"(post-rotation stall — check the next-epoch warmer)")
    if rep["catchup_heights"]:
        lines.append(f"{rep['catchup_heights']} height(s) arrived via "
                     f"catch-up push (no stage timeline)")
    if rep["late_signers"]:
        lines += ["", "chronically late signers (heights late after "
                      "quorum / absent from commit; net = in flight, "
                      "sign = signed late):"]
        lines.append(f"{'validator':>10}{'late':>7}{'absent':>8}"
                     f"{'total':>8}{'net ms':>10}{'sign ms':>10}")
        for row in rep["late_signers"]:
            lines.append(f"{row['val']:>10}{row['late_heights']:>7}"
                         f"{row['absent_heights']:>8}{row['total']:>8}"
                         f"{row.get('net_ms', 0.0):>10.3f}"
                         f"{row.get('sign_ms', 0.0):>10.3f}")
    elif rep["late_votes"] or rep["absent_votes"]:
        lines.append(f"late votes: {rep['late_votes']}, absent "
                     f"precommits: {rep['absent_votes']}")
    if rep.get("late_net_ms") or rep.get("late_sign_ms"):
        lines.append(
            f"late-vote decomposition: {rep['late_net_ms']} ms in "
            f"flight (network) vs {rep['late_sign_ms']} ms signed "
            f"late (crypto/host) — see /dump_peers for the hops")
    return "\n".join(lines)


def format_diff(diff: dict, path_a: str = "A", path_b: str = "B") -> str:
    lines = [f"height stage-delta: {path_a} ({diff['heights_a']} "
             f"heights) -> {path_b} ({diff['heights_b']} heights)"]
    lines += ["", f"{'stage':<20}{'cnt A':>6}{'cnt B':>6}"
                  f"{'mean A':>9}{'mean B':>9}{'Δ ms':>9}{'Δ %':>8}"
                  f"  {'flag'}"]
    for r in diff["stages"]:
        pct = f"{r['delta_pct']:+.1f}" if r["delta_pct"] is not None \
            else "-"
        lines.append(
            f"{r['stage']:<20}{r['count_a']:>6}{r['count_b']:>6}"
            f"{r['mean_ms_a']:>9.3f}{r['mean_ms_b']:>9.3f}"
            f"{r['delta_mean_ms']:>+9.3f}{pct:>8}  {r['flag']}")
    lines += ["", f"commit p99: {diff['commit_p99_ms_a']} -> "
                  f"{diff['commit_p99_ms_b']} ms"]
    for n in diff.get("notes", []):
        lines.append(f"NOTE: {n}")
    lines += ["", ("regressions: " + ", ".join(diff["regressions"])
                   if diff["regressions"] else "no regressions flagged")]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = build_parser(
        "per-stage commit-latency table from a /dump_heights "
        "document, or a stage-delta diff of two of them",
        operand_help="height dump file(s); two files with --diff",
        diff_help="diff two dumps: stage-delta table with regression "
                  "flags",
        default_pct=DEFAULT_THRESHOLD_PCT,
        default_abs=DEFAULT_THRESHOLD_MS,
        pct_help="relative regression floor (mean ms, %%)",
        abs_flag="--threshold-ms",
        abs_help="absolute regression floor (mean ms)")
    return run_cli(argv, parser=ap, load=load_heights,
                   report=stage_report, diff=diff_report,
                   fmt_report=format_report, fmt_diff=format_diff)


if __name__ == "__main__":
    raise SystemExit(main())
