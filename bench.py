"""Benchmark: 10k-validator commit verification (the BASELINE.json metric).

Measures p50 latency of the fused device pass — batched ed25519 ZIP-215
verification + voting-power quorum tally over a 10_000-signature commit —
on whatever backend JAX_PLATFORMS selects (the driver runs it on the real
TPU chip). Prints ONE JSON line.

Baseline: the reference's Go `crypto/batch` path (curve25519-voi batch
verify) has no committed absolute numbers (BASELINE.md) and no Go toolchain
exists in this image, so the CPU baseline is measured live with OpenSSL
(`cryptography` package) single verifies divided by 1.7 — a generous stand-
in for voi's batch speedup over single verification (voi's ZIP-215 batch is
~1.5-2x single-verify throughput at size 1024; see reference
crypto/ed25519/bench_test.go harness). vs_baseline = cpu_ms / device_ms.
"""
import json
import time

import numpy as np

N_VALIDATORS = 10_000
PAD = 16_384
CPU_BATCH_SPEEDUP = 1.7


def main():
    t0 = time.time()
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    import jax

    from cometbft_tpu.ops import ed25519_kernel as k

    # --- build a synthetic 10k-validator commit ---------------------------
    sk = Ed25519PrivateKey.generate()
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    # one key signing distinct messages models per-validator sign-bytes
    # (cost profile on device is identical; packing cost is identical)
    pub = sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    msgs = [b"vote-sign-bytes|h=12345|r=0|vote-%06d" % i for i in range(N_VALIDATORS)]
    sigs = [sk.sign(m) for m in msgs]
    pubs = [pub] * N_VALIDATORS

    # --- CPU baseline: OpenSSL verify loop (sampled) ----------------------
    pk = sk.public_key()
    sample = 500
    t = time.perf_counter()
    for i in range(sample):
        pk.verify(sigs[i], msgs[i])
    per_sig = (time.perf_counter() - t) / sample
    cpu_ms = per_sig * N_VALIDATORS * 1000 / CPU_BATCH_SPEEDUP

    # --- pack + stage -----------------------------------------------------
    t = time.perf_counter()
    pb = k.pack_batch(pubs, msgs, sigs, pad_to=PAD)
    pack_ms = (time.perf_counter() - t) * 1000

    powers = np.full((N_VALIDATORS,), 1000, np.int64)
    power5 = np.zeros((PAD, k.POWER_LIMBS), np.int32)
    power5[:N_VALIDATORS] = k.power_limbs(powers)
    counted = np.zeros((PAD,), np.bool_)
    counted[:N_VALIDATORS] = True
    commit_ids = np.zeros((PAD,), np.int32)
    thresh = k.threshold_limbs(int(powers.sum()) * 2 // 3)

    args = [
        jax.device_put(a)
        for a in (pb.ay, pb.asign, pb.ry, pb.rsign, pb.sdig, pb.hdig,
                  pb.precheck, power5, counted, commit_ids, thresh)
    ]

    # --- device p50 -------------------------------------------------------
    out = jax.block_until_ready(k.verify_tally_kernel(*args, n_commits=1))
    assert bool(np.asarray(out[2])[0]), "quorum must hold on valid commit"
    assert np.asarray(out[0])[:N_VALIDATORS].all()
    times = []
    for _ in range(10):
        t = time.perf_counter()
        out = jax.block_until_ready(k.verify_tally_kernel(*args, n_commits=1))
        times.append((time.perf_counter() - t) * 1000)
    p50 = float(np.percentile(times, 50))

    print(
        json.dumps(
            {
                "metric": "10k-validator VerifyCommitLight fused p50",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / p50, 2),
                "extra": {
                    "device": str(jax.devices()[0]),
                    "sigs_per_sec": round(N_VALIDATORS / (p50 / 1000)),
                    "cpu_baseline_ms": round(cpu_ms, 1),
                    "host_pack_ms": round(pack_ms, 1),
                    "min_ms": round(min(times), 3),
                    "total_bench_s": round(time.time() - t0, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
