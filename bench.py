"""Benchmark: the five BASELINE.md configs through the product paths.

Prints one JSON line per config, then ONE final headline line (the
driver-recorded metric): 10k-validator VerifyCommitLight fused p50.

Baseline methodology (round-3 rework — no assumed factors):
  * The CPU baseline is MEASURED on this host: an OpenSSL (`cryptography`)
    per-signature verify loop over the same real canonical sign-bytes the
    device verifies. This host has exactly ONE core (nproc=1), so the
    multi-process all-cores baseline the round-2 verdict asked for equals
    the single-core measurement.
  * The reference's Go batch path (curve25519-voi ZIP-215 RLC batch)
    would beat a single-verify loop by at most ~2x single-threaded; we
    report that bound as `cpu_batch_bound_2x_ms` in extra (a sensitivity
    endpoint, NOT a divisor applied to vs_baseline).
  * vs_baseline = measured CPU ms / device steady-state ms, nothing else.

Timing methodology: the axon tunnel to the TPU adds a fixed ~50-90 ms
dispatch+fetch round trip to ANY single device call (measured live as
`tunnel_floor_ms` with a trivial kernel). Production consensus/blocksync
streams commits, so the headline value is the steady-state per-commit
latency (K pipelined calls / K, including per-call H2D upload of the
compact packed batch); the raw single-shot p50 (tunnel round trip
included) is reported alongside.
"""
import json
import os
import time

import numpy as np

CHAIN_ID = "bench-chain"
RAW_REPS = 8
STEADY_K = 12
# streaming configs report best-of-N whole-run walls: the shared tunnel
# has multi-x run-to-run noise, and a single wall measurement turned
# that noise into phantom regressions (the r05 post-mortem — cfg3/cfg4
# moved 2-4x between rounds on identical code paths)
WALL_RUNS = 3


def _now_ms():
    return time.perf_counter() * 1000


def p50(xs):
    return float(np.percentile(xs, 50))


# --------------------------------------------------------------------------
# jax compile-event watch: per-config compile counts/time + persistent-
# cache hits, so cold-compile pollution of a streaming config is VISIBLE
# in its JSON instead of inferred from a suspicious wall clock
# --------------------------------------------------------------------------


class CompileWatch:
    """Per-config compile deltas, read from the device observatory's
    process-global compile ledger (libs/deviceledger) — the ONE
    jax.monitoring listener bench AND production share, so
    `extra.jax_compile` here and /dump_devices on a node can never
    report different compile truth. This class is a thin snapshot
    adapter kept for the established bench API (snap/delta)."""

    def arm(self) -> bool:
        from cometbft_tpu.libs import deviceledger

        return deviceledger.arm_compile_listener()

    def snap(self) -> dict:
        from cometbft_tpu.libs import deviceledger

        c = deviceledger.counters()
        return {"compiles": c["compiles"],
                "compile_s": round(c["compile_s"], 3),
                "pcache_hits": c["pcache_hits"]}

    def delta(self, before: dict) -> dict:
        now = self.snap()
        return {k: round(now[k] - before[k], 3) for k in now}


# --------------------------------------------------------------------------
# baseline comparison: current run vs a stored BENCH_rNN.json
# --------------------------------------------------------------------------

# units where a LARGER value is better; everything else (ms) is
# smaller-is-better
BETTER_HIGHER_UNITS = ("sigs/sec", "tx/s", "headers/sec", "x")
BASELINE_THRESHOLD_PCT = 30.0  # tunnel noise floor; see WALL_RUNS note


def load_bench_results(path: str) -> dict:
    """Parse a stored bench output into {cfg_name: result_dict}.

    Accepts three shapes: the driver's BENCH_rNN.json (a dict whose
    "tail" holds the bench's JSON-line stdout, possibly truncated at
    the head), a `--json-out` evidence file ({"results": {...}}), or a
    raw stdout capture (one JSON object per line). Unparseable lines
    (the tail's cut-off first line) are skipped."""
    with open(path) as f:
        text = f.read()
    lines = text.splitlines()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if "results" in doc:
            return dict(doc["results"])
        if "tail" in doc:
            lines = str(doc["tail"]).splitlines()
    out = {}
    for ln in lines:
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            r = json.loads(ln)
        except ValueError:
            continue  # truncated first line of a driver tail
        m = r.get("metric", "")
        if m.startswith("cfg"):
            out[m.split()[0]] = r
        elif "VerifyCommitLight fused p50" in m:
            out["headline"] = r
    return out


def compare_to_baseline(results: dict, baseline: dict,
                        threshold_pct: float = BASELINE_THRESHOLD_PCT,
                        ) -> dict:
    """Thresholded per-config pass/fail against a stored run. Direction
    is unit-aware (ms down = good, sigs/sec up = good); configs missing
    on either side (or failed: value None) are reported, not judged."""
    rows, regressed, missing = [], [], []
    for name in sorted(set(results) | set(baseline)):
        cur, base = results.get(name), baseline.get(name)
        cv = cur.get("value") if cur else None
        bv = base.get("value") if base else None
        if cv in (None, 0) or bv in (None, 0):
            missing.append(name)
            continue
        unit = (cur.get("unit") or base.get("unit") or "")
        higher_better = unit in BETTER_HIGHER_UNITS
        delta_pct = (float(cv) - float(bv)) / float(bv) * 100.0
        # flagging is RATIO-based, symmetric in both directions: a
        # percent delta saturates at -100% for higher-better units (a
        # 20x throughput collapse is "-95%"), which would make big
        # thresholds unable to flag throughput regressions at all
        slowdown = (float(bv) / float(cv) if higher_better
                    else float(cv) / float(bv))
        lim = 1.0 + threshold_pct / 100.0
        status = ("REGRESSED" if slowdown > lim else
                  "improved" if slowdown < 1.0 / lim else "ok")
        if status == "REGRESSED":
            regressed.append(name)
        rows.append({"config": name, "unit": unit, "current": cv,
                     "baseline": bv, "delta_pct": round(delta_pct, 1),
                     "status": status})
    return {"threshold_pct": threshold_pct, "rows": rows,
            "regressed": regressed, "missing": missing,
            "ok": not regressed}


def measure_tunnel_floor():
    """Fixed dispatch+fetch cost of ANY device call on this backend."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def trivial(x):
        return x + 1

    x = jnp.zeros((8, 128), jnp.int32)
    np.asarray(trivial(x))
    ts = []
    for _ in range(6):
        t = _now_ms()
        np.asarray(trivial(x))
        ts.append(_now_ms() - t)
    return min(ts)


# --------------------------------------------------------------------------
# fixtures: real validator sets + real commits (canonical sign-bytes)
# --------------------------------------------------------------------------


def make_ed_commit(n_vals, height=12345, power=1000, seed=7):
    """n_vals distinct ed25519 keys, each signing its real precommit
    sign-bytes (types/vote.go:139 canonical encoding)."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    privs = [
        PrivKey.generate(seed.to_bytes(2, "big") + i.to_bytes(4, "big")
                         + b"\x11" * 26)
        for i in range(n_vals)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), power) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\xab" * 32, PartSetHeader(2, b"\xcd" * 32))
    sigs = []
    for idx, v in enumerate(vs.validators):
        ts = Timestamp(1_700_000_000 + idx, 0)
        sb = canonical.canonical_vote_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, height, 0, bid, ts
        )
        sigs.append(
            CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                      by_addr[v.address].sign(sb))
        )
    return vs, Commit(height, 0, bid, sigs), bid


def cpu_ed25519_per_sig_ms(vs, commit, sample=400):
    """Measured OpenSSL (C-speed) verify of the commit's own sign-bytes.

    Deliberately NOT PubKey.verify_signature — that is the pure-Python
    ZIP-215 oracle (~40x slower than OpenSSL), which would inflate
    vs_baseline dishonestly. OpenSSL's cofactorless verify accepts all
    honestly-generated signatures, which is all this fixture contains.
    """
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    n = min(sample, len(vs.validators))
    msgs = [commit.vote_sign_bytes(CHAIN_ID, i) for i in range(n)]
    pks = [
        Ed25519PublicKey.from_public_bytes(vs.validators[i].pub_key.data)
        for i in range(n)
    ]
    t = _now_ms()
    for i in range(n):
        pks[i].verify(commit.signatures[i].signature, msgs[i])
    return (_now_ms() - t) / n


# --------------------------------------------------------------------------
# configs
# --------------------------------------------------------------------------


def cfg1_live_node():
    """#1: kvstore ABCI app, 4 validators — live in-process net, then
    VerifyCommitLight on a commit the network actually produced."""
    import tempfile

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types import validation as tv
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.01)
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("bench-live", vals)
    net = LocalNetwork()
    nodes = []
    with tempfile.TemporaryDirectory() as home:
        for i, priv in enumerate(privs):
            node = Node(KVStoreApplication(), state.copy(),
                        privval=FilePV(priv), home=f"{home}/n{i}",
                        broadcast=net.broadcaster(i), timeouts=fast)
            net.add(node)
            nodes.append(node)
        t_net = _now_ms()
        for n in nodes:
            n.start()
        try:
            ok = nodes[0].consensus.wait_for_height(4, timeout=60)
            net_ms = _now_ms() - t_net
            assert ok, "live net stalled"
            store = nodes[0].block_store
            block = store.load_block(3)
            commit = store.load_block_commit(3)  # block 4's LastCommit
            # the real part-set BlockID the network committed under
            bid = block.block_id()
        finally:
            for n in nodes:
                n.stop()

    def run_cpu():
        t = _now_ms()
        tv.verify_commit_light("bench-live", vals, bid, 3, commit,
                               batch_fn=None)
        return _now_ms() - t

    cpu = [run_cpu() for _ in range(20)]
    return {
        "metric": "cfg1 live 4-val kvstore net VerifyCommitLight",
        "value": round(p50(cpu), 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "extra": {
            "net_to_height4_ms": round(net_ms, 1),
            "note": "4 sigs is below any sane device batch threshold; "
                    "the product path verifies on CPU (shouldBatchVerify "
                    "economics), so baseline == value",
        },
    }


def _device_commit_bench(vs, commit, bid, height, steady_k=STEADY_K):
    """Product-path VerifyCommitLight on device: raw p50 + steady state.

    Steady state uses the cached-valset kernel (ops.ed25519_cached): the
    per-validator window table is built ONCE per valset (reported as
    table_build_ms) and amortized over the stream, which is exactly how
    consensus/blocksync verify thousands of commits against a slowly-
    changing set. Each steady iteration still pays the full per-commit
    host->device upload of the packed signature rows.

    host_pack_ms is the ZERO-COPY pack path (this PR): commit ->
    native template pack (ed25519_pack_commits, no Python sign-bytes
    objects) -> pack_rows_cached into a rotated pinned staging buffer.
    It now INCLUDES sign-bytes assembly (the old number excluded it),
    so it is the honest all-in host cost per flush. steady_overlap_ms
    runs the double-buffered loop — pack k+1 while the device verifies
    k with the rows buffer donated — and staging_overlap_eff is the
    fraction of pack time hidden behind the device.

    host_pack_stamped_ms is the DEVICE-STAMPED path's residual host
    cost: signature scatter + timestamp word split + flags into the
    per-row delta buffers. Sign-bytes assembly, SHA-512 padding and
    mod-L moved into the device prologue, but this residual is not 0
    and is reported so the r-series trajectory stays honest.
    """
    import jax

    from cometbft_tpu.crypto.batch import staging_pool
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.types import validation as tv

    batch_fn = tv.device_batch_fn(use_pallas=True)
    tv.verify_commit_light(CHAIN_ID, vs, bid, height, commit, batch_fn)
    raw = []
    for _ in range(RAW_REPS):
        t = _now_ms()
        tv.verify_commit_light(CHAIN_ID, vs, bid, height, commit, batch_fn)
        raw.append(_now_ms() - t)

    n = len(vs.validators)
    pubs = [v.pub_key.data for v in vs.validators]
    powers = np.asarray([v.voting_power for v in vs.validators], np.int64)
    t = _now_ms()
    table = ec.table_for_pubs(pubs, powers)
    np.asarray(table.ok).sum()  # block_until_ready is a no-op on axon
    table_build_ms = _now_ms() - t
    # valset-churn costs (round-4 verdict item 2): warm full rebuild
    # (compile cached) and a 10-validator incremental update — the
    # epoch-change price while streaming against a live valset
    t = _now_ms()
    t2 = ec.build_table(pubs, powers)
    np.asarray(t2.ok).sum()
    rebuild_warm_ms = _now_ms() - t
    from cometbft_tpu.crypto.keys import PrivKey as _PK

    churn = [(i * (n // 16) + 3,
              _PK.generate((5000 + i).to_bytes(4, "big") + b"\x66" * 28)
              .pub_key().data)
             for i in range(10)]
    t3 = ec.update_table(table, churn)  # compile
    np.asarray(t3.ok).sum()
    t = _now_ms()
    t3 = ec.update_table(table, churn, {churn[0][0]: 123})
    np.asarray(t3.ok).sum()
    update10_ms = _now_ms() - t
    pad = ec.pad_rows(n)
    counted = np.zeros((pad,), np.bool_)
    counted[:n] = True
    cid = np.zeros((pad,), np.int32)
    thresh = ek.threshold_limbs(int(powers.sum()) * 2 // 3)
    pool = staging_pool()

    def pack_once():
        pb, _ = tv.commit_packed_batch(CHAIN_ID, commit, pubs, pad_to=pad)
        out = pool.get("bench.rows", ec.packed_rows_shape(pad), np.int32)
        return ec.pack_rows_cached(pb, counted, cid, thresh, out=out)

    pack_times = []
    for _ in range(3):
        t = _now_ms()
        rows = pack_once()
        pack_times.append(_now_ms() - t)
    pack_ms = min(pack_times)

    valid, tally, quorum = ec.verify_tally_rows_cached(
        jax.device_put(rows), table, 1
    )
    assert bool(np.asarray(quorum)[0]) and np.asarray(valid)[:n].all()
    # steady state WITH the per-commit upload (the product streaming
    # shape). Best of 3 loops: the shared tunnel has multi-x run-to-run
    # noise, and the minimum is the reproducible device+transport cost.
    def steady_loop(get_rows):
        best = float("inf")
        for _ in range(3):
            outs = None
            t = _now_ms()
            for _ in range(steady_k):
                outs = ec.verify_tally_rows_cached(get_rows(), table, 1)
            assert bool(np.asarray(outs[2])[0])
            best = min(best, (_now_ms() - t) / steady_k)
        return best

    steady = steady_loop(lambda: jax.device_put(rows))
    dev_rows = jax.device_put(rows)
    steady_resident = steady_loop(lambda: dev_rows)

    # double-buffered overlap: re-pack EVERY iteration into the rotated
    # staging buffer while the previous flush is still on the device —
    # the verify-plane dispatcher's loop shape
    def overlap_loop():
        best = float("inf")
        for _ in range(3):
            pending = None
            t = _now_ms()
            for _ in range(steady_k):
                r = pack_once()
                nxt = ec.verify_tally_rows_cached(
                    jax.device_put(r), table, 1
                )
                if pending is not None:
                    assert bool(np.asarray(pending[2])[0])
                pending = nxt
            assert bool(np.asarray(pending[2])[0])
            best = min(best, (_now_ms() - t) / steady_k)
        return best

    steady_overlap = overlap_loop()
    eff = (pack_ms + steady - steady_overlap) / pack_ms if pack_ms else 0.0

    # the DEVICE-STAMPED path's residual host cost (ISSUE 19): raw-sig
    # scatter + (secs_lo, secs_hi, nanos) word extraction + flags. The
    # sign-bytes/SHA-512/mod-L work moved on device, but this is NOT 0
    # and the r-series trajectory must say so honestly.
    css = commit.signatures

    def delta_pack_once():
        sec_a = np.fromiter((cs.timestamp.seconds for cs in css),
                            np.int64, n)
        nan_a = np.fromiter((cs.timestamp.nanos for cs in css),
                            np.int64, n)
        dsig = pool.get("bench.dsig", (pad, 64), np.uint8)
        dsig[:n] = np.frombuffer(
            b"".join(cs.signature for cs in css), np.uint8
        ).reshape(-1, 64)
        dts = pool.get("bench.dts", (pad, 3), np.int32)
        dts[:n, 0] = (sec_a & 0xFFFFFFFF).astype(np.uint32) \
            .view(np.int32)
        dts[:n, 1] = (sec_a >> 32).astype(np.int32)
        dts[:n, 2] = nan_a.astype(np.int32)
        dfl = pool.get("bench.dflags", (pad,), np.int32)
        dfl[:n] = 3  # live | counted (single template, commit 0)
        return dsig, dts, dfl

    delta_times = []
    for _ in range(3):
        t = _now_ms()
        delta_pack_once()
        delta_times.append(_now_ms() - t)

    overlap = {
        "steady_overlap_ms": round(steady_overlap, 2),
        "staging_overlap_eff": round(max(0.0, min(1.0, eff)), 3),
        "host_pack_stamped_ms": round(min(delta_times), 3),
    }
    return (raw, steady, pack_ms,
            {"cold": table_build_ms, "rebuild_warm": rebuild_warm_ms,
             "update10": update10_ms},
            steady_resident, overlap)


def cfg2_1k_commit():
    """#2: 1000-validator ed25519 commit, batch verified on device."""
    vs, commit, bid = make_ed_commit(1000)
    per_sig = cpu_ed25519_per_sig_ms(vs, commit)
    cpu_ms = per_sig * 1000
    raw, steady, pack_ms, tbl_ms, resident, overlap = _device_commit_bench(
        vs, commit, bid, 12345
    )
    return {
        "metric": "cfg2 1000-validator commit batch verify",
        "value": round(steady, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / steady, 2),
        "extra": {
            "raw_p50_ms": round(p50(raw), 2),
            "host_pack_ms": round(pack_ms, 2),
            # residual host cost when the flush ships per-row deltas
            # and sign-bytes are stamped ON DEVICE (ISSUE 19) — small,
            # but not 0: sig scatter + ts word split + flags
            "host_pack_stamped_ms": overlap["host_pack_stamped_ms"],
            "steady_overlap_ms": overlap["steady_overlap_ms"],
            "staging_overlap_eff": overlap["staging_overlap_eff"],
            "table_build_ms": round(tbl_ms["cold"], 1),
            "table_rebuild_warm_ms": round(tbl_ms["rebuild_warm"], 1),
            "table_update_10vals_ms": round(tbl_ms["update10"], 1),
            "steady_resident_ms": round(resident, 2),
            "cpu_measured_ms": round(cpu_ms, 1),
            "cpu_batch_bound_2x_ms": round(cpu_ms / 2, 1),
            "sigs_per_sec": round(1000 / (steady / 1000)),
        },
    }


def cfg3_mixed():
    """#3: 10000-validator mixed ed25519/sr25519, fused quorum tally."""
    try:
        from cometbft_tpu.ops import sr25519_kernel  # noqa: F401
    except ImportError:
        return {
            "metric": "cfg3 10k mixed ed25519/sr25519 fused tally",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "extra": {"status": "sr25519 kernel not yet available"},
        }
    from cometbft_tpu.bench_support import mixed_commit_bench

    return mixed_commit_bench(CHAIN_ID)


def cfg4_streaming(n_blocks=256, n_vals=1000):
    """#4: blocksync replay — streamed batch verify through StreamVerifier
    (fused multi-commit chunks, double-buffered dispatch)."""
    from cometbft_tpu.blocksync.pipeline import CommitJob, StreamVerifier
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    privs = [
        PrivKey.generate((900 + i).to_bytes(4, "big") + b"\x22" * 28)
        for i in range(n_vals)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 50) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    t_gen = _now_ms()
    jobs = []
    for h in range(1, n_blocks + 1):
        bid = BlockID(h.to_bytes(4, "big") * 8,
                      PartSetHeader(1, b"\x0f" * 32))
        sigs = []
        for v in vs.validators:
            ts = Timestamp(1_700_000_000 + h, 0)
            sb = canonical.canonical_vote_bytes(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, h, 0, bid, ts
            )
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        jobs.append(CommitJob(vs, bid, h, Commit(h, 0, bid, sigs),
                              CHAIN_ID))
    gen_s = (_now_ms() - t_gen) / 1000

    sv = StreamVerifier(use_pallas=True)
    # warm (compiles every bucket shape used)
    r = sv.verify(jobs[:80])
    assert all(e is None for e in r)
    # best-of-N whole-run walls (r05 post-mortem): one wall sample on
    # the shared tunnel carries multi-x noise — the minimum is the
    # reproducible host-pack + device + transport cost
    walls = []
    for _ in range(WALL_RUNS):
        t = _now_ms()
        results = sv.verify(jobs)
        walls.append(_now_ms() - t)
        assert all(e is None for e in results)
    wall_ms = min(walls)
    total_sigs = n_blocks * n_vals
    per_sig = cpu_ed25519_per_sig_ms(vs, jobs[0].commit, sample=300)
    cpu_wall_ms = per_sig * total_sigs
    return {
        "metric": "cfg4 blocksync streamed batch verify",
        "value": round(total_sigs / (wall_ms / 1000)),
        "unit": "sigs/sec",
        "vs_baseline": round(cpu_wall_ms / wall_ms, 2),
        "extra": {
            "blocks": n_blocks,
            "vals_per_block": n_vals,
            "wall_ms": round(wall_ms, 1),
            "wall_ms_runs": [round(w, 1) for w in walls],
            "commits_per_sec": round(n_blocks / (wall_ms / 1000), 1),
            "cpu_measured_ms": round(cpu_wall_ms, 1),
            "fixture_gen_s": round(gen_s, 1),
            "note": "streaming overlap: host packs chunk k+1 while device "
                    "verifies chunk k (async dispatch)",
        },
    }


def cfg5_light_secp(n_vals=10_000, target_height=256):
    """#5: light-client skipping verification, 10k secp256k1 validators.

    The reference CANNOT batch this at all (crypto/batch/batch.go:12-21
    has no secp256k1 verifier; it falls to verifyCommitSingle,
    types/validation.go:266). Ours batches ECDSA on device."""
    from cometbft_tpu.crypto.keys import PubKey, Secp256k1PrivKey
    from cometbft_tpu.light import client as lc
    from cometbft_tpu.light import verifier as lv
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types import validation as tv
    from cometbft_tpu.types.block import Header
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    T0 = 1_700_000_000
    privs = [
        Secp256k1PrivKey.generate((3000 + i).to_bytes(4, "big") + b"\x33" * 28)
        for i in range(n_vals)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 5) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    blocks = {}

    def make_block(h):
        if h in blocks:
            return blocks[h]
        header = Header(
            chain_id=CHAIN_ID, height=h, time=Timestamp(T0 + h, 0),
            last_block_id=BlockID(), validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            proposer_address=vs.validators[0].address,
            app_hash=b"\x01" * 32,
        )
        bid = BlockID(header.hash(), PartSetHeader(1, header.hash()))
        sigs = []
        for v in vs.validators:
            ts = Timestamp(T0 + h, 42)
            sb = canonical.canonical_vote_bytes(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, h, 0, bid, ts
            )
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        blocks[h] = lv.LightBlock(
            lv.SignedHeader(header, Commit(h, 0, bid, sigs)), vs
        )
        return blocks[h]

    t_gen = _now_ms()
    make_block(1)
    make_block(target_height)
    gen_s = (_now_ms() - t_gen) / 1000

    # CPU baseline: serial secp256k1 verify (the reference's only option)
    b1 = blocks[1]
    sample = 200
    msgs = [b1.signed_header.commit.vote_sign_bytes(CHAIN_ID, i)
            for i in range(sample)]
    t = _now_ms()
    for i in range(sample):
        assert vs.validators[i].pub_key.verify_signature(
            msgs[i], b1.signed_header.commit.signatures[i].signature
        )
    secp_per_sig = (_now_ms() - t) / sample
    # bisection with a stable valset = one non-adjacent verify of the
    # target (1/3 trusting + 2/3 light): ~2 batch passes over 10k sigs
    cpu_ms = secp_per_sig * n_vals * 2

    provider = lc.Provider(CHAIN_ID, lambda h: make_block(h))
    batch_fn = tv.device_batch_fn(use_pallas=True)

    def run():
        c = lc.Client(CHAIN_ID, provider, trusting_period=1e6,
                      batch_fn=batch_fn)
        c.trust_light_block(blocks[1])
        t = _now_ms()
        c.verify_light_block_at_height(target_height,
                                       now=Timestamp(T0 + 500, 0))
        return _now_ms() - t

    run()  # warm compile
    times = [run() for _ in range(5)]
    val = p50(times)
    return {
        "metric": "cfg5 light-client skipping verify 10k secp256k1",
        "value": round(val, 1),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / val, 2),
        "extra": {
            "cpu_measured_ms": round(cpu_ms, 1),
            "cpu_per_sig_us": round(secp_per_sig * 1000, 1),
            "fixture_gen_s": round(gen_s, 1),
            "note": "reference has NO secp batch path (verifyCommitSingle)",
        },
    }


def cfg6_vote_plane(n_vals=256, n_threads=8):
    """#6: concurrent single-vote gossip through the verify plane.

    N threads each gossip a disjoint slice of one height's precommits
    into a shared VoteSet — the consensus hot path where, pre-plane,
    every vote signature single-verified serially on the host under the
    VoteSet lock. With the plane on, verification leaves the lock and
    concurrent votes coalesce into shared bucket passes (the fused
    cached-table pass on TPU backends), with the 2/3 tally computed in
    the same flush."""
    import threading

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.types.vote_set import VoteSet
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    privs = [
        PrivKey.generate((7000 + i).to_bytes(4, "big") + b"\x44" * 28)
        for i in range(n_vals)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    bid = BlockID(b"\x6b" * 32, PartSetHeader(1, b"\x6c" * 32))
    votes = []
    for p in privs:
        idx, _ = vs.get_by_address(p.pub_key().address())
        v = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=9, round=0,
                 block_id=bid, timestamp=Timestamp(1_700_000_000, 0),
                 validator_address=p.pub_key().address(),
                 validator_index=idx)
        v.signature = p.sign(v.sign_bytes(CHAIN_ID))
        votes.append(v)

    def run(plane_on):
        vset = VoteSet(CHAIN_ID, 9, 0, canonical.PRECOMMIT_TYPE, vs)
        plane = None
        if plane_on:
            plane = VerifyPlane(window_ms=1.5, max_batch=4096,
                                max_queue=16384)
            plane.start()
            set_global_plane(plane)
        lats, errs = [], []

        def worker(lo):
            mine = []
            for v in votes[lo::n_threads]:
                t = _now_ms()
                try:
                    vset.add_vote(v)
                except Exception as e:  # noqa: BLE001 - recorded below
                    errs.append(repr(e))
                mine.append(_now_ms() - t)
            lats.extend(mine)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        t0 = _now_ms()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _now_ms() - t0
        stats = plane.stats() if plane else None
        if plane:
            set_global_plane(None)
            plane.stop()
        assert not errs, errs[:3]
        assert vset.has_two_thirds_majority()
        return p50(lats), wall, stats

    serial_p50, serial_wall, _ = run(False)
    plane_p50, plane_wall, pstats = run(True)
    plane_sps = n_vals / (plane_wall / 1000)
    serial_sps = n_vals / (serial_wall / 1000)
    return {
        "metric": "cfg6 concurrent vote gossip via verify plane",
        "value": round(plane_sps),
        "unit": "sigs/sec",
        "vs_baseline": round(plane_sps / serial_sps, 2),
        "extra": {
            "threads": n_threads,
            "votes": n_vals,
            "plane_vote_p50_ms": round(plane_p50, 3),
            "serial_vote_p50_ms": round(serial_p50, 3),
            "plane_wall_ms": round(plane_wall, 1),
            "serial_wall_ms": round(serial_wall, 1),
            "serial_sigs_per_sec": round(serial_sps),
            "plane_batches": pstats["batches"] if pstats else None,
            "plane_rows": pstats["rows_verified"] if pstats else None,
            "plane_pack_ms_total": round(pstats["pack_seconds"] * 1000, 2)
            if pstats else None,
            "plane_h2d_bytes": pstats["h2d_bytes"] if pstats else None,
            "plane_overlapped_flushes": pstats["overlapped"]
            if pstats else None,
            "note": "baseline = serial host verify under the VoteSet "
                    "lock (the pre-plane product path)",
        },
    }


def disabled_flush_bookkeeping_us(k: int = 20_000) -> dict:
    """Per-flush cost of the verify plane's ALWAYS-ON accounting with
    tracing disabled — the r05 post-mortem's suspect #1, measured.

    Replays the exact bookkeeping sequence _stage/_finish_flight run
    per flush on the disabled path (four monotonic_ns reads, the one
    FIELDS-ordered scratch list that becomes the ring slot, the
    in-place stage fills, the ring append) plus the cost of one
    disabled tracing.span() call, in isolation, so the number is the
    hook overhead itself and not the workload around it."""
    from cometbft_tpu.libs import tracing
    from cometbft_tpu.verifyplane.plane import (PATH_HOST, STAMP_HOST,
                                                FlushLedger)

    assert not tracing.enabled(), "measure the DISABLED path"
    led = FlushLedger()
    t_led = _now_ms()
    for i in range(k):
        t0 = tracing.monotonic_ns()
        gen = tracing.clock_gen()
        rec = [i, round(t0 / 1e6, 3), 64, 4,
               round((t0 - t0) / 1e6, 3), 0.0, 0.0, 0.0, 0.0, 0,
               PATH_HOST, STAMP_HOST, "closed", 0, 0, 64, 0, 0, 0, 1,
               1, 0, 0, 0.0, 0.0, 0, 0.0, 0.0, (), t0, t0, gen, 0]
        t1 = tracing.monotonic_ns()
        rec[5] = round((t1 - t0) / 1e6, 3)
        t2 = tracing.monotonic_ns()
        rec[7] = round((t2 - t1) / 1e6, 3)
        t3 = tracing.monotonic_ns()
        rec[8] = round((t3 - t2) / 1e6, 3)
        led.record(rec)
    ledger_us = (_now_ms() - t_led) * 1000 / k
    t_span = _now_ms()
    for _ in range(k):
        if tracing.enabled():  # the guard every flush-path hook uses
            pass
        with tracing.span("bench.noop", cat="bench"):
            pass
    span_us = (_now_ms() - t_span) * 1000 / k
    return {
        "ledger_bookkeeping_us_per_flush": round(ledger_us, 3),
        "disabled_span_us_per_call": round(span_us, 3),
        "note": "always-on ledger + one disabled span, per flush; a "
                "cfg2 steady iteration is ~10^4x this",
    }


def device_ledger_bookkeeping_us(k: int = 20_000) -> dict:
    """Per-flush cost of the device observatory's ALWAYS-ON hooks with
    tracing disabled (ISSUE 15 acceptance: < 10 us/flush).

    Replays the exact per-flush sequence the dispatcher adds for the
    observatory — one attribution frame push/pop around the dispatch
    (attr_begin/attr_end), the two extra clock reads bracketing it,
    and the three in-place ledger stamps (comp/h2d/util) — in
    isolation. The compile RECORDING path itself is off this budget
    (compiles are rare, ms-scale events), but it is measured too so a
    storm can't hide a pathological record cost."""
    from cometbft_tpu.libs import deviceledger, tracing

    assert not tracing.enabled(), "measure the DISABLED path"
    led = deviceledger.CompileLedger()
    rec = [0, 0.0, 0.0, 0, "bench", -1, 0]
    t0 = _now_ms()
    for i in range(k):
        fr = deviceledger.attr_begin("plane.flush", i)
        a = tracing.monotonic_ns()
        b = tracing.monotonic_ns()
        deviceledger.attr_end(fr)
        rec[2] = round(fr.ms, 3)
        rec[3] = round(max((b - a) / 1e6 - fr.ms, 0.0), 3)
        rec[4] = 0.97
    attr_us = (_now_ms() - t0) * 1000 / k
    t1 = _now_ms()
    for i in range(2000):
        led.record(0.001, False, "bench", i)
    record_us = (_now_ms() - t1) * 1000 / 2000
    return {
        "flush_hook_us_per_flush": round(attr_us, 3),
        "compile_record_us": round(record_us, 3),
        "note": "always-on device observatory; the flush-path budget "
                "is <10us per flush (compile records are rare "
                "ms-scale events, off that budget)",
    }


def height_ledger_bookkeeping_us(k: int = 20_000) -> dict:
    """Per-step-transition cost of the ALWAYS-ON consensus height
    ledger with tracing disabled (ISSUE 13 acceptance: < 10 us/step,
    allocation-free in the FlushLedger sense — the scratch list is the
    ring slot; the step path builds no dicts/spans/strings).

    Replays the exact per-transition sequence _set_step drives
    (on_step: clock read + step-slot dict lookup + in-place stores,
    plus the once-per-height fsync anchor check) and the per-precommit
    note_vote stamp, in isolation, over a full open->steps->finalize
    height cycle per 8 transitions so the ring append amortizes in
    like production."""
    from cometbft_tpu.consensus.heightledger import HeightLedger
    from cometbft_tpu.libs import tracing

    assert not tracing.enabled(), "measure the DISABLED path"
    led = HeightLedger()
    steps = (2, 3, 4, 6, 8)  # new_round/propose/prevote/precommit/commit
    t0 = _now_ms()
    h = 0
    for i in range(k):
        if i % len(steps) == 0:
            h += 1
        led.on_step(h, 0, steps[i % len(steps)])
        led.note_wal_fsync_base(1234)
    step_us = (_now_ms() - t0) * 1000 / k
    # allocation audit: steady-state step transitions WITHIN one height
    # (no height open, no ring append) must hold the process block
    # count flat — the scratch list absorbs every stamp in place (the
    # clock's int objects churn through the freelist, netting zero)
    import sys as _sys

    led.on_step(h + 1, 0, 2)  # open once, off the measured window
    blocks0 = _sys.getallocatedblocks()
    for i in range(1024):
        led.on_step(h + 1, 0, steps[i % len(steps)])
    alloc_per_step = (_sys.getallocatedblocks() - blocks0) / 1024
    t1 = _now_ms()
    for i in range(k):
        led.note_vote(0, i & 63)
    vote_us = (_now_ms() - t1) * 1000 / k
    # one full height close (the once-per-height cost, NOT on the
    # step budget): record with a tiny synthetic commit
    class _Sig:
        def is_absent(self):
            return False

    t2 = _now_ms()
    for j in range(64):
        led.on_step(h + 1 + j, 0, 4)
        led.record_height(h + 1 + j, 0, "deadbeef", 0, 0,
                          commit_sigs=[_Sig()] * 4)
    finalize_us = (_now_ms() - t2) * 1000 / 64
    return {
        "step_transition_us": round(step_us, 3),
        "steady_alloc_blocks_per_step": round(alloc_per_step, 3),
        "note_vote_us": round(vote_us, 3),
        "finalize_record_us": round(finalize_us, 3),
        "note": "always-on height ledger, tracing off; budget is "
                "<10us per step transition (the finalize record runs "
                "once per height and is off that budget)",
    }


def peer_ledger_bookkeeping_us(k: int = 20_000) -> dict:
    """Per-message cost of the ALWAYS-ON gossip observatory with
    tracing disabled (ISSUE 14 acceptance: < 10 us/message — the seam
    rides every MConnection send/recv and every SimConn hop, so it
    must be integer stores, not dicts-per-message).

    Replays the exact per-message sequence the send and recv routines
    drive (note_sent: totals + the first-touch channel slot;
    note_recv per packet; note_queue_depth after each enqueue) plus
    the per-vote route stamp, in isolation."""
    from cometbft_tpu.libs import tracing
    from cometbft_tpu.p2p import peerledger

    assert not tracing.enabled(), "measure the DISABLED path"
    led = peerledger.PeerLedger()
    rec = led.open_peer("bench-peer", True)
    t0 = _now_ms()
    for i in range(k):
        peerledger.note_sent(rec, 0x22, 180)
        peerledger.note_queue_depth(rec, i & 15)
    send_us = (_now_ms() - t0) * 1000 / k
    t1 = _now_ms()
    for i in range(k):
        peerledger.note_recv(rec, 0x22, 180, eof=(i & 1) == 0)
    recv_us = (_now_ms() - t1) * 1000 / k
    # allocation audit: steady-state messages on a warmed channel slot
    # hold the process block count flat (first touch allocated it)
    import sys as _sys

    blocks0 = _sys.getallocatedblocks()
    for i in range(1024):
        peerledger.note_sent(rec, 0x22, 180)
    alloc_per_msg = (_sys.getallocatedblocks() - blocks0) / 1024
    t2 = _now_ms()
    for i in range(k):
        # prune periodically so the loop measures the steady-state
        # INSERT path, not the cheap at-capacity drop branch
        if i % 8000 == 0:
            led.prune_votes(1 << 60)
        led.note_vote_seen((i >> 6, 0, 2, i & 63), "bench-peer")
    vote_us = (_now_ms() - t2) * 1000 / k
    led.prune_votes(1 << 60)
    return {
        "send_us_per_msg": round(send_us, 3),
        "recv_us_per_msg": round(recv_us, 3),
        "steady_alloc_blocks_per_msg": round(alloc_per_msg, 3),
        "vote_seen_us": round(vote_us, 3),
        "note": "always-on peer ledger, tracing off; budget is <10us "
                "per message (vote stamps ride only VOTE_CHANNEL "
                "receives)",
    }


def cfg7_pack_only(n_vals=10_000):
    """#7: host packing microbench — template row packing vs the legacy
    per-vote sign-bytes paths, device-free.

    Three ways to build the same 10k canonical sign-bytes:
      legacy    — full canonical_vote_bytes re-encode per signature
                  (the reference's loop, types/validation.go:207);
      encoder   — the splice-cached CanonicalVoteEncoder loop
                  (Commit.vote_sign_bytes, the round-4 path);
      template  — ONE vectorized numpy patch over all rows
                  (Commit.sign_bytes_rows, this PR).
    All three are asserted byte-identical; value = legacy/template
    speedup (acceptance: >= 5x)."""
    from cometbft_tpu.types import canonical

    vs, commit, bid = make_ed_commit(n_vals, seed=9)

    def run_legacy():
        t = _now_ms()
        out = [
            canonical.canonical_vote_bytes(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, commit.height,
                commit.round, bid, cs.timestamp,
            )
            for cs in commit.signatures
        ]
        return _now_ms() - t, out

    def run_encoder():
        t = _now_ms()
        out = [commit.vote_sign_bytes(CHAIN_ID, i) for i in range(n_vals)]
        return _now_ms() - t, out

    def run_template():
        t = _now_ms()
        out = commit.sign_bytes_rows(CHAIN_ID)
        return _now_ms() - t, out

    legacy_ms = min(run_legacy()[0] for _ in range(3))
    encoder_ms = min(run_encoder()[0] for _ in range(3))
    template_ms = min(run_template()[0] for _ in range(3))
    a, b, c = run_legacy()[1], run_encoder()[1], run_template()[1]
    assert a == b == c, "packing paths diverged"
    speedup = legacy_ms / template_ms if template_ms else float("inf")
    return {
        "metric": "cfg7 pack-only: template rows vs per-vote sign-bytes",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
        "extra": {
            "rows": n_vals,
            "legacy_per_vote_ms": round(legacy_ms, 2),
            "encoder_splice_ms": round(encoder_ms, 2),
            "template_rows_ms": round(template_ms, 2),
            "encoder_vs_template": round(encoder_ms / template_ms, 2)
            if template_ms else None,
            # the r05 suspect-#1 exoneration row: the per-flush cost of
            # the flush ledger + disabled trace hooks, in microseconds
            "disabled_flush_path": disabled_flush_bookkeeping_us(),
            # the ISSUE-13 sibling: the always-on height ledger's
            # per-step-transition cost (budget < 10 us, tracing off)
            "height_ledger_path": height_ledger_bookkeeping_us(),
            "note": "host-only; same bytes asserted across all three "
                    "paths (the zero-copy hot path invariant)",
        },
    }


def cfg8_multichip_smoke(n_sigs=64):
    """#8: small-scale multichip smoke — the sharded fused verify+tally
    step over every local device, sized to finish well under the
    harness timeout (the round-5 MULTICHIP run was killed at rc=124).
    Also asserts the mesh step builders are memoized (a second build
    must HIT the step cache, not re-trace — the regression that caused
    the timeout)."""
    import jax

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.parallel import mesh as pm

    keys = [PrivKey.generate((600 + i).to_bytes(4, "big") + b"\x55" * 28)
            for i in range(n_sigs)]
    pubs = [kq.pub_key().data for kq in keys]
    msgs = [b"multichip-smoke-%d" % i for i in range(n_sigs)]
    sigs = [kq.sign(m) for kq, m in zip(keys, msgs)]
    n_dev = len(jax.devices())
    pad = max(64, n_dev)
    pb = ek.pack_batch(pubs, msgs, sigs, pad_to=pad)
    powers = np.full((n_sigs,), 1000, np.int64)
    power5 = np.zeros((pb.padded, ek.POWER_LIMBS), np.int32)
    power5[:n_sigs] = ek.power_limbs(powers)
    counted = np.zeros((pb.padded,), np.bool_)
    counted[:n_sigs] = True
    cids = np.zeros((pb.padded,), np.int32)
    thresh = ek.threshold_limbs(int(powers.sum()) * 2 // 3)

    mesh = pm.make_mesh()
    t = _now_ms()
    step = pm.sharded_verify_tally(mesh, n_commits=1)
    pb2, args = pm.shard_batch_arrays(mesh, pb, power5, counted, cids)
    valid, tally, quorum = jax.block_until_ready(step(*args, thresh))
    first_ms = _now_ms() - t
    assert np.asarray(valid)[:n_sigs].all() and bool(np.asarray(quorum)[0])
    before = pm.cache_stats()
    assert pm.sharded_verify_tally(mesh, n_commits=1) is step
    after = pm.cache_stats()
    assert after["hits"] > before["hits"], "mesh step cache not hit"
    t = _now_ms()
    jax.block_until_ready(step(*args, thresh))
    warm_ms = _now_ms() - t
    return {
        "metric": "cfg8 multichip smoke sharded verify+tally",
        "value": round(warm_ms, 2),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "devices": n_dev,
            "sigs": n_sigs,
            "first_call_ms": round(first_ms, 1),
            "mesh_cache": pm.cache_stats(),
            "note": "builders memoized per (mesh, n_commits); the "
                    "expensive programs are shared across tally widths",
        },
    }


def cfg9_sustained(rate=120.0, duration=45.0, n_nodes=4):
    """#9: sustained open-loop throughput — the ROADMAP item-5 metric.

    An in-process LocalNetwork commits blocks while node 0 eats an
    open-loop signed-tx flood through broadcast_tx (admission control +
    sigtx verification on the BULK lane of a running verify plane).
    Open-loop (tools/loadtime discipline): injections fire at fixed
    target times regardless of response latency, so overload shows up
    as queueing delay and explicit OVERLOADED verdicts instead of the
    generator politely backing off. Reports accepted tx/s + commits/s
    over the window and the per-lane submit-to-result p99s — the
    numbers the chaos-soak test bounds (zero CONSENSUS sheds, vote p99
    within 2x no-flood) are REPORTED here so --baseline can watch the
    sustained story drift release-over-release."""
    from tools.loadtime import run_inprocess

    rep = run_inprocess(rate, duration, n_nodes=n_nodes, signed=True,
                        plane=True)
    lane_waits = (rep.get("plane") or {}).get("lane_waits", {})
    sheds = (rep.get("plane") or {}).get("sheds", {})
    cons = lane_waits.get("consensus", {})
    bulk = lane_waits.get("bulk", {})
    return {
        "metric": "cfg9 sustained open-loop throughput",
        "value": rep["accepted_tx_per_s"],
        "unit": "tx/s",
        "vs_baseline": None,
        "extra": {
            "nodes": n_nodes,
            "offered_tx_per_s": rep["offered_tx_per_s"],
            "duration_s": rep["wall_s"],
            "commits": rep["commits"],
            "commits_per_s": rep["commits_per_s"],
            "accepted": rep["accepted"],
            "overloaded": rep["overloaded"],
            "rejected_other": rep["rejected_other"],
            "late_injections": rep["late_injections"],
            "checktx_p50_ms": rep["checktx_latency"].get("p50_ms"),
            "checktx_p99_ms": rep["checktx_latency"].get("p99_ms"),
            "vote_submit_p99_ms": cons.get("p99_ms"),
            "bulk_submit_p99_ms": bulk.get("p99_ms"),
            "consensus_sheds": sheds.get("consensus"),
            "bulk_sheds": sheds.get("bulk"),
            "admission": rep.get("admission"),
            # per-height commit-latency attribution (height ledger ->
            # tools/height_report): the sustained-load commit p50/p99
            # are first-class baseline numbers now
            "commit_p50_ms": rep.get("commit_p50_ms"),
            "commit_p99_ms": rep.get("commit_p99_ms"),
            "height_stage_table": rep.get("height_stage_table"),
            "height_dump": rep.get("height_dump"),
            "note": "open-loop signed flood vs a live committing net; "
                    "QoS invariants asserted in tests/test_soak.py",
        },
    }


def _make_light_chain(n_heights, n_vals, seed=9100):
    """Deterministic ed25519 light-block chain (stable valset) for the
    gateway benches: {height: LightBlock} + the Provider over it."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.light import client as lc
    from cometbft_tpu.light import verifier as lv
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block import Header
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    T0 = 1_700_000_000
    privs = [
        PrivKey.generate((seed + i).to_bytes(4, "big") + b"\x55" * 28)
        for i in range(n_vals)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    blocks = {}
    prev_bid = BlockID()
    for h in range(1, n_heights + 1):
        header = Header(
            chain_id=CHAIN_ID, height=h, time=Timestamp(T0 + h, 0),
            last_block_id=prev_bid, validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            proposer_address=vs.validators[0].address,
            app_hash=b"\x01" * 32,
        )
        bid = BlockID(header.hash(), PartSetHeader(1, header.hash()))
        sigs = []
        for v in vs.validators:
            ts = Timestamp(T0 + h, 42)
            sb = canonical.canonical_vote_bytes(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, h, 0, bid, ts
            )
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        blocks[h] = lv.LightBlock(
            lv.SignedHeader(header, Commit(h, 0, bid, sigs)), vs
        )
        prev_bid = bid
    provider = lc.Provider(CHAIN_ID, lambda h: blocks.get(h))
    return blocks, provider, (T0 + n_heights + 100)


def _gateway_run(blocks, provider, now_s, n_clients, targets_of,
                 use_gateway, ledger_cap=8192):
    """Drive n_clients worth of light-client syncs, with or without
    the gateway, against a FRESH host-path verify plane — and read the
    plane's flush ledger for the submission count (the acceptance
    metric: coalescing must be visible in ledger rows, not inferred).

    use_gateway=False is the uncoalesced baseline: every client owns a
    private light.Client + store (what N independent light clients do
    today). use_gateway=True routes everyone through ONE LightGateway
    (coalescer + shared store + LRU)."""
    import threading

    from cometbft_tpu.light import client as lc
    from cometbft_tpu.lightgate import LightGateway, gateway_batch_fn
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane
    from cometbft_tpu.verifyplane.plane import FlushLedger

    now = Timestamp(now_s, 0)
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.ledger = FlushLedger(capacity=ledger_cap)
    plane.start()
    set_global_plane(plane)
    gw = None
    if use_gateway:
        gw = LightGateway(CHAIN_ID, provider, cache_size=1024)
        gw.client.trust_light_block(blocks[1])
        gw.start(register=False)
    lats, errs = [], []
    lock = threading.Lock()

    def worker(k):
        mine = []
        try:
            if use_gateway:
                for t in targets_of(k):
                    t0 = _now_ms()
                    v = gw.verify(1, t, now=now)
                    mine.append(_now_ms() - t0)
                    assert v["status"] == "verified"
            else:
                c = lc.Client(CHAIN_ID, provider, trusting_period=1e6,
                              batch_fn=gateway_batch_fn())
                c.trust_light_block(blocks[1])
                for t in targets_of(k):
                    t0 = _now_ms()
                    c.verify_light_block_at_height(t, now=now)
                    mine.append(_now_ms() - t0)
        except Exception as e:  # noqa: BLE001 - recorded below
            with lock:
                errs.append(repr(e))
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_clients)]
    t0 = _now_ms()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = _now_ms() - t0
    set_global_plane(None)
    plane.stop()
    assert not errs, errs[:3]
    recs = plane.dump_flushes()["flushes"]
    subs = sum(r["subs"] for r in recs)
    g_rows = sum(r["g_rows"] for r in recs)
    out = {"wall_ms": wall, "lats": lats, "plane_subs": subs,
           "gateway_rows": g_rows, "flushes": len(recs)}
    if gw is not None:
        out["gw_stats"] = gw.stats()
    return out


def cfg10_gateway(n_clients=32, n_heights=48, n_vals=8):
    """#10: light-client gateway — N concurrent clients, coalesced
    skipping verification (ROADMAP item 3; ISSUE 8 acceptance).

    Each client syncs a mix of SHARED targets (the popular heights a
    wallet fleet all jumps to) and a personal one (disjoint spread).
    The uncoalesced baseline is N private light clients doing the same
    work — today's serving story. The acceptance bar: with the gateway,
    verify-plane submissions (counted from the always-on flush ledger,
    not inferred) must be <= 0.5x the uncoalesced count."""
    blocks, provider, now_s = _make_light_chain(n_heights, n_vals)
    shared = [n_heights // 3, 2 * n_heights // 3, n_heights]

    def targets_of(k):
        return sorted(set(shared + [2 + (k % 8)]))

    base = _gateway_run(blocks, provider, now_s, n_clients, targets_of,
                        use_gateway=False)
    gwr = _gateway_run(blocks, provider, now_s, n_clients, targets_of,
                       use_gateway=True)
    n_requests = len(gwr["lats"])
    assert gwr["plane_subs"] <= 0.5 * base["plane_subs"], (
        f"coalescing failed: gateway plane submissions "
        f"{gwr['plane_subs']} > 0.5x uncoalesced {base['plane_subs']}"
    )
    gws = gwr["gw_stats"]
    hdr_per_s = n_requests / (gwr["wall_ms"] / 1000)
    return {
        "metric": "cfg10 light-client gateway coalesced serving",
        "value": round(hdr_per_s),
        "unit": "headers/sec",
        "vs_baseline": round(base["wall_ms"] / gwr["wall_ms"], 2),
        "extra": {
            "clients": n_clients,
            "requests": n_requests,
            "client_p50_ms": round(p50(gwr["lats"]), 2),
            "client_p99_ms": round(
                float(np.percentile(gwr["lats"], 99)), 2),
            "uncoalesced_p50_ms": round(p50(base["lats"]), 2),
            "plane_subs_gateway": gwr["plane_subs"],
            "plane_subs_uncoalesced": base["plane_subs"],
            "coalesce_sub_ratio": round(
                gwr["plane_subs"] / max(1, base["plane_subs"]), 3),
            "verifies": gws["verifies"],
            "coalesced_requests": gws["coalesced"],
            "verifies_coalesced_ratio": round(
                gws["verifies"] / max(1, gws["requests"]), 3),
            "cache": {k: gws["cache"][k]
                      for k in ("hits", "misses", "size")},
            "gateway_lane_rows": gwr["gateway_rows"],
            "uncoalesced_wall_ms": round(base["wall_ms"], 1),
            "gateway_wall_ms": round(gwr["wall_ms"], 1),
            "note": "uncoalesced = N private light clients, same "
                    "targets, same host plane; submissions counted "
                    "from the flush ledger",
        },
    }


def cfg11_sharded_tally(n_vals=10_000, target_big=100_000):
    """#11: multichip sharded fused flush vs single-device (ISSUE 10).

    One valset, one commit group, the verify plane's fused layout at
    two row scales: a ~10k-row flush (where the single-device cached
    kernel is the baseline) and the biggest cross-chip flush the mesh
    supports up to ~100k rows (past 65536 a single device CANNOT run
    it at all — the sharded plane is the only path). Rows reuse each
    validator's one real signature across strides (verification cost
    is identical; fixture generation stays at one sign per validator).
    Asserts sharded verdicts/tally/quorum bit-match the single-device
    pass at the small shape, and that the mesh step + sharded table
    memos HIT on repeat dispatch (no steady-state re-trace/re-upload).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.parallel import mesh as pm
    from cometbft_tpu.verifyplane.fused import (
        effective_mesh,
        shard_positions,
    )

    n_local = len(jax.devices())
    keys = [PrivKey.generate((8100 + i).to_bytes(4, "big") + b"\x66" * 28)
            for i in range(n_vals)]
    pubs = [kq.pub_key().data for kq in keys]
    msgs = [b"cfg11-%d" % i for i in range(n_vals)]
    sigs = [kq.sign(m) for kq, m in zip(keys, msgs)]
    powers = np.full((n_vals,), 100, np.int64)
    thresh = ek.threshold_limbs(int(powers.sum()) * 2 // 3)

    # clamp like plan_fused does: empty shards would verify padding
    mesh, n_dev, m_s = effective_mesh(pm.make_mesh(), n_vals)
    if mesh is None:
        # 1-chip host / small valset: the degenerate 1-mesh still
        # measures the sharded program so --baseline has a row
        mesh = pm.make_mesh(jax.devices()[:1])
        n_dev, m_s = 1, ec.shard_stride(n_vals, 1)
    b_stride = n_dev * m_s          # rows per stride, all used devices
    max_strides = 65536 // m_s      # per-device kernel budget

    def build_rows(n_strides):
        """Position-ordered packed rows for the sharded fused layout
        (stride 0 counted; strides > 0 duplicate the signatures)."""
        b_loc = n_strides * m_s
        B = n_dev * b_loc
        p_pubs, p_msgs, p_sigs = [], [], []
        counted = np.zeros((B,), np.bool_)
        for p in range(B):
            d, q = divmod(p, b_loc)
            s, vloc = divmod(q, m_s)
            v = d * m_s + vloc
            if v < n_vals:
                p_pubs.append(pubs[v])
                p_msgs.append(msgs[v])
                p_sigs.append(sigs[v])
                counted[p] = s == 0
            else:
                p_pubs.append(b"")
                p_msgs.append(b"")
                p_sigs.append(b"")
        pb = ek.pack_batch(p_pubs, p_msgs, p_sigs, pad_to=B)
        rows = ec.pack_rows_cached(pb, counted,
                                   np.zeros((B,), np.int32))
        return rows, B, n_strides * n_vals  # real (non-padding) rows

    t = _now_ms()
    table_sh = ec.sharded_table_for_pubs(tuple(pubs),
                                         tuple(int(p) for p in powers),
                                         mesh)
    step = pm.sharded_fused_verify(mesh, 1)
    shard_table_ms = _now_ms() - t
    axis = mesh.axis_names[0]
    rows_sh = NamedSharding(mesh, P(None, axis))
    repl = NamedSharding(mesh, P(None, None))
    thresh_d = jax.device_put(thresh, repl)
    base_d = ec.base60_repl(mesh)

    def sharded_steady(rows, reps=STEADY_K):
        out = step(jax.device_put(rows, rows_sh), table_sh.tab,
                   table_sh.ok, table_sh.power5, base_d, thresh_d)
        assert bool(np.asarray(out[2])[0]), "sharded quorum missed"
        best = float("inf")
        for _ in range(3):
            t = _now_ms()
            for _ in range(reps):
                out = step(jax.device_put(rows, rows_sh), table_sh.tab,
                           table_sh.ok, table_sh.power5, base_d,
                           thresh_d)
            assert bool(np.asarray(out[2])[0])
            best = min(best, (_now_ms() - t) / reps)
        return best, out

    # small shape: ~n_vals rows, single-device comparable
    rows_small, b_small, real_small = build_rows(1)
    small_ms, out_small = sharded_steady(rows_small)

    # single-device baseline + bit-identity at the same scale —
    # impossible past the one-chip table budget (table_pad RAISES for
    # n > 65536; guard on n_vals, the sharded path is the only one)
    single_ms = None
    bit_identical = None
    if n_vals <= 65536:
        m_single = ec.table_pad(n_vals)
        table_1 = ec.table_for_pubs(tuple(pubs),
                                    tuple(int(p) for p in powers))
        pb1 = ek.pack_batch(pubs, msgs, sigs, pad_to=m_single)
        c1 = np.zeros((m_single,), np.bool_)
        c1[:n_vals] = True
        rows_1 = ec.pack_rows_cached(pb1, c1,
                                     np.zeros((m_single,), np.int32),
                                     thresh)
        out1 = ec.verify_tally_rows_cached(jax.device_put(rows_1),
                                           table_1, 1)
        best = float("inf")
        for _ in range(3):
            t = _now_ms()
            for _ in range(STEADY_K):
                out1 = ec.verify_tally_rows_cached(
                    jax.device_put(rows_1), table_1, 1)
            best = min(best, (_now_ms() - t) / STEADY_K)
        single_ms = best
        # map both layouts back to (validator) verdicts and compare
        v_sh = np.asarray(out_small[0])
        v_1 = np.asarray(out1[0])
        vv = np.arange(n_vals)
        pos_sh = shard_positions(vv, np.zeros(n_vals, np.int64), m_s, 1)
        bit_identical = bool(
            np.array_equal(v_sh[pos_sh], v_1[vv])
            and np.array_equal(np.asarray(out_small[1]),
                               np.asarray(out1[1]))
            and np.array_equal(np.asarray(out_small[2]),
                               np.asarray(out1[2])))
        assert bit_identical, "sharded != single-device at 10k rows"

    # big shape: as close to target_big as the mesh allows
    n_strides_big = max(1, min(max_strides,
                               -(-target_big // b_stride)))
    rows_big, b_big, real_big = build_rows(n_strides_big)
    big_ms, _ = sharded_steady(rows_big, reps=max(4, STEADY_K // 2))

    # steady state must hit the memos, not re-trace/re-upload
    mesh_before = pm.cache_stats()
    assert pm.sharded_fused_verify(mesh, 1) is step
    assert pm.cache_stats()["hits"] > mesh_before["hits"]
    tbl_before = ec.table_cache_stats()
    ec.sharded_table_for_pubs(tuple(pubs),
                              tuple(int(p) for p in powers), mesh)
    tbl_after = ec.table_cache_stats()
    assert tbl_after["shard_hits"] > tbl_before["shard_hits"]

    sps_big = round(real_big / (big_ms / 1000))
    return {
        "metric": "cfg11 sharded cross-chip fused verify+tally",
        "value": sps_big,
        "unit": "sigs/sec",
        "vs_baseline": (round(single_ms / small_ms, 2)
                        if single_ms else None),
        "extra": {
            "devices": n_local,
            "devices_used": n_dev,
            "shard_stride": m_s,
            "rows_small": real_small,
            "rows_big": real_big,
            "slots_small": b_small,
            "slots_big": b_big,
            "rows_big_target": target_big,
            # rows-x-cost utilization (the device observatory's util
            # model): live rows over padded slots swept per pass —
            # how much of the mesh the flush actually used
            "util_small": round(real_small / b_small, 4),
            "util_big": round(real_big / b_big, 4),
            "sharded_small_ms": round(small_ms, 2),
            "sharded_big_ms": round(big_ms, 2),
            "single_device_small_ms": (round(single_ms, 2)
                                       if single_ms else None),
            "bit_identical_small": bit_identical,
            "shard_table_build_ms": round(shard_table_ms, 1),
            "mesh_cache": pm.cache_stats(),
            "shard_table_cache": {
                k: v for k, v in ec.table_cache_stats().items()
                if k.startswith("shard")},
            "note": "one cross-chip pass per flush: per-shard "
                    "device-resident tables, psum tally, quorum on "
                    "device; rows > 65536 have NO single-device path",
        },
    }


def cfg12_pipelined(n_vals=4096, n_flushes=24):
    """#12: pipelined mesh halves (ISSUE 11) — deck-on vs deck-off
    sustained flush throughput through the REAL plane dispatcher.

    Streams fused valset-backed flushes (one submission = one flush,
    max_batch pinned to the flush size) through three plane arms:
    pipeline_flights=1 (the PR-9 single-flight baseline),
    pipeline_flights=2 at half-mesh size (alternating flushes fly
    DISJOINT halves; pack+dispatch of k+1 overlaps flight k), and
    pipeline_flights=2 with half_mesh_rows=1 (every flush forced to
    the full mesh — the drain-the-deck policy arm, bounding what the
    halves buy). Verdicts must match across arms; on a >=4-device
    host the deck arm's ledger must show genuinely concurrent flights
    (deck airborne_max >= 1). Degrades honestly on hosts without
    halves (deck == baseline; the row still records)."""
    import jax

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import QuorumGroup, VerifyPlane
    from cometbft_tpu.verifyplane import fused as fz

    n_local = len(jax.devices())
    host_only = jax.default_backend() == "cpu" and not fz.ALLOW_CPU_FUSED
    if host_only:
        # no device: the fused/deck path never engages — keep the row
        # alive at a tiny host-path shape instead of minutes of
        # pure-Python ed25519
        n_vals, n_flushes = 32, 4
    keys = [PrivKey.generate((9400 + i).to_bytes(4, "big") + b"\x55" * 28)
            for i in range(n_vals)]
    pubs_t = tuple(k.pub_key().data for k in keys)
    powers_t = tuple(100 for _ in range(n_vals))
    msgs = [b"cfg12-%d" % i for i in range(n_vals)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    rows_all = [(k.pub_key(), m, s) for k, m, s in zip(keys, msgs, sigs)]
    vidx_all = tuple(range(n_vals))

    def run(flights, half_rows=0, timed_flushes=n_flushes):
        plane = VerifyPlane(
            window_ms=0.5, max_batch=n_vals,
            max_queue=n_vals * (timed_flushes + 2),
            use_device=None if host_only else True,
            mesh_devices=0, mesh_min_rows=1, pipeline_flights=flights,
            half_mesh_rows=half_rows)
        plane.start()
        try:
            def burst(k):
                groups = [QuorumGroup(10 ** 15, valset_pubs=pubs_t,
                                      valset_powers=powers_t)
                          for _ in range(k)]
                futs = [plane.submit_many(rows_all, group=g,
                                          vidx=vidx_all)
                        for g in groups]
                return [f.result(300.0) for f in futs]

            burst(2)  # warm: compile the mesh programs off the clock
            t = _now_ms()
            verd = burst(timed_flushes)
            wall = _now_ms() - t
        finally:
            plane.stop()
        summary = plane.dump_flushes()["summary"]
        return wall, verd, summary, plane.stats()

    wall_1, verd_1, sum_1, st_1 = run(1)
    wall_deck, verd_deck, sum_deck, st_deck = run(2)
    wall_full, verd_full, sum_full, _ = run(2, half_rows=1)
    assert verd_deck == verd_1, "deck arm verdicts diverged"
    assert verd_full == verd_1, "full-mesh arm verdicts diverged"
    halves = st_deck["halves"]
    if halves == 2:
        assert sum_deck["deck"]["airborne_max"] >= 1, (
            "deck never flew two flights on a half-capable mesh",
            sum_deck)
    fps = n_flushes / (wall_deck / 1000) if wall_deck else 0.0
    return {
        "metric": "cfg12 pipelined mesh halves sustained flushes",
        "value": round(n_flushes * n_vals / (wall_deck / 1000))
        if wall_deck else None,
        "unit": "sigs/sec",
        "vs_baseline": round(wall_1 / wall_deck, 2) if wall_deck else None,
        "extra": {
            "devices": n_local,
            "halves": halves,
            "host_only": host_only,
            "flushes": n_flushes,
            "rows_per_flush": n_vals,
            "flushes_per_sec_deck": round(fps, 2),
            "wall_single_ms": round(wall_1, 1),
            "wall_deck_ms": round(wall_deck, 1),
            "wall_full_mesh_ms": round(wall_full, 1),
            "deck_airborne_max": sum_deck["deck"]["airborne_max"],
            "deck_overlapped_flushes":
                sum_deck["deck"]["overlapped_flushes"],
            "deck_peak": st_deck["deck_peak"],
            "single_airborne_max": sum_1["deck"]["airborne_max"],
            "full_mesh_airborne_max": sum_full["deck"]["airborne_max"],
            # the device observatory's per-flush split over the deck
            # arm: utilization (half-mesh flushes should pack denser
            # than forced-full-mesh ones) + on-device time estimates
            "util_est": sum_deck["device"]["util"],
            "util_full_mesh": sum_full["device"]["util"],
            "dev_ms_est": sum_deck["device"]["dev_ms"],
            "comp_ms_timed": sum_deck["device"]["comp_ms"],
            "note": "deck-on vs deck-off through the real dispatcher; "
                    "full-mesh arm exercises the drain-first policy",
        },
    }


def _churn_height_probe(n_nodes=3, rotate_at=3, target=8):
    """A LIVE consensus probe for cfg13: a small LocalNetwork commits
    through ONE real validator rotation (kvstore ``val:`` tx -> ABCI
    validator update -> update_with_change_set at H+2), and the
    always-on height ledger attributes per-height commit latency
    before vs after the rotation — plus the late/absent columns (the
    added validator never votes, so every post-rotation commit carries
    an absent precommit the ledger must attribute). Host-only, no jax,
    a few seconds; the device-side table-build numbers stay in the
    main cfg13 arms."""
    import base64

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from tools import height_report

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([40 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("cfg13-probe-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    extra_pub = PrivKey.generate(b"\x77" * 32).pub_key().data
    tx = b"val:" + base64.b64encode(extra_pub) + b"!5"
    try:
        for n in nodes:
            n.start()
        assert nodes[0].consensus.wait_for_height(rotate_at, 30.0)
        # LocalNetwork mempools don't gossip: every node carries the
        # rotation so whichever proposes next includes it
        for n in nodes:
            n.mempool.check_tx(tx)
        assert nodes[0].consensus.wait_for_height(target, 30.0), \
            "probe chain stalled after the rotation"
    finally:
        for n in nodes:
            n.stop()
    dump = nodes[0].consensus.height_ledger.dump()
    rep = height_report.stage_report(dump)
    recs = dump["heights"]
    rot_h = next((r["height"] for r in recs
                  if len(r["absent_bitmap"]) > 0), None)
    pre = [r["apply_ms"] for r in recs
           if r["via"] == "consensus" and r["apply_ms"] > 0
           and (rot_h is None or r["height"] < rot_h)]
    post = [r["apply_ms"] for r in recs
            if rot_h is not None and r["height"] >= rot_h]
    assert rot_h is not None, \
        "rotation never landed — no absent precommit attributed"
    dump["heights"] = recs[-32:]  # trim before embedding
    return {
        "rotation_height": rot_h,
        "pre_rotation_commit_p50_ms": round(p50(pre), 3) if pre else None,
        "post_rotation_commit_ms": [round(x, 3) for x in post[:4]],
        "commit_p50_ms": rep["commit_p50_ms"],
        "commit_p99_ms": rep["commit_p99_ms"],
        "absent_votes": rep["absent_votes"],
        "height_stage_table": rep["stages"],
        "height_dump": dump,
    }


def cfg13_churn(n_vals=10_000, churn=0.01):
    """#13: epoch churn (ISSUE 12) — first-commit-after-rotation
    latency, cold vs warmed.

    Epoch A's 10k-validator table is resident; the committee then
    rotates churn*n_vals members (past MAX_INCREMENTAL, so the cold
    path pays a FULL table rebuild — the worst post-rotation stall).
    The cold arm measures the first cached-path commit verify against
    the unseen epoch-B valset (build + verify inline, exactly what a
    node without the warmer pays); the warmed arm lets the next-epoch
    TableWarmer build epoch C's table in the background first, then
    measures the same first verify as a cache hit. value = the cold
    stall; the warmed/cold ratio is the warmer's win. On a host with
    no accelerator the kernel paths cost minutes of interpret compile,
    so the row degrades to the warmer MACHINERY at host speed
    (injected build; clearly labeled) and the real numbers come from
    the TPU round."""
    import jax

    from cometbft_tpu.ops import table_cache as tcache
    from cometbft_tpu.verifyplane.warmer import TableWarmer

    host_only = jax.default_backend() == "cpu"
    if host_only:
        return _cfg13_host_machinery()

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_cached as ec

    # the base committee derives ONCE; each epoch copies it and
    # re-elects only its churned slots (10k key derivations are the
    # fixture's dominant cost — 3 full regenerations tripled it)
    base_privs = [
        PrivKey.generate((13_000 + i).to_bytes(4, "big") + b"\x31" * 28)
        for i in range(n_vals)
    ]

    def epoch_keys(epoch: int):
        """Epoch e's keys: the base committee with `churn` of the
        slots re-elected per epoch (distinct per epoch)."""
        k = max(1, int(n_vals * churn))
        privs = list(base_privs)
        if epoch:
            for j in range(k):
                slot = (epoch * 37 + j * 97) % n_vals
                privs[slot] = PrivKey.generate(
                    (13_000 + epoch).to_bytes(4, "big")
                    + slot.to_bytes(4, "big") + b"\x32" * 24)
        return privs

    def arm(privs):
        pubs = tuple(p.pub_key().data for p in privs)
        powers = tuple(100 for _ in privs)
        msgs = [b"cfg13-%d" % i for i in range(len(privs))]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        return pubs, powers, msgs, sigs

    # epoch A: warm the kernel + epoch-A table off the clock (every
    # epoch pads to the same bucket, so no compile rides the arms)
    pubs_a, powers, msgs, sigs_a = arm(epoch_keys(0))
    table_a = ec.table_for_pubs(pubs_a, powers)
    valid = ec.verify_batch_cached(pubs_a, msgs, sigs_a, table=table_a)
    assert bool(valid.all()), "epoch-A fixture failed to verify"

    # COLD: epoch B is unseen — the first verify pays the table build
    privs_b = epoch_keys(1)
    pubs_b, _, _, sigs_b = arm(privs_b)
    assert sum(a != b for a, b in zip(pubs_a, pubs_b)) \
        > ec.MAX_INCREMENTAL, "churn under the incremental budget"
    t = _now_ms()
    valid = ec.verify_batch_cached(pubs_b, msgs, sigs_b)
    cold_ms = _now_ms() - t
    assert bool(valid.all())

    # WARMED: the warmer pre-builds epoch C; the first verify hits
    hits0 = tcache.STATS["warmed_hits"]
    privs_c = epoch_keys(2)
    pubs_c, _, _, sigs_c = arm(privs_c)
    warmer = TableWarmer(use_device=True)
    warmer.start()
    try:
        warmer.request(pubs_c, powers)
        assert warmer.wait_idle(300.0), "warm build never finished"
    finally:
        warmer.stop()
    t = _now_ms()
    table_c, warm = ec.table_for_pubs_info(pubs_c, powers)
    valid = ec.verify_batch_cached(pubs_c, msgs, sigs_c, table=table_c)
    warmed_ms = _now_ms() - t
    assert bool(valid.all())
    assert warm, "warmed lookup was not a cache hit"
    assert warmed_ms < cold_ms, (warmed_ms, cold_ms)
    hits = tcache.STATS["warmed_hits"] - hits0
    return {
        "metric": "cfg13 first-commit-after-rotation cold stall",
        "value": round(cold_ms, 1),
        "unit": "ms",
        "vs_baseline": round(cold_ms / warmed_ms, 2) if warmed_ms else None,
        "extra": {
            "vals": n_vals,
            "churned": max(1, int(n_vals * churn)),
            "warmed_ms": round(warmed_ms, 1),
            "warmed_hits": hits,
            "warmer_build_ms": warmer.last_build_ms,
            "cache": {k: v for k, v in ec.table_cache_stats().items()
                      if k.startswith("evictions") or k == "warmed_hits"},
            "resident_bytes": ec.table_cache_resident_bytes(),
            **_cfg13_probe_extra(),
            "note": "cold = first cached-path verify after rotation "
                    "(full table rebuild inline); warmed = same verify "
                    "after the background warmer built the table",
        },
    }


def _cfg13_probe_extra() -> dict:
    """The live-consensus churn probe, fault-isolated: cfg13's table
    numbers must survive a probe failure (the probe adds the
    commit-latency columns, it is not the metric)."""
    try:
        probe = _churn_height_probe()
        return {"height_probe": probe,
                "commit_p50_ms": probe["commit_p50_ms"],
                "commit_p99_ms": probe["commit_p99_ms"]}
    except Exception as e:  # noqa: BLE001 - report, don't fail cfg13
        return {"height_probe_error": repr(e)[:200]}


def _cfg13_host_machinery(n_vals=512, epochs=24):
    """cfg13's no-accelerator degrade: the bounded-cache + warmer
    machinery at host speed with an injected (hash-cost) build — keeps
    the row alive and the plumbing measured; device numbers come from
    the TPU round."""
    import hashlib

    from cometbft_tpu.ops import table_cache as tcache
    from cometbft_tpu.verifyplane.warmer import TableWarmer

    class _Tbl:
        __slots__ = ("nbytes", "digest")

        def __init__(self, pubs):
            h = hashlib.sha256()
            for p in pubs:
                h.update(p)
            self.digest = h.digest()
            self.nbytes = 64 * len(pubs)

    def key_of(pubs):
        h = hashlib.sha256(b"cfg13-host")
        for p in pubs:
            h.update(p)
        return h.digest()

    def build(pubs, powers):
        with tcache.LOCK:
            tcache.TABLES.put(key_of(pubs), _Tbl(pubs))
        tcache.note_warmed(key_of(pubs))

    def lookup(pubs):
        """The table_for_pubs shape: hit = return; miss = build."""
        k = key_of(pubs)
        with tcache.LOCK:
            t = tcache.TABLES.get(k)
            if t is not None:
                tcache.STATS["hits"] += 1
                tcache.consume_warmed(k)
                return t, True
        t = _Tbl(pubs)
        with tcache.LOCK:
            tcache.STATS["misses"] += 1
            tcache.TABLES.put(k, t)
        return t, False

    def epoch_pubs(e):
        return [hashlib.sha256(b"cfg13-%d-%d" % (e, i)).digest()
                for i in range(n_vals)]

    ev0 = tcache.stats()["evictions_tables"]
    res_peak = 0
    t = _now_ms()
    cold_ms = warmed_ms = None
    warmer = TableWarmer(build_fn=build, use_device=False)
    warmer.start()
    try:
        for e in range(epochs):
            pubs = epoch_pubs(e)
            if e == epochs - 1:
                warmer.request(tuple(pubs), None)
                assert warmer.wait_idle(30.0)
                t1 = _now_ms()
                _, warm = lookup(pubs)
                warmed_ms = _now_ms() - t1
                assert warm, "warmed lookup missed"
            else:
                t1 = _now_ms()
                _, warm = lookup(pubs)
                if cold_ms is None:
                    cold_ms = _now_ms() - t1
                assert not warm
            res_peak = max(res_peak, tcache.resident_bytes())
    finally:
        warmer.stop()
    wall = _now_ms() - t
    evictions = tcache.stats()["evictions_tables"] - ev0
    assert evictions > 0, "churn never evicted — caches unbounded?"
    return {
        "metric": "cfg13 churn cache machinery (host degrade)",
        "value": round(cold_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "host_only": True,
            "epochs": epochs,
            "warmed_ms": round(warmed_ms, 3),
            "evictions": evictions,
            "resident_bytes_peak": res_peak,
            "wall_ms": round(wall, 1),
            **_cfg13_probe_extra(),
            "note": "no accelerator: warmer/cache machinery only — "
                    "real cold-vs-warmed table numbers need the TPU "
                    "round",
        },
    }


def headline_10k():
    """The driver metric: 10k-validator VerifyCommitLight fused p50."""
    vs, commit, bid = make_ed_commit(10_000)
    per_sig = cpu_ed25519_per_sig_ms(vs, commit)
    cpu_ms = per_sig * 10_000
    raw, steady, pack_ms, tbl_ms, resident, overlap = _device_commit_bench(
        vs, commit, bid, 12345
    )
    return cpu_ms, raw, steady, pack_ms, tbl_ms, resident, overlap


# --------------------------------------------------------------------------
# --smoke: tier-1-safe miniatures. Tiny shapes, HOST paths only (no jax
# import, no accelerator, no tunnel), seconds not minutes — enough to
# catch bench.py rot (broken fixtures, drifted APIs, dead result shapes)
# in CI without pretending to measure device performance. Metric names
# carry a "_smoke" suffix so a smoke run can never be compared against
# a real BENCH_rNN baseline by accident.
# --------------------------------------------------------------------------


def smoke_commit_verify(n_vals=8):
    """Product-path VerifyCommitLight through the host verifier."""
    from cometbft_tpu.types import validation as tv

    vs, commit, bid = make_ed_commit(n_vals, seed=11)
    tv.verify_commit_light(CHAIN_ID, vs, bid, 12345, commit)  # warm
    best = float("inf")
    for _ in range(3):
        t = _now_ms()
        tv.verify_commit_light(CHAIN_ID, vs, bid, 12345, commit)
        best = min(best, _now_ms() - t)
    return {
        "metric": "cfg2_smoke host VerifyCommitLight",
        "value": round(best, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {"vals": n_vals, "path": "host batch (no device)"},
    }


def smoke_pack_rows(n_vals=64):
    """Template row packing byte-equality at tiny scale (cfg7's core)."""
    vs, commit, bid = make_ed_commit(n_vals, seed=12)
    t = _now_ms()
    rows = commit.sign_bytes_rows(CHAIN_ID)
    pack_ms = _now_ms() - t
    legacy = [commit.vote_sign_bytes(CHAIN_ID, i) for i in range(n_vals)]
    assert rows == legacy, "template rows diverged from encoder"
    return {
        "metric": "cfg4_smoke template pack rows",
        "value": round(pack_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {"rows": n_vals, "byte_equality": True,
                  "disabled_flush_path":
                      disabled_flush_bookkeeping_us(k=2000),
                  "height_ledger_path":
                      height_ledger_bookkeeping_us(k=2000)},
    }


def smoke_vote_plane(n_sigs=32):
    """A host-path verify plane end to end: coalescing dispatcher,
    futures, and the always-on flush ledger."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane

    keys = [PrivKey.generate((7000 + i).to_bytes(4, "big") + b"\x44" * 28)
            for i in range(n_sigs)]
    subs = [(k.pub_key(), b"smoke-%d" % i, k.sign(b"smoke-%d" % i))
            for i, k in enumerate(keys)]
    plane = VerifyPlane(window_ms=0.2, use_device=False)
    plane.start()
    try:
        t = _now_ms()
        futs = [plane.submit(p, m, s) for p, m, s in subs]
        verdicts = [f.result(10) for f in futs]
        wall_ms = _now_ms() - t
    finally:
        plane.stop()
    # result() yields a per-row verdict tuple, so check the rows — a
    # bare truthiness test passes even on (False,)
    assert all(all(v) for v in verdicts), "valid sigs rejected"
    # the ledger record lands after the futures resolve; stop() joins
    # the dispatcher, so only now is the last flush guaranteed visible
    led = plane.dump_flushes()["summary"]
    assert led["flushes"] > 0, "flush ledger recorded nothing"
    return {
        "metric": "cfg6_smoke host verify plane",
        "value": round(n_sigs / (wall_ms / 1000)),
        "unit": "sigs/sec",
        "vs_baseline": None,
        "extra": {"sigs": n_sigs, "wall_ms": round(wall_ms, 2),
                  "ledger": {"flushes": led["flushes"],
                             "rows": led["rows"],
                             "paths": led["paths"]}},
    }


def smoke_gateway(n_clients=4, n_heights=6, n_vals=3):
    """cfg10's miniature: the gateway end to end on the host plane —
    coalescer, shared store, LRU, and the ledger-counted coalescing
    assertion — at tier-1-safe scale (pure-Python crypto, no jax)."""
    blocks, provider, now_s = _make_light_chain(n_heights, n_vals,
                                                seed=9700)
    targets = [n_heights - 2, n_heights]

    def targets_of(k):
        return targets

    base = _gateway_run(blocks, provider, now_s, n_clients, targets_of,
                        use_gateway=False, ledger_cap=256)
    gwr = _gateway_run(blocks, provider, now_s, n_clients, targets_of,
                       use_gateway=True, ledger_cap=256)
    assert gwr["plane_subs"] <= 0.5 * base["plane_subs"], (
        gwr["plane_subs"], base["plane_subs"])
    gws = gwr["gw_stats"]
    assert gws["verifies"] < gws["requests"], gws
    assert gwr["gateway_rows"] > 0, "gateway rows never rode its lane"
    n_requests = len(gwr["lats"])
    return {
        "metric": "cfg10_smoke light-client gateway",
        "value": round(n_requests / (gwr["wall_ms"] / 1000)),
        "unit": "headers/sec",
        "vs_baseline": None,
        "extra": {
            "clients": n_clients,
            "plane_subs_gateway": gwr["plane_subs"],
            "plane_subs_uncoalesced": base["plane_subs"],
            "verifies": gws["verifies"],
            "requests": gws["requests"],
            "cache_hits": gws["cache"]["hits"],
        },
    }


def smoke_sharded_layout(n_vals=300, n_strides=2):
    """cfg11's host-only miniature: the sharded fused LAYOUT math and
    the ledger's cross-chip attribution surfaces, with no jax in the
    process. shard_positions is the one home of the scatter formula
    (plan_fused and the per-shard tables both trust it), so the smoke
    brute-forces the bijection; a host-plane flush then proves the
    n_dev ledger column and shard summary the TPU-round cfg11 reads."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane
    from cometbft_tpu.verifyplane.fused import shard_positions
    from cometbft_tpu.verifyplane.plane import FlushLedger

    assert "n_dev" in FlushLedger.FIELDS
    m_s = 128  # a table_pad bucket; jax-free smoke pins it explicitly
    t = _now_ms()
    rng = np.random.RandomState(11)
    v = rng.randint(0, n_vals, size=512).astype(np.int64)
    s = rng.randint(0, n_strides, size=512).astype(np.int64)
    pos = shard_positions(v, s, m_s, n_strides)
    b_loc = n_strides * m_s
    # brute-force the layout contract: device owns v // m_s, local
    # column s*m_s + v % m_s — and distinct (v, s) never collide
    for vi, si, pi in zip(v, s, pos):
        assert pi == (vi // m_s) * b_loc + si * m_s + vi % m_s
    # injectivity: DISTINCT (v, s) pairs must never share a position
    # (a collision would silently overwrite one signature's rows)
    pairs = set(zip(v.tolist(), s.tolist()))
    by_pair = {(vi, si): pi for vi, si, pi in
               zip(v.tolist(), s.tolist(), pos.tolist())}
    assert len(set(by_pair.values())) == len(pairs)
    layout_ms = _now_ms() - t

    # ledger attribution on a host plane: single-device flushes stamp
    # n_dev=1, the shard summary exists and stays empty
    plane = VerifyPlane(window_ms=0.2, use_device=False)
    plane.start()
    try:
        kq = PrivKey.generate(b"\x13" * 32)
        fut = plane.submit(kq.pub_key(), b"cfg11-smoke",
                           kq.sign(b"cfg11-smoke"))
        assert fut.result(10) == (True,)
    finally:
        plane.stop()
    dump = plane.dump_flushes()
    recs = dump["flushes"]
    assert recs and all(r["n_dev"] == 1 for r in recs), recs
    shard = dump["summary"]["shard"]
    assert shard["flushes"] == 0 and shard["n_dev_max"] == 1
    assert plane.stats()["mesh_ndev"] == 0  # no mesh configured
    return {
        "metric": "cfg11_smoke sharded layout + ledger attribution",
        "value": round(layout_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "positions_checked": int(len(pos)),
            "shard_summary": shard,
            "ledger_n_dev": recs[-1]["n_dev"],
        },
    }


def smoke_pipelined_deck(n_sigs=24):
    """cfg12's host-only miniature: the flight-deck plumbing with no
    jax in the process — the ledger's airborne/n_host/dev0 columns and
    deck summary, the staging-pool depth wired to pipeline_flights,
    the out-of-order landing picker, and the [verify_plane] knob path
    into a live (host) plane."""
    from cometbft_tpu.config.config import Config
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import plane as vp

    for col in ("airborne", "n_dev", "n_host", "dev0"):
        assert col in vp.FlushLedger.FIELDS, col

    # the ready-first landing picker: a later flight whose probe says
    # ready lands FIRST (out-of-order — no head-of-line blocking);
    # with no probe (or none ready) callers fall back to FIFO
    class _F:
        def __init__(self, ready):
            self.ready = ready

    deck = [_F(lambda: False), _F(lambda: True), _F(lambda: False)]
    assert vp._ready_index(deck) == 1
    assert vp._ready_index([_F(None), _F(lambda: False)]) is None

    cfg = Config()
    cfg.verify_plane.enable = True
    cfg.verify_plane.pipeline_flights = 2
    cfg.verify_plane.half_mesh_rows = 512
    cfg.validate_basic()
    plane = cfg.verify_plane.build()
    assert plane.flights == 2 and plane.half_mesh_rows == 512
    # the multi-flight staging contract: flights+1 slots per shape so
    # pack(k+2) never lands in a buffer still pinned under flight k
    assert plane._staging.slots == 3
    plane.start()
    try:
        keys = [PrivKey.generate((9500 + i).to_bytes(4, "big")
                                 + b"\x21" * 28) for i in range(n_sigs)]
        t = _now_ms()
        futs = [plane.submit(k.pub_key(), b"deck-%d" % i,
                             k.sign(b"deck-%d" % i))
                for i, k in enumerate(keys)]
        verdicts = [f.result(10) for f in futs]
        wall_ms = _now_ms() - t
    finally:
        plane.stop()
    assert all(all(v) for v in verdicts), "valid sigs rejected"
    dump = plane.dump_flushes()
    recs = dump["flushes"]
    # host flushes are synchronous: never airborne, single host+device,
    # and the legacy overlapped bool derives from the airborne count
    assert recs and all(
        r["airborne"] == 0 and r["overlapped"] is False
        and r["n_host"] == 1 and r["dev0"] == 0 for r in recs), recs
    deck_sum = dump["summary"]["deck"]
    assert deck_sum == {"airborne_max": 0, "overlapped_flushes": 0}
    st = plane.stats()
    assert st["flights"] == 2 and st["deck_peak"] == 0
    assert st["halves"] == 0  # no mesh on a host plane
    return {
        "metric": "cfg12_smoke flight-deck plumbing",
        "value": round(wall_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "sigs": n_sigs,
            "staging_slots": plane._staging.slots,
            "deck_summary": deck_sum,
            "ledger_cols": [c for c in ("airborne", "n_dev", "n_host",
                                        "dev0")],
        },
    }


def smoke_churn_warmer(epochs=12):
    """cfg13's host-only miniature: epoch churn through the bounded
    valset-table caches and the next-epoch warmer, with no jax in the
    process — eviction pressure holds resident bytes flat, the live
    key never evicts, the warmer's failpoint degrade leaves the cold
    path intact, and a warmed lookup is attributed (warmed_hits)."""
    import hashlib

    from cometbft_tpu.libs import failpoints as fp
    from cometbft_tpu.ops import table_cache as tcache
    from cometbft_tpu.verifyplane import plane as vp
    from cometbft_tpu.verifyplane.warmer import TableWarmer

    assert "warm" in vp.FlushLedger.FIELDS  # the ledger's churn column

    class _Tbl:
        __slots__ = ("nbytes",)

        def __init__(self):
            self.nbytes = 4096

    cache = tcache.BoundedLRU("tables", 4, size_fn=tcache.default_size)
    live = b"live"
    cache.put(live, _Tbl())
    ev0 = tcache.STATS["evictions_tables"]
    peak = 0
    t = _now_ms()
    for e in range(epochs):
        assert cache.get(live) is not None, "live table evicted"
        cache.put(b"epoch-%d" % e, _Tbl())
        peak = max(peak, cache.resident_bytes())
    churn_ms = _now_ms() - t
    evictions = tcache.STATS["evictions_tables"] - ev0
    assert evictions == epochs - 3 and peak <= 4 * 4096

    # warmer plumbing: a failed build degrades (nothing inserted), a
    # clean build lands + attributes its first hit
    built = []

    def build(pubs, powers):
        key = hashlib.sha256(b"".join(pubs)).digest()
        with tcache.LOCK:
            tcache.TABLES.put(key, _Tbl())
        tcache.note_warmed(key)
        built.append(key)

    fp.registry().arm_from_spec("warmer.build=raise*1")
    w = TableWarmer(build_fn=build, use_device=False)
    w.start()
    try:
        w.request((b"epoch-f",), None)
        assert w.wait_idle(10.0)
        assert not built and w.stats()["builds_failed"] == 1
        hits0 = tcache.STATS["warmed_hits"]
        w.request((b"epoch-w",), None)
        assert w.wait_idle(10.0)
        assert len(built) == 1
        with tcache.LOCK:
            assert tcache.TABLES.get(built[0]) is not None
        assert tcache.consume_warmed(built[0])
        assert tcache.STATS["warmed_hits"] - hits0 == 1
    finally:
        w.stop()
        fp.reset()
    return {
        "metric": "cfg13_smoke churn cache + warmer plumbing",
        "value": round(churn_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "epochs": epochs,
            "evictions": evictions,
            "resident_bytes_peak": peak,
            "warmer": w.stats(),
        },
    }


def smoke_peer_ledger(n_msgs=512):
    """cfg14's host-only miniature: the gossip observatory end to end
    with no jax in the process — record shape over the FlushLedger
    discipline (the live scratch list becomes the drop-ring slot),
    per-channel split, vote first-seen/dup/relay routing, the
    starvation counters the peer_starvation incident watches, and the
    per-message bookkeeping budget."""
    from cometbft_tpu.p2p import peerledger

    led = peerledger.PeerLedger()
    rec = led.open_peer("smoke-peer", True)
    t = _now_ms()
    for i in range(n_msgs):
        peerledger.note_sent(rec, 0x22, 200)
        peerledger.note_recv(rec, 0x21, 100)
        peerledger.note_queue_depth(rec, i % 7)
    wall_ms = _now_ms() - t
    peerledger.note_full_drop(rec)
    peerledger.note_blocked_put(rec)
    led.note_vote_seen((1, 0, 2, 3), "smoke-peer")
    led.note_vote_seen((1, 0, 2, 3), "other")     # duplicate receipt
    led.note_vote_relayed((1, 0, 2, 3))
    route = led.vote_route(1, 0, 2, 3)
    assert route is not None and route[0] == "smoke-peer" \
        and route[1] == 1, route
    led.drop_peer(rec, "smoke_done")
    dump = led.dump()
    assert set(dump["peers"][0]) == set(peerledger.PeerLedger.FIELDS)
    p = dump["peers"][0]
    assert p["state"] == "dropped" and p["reason"] == "smoke_done"
    assert p["msgs_tx"] == n_msgs and p["bytes_tx"] == 200 * n_msgs
    assert p["chans"]["0x22"]["msgs_tx"] == n_msgs
    assert p["chans"]["0x21"]["msgs_rx"] == n_msgs
    assert p["q_hiwater"] == 6
    s = dump["summary"]
    assert s["full_drops"] == 1 and s["blocked_puts"] == 1
    assert s["votes"] == {"seen": 1, "dups": 1, "relayed": 1,
                          "tracked": 1, "dropped": 0}
    budget = peer_ledger_bookkeeping_us(k=2000)
    return {
        "metric": "cfg14_smoke peer ledger bookkeeping",
        "value": round(wall_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "msgs": n_msgs,
            "peer_path": budget,
            "summary": {k: s[k] for k in
                        ("msgs_tx", "msgs_rx", "full_drops",
                         "blocked_puts")},
        },
    }


def smoke_device_observatory(n_compiles=64):
    """cfg15's host-only miniature: the device observatory end to end
    with no jax in the process — compile-record shape over the
    attribution stack (site + flush seq + steady flag), the
    compile_storm incident trigger (burst fires, snapshot carries the
    compile tail), per-family/per-device residency math over fake
    tables with the exact-accounting split, and the per-flush hook
    budget."""
    from cometbft_tpu.libs import deviceledger, incidents

    led = deviceledger.CompileLedger()
    old_led = deviceledger.install(led)
    old_rec = incidents.install(incidents.IncidentRecorder(
        compile_storm=3, window_s=60.0, cooldown_s=0.0))
    try:
        t = _now_ms()
        for i in range(n_compiles):
            fr = deviceledger.attr_begin("smoke.flush", i)
            deviceledger.record_compile(0.002)
            deviceledger.attr_end(fr)
        wall_ms = _now_ms() - t
        recs = led.records()
        assert set(recs[0]) == set(deviceledger.CompileLedger.FIELDS)
        assert recs[0]["site"] == "smoke.flush"
        assert recs[0]["flush_seq"] == 0 and not recs[0]["steady"]
        c = led.counters()
        assert c["compiles"] == n_compiles
        assert c["steady_compiles"] == 0
        # steady-state recompiles: the round-5 class — a burst past
        # the threshold fires ONE compile_storm whose snapshot
        # carries the compile tail naming the sites
        led.mark_steady()
        with deviceledger.attr_context("smoke.storm", 99):
            for _ in range(3):
                deviceledger.record_compile(0.004)
        incidents.poke()   # anchor the window
        incidents.poke()   # evaluate it
        snaps = incidents.recorder().incidents()
        assert len(snaps) == 1, [s["trigger"] for s in snaps]
        assert snaps[0]["trigger"] == "compile_storm"
        assert any("smoke.storm" in ln and "STEADY" in ln
                   for ln in snaps[0]["device_tail"]), snaps[0]
        assert led.counters()["steady_compiles"] == 3

        # residency: fake tables through the same duck-typed split the
        # real sampler uses — bytes and slots land per device, exactly
        class _T:
            def __init__(self, nbytes, n_vals=0, m_shard=0, devs=None):
                self.nbytes = nbytes
                self.n_vals = n_vals
                self.m_shard = m_shard
                if devs is not None:
                    self.devs = devs

        fams = deviceledger.residency(
            tables=[_T(1000, n_vals=4096), _T(500, n_vals=2048)],
            shards=[_T(901, m_shard=2048, devs=[0, 1, 2, 3])])
        vt = fams["valset_tables"]
        assert vt[0]["bytes"] == 1500 and vt[0]["slots"] == 6144
        sh = fams["shard_tables"]
        assert sum(s["bytes"] for s in sh.values()) == 901  # exact
        assert sh[1]["slots"] == 2048
        head = deviceledger.headroom_rows(fams)
        assert head[0] == deviceledger.HBM_SLOT_BUDGET - 6144 - 2048
        assert head[3] == deviceledger.HBM_SLOT_BUDGET - 2048
        budget = device_ledger_bookkeeping_us(k=2000)
        return {
            "metric": "cfg15_smoke device observatory",
            "value": round(wall_ms, 3),
            "unit": "ms",
            "vs_baseline": None,
            "extra": {
                "compiles": n_compiles,
                "storm_fired": snaps[0]["trigger"],
                "flush_hooks": budget,
                "headroom_dev0": head[0],
            },
        }
    finally:
        deviceledger.install(old_led)
        incidents.install(old_rec)


def _cfg15_host_machinery():
    """cfg15 on a no-accelerator host: the observatory MACHINERY at
    host speed (nothing compiles here — the real compile/residency
    numbers come from the TPU round; clearly labeled)."""
    budget = device_ledger_bookkeeping_us()
    return {
        "metric": "cfg15 device observatory (host-only MACHINERY run)",
        "value": budget["flush_hook_us_per_flush"],
        "unit": "us",
        "vs_baseline": None,
        "extra": {
            "host_only": True,
            "flush_hooks": budget,
            "note": "no accelerator: per-flush hook budget only; "
                    "compile/residency/headroom numbers need the TPU "
                    "round",
        },
    }


def cfg15_device(n_vals=1024, steady_reps=5):
    """#15: the device observatory on the REAL device path — cold
    compile attribution, zero steady-state recompiles (the r05/round-5
    guard, asserted), HBM residency + headroom, and the
    exact-accounting cross-check, all read from the same module core
    /dump_devices serves.

    Runs LAST in the full set, by which point the plane has long since
    declared the process steady — so the config installs its OWN fresh
    compile ledger (the jax listener writes through the module global)
    and measures cold-vs-steady as this config's delta, not the whole
    run's. The fresh ledger's dump is what gets embedded for
    device_report; the process ledger is restored on exit and keeps
    accumulating the run-wide truth."""
    import jax

    from cometbft_tpu.libs import deviceledger

    if jax.default_backend() == "cpu":
        return _cfg15_host_machinery()
    import numpy as np

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek

    deviceledger.arm_compile_listener()
    privs = [PrivKey.generate(i.to_bytes(32, "big"))
             for i in range(1, n_vals + 1)]
    pubs = [p.pub_key().data for p in privs]
    msgs = [b"cfg15-%d" % i for i in range(n_vals)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    led = deviceledger.CompileLedger()
    old_led = deviceledger.install(led)
    try:
        with deviceledger.attr_context("cfg15.cold"):
            table = ec.table_for_pubs(tuple(pubs))
            m_pad = ec.table_pad(n_vals)
            pb = ek.pack_batch(pubs, msgs, sigs, pad_to=m_pad)
            rows = ec.pack_rows_cached(
                pb, np.zeros((m_pad,), np.bool_),
                np.zeros((m_pad,), np.int32),
                np.zeros((1, ek.TALLY_LIMBS), np.int32))
            out = ec.verify_tally_rows_cached(jax.device_put(rows),
                                              table, 1)
            assert bool(np.asarray(out[0])[:n_vals].all())
        cold = led.counters()
        led.mark_steady()
        t = _now_ms()
        with deviceledger.attr_context("cfg15.steady"):
            for _ in range(steady_reps):
                out = ec.verify_tally_rows_cached(
                    jax.device_put(rows), table, 1)
            np.asarray(out[0])
        steady_ms = (_now_ms() - t) / steady_reps
        after = led.counters()
        steady_compiles = after["steady_compiles"]
        # THE acceptance: a steady-state verify stream recompiles
        # nothing (the round-5 class the compile_storm trigger
        # watches) — measured on THIS config's fresh ledger, so the
        # earlier configs' compiles can't pollute it either way
        assert steady_compiles == 0, \
            f"steady-state recompiled {steady_compiles}x"
        fams = deviceledger.residency()
        rec = deviceledger.reconcile(fams)
        assert rec["table_drift"] == 0, rec
        head = deviceledger.headroom_rows(fams)
        dump = deviceledger.dump_devices()
        dump["compiles"] = dump["compiles"][-32:]
        return {
            "metric": "cfg15 device observatory steady verify",
            "value": round(steady_ms, 2),
            "unit": "ms",
            "vs_baseline": None,
            "extra": {
                "cold_compiles": cold["compiles"],
                "cold_compile_s": round(cold["compile_s"], 3),
                "pcache_hits": after["pcache_hits"],
                "steady_compiles": steady_compiles,
                "resident_bytes": {
                    fam: sum(s["bytes"] for s in devs.values())
                    for fam, devs in fams.items()},
                "headroom_rows_min": min(head.values()) if head
                else None,
                "reconcile": rec,
                "compile_sites": [r["site"]
                                  for r in led.records()[-8:]],
                # the config's own dump (compile ring trimmed) so
                # tools/device_report.py can read this --json-out
                # file directly and --diff it against the next
                # round's; extra.jax_compile reads ~0 for cfg15 by
                # design (its compiles land on this private ledger)
                "device_dump": dump,
            },
        }
    finally:
        deviceledger.install(old_led)


def _controller_closed_loop(n_cycles, peak_evals, trough_evals):
    """Shared cfg16 driver: a real host-path VerifyPlane + real
    AdmissionController as ACTUATORS, a synthetic commit-latency
    sensor as the pressure input, cycled peak -> trough. Returns
    (wall_ms, evals, ctl_dump, checks)."""
    from cometbft_tpu.libs import controller as controlplane
    from cometbft_tpu.mempool.admission import AdmissionController
    from cometbft_tpu.verifyplane.plane import VerifyPlane

    class _Sensor:
        p99 = 0.0

        def __len__(self):
            return 1

        def summary(self):
            return {"commit_latency_ms": {"p99": self.p99}}

    fill = {"v": 0.1}
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    adm = AdmissionController(high_watermark=0.9, low_watermark=0.7,
                              fill_fn=lambda: fill["v"])
    sensor = _Sensor()
    ctl = controlplane.Controller(slo_commit_p99_ms=100.0,
                                  decision_interval=1, cooldown=0)
    try:
        ctl.attach(plane=plane, admission=adm, height_ledger=sensor,
                   bounds={
                       controlplane.ACT_BULK_WINDOW: (1.0, 8.0),
                       controlplane.ACT_GATEWAY_WINDOW: (0.5, 4.0),
                       controlplane.ACT_ADMISSION: (0.3, 0.9),
                   })
        consensus_window = plane.window
        base_bulk = plane.bulk_window
        height, evals = 0, 0
        t = _now_ms()
        for _ in range(n_cycles):
            sensor.p99, fill["v"] = 500.0, 0.8   # peak: 5x over SLO
            for _ in range(peak_evals):
                height += 1
                ctl.poke(height, 0)
            tightened = (plane.bulk_window > base_bulk
                         and adm.high_watermark < 0.9)
            sensor.p99, fill["v"] = 10.0, 0.1    # trough: headroom
            for _ in range(trough_evals):
                height += 1
                ctl.poke(height, 0)
            evals += peak_evals + trough_evals
        wall_ms = _now_ms() - t
        dump = ctl.dump()
        checks = {
            "tightened_at_peak": tightened,
            "relaxed_to_base": (
                abs(plane.bulk_window - base_bulk) < 1e-9
                and adm.high_watermark == 0.9),
            "consensus_untouched": plane.window == consensus_window,
            "all_within_bounds": all(
                a["min"] - 1e-9 <= d["new"] <= a["max"] + 1e-9
                for d in dump["decisions"]
                for a in (dump["actuators"][d["actuator"]],)),
        }
        return wall_ms, evals, dump, checks
    finally:
        controlplane.clear_global_controller(ctl)
        plane.stop()


def smoke_controller(n_cycles=3):
    """cfg16's host-only miniature: the closed loop end to end with no
    jax in the process — tighten BEFORE the static config would shed
    (windows widen, watermark drops on the pressure latch), relax back
    to the configured base at the trough, clamp bounds honored on
    every decision, the CONSENSUS lane untouched by construction, and
    the decision dump embedded so tools/controller_report.py reads
    this --json-out file directly."""
    wall_ms, evals, dump, checks = _controller_closed_loop(
        n_cycles, peak_evals=8, trough_evals=16)
    assert all(checks.values()), checks
    assert dump["state"]["decisions_total"] >= 2 * n_cycles
    return {
        "metric": "cfg16_smoke closed-loop controller",
        "value": round(wall_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "evals": evals,
            "decisions_total": dump["state"]["decisions_total"],
            "checks": checks,
            "controller_dump": dump,
        },
    }


def cfg16_controller(n_cycles=50):
    """#16: the self-tuning control plane at sustained cadence. The
    loop is host-side BY DESIGN (decisions ride consensus step
    transitions; nothing in the decision path may touch the device),
    so this config measures what production pays: per-eval overhead on
    the step-transition seam across many peak/trough cycles, plus the
    same closed-loop invariants as the smoke (tighten at peak, relax
    to base, clamps, consensus untouched). The embedded dump is the
    --diff input for tools/controller_report.py across rounds."""
    wall_ms, evals, dump, checks = _controller_closed_loop(
        n_cycles, peak_evals=8, trough_evals=16)
    assert all(checks.values()), checks
    dump["decisions"] = dump["decisions"][-64:]
    return {
        "metric": "cfg16 controller eval overhead",
        "value": round(wall_ms * 1000.0 / max(1, evals), 3),
        "unit": "us",
        "vs_baseline": None,
        "extra": {
            "evals": evals,
            "decisions_total": dump["state"]["decisions_total"],
            "wall_ms": round(wall_ms, 3),
            "checks": checks,
            "controller_dump": dump,
        },
    }


def _tenant_pod(k_chains, rounds, rows_per_sub):
    """Shared cfg17 driver: the SAME K-chain ed25519 verify workload
    run two ways — K chains sharing ONE multi-tenant plane (per-round
    submissions from every chain coalesce into fused flushes with
    per-tenant ledger attribution) vs one plane per chain (the
    pod-per-chain status quo this subsystem replaces). Returns
    (shared_ms, split_ms, checks, figures)."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane.plane import LANE_BULK, VerifyPlane

    chains = [f"bench-{i}" for i in range(k_chains)]
    rows = {}
    for i, chain in enumerate(chains):
        msg = b"cfg17:" + chain.encode()
        rows[chain] = []
        for j in range(rows_per_sub):
            priv = PrivKey.generate(bytes([200 + i, j]) + b"\x33" * 30)
            rows[chain].append((priv.pub_key(), msg, priv.sign(msg)))

    def drive(plane_of):
        verdicts = []
        t = _now_ms()
        for _ in range(rounds):
            futs = [plane_of(c).submit_many(
                        list(rows[c]), lane=LANE_BULK, chain_id=c)
                    for c in chains]
            verdicts.append(tuple(f.result(30.0) for f in futs))
        return _now_ms() - t, verdicts

    shared = VerifyPlane(window_ms=0.5, use_device=False)
    shared.start()
    try:
        shared_ms, v_shared = drive(lambda c: shared)
        summary = shared.ledger.summary()
        recs = shared.ledger.records()
        dump = shared.tenants.dump()
        flushes_shared = len(recs)
    finally:
        shared.stop()

    split = {c: VerifyPlane(window_ms=0.5, use_device=False)
             for c in chains}
    for p in split.values():
        p.start()
    try:
        split_ms, v_split = drive(lambda c: split[c])
        flushes_split = sum(len(p.ledger.records())
                            for p in split.values())
    finally:
        for p in split.values():
            p.stop()

    total_rows = k_chains * rounds * rows_per_sub
    checks = {
        # sharing the plane changes the economics, never the verdicts
        "verdicts_identical": v_shared == v_split,
        "all_verified": all(all(v) for r in v_shared for v in r),
        # the ledger's per-tenant attribution sums to each flush total
        "attribution_sums": all(
            sum(n for _, n in r["tenants"]) == r["rows"]
            for r in recs),
        # the whole point: multi-chain rows landed in FUSED flushes
        "coalesced": summary.get("coalesced_flushes", 0) >= 1,
        "every_tenant_accounted": all(
            dump["tenants"][c]["rows"] == rounds * rows_per_sub
            for c in chains),
    }
    figures = {
        "k_chains": k_chains,
        "rows_total": total_rows,
        "flushes_shared": flushes_shared,
        "flushes_split": flushes_split,
        "coalesced_flushes": summary.get("coalesced_flushes", 0),
        "split_ms": round(split_ms, 3),
        "speedup_vs_split": round(split_ms / max(shared_ms, 1e-9), 3),
        "tenants_dump": dump,
    }
    return shared_ms, split_ms, checks, figures


def smoke_tenants(k_chains=2, rounds=3, rows_per_sub=4):
    """cfg17's host-only miniature: two chains on one plane with no
    jax in the process — identical verdicts to the per-chain-plane
    arm, fused cross-tenant flushes on the ledger, attribution sums
    exact, and the tenants_dump embedded so tools/tenant_report.py
    reads this --json-out file directly."""
    shared_ms, _, checks, figures = _tenant_pod(
        k_chains, rounds, rows_per_sub)
    assert all(checks.values()), checks
    return {
        "metric": "cfg17_smoke multi-tenant pod",
        "value": round(shared_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "extra": dict(figures, checks=checks),
    }


def cfg17_tenants(k_chains=8, rounds=12, rows_per_sub=16):
    """#17: the multi-tenant verify plane at pod scale — K chains'
    BULK verify traffic through ONE plane vs K per-chain planes over
    the same signed rows. The shared arm's flush count collapses
    (cross-tenant coalescing: one drain cycle serves many chains) and
    its throughput is the headline figure; verdicts must match the
    split arm bit-for-bit. The embedded tenants_dump is the --diff
    input for tools/tenant_report.py across rounds."""
    shared_ms, split_ms, checks, figures = _tenant_pod(
        k_chains, rounds, rows_per_sub)
    assert all(checks.values()), checks
    total_rows = figures["rows_total"]
    return {
        "metric": "cfg17 shared-plane verify throughput",
        "value": round(total_rows / max(shared_ms, 1e-9) * 1000.0, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "extra": dict(figures, checks=checks,
                      shared_ms=round(shared_ms, 3)),
    }


def _catchup_history(n_blocks, n_vals=3, epoch_len=0,
                     chain_id="cfg18-chain"):
    """A real ed25519-signed history: per-epoch valsets (rotated every
    ``epoch_len`` blocks when set), real Block objects whose
    block_id()s the commits actually sign. Returns (items, vals_at)
    with items = {h: (block, commit)} and vals_at(h) the valset that
    signs block h."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block import Block, Data, Header
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    n_epochs = (n_blocks // epoch_len + 2) if epoch_len else 1
    epochs = []
    for e in range(n_epochs):
        privs = [PrivKey.generate(bytes([40 + e, i + 1]) + b"\x18" * 30)
                 for i in range(n_vals)]
        vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
        by_addr = {p.pub_key().address(): p for p in privs}
        epochs.append((vs, by_addr))

    def vals_at(h):
        e = (h - 1) // epoch_len if epoch_len else 0
        return epochs[min(e, n_epochs - 1)][0]

    items = {}
    last_bid = None
    for h in range(1, n_blocks + 1):
        vs, by_addr = epochs[min((h - 1) // epoch_len
                                 if epoch_len else 0, n_epochs - 1)]
        hdr = Header(
            chain_id=chain_id, height=h,
            time=Timestamp(1700000000 + h, 0),
            validators_hash=vs.hash(),
            next_validators_hash=vals_at(h + 1).hash(),
            proposer_address=vs.validators[0].address,
        )
        if last_bid is not None:
            hdr.last_block_id = last_bid
        blk = Block(hdr, Data())
        blk.fill_header()
        bid = blk.block_id()
        sigs = []
        for v in vs.validators:
            ts = Timestamp(1700000000 + h, 1)
            sb = canonical.canonical_vote_bytes(
                chain_id, canonical.PRECOMMIT_TYPE, h, 0, bid, ts)
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        items[h] = (blk, Commit(h, 0, bid, sigs))
        last_bid = bid
    return items, vals_at


class _HistorySource:
    """In-memory history source for the catch-up bench drivers."""

    def __init__(self, items):
        self.items = items

    def base(self):
        return min(self.items)

    def tip(self):
        return max(self.items)

    def load(self, h):
        return self.items[h]


class _ReplayState:
    """The slice of State the catch-up engine reads, without dragging
    the execution stack into a bench driver."""

    __slots__ = ("chain_id", "last_block_height", "validators",
                 "next_validators")

    def __init__(self, chain_id, h, validators, next_validators):
        self.chain_id = chain_id
        self.last_block_height = h
        self.validators = validators
        self.next_validators = next_validators


class _RecordingWarmer:
    def __init__(self):
        self.requests = []

    def request_valset(self, vals, chain_id=None):
        self.requests.append((vals.hash(), chain_id))


def _catchup_drive(items, vals_at, *, verifier, cursor_path,
                   read_ahead=128, max_run=64, kill_at_read=0,
                   warm_ahead=True, start_height=0):
    """Run one CatchupEngine pass over an in-memory history. Returns
    (engine, wall_ms, crashed) — with ``kill_at_read`` > 0 the
    catchup.read_ahead failpoint raises at that read and the partial
    run returns crashed=True (the persisted cursor is the evidence)."""
    from cometbft_tpu.blocksync.catchup import CatchupEngine
    from cometbft_tpu.libs import failpoints as fp

    chain_id = getattr(items[min(items)][0].header, "chain_id",
                       "cfg18-chain")
    state = _ReplayState(chain_id, start_height,
                         vals_at(start_height + 1),
                         vals_at(start_height + 2))

    def apply_fn(st, blk, commit):
        h = blk.header.height
        return _ReplayState(st.chain_id, h, vals_at(h + 1),
                            vals_at(h + 2))

    warmer = _RecordingWarmer()
    eng = CatchupEngine(
        _HistorySource(items), state, apply_fn=apply_fn,
        verifier=verifier, cursor_path=cursor_path,
        read_ahead=read_ahead, max_run=max_run,
        warm_ahead=warm_ahead, warmer=warmer)
    crashed = False
    if kill_at_read:
        # flake fires on the Nth evaluation: a kill mid-replay, with
        # whatever the cursor persisted by then as the resume point
        fp.arm("catchup.read_ahead", "flake", kill_at_read, count=1)
    t = _now_ms()
    try:
        eng.run()
    except fp.FailpointError:
        crashed = True
    finally:
        fp.disarm("catchup.read_ahead")
    return eng, _now_ms() - t, crashed


def smoke_catchup(n_blocks=12, n_vals=3, epoch_len=5):
    """cfg18's host-only miniature: a real ed25519-signed history
    replayed through the catch-up firehose with the jax-free host
    verifier — fused cross-height segments bounded at REAL valset
    boundaries, warm-ahead requests fired before each boundary, then a
    mid-replay kill + resume from the persisted cursor re-verifying
    ZERO already-verified blocks. The catchup_dump is embedded so
    tools/catchup_report.py reads this --json-out file directly."""
    import tempfile

    from cometbft_tpu.blocksync.catchup import HostCommitVerifier

    items, vals_at = _catchup_history(n_blocks, n_vals, epoch_len)
    with tempfile.TemporaryDirectory() as td:
        cursor = os.path.join(td, "cursor.json")
        # phase 1: kill at the 8th read-ahead read
        eng1, _, crashed = _catchup_drive(
            items, vals_at, verifier=HostCommitVerifier(),
            cursor_path=cursor, read_ahead=4, max_run=4,
            kill_at_read=8)
        assert crashed and eng1.cursor.applied >= 1
        verified_at_crash = eng1.cursor.verified

        # phase 2: resume from the persisted cursor + applied state
        class _CountingVerifier(HostCommitVerifier):
            def __init__(self):
                self.heights = []

            def verify(self, jobs):
                self.heights.extend(j.height for j in jobs)
                return super().verify(jobs)

        v2 = _CountingVerifier()
        eng2, wall_ms, crashed2 = _catchup_drive(
            items, vals_at, verifier=v2, cursor_path=cursor,
            read_ahead=4, max_run=4,
            start_height=eng1.cursor.applied)
        reverified = [h for h in v2.heights if h <= verified_at_crash]
        checks = {
            "resumed_clean": not crashed2,
            "caught_up": eng2.state.last_block_height == n_blocks,
            "zero_reverified": not reverified,
            "cursor_resumed": eng2.cursor.resumed,
            "boundaries_found": eng2.ledger.counters["boundaries"]
            + eng1.ledger.counters["boundaries"] >= 1,
            "warm_ahead_fired": eng2.ledger.counters["warm_requests"]
            + eng1.ledger.counters["warm_requests"] >= 1,
        }
        assert all(checks.values()), checks
        from cometbft_tpu.blocksync import catchup as catchup_mod

        dump = catchup_mod.dump_catchup()
        return {
            "metric": "cfg18_smoke catch-up firehose",
            "value": round(wall_ms, 3),
            "unit": "ms",
            "vs_baseline": None,
            "extra": {
                "blocks": n_blocks,
                "verified_at_crash": verified_at_crash,
                "reverified_after_resume": len(reverified),
                "checks": checks,
                "catchup_dump": dump,
            },
        }


def _cfg18_machinery(n_blocks=100_000, epoch_len=10_000, max_run=64):
    """The ≥100k-block synthetic replay: stub crypto (the engine
    MACHINERY is the thing under test — read-ahead, segmentation,
    cursor persistence, ledger accounting — not the host's ed25519
    throughput), with a mid-replay kill + resume proving zero
    re-verification at scale."""
    import tempfile

    class _FakeVals:
        __slots__ = ("tag",)

        def __init__(self, tag):
            self.tag = tag

        def hash(self):
            return self.tag

    class _FakeHeader:
        __slots__ = ("validators_hash", "height")

        def __init__(self, vh, h):
            self.validators_hash = vh
            self.height = h

    class _FakeBlock:
        __slots__ = ("header", "_bid")

        def __init__(self, hdr):
            self.header = hdr
            self._bid = ("bid", hdr.height)

        def block_id(self):
            return self._bid

    class _FakeSig:
        __slots__ = ()
        signature = b"\x01"

    class _FakeCommit:
        __slots__ = ("signatures",)

        def __init__(self, sigs):
            self.signatures = sigs

    class _StubVerifier:
        def __init__(self):
            self.heights = []

        def verify(self, jobs):
            self.heights.extend(j.height for j in jobs)
            return [None] * len(jobs)

    n_epochs = n_blocks // epoch_len + 2
    epoch_vals = [_FakeVals(b"epoch-%d" % e) for e in range(n_epochs)]

    def vals_at(h):
        return epoch_vals[min((h - 1) // epoch_len, n_epochs - 1)]

    shared_sigs = tuple(_FakeSig() for _ in range(4))
    items = {h: (_FakeBlock(_FakeHeader(vals_at(h).hash(), h)),
                 _FakeCommit(shared_sigs))
             for h in range(1, n_blocks + 1)}

    with tempfile.TemporaryDirectory() as td:
        cursor = os.path.join(td, "cursor.json")
        v1 = _StubVerifier()
        eng1, _, crashed = _catchup_drive(
            items, vals_at, verifier=v1, cursor_path=cursor,
            max_run=max_run, kill_at_read=n_blocks // 2)
        assert crashed, "mid-replay kill did not fire"
        verified_at_crash = eng1.cursor.verified
        v2 = _StubVerifier()
        eng2, wall_ms, crashed2 = _catchup_drive(
            items, vals_at, verifier=v2, cursor_path=cursor,
            max_run=max_run, start_height=eng1.cursor.applied)
        reverified = sum(1 for h in v2.heights
                         if h <= verified_at_crash)
        resumed_blocks = n_blocks - eng1.cursor.applied
        checks = {
            "caught_up": eng2.state.last_block_height == n_blocks,
            "resumed_clean": not crashed2,
            "zero_reverified": reverified == 0,
            # boundary crossings left after the resume point: epoch
            # walls at k*epoch_len strictly below the tip
            "every_boundary_found":
                eng2.ledger.counters["boundaries"]
                == (n_blocks - 1) // epoch_len
                - eng1.cursor.applied // epoch_len,
            "warm_ahead_per_boundary":
                eng2.ledger.counters["warm_requests"]
                >= eng2.ledger.counters["boundaries"],
        }
        assert all(checks.values()), checks
        summary = eng2.ledger.summary()
        return {
            "blocks": n_blocks,
            "epoch_len": epoch_len,
            "resumed_blocks": resumed_blocks,
            "verified_at_crash": verified_at_crash,
            "reverified_after_resume": reverified,
            "wall_ms": round(wall_ms, 3),
            "blocks_per_s": round(
                resumed_blocks / max(wall_ms, 1e-9) * 1000.0, 1),
            "flushes": eng2.ledger.counters["flushes"],
            "boundaries": eng2.ledger.counters["boundaries"],
            "warm_requests": eng2.ledger.counters["warm_requests"],
            "checks": checks,
            "summary": summary,
        }


def _cfg18_host_machinery():
    """cfg18 on a no-accelerator host: the firehose MACHINERY over the
    full 100k-block synthetic history at host speed (no real sig
    throughput here — that number needs the TPU round; clearly
    labeled)."""
    figs = _cfg18_machinery()
    from cometbft_tpu.blocksync import catchup as catchup_mod

    return {
        "metric": "cfg18 catch-up firehose (host-only MACHINERY run)",
        "value": figs["blocks_per_s"],
        "unit": "blocks/s",
        "vs_baseline": None,
        "extra": {
            "host_only": True,
            "machinery": {k: v for k, v in figs.items()
                          if k != "summary"},
            "catchup_dump": catchup_mod.dump_catchup(),
            "note": "no accelerator: engine machinery blocks/s over a "
                    "100k-block synthetic history with stub crypto; "
                    "real sigs/s needs the TPU round",
        },
    }


def cfg18_catchup(n_blocks=768, n_vals=64, epoch_len=256):
    """#18: the archival catch-up firehose. Host machinery figures ride
    a 100k-block synthetic replay (kill mid-replay, resume, ZERO
    re-verified); on a real accelerator the same engine replays a
    real-signed multi-epoch history through the fused device pipeline
    twice — COLD (no warm-ahead: every valset boundary pays its table
    build inside the verify path) vs WARMED (epoch tables built ahead
    of the replay cursor) — and the headline is warmed sigs/s."""
    import tempfile

    import jax

    if jax.default_backend() == "cpu":
        return _cfg18_host_machinery()

    from cometbft_tpu.blocksync.pipeline import make_stream_verifier
    from cometbft_tpu.verifyplane.warmer import TableWarmer

    machinery = _cfg18_machinery()
    items, vals_at = _catchup_history(n_blocks, n_vals, epoch_len)
    total_sigs = n_blocks * n_vals

    def run(warm_ahead):
        with tempfile.TemporaryDirectory() as td:
            from cometbft_tpu.blocksync.catchup import CatchupEngine

            state = _ReplayState("cfg18-chain", 0, vals_at(1),
                                 vals_at(2))

            def apply_fn(st, blk, commit):
                h = blk.header.height
                return _ReplayState(st.chain_id, h, vals_at(h + 1),
                                    vals_at(h + 2))

            warmer = TableWarmer()
            warmer.start()
            try:
                eng = CatchupEngine(
                    _HistorySource(items), state, apply_fn=apply_fn,
                    verifier=make_stream_verifier(),
                    cursor_path=os.path.join(td, "cursor.json"),
                    warm_ahead=warm_ahead, warmer=warmer)
                t = _now_ms()
                eng.run()
                wall_ms = _now_ms() - t
                return wall_ms, eng.ledger
            finally:
                warmer.stop()

    cold_ms, _ = run(warm_ahead=False)
    warm_ms, led = run(warm_ahead=True)
    boundary_recs = [r for r in led.records() if r["boundary"]]
    return {
        "metric": "cfg18 catch-up firehose warmed replay",
        "value": round(total_sigs / max(warm_ms, 1e-9) * 1000.0, 1),
        "unit": "sigs/s",
        "vs_baseline": None,
        "extra": {
            "blocks": n_blocks,
            "sigs": total_sigs,
            "cold_ms": round(cold_ms, 3),
            "warm_ms": round(warm_ms, 3),
            "cold_vs_warm_speedup": round(
                cold_ms / max(warm_ms, 1e-9), 3),
            "boundaries": led.counters["boundaries"],
            "warm_requests": led.counters["warm_requests"],
            "boundary_verify_ms": [r["verify_ms"]
                                   for r in boundary_recs],
            "machinery": {k: v for k, v in machinery.items()
                          if k != "summary"},
        },
    }


def smoke_device_stamp(n_rows=10_000):
    """cfg19's host-only miniature (no jax): the delta extraction that
    feeds device stamping, proven byte-equal to the host packer across
    fuzzed varint widths, plus the staged-bytes budget (delta slots vs
    full-row slots at the 10k-row flush shape — the ISSUE 19 >=4x
    acceptance line) and the flush ledger's stamp/delta_bytes
    attribution."""
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.verifyplane import fused as fz
    from cometbft_tpu.verifyplane.plane import (
        STAMP_DEVICE,
        STAMP_HOST,
        FlushLedger,
    )

    bid = BlockID(b"\x19" * 32, PartSetHeader(1, b"\x91" * 32))
    tpl = canonical.VoteRowTemplate(
        CHAIN_ID, canonical.PRECOMMIT_TYPE, 4242, 1, bid)
    # every varint width boundary, zero-skip, and negative (64-bit
    # two's complement) case, then a deterministic bulk fill
    edge_s = [0, 1, 127, 128, 16383, 16384, 1_700_000_000,
              2 ** 31 - 1, 2 ** 31, 2 ** 40, 2 ** 62, -1, -2 ** 33]
    edge_n = [0, 1, 127, 128, 999_999_999, 5, 42, 999, 7, 0, 1, 0, 3]
    secs = np.resize(np.array(edge_s, np.int64), n_rows)
    secs[len(edge_s):] = 1_700_000_000 + np.arange(
        n_rows - len(edge_s), dtype=np.int64)
    nanos = np.resize(np.array(edge_n, np.int64), n_rows)
    nanos[len(edge_n):] = np.arange(n_rows - len(edge_n),
                                    dtype=np.int64) % 1_000_000_000
    t = _now_ms()
    dr = tpl.delta_rows(secs, nanos)
    got = dr.expand()
    expand_ms = _now_ms() - t
    t = _now_ms()
    ref = tpl.patch_rows(secs, nanos)
    patch_ms = _now_ms() - t
    assert dr.stampable()
    assert all(got.row(i) == ref.row(i) for i in range(n_rows)), (
        "delta expansion diverged from patch_rows")

    # staged bytes per flush at the 10k-row bucket: what the delta
    # path puts on the bus vs the full-row pack (pure slot-spec
    # arithmetic — the same shapes plan_fused stages)
    B = 10240
    delta_b = fz.specs_bytes(fz.delta_slot_specs(B))
    legacy_b = fz.specs_bytes(fz.legacy_slot_specs(B))
    ratio = legacy_b / delta_b
    assert ratio >= 4.0, (legacy_b, delta_b, ratio)

    # ledger attribution: stamp + delta_bytes are first-class FIELDS
    # (built from FIELDS so this can't drift from the plane)
    assert "stamp" in FlushLedger.FIELDS
    assert "delta_bytes" in FlushLedger.FIELDS
    led = FlushLedger()

    def rec(seq, stamp, dbytes):
        base = {f: 0 for f in FlushLedger.FIELDS}
        base.update(seq=seq, ts_ms=0.0, rows=B, subs=1, path="fused",
                    stamp=stamp, breaker="closed",
                    delta_bytes=dbytes, tenants=())
        return [base[f] for f in FlushLedger.FIELDS] + [0, 0, 0, 0]

    led.record(rec(1, STAMP_DEVICE, delta_b))
    led.record(rec(2, STAMP_HOST, 0))
    s = led.summary()
    assert s["stamp"]["device"] == 1 and s["stamp"]["host"] == 1, s
    assert s["stamp"]["delta_bytes"] == delta_b, s
    return {
        "metric": "cfg19_smoke delta staging shrink",
        "value": round(ratio, 2),
        "unit": "x",
        "vs_baseline": None,
        "extra": {
            "rows": n_rows,
            "byte_equality": True,
            "staged_bytes_delta": delta_b,
            "staged_bytes_legacy": legacy_b,
            "delta_bytes_per_row": round(delta_b / B, 1),
            "legacy_bytes_per_row": round(legacy_b / B, 1),
            "expand_ms": round(expand_ms, 3),
            "patch_ms": round(patch_ms, 3),
            "ledger_stamp": s["stamp"],
        },
    }


def cfg19_device_stamp(n_vals=2048, reps=5, n_flushes=12):
    """#19: device-side sign-bytes stamping through the REAL plane
    dispatcher — delta-staged flushes (template resident, 80 B/row on
    the bus) vs the legacy full-row pack, same rows, verdicts
    bit-equal. The headline is the stamped arm's sigs/s; the ledger's
    h2d_ms / pack_ms / delta_bytes deltas are the mechanism evidence.
    Degrades honestly on hosts without an accelerator (host path
    never stamps — the slot-spec byte budget still reports)."""
    import jax

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.verifyplane import QuorumGroup, VerifyPlane
    from cometbft_tpu.verifyplane import fused as fz

    host_only = jax.default_backend() == "cpu" \
        and not fz.ALLOW_CPU_FUSED
    if host_only:
        n_vals, reps, n_flushes = 16, 2, 2
    n_rows = n_vals * reps
    keys = [PrivKey.generate((9900 + i).to_bytes(4, "big") + b"\x66" * 28)
            for i in range(n_vals)]
    pubs_t = tuple(k.pub_key().data for k in keys)
    powers_t = tuple(100 for _ in range(n_vals))
    bid = BlockID(b"\x19" * 32, PartSetHeader(1, b"\x92" * 32))
    tpl = canonical.VoteRowTemplate(
        CHAIN_ID, canonical.PRECOMMIT_TYPE, 1919, 0, bid)
    rows_all, vidx_all, stamp_all = [], [], []
    for r in range(reps):
        secs = 1_700_000_000 + r
        sr = tpl.patch_rows(
            np.full(n_vals, secs, np.int64),
            np.arange(n_vals, dtype=np.int64) + r)
        for i, k in enumerate(keys):
            msg = sr.row(i)
            rows_all.append((k.pub_key(), msg, k.sign(msg)))
            vidx_all.append(i)
            stamp_all.append((tpl, secs, i + r))

    def run(stamped):
        fz.set_device_stamping(stamped)
        plane = VerifyPlane(
            window_ms=0.5, max_batch=n_rows,
            max_queue=n_rows * (n_flushes + 2),
            use_device=None if host_only else True,
            mesh_devices=0, mesh_min_rows=1)
        plane.start()
        try:
            def burst(k):
                futs = [plane.submit_many(
                    rows_all, group=QuorumGroup(
                        10 ** 15, valset_pubs=pubs_t,
                        valset_powers=powers_t),
                    vidx=vidx_all, stamp=stamp_all)
                    for _ in range(k)]
                return [f.result(300.0) for f in futs]

            burst(2)  # warm: compile + template/table residency
            t = _now_ms()
            verd = burst(n_flushes)
            wall = _now_ms() - t
        finally:
            plane.stop()
            fz.set_device_stamping(True)
        dump = plane.dump_flushes()
        recs = [r for r in dump["flushes"]
                if r["path"].startswith("fused")][-n_flushes:]
        return wall, verd, dump["summary"], recs

    wall_s, verd_s, sum_s, recs_s = run(True)
    wall_l, verd_l, sum_l, recs_l = run(False)
    assert verd_s == verd_l, "stamped arm verdicts diverged"

    def med(recs, field):
        return round(float(np.median([r[field] for r in recs])), 3) \
            if recs else None

    stamped_recs = [r for r in recs_s if r["stamp"] == "device"]
    sps = n_rows * n_flushes / (wall_s / 1000) if wall_s else 0.0
    return {
        "metric": "cfg19 device-stamped flush throughput",
        "value": round(sps),
        "unit": "sigs/sec",
        "vs_baseline": round(wall_l / wall_s, 2) if wall_s else None,
        "extra": {
            "host_only": host_only,
            "rows_per_flush": n_rows,
            "flushes": n_flushes,
            "wall_stamped_ms": round(wall_s, 1),
            "wall_legacy_ms": round(wall_l, 1),
            "stamped_flushes": len(stamped_recs),
            "h2d_ms_stamped": med(recs_s, "h2d_ms"),
            "h2d_ms_legacy": med(recs_l, "h2d_ms"),
            "pack_ms_stamped": med(recs_s, "pack_ms"),
            "pack_ms_legacy": med(recs_l, "pack_ms"),
            "delta_bytes_per_flush": med(stamped_recs, "delta_bytes"),
            "stamp_split": sum_s.get("stamp"),
            "note": "host-only runs never stamp (fused path bypassed "
                    "on CPU); the smoke row carries the byte budget",
        },
    }


def cost_hooks_bookkeeping_us(k: int = 20_000) -> dict:
    """Per-flush cost of the ISSUE 20 cost-observatory hooks with
    tracing disabled (< 10 us/flush, tier-1-asserted).

    Replays the exact sequence _charge_flush adds to every flush — one
    split_device_columns call over a fused three-tenant batch (the
    worst common case: integer shares plus the last-tenant residual),
    the per-share note_device accumulation, and the cost-surface
    observe() bucketing — against throwaway registry/surface instances
    so the session's live observatory is untouched."""
    from cometbft_tpu.libs import deviceledger, tracing
    from cometbft_tpu.verifyplane.plane import split_device_columns
    from cometbft_tpu.verifyplane.tenants import TenantRegistry

    assert not tracing.enabled(), "measure the DISABLED path"
    reg = TenantRegistry()
    surf = deviceledger.CostSurfaces()
    tens = (("bench-a", 24), ("bench-b", 24), ("bench-c", 16))
    t0 = _now_ms()
    for _ in range(k):
        rule, shares = split_device_columns(tens, 64, 1.25, 0.5,
                                            3.75, 5121)
        reg.note_device_shares(shares)
        surf.observe("fused:stamped", 64, 1, 1.25, 0.5, 3.75)
    hook_us = (_now_ms() - t0) * 1000 / k
    return {
        "cost_hooks_us_per_flush": round(hook_us, 3),
        "note": "tenant split + per-share charge + cost-surface "
                "bucket, per flush; always-on (<10us budget)",
    }


def smoke_cost_observatory():
    """cfg20's host-only miniature (no jax, no plane): the cost
    observatory's arithmetic proven in isolation — the tenant split
    rule (exact at sub-flush boundaries, row-proportional with an
    integer last-tenant residual inside a fused batch), charge
    conservation across eviction/retirement (reconcile_device drift
    identically zero — integer us, no tolerance band), the
    rows-bucket / percentile / marginal-slope math of the cost
    surfaces, the CostModel estimate extension past the learned
    range, and the always-on per-flush hook budget."""
    from cometbft_tpu.libs import deviceledger
    from cometbft_tpu.verifyplane.plane import (
        SPLIT_EXACT,
        SPLIT_ROWS,
        ms_to_us,
        split_device_columns,
    )
    from cometbft_tpu.verifyplane.tenants import (
        TenantRegistry,
        reconcile_device,
    )

    checks = {}
    # the split rule: nothing charged without tenants, full charge for
    # a single tenant, row-proportional shares that conserve EVERY
    # column exactly (the residual lands on the last tenant)
    checks["empty_tenants_no_charge"] = split_device_columns(
        (), 0, 1.0, 1.0, 1.0, 64) == (SPLIT_EXACT, [])
    rule, shares = split_device_columns(
        (("a", 64),), 64, 1.25, 0.5, 3.75, 5120)
    checks["single_tenant_exact"] = (
        rule == SPLIT_EXACT
        and shares == [("a", 1250, 500, 3750, 5120)])
    rule, shares = split_device_columns(
        (("a", 24), ("b", 24), ("c", 16)), 64, 1.25, 0.5, 3.75, 5121)
    checks["fused_rows_rule"] = rule == SPLIT_ROWS
    checks["fused_conserves_every_column"] = all(
        sum(s[i] for s in shares) == tot
        for i, tot in ((1, ms_to_us(1.25)), (2, ms_to_us(0.5)),
                       (3, ms_to_us(3.75)), (4, 5121)))

    # conservation across eviction: charge a registry from synthetic
    # ledger records, reconcile (drift zero), retire one tenant, and
    # reconcile again — the retired fold must keep the totals exact
    reg = TenantRegistry()
    recs = [
        {"tenants": (("a", 8),), "rows": 8, "comp_ms": 2.0,
         "h2d_ms": 0.25, "dev_ms": 1.5, "delta_bytes": 640},
        {"tenants": (("a", 30), ("b", 34)), "rows": 64,
         "comp_ms": 0.0, "h2d_ms": 0.125, "dev_ms": 3.125,
         "delta_bytes": 5120},
        # shed-only record: () tenants, never charged
        {"tenants": (), "rows": 16, "comp_ms": 9.0, "h2d_ms": 9.0,
         "dev_ms": 9.0, "delta_bytes": 999},
    ]
    for r in recs:
        if r["tenants"]:
            _, sh = split_device_columns(
                r["tenants"], r["rows"], r["comp_ms"], r["h2d_ms"],
                r["dev_ms"], r["delta_bytes"])
            for chain, comp_us, h2d_us, dev_us, dbytes in sh:
                reg.note_device(chain, comp_us, h2d_us, dev_us, dbytes)
    checks["conservation"] = all(
        v == 0 for v in reconcile_device(recs, reg)["drift"].values())
    reg.evict("a")
    checks["conservation_after_retirement"] = all(
        v == 0 for v in reconcile_device(recs, reg)["drift"].values())
    checks["retired_fold"] = reg.dump()["retired"]["device_us"] > 0

    # cost-bucket math against an isolated recorder: power-of-two
    # buckets, sorted surfaces, the marginal slope between adjacent
    # buckets, and the estimate extension past the learned range
    checks["bucket_boundaries"] = (
        [deviceledger.rows_bucket(n) for n in (0, 1, 2, 3, 64, 65)]
        == [1, 1, 2, 4, 64, 128])
    prev = deviceledger.install_surfaces(deviceledger.CostSurfaces())
    try:
        for rows, dev in ((8, 0.6), (64, 1.1), (512, 4.0)):
            for _ in range(5):
                deviceledger.observe_flush(
                    "fused", "device", rows, 1, 0.0, 0.1, dev)
        cs = deviceledger.surfaces().surfaces()
        p50s = [r["dev_ms_p50"] for r in cs]
        checks["surfaces_populated"] = len(cs) == 3
        checks["stamped_family_label"] = all(
            r["family"] == "fused:stamped" for r in cs)
        checks["monotone_dev_p50"] = p50s == sorted(p50s)
        checks["marginal_math"] = (
            cs[1]["marginal_ms_per_row"]
            == round((1.1 - 0.6) / (64 - 8), 6))
        model = deviceledger.cost_model()
        checks["estimate_extends"] = (
            model.estimate_dev_ms("fused:stamped", 2000) is not None
            and model.estimate_dev_ms("unobserved", 64) is None)
    finally:
        deviceledger.install_surfaces(prev)

    budget = cost_hooks_bookkeeping_us(k=2000)
    checks["hook_budget"] = budget["cost_hooks_us_per_flush"] < 10.0
    assert all(checks.values()), checks
    return {
        "metric": "cfg20_smoke cost observatory hooks",
        "value": budget["cost_hooks_us_per_flush"],
        "unit": "us/flush",
        "vs_baseline": None,
        "extra": {"checks": checks, "budget": budget,
                  "surfaces_sample": cs},
    }


def cfg20_cost_pod(rounds=6, row_sizes=(12, 96, 768)):
    """#20: the cost observatory end to end — K chains at DISTINCT
    flush shapes through one shared plane, so the per-flush hook
    populates separated rows-buckets of the cost surfaces while the
    tenant registry accrues each chain's device charge. Sequential
    per-chain rounds give each shape its own bucket; a final
    concurrent round coalesces cross-tenant rows into a fused flush
    and exercises the row-proportional split. The row sizes sit
    MID-bucket (12->16, 96->128, 768->1024) so any cross-tenant
    fusion lands in the largest member's own bucket with MORE rows —
    coalescing can only pull a bucket's p50 up, never park a
    bottom-of-bucket flush under the previous bucket's top. Evidence:
    (a) reconcile_device drift is exactly zero against the flush
    ledger; (b) cost_surfaces is non-empty with dev p50 monotone
    non-decreasing across rows-buckets within each (family, n_dev)
    series; (c) the embedded tenants_dump / devices_dump are the
    tenant_report / device_report inputs."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.libs import deviceledger
    from cometbft_tpu.verifyplane.plane import (
        LANE_BULK,
        SPLIT_ROWS,
        VerifyPlane,
    )
    from cometbft_tpu.verifyplane.tenants import reconcile_device

    chains = {}
    for i, n in enumerate(row_sizes):
        chain = f"cost-{n}"
        msg = b"cfg20:" + chain.encode()
        rows = []
        for j in range(n):
            priv = PrivKey.generate(
                bytes([210 + i]) + j.to_bytes(2, "big") + b"\x44" * 29)
            rows.append((priv.pub_key(), msg, priv.sign(msg)))
        chains[chain] = rows

    prev = deviceledger.install_surfaces(deviceledger.CostSurfaces())
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        max_batch=2 * sum(row_sizes))
    plane.start()
    t = _now_ms()
    try:
        for _ in range(rounds):
            for c, rows in chains.items():
                assert all(plane.submit_many(
                    list(rows), lane=LANE_BULK,
                    chain_id=c).result(60.0))
        futs = [plane.submit_many(list(rows), lane=LANE_BULK,
                                  chain_id=c)
                for c, rows in chains.items()]
        for f in futs:
            assert all(f.result(60.0))
        wall_ms = _now_ms() - t
        recs = plane.ledger.records()
        rd = reconcile_device(recs, plane.tenants)
        tenants_dump = plane.tenants.dump()
        devices_dump = deviceledger.dump_devices()
        cs = devices_dump["cost_surfaces"]
        model = deviceledger.cost_model()
    finally:
        plane.stop()
        deviceledger.install_surfaces(prev)

    series = {}
    for r in cs:
        series.setdefault((r["family"], r["n_dev"]), []).append(
            (r["rows_bucket"], r["dev_ms_p50"]))
    fam0 = cs[0]["family"] if cs else ""
    checks = {
        "conservation_drift_zero": all(
            v == 0 for v in rd["drift"].values()),
        "surfaces_nonempty": len(cs) >= len(row_sizes),
        "buckets_separated": len({r["rows_bucket"] for r in cs})
        >= len(row_sizes),
        "monotone_dev_p50": all(
            p[1] <= q[1]
            for pts in series.values()
            for p, q in zip(sorted(pts), sorted(pts)[1:])),
        "fused_split_recorded": any(
            r["split"] == SPLIT_ROWS for r in recs
            if len(r["tenants"]) > 1),
        "every_flush_observed":
            devices_dump["cost_counters"]["observed"] >= len(recs),
        "estimate_available": bool(cs) and model.estimate_dev_ms(
            fam0, row_sizes[0]) is not None,
    }
    assert all(checks.values()), checks
    total_rows = (rounds + 1) * sum(row_sizes)
    budget = cost_hooks_bookkeeping_us()
    return {
        "metric": "cfg20 cost-observatory pod throughput",
        "value": round(total_rows / max(wall_ms, 1e-9) * 1000.0, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "extra": {
            "rows_total": total_rows,
            "flushes": len(recs),
            "split_rules": {
                rule: sum(1 for r in recs if r["split"] == rule)
                for rule in {r["split"] for r in recs}},
            "reconcile": rd,
            "cost_counters": devices_dump["cost_counters"],
            "cost_surfaces": cs,
            "budget": budget,
            "checks": checks,
            "tenants_dump": tenants_dump,
            "devices_dump": devices_dump,
        },
    }


SMOKE_CONFIGS = [("cfg2_smoke", smoke_commit_verify),
                 ("cfg4_smoke", smoke_pack_rows),
                 ("cfg6_smoke", smoke_vote_plane),
                 ("cfg10_smoke", smoke_gateway),
                 ("cfg11_smoke", smoke_sharded_layout),
                 ("cfg12_smoke", smoke_pipelined_deck),
                 ("cfg13_smoke", smoke_churn_warmer),
                 ("cfg14_smoke", smoke_peer_ledger),
                 ("cfg15_smoke", smoke_device_observatory),
                 ("cfg16_smoke", smoke_controller),
                 ("cfg17_smoke", smoke_tenants),
                 ("cfg18_smoke", smoke_catchup),
                 ("cfg19_smoke", smoke_device_stamp),
                 ("cfg20_smoke", smoke_cost_observatory)]

TRACED_CONFIGS = ("cfg2", "cfg6")  # flush-pipeline configs worth a trace

# the full (TPU-host) config set, in run order — tools/bench_history.py
# seeds its per-config rows from these names so a config added here is
# trackable from the next bench round onward even before any BENCH
# file records it
FULL_CONFIGS = [("cfg1", cfg1_live_node), ("cfg2", cfg2_1k_commit),
                ("cfg3", cfg3_mixed), ("cfg4", cfg4_streaming),
                ("cfg5", cfg5_light_secp), ("cfg6", cfg6_vote_plane),
                ("cfg7", cfg7_pack_only), ("cfg8", cfg8_multichip_smoke),
                ("cfg9", cfg9_sustained), ("cfg10", cfg10_gateway),
                ("cfg11", cfg11_sharded_tally),
                ("cfg12", cfg12_pipelined), ("cfg13", cfg13_churn),
                ("cfg15", cfg15_device), ("cfg16", cfg16_controller),
                ("cfg17", cfg17_tenants),
                ("cfg18", cfg18_catchup),
                ("cfg19", cfg19_device_stamp),
                ("cfg20", cfg20_cost_pod)]
FULL_CONFIG_NAMES = [name for name, _ in FULL_CONFIGS] + ["headline"]


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description="BASELINE configs bench")
    ap.add_argument(
        "--trace-out", default="",
        help="path prefix: run cfg2/cfg6 with tracing ON, write "
             "<prefix>.<cfg>.trace.json (perfetto-loadable) and embed "
             "the trace-derived stage table in each config's JSON. "
             "Tracing stays OFF for every other config and when the "
             "flag is absent — the headline numbers are untraced.")
    ap.add_argument(
        "--baseline", default="",
        help="a stored bench output (driver BENCH_rNN.json, --json-out "
             "file, or raw stdout capture): compare this run per-config "
             "with thresholded pass/fail and print the table as the "
             "last JSON line")
    ap.add_argument(
        "--baseline-threshold", type=float,
        default=BASELINE_THRESHOLD_PCT,
        help="regression threshold in percent (default %(default)s — "
             "the shared-tunnel noise floor)")
    ap.add_argument(
        "--fail-on-regression", action="store_true",
        help="exit 1 when --baseline flags any config REGRESSED")
    ap.add_argument(
        "--json-out", default="",
        help="also write {results, baseline_check} to this path (the "
             "evidence-file shape load_bench_results() accepts)")
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 mode: tiny shapes, host paths only, no jax import "
             "and no accelerator; catches bench.py rot in seconds")
    args = ap.parse_args(argv)
    if args.fail_on_regression and not args.baseline:
        # a CI gate that never compares anything would be permanently
        # green — surface the misconfiguration instead
        ap.error("--fail-on-regression requires --baseline")

    t0 = time.time()
    results = {}

    if args.smoke:
        for name, fn in SMOKE_CONFIGS:
            try:
                r = fn()
            except Exception as e:  # a config failure must not kill it
                r = {"metric": f"{name} FAILED", "value": None,
                     "unit": "", "vs_baseline": None,
                     "extra": {"error": repr(e)[:300]}}
            results[name] = r
            print(json.dumps(r), flush=True)
        print(json.dumps({
            "metric": "smoke summary",
            "value": len([r for r in results.values()
                          if r.get("value") is not None]),
            "unit": "configs",
            "vs_baseline": None,
            "extra": {"mode": "smoke (host-only, tiny shapes)",
                      "total_bench_s": round(time.time() - t0, 2)},
        }), flush=True)
        return _finish(args, results)

    import jax

    from cometbft_tpu.libs import tracing
    from tools import trace_report

    watch = CompileWatch()
    watch.arm()

    from cometbft_tpu.libs import deviceledger

    for name, fn in FULL_CONFIGS:
        traced = bool(args.trace_out) and name in TRACED_CONFIGS
        if traced:
            tracing.enable(capacity=1 << 18)
        compile_before = watch.snap()
        try:
            # the attribution frame names this config as the compile
            # site in /dump_devices (plane flushes carry their own
            # richer per-flush frames on the dispatcher thread)
            with deviceledger.attr_context(f"bench.{name}"):
                r = fn()
        except Exception as e:  # a config failure must not kill the run
            r = {"metric": f"{name} FAILED", "value": None, "unit": "",
                 "vs_baseline": None, "extra": {"error": repr(e)[:300]}}
        # cold-compile pollution must be VISIBLE per config: how many
        # backend compiles ran during this config, their total seconds,
        # and how many were absorbed by the persistent cache
        r.setdefault("extra", {})["jax_compile"] = \
            watch.delta(compile_before)
        if traced:
            try:
                path = f"{args.trace_out}.{name}.trace.json"
                doc = tracing.export_chrome()  # one ring snapshot
                with open(path, "w") as f:
                    json.dump(doc, f)
                rep = trace_report.stage_report(doc["traceEvents"])
                extra = r.setdefault("extra", {})
                extra["trace_file"] = path
                extra["trace_stages"] = rep["stages"]
                if rep["plane"]:
                    extra["trace_plane"] = rep["plane"]
            except Exception as e:  # noqa: BLE001 - a bad --trace-out
                # path must not kill the remaining configs
                r.setdefault("extra", {})["trace_error"] = repr(e)[:200]
            finally:
                # never leak tracing into the untraced configs/headline
                tracing.disable()
        results[name] = r
        print(json.dumps(r), flush=True)

    tunnel_floor = measure_tunnel_floor()
    compile_before = watch.snap()
    cpu_ms, raw, steady, pack_ms, tbl_ms, resident, overlap = headline_10k()
    headline = {
                "metric": "10k-validator VerifyCommitLight fused p50",
                "value": round(steady, 2),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / steady, 2),
                "extra": {
                    "device": str(jax.devices()[0]),
                    "kernel": "pallas-valset-cached + int8 MXU entry fetch",
                    "sigs_per_sec": round(10_000 / (steady / 1000)),
                    "raw_single_shot_p50_ms": round(p50(raw), 2),
                    "tunnel_floor_ms": round(tunnel_floor, 1),
                    "host_pack_ms": round(pack_ms, 2),
                    # stamped path's residual host cost (sig scatter +
                    # ts word split + flags) — not 0, just small
                    "host_pack_stamped_ms":
                        overlap["host_pack_stamped_ms"],
                    "steady_overlap_ms": overlap["steady_overlap_ms"],
                    "staging_overlap_eff": overlap["staging_overlap_eff"],
                    "table_build_ms_cold_compile": round(tbl_ms["cold"], 1),
                    "table_rebuild_warm_ms": round(tbl_ms["rebuild_warm"], 1),
                    "table_update_10vals_ms": round(tbl_ms["update10"], 1),
                    "steady_resident_ms": round(resident, 2),
                    "sigs_per_sec_resident": round(
                        10_000 / (resident / 1000)),
                    "end_to_end_ms": round(pack_ms + steady, 1),
                    "cpu_measured_ms": round(cpu_ms, 1),
                    "cpu_batch_bound_2x_ms": round(cpu_ms / 2, 1),
                    "baseline_method": "measured 1-core OpenSSL verify "
                                       "loop on real sign-bytes (host has "
                                       "nproc=1; no fudge factors)",
                    "configs": {
                        k: {"value": v.get("value"),
                            "unit": v.get("unit"),
                            "vs_baseline": v.get("vs_baseline")}
                        for k, v in results.items()
                    },
                    "total_bench_s": round(time.time() - t0, 1),
                },
            }
    headline["extra"]["jax_compile"] = watch.delta(compile_before)
    print(json.dumps(headline))
    results["headline"] = headline
    return _finish(args, results)


def _finish(args, results: dict) -> int:
    """Shared tail for full and smoke runs: the --baseline comparison
    table (printed as the LAST JSON line so drivers and eyeballs both
    find it), the --json-out evidence file, and the exit code."""
    cmp_doc = None
    if args.baseline:
        cmp_doc = compare_to_baseline(
            results, load_bench_results(args.baseline),
            threshold_pct=args.baseline_threshold)
        print(json.dumps({
            "metric": f"baseline comparison vs {args.baseline}",
            "value": len(cmp_doc["regressed"]),
            "unit": "regressions",
            "vs_baseline": None,
            "extra": cmp_doc,
        }), flush=True)
    if args.json_out:
        doc = {"results": results}
        if cmp_doc is not None:
            doc["baseline_check"] = cmp_doc
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=1)
    if args.fail_on_regression and cmp_doc is not None \
            and not cmp_doc["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
