"""Benchmark: 10k-validator commit verification (the BASELINE.json metric).

Measures p50 latency of the fused device pass — batched ed25519 ZIP-215
verification (Pallas TPU kernel) + voting-power quorum tally over a
10_000-signature commit — on whatever backend JAX selects (the driver
runs it on the real TPU chip). Prints ONE JSON line.

Baseline: the reference's Go `crypto/batch` path (curve25519-voi batch
verify) has no committed absolute numbers (BASELINE.md) and no Go
toolchain exists in this image, so the CPU baseline is measured live with
OpenSSL (`cryptography` package) single verifies and scaled by an assumed
voi batch speedup — both the raw measurement and the assumption are
reported explicitly (`cpu_single_ms_meas`, `assumed_batch_speedup`).
vs_baseline = cpu_est_ms / device_p50_ms.
"""
import json
import time

import numpy as np

N_VALIDATORS = 10_000
PAD = 10_240  # multiple of the 128-lane Pallas tile; 80 grid steps
ASSUMED_BATCH_SPEEDUP = 1.7  # voi ZIP-215 batch vs single, size ~1k (est.)


def main():
    t0 = time.time()
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    import jax

    from cometbft_tpu.ops import ed25519_kernel as k
    from cometbft_tpu.ops import ed25519_pallas as kp

    # --- build a synthetic 10k-validator commit (distinct keys) -----------
    n_keys = 64  # distinct signing keys, cycled (keygen cost cap)
    sks = [Ed25519PrivateKey.generate() for _ in range(n_keys)]
    pubs_pool = [
        s.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        for s in sks
    ]
    msgs = [
        b"vote-sign-bytes|h=12345|r=0|vote-%06d" % i
        for i in range(N_VALIDATORS)
    ]
    sigs = [sks[i % n_keys].sign(m) for i, m in enumerate(msgs)]
    pubs = [pubs_pool[i % n_keys] for i in range(N_VALIDATORS)]

    # --- CPU baseline: OpenSSL verify loop (sampled) ----------------------
    pk_objs = [s.public_key() for s in sks]  # hoisted: no per-verify serde
    sample = 500
    t = time.perf_counter()
    for i in range(sample):
        pk_objs[i % n_keys].verify(sigs[i], msgs[i])
    per_sig = (time.perf_counter() - t) / sample
    cpu_single_ms = per_sig * N_VALIDATORS * 1000
    cpu_est_ms = cpu_single_ms / ASSUMED_BATCH_SPEEDUP

    # --- pack + stage -----------------------------------------------------
    t = time.perf_counter()
    pb = k.pack_batch(pubs, msgs, sigs, pad_to=PAD)
    targs = kp.pack_transposed(pb)
    pack_ms = (time.perf_counter() - t) * 1000

    powers = np.full((N_VALIDATORS,), 1000, np.int64)
    power5 = np.zeros((PAD, k.POWER_LIMBS), np.int32)
    power5[:N_VALIDATORS] = k.power_limbs(powers)
    counted = np.zeros((PAD,), np.bool_)
    counted[:N_VALIDATORS] = True
    commit_ids = np.zeros((PAD,), np.int32)
    thresh = k.threshold_limbs(int(powers.sum()) * 2 // 3)

    t = time.perf_counter()
    args = [jax.device_put(a) for a in targs] + [
        jax.device_put(a) for a in (power5, counted, commit_ids, thresh)
    ]
    # device_put is async (and block_until_ready does not block on the
    # axon tunnel backend) — fetch one element per array to pin the
    # transfers before stopping the clock
    for a in args:
        np.asarray(a).ravel()[:1]
    h2d_ms = (time.perf_counter() - t) * 1000

    # --- device p50 (quorum bit fetched each run — the happy-path output;
    # np.asarray forces real completion, block_until_ready does not block
    # on the axon tunnel backend) ------------------------------------------
    valid, tally, quorum = kp.verify_tally_pallas(*args)
    assert bool(np.asarray(quorum)[0]), "quorum must hold on valid commit"
    assert np.asarray(valid)[:N_VALIDATORS].all()
    times = []
    for _ in range(10):
        t = time.perf_counter()
        _, _, quorum = kp.verify_tally_pallas(*args)
        ok = bool(np.asarray(quorum)[0])
        times.append((time.perf_counter() - t) * 1000)
        assert ok
    p50 = float(np.percentile(times, 50))

    print(
        json.dumps(
            {
                "metric": "10k-validator VerifyCommitLight fused p50",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_est_ms / p50, 2),
                "extra": {
                    "device": str(jax.devices()[0]),
                    "kernel": "pallas",
                    "sigs_per_sec": round(N_VALIDATORS / (p50 / 1000)),
                    "cpu_single_ms_meas": round(cpu_single_ms, 1),
                    "assumed_batch_speedup": ASSUMED_BATCH_SPEEDUP,
                    "cpu_baseline_est_ms": round(cpu_est_ms, 1),
                    "host_pack_ms": round(pack_ms, 1),
                    "h2d_ms": round(h2d_ms, 1),
                    "end_to_end_ms": round(pack_ms + h2d_ms + p50, 1),
                    "min_ms": round(min(times), 3),
                    "total_bench_s": round(time.time() - t0, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
