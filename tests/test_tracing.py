"""The trace plane (libs/tracing.py): tracer semantics, Chrome-trace
export, the /dump_traces surface, trace_report's stage table, and the
simnet trace-determinism acceptance (same seed+schedule => identical
span names/order/timestamps under the virtual clock).
"""
import json

import pytest

from cometbft_tpu.libs import tracing


@pytest.fixture(autouse=True)
def _clean_tracer():
    tracing.disable()
    tracing.set_clock(None)
    yield
    tracing.disable()
    tracing.set_clock(None)


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_disabled_is_noop():
    assert not tracing.enabled()
    with tracing.span("never", cat="x", k=1) as s:
        assert s is None
    tracing.instant("never")
    tracing.flight_begin("never", 1)
    tracing.flight_end("never", 1)
    assert tracing.export_chrome()["traceEvents"] == []
    assert tracing.tail() == []


def test_span_instant_flight_export():
    tracing.enable(capacity=128)
    with tracing.span("outer", cat="t", height=3):
        tracing.instant("mark", cat="t", n=1)
        with tracing.span("inner", cat="t"):
            pass
    tracing.flight_begin("fly", 7, cat="t", rows=4)
    tracing.flight_end("fly", 7, cat="t")
    evs = tracing.export_chrome()["traceEvents"]
    by_name = {e["name"]: e for e in evs}
    assert by_name["outer"]["ph"] == "X"
    assert by_name["outer"]["args"] == {"height": 3}
    assert by_name["outer"]["dur"] >= by_name["inner"]["dur"] >= 0
    assert by_name["mark"]["ph"] == "i" and by_name["mark"]["s"] == "t"
    # async pair correlated by id, required for perfetto overlap tracks
    b = [e for e in evs if e["ph"] == "b"][0]
    e = [e for e in evs if e["ph"] == "e"][0]
    assert b["id"] == e["id"] == "7"
    assert b["cat"] == e["cat"] == "t"
    # inner closed before outer: ring order is completion order
    names = [ev["name"] for ev in evs]
    assert names.index("inner") < names.index("outer")
    # the whole document is valid JSON with the chrome keys
    doc = json.loads(json.dumps(tracing.export_chrome()))
    assert doc["displayTimeUnit"] == "ms"


def test_ring_buffer_bounds_and_drop_count():
    t = tracing.enable(capacity=16)
    for i in range(40):
        tracing.instant(f"e{i}")
    evs = t.events()
    assert len(evs) == 16
    assert evs[0]["name"] == "e24" and evs[-1]["name"] == "e39"
    assert t.dropped == 24


def test_deterministic_mode_and_custom_clock():
    ticks = iter(range(0, 10_000, 1000))
    tracing.enable(capacity=32, clock=lambda: next(ticks),
                   deterministic=True)
    with tracing.span("a"):
        tracing.instant("b")
    evs = tracing.export_chrome()["traceEvents"]
    assert all(e["tid"] == 0 and e["pid"] == 1 for e in evs)
    assert [e["ts"] for e in evs] == [1.0, 0.0]  # ns -> us
    assert evs[1]["dur"] == 2.0  # span a: t0=0, closed at t=2000ns


def test_write_and_tail(tmp_path):
    tracing.enable(capacity=32)
    tracing.instant("alpha")
    with tracing.span("beta"):
        pass
    path = str(tmp_path / "trace.json")
    tracing.write(path)
    with open(path) as f:
        doc = json.load(f)
    assert [e["name"] for e in doc["traceEvents"]] == ["alpha", "beta"]
    assert tracing.tail(1) == ["beta(X)"]


def test_profiler_bracket_noop_without_dir():
    tracing.set_profile_dir("")
    assert tracing.profiler_start() is False
    tracing.profiler_stop()  # must not raise


def test_dump_traces_route():
    from cometbft_tpu.rpc.server import Routes

    tracing.enable(capacity=32)
    tracing.instant("rpc-visible")
    doc = Routes(None).dump_traces()
    assert doc["traceEvents"][0]["name"] == "rpc-visible"


def test_tracing_config_applies():
    from cometbft_tpu.config.config import Config, ConfigError

    cfg = Config()
    assert cfg.tracing.enable is False
    cfg.tracing.enable = True
    cfg.tracing.buffer = 64
    cfg.validate_basic()
    cfg.tracing.apply()
    assert tracing.enabled() and tracing.tracer().capacity == 64
    cfg.tracing.buffer = 1
    with pytest.raises(ConfigError, match="tracing"):
        cfg.validate_basic()


# ---------------------------------------------------------------------------
# instrumented seams produce spans
# ---------------------------------------------------------------------------


def test_wal_spans_and_fsync_stats(tmp_path):
    from cometbft_tpu.consensus import wal as walmod

    tracing.enable(capacity=64)
    before = walmod.fsync_stats()
    w = walmod.WAL(str(tmp_path / "t.wal"))
    w.write_sync(walmod.MSG_INFO, b"payload")
    w.close()
    after = walmod.fsync_stats()
    assert after["count"] >= before["count"] + 1
    assert after["seconds"] >= before["seconds"]
    names = [e["name"] for e in tracing.export_chrome()["traceEvents"]]
    assert "wal.fsync" in names and "wal.write_sync" in names


def test_plane_flush_lifecycle_spans():
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane

    tracing.enable(capacity=256)
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    try:
        priv = PrivKey.generate(b"\x61" * 32)
        msg = b"traced-vote"
        fut = plane.submit(priv.pub_key(), msg, priv.sign(msg))
        assert fut.result(10.0) == (True,)
    finally:
        plane.stop()
    evs = tracing.export_chrome()["traceEvents"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    assert "plane.submit" in by_name
    packs = by_name["plane.pack"]
    settles = by_name["plane.settle"]
    assert packs and settles
    # pack and settle of one flush correlate by flush id (ids are
    # process-global so concurrent planes can never cross-pair flights)
    assert packs[0]["args"]["flush"] == settles[0]["args"]["flush"]
    assert packs[0]["args"]["rows"] == 1
    assert packs[0]["args"]["queued_ms"] >= 0


def test_queued_ms_ignores_cross_clock_stamps():
    """A submission stamped before a clock install (a simnet
    enter/exit lands between submit and flush) must not difference two
    clock domains: the stale stamp is skipped and queued_ms falls back
    to 0 instead of an absurd virtual-minus-perf_counter delta."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import plane as vp

    priv = PrivKey.generate(b"\x62" * 32)
    msg = b"cross-clock"
    rows = [(priv.pub_key(), msg, priv.sign(msg))]
    p = vp.VerifyPlane(window_ms=0.5, use_device=False)
    sub = vp._Submission(rows, None, 0, False)  # perf_counter domain
    # a simnet-style virtual clock (ns since epoch) lands mid-queue
    tracing.set_clock(lambda: 1_700_000_000_000_000_000)
    try:
        flight = p._stage([sub])
        verdicts, _ = flight.finish()
        led = flight.led
    finally:
        tracing.set_clock(None)
    assert list(verdicts) == [True]
    assert led[vp.FlushLedger.FIELDS.index("queued_ms")] == 0.0


def test_consensus_step_metrics_and_instants(tmp_path):
    """A live single-validator node emits consensus.step instants and
    per-step duration observations while committing blocks."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    tracing.enable(capacity=4096)
    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.01)
    priv = PrivKey.generate(bytes([29]) * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("trace-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=fast)
    node.start()
    try:
        assert node.consensus.wait_for_height(2, timeout=30)
        text = node.metrics.expose_text()
    finally:
        node.stop()
    steps = [e for e in tracing.export_chrome()["traceEvents"]
             if e["name"] == "consensus.step"]
    seen = {e["args"]["step"] for e in steps}
    assert {"propose", "prevote", "precommit", "commit"} <= seen
    # per-step durations landed in the labeled histogram
    assert 'cometbft_consensus_step_duration_seconds_count' \
        '{step="propose"}' in text


# ---------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------


def test_trace_report_stage_table(tmp_path):
    from tools import trace_report

    clock = iter(range(0, 10_000_000, 500_000))  # 0.5 ms ticks
    tracing.enable(capacity=256, clock=lambda: next(clock),
                   deterministic=True)
    # flush 0 flies while flush 1 packs: pack(1) must show overlap
    tracing.flight_begin("plane.flight", 0, cat="verifyplane")
    with tracing.span("plane.pack", cat="verifyplane", flush=1):
        pass
    tracing.flight_end("plane.flight", 0, cat="verifyplane")
    with tracing.span("plane.collect", cat="verifyplane", flush=0):
        pass
    tracing.instant("simnet.op", cat="simnet", op="heal")
    path = str(tmp_path / "t.json")
    tracing.write(path)

    rep = trace_report.stage_report(trace_report.load(path))
    stages = {r["stage"]: r for r in rep["stages"]}
    assert stages["plane.pack"]["count"] == 1
    assert stages["plane.pack"]["total_ms"] == pytest.approx(0.5)
    assert stages["plane.collect"]["count"] == 1
    # plane pipeline order leads the table
    assert rep["stages"][0]["stage"] == "plane.pack"
    assert rep["plane"]["flights"] == 1
    # flight: begin tick 0 -> end tick 3 = 1.5 ms on the 0.5 ms clock
    assert rep["plane"]["flight_total_ms"] == pytest.approx(1.5)
    # the whole pack happened while flight 0 was airborne
    assert rep["plane"]["pack_overlap_frac"] == pytest.approx(1.0)
    assert rep["instants"] == {"simnet.op": 1}
    txt = trace_report.format_report(rep)
    assert "plane.pack" in txt and "verify-plane flights: 1" in txt


def test_trace_report_deck_occupancy_and_overlap_union():
    """ISSUE 11 satellite: the overlap/critical-path math must handle
    MORE than one airborne flight. Two concurrent flights overlapping
    one pack span used to double-count it (fractions over 1.0); the
    fix computes pack overlap against the UNION of flight intervals,
    and the new deck block sweeps concurrency: fraction of wall time
    with >=1 and >=2 flights airborne."""
    from tools import trace_report

    # synthetic trace, us timestamps: flight A [0, 100], flight B
    # [40, 140] (60 us of two-deep deck), one pack span [50, 90]
    # entirely inside BOTH flights
    events = [
        {"ph": "b", "name": "plane.flight", "id": "a", "ts": 0},
        {"ph": "b", "name": "plane.flight", "id": "b", "ts": 40},
        {"ph": "X", "name": "plane.pack", "ts": 50, "dur": 40},
        {"ph": "e", "name": "plane.flight", "id": "a", "ts": 100},
        {"ph": "e", "name": "plane.flight", "id": "b", "ts": 140},
    ]
    rep = trace_report.stage_report(events)
    p = rep["plane"]
    assert p["flights"] == 2
    # union, not per-flight sums: the 40 us pack overlaps ONCE
    assert p["pack_overlapped_ms"] == pytest.approx(0.04)
    assert p["pack_overlap_frac"] == pytest.approx(1.0)
    deck = p["deck"]
    assert deck["max_airborne"] == 2
    # >=1 flight over [0, 140] = the whole 140 us wall; >=2 over
    # [40, 100] = 60 us
    assert deck["airborne_ge1_ms"] == pytest.approx(0.14)
    assert deck["airborne_ge2_ms"] == pytest.approx(0.06)
    assert deck["occupancy_ge1"] == pytest.approx(1.0)
    assert deck["occupancy_ge2"] == pytest.approx(60 / 140, abs=1e-3)
    txt = trace_report.format_report(rep)
    assert "deck occupancy" in txt and "max airborne 2" in txt
    # the diff's overlap block carries the occupancy deltas
    diff = trace_report.diff_report(rep, rep)
    assert diff["overlap"]["occupancy_ge2_a"] == \
        diff["overlap"]["occupancy_ge2_b"]
    assert diff["overlap"]["max_airborne_b"] == 2
    assert not diff["regressions"]


def test_trace_report_cli(tmp_path, capsys):
    from tools import trace_report

    tracing.enable(capacity=16)
    with tracing.span("stage.a"):
        pass
    path = str(tmp_path / "t.json")
    tracing.write(path)
    assert trace_report.main([path]) == 0
    assert "stage.a" in capsys.readouterr().out
    assert trace_report.main([path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["stages"][0]["stage"] == "stage.a"


def _write_trace(tmp_path, name, events):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _span_ev(name, ts, dur, **args):
    ev = {"ph": "X", "name": name, "cat": "t", "ts": ts, "dur": dur,
          "pid": 1, "tid": 0}
    if args:
        ev["args"] = args
    return ev


def test_trace_report_diff_flags_regressions(tmp_path, capsys):
    """ISSUE 6 tentpole: --diff aligns two stage tables and flags the
    stage whose mean grew past the thresholds, the stage that appeared,
    and an overlap collapse (flights vanished = the plane degraded to
    synchronous flushes)."""
    from tools import trace_report

    a = [_span_ev("plane.pack", i * 1000, 400) for i in range(8)]
    a += [{"ph": "b", "name": "plane.flight", "id": str(i),
           "ts": i * 1000 + 100, "pid": 1, "tid": 0} for i in range(8)]
    a += [{"ph": "e", "name": "plane.flight", "id": str(i),
           "ts": i * 1000 + 600, "pid": 1, "tid": 0} for i in range(8)]
    b = [_span_ev("plane.pack", i * 1000, 900) for i in range(8)]
    b += [_span_ev("plane.verify", i * 1000 + 900, 300)
          for i in range(8)]
    pa = _write_trace(tmp_path, "a.json", a)
    pb = _write_trace(tmp_path, "b.json", b)

    diff = trace_report.diff_report(
        trace_report.stage_report(trace_report.load(pa)),
        trace_report.stage_report(trace_report.load(pb)),
    )
    rows = {r["stage"]: r for r in diff["stages"]}
    assert rows["plane.pack"]["flag"] == "REGRESSED"
    assert rows["plane.pack"]["delta_mean_ms"] == pytest.approx(0.5)
    assert rows["plane.verify"]["flag"] == "appeared"
    assert diff["overlap"]["flag"] == "REGRESSED"  # flights 8 -> 0
    assert "plane.pack" in diff["regressions"]
    assert "pack_overlap_frac" in diff["regressions"]

    # CLI: table mode exits 0, --fail-on-regression exits 1
    assert trace_report.main(["--diff", pa, pb]) == 0
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "plane.pack" in out
    assert trace_report.main(
        ["--diff", pa, pb, "--fail-on-regression"]) == 1
    capsys.readouterr()
    # the reverse direction (B -> A) is pure improvement: pack shrank,
    # the flights (and their overlap) came back — nothing flags, so
    # --fail-on-regression exits 0
    assert trace_report.main(
        ["--diff", pb, pa, "--fail-on-regression", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["regressions"] == []
    assert rep["overlap"]["flag"] == "improved"


def test_trace_report_consensus_fallback(tmp_path, capsys):
    """ISSUE 6 satellite: a trace with zero plane spans (consensus-only
    run) must not crash or print an empty table — it falls back to the
    per-step dwell table derived from consensus.step instants and says
    so."""
    from tools import trace_report

    evs = []
    steps = ["propose", "prevote", "precommit", "commit", "propose"]
    for i, st in enumerate(steps):
        evs.append({"ph": "i", "name": "consensus.step",
                    "cat": "consensus", "ts": i * 500, "s": "t",
                    "pid": 1, "tid": 0,
                    "args": {"step": st, "height": 1, "round": 0}})
    path = _write_trace(tmp_path, "c.json", evs)
    rep = trace_report.stage_report(trace_report.load(path))
    assert rep["fallback"]
    names = [r["stage"] for r in rep["stages"]]
    assert "step.propose" in names and "step.commit" in names
    # each step dwelled one 500 us tick before the next instant
    assert all(r["mean_ms"] == pytest.approx(0.5)
               for r in rep["stages"])
    assert trace_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "NOTE:" in out and "step.propose" in out


# ---------------------------------------------------------------------------
# simnet determinism (the acceptance criterion)
# ---------------------------------------------------------------------------

TRACE_SCHEDULE = [
    {"at": 0.1, "op": "link", "drop": 0.05, "delay": 0.02},
    {"at": 0.8, "op": "heal"},
]


@pytest.mark.simnet
def test_simnet_trace_byte_identical(tmp_path):
    """Same (seed, schedule) twice => the exported trace is
    BYTE-identical: every span/instant name, order, argument, and
    virtual-clock timestamp matches. This is what makes a trace of a
    wedged schedule replayable evidence. (Budgeted small for tier-1:
    3 nodes, 2 heights — the trace shape, not the fault coverage,
    is under test; test_simnet owns the scenario matrix.)"""
    from cometbft_tpu.simnet import Simnet

    def run_once(tag):
        tracing.enable(capacity=1 << 15, deterministic=True)
        try:
            with Simnet(3, seed=42, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(TRACE_SCHEDULE, until_height=2,
                               max_time=60.0)
                sim.assert_safety()
            return json.dumps(tracing.export_chrome(), sort_keys=True)
        finally:
            tracing.disable()

    a = run_once("a")
    b = run_once("b")
    assert a == b
    evs = json.loads(a)["traceEvents"]
    names = {e["name"] for e in evs}
    # the run actually traced the layers that matter
    assert "consensus.step" in names
    assert "wal.fsync" in names
    assert "simnet.op" in names
    # timestamps ride the VIRTUAL clock: they live inside the sim's
    # epoch (seconds around SIM_EPOCH_SECONDS, expressed in us)
    from cometbft_tpu.simnet.core import SIM_EPOCH_SECONDS

    ts0 = min(e["ts"] for e in evs)
    assert ts0 >= SIM_EPOCH_SECONDS * 1e6
