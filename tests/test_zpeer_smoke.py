"""Gossip observatory tier-1 wiring (ISSUE 14): peer-ledger record
shape over a REAL Switch pair on TCP (traffic counts, ping RTT measured
for real, drop attribution), the MConnection full-queue observability
(blocked puts / full drops distinguishable from a stopped conn), the
fuzzer's injected-fault attribution, GET+JSON-RPC /dump_peers
(including the stopping-switch concurrency hammer — the _LAST
pattern), the peer_report --diff regression detector, the
peer_starvation incident trigger, and the < 10 us/message budget.

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP).
Host-only: the whole file must run with NO jax import (asserted).
"""
import copy
import json
import sys
import threading
import time
import urllib.request

import pytest

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import incidents
from cometbft_tpu.p2p import peerledger

_JAX_LOADED_BEFORE = "jax" in sys.modules


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


def test_record_shape_and_seam():
    """Every hook on the shared seam lands in the right FIELDS column;
    the live scratch list becomes the drop-ring slot (FlushLedger
    discipline) and readers never see the internal ping-stamp slots."""
    led = peerledger.PeerLedger()
    rec = led.open_peer("peer-a", True)
    peerledger.note_sent(rec, 0x22, 500)
    peerledger.note_sent(rec, 0x21, 100)
    peerledger.note_recv(rec, 0x22, 80, eof=False)
    peerledger.note_recv(rec, 0x22, 80, eof=True)
    peerledger.note_queue_depth(rec, 9)
    peerledger.note_queue_depth(rec, 2)
    peerledger.note_throttle(rec, 5.0)
    peerledger.note_link_drop(rec)
    recs = led.records()
    assert len(recs) == 1 and set(recs[0]) == set(led.FIELDS)
    r = recs[0]
    assert r["peer"] == "peer-a" and r["dir"] == "out"
    assert r["state"] == "up" and r["msgs_tx"] == 2
    assert r["bytes_tx"] == 600
    # one logical message from two packets
    assert r["msgs_rx"] == 1 and r["bytes_rx"] == 160
    assert r["chans"]["0x22"] == {"msgs_tx": 1, "bytes_tx": 500,
                                  "msgs_rx": 1, "bytes_rx": 160}
    assert r["q_depth"] == 2 and r["q_hiwater"] == 9
    assert r["throttle_stalls"] == 1 and r["throttle_ms"] == 5.0
    # the SAME list object is the ring slot after the drop
    led.drop_peer(rec, "test_drop")
    assert len(led) == 0
    post = led.records()[0]
    assert post["state"] == "dropped" and post["reason"] == "test_drop"
    assert post["msgs_tx"] == 2  # history intact
    # double-drop is idempotent (reconnect racing its teardown)
    led.drop_peer(rec, "again")
    assert led.summary()["peers_dropped"] == 1
    # lifecycle events recorded with the drop
    assert [e["event"] for e in led.events()] == ["up", "drop"]


def test_summary_totals_monotone_across_ring_eviction():
    """Review regression: the drop ring evicting an old record must
    NOT subtract its traffic from the summary totals — the /metrics
    counters sampled from them would read as a reset and fabricate
    rate spikes. Evicted records fold into retired totals."""
    led = peerledger.PeerLedger(capacity=16)
    last = 0
    for i in range(40):  # well past the 16-slot ring
        rec = led.open_peer(f"churn-{i}", True)
        peerledger.note_sent(rec, 0x22, 100)
        peerledger.note_full_drop(rec)
        led.drop_peer(rec, "churn")
        s = led.summary()
        assert s["msgs_tx"] >= last, (i, s["msgs_tx"], last)
        last = s["msgs_tx"]
    s = led.summary()
    assert s["msgs_tx"] == 40 and s["full_drops"] == 40
    assert s["bytes_tx"] == 4000 and s["peers_dropped"] == 40
    # the per-record window is still bounded
    assert len(led.records()) == 16


def test_switch_pair_traffic_rtt_and_drop_attribution(monkeypatch):
    """A real Switch pair over TCP: the ledger counts both directions,
    the patched ping interval produces a REAL measured RTT on both
    sides (the pong stamp satellite), and stop_peer_for_error retires
    the record with the structured reason."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.p2p.conn import connection as connmod
    from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.switch import Reactor, Switch

    monkeypatch.setattr(connmod, "PING_INTERVAL", 0.05)

    class Echo(Reactor):
        def __init__(self):
            super().__init__("ECHO")
            self.got = []

        def channel_descriptors(self):
            return [ChannelDescriptor(0x7F)]

        def receive(self, chan_id, peer, msg):
            self.got.append(msg)

    ka = NodeKey(PrivKey.generate(b"\x5a" * 32))
    kb = NodeKey(PrivKey.generate(b"\x5b" * 32))
    sa, sb = Switch(ka, "zpeer-net"), Switch(kb, "zpeer-net")
    ea, eb = Echo(), Echo()
    sa.add_reactor(ea)
    sb.add_reactor(eb)
    addr_a = sa.listen()
    sa.start()
    sb.start()
    try:
        sb.dial_peer(addr_a, persistent=False)
        deadline = time.time() + 10
        while sa.num_peers() < 1 or sb.num_peers() < 1:
            assert time.time() < deadline, "peers never connected"
            time.sleep(0.02)
        for i in range(5):
            sb.broadcast(0x7F, b"zpeer-%d" % i)
        deadline = time.time() + 10
        while len(ea.got) < 5:
            assert time.time() < deadline, "messages never arrived"
            time.sleep(0.02)

        # traffic attributed on both ledgers
        a_dump = sa.peer_ledger.dump()
        b_dump = sb.peer_ledger.dump()
        assert a_dump["summary"]["peers_live"] == 1
        a_rec = a_dump["peers"][0]
        b_rec = b_dump["peers"][0]
        assert a_rec["peer"] == kb.node_id[:12]
        assert b_rec["peer"] == ka.node_id[:12]
        assert {a_rec["dir"], b_rec["dir"]} == {"in", "out"}
        assert b_rec["msgs_tx"] >= 5
        assert a_rec["msgs_rx"] >= 5 and a_rec["bytes_rx"] > 0
        # channel split carries the echo channel
        assert b_rec["chans"]["0x7f"]["msgs_tx"] >= 5
        # dial lifecycle landed on the dialer's event ring
        assert any(e["event"] == "dial" for e in b_dump["events"])

        # ping RTT: the 50 ms interval has fired by now and the pong
        # stamped a real round trip on the side that pinged
        deadline = time.time() + 10
        while not (sa.peer_ledger.rtt_rows() or
                   sb.peer_ledger.rtt_rows()):
            assert time.time() < deadline, "no RTT ever measured"
            time.sleep(0.05)
        peer_label, rtt = (sa.peer_ledger.rtt_rows()
                           or sb.peer_ledger.rtt_rows())[0]
        assert rtt > 0.0, "pong arrived but RTT not computed"

        # structured drop reason
        peer_b = list(sa.peers.values())[0]
        sa.stop_peer_for_error(peer_b, "zpeer test reason")
        dropped = [p for p in sa.peer_ledger.records()
                   if p["state"] == "dropped"]
        assert dropped and dropped[-1]["reason"] == "zpeer test reason"
    finally:
        sa.stop()
        sb.stop()
    # post-stop: every record retired, history served via the module
    # fallback (_LAST pattern — sb registered last or sa did; either
    # way SOME switch's history is there)
    assert peerledger.dump_peers()["summary"]["peers_dropped"] >= 1


def test_mconnection_full_queue_observable(monkeypatch):
    """ISSUE 14 satellite: a full send queue is OBSERVABLE — the
    non-blocking send counts a full_drop, the blocking send counts a
    blocked_put and (after the timeout) a full_drop, and both return
    False only AFTER the ledger heard about it (previously
    indistinguishable from a stopped conn)."""
    from cometbft_tpu.p2p.conn import connection as connmod
    from cometbft_tpu.p2p.conn.connection import (
        ChannelDescriptor,
        MConnection,
    )

    monkeypatch.setattr(connmod, "SEND_TIMEOUT", 0.05)

    class _DeadConn:
        class _stream:  # noqa: N801 - stop() pokes conn._stream.close
            @staticmethod
            def close():
                pass

        def write_msg(self, b):
            pass

        def read_msg(self):
            time.sleep(3600)

    rec = peerledger.detached_record("full-q", True)
    # never start the routines: the queue can only fill
    mc = MConnection(_DeadConn(), [ChannelDescriptor(1,
                                                     send_queue_capacity=2)],
                     on_receive=lambda c, m: None, ledger_rec=rec)
    assert mc.send(1, b"a") and mc.send(1, b"b")
    # non-blocking on a full queue: explicit drop
    assert mc.send(1, b"c", block=False) is False
    assert rec[peerledger._P_FULLDROP] == 1
    assert rec[peerledger._P_BLOCKED] == 0
    # blocking on a full queue: blocked-put counted, then the timeout
    # drop — and the return is False, not a hang
    t0 = time.monotonic()
    assert mc.send(1, b"d", block=True) is False
    assert time.monotonic() - t0 < 2.0
    assert rec[peerledger._P_BLOCKED] == 1
    assert rec[peerledger._P_FULLDROP] == 2
    # a STOPPED conn still returns False without touching the counters
    mc._stop.set()
    assert mc.send(1, b"e", block=False) is False
    assert rec[peerledger._P_FULLDROP] == 2


def test_fuzzed_socket_attributes_injected_faults():
    """ISSUE 14 satellite: FuzzedSocket drops/delays land in the peer
    ledger as injected faults, so a chaos run's /dump_peers blames the
    fuzzer, not the network."""
    from cometbft_tpu.p2p.fuzz import FuzzConnConfig, FuzzedSocket

    class _Sock:
        def __init__(self):
            self.sent = []

        def sendall(self, b):
            self.sent.append(b)

        def close(self):
            pass

    rec = peerledger.detached_record("fuzzed", True)
    fz = FuzzedSocket(_Sock(), FuzzConnConfig(
        prob_drop_rw=1.0, seed=7), ledger_rec=rec)
    for _ in range(4):
        fz.sendall(b"x")
    assert rec[peerledger._P_INJDROP] == 4
    assert not fz._sock.sent, "dropped writes reached the socket"
    fz2 = FuzzedSocket(_Sock(), FuzzConnConfig(
        prob_drop_rw=0.0, prob_sleep=1.0, max_sleep_s=0.001, seed=7),
        ledger_rec=rec)
    fz2.sendall(b"y")
    assert rec[peerledger._P_INJDELAY] == 1
    assert fz2._sock.sent == [b"y"]  # delayed, not dropped


def test_peer_starvation_incident_trigger():
    """The ledger's full-drop/blocked-put counters feed the
    peer_starvation window: an in-window burst fires ONE incident
    whose snapshot carries the peer-ledger tail; a slow drip over
    longer than window_s stays quiet (the shed-storm expiry-first
    semantics)."""
    from cometbft_tpu.libs import tracing

    now = [10 ** 15]
    tracing.set_clock(lambda: now[0])
    led = peerledger.PeerLedger()
    rec_obj = incidents.IncidentRecorder(
        peer_starvation=10, window_s=2.0, commit_stall_s=0.0,
        cooldown_s=100.0)
    old = incidents.install(rec_obj)
    try:
        r = led.open_peer("starved", True)
        peerledger.set_global_ledger(led)
        for _ in range(5):
            peerledger.note_full_drop(r)
        rec_obj.poke(1, 0)          # anchors the starvation window
        now[0] += int(60e9)         # a minute of drip
        for _ in range(8):
            peerledger.note_blocked_put(r)
        rec_obj.poke(1, 0)          # expired window: 13 stalls, quiet
        assert "peer_starvation" not in rec_obj.fired, rec_obj.fired
        for _ in range(12):         # burst INSIDE the fresh window
            peerledger.note_full_drop(r)
        now[0] += int(1e9)
        rec_obj.poke(2, 0)
        assert rec_obj.fired.get("peer_starvation") == 1, rec_obj.fired
        snap = rec_obj.incidents()[-1]
        assert snap["detail"]["stalls"] == 12
        # the snapshot's peer tail names the starving peer
        assert any("starved" in ln for ln in snap["peer_tail"]), snap
        assert snap["counters"]["peers"]["full_drops"] == 17
        # thresholds surface the new knob
        assert rec_obj.thresholds()["peer_starvation"] == 10
    finally:
        incidents.install(old)
        peerledger.clear_global_ledger(led)
        tracing.set_clock(None)


def _mini_net(n_nodes=2):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([70 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("zpeer-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    return nodes


def test_dump_peers_over_real_rpc():
    """GET /dump_peers and the JSON-RPC form over a live server (the
    curl surface operators actually use). The LocalNetwork node has no
    switch, so the route serves the registered module-global ledger —
    the same fallback an inspect server uses post-mortem."""
    led = peerledger.PeerLedger()
    rec = led.open_peer("rpc-peer", False)
    peerledger.note_sent(rec, 0x22, 64)
    peerledger.set_global_ledger(led)
    nodes = _mini_net(2)
    try:
        for n in nodes:
            n.start()
        url = nodes[0].rpc_listen("127.0.0.1", 0)
        assert nodes[0].consensus.wait_for_height(1, timeout=30.0)
        with urllib.request.urlopen(url + "/dump_peers",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["summary"]["peers_live"] == 1
        assert doc["peers"][0]["peer"] == "rpc-peer"
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_peers",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["summary"]["msgs_tx"] == 1
        # /metrics carries the new p2p families, sampled from the
        # registered ledger
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("cometbft_p2p_peer_msgs_total",
                    "cometbft_p2p_send_queue_full_drops_total",
                    "cometbft_p2p_send_blocked_puts_total",
                    "cometbft_p2p_link_drops_total",
                    "cometbft_p2p_injected_faults_total",
                    "cometbft_p2p_duplicate_votes_total",
                    "cometbft_p2p_ping_rtt_ms",
                    "cometbft_p2p_peer_ledger_peers"):
            assert fam in text, fam
        line = next(ln for ln in text.splitlines()
                    if ln.startswith(
                        'cometbft_p2p_peer_msgs_total{dir="tx"}'))
        assert float(line.split()[-1]) == 1.0
    finally:
        peerledger.clear_global_ledger(led)
        for n in nodes:
            n.stop()


def test_dump_peers_concurrent_with_switch_stop():
    """The PR-13 dump-route pattern: threads hammer /dump_peers WHILE
    a real switch pair (plus its peers) stops — no crash, every
    response well-formed, post-stop history still served."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.switch import Reactor, Switch

    class Chan(Reactor):
        def __init__(self):
            super().__init__("CHAN")

        def channel_descriptors(self):
            return [ChannelDescriptor(0x7E)]

    ka = NodeKey(PrivKey.generate(b"\x6a" * 32))
    kb = NodeKey(PrivKey.generate(b"\x6b" * 32))
    sa, sb = Switch(ka, "zpeer-ham"), Switch(kb, "zpeer-ham")
    sa.add_reactor(Chan())
    sb.add_reactor(Chan())
    addr_a = sa.listen()
    sa.start()
    sb.start()
    stop_ev = threading.Event()
    errors = []
    responses = [0]
    try:
        sb.dial_peer(addr_a, persistent=False)
        deadline = time.time() + 10
        while sa.num_peers() < 1 or sb.num_peers() < 1:
            assert time.time() < deadline, "peers never connected"
            time.sleep(0.02)

        def hammer():
            while not stop_ev.is_set():
                try:
                    for led in (sa.peer_ledger, sb.peer_ledger):
                        json.dumps(led.dump())
                    json.dumps(peerledger.dump_peers())
                    responses[0] += 1
                except Exception as e:  # noqa: BLE001 - the assertion
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        sa.stop()
        sb.stop()
        stop_ev.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:3]
        assert responses[0] > 0
    finally:
        stop_ev.set()
        if sa.is_running():
            sa.stop()
        if sb.is_running():
            sb.stop()
    # history after both switches stopped: records retired, not lost
    post = peerledger.dump_peers()
    assert post["summary"]["peers_dropped"] >= 1
    assert all(p["state"] == "dropped" for p in post["peers"])


def test_peer_report_diff_detects_synthetic_regression(tmp_path,
                                                       capsys):
    """The --diff CLI path flags an injected full-drop/RTT regression
    (exit 1 under --fail-on-regression), stays quiet on identical
    dumps, and errors on a miswired gate (--fail-on-regression without
    --diff)."""
    from tools import peer_report

    led = peerledger.PeerLedger()
    for i in range(3):
        r = led.open_peer(f"p{i}", True)
        peerledger.note_sent(r, 0x22, 1000)
        peerledger.note_recv(r, 0x22, 500)
        r[peerledger._P_PINGS] = 4
        r[peerledger._P_RTT] = 1.5
    dump = led.dump()
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump))
    doctored = copy.deepcopy(dump)
    for p in doctored["peers"]:
        p["full_drops"] += 50
        p["blocked_puts"] += 20
        p["rtt_ms"] += 40.0
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(doctored))

    rc = peer_report.main([str(a_path), str(a_path), "--diff",
                           "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = peer_report.main([str(a_path), str(b_path), "--diff",
                           "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "full_drops" in out
    assert "rtt_p50_ms" in out
    with pytest.raises(SystemExit):
        peer_report.main([str(a_path), "--fail-on-regression"])
    # the single-dump report renders the per-peer table
    capsys.readouterr()
    assert peer_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "p0" in out and "totals:" in out


def test_peer_ledger_message_budget():
    """ISSUE 14 acceptance: < 10 us per message with tracing OFF (best
    of 3 to dodge 1-core scheduler spikes; typical is < 1 us)."""
    import bench

    rows = [bench.peer_ledger_bookkeeping_us(k=5_000)
            for _ in range(3)]
    best_send = min(r["send_us_per_msg"] for r in rows)
    best_recv = min(r["recv_us_per_msg"] for r in rows)
    assert best_send < 10.0, f"send bookkeeping {best_send} us"
    assert best_recv < 10.0, f"recv bookkeeping {best_recv} us"
    # allocation-free in the FlushLedger sense on a warmed channel
    assert min(r["steady_alloc_blocks_per_msg"] for r in rows) < 0.5


def test_no_jax_import():
    """Host-only contract: nothing in this file (peer ledger, real
    switches, RPC, peer_report, the bench helper) may pull jax into
    the process."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
