"""Template row packing (the zero-copy verify hot path): property-style
byte-equality of the vectorized patch paths against the legacy per-vote
encoders, across fuzzed heights/rounds/timestamps/BlockIDs/chain ids.

Host-only numpy — no kernels, no compiles (tier-1 friendly)."""
import random

import numpy as np
import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.types import canonical
from cometbft_tpu.types import validation as tv
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import sign_bytes_template

# timestamps chosen to cross every varint width boundary, including the
# zero-skipping cases and the 10-byte two's-complement negatives
FUZZ_SECS = [0, 1, 127, 128, 16383, 16384, 1_700_000_000, 2**31 - 1,
             2**31, 2**40, 2**62, -1, -2**33]
FUZZ_NANOS = [0, 1, 127, 128, 999_999_999, 5, 42, -7]


def _bids():
    return [
        None,
        BlockID(),
        BlockID(b"\xab" * 32, PartSetHeader(2, b"\xcd" * 32)),
        BlockID(b"\x00" * 32, PartSetHeader(1, b"\x11" * 32)),
    ]


def test_patch_rows_matches_canonical_vote_bytes_fuzzed():
    """The acceptance property: template-packed rows are byte-identical
    to per-vote canonical_vote_bytes for every fuzzed combination —
    including chain ids sized to push the outer length prefix across
    the 127/128 one-vs-two-byte varint boundary."""
    rng = random.Random(1234)
    chains = ["a", "zero-copy-chain", "c" * 49, "q" * 107, "w" * 120]
    checked = 0
    for chain in chains:
        for bid in _bids():
            for vote_type in (canonical.PREVOTE_TYPE,
                              canonical.PRECOMMIT_TYPE):
                h = rng.choice([0, 1, 4096, 2**31, 2**62 - 1])
                r = rng.choice([0, 1, 255])
                tmpl = sign_bytes_template(chain, vote_type, h, r, bid)
                secs = [rng.choice(FUZZ_SECS) for _ in range(24)]
                nanos = [rng.choice(FUZZ_NANOS) for _ in range(24)]
                rows = tmpl.patch_rows(secs, nanos)
                lst = rows.tolist()
                for i, (s, nn) in enumerate(zip(secs, nanos)):
                    exp = canonical.canonical_vote_bytes(
                        chain, vote_type, h, r, bid, Timestamp(s, nn)
                    )
                    assert rows.row(i) == exp, (chain, bid, h, r, s, nn)
                    assert lst[i] == exp
                    checked += 1
    assert checked >= 500


def test_delta_rows_roundtrip_matches_patch_rows_fuzzed():
    """ISSUE 19: the per-row delta payload (what a stamped flush ships
    to the device — 80 B/row instead of full packed rows) must expand
    back to EXACTLY the patch_rows bytes, for every varint width
    boundary, both vote types, and nil/real BlockIDs. Also pins the
    wire layout: ts_words() is (secs_lo u32-view, secs_hi, nanos) as
    int32 — the device stamping prologue decodes exactly this."""
    rng = random.Random(919)
    checked = 0
    for chain in ("d", "delta-chain", "y" * 96):
        for bid in _bids():
            for vote_type in (canonical.PREVOTE_TYPE,
                              canonical.PRECOMMIT_TYPE):
                h = rng.choice([1, 4096, 2**62 - 1])
                tmpl = sign_bytes_template(chain, vote_type, h, 1, bid)
                secs = FUZZ_SECS + [rng.choice(FUZZ_SECS)
                                    for _ in range(8)]
                nanos = (FUZZ_NANOS * 3)[:len(secs)]
                dr = tmpl.delta_rows(secs, nanos)
                assert dr.stampable()
                got, ref = dr.expand(), tmpl.patch_rows(secs, nanos)
                for i in range(len(secs)):
                    assert got.row(i) == ref.row(i), (chain, bid, i)
                    checked += 1
                w = np.asarray(dr.ts_words())
                assert w.shape == (len(secs), 3) and w.dtype == np.int32
                sa = np.asarray(secs, np.int64)
                np.testing.assert_array_equal(
                    w[:, 0],
                    (sa & 0xFFFFFFFF).astype(np.uint32).view(np.int32))
                np.testing.assert_array_equal(
                    w[:, 1], (sa >> 32).astype(np.int32))
                np.testing.assert_array_equal(
                    w[:, 2], np.asarray(nanos, np.int32))
                # the shipped payload really is delta-sized: ts words +
                # nothing per-row from the template body
                assert dr.nbytes < len(ref.row(0)) * len(secs)
    assert checked >= 500


def _stamp_fixture(n=16, seed=7777):
    """n signed precommit rows over one template, every FUZZ edge
    timestamp represented, plus the host-packed reference rows and the
    staged delta buffers (dsig/dts/dflags with zeroed dead lanes)."""
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek

    rng = random.Random(seed)
    privs = [PrivKey.generate(bytes([160 + i]) * 32) for i in range(n)]
    pubs = [p.pub_key().data for p in privs]
    bid = BlockID(b"\x23" * 32, PartSetHeader(5, b"\x34" * 32))
    chain, h, r = "stamp-chain", 77, 1
    tmpl = sign_bytes_template(chain, canonical.PRECOMMIT_TYPE, h, r,
                               bid)
    secs = list(FUZZ_SECS) + [rng.choice(FUZZ_SECS)
                              for _ in range(n - len(FUZZ_SECS))]
    nanos = (FUZZ_NANOS * ((n + 7) // 8))[:n]
    msgs = [canonical.canonical_vote_bytes(
        chain, canonical.PRECOMMIT_TYPE, h, r, bid, Timestamp(s, nn))
        for s, nn in zip(secs, nanos)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]

    B = ec.pad_rows(n)
    thresh = ek.threshold_limbs(101)
    counted = np.zeros(B, np.bool_)
    counted[:n] = True
    cids = np.zeros(B, np.int32)
    pb = ek.pack_batch(pubs, msgs, sigs, pad_to=B)
    ref = np.asarray(ec.pack_rows_cached(pb, counted, cids, thresh))

    ent = ec.template_entry([tmpl.stamp_site()])
    sec_a = np.asarray(secs, np.int64)
    dsig = np.zeros((B, 64), np.uint8)
    dsig[:n] = np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64)
    dts = np.zeros((B, 3), np.int32)
    dts[:n, 0] = (sec_a & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    dts[:n, 1] = (sec_a >> 32).astype(np.int32)
    dts[:n, 2] = np.asarray(nanos, np.int32)
    dfl = np.zeros((B,), np.int32)
    dfl[:n] = 3  # live | counted, template 0, commit 0
    return pubs, B, thresh, ref, ent, dsig, dts, dfl


def test_stamp_rows_device_matches_host_pack():
    """ISSUE 19 acceptance: stamp_rows_cached — the device prologue
    that assembles sign-bytes rows from (template, per-row deltas) —
    is BIT-IDENTICAL to the host pack_rows_cached output for the same
    flush, across fuzzed varint-boundary timestamps, including the
    zero dead lanes a rotated staging buffer ships. CPU XLA (tier-1):
    the prologue only consumes the table's pub_raw matrix, so a stub
    table keeps this under the tier-1 clock — the slow sibling runs
    the REAL table + fused verify end to end."""
    pytest.importorskip("jax")
    from types import SimpleNamespace

    from cometbft_tpu.ops import ed25519_cached as ec

    pubs, B, thresh, ref, ent, dsig, dts, dfl = _stamp_fixture()
    table = SimpleNamespace(pub_raw=ec._pub_raw(pubs, B))
    got = np.asarray(ec.stamp_rows_cached(
        dsig, dts, dfl, ent, table, 1, thresh))
    np.testing.assert_array_equal(got, ref)


def test_delta_donation_still_noop():
    """ISSUE 19 satellite: donate_argnums RE-EVALUATED on the staged
    delta buffers. Structural verdict: no output aval of the stamping
    prologue matches any delta input aval — the rows output is
    (R, B) int32 while dsig is (B, 64) uint8, dts (B, 3) int32 and
    dflags (B,) int32 — so XLA cannot alias a donated delta buffer
    into the output and donation stays a NO-OP; staging turnover
    remains the host-side pool rotation (README "Zero-copy hot
    path"). The empirical half jits the same prologue WITH donation
    and proves XLA merely warns the donated buffers were unusable
    while the output stays bit-identical."""
    pytest.importorskip("jax")
    import warnings
    from types import SimpleNamespace

    import jax
    import jax.numpy as jnp

    from cometbft_tpu.ops import ed25519_cached as ec

    pubs, B, thresh, ref, ent, dsig, dts, dfl = _stamp_fixture()
    table = SimpleNamespace(pub_raw=ec._pub_raw(pubs, B))
    for a in (dsig, dts, dfl):  # the structural reason, kept honest
        assert not (a.shape == ref.shape and a.dtype == np.int32)

    donated = jax.jit(ec._stamp_rows_core,
                      static_argnames=("msg_max", "t_rows"),
                      donate_argnums=(0, 1, 2))
    t_rows = ec.packed_rows_shape(B, 1)[0] - ec.V_THRESH
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got = np.asarray(donated(
            jnp.asarray(dsig), jnp.asarray(dts), jnp.asarray(dfl),
            ent.pre_mat, ent.pre_len, ent.suf_mat, ent.suf_len,
            ent.ts_tag, table.pub_raw,
            jnp.asarray(np.asarray(thresh, np.int32)),
            msg_max=ent.msg_max, t_rows=t_rows))
    np.testing.assert_array_equal(got, ref)
    assert any("donat" in str(w.message).lower() for w in caught), \
        [str(w.message) for w in caught]


@pytest.mark.slow
def test_stamp_verify_delta_matches_host_pack_real_table():
    """Slow sibling of the stamp byte-equality test: the REAL valset
    table (pub_raw present by default) and the fused delta verify —
    verdicts and tallies bit-equal to the host-packed kernel, rows
    never leaving the device between stamp and verify."""
    pytest.importorskip("jax")
    import jax

    from cometbft_tpu.ops import ed25519_cached as ec

    n = 16
    pubs, B, thresh, ref, ent, dsig, dts, dfl = _stamp_fixture(n)
    table = ec.table_for_pubs(pubs)
    assert table.pub_raw is not None  # stamping-aware by default
    got = np.asarray(ec.stamp_rows_cached(
        dsig, dts, dfl, ent, table, 1, thresh))
    np.testing.assert_array_equal(got, ref)
    v_ref = ec.verify_tally_rows_cached(jax.device_put(ref), table, 1)
    v_got = ec.verify_tally_delta_cached(
        dsig, dts, dfl, ent, table, 1, thresh)
    np.testing.assert_array_equal(np.asarray(v_got[0]),
                                  np.asarray(v_ref[0]))
    assert np.asarray(v_got[0])[:n].all()
    np.testing.assert_array_equal(np.asarray(v_got[1]),
                                  np.asarray(v_ref[1]))


@pytest.mark.slow
def test_stamp_rows_device_matches_host_pack_wide():
    """Slow sibling: every FUZZ_SECS x FUZZ_NANOS cross product, two
    templates in one flush (tmpl_id bits live), nil BlockID — the
    multi-site stamp path cfg19 drives at 10k rows."""
    pytest.importorskip("jax")
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek

    combos = [(s, nn) for s in FUZZ_SECS for nn in FUZZ_NANOS]
    n = len(combos)  # 104
    privs = [PrivKey.generate((900 + i).to_bytes(2, "big") * 16)
             for i in range(n)]
    pubs = [p.pub_key().data for p in privs]
    chain, r = "stamp-wide", 0
    bids = [None, BlockID(b"\x55" * 32, PartSetHeader(9, b"\x66" * 32))]
    tmpls = [sign_bytes_template(chain, canonical.PRECOMMIT_TYPE,
                                 1000 + t, r, bids[t])
             for t in range(2)]
    msgs, sigs, tids = [], [], []
    for i, (s, nn) in enumerate(combos):
        t = i % 2
        tids.append(t)
        msgs.append(canonical.canonical_vote_bytes(
            chain, canonical.PRECOMMIT_TYPE, 1000 + t, r, bids[t],
            Timestamp(s, nn)))
        sigs.append(privs[i].sign(msgs[-1]))

    B = ec.pad_rows(n)
    thresh = ek.threshold_limbs(3)
    counted = np.zeros(B, np.bool_)
    counted[:n] = True
    cids = np.zeros(B, np.int32)
    pb = ek.pack_batch(pubs, msgs, sigs, pad_to=B)
    ref = np.asarray(ec.pack_rows_cached(pb, counted, cids, thresh))

    table = ec.table_for_pubs(pubs)
    ent = ec.template_entry([t.stamp_site() for t in tmpls])
    sec_a = np.asarray([s for s, _ in combos], np.int64)
    dsig = np.zeros((B, 64), np.uint8)
    dsig[:n] = np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64)
    dts = np.zeros((B, 3), np.int32)
    dts[:n, 0] = (sec_a & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    dts[:n, 1] = (sec_a >> 32).astype(np.int32)
    dts[:n, 2] = np.asarray([nn for _, nn in combos], np.int32)
    dfl = np.zeros((B,), np.int32)
    dfl[:n] = 3 | (np.asarray(tids, np.int32) << 2)
    got = np.asarray(ec.stamp_rows_cached(
        dsig, dts, dfl, ent, table, 1, thresh))
    np.testing.assert_array_equal(got, ref)


def test_patch_rows_empty_and_singleton():
    tmpl = sign_bytes_template("c", canonical.PRECOMMIT_TYPE, 3, 0, None)
    assert tmpl.patch_rows([], []).tolist() == []
    one = tmpl.patch_rows([7], [0])
    assert one.row(0) == canonical.canonical_vote_bytes(
        "c", canonical.PRECOMMIT_TYPE, 3, 0, None, Timestamp(7, 0)
    )


def _fixture_commit(n=12, height=9, round_=2, seed=50):
    privs = [PrivKey.generate(bytes([seed + i]) * 32) for i in range(n)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\x77" * 32, PartSetHeader(3, b"\x88" * 32))
    sigs = []
    for idx, v in enumerate(vs.validators):
        if idx == 4:
            sigs.append(CommitSig(BLOCK_ID_FLAG_ABSENT))
            continue
        nil = idx == 7
        ts = Timestamp(1_700_000_000 + idx * 129, idx * 1000)
        sb = canonical.canonical_vote_bytes(
            "tmpl-chain", canonical.PRECOMMIT_TYPE, height, round_,
            None if nil else bid, ts,
        )
        sigs.append(CommitSig(
            BLOCK_ID_FLAG_NIL if nil else BLOCK_ID_FLAG_COMMIT,
            v.address, ts, by[v.address].sign(sb),
        ))
    return vs, Commit(height, round_, bid, sigs), bid


def test_commit_sign_bytes_rows_matches_per_vote():
    """Commit.sign_bytes_rows (mixed for-block / nil / absent rows) is
    byte-equal to the legacy vote_sign_bytes loop, over any index
    subset and in subset order."""
    _, commit, _ = _fixture_commit()
    n = len(commit.signatures)
    all_idx = list(range(n))
    assert commit.sign_bytes_rows("tmpl-chain", all_idx) == [
        commit.vote_sign_bytes("tmpl-chain", i) for i in all_idx
    ]
    sub = [7, 1, 11, 3]
    assert commit.sign_bytes_rows("tmpl-chain", sub) == [
        commit.vote_sign_bytes("tmpl-chain", i) for i in sub
    ]
    # a different chain id invalidates the cached templates
    assert commit.sign_bytes_rows("other", [1]) == [
        commit.vote_sign_bytes("other", 1)
    ]


def test_verify_commit_template_toggle_equivalence():
    """verify_commit passes with the oracle batch_fn under BOTH packing
    paths, and a wrong-signature commit is blamed identically — the
    toggle must never change behavior (simnet determinism guard's
    local half)."""
    vs, commit, bid = _fixture_commit()
    for on in (True, False):
        prev = tv.set_template_packing(on)
        try:
            tv.verify_commit("tmpl-chain", vs, bid, 9, commit,
                             batch_fn=tv.oracle_batch_fn())
            bad = Commit(commit.height, commit.round, commit.block_id,
                         list(commit.signatures))
            cs = bad.signatures[2]
            bad.signatures[2] = CommitSig(cs.flag, cs.validator_address,
                                          cs.timestamp, b"\x5a" * 64)
            with pytest.raises(tv.InvalidSignatureError) as ei:
                tv.verify_commit("tmpl-chain", vs, bid, 9, bad,
                                 batch_fn=tv.oracle_batch_fn())
            assert ei.value.idx == 2
        finally:
            tv.set_template_packing(prev)


def test_commit_packed_batch_matches_pack_batch():
    """The zero-copy staging path (native template pack when available,
    numpy template fallback otherwise) produces the exact arrays of the
    legacy msgs+pack_batch pipeline."""
    from cometbft_tpu.ops import ed25519_kernel as ek

    vs, commit, bid = _fixture_commit()
    keys = [v.pub_key.data for v in vs.validators]
    pb, idxs = tv.commit_packed_batch("tmpl-chain", commit, keys)
    assert idxs == [i for i, cs in enumerate(commit.signatures)
                    if cs.for_block()]
    msgs = [commit.vote_sign_bytes("tmpl-chain", i) for i in idxs]
    ref = ek.pack_batch([keys[i] for i in idxs], msgs,
                        [commit.signatures[i].signature for i in idxs],
                        pad_to=pb.padded)
    for name in ("ay", "asign", "ry", "rsign", "sdig", "hdig",
                 "precheck"):
        np.testing.assert_array_equal(
            np.asarray(getattr(pb, name)), np.asarray(getattr(ref, name)),
            err_msg=name,
        )


def test_pack_rows_cached_out_buffer_parity():
    """pack_rows_cached into a rotated (zeroed) staging buffer is
    bit-identical to the allocating path, including threshold rows and
    dead padding — the double-buffer must never leak a previous
    flush's rows."""
    from cometbft_tpu.libs.staging import StagingPool
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek

    vs, commit, bid = _fixture_commit()
    keys = [v.pub_key.data for v in vs.validators]
    pb, idxs = tv.commit_packed_batch("tmpl-chain", commit, keys,
                                      pad_to=128)
    counted = np.zeros(128, np.bool_)
    counted[: len(idxs)] = True
    cids = np.zeros(128, np.int32)
    thresh = ek.threshold_limbs(77)
    ref = ec.pack_rows_cached(pb, counted, cids, thresh)
    pool = StagingPool(slots=2)
    a = pool.get("rows", ref.shape, np.int32)
    a[:] = -1  # dirty slot A, rotate past it so the pool re-zeroes
    pool.get("rows", ref.shape, np.int32)
    out = pool.get("rows", ref.shape, np.int32)
    assert out is a
    got = ec.pack_rows_cached(pb, counted, cids, thresh, out=out)
    assert got is out
    np.testing.assert_array_equal(got, ref)
    # a mismatched out buffer is ignored, not corrupted
    wrong = np.full((ref.shape[0] + 1, ref.shape[1]), 3, np.int32)
    got2 = ec.pack_rows_cached(pb, counted, cids, thresh, out=wrong)
    assert got2 is not wrong
    np.testing.assert_array_equal(got2, ref)


def test_table_for_valset_identity_memo(monkeypatch):
    """ed25519_cached.table_for_valset: memoized by ValidatorSet
    identity, invalidated when update_with_change_set replaces the
    validators list (the only mutation that can change keys/powers).
    The underlying build is stubbed — no device table on CPU."""
    from cometbft_tpu.ops import ed25519_cached as ec

    calls = []

    def fake_table_for_pubs(pubs, powers=None):
        calls.append((pubs, powers))
        return "TBL%d" % len(calls)

    monkeypatch.setattr(ec, "table_for_pubs", fake_table_for_pubs)
    vs, _, _ = _fixture_commit()
    ec._VALSET_MEMO.clear()
    try:
        t1 = ec.table_for_valset(vs)
        t2 = ec.table_for_valset(vs)
        assert t1 is t2 and len(calls) == 1
        st = ec.table_cache_stats()
        assert st["valset_hits"] >= 1
        # a wholesale validators-list replacement (what
        # update_with_change_set does) must invalidate the memo
        vs.validators = list(vs.validators)
        ec.table_for_valset(vs)
        assert len(calls) == 2
    finally:
        ec._VALSET_MEMO.clear()


def test_packed_rows_shape_matches_pack_rows_cached():
    """The staging-buffer sizing helper agrees with what
    pack_rows_cached actually builds, across thresh widths."""
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek

    vs, commit, _ = _fixture_commit()
    keys = [v.pub_key.data for v in vs.validators]
    pb, idxs = tv.commit_packed_batch("tmpl-chain", commit, keys,
                                      pad_to=128)
    for n_commits in (1, 3, 64):
        thresh = np.zeros((n_commits, ek.TALLY_LIMBS), np.int32)
        rows = ec.pack_rows_cached(pb, None, None, thresh)
        assert rows.shape == ec.packed_rows_shape(128, n_commits)


def test_staging_pool_rotation_and_reuse():
    """libs/staging: two slots per shape rotate; a third request
    returns the first buffer again, zeroed."""
    from cometbft_tpu.libs.staging import StagingPool

    p = StagingPool(slots=2)
    a = p.get("rows", (3, 4), np.int32)
    a[:] = 9
    b = p.get("rows", (3, 4), np.int32)
    assert b is not a
    c = p.get("rows", (3, 4), np.int32)
    assert c is a and (c == 0).all()
    # distinct shapes/names never alias
    d = p.get("rows", (3, 5), np.int32)
    e = p.get("other", (3, 4), np.int32)
    assert d is not a and e is not a
    st = p.stats()
    assert st["hits"] == 1 and st["misses"] == 4


def test_staging_pool_concurrent_flushes():
    """ISSUE 6 satellite: the pool under concurrent flush traffic.

    The rotation contract is one writer per KEY (each dispatcher/
    pipeline owns its buffer names), but nothing serializes DIFFERENT
    keys — the verify-plane dispatcher, blocksync's private pool
    pattern, and bench all hammer one process-global pool from their
    own threads. Each thread here rotates its own key under load and
    checks its buffer still holds its own pattern after every get
    (cross-key aliasing would corrupt it); the lock-protected counters
    must come out EXACT, not approximately."""
    import threading

    from cometbft_tpu.libs.staging import StagingPool

    slots, iters, n_threads = 2, 200, 6
    p = StagingPool(slots=slots)
    errs = []
    start = threading.Barrier(n_threads)

    def flusher(tid):
        try:
            start.wait(5)
            for i in range(iters):
                buf = p.get(f"flush.t{tid}", (16, 8), np.int32)
                if buf.any():  # zeroed on every handout
                    raise AssertionError(f"t{tid} got a dirty buffer")
                buf[:] = tid * 1000 + i
                # the buffer must still be OURS after other threads run
                # their own gets (no cross-key slot sharing)
                if not (buf == tid * 1000 + i).all():
                    raise AssertionError(f"t{tid} buffer overwritten")
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=flusher, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not errs, errs
    st = p.stats()
    # exhaustion accounting: per key exactly `slots` allocation misses,
    # every other get recycled a slot (a rotation hit)
    assert st["misses"] == n_threads * slots
    assert st["hits"] == n_threads * (iters - slots)
    assert st["shapes"] == n_threads
    assert st["resident_bytes"] == n_threads * slots * 16 * 8 * 4


def test_staging_pool_depth_tracks_flight_count():
    """ISSUE 11 satellite: under the flight deck, up to `flights`
    flushes keep their packed buffers pinned while the NEXT flush
    packs — the plane must size its private pool flights+1 deep (the
    old hardcoded 2 aliased the third concurrent pack: pack(k+2) wrote
    into the buffer flight k was still uploading from). Exact
    accounting: with depth flights+1, flights+1 outstanding buffers
    per key never alias and every rotation hit/miss is counted."""
    import threading

    from cometbft_tpu.libs.staging import StagingPool
    from cometbft_tpu.verifyplane import VerifyPlane

    # the plane wires the knob straight into its pool depth
    for flights in (1, 2, 3):
        plane = VerifyPlane(pipeline_flights=flights)
        assert plane._staging.slots == flights + 1

    flights, iters, n_threads = 2, 120, 4
    depth = flights + 1
    p = StagingPool(slots=depth)
    errs = []
    start = threading.Barrier(n_threads)

    def deck_packer(tid):
        """Hold `depth` buffers outstanding (flights airborne + the
        pack in progress) and verify none alias within the window."""
        try:
            start.wait(5)
            window = []
            for i in range(iters):
                buf = p.get(f"deck.t{tid}", (8, 4), np.int32)
                if buf.any():
                    raise AssertionError(f"t{tid} got a dirty buffer")
                buf[:] = tid * 10_000 + i
                window.append((buf, tid * 10_000 + i))
                if len(window) > depth:
                    window.pop(0)
                # every buffer still pinned under an airborne flight
                # must hold ITS flush's rows — an alias would show the
                # newest pack's pattern in an older flight's buffer
                for b, pat in window:
                    if not (b == pat).all():
                        raise AssertionError(
                            f"t{tid} airborne buffer overwritten")
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=deck_packer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not errs, errs
    st = p.stats()
    # exact accounting: per key exactly `depth` allocation misses,
    # every other get a rotation hit, footprint capped at depth x shape
    assert st["misses"] == n_threads * depth
    assert st["hits"] == n_threads * (iters - depth)
    assert st["resident_bytes"] == n_threads * depth * 8 * 4 * 4


def test_staging_pool_exhaustion_aliases_oldest():
    """More outstanding buffers than slots is the documented hazard:
    request slots+1 of one key while all are 'in flight' and the pool
    recycles the OLDEST — callers must be done writing before asking
    for `slots` more. The stats make the exhaustion visible (hits move
    while misses stay at the slot count)."""
    from cometbft_tpu.libs.staging import StagingPool

    p = StagingPool(slots=3)
    outstanding = [p.get("x", (4,), np.int64) for _ in range(3)]
    assert p.stats()["misses"] == 3 and p.stats()["hits"] == 0
    again = p.get("x", (4,), np.int64)  # exhausted: recycles slot 0
    assert again is outstanding[0]
    assert p.stats()["hits"] == 1 and p.stats()["misses"] == 3
    # resident footprint never grows past slots x shape
    assert p.stats()["resident_bytes"] == 3 * 4 * 8
    assert p.nbytes() == 3 * 4 * 8
