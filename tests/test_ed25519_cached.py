"""Differential tests: cached-valset ed25519 path vs oracle.

The cached path (ops.ed25519_cached) must be bit-for-bit equivalent to
the pure-Python ZIP-215 oracle — the per-validator window tables and
the in-kernel entry select are a pure re-layout of h*(-A), so any
divergence is a consensus fork.

RUNS ON THE REAL TPU ONLY (CBT_TEST_ON_TPU=1): the round-5 kernel
keeps its valset table block in VMEM via a BlockSpec index_map, and
the Pallas INTERPRET path for that shape compiles for multiple HOURS
on this 1-core CPU host (measured; Mosaic compiles the same kernel in
~90 s). CPU coverage of the surrounding bookkeeping lives in
test_ed25519_cached_host.py; the kernel itself is exercised on TPU by
these tests, by `python tools/tpu_differential.py`, and by every
bench.py run (which asserts correctness before timing).
"""
import os

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_cached as ec
from cometbft_tpu.ops import ed25519_kernel as k

pytestmark = pytest.mark.skipif(
    not os.environ.get("CBT_TEST_ON_TPU"),
    reason="pallas-interpret compile of the in-kernel-gather kernel "
           "takes hours on CPU; set CBT_TEST_ON_TPU=1 (Mosaic ~90s). "
           "TPU coverage: tools/tpu_differential.py + bench.py asserts.",
)


def make_sigs(n, msg_fn=lambda i: b"msg-%d" % i):
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [msg_fn(i) for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def test_cached_mixed_batch_vs_oracle():
    """Valid rows, tampered sig, tampered msg, S>=L malleability, bad
    pubkey — all against the oracle, one batch."""
    pubs, msgs, sigs = make_sigs(8)
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    msgs[5] = msgs[5] + b"tampered"
    sigs[6] = sigs[6][:32] + int.to_bytes(
        int.from_bytes(sigs[6][32:], "little") + ed.L, 32, "little"
    )
    pubs[7] = b"\xff" * 32
    got = ec.verify_batch_cached(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert got[0] and not got[2] and not got[5] and not got[6] \
        and not got[7]


def test_cached_zip215_edges():
    """Non-canonical y, small-order identity, -0 sign — the cached
    table build decompresses A exactly like the oracle."""
    ident = ed.pt_compress(ed.IDENT)
    cases = [(ident, b"m", ident + b"\x00" * 32)]
    for y in range(19):
        u, v = (y * y - 1) % ed.P, (ed.D * y * y + 1) % ed.P
        ok, x = ed._sqrt_ratio(u, v)
        if ok:
            enc_nc = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32,
                                  "little")
            break
    seed = bytes(32)
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, b"x")
    cases.append((pub, b"x", enc_nc + sig[32:]))  # non-canonical R
    cases.append((enc_nc, b"x", sig))             # non-canonical A
    neg_zero = int.to_bytes(1 | (1 << 255), 32, "little")
    cases.append((neg_zero, b"m", neg_zero + b"\x00" * 32))
    pubs, msgs, sigs = (list(z) for z in zip(*cases))
    got = ec.verify_batch_cached(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in cases]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert any(exp)


def test_cached_table_lru():
    pubs, msgs, sigs = make_sigs(3)
    t1 = ec.table_for_pubs(pubs)
    t2 = ec.table_for_pubs(pubs)
    assert t1 is t2  # LRU hit
    # order matters: the validator index is the key into the table
    t3 = ec.table_for_pubs(list(reversed(pubs)))
    assert t3 is not t1
    got = ec.verify_batch_cached(
        list(reversed(pubs)), list(reversed(msgs)), list(reversed(sigs)),
        table=t3,
    )
    assert got.all()


def test_cached_multi_commit_stride_tally():
    """Two commits of the same 64-val set packed at the table stride M:
    per-commit tallies and quorums come out right, including an invalid
    row in commit 1 only."""
    pubs, msgs, sigs = make_sigs(64)
    table = ec.table_for_pubs(pubs, [5] * 64)
    M = table.n_vals
    assert M == 128
    B = 2 * M  # commit c occupies rows [c*M, c*M + 64)
    pubs2 = (pubs + [b""] * (M - 64)) * 2
    msgs2 = (msgs + [b""] * (M - 64)) * 2
    sig_rows = (sigs + [b""] * (M - 64)) * 2
    sig_rows[M + 7] = b"\x01" * 64  # bad sig in commit 1 at val 7
    pb = k.pack_batch(pubs2, msgs2, sig_rows, pad_to=B)
    counted = np.zeros(B, np.bool_)
    cids = np.zeros(B, np.int32)
    for c in range(2):
        counted[c * M:c * M + 64] = True
        cids[c * M:c * M + 64] = c
    thresh = k.threshold_limbs(64 * 5 * 2 // 3, n_commits=2)
    rows = ec.pack_rows_cached(pb, counted, cids, thresh)
    valid, tally, quorum = ec.verify_tally_rows_cached(rows, table, 2)
    valid = np.asarray(valid)
    assert valid[:64].all()
    assert valid[M:M + 64].sum() == 63 and not valid[M + 7]
    t = k.tally_to_int(np.asarray(tally))
    assert t[0] == 64 * 5 and t[1] == 63 * 5
    q = np.asarray(quorum)
    assert bool(q[0]) and bool(q[1])


def test_stream_verifier_cached_strided_path():
    """StreamVerifier with use_pallas=True routes same-valset chunks
    through the strided cached-table pack; blame and quorum still match
    the dense path. (B=256 — shares the stride test's compile.)"""
    from cometbft_tpu.blocksync.pipeline import CommitJob, StreamVerifier
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validation import InvalidSignatureError
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    privs = [PrivKey.generate(bytes([60 + i]) * 32) for i in range(64)]
    vs = ValidatorSet([Validator(p.pub_key(), 9) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    jobs = []
    for h in (1, 2):
        bid = BlockID(bytes([h]) * 32, PartSetHeader(1, b"\x0f" * 32))
        sigs = []
        for v in vs.validators:
            ts = Timestamp(1_700_000_000 + h, 0)
            sb = canonical.canonical_vote_bytes(
                "sv-chain", canonical.PRECOMMIT_TYPE, h, 0, bid, ts
            )
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        jobs.append(CommitJob(vs, bid, h, Commit(h, 0, bid, sigs),
                              "sv-chain"))
    # corrupt one signature in the second commit
    jobs[1].commit.signatures[11].signature = b"\x02" * 64
    sv = StreamVerifier(use_pallas=True, max_sigs=256,
                        min_device_sigs=2)
    table = sv._cached_table([(0, jobs[0]), (1, jobs[1])])
    assert table is not None and table.n_vals == 128
    res = sv.verify(jobs)
    assert res[0] is None
    assert isinstance(res[1], InvalidSignatureError) and res[1].idx == 11


def test_pad_rows_buckets():
    assert ec.pad_rows(1) == 128
    assert ec.pad_rows(129) == 256
    assert ec.pad_rows(2049) == 4096
    assert ec.pad_rows(5000) == 6144
    assert ec.pad_rows(10_000) == 10_240
    with pytest.raises(ValueError):
        ec.pad_rows(70_000)


def test_incremental_update_matches_rebuild():
    """Valset churn (types/validator_set.go:589-651 updateWithChangeSet):
    update_table on a small delta must verify exactly like a fresh
    build — changed slots verify new keys' sigs, old keys' sigs against
    changed slots now fail, untouched slots unaffected. Also covers a
    slot changed to garbage (ok=False)."""
    pubs, msgs, sigs = make_sigs(128)
    table = ec.table_for_pubs(pubs, [7] * 128)

    new_seeds = {3: b"\xaa" * 32, 77: b"\xbb" * 32, 120: b"\xcc" * 32}
    pubs2 = list(pubs)
    msgs2 = list(msgs)
    sigs2 = list(sigs)
    for i, s in new_seeds.items():
        pubs2[i] = ed.pubkey_from_seed(s)
        sigs2[i] = ed.sign(s, msgs[i])
    pubs2[9] = b"\x00" * 31  # bad length -> slot must go dead

    changes = [(i, pubs2[i]) for i in (3, 9, 77, 120)]
    t2 = ec.update_table(table, changes, {3: 9})
    got = ec.verify_batch_cached(pubs2, msgs2, sigs2, table=t2)
    exp = [ed.verify(p, m, s) if len(p) == 32 else False
           for p, m, s in zip(pubs2, msgs2, sigs2)]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert got[3] and got[77] and got[120] and not got[9]
    # old signature against a replaced slot must now fail
    got_old = ec.verify_batch_cached(pubs2, msgs, sigs, table=t2)
    assert not got_old[3] and got_old[0]
    # power updated only where asked
    p5 = np.asarray(t2.power5)
    assert k.tally_to_int(p5[3]) == 9 and k.tally_to_int(p5[4]) == 7
    # the original table is untouched (functional update)
    assert ec.verify_batch_cached(pubs, msgs, sigs, table=table).all()


def test_table_for_pubs_near_miss_incremental():
    """A changed valset list hits the near-miss path (no full rebuild)
    and still verifies correctly under the new key list."""
    pubs, msgs, sigs = make_sigs(128, msg_fn=lambda i: b"nm-%d" % i)
    powers = list(range(1, 129))
    t1 = ec.table_for_pubs(pubs, powers)
    s = b"\xdd" * 32
    pubs2 = list(pubs)
    pubs2[50] = ed.pubkey_from_seed(s)
    sigs2 = list(sigs)
    sigs2[50] = ed.sign(s, msgs[50])
    powers2 = list(powers)
    powers2[50] = 1000
    t2 = ec.table_for_pubs(pubs2, powers2)
    assert t2 is not t1
    got = ec.verify_batch_cached(pubs2, msgs, sigs2, table=t2)
    assert got.all()
    assert k.tally_to_int(np.asarray(t2.power5)[50]) == 1000
    # second lookup is a plain LRU hit
    assert ec.table_for_pubs(pubs2, powers2) is t2


def test_near_miss_large_valset_power_delta():
    """Near-miss churn on a >128-slot valset must take the incremental
    path without tripping the update budget (the review-found crash:
    a full per-validator power map blew UPDATE_PAD), and only changed
    powers may ride the update."""
    pubs, msgs, sigs = make_sigs(130, msg_fn=lambda i: b"lg-%d" % i)
    powers = [3] * 130
    t1 = ec.table_for_pubs(pubs, powers)
    assert t1.n_vals == 256  # padded beyond one lane tile

    s = b"\xee" * 32
    pubs2 = list(pubs)
    pubs2[129] = ed.pubkey_from_seed(s)
    sigs2 = list(sigs)
    sigs2[129] = ed.sign(s, msgs[129])
    powers2 = list(powers)
    powers2[7] = 99  # power-only change on an untouched slot
    t2 = ec.table_for_pubs(pubs2, powers2)
    assert t2 is not t1
    # powers_host proves the incremental path ran (a rebuild would
    # also satisfy verification, so check the delta bookkeeping)
    assert t2.powers_host[7] == 99 and t2.powers_host[129] == 3
    assert t2.powers_host[0] == 3
    got = ec.verify_batch_cached(pubs2, msgs, sigs2, table=t2)
    assert got.all()

    # a delta larger than UPDATE_PAD falls back to a full rebuild
    # rather than raising (ValueError is caught in table_for_pubs)
    pubs3 = [ed.pubkey_from_seed(bytes([i % 251, 9]) + b"\x31" * 30)
             for i in range(130)]
    t3 = ec.table_for_pubs(pubs3, powers)
    assert t3 is not t2 and t3.n_vals == 256


def test_warm_incremental_byte_identical_to_cold_build():
    """The warmer's incremental patch must be indistinguishable from
    the full next-epoch build: every device/host array of the patched
    table equals the cold build_table result byte-for-byte."""
    pubs, _, _ = make_sigs(8, msg_fn=lambda i: b"wi-%d" % i)
    powers = list(range(1, 9))
    ec.table_for_pubs(pubs, powers)  # the base epoch's table
    s = b"\xcf" * 32
    pubs2 = list(pubs)
    pubs2[3] = ed.pubkey_from_seed(s)
    powers2 = list(powers)
    powers2[3] = 77
    key2 = tuple(pubs2)
    assert ec.warm_incremental(key2, powers2) is True
    patched = ec.table_for_pubs(key2, powers2)  # plain LRU hit now
    cold = ec.build_table(pubs2, powers2)
    assert patched is not cold
    np.testing.assert_array_equal(np.asarray(patched.tab),
                                  np.asarray(cold.tab))
    np.testing.assert_array_equal(np.asarray(patched.ok),
                                  np.asarray(cold.ok))
    np.testing.assert_array_equal(np.asarray(patched.power5),
                                  np.asarray(cold.power5))
    assert patched.pubs_host == cold.pubs_host
    np.testing.assert_array_equal(patched.powers_host,
                                  cold.powers_host)
