"""Vote extensions end-to-end.

Reference: state/execution.go:318 (ExtendVote), :349
(VerifyVoteExtension), :472 (buildExtendedCommitInfo into
PrepareProposal), types/block.go:714-722 (ExtendedCommitSig),
store/store.go:254 (extended-commit persistence), params.go
VoteExtensionsEnableHeight discipline (required >= enable height,
forbidden below).
"""
import threading

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.params import ABCIParams, ConsensusParams
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.types.vote_set import VoteSet, VoteSetError

import pytest

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)
CHAIN = "ext-chain"


class ExtensionApp(KVStoreApplication):
    """kvstore + deterministic vote extensions; records what
    PrepareProposal received so the test can assert the hand-off."""

    def __init__(self):
        super().__init__()
        self.seen_local_last_commits = []
        self._elock = threading.Lock()

    def extend_vote(self, req: abci.RequestExtendVote):
        return abci.ResponseExtendVote(
            vote_extension=b"ext@%d" % req.height
        )

    def verify_vote_extension(self, req: abci.RequestVerifyVoteExtension):
        ok = req.vote_extension == b"ext@%d" % req.height
        return abci.ResponseVerifyVoteExtension(
            status=abci.VERIFY_VOTE_EXTENSION_ACCEPT if ok
            else abci.VERIFY_VOTE_EXTENSION_REJECT
        )

    def prepare_proposal(self, req: abci.RequestPrepareProposal):
        with self._elock:
            if req.local_last_commit is not None:
                self.seen_local_last_commits.append(
                    (req.height, req.local_last_commit)
                )
        return super().prepare_proposal(req)


def _mk_vote(priv, vs, height, round_, bid, ext=b""):
    addr = priv.pub_key().address()
    idx, _ = vs.get_by_address(addr)
    v = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=height,
             round=round_, block_id=bid,
             timestamp=Timestamp(1_700_000_000, 0),
             validator_address=addr, validator_index=idx,
             extension=ext)
    v.signature = priv.sign(v.sign_bytes(CHAIN))
    if ext:
        v.extension_signature = priv.sign(v.extension_sign_bytes(CHAIN))
    return v


def _fixture(n=4):
    privs = [PrivKey.generate(bytes([i + 21]) * 32) for i in range(n)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    return privs, vs


def test_voteset_requires_extension_when_enabled():
    privs, vs = _fixture()
    bid = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))
    vset = VoteSet(CHAIN, 5, 0, canonical.PRECOMMIT_TYPE, vs,
                   ext_enabled=True)
    # missing extension signature -> rejected
    with pytest.raises(VoteSetError, match="extension"):
        vset.add_vote(_mk_vote(privs[0], vs, 5, 0, bid))
    # forged extension signature -> rejected
    v = _mk_vote(privs[0], vs, 5, 0, bid, ext=b"data")
    v.extension_signature = b"\x01" * 64
    with pytest.raises(VoteSetError, match="extension"):
        vset.add_vote(v)
    # well-signed extension -> accepted
    assert vset.add_vote(_mk_vote(privs[0], vs, 5, 0, bid, ext=b"data"))
    # nil precommits need no extension even when enabled
    assert vset.add_vote(_mk_vote(privs[1], vs, 5, 0, BlockID()))


def test_voteset_forbids_extension_when_disabled():
    privs, vs = _fixture()
    bid = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))
    vset = VoteSet(CHAIN, 5, 0, canonical.PRECOMMIT_TYPE, vs,
                   ext_enabled=False)
    with pytest.raises(VoteSetError, match="unexpected"):
        vset.add_vote(_mk_vote(privs[0], vs, 5, 0, bid, ext=b"data"))


def test_empty_extensions_still_progress(tmp_path):
    """An app that returns EMPTY extensions (the base Application
    default) must not halt the chain: the extension signature is
    required and produced even over empty bytes."""
    privs, vs = _fixture(2)
    params = ConsensusParams(
        abci=ABCIParams(vote_extensions_enable_height=1)
    )
    state = State.make_genesis(CHAIN, vs, params=params)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), home=str(tmp_path / f"e{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    try:
        for n in nodes:
            assert n.consensus.wait_for_height(3, timeout=60), \
                f"stuck at {n.height()}"
        ec = nodes[0].block_store.load_extended_commit(2)
        assert ec is not None
        assert all(e.extension == b"" and e.extension_signature
                   for e in ec.extended_signatures
                   if e.commit_sig.is_commit())
    finally:
        for n in nodes:
            n.stop()


def test_extensions_flow_through_network(tmp_path):
    """4 validators with extensions enabled from height 1: extended
    commits are persisted with every signer's extension, and the next
    proposer hands them to PrepareProposal as local_last_commit."""
    privs, vs = _fixture()
    params = ConsensusParams(
        abci=ABCIParams(vote_extensions_enable_height=1)
    )
    state = State.make_genesis(CHAIN, vs, params=params)
    net = LocalNetwork()
    nodes, apps = [], []
    for i, priv in enumerate(privs):
        app = ExtensionApp()
        node = Node(app, state.copy(), privval=FilePV(priv),
                    home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
        apps.append(app)
    for n in nodes:
        n.start()
    try:
        for n in nodes:
            assert n.consensus.wait_for_height(4, timeout=60), \
                f"stuck at {n.height()}"
        # extended commit persisted w/ verified extensions per signer
        ec = nodes[0].block_store.load_extended_commit(2)
        assert ec is not None
        n_with_ext = 0
        for i, e in enumerate(ec.extended_signatures):
            if not e.commit_sig.is_commit():
                continue
            assert e.extension == b"ext@2"
            v = ec.get_extended_vote(i)
            _, val = vs.get_by_address(e.commit_sig.validator_address)
            v.verify_extension(CHAIN, val.pub_key)  # raises on forgery
            n_with_ext += 1
        assert n_with_ext >= 3  # +2/3 of 4 validators
    finally:
        for n in nodes:
            n.stop()

    # some proposer saw the previous height's extensions in
    # PrepareProposal.local_last_commit
    seen = [(h, llc) for app in apps
            for (h, llc) in app.seen_local_last_commits]
    assert seen, "no proposer ever received local_last_commit"
    h, llc = seen[0]
    exts = [v.vote_extension for v in llc.votes
            if v.block_id_flag == 2 and v.vote_extension]
    assert exts and all(x == b"ext@%d" % (h - 1) for x in exts)
