"""XChaCha20-Poly1305 AEAD.

The HChaCha20 core is differentially tested against OpenSSL's ChaCha20
(the `cryptography` library): HChaCha20's output equals the ChaCha20
block-function state WITHOUT the feed-forward, so subtracting the
initial state words from the keystream recovers it exactly. Only that
differential needs the wheel — the roundtrip/tamper/length tests run on
whichever AEAD backend symmetric.py loaded (OpenSSL or the pure-Python
aead_ref fallback), so the fallback-backed XChaCha path stays covered
in wheel-less containers.
"""
import os
import struct

import pytest

from cometbft_tpu.crypto import symmetric as sym

try:
    from cryptography.exceptions import InvalidTag
except ImportError:  # no-OpenSSL container: the fallback's exception
    from cometbft_tpu.crypto.aead_ref import InvalidTag


def _hchacha_via_openssl(key: bytes, nonce16: bytes) -> bytes:
    """Independent HChaCha20 from OpenSSL's ChaCha20 keystream."""
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
    )

    cipher = Cipher(algorithms.ChaCha20(key, nonce16), mode=None)
    ks = cipher.encryptor().update(b"\x00" * 64)
    ks_words = struct.unpack("<16L", ks)
    init = list(sym._SIGMA) + list(struct.unpack("<8L", key)) + \
        list(struct.unpack("<4L", nonce16))
    M = 0xFFFFFFFF
    out = [(ks_words[i] - init[i]) & M for i in (0, 1, 2, 3)] + \
          [(ks_words[i] - init[i]) & M for i in (12, 13, 14, 15)]
    return struct.pack("<8L", *out)


def test_hchacha20_differential_vs_openssl():
    pytest.importorskip(
        "cryptography",
        reason="OpenSSL differential needs the cryptography wheel",
    )
    rnd = os.urandom
    for _ in range(20):
        key, nonce16 = rnd(32), rnd(16)
        assert sym.hchacha20(key, nonce16) == \
            _hchacha_via_openssl(key, nonce16)


def test_hchacha20_cfrg_vector():
    """draft-irtf-cfrg-xchacha §2.2.1 test vector: pins the subkey
    derivation with no OpenSSL dependency."""
    key = bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f"
        "101112131415161718191a1b1c1d1e1f")
    nonce16 = bytes.fromhex("000000090000004a0000000031415927")
    assert sym.hchacha20(key, nonce16).hex() == (
        "82413b4227b27bfed30e42508a877d73"
        "a0f9e4d58a74a853c12ec41326d3ecdc")


def test_seal_open_roundtrip_and_tamper():
    key = os.urandom(32)
    aead = sym.XChaCha20Poly1305(key)
    nonce = os.urandom(24)
    pt = b"the validator key file contents"
    ct = aead.seal(nonce, pt, aad=b"meta")
    assert aead.open(nonce, ct, aad=b"meta") == pt
    with pytest.raises(InvalidTag):
        aead.open(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), aad=b"meta")
    with pytest.raises(InvalidTag):
        aead.open(nonce, ct, aad=b"other")
    with pytest.raises(InvalidTag):
        sym.XChaCha20Poly1305(os.urandom(32)).open(nonce, ct, b"meta")


def test_sealed_blob_convenience():
    key = os.urandom(32)
    blob = sym.seal_with_random_nonce(key, b"hello")
    assert sym.open_sealed(key, blob) == b"hello"
    with pytest.raises(ValueError):
        sym.open_sealed(key, b"short")


def test_bad_lengths():
    with pytest.raises(ValueError):
        sym.XChaCha20Poly1305(b"short")
    aead = sym.XChaCha20Poly1305(os.urandom(32))
    with pytest.raises(ValueError):
        aead.seal(os.urandom(12), b"x")  # 12B nonce is the IETF size
