"""Dedicated ChunkQueue / ChunkFetcher tests (ISSUE 18 satellite).

The chunk engine predates this PR but only ever ran under the full
syncer integration tests — these pin its contracts directly: slot
reclaim under a hung fetch (the chunkTimeout re-request of
syncer.go:415), the punish-to-drop provider lifecycle at
MAX_PROVIDER_FAILURES, the cache-dir round-trip a restart resumes
from, and a multi-provider concurrency hammer with exact
statesync-stats accounting. The reclaim test also pins satellite 1's
bugfix: request ages run on the LEDGER clock (tracing.monotonic_ns),
so the simnet's virtual clock drives them deterministically.
"""
import threading
import time

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.statesync.chunks import (
    MAX_PROVIDER_FAILURES, ChunkFetcher, ChunkQueue)


def test_allocate_add_done_basics():
    q = ChunkQueue(3)
    assert [q.allocate() for _ in range(3)] == [0, 1, 2]
    assert q.allocate() is None  # everything requested
    assert q.add(0, b"a", "p1") and q.add(1, b"b", "p1")
    assert not q.add(0, b"dup", "p2")  # first copy wins
    assert q.sender_of(0) == "p1"
    assert not q.done()
    assert q.add(2, b"c", "p2")
    assert q.done()
    assert q.wait_for(1, timeout=0.1) == b"b"


def test_reclaim_expired_frees_hung_slot_on_ledger_clock():
    """A REQUESTED slot older than max_age goes back to PENDING so
    another worker can grab it — and 'older' is judged on the ledger
    clock, so a virtual clock drives reclaim without real sleeping."""
    now_ns = [1_000_000_000]
    tracing.set_clock(lambda: now_ns[0])
    try:
        q = ChunkQueue(2)
        assert q.allocate() == 0
        # young request: nothing to reclaim
        assert q.reclaim_expired(max_age=5.0) == 0
        # hang for 6 virtual seconds without any wall time passing
        now_ns[0] += 6_000_000_000
        assert q.reclaim_expired(max_age=5.0) == 1
        # the slot is allocatable again (a different worker retries it)
        assert q.allocate() == 0
        # RECEIVED slots are never reclaimed
        q.add(0, b"x", "p1")
        now_ns[0] += 60_000_000_000
        assert q.reclaim_expired(max_age=5.0) == 0
        assert q.wait_for(0, timeout=0.0) == b"x"
    finally:
        tracing.set_clock(None)


def test_hung_provider_does_not_stall_sync():
    """One provider blocks forever on its fetch; the applier's
    reclaim loop frees the pinned slot and the healthy provider
    finishes the snapshot."""
    q = ChunkQueue(4)
    unblock = threading.Event()

    def hung(i):
        unblock.wait(5.0)
        return None

    f = ChunkFetcher(q, {"hung": hung,
                         "good": lambda i: b"chunk-%d" % i},
                     chunk_timeout=0.1)
    f.start()
    try:
        deadline = time.monotonic() + 5.0
        while not q.done() and time.monotonic() < deadline:
            q.reclaim_expired(max_age=0.1)
            time.sleep(0.02)
        assert q.done(), "hung provider pinned a slot"
        for i in range(4):
            assert q.wait_for(i, 0.1) == b"chunk-%d" % i
            assert q.sender_of(i) == "good"
    finally:
        unblock.set()
        f.stop()


def test_punish_to_drop_lifecycle():
    ss_stats.reset()
    q = ChunkQueue(1)
    f = ChunkFetcher(q, {"bad": lambda i: None,
                         "good": lambda i: b"x"})
    f.punish(None)  # unknown sender: no-op, never counted
    for k in range(MAX_PROVIDER_FAILURES):
        assert f.has_providers()
        assert ("bad" in f.providers) == True  # noqa: E712
        f.punish("bad")
    assert "bad" not in f.providers  # dropped at the limit
    assert "good" in f.providers and f.has_providers()
    f.punish("bad")  # punishing a dropped provider is idempotent
    c = ss_stats.stats()
    assert c["providers_punished"] == MAX_PROVIDER_FAILURES + 1
    assert c["providers_dropped"] == 1


def test_fetch_failpoint_drives_punish_path():
    """statesync.fetch raising inside the worker counts as a provider
    failure — MAX_PROVIDER_FAILURES firings drop the provider without
    the transport ever being called."""
    ss_stats.reset()
    calls = []
    q = ChunkQueue(2)
    f = ChunkFetcher(q, {"p": lambda i: calls.append(i) or b"x"})
    fp.arm("statesync.fetch", "raise", count=MAX_PROVIDER_FAILURES)
    try:
        f.start()
        deadline = time.monotonic() + 5.0
        while f.has_providers() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not f.has_providers(), "failpoint never dropped provider"
        assert calls == []  # the failpoint fired before the transport
        assert ss_stats.stats()["providers_dropped"] == 1
    finally:
        fp.disarm("statesync.fetch")
        f.stop()


def test_cache_dir_roundtrip_survives_restart(tmp_path):
    """Chunks persist as they arrive; a fresh queue over the same dir
    starts with them RECEIVED (sender 'cache') so a restarted restore
    refetches nothing; retry() evicts the cache copy too."""
    cache = str(tmp_path / "chunks")
    q1 = ChunkQueue(3, cache_dir=cache)
    q1.add(0, b"zero", "p1")
    q1.add(2, b"two", "p2")

    q2 = ChunkQueue(3, cache_dir=cache)  # the restart
    assert q2.wait_for(0, 0.0) == b"zero"
    assert q2.wait_for(2, 0.0) == b"two"
    assert q2.sender_of(0) == "cache" and q2.sender_of(2) == "cache"
    assert q2.allocate() == 1  # only the missing chunk is fetchable
    assert q2.allocate() is None

    # the app rejects chunk 0: discard drops the cache file as well
    assert q2.retry(0) == "cache"
    q3 = ChunkQueue(3, cache_dir=cache)
    assert q3.wait_for(0, 0.0) is None
    assert q3.wait_for(2, 0.0) == b"two"


def test_multi_provider_hammer_exact_accounting():
    """Four concurrent providers race over 64 chunks — one flaky
    (returns None every 3rd call). Every chunk lands exactly once
    (chunks_fetched == 64 despite races), every flaky None is punished,
    and the flaky provider survives because reclaim keeps resetting no
    one: punishment counts are per-failure, drops need consecutive
    bookkeeping only in the failures map."""
    ss_stats.reset()
    q = ChunkQueue(64)
    flaky_nones = []
    lock = threading.Lock()

    def make(pid, period=0):
        n = [0]

        def fetch(i):
            with lock:
                n[0] += 1
                if period and n[0] % period == 0:
                    flaky_nones.append(i)
                    return None
            return b"%s:%d" % (pid.encode(), i)
        return fetch

    providers = {"a": make("a"), "b": make("b"),
                 "c": make("c"), "flaky": make("flaky", period=3)}
    f = ChunkFetcher(q, providers, chunk_timeout=1.0)
    # keep the flaky provider alive for the whole hammer: the drop
    # limit is what test_punish_to_drop_lifecycle pins; here we want
    # sustained concurrency, so give it headroom
    f.failures["flaky"] = -1_000_000
    f.start()
    try:
        deadline = time.monotonic() + 10.0
        while not q.done() and time.monotonic() < deadline:
            q.reclaim_expired(max_age=0.5)
            time.sleep(0.01)
        assert q.done(), "hammer did not converge"
    finally:
        f.stop()
    c = ss_stats.stats()
    assert c["chunks_fetched"] == 64  # duplicates never double-count
    assert c["providers_punished"] == len(flaky_nones)
    assert c["providers_dropped"] == 0
    for i in range(64):
        data = q.wait_for(i, 0.1)
        pid = q.sender_of(i)
        assert data == b"%s:%d" % (pid.encode(), i)
