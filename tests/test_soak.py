"""Chaos soak: sustained open-loop tx flood WHILE the fault schedule
fires, over the deterministic simnet (ISSUE 7 acceptance).

The one scenario every overload mechanism must survive together:
an open-loop signed-tx flood rides the BULK verify lane and the
mempool admission gate while partitions, a kill+restart, garbage
signers, and a verify-plane dispatch fault (breaker trip path) all
fire — and the chain must keep committing, consensus verification must
never be shed, overload verdicts must be explicit, and the whole run
must replay byte-identically from its (seed, schedule).

File named test_soak.py to land late in the alphabetical tier-1 order
(ROADMAP timeout note). Budget: the flood/base/replay runs are built
ONCE in a module-scoped cache and shared across tests (the suite sits
near the tier-1 870 s ceiling — identical (seed, schedule) runs must
not be paid twice).
"""
import json

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.simnet import Simnet
from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

pytestmark = pytest.mark.simnet


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


FLOOD = {"at": 0.6, "op": "flood", "node": 0, "rate": 30.0,
         "duration": 6.0, "signed": True, "size": 24}

# the chaos half: partition+heal, a kill with WAL-recovery restart,
# garbage votes through the running plane, and a one-shot verify-plane
# dispatch fault (the flush degrades to the failpoint host path — the
# same seam a breaker trip exercises)
CHAOS = [
    {"at": 1.0, "op": "garbage", "node": 2, "votes": 2},
    {"at": 1.5, "op": "partition", "groups": [[0, 1, 2], [3]]},
    {"at": 3.0, "op": "heal"},
    {"at": 3.5, "op": "kill", "node": 1},
    {"at": 5.0, "op": "restart", "node": 1},
    {"at": 5.5, "op": "link", "drop": 0.05, "delay": 0.01,
     "jitter": 0.005},
    {"at": 7.0, "op": "heal"},
]


def _run_soak(basedir, flood: bool, seed: int = 2024):
    """One soak run; returns (commit hashes, flood results, plane,
    ledger records). The verify plane is process-global for the run —
    votes ride CONSENSUS, flood sigtx checks ride BULK."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    try:
        # the dispatch failpoint is evaluated on the plane's dispatcher
        # thread, so it is armed process-globally (simnet/core.py note)
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir)) as sim:
            sched = list(CHAOS) + ([dict(FLOOD)] if flood else [])
            assert sim.run(sched, until_height=6, max_time=60.0), \
                "soak run never reached target height"
            sim.assert_safety()
            # liveness WHILE the flood runs: commits landed during the
            # flood window, not only after it drained
            if flood:
                alive = [n for n in sim.net.nodes if n.alive]
                assert all(n.height() >= 6 for n in alive)
            hashes = sim.commit_hashes()
            results = list(sim.flood_results)
    finally:
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return hashes, results, plane, plane.dump_flushes()["flushes"]


@pytest.fixture(scope="module")
def soak_runs(tmp_path_factory):
    """Lazy shared cache of soak runs: "flood_a"/"flood_b" (identical
    (seed, schedule) — the replay pair) and "base" (no flood). Tests
    only READ the returned tuples."""
    runs = {}

    def get(kind):
        if kind not in runs:
            fp.reset()
            runs[kind] = _run_soak(tmp_path_factory.mktemp(kind),
                                   flood=(kind != "base"))
        return runs[kind]

    return get


def test_chaos_soak_survives_flood(soak_runs):
    """Liveness + QoS under sustained traffic and chaos: commits keep
    landing, zero CONSENSUS-lane sheds, BULK/admission overload
    verdicts are explicit (OVERLOADED code + retry hint) and never
    silent, and the flood really rode the BULK lane."""
    hashes, results, plane, _ = soak_runs("flood_a")
    # every node (incl. the restarted one) committed through the chaos
    assert all(len(h) >= 6 for h in hashes)
    # the flood was injected and answered — open-loop, every tx got an
    # explicit verdict (None only for injections at a dead target)
    assert len(results) == int(FLOOD["rate"] * FLOOD["duration"])
    answered = [r for r in results if r["code"] is not None]
    assert answered, "no flood tx ever reached a live mempool"
    accepted = [r for r in answered if r["code"] == abci.CODE_TYPE_OK]
    assert accepted, "flood fully rejected — admission gate miswired"
    # overload verdicts (if any) are explicit and carry the hint
    for r in answered:
        if r["code"] == abci.CODE_TYPE_OVERLOADED:
            assert "retry_after_ms=" in r["log"], r
    # QoS: consensus submissions are NEVER shed; the signed flood
    # really ran through the BULK lane of the shared plane
    stats = plane.stats()
    assert stats["sheds"]["consensus"] == 0, stats
    assert stats["lane_rows"]["bulk"] > 0, stats
    assert stats["lane_rows"]["consensus"] > 0, stats


def test_chaos_soak_vote_latency_bounded(soak_runs):
    """The QoS guarantee, measured: consensus-lane submit-to-result
    p99 under the flood stays within 2x its no-flood value (plus an
    absolute floor for 1-core wall-clock noise — without lanes, the
    bulk backlog pushes vote verification out by the entire flood)."""
    _, _, plane_base, _ = soak_runs("base")
    _, _, plane_flood, _ = soak_runs("flood_a")
    base = plane_base.lane_wait_stats()["consensus"]
    flood = plane_flood.lane_wait_stats()["consensus"]
    assert base["n"] > 0 and flood["n"] > 0
    # 2x the no-flood p99, floored generously: the bound exists to
    # catch priority inversion (seconds of added latency), not to
    # flake on scheduler jitter
    limit = max(2.0 * base["p99_ms"], 50.0)
    assert flood["p99_ms"] <= limit, \
        f"consensus p99 {flood['p99_ms']}ms under flood vs " \
        f"{base['p99_ms']}ms base (limit {limit}ms) — QoS inversion"


def test_chaos_soak_deterministic(soak_runs):
    """Same (seed, schedule) twice — flood, chaos, plane and all —
    yields identical commit hashes at every height on every node AND
    an identical flood verdict sequence."""
    h1, r1, _, led1 = soak_runs("flood_a")
    h2, r2, _, led2 = soak_runs("flood_b")
    assert h1 == h2
    # the verdict STREAM is part of the deterministic surface: same
    # txs, same codes, same order (logs include retry hints, which are
    # config-derived constants)
    assert [(r["seq"], r["code"], r["log"]) for r in r1] == \
        [(r["seq"], r["code"], r["log"]) for r in r2]
    # per-lane ledger composition replays identically too (stage
    # timings ride the virtual clock; see the PR 6 determinism test)
    comp1 = [(r["rows"], r["c_rows"], r["b_rows"], r["path"])
             for r in led1]
    comp2 = [(r["rows"], r["c_rows"], r["b_rows"], r["path"])
             for r in led2]
    assert comp1 == comp2


# ---------------------------------------------------------------------------
# Epoch-scale validator churn (ISSUE 12): proportional re-election of a
# passive validator tail WHILE the chaos half fires — rotation during a
# partition, right after a kill, and under the signed flood. The
# rotation flows through the real ABCI -> update_with_change_set ->
# state/execution.py path on every node; liveness, QoS and byte-
# identical replay must all survive it.
# ---------------------------------------------------------------------------

EPOCHS = [
    {"at": 1.0, "op": "epoch", "node": 0, "churn": 0.25},
    {"at": 2.2, "op": "epoch", "node": 3, "churn": 0.25},  # partitioned
    {"at": 4.2, "op": "epoch", "node": 1, "churn": 0.25},  # node 1 dead
]


def _run_churn(basedir, seed: int = 4242):
    """One churn soak run: chaos + flood + three epoch rotations over a
    32-member tail. Returns (commit hashes, epoch records, final tail
    committee per node, plane stats)."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    try:
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir), power=100_000,
                    extra_validators=32) as sim:
            genesis_committee = list(sim.net.epoch_state["committee"])
            sched = list(CHAOS) + list(EPOCHS) + [dict(FLOOD)]
            assert sim.run(sched, until_height=9, max_time=90.0), \
                "churn soak never reached target height"
            sim.assert_safety()
            hashes = sim.commit_hashes()
            epochs = [dict(r) for r in sim.epoch_results]
            committees = []
            for n in sim.net.nodes:
                if not n.alive:
                    continue
                vs = n.node.consensus.state.validators
                pubs = {v.pub_key.data for v in vs.validators}
                committees.append(sorted(
                    i for i, p in enumerate(sim.net.tail_pubs)
                    if p in pubs))
            flood_results = list(sim.flood_results)
    finally:
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return (hashes, epochs, committees, genesis_committee,
            plane.stats(), flood_results)


@pytest.fixture(scope="module")
def churn_runs(tmp_path_factory):
    """Shared churn-soak runs: "a"/"b" are the identical-(seed,
    schedule) replay pair (same budget discipline as soak_runs)."""
    runs = {}

    def get(kind):
        if kind not in runs:
            fp.reset()
            runs[kind] = _run_churn(tmp_path_factory.mktemp(kind))
        return runs[kind]

    return get


def test_churn_soak_rotation_survives_chaos(churn_runs):
    """Rotations fired during a partition, after a kill, and under the
    flood all LAND: the live valset's tail committee moved off the
    genesis election, the chain kept committing, and consensus
    verification was never shed."""
    hashes, epochs, committees, genesis_committee, stats, _ = \
        churn_runs("a")
    # all four nodes (incl. the restarted one) committed through the
    # churn; height >= 9 means the last rotation's H+2 landed too
    assert all(len(h) >= 9 for h in hashes)
    # every epoch op elected and injected (no silent no-ops); all
    # CheckTx verdicts for the val txs on the recording node were OK
    assert len(epochs) == len(EPOCHS)
    for rec in epochs:
        assert "error" not in rec, rec
        assert rec["txs"] > 0 and rec["out"] and rec["in"]
        assert all(c == 0 for c in rec["codes"]), rec
    # the rotation actually reached the valset on every live node —
    # and every node agrees on the committee
    assert committees and all(c == committees[0] for c in committees)
    assert committees[0] != sorted(genesis_committee)
    # QoS held through the rotation: CONSENSUS never shed
    assert stats["sheds"]["consensus"] == 0, stats
    assert stats["lane_rows"]["consensus"] > 0, stats


def test_churn_soak_deterministic(churn_runs):
    """Same (seed, schedule) — chaos, flood, elections and all — gives
    identical commit hashes at every height AND an identical election
    stream (who rotated out/in, per epoch, per replay)."""
    h1, e1, c1, _, _, f1 = churn_runs("a")
    h2, e2, c2, _, _, f2 = churn_runs("b")
    assert h1 == h2
    assert e1 == e2
    assert c1 == c2
    assert [(r["seq"], r["code"]) for r in f1] == \
        [(r["seq"], r["code"]) for r in f2]


@pytest.mark.slow
def test_churn_soak_10k_scale(tmp_path):
    """The acceptance-scale run: a 10k-validator valset (4 operator
    nodes + a 9996-member passive tail) rotating 2% per epoch under a
    partition — liveness and safety hold, and the rotation lands
    through the real update path at H+2. Slow-marked: 10k-row commits
    make every height wall-expensive on the 1-core host; the fast
    sibling above runs the same machinery at 32 tail members."""
    with Simnet(4, seed=77, basedir=str(tmp_path), power=1_000_000,
                extra_validators=9_996) as sim:
        assert len(sim.net.genesis.validators) >= 5_000
        sched = [
            {"at": 0.8, "op": "epoch", "node": 0, "churn": 0.02},
            {"at": 1.5, "op": "partition", "groups": [[0, 1, 2], [3]]},
            {"at": 2.5, "op": "heal"},
        ]
        assert sim.run(sched, until_height=5, max_time=120.0)
        sim.assert_safety()
        rec = sim.epoch_results[0]
        assert "error" not in rec and rec["txs"] > 0
        vs = sim.net.nodes[0].node.consensus.state.validators
        pubs = {v.pub_key.data for v in vs.validators}
        rotated_in = [i for i in rec["in"]
                      if sim.net.tail_pubs[i] in pubs]
        rotated_out = [i for i in rec["out"]
                       if sim.net.tail_pubs[i] in pubs]
        assert rotated_in == rec["in"] and not rotated_out


# ---------------------------------------------------------------------------
# Incident flight recorder under chaos (ISSUE 13 acceptance): a
# partition-induced commit stall — WHILE the signed flood and a
# dispatch fault fire — freezes a commit_stall incident whose whole
# snapshot stream replays byte-identically from (seed, schedule).
# ---------------------------------------------------------------------------


def _run_incident_soak(basedir, seed: int = 3131):
    from cometbft_tpu.libs import incidents

    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    rec = incidents.IncidentRecorder(
        commit_stall_s=3.0, round_limit=3, cooldown_s=6.0)
    old = incidents.install(rec)
    try:
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir)) as sim:
            # quorumless 2/2 partition mid-flood: commits stop DEAD —
            # no side holds 2/3, the step machine wedges with no
            # transitions at all, and the stall is detected at the
            # first post-heal transition (the deterministic simnet
            # evaluator; live nodes additionally have the real-clock
            # watchdog ticker for exactly this wedge)
            sched = [
                {"at": 0.3, "op": "partition",
                 "groups": [[0, 1], [2, 3]]},
                {"at": 0.6, "op": "flood", "node": 0, "rate": 20.0,
                 "duration": 4.0, "signed": True, "size": 24},
                {"at": 9.0, "op": "heal"},
            ]
            assert sim.run(sched, until_height=4, max_time=90.0), \
                "chain never recovered after the quorumless partition"
            sim.assert_safety()
            hashes = sim.commit_hashes()
            peer_dumps = [n.peer_ledger.dump() for n in sim.net.nodes]
    finally:
        incidents.install(old)
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return hashes, rec.dump(), peer_dumps


def test_chaos_soak_commit_stall_incident_replays(tmp_path):
    """The acceptance scenario: the partition-induced stall fires a
    commit_stall incident with the height/flush/peer tails frozen AT
    the stall, the gossip observatory attributes the partition's lost
    messages to the partitioned peers, and the same (seed, schedule)
    yields a byte-identical incident stream, chain, AND per-node peer
    ledger (ISSUE 14 chaos-soak acceptance)."""
    h1, d1, p1 = _run_incident_soak(tmp_path / "a")
    h2, d2, p2 = _run_incident_soak(tmp_path / "b")
    assert h1 == h2
    assert d1["fired"].get("commit_stall", 0) >= 1, d1["fired"]
    assert json.dumps(d1, sort_keys=True) == \
        json.dumps(d2, sort_keys=True)
    snap = next(s for s in d1["incidents"]
                if s["trigger"] == "commit_stall")
    # the black box froze real evidence: the last heights' stage
    # timelines and the plane's last flushes (the flood was riding it)
    assert snap["height_tail"], snap
    assert snap["flush_tail"], snap
    # ... and the gossip observatory's per-peer tail (which links were
    # eating messages when the stall hit)
    assert snap["peer_tail"], snap
    assert snap["counters"]["plane"]["rows"] > 0
    # peer ledgers replay byte-identically and the 2/2 partition is
    # attributed: node 0's cross-group records ate link drops, its
    # same-side record did not
    assert json.dumps(p1, sort_keys=True) == \
        json.dumps(p2, sort_keys=True)
    n0 = {p["peer"]: p for p in p1[0]["peers"]}
    assert n0["n2"]["link_drops"] + n0["n3"]["link_drops"] > 0, n0
    assert n0["n1"]["link_drops"] == 0, n0


def test_flood_reaches_blocks(tmp_path):
    """Sustained-throughput sanity: flooded txs COMMIT — the accepted
    stream shows up in blocks, not just in mempool counters."""
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        with Simnet(4, seed=77, basedir=str(tmp_path)) as sim:
            assert sim.run(
                [{"at": 0.4, "op": "flood", "node": 0, "rate": 20.0,
                  "duration": 3.0, "signed": True}],
                until_height=5, max_time=60.0,
            )
            sim.assert_safety()
            committed = 0
            store = sim.net.nodes[0].node.block_store
            for h in range(1, sim.net.nodes[0].height() + 1):
                blk = store.load_block(h)
                if blk is not None:
                    committed += sum(
                        1 for tx in blk.data.txs if b"flood-" in tx)
            assert committed > 0, "no flooded tx ever committed"
    finally:
        set_global_plane(None)
        plane.stop()
