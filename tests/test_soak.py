"""Chaos soak: sustained open-loop tx flood WHILE the fault schedule
fires, over the deterministic simnet (ISSUE 7 acceptance).

The one scenario every overload mechanism must survive together:
an open-loop signed-tx flood rides the BULK verify lane and the
mempool admission gate while partitions, a kill+restart, garbage
signers, and a verify-plane dispatch fault (breaker trip path) all
fire — and the chain must keep committing, consensus verification must
never be shed, overload verdicts must be explicit, and the whole run
must replay byte-identically from its (seed, schedule).

File named test_soak.py to land late in the alphabetical tier-1 order
(ROADMAP timeout note). Budget: the flood/base/replay runs are built
ONCE in a module-scoped cache and shared across tests (the suite sits
near the tier-1 870 s ceiling — identical (seed, schedule) runs must
not be paid twice).
"""
import json

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.simnet import Simnet
from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

pytestmark = pytest.mark.simnet


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


FLOOD = {"at": 0.6, "op": "flood", "node": 0, "rate": 30.0,
         "duration": 6.0, "signed": True, "size": 24}

# the chaos half: partition+heal, a kill with WAL-recovery restart,
# garbage votes through the running plane, and a one-shot verify-plane
# dispatch fault (the flush degrades to the failpoint host path — the
# same seam a breaker trip exercises)
CHAOS = [
    {"at": 1.0, "op": "garbage", "node": 2, "votes": 2},
    {"at": 1.5, "op": "partition", "groups": [[0, 1, 2], [3]]},
    {"at": 3.0, "op": "heal"},
    {"at": 3.5, "op": "kill", "node": 1},
    {"at": 5.0, "op": "restart", "node": 1},
    {"at": 5.5, "op": "link", "drop": 0.05, "delay": 0.01,
     "jitter": 0.005},
    {"at": 7.0, "op": "heal"},
]


def _run_soak(basedir, flood: bool, seed: int = 2024):
    """One soak run; returns (commit hashes, flood results, plane,
    ledger records). The verify plane is process-global for the run —
    votes ride CONSENSUS, flood sigtx checks ride BULK."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    try:
        # the dispatch failpoint is evaluated on the plane's dispatcher
        # thread, so it is armed process-globally (simnet/core.py note)
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir)) as sim:
            sched = list(CHAOS) + ([dict(FLOOD)] if flood else [])
            assert sim.run(sched, until_height=6, max_time=60.0), \
                "soak run never reached target height"
            sim.assert_safety()
            # liveness WHILE the flood runs: commits landed during the
            # flood window, not only after it drained
            if flood:
                alive = [n for n in sim.net.nodes if n.alive]
                assert all(n.height() >= 6 for n in alive)
            hashes = sim.commit_hashes()
            results = list(sim.flood_results)
    finally:
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return hashes, results, plane, plane.dump_flushes()["flushes"]


@pytest.fixture(scope="module")
def soak_runs(tmp_path_factory):
    """Lazy shared cache of soak runs: "flood_a"/"flood_b" (identical
    (seed, schedule) — the replay pair) and "base" (no flood). Tests
    only READ the returned tuples."""
    runs = {}

    def get(kind):
        if kind not in runs:
            fp.reset()
            runs[kind] = _run_soak(tmp_path_factory.mktemp(kind),
                                   flood=(kind != "base"))
        return runs[kind]

    return get


def test_chaos_soak_survives_flood(soak_runs):
    """Liveness + QoS under sustained traffic and chaos: commits keep
    landing, zero CONSENSUS-lane sheds, BULK/admission overload
    verdicts are explicit (OVERLOADED code + retry hint) and never
    silent, and the flood really rode the BULK lane."""
    hashes, results, plane, _ = soak_runs("flood_a")
    # every node (incl. the restarted one) committed through the chaos
    assert all(len(h) >= 6 for h in hashes)
    # the flood was injected and answered — open-loop, every tx got an
    # explicit verdict (None only for injections at a dead target)
    assert len(results) == int(FLOOD["rate"] * FLOOD["duration"])
    answered = [r for r in results if r["code"] is not None]
    assert answered, "no flood tx ever reached a live mempool"
    accepted = [r for r in answered if r["code"] == abci.CODE_TYPE_OK]
    assert accepted, "flood fully rejected — admission gate miswired"
    # overload verdicts (if any) are explicit and carry the hint
    for r in answered:
        if r["code"] == abci.CODE_TYPE_OVERLOADED:
            assert "retry_after_ms=" in r["log"], r
    # QoS: consensus submissions are NEVER shed; the signed flood
    # really ran through the BULK lane of the shared plane
    stats = plane.stats()
    assert stats["sheds"]["consensus"] == 0, stats
    assert stats["lane_rows"]["bulk"] > 0, stats
    assert stats["lane_rows"]["consensus"] > 0, stats


def test_chaos_soak_vote_latency_bounded(soak_runs):
    """The QoS guarantee, measured: consensus-lane submit-to-result
    p99 under the flood stays within 2x its no-flood value (plus an
    absolute floor for 1-core wall-clock noise — without lanes, the
    bulk backlog pushes vote verification out by the entire flood)."""
    _, _, plane_base, _ = soak_runs("base")
    _, _, plane_flood, _ = soak_runs("flood_a")
    base = plane_base.lane_wait_stats()["consensus"]
    flood = plane_flood.lane_wait_stats()["consensus"]
    assert base["n"] > 0 and flood["n"] > 0
    # 2x the no-flood p99, floored generously: the bound exists to
    # catch priority inversion (seconds of added latency), not to
    # flake on scheduler jitter
    limit = max(2.0 * base["p99_ms"], 50.0)
    assert flood["p99_ms"] <= limit, \
        f"consensus p99 {flood['p99_ms']}ms under flood vs " \
        f"{base['p99_ms']}ms base (limit {limit}ms) — QoS inversion"


def test_chaos_soak_deterministic(soak_runs):
    """Same (seed, schedule) twice — flood, chaos, plane and all —
    yields identical commit hashes at every height on every node AND
    an identical flood verdict sequence."""
    h1, r1, _, led1 = soak_runs("flood_a")
    h2, r2, _, led2 = soak_runs("flood_b")
    assert h1 == h2
    # the verdict STREAM is part of the deterministic surface: same
    # txs, same codes, same order (logs include retry hints, which are
    # config-derived constants)
    assert [(r["seq"], r["code"], r["log"]) for r in r1] == \
        [(r["seq"], r["code"], r["log"]) for r in r2]
    # per-lane ledger composition replays identically too (stage
    # timings ride the virtual clock; see the PR 6 determinism test)
    comp1 = [(r["rows"], r["c_rows"], r["b_rows"], r["path"])
             for r in led1]
    comp2 = [(r["rows"], r["c_rows"], r["b_rows"], r["path"])
             for r in led2]
    assert comp1 == comp2


# ---------------------------------------------------------------------------
# Epoch-scale validator churn (ISSUE 12): proportional re-election of a
# passive validator tail WHILE the chaos half fires — rotation during a
# partition, right after a kill, and under the signed flood. The
# rotation flows through the real ABCI -> update_with_change_set ->
# state/execution.py path on every node; liveness, QoS and byte-
# identical replay must all survive it.
# ---------------------------------------------------------------------------

EPOCHS = [
    {"at": 1.0, "op": "epoch", "node": 0, "churn": 0.25},
    {"at": 2.2, "op": "epoch", "node": 3, "churn": 0.25},  # partitioned
    {"at": 4.2, "op": "epoch", "node": 1, "churn": 0.25},  # node 1 dead
]


def _run_churn(basedir, seed: int = 4242):
    """One churn soak run: chaos + flood + three epoch rotations over a
    32-member tail. Returns (commit hashes, epoch records, final tail
    committee per node, plane stats)."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    try:
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir), power=100_000,
                    extra_validators=32) as sim:
            genesis_committee = list(sim.net.epoch_state["committee"])
            sched = list(CHAOS) + list(EPOCHS) + [dict(FLOOD)]
            assert sim.run(sched, until_height=9, max_time=90.0), \
                "churn soak never reached target height"
            sim.assert_safety()
            hashes = sim.commit_hashes()
            epochs = [dict(r) for r in sim.epoch_results]
            committees = []
            for n in sim.net.nodes:
                if not n.alive:
                    continue
                vs = n.node.consensus.state.validators
                pubs = {v.pub_key.data for v in vs.validators}
                committees.append(sorted(
                    i for i, p in enumerate(sim.net.tail_pubs)
                    if p in pubs))
            flood_results = list(sim.flood_results)
    finally:
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return (hashes, epochs, committees, genesis_committee,
            plane.stats(), flood_results)


@pytest.fixture(scope="module")
def churn_runs(tmp_path_factory):
    """Shared churn-soak runs: "a"/"b" are the identical-(seed,
    schedule) replay pair (same budget discipline as soak_runs)."""
    runs = {}

    def get(kind):
        if kind not in runs:
            fp.reset()
            runs[kind] = _run_churn(tmp_path_factory.mktemp(kind))
        return runs[kind]

    return get


def test_churn_soak_rotation_survives_chaos(churn_runs):
    """Rotations fired during a partition, after a kill, and under the
    flood all LAND: the live valset's tail committee moved off the
    genesis election, the chain kept committing, and consensus
    verification was never shed."""
    hashes, epochs, committees, genesis_committee, stats, _ = \
        churn_runs("a")
    # all four nodes (incl. the restarted one) committed through the
    # churn; height >= 9 means the last rotation's H+2 landed too
    assert all(len(h) >= 9 for h in hashes)
    # every epoch op elected and injected (no silent no-ops); all
    # CheckTx verdicts for the val txs on the recording node were OK
    assert len(epochs) == len(EPOCHS)
    for rec in epochs:
        assert "error" not in rec, rec
        assert rec["txs"] > 0 and rec["out"] and rec["in"]
        assert all(c == 0 for c in rec["codes"]), rec
    # the rotation actually reached the valset on every live node —
    # and every node agrees on the committee
    assert committees and all(c == committees[0] for c in committees)
    assert committees[0] != sorted(genesis_committee)
    # QoS held through the rotation: CONSENSUS never shed
    assert stats["sheds"]["consensus"] == 0, stats
    assert stats["lane_rows"]["consensus"] > 0, stats


def test_churn_soak_deterministic(churn_runs):
    """Same (seed, schedule) — chaos, flood, elections and all — gives
    identical commit hashes at every height AND an identical election
    stream (who rotated out/in, per epoch, per replay)."""
    h1, e1, c1, _, _, f1 = churn_runs("a")
    h2, e2, c2, _, _, f2 = churn_runs("b")
    assert h1 == h2
    assert e1 == e2
    assert c1 == c2
    assert [(r["seq"], r["code"]) for r in f1] == \
        [(r["seq"], r["code"]) for r in f2]


@pytest.mark.slow
def test_churn_soak_10k_scale(tmp_path):
    """The acceptance-scale run: a 10k-validator valset (4 operator
    nodes + a 9996-member passive tail) rotating 2% per epoch under a
    partition — liveness and safety hold, and the rotation lands
    through the real update path at H+2. Slow-marked: 10k-row commits
    make every height wall-expensive on the 1-core host; the fast
    sibling above runs the same machinery at 32 tail members."""
    with Simnet(4, seed=77, basedir=str(tmp_path), power=1_000_000,
                extra_validators=9_996) as sim:
        assert len(sim.net.genesis.validators) >= 5_000
        sched = [
            {"at": 0.8, "op": "epoch", "node": 0, "churn": 0.02},
            {"at": 1.5, "op": "partition", "groups": [[0, 1, 2], [3]]},
            {"at": 2.5, "op": "heal"},
        ]
        assert sim.run(sched, until_height=5, max_time=120.0)
        sim.assert_safety()
        rec = sim.epoch_results[0]
        assert "error" not in rec and rec["txs"] > 0
        vs = sim.net.nodes[0].node.consensus.state.validators
        pubs = {v.pub_key.data for v in vs.validators}
        rotated_in = [i for i in rec["in"]
                      if sim.net.tail_pubs[i] in pubs]
        rotated_out = [i for i in rec["out"]
                       if sim.net.tail_pubs[i] in pubs]
        assert rotated_in == rec["in"] and not rotated_out


# ---------------------------------------------------------------------------
# Incident flight recorder under chaos (ISSUE 13 acceptance): a
# partition-induced commit stall — WHILE the signed flood and a
# dispatch fault fire — freezes a commit_stall incident whose whole
# snapshot stream replays byte-identically from (seed, schedule).
# ---------------------------------------------------------------------------


def _run_incident_soak(basedir, seed: int = 3131):
    from cometbft_tpu.libs import incidents

    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    rec = incidents.IncidentRecorder(
        commit_stall_s=3.0, round_limit=3, cooldown_s=6.0)
    old = incidents.install(rec)
    try:
        fp.registry().arm_from_spec("verifyplane.dispatch=raise*1")
        with Simnet(4, seed=seed, basedir=str(basedir)) as sim:
            # quorumless 2/2 partition mid-flood: commits stop DEAD —
            # no side holds 2/3, the step machine wedges with no
            # transitions at all, and the stall is detected at the
            # first post-heal transition (the deterministic simnet
            # evaluator; live nodes additionally have the real-clock
            # watchdog ticker for exactly this wedge)
            sched = [
                {"at": 0.3, "op": "partition",
                 "groups": [[0, 1], [2, 3]]},
                {"at": 0.6, "op": "flood", "node": 0, "rate": 20.0,
                 "duration": 4.0, "signed": True, "size": 24},
                {"at": 9.0, "op": "heal"},
            ]
            assert sim.run(sched, until_height=4, max_time=90.0), \
                "chain never recovered after the quorumless partition"
            sim.assert_safety()
            hashes = sim.commit_hashes()
            peer_dumps = [n.peer_ledger.dump() for n in sim.net.nodes]
    finally:
        incidents.install(old)
        set_global_plane(None)
        plane.stop()
        fp.reset()
    return hashes, rec.dump(), peer_dumps


def test_chaos_soak_commit_stall_incident_replays(tmp_path):
    """The acceptance scenario: the partition-induced stall fires a
    commit_stall incident with the height/flush/peer tails frozen AT
    the stall, the gossip observatory attributes the partition's lost
    messages to the partitioned peers, and the same (seed, schedule)
    yields a byte-identical incident stream, chain, AND per-node peer
    ledger (ISSUE 14 chaos-soak acceptance)."""
    h1, d1, p1 = _run_incident_soak(tmp_path / "a")
    h2, d2, p2 = _run_incident_soak(tmp_path / "b")
    assert h1 == h2
    assert d1["fired"].get("commit_stall", 0) >= 1, d1["fired"]
    assert json.dumps(d1, sort_keys=True) == \
        json.dumps(d2, sort_keys=True)
    snap = next(s for s in d1["incidents"]
                if s["trigger"] == "commit_stall")
    # the black box froze real evidence: the last heights' stage
    # timelines and the plane's last flushes (the flood was riding it)
    assert snap["height_tail"], snap
    assert snap["flush_tail"], snap
    # ... and the gossip observatory's per-peer tail (which links were
    # eating messages when the stall hit)
    assert snap["peer_tail"], snap
    assert snap["counters"]["plane"]["rows"] > 0
    # peer ledgers replay byte-identically and the 2/2 partition is
    # attributed: node 0's cross-group records ate link drops, its
    # same-side record did not
    assert json.dumps(p1, sort_keys=True) == \
        json.dumps(p2, sort_keys=True)
    n0 = {p["peer"]: p for p in p1[0]["peers"]}
    assert n0["n2"]["link_drops"] + n0["n3"]["link_drops"] > 0, n0
    assert n0["n1"]["link_drops"] == 0, n0


def test_flood_reaches_blocks(tmp_path):
    """Sustained-throughput sanity: flooded txs COMMIT — the accepted
    stream shows up in blocks, not just in mempool counters."""
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        with Simnet(4, seed=77, basedir=str(tmp_path)) as sim:
            assert sim.run(
                [{"at": 0.4, "op": "flood", "node": 0, "rate": 20.0,
                  "duration": 3.0, "signed": True}],
                until_height=5, max_time=60.0,
            )
            sim.assert_safety()
            committed = 0
            store = sim.net.nodes[0].node.block_store
            for h in range(1, sim.net.nodes[0].height() + 1):
                blk = store.load_block(h)
                if blk is not None:
                    committed += sum(
                        1 for tx in blk.data.txs if b"flood-" in tx)
            assert committed > 0, "no flooded tx ever committed"
    finally:
        set_global_plane(None)
        plane.stop()


# ---------------------------------------------------------------------------
# Self-tuning controller under a diurnal load cycle (ISSUE 16
# acceptance): a 10x flood ramp up and back down with a partition
# firing mid-peak. Three arms over the SAME (seed, schedule) traffic:
#   * controller — watermarks start generous, the loop tightens them
#     at the peak and relaxes them back at the trough;
#   * static-tight — hand-tuned for the peak: sheds needlessly at
#     off-peak load;
#   * static-loose — hand-tuned for the trough: the peak drives the
#     mempool to its ceiling (the melt the controller pre-empts).
# The controller arm runs twice (a/b): the /dump_controller decision
# stream must replay byte-identically from (seed, schedule).
# ---------------------------------------------------------------------------

# 10x diurnal ramp: trough -> shoulder -> peak (partition mid-peak)
# -> shoulder -> trough. Absolute sim times; mounted in a SECOND
# sim.run() call after height 1 so the arm mutations (mempool sizing,
# static watermarks) land at a deterministic point of the run.
DIURNAL = [
    {"at": 2.0, "op": "flood", "node": 0, "rate": 6.0,
     "duration": 1.2, "signed": True},
    {"at": 3.4, "op": "flood", "node": 0, "rate": 12.0,
     "duration": 1.2, "signed": True},
    {"at": 4.8, "op": "flood", "node": 0, "rate": 60.0,
     "duration": 1.2, "signed": True},
    {"at": 6.4, "op": "flood", "node": 0, "rate": 12.0,
     "duration": 1.2, "signed": True},
    {"at": 7.8, "op": "flood", "node": 0, "rate": 6.0,
     "duration": 1.2, "signed": True},
    {"at": 5.0, "op": "partition", "groups": [[0, 1, 2], [3]]},
    {"at": 5.6, "op": "heal"},
]
PEAK_WINDOW = (4.8, 6.4)  # injections in here may be shed by design
DIURNAL_SLO_MS = 5000.0
DIURNAL_MEMPOOL = 40  # small enough that the ramp moves fill

CTL_OP = {
    "at": 1.9, "op": "controller", "node": 0,
    "slo_commit_p99_ms": DIURNAL_SLO_MS,
    "decision_interval": 4, "cooldown": 2,
    "fill_high": 0.45, "fill_low": 0.38,
    "watermark_step": 0.2,
    "bounds": {"admission_high_watermark": [0.3, 0.9],
               "bulk_window_ms": [2.0, 40.0],
               "gateway_window_ms": [1.0, 20.0]},
}


def _run_diurnal(basedir, arm: str, seed: int = 6161):
    """One diurnal arm; returns (commit hashes, flood results,
    controller dump or None, admission stats, max observed fill,
    plane stats, commit p99 ms)."""
    from cometbft_tpu.libs import controller as controlplane

    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        with Simnet(4, seed=seed, basedir=str(basedir)) as sim:
            assert sim.run([], until_height=1, max_time=30.0)
            node = sim.net.nodes[0].node
            node.mempool.max_txs = DIURNAL_MEMPOOL
            adm = node.mempool.admission
            if arm == "tight":
                adm.set_watermarks(0.25, 0.05)
            # max-fill probe: try_acquire and the controller both read
            # through _fill_fn, so this sees every gate evaluation
            inner = adm._fill_fn
            seen = {"max": 0.0}

            def probe():
                f = float(inner())
                if f > seen["max"]:
                    seen["max"] = f
                return f

            adm._fill_fn = probe
            sched = list(DIURNAL) + \
                ([dict(CTL_OP)] if arm == "controller" else [])
            assert sim.run(sched, until_height=8, max_time=90.0), \
                f"diurnal {arm} arm never reached target height"
            sim.assert_safety()
            hashes = sim.commit_hashes()
            results = list(sim.flood_results)
            dump = (node.controller.dump()
                    if arm == "controller" else None)
            adm_stats = adm.stats()
            p99 = node.consensus.height_ledger.summary()[
                "commit_latency_ms"]["p99"]
    finally:
        controlplane.set_global_controller(None)
        set_global_plane(None)
        plane.stop()
    return (hashes, results, dump, adm_stats, seen["max"],
            plane.stats(), p99)


@pytest.fixture(scope="module")
def diurnal_runs(tmp_path_factory):
    """Shared diurnal arms; "ctl_a"/"ctl_b" are the replay pair."""
    runs = {}

    def get(kind):
        if kind not in runs:
            arm = "controller" if kind.startswith("ctl") else kind
            runs[kind] = _run_diurnal(
                tmp_path_factory.mktemp(kind), arm)
        return runs[kind]

    return get


def _off_peak(results):
    return [r for r in results if r["code"] is not None
            and not PEAK_WINDOW[0] <= r["at"] < PEAK_WINDOW[1]
            and r["at"] < 6.4]  # pre-peak windows: shed-free by right


def test_diurnal_controller_holds_slo(diurnal_runs):
    """The closed loop rides the ramp: commit p99 holds the declared
    SLO through peak + partition, CONSENSUS sheds zero, admission is
    tightened AT the peak (fill-attributed in the decision trigger),
    relaxed back to base BY the trough, and never leaves its clamps."""
    hashes, results, dump, adm_stats, max_fill, pstats, p99 = \
        diurnal_runs("ctl_a")
    assert all(len(h) >= 8 for h in hashes)
    assert p99 <= DIURNAL_SLO_MS, \
        f"commit p99 {p99}ms blew the {DIURNAL_SLO_MS}ms SLO"
    assert dump["state"]["slo_violation_s"] == 0.0
    assert pstats["sheds"]["consensus"] == 0, pstats
    decs = dump["decisions"]
    adm_decs = [d for d in decs
                if d["actuator"] == "admission_high_watermark"]
    # tightened under fill pressure (the pre-shed_storm trigger): at
    # least one non-relax down move whose own trigger shows the fill
    tightens = [d for d in adm_decs if d["direction"] == "down"
                and not d["relax"]]
    assert tightens, decs
    assert any(d["trigger"]["fill"] >= CTL_OP["fill_high"]
               for d in tightens), tightens
    # relaxed back: up moves flagged relax=True, and the watermark is
    # back at its configured base by the end of the trough
    assert any(d["direction"] == "up" and d["relax"]
               for d in adm_decs), adm_decs
    a = dump["actuators"]["admission_high_watermark"]
    assert a["value"] == a["base"] == 0.9
    # clamp discipline: no decision ever left [min, max]
    for d in decs:
        act = dump["actuators"][d["actuator"]]
        assert act["min"] <= d["new"] <= act["max"], d
    # no needless off-peak shedding: every pre-peak injection that
    # reached a live mempool was answered OK
    off = _off_peak(results)
    assert off and all(r["code"] == abci.CODE_TYPE_OK for r in off)
    # the peak was actually shed against (the load was real)
    assert any(r["code"] == abci.CODE_TYPE_OVERLOADED
               for r in results if r["code"] is not None)
    # ... and the loop kept the mempool off its static ceiling
    assert max_fill < 0.9, max_fill


def test_diurnal_static_arms_fail(diurnal_runs):
    """The two hand-tunings the controller obsoletes, asserted to
    fail: tuned-for-peak sheds the off-peak traffic it has headroom
    for; tuned-for-trough lets the peak drive the mempool to its
    ceiling (the fill the controller arm never reaches)."""
    _, tight_results, _, tight_stats, _, _, _ = diurnal_runs("tight")
    off = _off_peak(tight_results)
    assert any(r["code"] == abci.CODE_TYPE_OVERLOADED for r in off), \
        "static-tight arm never shed off-peak — scenario miscalibrated"
    assert tight_stats["counts"]["rejected_watermark"] > 0
    _, _, _, loose_stats, loose_max_fill, _, _ = diurnal_runs("loose")
    *_, ctl_max_fill, _, _ = diurnal_runs("ctl_a")
    assert loose_max_fill >= 0.9, loose_max_fill
    assert ctl_max_fill < loose_max_fill
    # the melt is explicit, not silent: the loose arm's latch tripped
    assert loose_stats["counts"]["rejected_watermark"] > 0


def test_diurnal_decision_stream_deterministic(diurnal_runs):
    """Same (seed, schedule) twice: identical commit hashes, identical
    flood verdict stream, and a byte-identical /dump_controller
    document — decisions, triggers, actuator values, violation
    accrual and all. (drain_pokes is the one real-thread counter on
    the dump: the dispatcher-drain seam never *decides* on a simnet
    plane, but its poke count rides the real clock, so it is excluded
    from the byte comparison.)"""
    h1, r1, d1, *_ = diurnal_runs("ctl_a")
    h2, r2, d2, *_ = diurnal_runs("ctl_b")
    assert h1 == h2
    assert [(r["seq"], r["code"], r["log"]) for r in r1] == \
        [(r["seq"], r["code"], r["log"]) for r in r2]

    def canon(d):
        d = json.loads(json.dumps(d))
        d["state"].pop("drain_pokes")
        return json.dumps(d, sort_keys=True)

    assert d1["decisions"], "replay pair never decided anything"
    assert canon(d1) == canon(d2)
