"""POL-based unlocking (liveness): a node locked on block A in round 0
prevotes a different block B in a later round iff the proposal carries a
proof-of-lock round vr with locked_round <= vr < round AND the node has
seen +2/3 prevotes for B at vr.

Reference: consensus/state.go:1360 defaultDoPrevote (arXiv Tendermint
alg. lines 22-33); driven single-threaded via the swappable
decide_proposal hook + ManualTicker (state.go:122-125 test seams).
"""
import queue

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.state import (
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE_WAIT,
    ConsensusState,
    ProposalMsg,
    VoteMsg,
)
from cometbft_tpu.consensus.ticker import TimeoutInfo
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import State, StateStore
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

CHAIN = "pol-chain"


def drain(cs):
    """Process everything the machine queued for itself (own votes,
    scheduled round starts) — the single-threaded receiveRoutine stand-in."""
    while True:
        try:
            item = cs.internal_queue.get_nowait()
        except queue.Empty:
            return
        cs._handle(item, write_wal=False)


def peer_vote(cs, priv, vs, vote_type, round_, bid):
    addr = priv.pub_key().address()
    idx, _ = vs.get_by_address(addr)
    v = Vote(vote_type=vote_type, height=cs.height, round=round_,
             block_id=bid, timestamp=Timestamp(1_700_000_100, 0),
             validator_address=addr, validator_index=idx)
    v.signature = priv.sign(v.sign_bytes(CHAIN))
    cs._handle(("vote", VoteMsg(v)), write_wal=False)
    drain(cs)


def signed_proposal(cs, privs, vs, round_, pol_round, block):
    proposer = cs.proposer_for_round(round_)
    priv = next(p for p in privs
                if p.pub_key().address() == proposer.address)
    bid = block.block_id()
    prop = Proposal(cs.height, round_, pol_round, bid,
                    Timestamp(1_700_000_050, 0))
    prop.signature = priv.sign(prop.sign_bytes(CHAIN))
    return ProposalMsg(prop, block)


def fire(cs, round_, step):
    cs._handle_timeout(TimeoutInfo(cs.height, round_, step, 0))
    drain(cs)


def own_votes(captured, vote_type, round_):
    return [m[1] for m in captured
            if m[0] == "vote" and m[1].vote_type == vote_type
            and m[1].round == round_]


def test_pol_unlock_prevotes_new_block():
    privs = [PrivKey.generate(bytes([i + 40]) * 32) for i in range(4)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis(CHAIN, vs)
    exec_ = BlockExecutor(KVStoreApplication(), StateStore(":memory:"))
    captured = []
    # our node is whichever validator holds privs[0]
    cs = ConsensusState(state, exec_, BlockStore(":memory:"),
                        privval=FilePV(privs[0]), manual_ticker=True,
                        broadcast=captured.append)
    cs._started = True  # drive by hand, no thread
    cs.decide_proposal_fn = lambda h, r: None  # never self-propose

    last_commit = Commit(0, 0, BlockID(), [])
    prop0 = cs.proposer_for_round(0).address
    block_a = exec_.create_proposal_block(1, state, last_commit, prop0,
                                          txs=[b"a=1"])
    block_b = exec_.create_proposal_block(1, state, last_commit, prop0,
                                          txs=[b"b=2"])
    assert block_a.hash() != block_b.hash()
    others = [p for p in privs if p is not privs[0]]

    # -- round 0: lock on A -------------------------------------------------
    cs._enter_new_round(1, 0)
    drain(cs)
    cs._handle(("proposal", signed_proposal(cs, privs, vs, 0, -1, block_a)),
               write_wal=False)
    drain(cs)
    assert own_votes(captured, canonical.PREVOTE_TYPE, 0), "no prevote"
    for p in others[:2]:
        peer_vote(cs, p, vs, canonical.PREVOTE_TYPE, 0,
                  block_a.block_id())
    assert cs.locked_round == 0
    assert cs.locked_block.hash() == block_a.hash()
    # round 0 fails to commit: +2/3 precommit nil -> next round
    for p in others:
        peer_vote(cs, p, vs, canonical.PRECOMMIT_TYPE, 0, BlockID())
    fire(cs, 0, STEP_PRECOMMIT_WAIT)
    assert cs.round == 1

    # -- round 1: B gets +2/3 prevotes, but we see the last one late --------
    # (so the majority never reaches enterPrecommit, which would re-lock)
    peer_vote(cs, others[0], vs, canonical.PREVOTE_TYPE, 1,
              block_b.block_id())
    peer_vote(cs, others[1], vs, canonical.PREVOTE_TYPE, 1,
              block_b.block_id())
    # we never saw a round-1 proposal: prevote nil off the propose timeout
    from cometbft_tpu.consensus.state import STEP_PROPOSE
    fire(cs, 1, STEP_PROPOSE)
    nil_pv = own_votes(captured, canonical.PREVOTE_TYPE, 1)
    assert nil_pv and nil_pv[-1].block_id.is_nil(), \
        "locked node must prevote nil without the proposal"
    fire(cs, 1, STEP_PREVOTE_WAIT)  # -> precommit nil, lock kept
    assert cs.locked_round == 0, "lock must survive a nil round"
    # the straggler round-1 prevote lands AFTER we precommitted: now our
    # vote sets hold a POL for B at round 1
    peer_vote(cs, others[2], vs, canonical.PREVOTE_TYPE, 1,
              block_b.block_id())
    assert cs.locked_block.hash() == block_a.hash()
    for p in others:
        peer_vote(cs, p, vs, canonical.PRECOMMIT_TYPE, 1, BlockID())
    fire(cs, 1, STEP_PRECOMMIT_WAIT)
    assert cs.round == 2

    # -- round 2: proposal B arrives with pol_round=1 -> unlock -------------
    cs._handle(("proposal", signed_proposal(cs, privs, vs, 2, 1, block_b)),
               write_wal=False)
    drain(cs)
    pv2 = own_votes(captured, canonical.PREVOTE_TYPE, 2)
    assert pv2 and pv2[-1].block_id.hash == block_b.hash(), \
        "POL at round 1 must unlock the round-0 lock"

    # +2/3 prevotes for B in round 2 -> re-lock on B, precommit B
    for p in others[:2]:
        peer_vote(cs, p, vs, canonical.PREVOTE_TYPE, 2,
                  block_b.block_id())
    assert cs.locked_round == 2
    assert cs.locked_block.hash() == block_b.hash()
    pc2 = own_votes(captured, canonical.PRECOMMIT_TYPE, 2)
    assert pc2 and pc2[-1].block_id.hash == block_b.hash()


def test_no_unlock_without_pol_evidence():
    """A proposal claiming pol_round=1 without +2/3 prevotes at round 1 in
    our sets must NOT unlock (the 2f+1 trigger of alg. line 28)."""
    privs = [PrivKey.generate(bytes([i + 80]) * 32) for i in range(4)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis(CHAIN, vs)
    exec_ = BlockExecutor(KVStoreApplication(), StateStore(":memory:"))
    captured = []
    cs = ConsensusState(state, exec_, BlockStore(":memory:"),
                        privval=FilePV(privs[0]), manual_ticker=True,
                        broadcast=captured.append)
    cs._started = True
    cs.decide_proposal_fn = lambda h, r: None

    last_commit = Commit(0, 0, BlockID(), [])
    prop0 = cs.proposer_for_round(0).address
    block_a = exec_.create_proposal_block(1, state, last_commit, prop0,
                                          txs=[b"a=1"])
    block_b = exec_.create_proposal_block(1, state, last_commit, prop0,
                                          txs=[b"b=2"])
    others = [p for p in privs if p is not privs[0]]

    cs._enter_new_round(1, 0)
    drain(cs)
    cs._handle(("proposal", signed_proposal(cs, privs, vs, 0, -1, block_a)),
               write_wal=False)
    drain(cs)
    for p in others[:2]:
        peer_vote(cs, p, vs, canonical.PREVOTE_TYPE, 0,
                  block_a.block_id())
    assert cs.locked_round == 0
    for p in others:
        peer_vote(cs, p, vs, canonical.PRECOMMIT_TYPE, 0, BlockID())
    fire(cs, 0, STEP_PRECOMMIT_WAIT)
    for p in others:
        peer_vote(cs, p, vs, canonical.PRECOMMIT_TYPE, 1, BlockID())
    from cometbft_tpu.consensus.state import STEP_PROPOSE
    fire(cs, 1, STEP_PROPOSE)
    fire(cs, 1, STEP_PRECOMMIT_WAIT)
    assert cs.round == 2

    # round 2: B proposed with a LYING pol_round=1 (no prevotes seen)
    cs._handle(("proposal", signed_proposal(cs, privs, vs, 2, 1, block_b)),
               write_wal=False)
    drain(cs)
    pv2 = own_votes(captured, canonical.PREVOTE_TYPE, 2)
    assert pv2 and pv2[-1].block_id.is_nil(), \
        "no POL evidence -> stay locked, prevote nil"
    assert cs.locked_block.hash() == block_a.hash()
