"""Canonical sign-bytes: byte-exact golden vectors from the reference.

Vectors copied from types/vote_test.go TestVoteSignBytesTestVectors and
types/proposal_test.go — if these bytes drift, every signature in the
network becomes invalid, so they are THE compatibility gate.
"""
import pytest

from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.timestamp import Timestamp, ZERO


def bz(*v):
    return bytes(v)


CASES = [
    # 0: empty vote, empty chain id
    (
        "", 0, 0, 0, None, ZERO,
        bz(0x0D, 0x2A, 0x0B, 0x08, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE,
           0xFF, 0xFF, 0xFF, 0x01),
    ),
    # 1: precommit, height 1 round 1
    (
        "", canonical.PRECOMMIT_TYPE, 1, 1, None, ZERO,
        bz(0x21,
           0x08, 0x02,
           0x11, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x19, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x2A, 0x0B, 0x08, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF,
           0xFF, 0xFF, 0x01),
    ),
    # 2: prevote, height 1 round 1
    (
        "", canonical.PREVOTE_TYPE, 1, 1, None, ZERO,
        bz(0x21,
           0x08, 0x01,
           0x11, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x19, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x2A, 0x0B, 0x08, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF,
           0xFF, 0xFF, 0x01),
    ),
    # 3: no type, height 1 round 1
    (
        "", 0, 1, 1, None, ZERO,
        bz(0x1F,
           0x11, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x19, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x2A, 0x0B, 0x08, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF,
           0xFF, 0xFF, 0x01),
    ),
    # 4: with chain id
    (
        "test_chain_id", 0, 1, 1, None, ZERO,
        bz(0x2E,
           0x11, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x19, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
           0x2A, 0x0B, 0x08, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF,
           0xFF, 0xFF, 0x01,
           0x32, 0x0D) + b"test_chain_id",
    ),
]


@pytest.mark.parametrize("case", range(len(CASES)))
def test_vote_sign_bytes_golden(case):
    chain_id, vtype, h, r, bid, ts, want = CASES[case]
    got = canonical.canonical_vote_bytes(chain_id, vtype, h, r, bid, ts)
    assert got == want, f"case {case}: {got.hex()} != {want.hex()}"


def test_block_id_encoding():
    bid = BlockID(b"\xaa" * 32, PartSetHeader(3, b"\xbb" * 32))
    body = canonical.canonical_block_id_body(bid)
    # field 1: hash; field 2: part set header {total: varint, hash}
    assert body[0] == 0x0A and body[1] == 32
    psh_off = 2 + 32
    assert body[psh_off] == 0x12  # field 2, wire bytes
    inner = body[psh_off + 2:]
    assert inner[0] == 0x08 and inner[1] == 3
    assert inner[2] == 0x12 and inner[3] == 32


def test_nil_block_id_omitted():
    with_nil = canonical.canonical_vote_bytes(
        "c", canonical.PRECOMMIT_TYPE, 5, 0, BlockID(), ZERO
    )
    with_none = canonical.canonical_vote_bytes(
        "c", canonical.PRECOMMIT_TYPE, 5, 0, None, ZERO
    )
    assert with_nil == with_none
    assert b"\x22" not in with_nil[:3]  # no field-4 tag


def test_timestamp_roundtrip_values():
    # positive time: 2022-01-01T00:00:00.5Z
    ts = Timestamp(1640995200, 500000000)
    got = canonical.canonical_vote_bytes("x", 1, 2, 3, None, ts)
    # must contain the timestamp submessage with both fields
    from cometbft_tpu.libs import protoenc as pe
    sub = pe.timestamp(ts.seconds, ts.nanos)
    assert sub in got
