"""The self-tuning control plane (libs/controller): hysteresis,
cooldowns, clamp bounds, the structurally-off-limits CONSENSUS lane,
the bounded decision ledger, and the module-global dump surface.

All host-only: the controller is driven against fakes here (the live
plane/admission integration is covered by test_verify_plane's setter
tests and the simnet scenarios in test_soak)."""
import pytest

from cometbft_tpu.libs import controller as cp


class FakeLedger:
    """Height ledger stand-in: len + the commit-latency summary."""

    def __init__(self):
        self.p99 = 0.0

    def __len__(self):
        return 1

    def summary(self):
        return {"commit_latency_ms": {"p99": self.p99}}


class FakeFlushLedger:
    def __init__(self):
        self.device = {}

    def summary(self):
        return {"device": self.device} if self.device else {}


class FakePlane:
    def __init__(self, bulk_ms=8.0, gw_ms=4.0, deadline_ms=400.0,
                 flights=1, flights_max=4):
        self.bulk_window = bulk_ms / 1000.0
        self.gateway_window = gw_ms / 1000.0
        self.bulk_deadline = deadline_ms / 1000.0
        self.flights = flights
        self.flights_max = flights_max
        self.sheds = {"consensus": 0, "gateway": 0, "bulk": 0}
        self.ledger = FakeFlushLedger()
        self.applied = []

    def set_lane_window_ms(self, lane, ms):
        assert lane in ("gateway", "bulk")
        self.applied.append(("window", lane, ms))
        if lane == "bulk":
            self.bulk_window = ms / 1000.0
        else:
            self.gateway_window = ms / 1000.0
        return ms

    def set_lane_deadline_ms(self, lane, ms):
        assert lane in ("gateway", "bulk")
        self.applied.append(("deadline", lane, ms))
        self.bulk_deadline = ms / 1000.0
        return ms

    def set_flights(self, n):
        self.applied.append(("flights", n))
        self.flights = min(self.flights_max, max(1, int(n)))
        return self.flights


class FakeAdmission:
    def __init__(self, high=0.9, low=0.7):
        self.high_watermark = high
        self.low_watermark = low
        self.fill = 0.0
        self._fill_fn = lambda: self.fill

    def set_watermarks(self, high, low):
        self.high_watermark = min(1.0, max(0.01, float(high)))
        self.low_watermark = min(max(0.0, float(low)),
                                 self.high_watermark)
        return (self.high_watermark, self.low_watermark)


BOUNDS = {
    cp.ACT_BULK_WINDOW: (8.0, 24.0),
    cp.ACT_GATEWAY_WINDOW: (4.0, 12.0),
    cp.ACT_BULK_DEADLINE: (50.0, 400.0),
    cp.ACT_ADMISSION: (0.2, 0.9),
}


def make(plane=None, admission=None, ledger=None, **kw):
    kw.setdefault("decision_interval", 1)
    kw.setdefault("cooldown", 0)
    c = cp.Controller(**kw)
    c.attach(plane=plane, admission=admission, height_ledger=ledger,
             bounds=BOUNDS)
    return c


def test_attach_builds_only_sheddable_actuators():
    plane, adm = FakePlane(), FakeAdmission()
    c = make(plane, adm, FakeLedger())
    names = set(c.actuator_values())
    assert names == {cp.ACT_BULK_WINDOW, cp.ACT_GATEWAY_WINDOW,
                     cp.ACT_BULK_DEADLINE, cp.ACT_ADMISSION,
                     cp.ACT_FLIGHTS}
    # no CONSENSUS knob exists anywhere in the table
    assert not any("consensus" in n for n in names)


def test_consensus_lane_setters_rejected():
    """The plane-side half of the structural guarantee: the CONSENSUS
    lane has no controller-reachable setter path."""
    from cometbft_tpu.verifyplane.plane import VerifyPlane

    p = VerifyPlane(use_device=False)
    try:
        with pytest.raises(ValueError):
            p.set_lane_window_ms("consensus", 10.0)
        with pytest.raises(ValueError):
            p.set_lane_deadline_ms("consensus", 10.0)
    finally:
        p.stop()  # a live dispatcher thread would drag the whole suite


def test_pressure_latch_tightens_then_relaxes_to_base():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0)
    base = c.actuator_values()
    # SLO violated: the latch presses and every pressure actuator
    # takes one step in its tighten direction
    led.p99 = 250.0
    c.poke(1, 0)
    vals = c.actuator_values()
    assert vals[cp.ACT_ADMISSION] < base[cp.ACT_ADMISSION]
    assert vals[cp.ACT_BULK_WINDOW] > base[cp.ACT_BULK_WINDOW]
    assert vals[cp.ACT_GATEWAY_WINDOW] > base[cp.ACT_GATEWAY_WINDOW]
    assert vals[cp.ACT_BULK_DEADLINE] < base[cp.ACT_BULK_DEADLINE]
    # the admission spread is preserved by the apply
    assert adm.high_watermark == pytest.approx(
        vals[cp.ACT_ADMISSION])
    assert adm.high_watermark - adm.low_watermark == pytest.approx(
        0.2)
    # p99 back to mid-range but above pressure_low * slo: the latch
    # HOLDS (hysteresis — no flap at the boundary)
    led.p99 = 80.0
    c.poke(2, 0)
    assert c.dump()["state"]["pressed"]
    # full headroom: latch releases and actuators walk back to base
    led.p99 = 10.0
    for h in range(3, 20):
        c.poke(h, 0)
    vals = c.actuator_values()
    for name, v in vals.items():
        assert v == pytest.approx(base[name]), name
    assert not c.dump()["state"]["pressed"]


def test_relax_never_passes_base():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0)
    base = c.actuator_values()
    led.p99 = 0.0
    for h in range(40):  # headroom forever: nothing may drift past base
        c.poke(h, 0)
    assert c.actuator_values() == pytest.approx(base)
    assert c.dump()["state"]["decisions_total"] == 0


def test_fill_pressure_triggers_before_shed_storm():
    """Mempool fill climbing toward the watermark presses the latch
    even with commit p99 healthy — the pre-shed_storm trigger."""
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, fill_high=0.6, fill_low=0.3)
    adm.fill = 0.7
    c.poke(1, 0)
    assert c.dump()["state"]["pressed"]
    assert c.actuator_values()[cp.ACT_ADMISSION] < 0.9


def test_cooldown_gates_repeat_moves():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0, cooldown=3)
    led.p99 = 500.0
    c.poke(1, 0)
    n0 = c.dump()["state"]["decisions_total"]
    assert n0 > 0
    for h in range(2, 5):  # within the cooldown: no further moves
        c.poke(h, 0)
    assert c.dump()["state"]["decisions_total"] == n0
    c.poke(5, 0)  # cooldown elapsed: the next step lands
    assert c.dump()["state"]["decisions_total"] > n0


def test_runaway_loop_clamps_at_bounds():
    """Sustained pressure walks every actuator to its config bound and
    STOPS — a runaway loop degrades to the clamp, never past it."""
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0)
    led.p99 = 10_000.0
    for h in range(60):
        c.poke(h, 0)
    vals = c.actuator_values()
    assert vals[cp.ACT_ADMISSION] == pytest.approx(0.2)
    assert vals[cp.ACT_BULK_DEADLINE] == pytest.approx(50.0)
    # the window ceiling is the TIGHTER of the config bound and half
    # the lane's wait SLO (a window IS added latency on its lane)
    assert vals[cp.ACT_BULK_WINDOW] <= 24.0
    assert vals[cp.ACT_GATEWAY_WINDOW] <= 12.0
    # and the plane/admission saw only clamped values
    assert all(0.2 <= ms[2] or ms[0] != "window"
               for ms in plane.applied)
    assert adm.high_watermark >= 0.2


def test_window_ceiling_capped_by_wait_slo():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0,
             slo_bulk_wait_ms=20.0, slo_gateway_wait_ms=10.0)
    led.p99 = 10_000.0
    for h in range(60):
        c.poke(h, 0)
    vals = c.actuator_values()
    assert vals[cp.ACT_BULK_WINDOW] <= 10.0   # 20/2, not the 24 bound
    assert vals[cp.ACT_GATEWAY_WINDOW] <= 5.0


def test_decision_interval_gates_evaluation():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, decision_interval=4,
             slo_commit_p99_ms=100.0)
    led.p99 = 500.0
    for h in range(3):
        c.poke(h, 0)
    assert c.dump()["state"]["evals"] == 0
    c.poke(3, 0)
    assert c.dump()["state"]["evals"] == 1


def test_deck_grows_on_low_util_h2d_bound():
    from cometbft_tpu.libs import incidents

    plane, adm, led = FakePlane(flights=1, flights_max=4), \
        FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, deck_min_flushes=4)
    # storms fired earlier in the test session are history, not signal
    c._last_storms = int(
        incidents.recorder().fired.get("compile_storm", 0))
    plane.ledger.device = {
        "fused_flushes": 10,
        "util": {"p50": 0.2}, "h2d_ms": {"p50": 3.0},
        "dev_ms": {"p50": 1.0},
    }
    c.poke(1, 0)
    assert plane.flights == 2
    # no FRESH fused evidence since the grow: no further move
    c.poke(2, 0)
    assert plane.flights == 2
    plane.ledger.device["fused_flushes"] = 20
    c.poke(3, 0)
    assert plane.flights == 3
    # the ceiling: flights_max, never past
    plane.ledger.device["fused_flushes"] = 99
    for h in range(4, 10):
        plane.ledger.device["fused_flushes"] += 10
        c.poke(h, 0)
    assert plane.flights <= plane.flights_max


def test_deck_shrinks_on_compile_storm():
    from cometbft_tpu.libs import incidents

    plane = FakePlane(flights=3, flights_max=4)
    c = make(plane, FakeAdmission(), FakeLedger())
    rec = incidents.recorder()
    # pre-existing storm counts must NOT shrink a fresh controller:
    # only a NEW storm (delta) is a signal
    c._last_storms = int(rec.fired.get("compile_storm", 0))
    rec.fired["compile_storm"] = c._last_storms + 1
    try:
        c.poke(1, 0)
        assert plane.flights == 2
    finally:
        rec.fired["compile_storm"] = max(
            0, rec.fired.get("compile_storm", 1) - 1)


def test_decision_ring_bounded_and_dump_shape():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0, capacity=8)
    led.p99 = 500.0
    for h in range(200):
        led.p99 = 500.0 if h % 2 else 1.0  # thrash to generate moves
        c.poke(h, 0)
    d = c.dump()
    assert len(d["decisions"]) <= 8
    assert set(d["decisions"][-1]) >= {
        "seq", "at_ms", "height", "actuator", "direction", "old",
        "new", "relax", "trigger", "cooldowns"}
    for name, a in d["actuators"].items():
        assert a["min"] <= a["value"] <= a["max"], name
    assert d["slo"]["commit_p99_ms"] == 100.0
    assert d["state"]["decisions_total"] >= len(d["decisions"])
    # decision_counts agree with the total
    assert sum(c.decision_counts.values()) == \
        d["state"]["decisions_total"]


def test_refused_apply_is_a_non_decision():
    class RefusingAdmission(FakeAdmission):
        def set_watermarks(self, high, low):
            raise RuntimeError("refused")

    adm = RefusingAdmission()
    led = FakeLedger()
    c = make(None, adm, led, slo_commit_p99_ms=100.0)
    led.p99 = 500.0
    c.poke(1, 0)
    assert c.dump()["state"]["decisions_total"] == 0
    assert adm.high_watermark == 0.9  # untouched


def test_module_globals_and_dump_survive_clear():
    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0)
    old_global, old_last = cp._GLOBAL, cp._LAST
    try:
        cp.set_global_controller(c)
        assert cp.global_controller() is c
        led.p99 = 500.0
        cp.poke(1, 0)  # the module seam drives the registered one
        assert c.dump()["state"]["pokes"] == 1
        mark = cp.controller_mark()
        assert not cp.controller_advanced(mark)
        cp.clear_global_controller(c)
        assert cp.global_controller() is None
        # _LAST serves post-mortem dumps after stop
        assert cp.dump_controller()["state"]["pokes"] == 1
        assert cp.controller_tail(4) != [] or \
            cp.dump_controller()["state"]["decisions_total"] == 0
        # pokes after clear are no-ops
        cp.poke(2, 0)
        assert c.dump()["state"]["pokes"] == 1
    finally:
        cp._GLOBAL, cp._LAST = old_global, old_last


def test_empty_dump_shape():
    old_global, old_last = cp._GLOBAL, cp._LAST
    try:
        cp._GLOBAL = cp._LAST = None
        d = cp.dump_controller()
        assert d["decisions"] == [] and d["actuators"] == {}
        assert d["state"]["decisions_total"] == 0
        assert cp.controller_mark() == (None, -1)
        assert cp.controller_tail() == []
    finally:
        cp._GLOBAL, cp._LAST = old_global, old_last


def test_metrics_families_sampled():
    """The controller_* families land in /metrics from the registered
    controller, and survive its clearing via _LAST."""
    from cometbft_tpu.libs.metrics import NodeMetrics

    plane, adm, led = FakePlane(), FakeAdmission(), FakeLedger()
    c = make(plane, adm, led, slo_commit_p99_ms=100.0)
    old_global, old_last = cp._GLOBAL, cp._LAST
    try:
        cp.set_global_controller(c)
        led.p99 = 500.0
        c.poke(1, 0)
        text = NodeMetrics().expose_text()
        assert "cometbft_controller_decisions_total{" in text
        assert 'actuator="admission_high_watermark"' in text
        assert "cometbft_controller_actuator_value{" in text
        assert "cometbft_controller_slo_violation_seconds_total" \
            in text
        cp.clear_global_controller(c)
        assert "cometbft_controller_decisions_total{" in \
            NodeMetrics().expose_text()
    finally:
        cp._GLOBAL, cp._LAST = old_global, old_last


def test_config_section_build_bounds_and_roundtrip(tmp_path):
    from cometbft_tpu.config.config import (
        Config,
        ConfigError,
        load_config,
        save_config,
    )

    cfg = Config()
    assert cfg.controller.build() is None  # off by default
    cfg.controller.enable = True
    ctl = cfg.controller.build()
    assert ctl is not None
    b = cfg.controller.bounds(cfg.verify_plane, cfg.mempool)
    assert set(b) == {cp.ACT_BULK_WINDOW, cp.ACT_GATEWAY_WINDOW,
                      cp.ACT_BULK_DEADLINE, cp.ACT_ADMISSION}
    for lo, hi in b.values():
        assert lo <= hi
    # the admission floor never exceeds the configured watermark
    assert b[cp.ACT_ADMISSION][1] == cfg.mempool.high_watermark
    # TOML round-trip preserves the section
    cfg.controller.slo_commit_p99_ms = 321.0
    path = str(tmp_path / "config.toml")
    save_config(cfg, path)
    cfg2 = load_config(path)
    assert cfg2.controller.enable is True
    assert cfg2.controller.slo_commit_p99_ms == 321.0
    # validation: a deadline floor under one flush window is the
    # shed-everything misconfiguration and must be refused
    cfg2.controller.bulk_deadline_min_ms = 0.1
    with pytest.raises(ConfigError):
        cfg2.validate_basic()
    cfg2 = load_config(path)
    cfg2.controller.fill_low = 0.9  # must stay < fill_high
    with pytest.raises(ConfigError):
        cfg2.validate_basic()
    cfg2 = load_config(path)
    cfg2.controller.admission_floor = 0.99  # above mempool high mark
    with pytest.raises(ConfigError):
        cfg2.validate_basic()


def test_flights_max_config_validation():
    from cometbft_tpu.config.config import Config, ConfigError

    cfg = Config()
    cfg.verify_plane.pipeline_flights = 2
    cfg.verify_plane.pipeline_flights_max = 1  # below the static value
    with pytest.raises(ConfigError):
        cfg.validate_basic()


def test_node_controller_attr():
    """Every Node exposes .controller (None when the section is off) —
    the rpc dump route's lookup contract."""
    import inspect as _inspect

    from cometbft_tpu.node.node import Node

    assert "controller" in _inspect.signature(Node.__init__).parameters
