"""e2e: manifest-driven multi-PROCESS testnet with perturbations.

Reference: test/e2e — TOML manifests (pkg/manifest.go) rendered into
networks by runner/setup.go, perturbations (runner/perturb.go:44:
kill/restart/disconnect), then black-box invariant tests over RPC
(tests/block_test.go: all nodes agree on block hashes; chain keeps
growing). Here the manifest is a dataclass, nodes are real OS
processes running the operator CLI, and all assertions go through
each node's public RPC — nothing in-process.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Manifest:
    """test/e2e/pkg/manifest.go (subset)."""

    validators: int = 3
    chain_id: str = "e2e-chain"
    initial_height_target: int = 3
    perturbations: List[str] = field(default_factory=list)  # "kill:0" etc.


class Testnet:
    """runner/setup.go + start.go: generate homes via the CLI, run each
    node as a subprocess, expose RPC helpers."""

    __test__ = False  # pytest: not a test class despite the name

    def __init__(self, manifest: Manifest, root: str):
        self.m = manifest
        self.root = root
        self.procs: Dict[int, Optional[subprocess.Popen]] = {}
        self.rpc_ports: Dict[int, int] = {}
        # pid-derived port base so concurrent runs don't collide
        base = 20000 + (os.getpid() % 1000) * 32
        p2p_base, rpc_base = base, base + 16
        r = subprocess.run(
            [sys.executable, "-m", "cometbft_tpu", "testnet",
             "--v", str(manifest.validators), "--output", root,
             "--chain-id", manifest.chain_id,
             "--p2p-port", str(p2p_base), "--rpc-port", str(rpc_base)],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env=self._env(),
        )
        assert r.returncode == 0, r.stderr
        # fast timeouts for the test (manifest-level tuning knob)
        sys.path.insert(0, REPO)
        from cometbft_tpu.config.config import load_config, save_config

        for i in range(manifest.validators):
            cpath = os.path.join(root, f"node{i}", "config",
                                 "config.toml")
            cfg = load_config(cpath)
            cfg.consensus.timeout_propose = 1.0
            cfg.consensus.timeout_propose_delta = 0.3
            cfg.consensus.timeout_prevote = 0.5
            cfg.consensus.timeout_prevote_delta = 0.2
            cfg.consensus.timeout_precommit = 0.5
            cfg.consensus.timeout_precommit_delta = 0.2
            cfg.consensus.timeout_commit = 0.2
            cfg.crypto.verifier = "cpu"  # no TPU in subprocesses
            save_config(cfg, cpath)
            self.rpc_ports[i] = rpc_base + 2 * i

    @staticmethod
    def _env():
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        return env

    def start_node(self, i: int) -> None:
        home = os.path.join(self.root, f"node{i}")
        log = open(os.path.join(home, "node.log"), "ab")
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu", "start",
             "--home", home],
            stdout=log, stderr=log, cwd=REPO, env=self._env(),
        )

    def start(self) -> None:
        for i in range(self.m.validators):
            self.start_node(i)

    def kill_node(self, i: int) -> None:
        """perturb.go: kill (SIGKILL, no graceful anything)."""
        p = self.procs.get(i)
        if p is not None:
            p.kill()
            p.wait(timeout=30)
            self.procs[i] = None

    def pause_node(self, i: int) -> None:
        """perturb.go: pause (docker pause -> SIGSTOP here): the
        process freezes mid-whatever; peers see silence, not a
        closed socket."""
        p = self.procs.get(i)
        assert p is not None
        os.kill(p.pid, signal.SIGSTOP)

    def resume_node(self, i: int) -> None:
        p = self.procs.get(i)
        assert p is not None
        os.kill(p.pid, signal.SIGCONT)

    def privval_key(self, i: int):
        """The node's consensus signing key (for evidence forging,
        runner/evidence.go reads exactly this file)."""
        from cometbft_tpu.privval.file_pv import FilePV

        return FilePV.load(
            os.path.join(self.root, f"node{i}", "config")
        ).priv_key

    def genesis(self, i: int = 0) -> dict:
        with open(os.path.join(self.root, f"node{i}", "config",
                               "genesis.json")) as f:
            return json.load(f)

    def stop(self) -> None:
        for i, p in self.procs.items():
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 15
        for p in self.procs.values():
            if p is None:
                continue
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)

    # -- RPC helpers (black-box; tests/block_test.go style) ----------------

    def rpc(self, i: int, method: str, timeout: float = 5.0, **params):
        url = f"http://127.0.0.1:{self.rpc_ports[i]}/"
        body = json.dumps({"jsonrpc": "2.0", "method": method,
                           "params": params, "id": 1}).encode()
        req = urllib.request.Request(url, data=body, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            doc = json.loads(r.read())
        if "error" in doc:
            raise RuntimeError(doc["error"])
        return doc["result"]

    def height(self, i: int) -> int:
        try:
            return int(self.rpc(i, "status")["sync_info"]
                       ["latest_block_height"])
        except Exception:
            return -1

    def wait_for_height(self, target: int, nodes=None,
                        timeout: float = 180.0) -> None:
        nodes = list(nodes if nodes is not None
                     else range(self.m.validators))
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(self.height(i) >= target for i in nodes):
                return
            time.sleep(0.5)
        hs = {i: self.height(i) for i in nodes}
        raise AssertionError(f"testnet never reached {target}: {hs}")

    def assert_blocks_agree(self, upto: int, nodes=None) -> None:
        """block_test.go: every node reports the same hash per height."""
        nodes = list(nodes if nodes is not None
                     else range(self.m.validators))
        for h in range(1, upto + 1):
            hashes = set()
            for i in nodes:
                blk = self.rpc(i, "block", height=h)
                hashes.add(json.dumps(blk["block_id"], sort_keys=True))
            assert len(hashes) == 1, f"divergence at height {h}"


@pytest.mark.slow
def test_e2e_basic_and_kill_restart(tmp_path):
    """The core e2e scenario: a 4-validator subprocess net makes
    progress over real TCP + RPC; killing one validator does not halt
    the chain (3/4 power > 2/3 quorum remains); the restarted node
    recovers from its WAL/stores and catches back up; all nodes agree
    on every block hash."""
    m = Manifest(validators=4, perturbations=["kill:3", "restart:3"])
    net = Testnet(m, str(tmp_path / "net"))
    net.start()
    try:
        net.wait_for_height(2, timeout=240)

        # perturbation: kill node 3 (perturb.go kill arm)
        net.kill_node(3)
        survivors = [0, 1, 2]
        h = max(net.height(i) for i in survivors)
        net.wait_for_height(h + 2, nodes=survivors, timeout=180)

        # perturbation: restart (perturb.go restart arm) — node must
        # recover from its own WAL + stores and rejoin
        net.start_node(3)
        target = max(net.height(i) for i in survivors) + 2
        net.wait_for_height(target, timeout=240)

        net.assert_blocks_agree(2)
    finally:
        net.stop()
        for i in range(m.validators):
            logp = os.path.join(str(tmp_path / "net"), f"node{i}",
                                "node.log")
            if os.path.exists(logp):
                with open(logp, "rb") as f:
                    tail = f.read()[-800:]
                print(f"--- node{i} log tail ---\n"
                      f"{tail.decode(errors='replace')}")


@pytest.mark.slow
def test_e2e_perturbation_matrix(tmp_path):
    """perturb.go:44-60 matrix on a 5-validator net: pause (brief
    SIGSTOP — peers see silence), disconnect (long SIGSTOP — peer
    connections drop and must re-establish), kill+restart. After each
    perturbation the chain keeps committing and the perturbed node
    catches back up; at the end all five agree on every block hash."""
    m = Manifest(validators=5, chain_id="e2e-perturb",
                 perturbations=["pause:1", "disconnect:2", "kill:3",
                                "restart:3"])
    net = Testnet(m, str(tmp_path / "net"))
    net.start()
    try:
        net.wait_for_height(2, timeout=240)
        others = [0, 2, 3, 4]

        # pause: freeze node 1 for a few seconds; quorum (4/5) holds
        net.pause_node(1)
        h = max(net.height(i) for i in others)
        net.wait_for_height(h + 2, nodes=others, timeout=180)
        net.resume_node(1)
        net.wait_for_height(max(net.height(i) for i in others),
                            nodes=[1], timeout=180)

        # disconnect: freeze node 2 long enough that its TCP peers
        # drop it (send/recv stall -> peer error), then resume; it
        # must redial and catch up
        net.pause_node(2)
        time.sleep(12)
        others = [0, 1, 3, 4]
        h = max(net.height(i) for i in others)
        net.wait_for_height(h + 2, nodes=others, timeout=180)
        net.resume_node(2)
        net.wait_for_height(max(net.height(i) for i in others),
                            nodes=[2], timeout=240)

        # kill + restart (the round-4 scenario, now at 5 validators)
        net.kill_node(3)
        others = [0, 1, 2, 4]
        h = max(net.height(i) for i in others)
        net.wait_for_height(h + 2, nodes=others, timeout=180)
        net.start_node(3)
        net.wait_for_height(max(net.height(i) for i in others) + 1,
                            timeout=240)

        net.assert_blocks_agree(3)
    finally:
        net.stop()


@pytest.mark.slow
def test_e2e_byzantine_evidence_committed(tmp_path):
    """runner/evidence.go: forge DuplicateVoteEvidence with a real
    validator's key (two conflicting precommits at a past height),
    submit over public RPC, and require it to land inside a committed
    block which every node agrees on — the full byzantine pipeline
    pool -> gossip -> proposal -> commit, multi-process."""
    sys.path.insert(0, REPO)
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.evidence import (
        DuplicateVoteEvidence,
        evidence_to_j,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.vote import Vote

    m = Manifest(validators=5, chain_id="e2e-byz")
    net = Testnet(m, str(tmp_path / "net"))
    net.start()
    try:
        net.wait_for_height(3, timeout=240)

        # the byzantine double-signer: validator 4's real key
        priv = net.privval_key(4)
        addr = priv.pub_key().address()
        gen = net.genesis()
        from cometbft_tpu.crypto.keys import PubKey
        from cometbft_tpu.types.validator import Validator, ValidatorSet

        vset = ValidatorSet([
            Validator(PubKey(bytes.fromhex(v["pub_key"]["value"]),
                             v["pub_key"]["type"]), int(v["power"]))
            for v in gen["validators"]
        ])
        vidx, val = vset.get_by_address(addr)
        power = val.voting_power
        total = vset.total_voting_power()

        ev_h = 2  # a committed past height (valset known everywhere)
        now = Timestamp(int(time.time()), 0)

        def vote(tag):
            bid = BlockID(tag * 32, PartSetHeader(1, tag * 32))
            v = Vote(
                vote_type=canonical.PRECOMMIT_TYPE, height=ev_h,
                round=0, block_id=bid, timestamp=now,
                validator_address=addr, validator_index=vidx,
            )
            v.signature = priv.sign(v.sign_bytes(m.chain_id))
            return v

        ev = DuplicateVoteEvidence.from_votes(
            vote(b"\xaa"), vote(b"\xbb"), now, total, power
        )
        r = net.rpc(0, "broadcast_evidence",
                    evidence=evidence_to_j(ev))
        assert r["hash"]

        # the evidence must appear inside a committed block
        deadline = time.time() + 180
        found_at = None
        scanned = 3
        while time.time() < deadline and found_at is None:
            head = net.height(0)
            for h in range(scanned, head + 1):
                blk = net.rpc(0, "block", height=h)["block"]
                evs = blk.get("evidence") or []
                if any(e.get("t") == "duplicate_vote" for e in evs):
                    found_at = h
                    break
            scanned = max(scanned, head)
            time.sleep(0.5)
        assert found_at is not None, "evidence never committed"
        # every node sees the same evidence block (gossip + agreement)
        net.wait_for_height(found_at, timeout=120)
        for i in range(5):
            blk = net.rpc(i, "block", height=found_at)["block"]
            evs = blk.get("evidence") or []
            assert any(e.get("t") == "duplicate_vote" for e in evs)
        # and the chain keeps going after punishing its validator
        net.wait_for_height(found_at + 2, timeout=120)
    finally:
        net.stop()
