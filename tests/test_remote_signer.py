"""Remote signer: node holds no key, the signer process does.

Reference: privval/signer_listener_endpoint.go + signer_client_test.go.
"""
import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import DoubleSignError, FilePV
from cometbft_tpu.privval.remote import (
    RemoteSignerError,
    SignerListenerEndpoint,
    SignerServer,
)
from cometbft_tpu.state.state import State
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


@pytest.fixture()
def remote_pair():
    priv = PrivKey.generate(b"\x0c" * 32)
    listener = SignerListenerEndpoint()
    signer = SignerServer(FilePV(priv), *listener.addr)
    signer.start()
    assert listener.wait_for_signer(10)
    try:
        yield priv, listener
    finally:
        signer.stop()
        listener.close()


def test_sign_and_double_sign_protection(remote_pair):
    priv, listener = remote_pair
    assert listener.pub_key().data == priv.pub_key().data
    bid_a = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xaa" * 32))
    bid_b = BlockID(b"\xbb" * 32, PartSetHeader(1, b"\xbb" * 32))
    addr = priv.pub_key().address()
    v1 = Vote(vote_type=canonical.PREVOTE_TYPE, height=5, round=0,
              block_id=bid_a, timestamp=Timestamp(1, 0),
              validator_address=addr, validator_index=0)
    sig = listener.sign_vote("rs-chain", v1)
    assert priv.pub_key().verify_signature(v1.sign_bytes("rs-chain"), sig)
    # conflicting vote at the same HRS: the SIGNER refuses
    v2 = Vote(vote_type=canonical.PREVOTE_TYPE, height=5, round=0,
              block_id=bid_b, timestamp=Timestamp(1, 0),
              validator_address=addr, validator_index=0)
    with pytest.raises(RemoteSignerError) as ei:
        listener.sign_vote("rs-chain", v2)
    assert "DoubleSign" in str(ei.value)


def test_validator_runs_with_remote_signer(tmp_path, remote_pair):
    priv, listener = remote_pair
    state = State.make_genesis(
        "rs-chain", ValidatorSet([Validator(priv.pub_key(), 10)])
    )
    node = Node(KVStoreApplication(), state, privval=listener,
                home=str(tmp_path / "n0"), timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(3, timeout=60)
    finally:
        node.stop()
