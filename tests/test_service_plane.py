"""Service plane: rollback, compact, inspect, light proxy, abci-cli.

Reference: cmd/cometbft/commands/rollback.go, compact.go,
inspect/inspect.go, light/proxy/proxy.go, abci/cmd/abci-cli.
"""
import base64
import json
import urllib.request

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.cmd.cli import main as cli_main
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def _run_chain(tmp_path, name="n0", height=4):
    priv = PrivKey.generate(bytes([9]) * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("svc-chain", vals)
    home = str(tmp_path / name)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=home, timeouts=FAST)
    node.start()
    assert node.consensus.wait_for_height(height, timeout=60)
    node.stop()
    return home, priv, state


def test_rollback_and_restart(tmp_path):
    home, priv, genesis = _run_chain(tmp_path)
    from cometbft_tpu.state.state import StateStore

    ss = StateStore(f"{home}/state.db")
    before = ss.load().last_block_height
    ss.close()

    rc = cli_main(["rollback", "--home", str(tmp_path / "n0x")])
    assert rc == 1  # empty home: nothing to roll back

    # the CLI's home layout is <home>/data; our test node wrote straight
    # into `home`, so fake the layout with a symlink-style shim
    import os
    os.makedirs(str(tmp_path / "wrap"), exist_ok=True)
    os.symlink(home, str(tmp_path / "wrap" / "data"))
    rc = cli_main(["rollback", "--home", str(tmp_path / "wrap")])
    assert rc == 0
    ss = StateStore(f"{home}/state.db")
    after = ss.load()
    assert after.last_block_height == before - 1
    ss.close()

    # a node over the rolled-back home re-applies the block and continues
    node = Node(KVStoreApplication(), genesis,
                privval=FilePV(priv), home=home, timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(before + 1, timeout=60)
    finally:
        node.stop()


def test_compact(tmp_path, capsys):
    home, _, _ = _run_chain(tmp_path)
    import os
    os.makedirs(str(tmp_path / "wrap2"), exist_ok=True)
    os.symlink(home, str(tmp_path / "wrap2" / "data"))
    assert cli_main(["compact", "--home", str(tmp_path / "wrap2")]) == 0
    out = capsys.readouterr().out
    assert "blockstore.db" in out


def test_inspect_server(tmp_path):
    home, _, _ = _run_chain(tmp_path)
    from cometbft_tpu.inspect import InspectServer

    srv = InspectServer(home)
    srv.start()
    try:
        base = srv.address
        with urllib.request.urlopen(base + "/status", timeout=5) as r:
            st = json.loads(r.read())["result"]
        assert int(st["sync_info"]["latest_block_height"]) >= 4
        with urllib.request.urlopen(base + "/block?height=2",
                                    timeout=5) as r:
            blk = json.loads(r.read())["result"]
        assert blk["block"]["header"]["height"] == 2
        # read-only: broadcast refused
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "broadcast_tx_sync",
                           "params": {"tx": "aa"}}).encode()
        req = urllib.request.Request(base + "/", data=body, method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            doc = json.loads(r.read())
        assert "error" in doc
    finally:
        srv.stop()


def test_light_proxy(tmp_path):
    """A light proxy against a live node verifies what it serves."""
    priv = PrivKey.generate(bytes([12]) * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("proxy-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "full"), timeouts=FAST)
    node.start()
    url = node.rpc_listen("127.0.0.1", 0)
    try:
        assert node.consensus.wait_for_height(3, timeout=60)
        from cometbft_tpu.light.proxy import LightProxy

        proxy = LightProxy("proxy-chain", url, trusted_height=1)
        proxy.start()
        try:
            base = proxy.address
            with urllib.request.urlopen(base + "/commit?height=2",
                                        timeout=30) as r:
                c = json.loads(r.read())["result"]
            assert c["verified"] is True
            assert c["signed_header"]["header"]["height"] == 2
            with urllib.request.urlopen(base + "/block?height=2",
                                        timeout=30) as r:
                b = json.loads(r.read())["result"]
            assert b["verified"] is True
            with urllib.request.urlopen(base + "/validators?height=2",
                                        timeout=30) as r:
                v = json.loads(r.read())["result"]
            assert v["verified"] and len(v["validators"]) == 1

            # VERIFIED data queries (light/rpc/client.go:117): commit a
            # tx, query it through the proxy — result is proof-checked
            # against the trusted header chain
            from cometbft_tpu.rpc.client import HTTPClient

            res = HTTPClient(url).broadcast_tx_commit(b"lp=ok")
            assert node.consensus.wait_for_height(res["height"] + 1,
                                                  timeout=60)
            with urllib.request.urlopen(
                base + "/abci_query?data=" + b"lp".hex(), timeout=60
            ) as r:
                q = json.loads(r.read())["result"]["response"]
            assert q["verified"] is True
            assert base64.b64decode(q["value"]) == b"ok"

            txhash = res["hash"]
            with urllib.request.urlopen(
                base + f"/tx?hash={txhash}", timeout=60
            ) as r:
                t = json.loads(r.read())["result"]
            assert t["verified"] is True
            assert base64.b64decode(t["tx"]) == b"lp=ok"

            # a LYING primary is caught: tamper the served value by
            # pointing the proxy's raw-http client at a mitm that
            # rewrites query responses
            class _MITM:
                def __init__(self, inner):
                    self.inner = inner

                def __getattr__(self, a):
                    return getattr(self.inner, a)

                def call(self, method, **params):
                    r = self.inner.call(method, **params)
                    if method == "abci_query":
                        r["response"]["value"] = base64.b64encode(
                            b"evil"
                        ).decode()
                    if method == "tx":
                        r["tx"] = base64.b64encode(b"evil=1").decode()
                        if "proof" in r:
                            r["proof"]["data"] = b"evil=1".hex()
                    return r

            proxy_obj = proxy.httpd.proxy
            saved = proxy_obj.http
            proxy_obj.http = _MITM(saved)
            try:
                with urllib.request.urlopen(
                    base + "/abci_query?data=" + b"lp".hex(), timeout=60
                ) as r:
                    doc = json.loads(r.read())
                assert "error" in doc, "tampered query result accepted!"
                with urllib.request.urlopen(
                    base + f"/tx?hash={txhash}", timeout=60
                ) as r:
                    doc = json.loads(r.read())
                assert "error" in doc, "tampered tx accepted!"
            finally:
                proxy_obj.http = saved
        finally:
            proxy.stop()
    finally:
        node.stop()


def test_abci_cli_oneshot(capsys):
    from cometbft_tpu.abci.server import ABCISocketServer

    srv = ABCISocketServer(KVStoreApplication())
    srv.start()
    try:
        addr = f"{srv.addr[0]}:{srv.addr[1]}"
        assert cli_main(["abci", "info", "--addr", addr]) == 0
        assert "height: 0" in capsys.readouterr().out
        assert cli_main(["abci", "check_tx", "k=v", "--addr", addr]) == 0
        assert "code: 0" in capsys.readouterr().out
        assert cli_main(["abci", "query", "k", "--addr", addr]) == 0
    finally:
        srv.stop()
