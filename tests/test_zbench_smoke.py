"""Tier-1 guard against bench.py rot (ISSUE 6 satellite).

bench.py only runs on the driver's TPU host, so a broken fixture or a
drifted API surfaced one round LATE — as a FAILED config in the next
BENCH_rNN instead of a red test here. `--smoke` is the tier-1-safe
slice: tiny shapes, host paths only, no jax import, seconds not
minutes. This file (late in the alphabet on purpose: by the time it
runs, the cheap unit tests have already localized any real breakage)
drives the smoke run through main() exactly like the CLI would, then
proves the --baseline comparator actually catches a regression by
injecting a synthetic one.
"""
import json
import sys

import pytest

import bench


def _run(argv, capsys):
    rc = bench.main(argv)
    out = capsys.readouterr().out
    lines = [json.loads(ln) for ln in out.strip().splitlines()
             if ln.startswith("{")]
    return rc, lines


def test_bench_smoke_runs_host_only(tmp_path, capsys):
    """The smoke slice completes in seconds, produces the JSON-line
    shape every consumer (driver tails, load_bench_results, the
    baseline comparator) parses, and never imports jax."""
    jax_loaded_before = "jax" in sys.modules
    out_path = tmp_path / "smoke.json"
    rc, lines = _run(["--smoke", "--json-out", str(out_path)], capsys)
    assert rc == 0
    by_metric = {ln["metric"]: ln for ln in lines}
    assert "smoke summary" in by_metric
    assert by_metric["smoke summary"]["value"] == 14  # all configs ran
    for ln in lines:
        assert set(ln) >= {"metric", "value", "unit", "vs_baseline"}
    # every smoke config produced a real number (no FAILED entries)
    results = json.loads(out_path.read_text())["results"]
    assert sorted(results) == ["cfg10_smoke", "cfg11_smoke",
                               "cfg12_smoke", "cfg13_smoke",
                               "cfg14_smoke", "cfg15_smoke",
                               "cfg16_smoke", "cfg17_smoke",
                               "cfg18_smoke", "cfg19_smoke",
                               "cfg20_smoke", "cfg2_smoke",
                               "cfg4_smoke", "cfg6_smoke"]
    assert all(r["value"] is not None for r in results.values())
    # the cfg6 miniature exercised the always-on flush ledger
    assert results["cfg6_smoke"]["extra"]["ledger"]["flushes"] >= 1
    # the cfg10 miniature proved ledger-counted gateway coalescing
    g = results["cfg10_smoke"]["extra"]
    assert g["plane_subs_gateway"] <= 0.5 * g["plane_subs_uncoalesced"]
    assert g["verifies"] < g["requests"]
    # the cfg4 miniature carries the disabled-path hook-cost proof row
    dfp = results["cfg4_smoke"]["extra"]["disabled_flush_path"]
    assert dfp["ledger_bookkeeping_us_per_flush"] > 0
    # the cfg11 miniature proved the sharded layout + ledger n_dev
    sh = results["cfg11_smoke"]["extra"]
    assert sh["ledger_n_dev"] == 1
    assert sh["shard_summary"]["flushes"] == 0
    # the cfg12 miniature proved the flight-deck plumbing (staging
    # depth flights+1, deck ledger columns, ready-first picker)
    dk = results["cfg12_smoke"]["extra"]
    assert dk["staging_slots"] == 3
    assert dk["deck_summary"]["airborne_max"] == 0
    # the cfg13 miniature proved churn eviction pressure + the warmer
    # degrade/attribution plumbing (bounded caches, jax-free)
    ch = results["cfg13_smoke"]["extra"]
    assert ch["evictions"] > 0
    assert ch["resident_bytes_peak"] <= 4 * 4096
    assert ch["warmer"]["builds_failed"] == 1
    assert ch["warmer"]["builds_ok"] == 1
    # the cfg14 miniature proved the gossip-observatory bookkeeping
    # cost (the per-message seam every MConnection/SimConn hop rides)
    pd = results["cfg14_smoke"]["extra"]["peer_path"]
    assert 0 < pd["send_us_per_msg"] < 10.0
    assert 0 < pd["recv_us_per_msg"] < 10.0
    # the cfg15 miniature proved the device observatory: compile
    # attribution, the compile_storm trigger, residency math, and the
    # per-flush hook budget
    dv = results["cfg15_smoke"]["extra"]
    assert dv["storm_fired"] == "compile_storm"
    assert dv["compiles"] == 64
    assert 0 < dv["flush_hooks"]["flush_hook_us_per_flush"] < 10.0
    # the cfg16 miniature proved the closed loop: tighten at peak,
    # relax to base at the trough, clamps honored, consensus untouched
    # — and embedded the dump tools/controller_report.py reads
    ct = results["cfg16_smoke"]["extra"]
    assert all(ct["checks"].values()), ct["checks"]
    assert ct["decisions_total"] >= 6
    assert ct["controller_dump"]["decisions"], ct["controller_dump"]
    # the cfg17 miniature proved the multi-tenant pod: identical
    # verdicts shared vs split, fused cross-tenant flushes with exact
    # per-tenant attribution, and the embedded /dump_tenants document
    # tools/tenant_report.py reads
    tn = results["cfg17_smoke"]["extra"]
    assert all(tn["checks"].values()), tn["checks"]
    assert tn["coalesced_flushes"] >= 1
    assert tn["flushes_shared"] <= tn["flushes_split"]
    # residency attribution may add a "default" entry for tables other
    # smoke configs left in the process-global cache — the bench
    # tenants themselves must both be present with their full rows
    assert {"bench-0", "bench-1"} <= set(tn["tenants_dump"]["tenants"])
    # the cfg18 miniature proved the catch-up firehose: mid-replay
    # kill resumes from the persisted cursor re-verifying ZERO
    # already-applied blocks, boundaries pre-scanned, warm-ahead
    # fired, and the /dump_catchup document embedded for
    # tools/catchup_report.py
    cu = results["cfg18_smoke"]["extra"]
    assert all(cu["checks"].values()), cu["checks"]
    assert cu["reverified_after_resume"] == 0
    assert cu["catchup_dump"]["records"], cu["catchup_dump"]
    assert cu["catchup_dump"]["counters"]["resumes"] >= 1
    # the cfg19 miniature proved the delta-staging shrink (>=4x fewer
    # bytes on the bus than full-row packing at the 10k-row shape),
    # delta-vs-patch byte equality, and the ledger's stamp attribution
    ds = results["cfg19_smoke"]
    assert ds["value"] >= 4.0
    assert ds["extra"]["byte_equality"] is True
    assert ds["extra"]["staged_bytes_delta"] < \
        ds["extra"]["staged_bytes_legacy"]
    assert ds["extra"]["ledger_stamp"]["device"] == 1
    assert ds["extra"]["ledger_stamp"]["host"] == 1
    # the cfg20 miniature proved the cost observatory's arithmetic:
    # the tenant split rule, integer-us charge conservation across
    # eviction/retirement, the rows-bucket/percentile/marginal math,
    # and the always-on per-flush hook under its 10 us budget
    co = results["cfg20_smoke"]
    assert all(co["extra"]["checks"].values()), co["extra"]["checks"]
    assert 0 < co["value"] < 10.0  # us/flush, tier-1-asserted budget
    assert co["extra"]["surfaces_sample"], co["extra"]
    marg = [r["marginal_ms_per_row"]
            for r in co["extra"]["surfaces_sample"]]
    assert marg[0] is None and all(m is not None for m in marg[1:])
    # host-only contract: a smoke run must never pull in jax (tier-1
    # budget); only check when this process hadn't loaded it already
    if not jax_loaded_before:
        assert "jax" not in sys.modules
    # round-trip: the evidence file parses back per config
    assert sorted(bench.load_bench_results(str(out_path))) == \
        sorted(results)


def test_bench_baseline_comparator_detects_injected_regression(
        tmp_path, capsys):
    """compare_to_baseline must FLAG a synthetic regression and stay
    quiet against the run's own numbers — both through the real
    --baseline/--fail-on-regression CLI path."""
    base_path = tmp_path / "base.json"
    rc, _ = _run(["--smoke", "--json-out", str(base_path)], capsys)
    assert rc == 0

    # clean compare: same host moments apart; a huge threshold keeps
    # scheduler jitter from flaking tier-1 — the point is the exit code
    # path, the sensitivity is proven below with a 20x injection
    rc, lines = _run(["--smoke", "--baseline", str(base_path),
                      "--baseline-threshold", "400",
                      "--fail-on-regression"], capsys)
    assert rc == 0
    cmp_line = lines[-1]
    assert cmp_line["metric"].startswith("baseline comparison")
    assert cmp_line["extra"]["regressed"] == []

    # inject: baseline claims cfg6 once did 10000x the throughput
    # (unit sigs/sec, higher-better) and cfg2 ran 10000x faster (ms,
    # lower-better) — BOTH directions must flag. The margin is huge on
    # purpose: warm in-process reruns beat the cold first run by
    # double-digit factors, and this test must never flake on that
    doc = json.loads(base_path.read_text())
    doc["results"]["cfg6_smoke"]["value"] *= 10_000
    doc["results"]["cfg2_smoke"]["value"] /= 10_000
    doctored = tmp_path / "doctored.json"
    doctored.write_text(json.dumps(doc))
    rc, lines = _run(["--smoke", "--baseline", str(doctored),
                      "--baseline-threshold", "400",
                      "--fail-on-regression"], capsys)
    assert rc == 1
    flagged = lines[-1]["extra"]["regressed"]
    assert "cfg6_smoke" in flagged and "cfg2_smoke" in flagged
    rows = {r["config"]: r for r in lines[-1]["extra"]["rows"]}
    assert rows["cfg6_smoke"]["status"] == "REGRESSED"
    assert rows["cfg4_smoke"]["status"] == "ok"


def test_compare_to_baseline_unit_directions():
    """Direction table: ms down = improved, sigs/sec down = REGRESSED,
    missing/failed configs are reported but never judged."""
    cur = {"a": {"value": 50.0, "unit": "ms"},
           "b": {"value": 50_000, "unit": "sigs/sec"},
           "c": {"value": None, "unit": "ms"}}
    base = {"a": {"value": 100.0, "unit": "ms"},
            "b": {"value": 100_000, "unit": "sigs/sec"},
            "d": {"value": 1.0, "unit": "x"}}
    cmp_doc = bench.compare_to_baseline(cur, base, threshold_pct=30.0)
    rows = {r["config"]: r for r in cmp_doc["rows"]}
    assert rows["a"]["status"] == "improved"        # ms halved
    assert rows["b"]["status"] == "REGRESSED"       # throughput halved
    assert rows["b"]["delta_pct"] == pytest.approx(-50.0)
    assert sorted(cmp_doc["missing"]) == ["c", "d"]
    assert cmp_doc["regressed"] == ["b"] and not cmp_doc["ok"]
