"""Multi-tenant verify plane unit tests (ISSUE 17).

Host-only coverage of the tenancy subsystem in isolation: the
registry's quotas/rotation/eviction accounting, the plane's per-tenant
ledger attribution and fair-share sheddable drain, the explicit
retry-hinted TenantOverloaded quota verdict, the structural
per-tenant unsheddability of CONSENSUS, residency attribution against
the live table caches, and the warmer's residency-budget gate. The
simnet-scale story (K chains on one plane, noisy-neighbor soak,
byte-identical replays) lives in test_tenants_soak.py.
"""
import pytest

from cometbft_tpu.ops import table_cache as tc
from cometbft_tpu.verifyplane import (
    DEFAULT_TENANT,
    LANE_BULK,
    LANE_CONSENSUS,
    LANE_GATEWAY,
    PlaneOverloaded,
    TenantOverloaded,
    TenantRegistry,
    VerifyPlane,
)
from cometbft_tpu.verifyplane import tenants as vtenants


class _Pub:
    """Stub pubkey: every signature verifies (the tenancy layer under
    test never looks at row contents)."""

    def verify_signature(self, msg, sig):
        return True


def _rows(n):
    return [(_Pub(), b"m", b"s")] * n


def _queued_plane(**kw):
    """A plane that ACCEPTS submissions but never drains them: the
    running flag is set without the dispatcher thread, so queue state
    (and the quota gate reading it) is fully deterministic."""
    p = VerifyPlane(window_ms=0.5, use_device=False, **kw)
    p._running = True
    return p


# -- registry ---------------------------------------------------------------


def test_registry_register_retune_and_quota_reads():
    reg = TenantRegistry()
    reg.register("chain-a", row_quota=8, residency_budget=4096)
    assert reg.row_quota("chain-a") == 8
    # retune: None keeps, value replaces
    reg.register("chain-a", row_quota=16)
    assert reg.row_quota("chain-a") == 16
    # unknown chains are unlimited and NOT auto-registered by the read
    assert reg.row_quota("never-seen") == 0
    assert reg.tenants() == ["chain-a"]


def test_drain_order_rotates_deterministically():
    reg = TenantRegistry()
    names = ["b", "a", "c"]
    assert reg.drain_order(names) == ["a", "b", "c"]
    assert reg.drain_order(names) == ["b", "c", "a"]
    assert reg.drain_order(names) == ["c", "a", "b"]
    assert reg.drain_order(names) == ["a", "b", "c"]
    # the cursor advances even when the queued set changes size
    assert reg.drain_order(["x", "y"]) == ["x", "y"]
    assert reg.drain_order(["x", "y"]) == ["y", "x"]


def test_eviction_folds_totals_into_retired_monotone():
    reg = TenantRegistry()
    reg.note_served("a", LANE_BULK, 10, 1.0)
    reg.note_served("b", LANE_BULK, 3, 1.0)
    reg.note_shed("a", LANE_BULK)
    before = reg.metrics_rows()
    total_before = (sum(r["rows"] for r in before["top"].values())
                    + before["retired"]["rows"])
    assert reg.evict("a")
    assert not reg.evict("a")  # second evict is a no-op
    after = reg.metrics_rows()
    total_after = (sum(r["rows"] for r in after["top"].values())
                   + after["retired"]["rows"])
    # the family-wide sum never regresses across an eviction — the
    # scrape's tenant="_retired" series absorbs the departed totals
    assert total_after == total_before == 13
    assert after["retired"] == {"rows": 10, "sheds": 1,
                                "warm_skips": 0, "cold_evictions": 0,
                                "device_us": 0, "comp_us": 0,
                                "h2d_us": 0, "delta_bytes": 0}
    assert "a" not in after["top"] and after["registry_size"] == 1


def test_metrics_rows_top_k_by_cumulative_rows():
    reg = TenantRegistry()
    for i in range(12):
        reg.note_served(f"c{i:02d}", LANE_BULK, i + 1, 0.5)
    mr = reg.metrics_rows(k=3)
    assert list(mr["top"]) == ["c11", "c10", "c09"]
    assert mr["registry_size"] == 12


def test_device_attribution_conserves_across_eviction():
    """ISSUE 20 conservation criterion, unit form: charge synthetic
    flush records through split_device_columns -> note_device exactly
    like the plane's _charge_flush, then prove reconcile_device drift
    is zero BEFORE an eviction, AFTER evict() folds a tenant into
    retired, and AFTER post-eviction charges — exact integer equality,
    no tolerance band."""
    from cometbft_tpu.verifyplane.plane import split_device_columns
    from cometbft_tpu.verifyplane.tenants import reconcile_device

    reg = TenantRegistry()
    # flush records the way FlushLedger.records() renders them: ms
    # columns rounded to 3 decimals (ms_to_us is lossless on these)
    records = [
        {"tenants": (("a", 7), ("b", 13)), "rows": 20,
         "comp_ms": 12.345, "h2d_ms": 0.071, "dev_ms": 3.007,
         "delta_bytes": 1234},
        {"tenants": (("a", 100),), "rows": 100,
         "comp_ms": 0.0, "h2d_ms": 0.25, "dev_ms": 1.5,
         "delta_bytes": 4096},
        {"tenants": (("a", 1), ("b", 1), ("c", 1)), "rows": 3,
         "comp_ms": 0.001, "h2d_ms": 0.001, "dev_ms": 0.001,
         "delta_bytes": 7},
        # tenantless record (shed-only / drain shape): never charged
        {"tenants": (), "rows": 0,
         "comp_ms": 9.0, "h2d_ms": 9.0, "dev_ms": 9.0,
         "delta_bytes": 999},
    ]

    def charge(rec):
        rule, shares = split_device_columns(
            rec["tenants"], rec["rows"], rec["comp_ms"],
            rec["h2d_ms"], rec["dev_ms"], rec["delta_bytes"])
        assert rule == ("exact" if len(rec["tenants"]) <= 1 else "rows")
        for chain, comp_us, h2d_us, dev_us, dbytes in shares:
            reg.note_device(chain, comp_us, h2d_us, dev_us, dbytes)

    for rec in records[:2]:
        charge(rec)
    rd = reg and reconcile_device(records[:2], reg)
    assert rd["drift"] == {"comp_us": 0, "h2d_us": 0,
                           "device_us": 0, "delta_bytes": 0}, rd
    # evict the heavy tenant: its totals fold into retired and the
    # registry-wide sum (live + retired) still matches the ledger
    assert reg.evict("a")
    rd = reconcile_device(records[:2], reg)
    assert rd["drift"] == {"comp_us": 0, "h2d_us": 0,
                           "device_us": 0, "delta_bytes": 0}, rd
    assert rd["registry"]["device_us"] > 0
    # new flushes after the eviction (one re-registers "a") keep the
    # identity; the tenantless record contributes to neither side
    for rec in records[2:]:
        charge(rec)
    rd = reconcile_device(records, reg)
    assert rd["drift"] == {"comp_us": 0, "h2d_us": 0,
                           "device_us": 0, "delta_bytes": 0}, rd
    # the dump renders the charged columns per live tenant
    d = reg.dump()
    assert d["tenants"]["b"]["device_ms"] > 0
    assert d["retired"]["device_us"] > 0


# -- plane integration: attribution, quotas, fair share ---------------------


def test_flush_ledger_attributes_rows_per_tenant():
    p = VerifyPlane(window_ms=0.5, use_device=False)
    p.start()
    try:
        f1 = p.submit_many(_rows(2), chain_id="chain-a")
        f2 = p.submit_many(_rows(1), chain_id="chain-b")
        f3 = p.submit_many(_rows(1))  # no chain_id -> default tenant
        assert f1.result(5) == (True, True)
        assert f2.result(5) == (True,)
        assert f3.result(5) == (True,)
    finally:
        p.stop()
    recs = p.ledger.records()
    # per-flush attribution sums to the flush total
    for r in recs:
        assert sum(n for _, n in r["tenants"]) == r["rows"]
    split = {}
    for r in recs:
        for chain, n in r["tenants"]:
            split[chain] = split.get(chain, 0) + n
    assert split == {"chain-a": 2, "chain-b": 1, DEFAULT_TENANT: 1}
    s = p.ledger.summary()
    assert s["tenants"] == split
    # the registry saw the same rows, lane-attributed
    d = p.tenants.dump()
    assert d["tenants"]["chain-a"]["lane_rows"][LANE_CONSENSUS] == 2
    assert d["tenants"]["chain-b"]["rows"] == 1
    assert d["tenants"]["chain-a"]["wait_ms"]["n"] == 1


def test_quota_shed_is_explicit_retry_hinted_verdict():
    p = _queued_plane()
    p.tenants.register("noisy", row_quota=3)
    # first submission enters (quota gates on what is ALREADY pending)
    p.submit_many(_rows(2), lane=LANE_BULK, chain_id="noisy")
    with pytest.raises(TenantOverloaded) as ei:
        p.submit_many(_rows(2), lane=LANE_BULK, chain_id="noisy")
    err = ei.value
    # subclass contract: every existing PlaneOverloaded arm (mempool
    # OVERLOADED verdict, lightgate 503) handles the tenant shed too
    assert isinstance(err, PlaneOverloaded)
    assert err.tenant == "noisy"
    assert err.retry_after_ms > 0
    assert "quota" in str(err)
    assert p.sheds[LANE_BULK] == 1
    assert p.tenants.dump()["tenants"]["noisy"]["lane_sheds"][
        LANE_BULK] == 1
    # other tenants on the same lane are untouched by noisy's quota
    p.submit_many(_rows(2), lane=LANE_BULK, chain_id="quiet")
    # and noisy's GATEWAY pending is a separate (lane, tenant) key
    p.submit_many(_rows(2), lane=LANE_GATEWAY, chain_id="noisy")


def test_consensus_lane_is_outside_every_tenant_gate():
    p = _queued_plane()
    p.tenants.register("noisy", row_quota=1)
    # CONSENSUS submissions far past the row quota: never gated — the
    # quota applies to sheddable lanes only, structurally
    for _ in range(4):
        p.submit_many(_rows(3), lane=LANE_CONSENSUS, chain_id="noisy")
    assert p.tenant_depths()[LANE_CONSENSUS] == {"noisy": 12}
    assert p.sheds[LANE_CONSENSUS] == 0


def test_fair_share_drain_splits_budget_and_rotates():
    p = _queued_plane(max_batch=8)
    # chain-a floods (4 x 2 rows), chain-b queues one 2-row submission
    for _ in range(4):
        p.submit_many(_rows(2), lane=LANE_BULK, chain_id="chain-a")
    p.submit_many(_rows(2), lane=LANE_BULK, chain_id="chain-b")
    batch = []
    with p._cv:
        taken = p._drain_sheddable(LANE_BULK, p._pending[LANE_BULK],
                                   4, batch)
    # budget 4, two tenants -> share 2 each: the flooder gets its
    # slice, the quiet tenant gets its slice, leftover none
    assert taken == 4
    split = {}
    for sub in batch:
        split[sub.tenant] = split.get(sub.tenant, 0) + len(sub.rows)
    assert split == {"chain-a": 2, "chain-b": 2}
    # bookkeeping: drained rows left the per-(lane, tenant) split
    assert p.tenant_depths()[LANE_BULK] == {"chain-a": 6}
    # chain-b's bucket is empty now: the SECOND drain hands the whole
    # budget to chain-a (single-tenant fast path)
    batch2 = []
    with p._cv:
        taken2 = p._drain_sheddable(LANE_BULK, p._pending[LANE_BULK],
                                    4, batch2)
    assert taken2 == 4
    assert all(s.tenant == "chain-a" for s in batch2)
    assert p.tenant_depths()[LANE_BULK] == {"chain-a": 2}


def test_fair_share_leftover_goes_to_the_flooder():
    p = _queued_plane(max_batch=16)
    for _ in range(4):
        p.submit_many(_rows(2), lane=LANE_BULK, chain_id="chain-a")
    p.submit_many(_rows(2), lane=LANE_BULK, chain_id="chain-b")
    batch = []
    with p._cv:
        taken = p._drain_sheddable(LANE_BULK, p._pending[LANE_BULK],
                                   10, batch)
    # share 5 each: b only has 2 queued, so the flooder's second pass
    # picks up the 3-row leftover (2+2 more rows fit within 10 total)
    assert taken == 10
    split = {}
    for sub in batch:
        split[sub.tenant] = split.get(sub.tenant, 0) + len(sub.rows)
    assert split == {"chain-a": 8, "chain-b": 2}
    assert p.tenant_depths()[LANE_BULK] == {}


def test_fair_share_preserves_fifo_within_each_tenant():
    p = _queued_plane(max_batch=32)
    subs = []
    for i in range(3):
        f = p.submit_many(_rows(1), lane=LANE_BULK, chain_id="chain-a")
        subs.append(f)
    p.submit_many(_rows(1), lane=LANE_BULK, chain_id="chain-b")
    batch = []
    with p._cv:
        p._drain_sheddable(LANE_BULK, p._pending[LANE_BULK], 32, batch)
    a_subs = [s for s in batch if s.tenant == "chain-a"]
    assert [s.future for s in a_subs] == subs


# -- residency + cold eviction ---------------------------------------------


class _FakeTable:
    def __init__(self, nbytes):
        self.nbytes = nbytes


@pytest.fixture()
def _clean_caches():
    tc.reset_for_tests()
    yield
    tc.reset_for_tests()


def test_residency_attribution_at_read_time(_clean_caches):
    reg = TenantRegistry()
    tc.TABLES.put(b"k-a", _FakeTable(1000))
    tc.TABLES.put(b"k-b", _FakeTable(2000))
    tc.TABLES.put(b"k-unowned", _FakeTable(4000))
    tc.SHARDS.put((b"k-a", "mesh0"), _FakeTable(500))
    reg.note_table_owner(b"k-a", "chain-a")
    reg.note_table_owner(b"k-b", "chain-b")
    res = reg.residency_by_tenant()
    # shard entries attribute through their table key's owner
    assert res["chain-a"] == {"bytes": 1500, "tables": 2}
    assert res["chain-b"] == {"bytes": 2000, "tables": 1}
    assert res[DEFAULT_TENANT] == {"bytes": 4000, "tables": 1}
    # an LRU eviction is reflected immediately (no double entry)
    tc.TABLES.pop(b"k-b")
    assert "chain-b" not in reg.residency_by_tenant()


def test_cold_eviction_keeps_the_live_epoch(_clean_caches):
    reg = TenantRegistry()
    for i in range(3):  # insertion order == LRU coldness order
        key = b"epoch-%d" % i
        tc.TABLES.put(key, _FakeTable(100))
        tc.SHARDS.put((key, "m"), _FakeTable(10))
        reg.note_table_owner(key, "chain-a")
    tc.TABLES.put(b"other", _FakeTable(100))
    n = reg.evict_cold_tables("chain-a")
    # the two retired epochs (plain + shard each) go; the newest owned
    # table AND its shard stay; other tenants' tables are untouched
    assert n == 4
    assert b"epoch-2" in tc.TABLES and (b"epoch-2", "m") in tc.SHARDS
    assert b"epoch-0" not in tc.TABLES and b"other" in tc.TABLES
    assert reg.dump()["tenants"]["chain-a"]["cold_evictions"] == 4


def test_warm_budget_gate_skips_and_counts(_clean_caches):
    from cometbft_tpu.verifyplane.warmer import TableWarmer

    reg = TenantRegistry()
    vtenants.set_global_registry(reg)
    built = []
    try:
        w = TableWarmer(build_fn=lambda pubs, powers:
                        built.append(len(pubs)))
        w.start()
        try:
            # budgeted tenant: a 4-val table estimate blows 1 byte
            reg.register("tight", residency_budget=1)
            w.request((b"p",) * 4, None, chain_id="tight")
            assert w.wait_idle(5)
            assert built == []
            assert w.stats()["builds_skipped_quota"] == 1
            assert reg.dump()["tenants"]["tight"]["warm_skips"] == 1
            # unbudgeted tenant and tenant-less warms build normally
            # (wait_idle between them: the request slot is latest-wins)
            w.request((b"p",) * 4, None, chain_id="roomy")
            assert w.wait_idle(5)
            w.request((b"p",) * 4, None)
            assert w.wait_idle(5)
            assert built == [4, 4]
            assert w.stats()["builds_ok"] == 2
        finally:
            w.stop()
    finally:
        vtenants.clear_global_registry(reg)


# -- dump surfaces ----------------------------------------------------------


def test_dump_tenants_module_fallback_survives_stop():
    p = VerifyPlane(window_ms=0.5, use_device=False)
    p.start()
    from cometbft_tpu.verifyplane import plane as planemod

    prev_g, prev_l = planemod._GLOBAL, planemod._LAST
    prev_rg = vtenants._GLOBAL
    prev_rl = vtenants._LAST
    try:
        planemod.set_global_plane(p)
        assert vtenants.global_registry() is p.tenants
        f = p.submit_many(_rows(2), chain_id="chain-z")
        assert f.result(5) == (True, True)
        p.stop()
        planemod.set_global_plane(None)
        # post-stop history: _LAST serves the dump after the plane went
        d = vtenants.dump_tenants()
        assert d["tenants"]["chain-z"]["rows"] == 2
    finally:
        planemod._GLOBAL, planemod._LAST = prev_g, prev_l
        vtenants._GLOBAL = prev_rg
        vtenants._LAST = prev_rl
        if p._running:
            p.stop()
