"""PartSet: split/prove/reassemble + multi-part block propagation over
real TCP consensus.

Reference: types/part_set_test.go (round trip, proof tamper) and the
consensus reactor's gossipDataRoutine part gossip (reactor.go:569) —
a block bigger than one part must still commit across a TCP mesh.
"""
import os
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types import part_set as psmod
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.6, propose_delta=0.2,
    prevote=0.3, prevote_delta=0.1,
    precommit=0.3, precommit_delta=0.1,
    commit=0.02,
)


def test_round_trip_multi_part():
    data = os.urandom(5 * 65536 + 12345)
    ps = psmod.PartSet.from_data(data)
    assert ps.total() == 6
    assert ps.is_complete()
    hdr = ps.header()
    assert hdr.total == 6 and len(hdr.hash) == 32

    rx = psmod.PartSet.from_header(hdr)
    assert not rx.is_complete()
    # out-of-order arrival, with wire round trip per part
    for i in [3, 0, 5, 1, 4, 2]:
        wire = psmod.Part.from_j(ps.get_part(i).to_j())
        assert rx.add_part(wire) is True
        assert rx.add_part(wire) is False  # duplicate
    assert rx.is_complete()
    assert rx.assemble() == data
    assert rx.bit_array().get_index(3)


def test_tampered_part_rejected():
    data = os.urandom(3 * 65536)
    ps = psmod.PartSet.from_data(data)
    rx = psmod.PartSet.from_header(ps.header())
    part = ps.get_part(1)
    evil = psmod.Part(1, part.data[:-1] + b"\x00", part.proof)
    with pytest.raises(psmod.PartSetError):
        rx.add_part(evil)
    # proof from the wrong slot
    wrong = psmod.Part(2, part.data, part.proof)
    with pytest.raises(psmod.PartSetError):
        rx.add_part(wrong)


def test_oversized_proof_rejected():
    """A peer cannot attach unbounded aunts/hashes to a part: the
    receive side buffers orphan parts before proof verification, so
    validate_basic must bound attacker-controlled proof bytes."""
    from cometbft_tpu.crypto import merkle

    ps = psmod.PartSet.from_data(os.urandom(65536 * 2))
    good = ps.get_part(0)
    # too many aunts
    bloated = psmod.Part(0, good.data, merkle.Proof(
        good.proof.total, 0, good.proof.leaf_hash,
        [os.urandom(32)] * (psmod.Part.MAX_AUNTS + 1)))
    with pytest.raises(psmod.PartSetError):
        bloated.validate_basic()
    # wrong-size aunt
    fat = psmod.Part(0, good.data, merkle.Proof(
        good.proof.total, 0, good.proof.leaf_hash,
        [os.urandom(1 << 20)]))
    with pytest.raises(psmod.PartSetError):
        fat.validate_basic()
    # wrong-size leaf hash
    badleaf = psmod.Part(0, good.data, merkle.Proof(
        good.proof.total, 0, b"\x00" * 31, list(good.proof.aunts)))
    with pytest.raises(psmod.PartSetError):
        badleaf.validate_basic()
    # absurd total
    badtotal = psmod.Part(0, good.data, merkle.Proof(
        psmod.PartSet.MAX_TOTAL + 1, 0, good.proof.leaf_hash,
        list(good.proof.aunts)))
    with pytest.raises(psmod.PartSetError):
        badtotal.validate_basic()
    good.validate_basic()  # the honest part still passes


def test_wal_rotated_segment_truncation_stops_replay(tmp_path):
    """A torn header inside a ROTATED segment is mid-stream corruption:
    replay must stop rather than splice older records onto newer ones.
    A torn header in the head file is a normal crash artifact."""
    import struct
    import zlib

    from cometbft_tpu.consensus.wal import WAL

    def rec(payload: bytes) -> bytes:
        body = b"\x01" + payload
        return struct.pack(">II", zlib.crc32(body) & 0xFFFFFFFF,
                           len(body)) + body

    head = str(tmp_path / "wal")
    # rotated segment with one good record + a 3-byte torn header
    with open(head + ".000", "wb") as f:
        f.write(rec(b"seg0") + b"\x00\x01\x02")
    with open(head, "wb") as f:
        f.write(rec(b"head0") + rec(b"head1"))
    got = [r.data for r in WAL.iter_records(head)]
    assert got == [b"seg0"], got  # stream stops at the rotated tear
    # same tear in the HEAD file: records before it replay fine
    os.truncate(head + ".000", len(rec(b"seg0")))
    with open(head, "ab") as f:
        f.write(b"\x00\x01")
    got = [r.data for r in WAL.iter_records(head)]
    assert got == [b"seg0", b"head0", b"head1"], got


def test_single_small_part():
    ps = psmod.PartSet.from_data(b"tiny")
    assert ps.total() == 1
    rx = psmod.PartSet.from_header(ps.header())
    rx.add_part(ps.get_part(0))
    assert rx.assemble() == b"tiny"


def test_block_id_psh_is_deterministic(tmp_path):
    """block_id()'s PartSetHeader must be a pure function of block
    content — every validator derives the identical BlockID to vote on
    (consensus-critical; types/block.go:140 MakePartSet)."""
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import StateStore
    from cometbft_tpu.types import serde
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.commit import Commit

    privs = [PrivKey.generate(bytes([i + 9]) * 32) for i in range(2)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("psh-chain", vals)
    exec_ = BlockExecutor(KVStoreApplication(), StateStore(":memory:"))
    block = exec_.create_proposal_block(
        1, state, Commit(0, 0, BlockID(), []),
        vals.get_proposer().address, txs=[os.urandom(100_000).hex().encode()]
    )
    bid = block.block_id()
    assert bid.part_set_header.total >= 2  # really multi-part
    # wire round trip -> same BlockID
    again = serde.block_from_json(serde.block_to_json(block))
    assert again.block_id() == bid


@pytest.mark.slow
def test_multipart_block_commits_over_tcp(tmp_path):
    """A block whose wire form spans several 64KiB parts commits on a
    4-node TCP mesh — whole-block messages never cross the wire."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("part-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x60 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        for i, n in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    n.dial(a)
        deadline = time.time() + 10
        while any(n.switch.num_peers() < 3 for n in nodes):
            assert time.time() < deadline, "mesh never formed"
            time.sleep(0.05)
        # ~200 KiB of tx payload -> several parts once hex-encoded
        big = b"big=" + os.urandom(100_000).hex().encode()
        nodes[0].broadcast_tx(big)
        target = nodes[0].height() + 3
        for n in nodes:
            assert n.consensus.wait_for_height(target, timeout=120), \
                f"stuck at {n.height()}"
        # the big tx committed somewhere and all stores agree
        found = False
        for h in range(1, target + 1):
            b = nodes[1].block_store.load_block(h)
            if b and any(t == big for t in b.data.txs):
                found = True
                assert b.block_id().part_set_header.total >= 2
        assert found, "big tx never committed"
    finally:
        for n in nodes:
            n.stop()
