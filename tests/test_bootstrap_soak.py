"""Archival bootstrap chaos soak (ISSUE 18 acceptance).

A four-validator simnet loses node 3's machine entirely (halt + home
wipe) while the three donors keep committing under a signed flood and
a quorum-killing partition. The lost node then bootstraps through the
archival plane — chunked merkle-verified snapshot serving behind the
ServeGate, then the catch-up firehose replaying the donor's block
store through the REAL execution stack into the node's own home dir —
and a plain simnet restart brings it up live at the donors' tip:

  * the donor side never sheds CONSENSUS work: every gate verdict
    lands on serving traffic, with an explicit retry hint the
    bootstrapping peer honors on the virtual clock;
  * the catch-up run is killed mid-replay by a failpoint and resumed
    from the persisted cursor, re-verifying ZERO already-verified
    blocks;
  * the whole thing — commit hashes, flood verdicts, serve sheds, and
    the catch-up ledger including its timestamps — replays
    byte-identically from (seed, schedule), because the simnet's
    virtual clock stays installed across the bootstrap phase.

Budget discipline follows test_tenants_soak.py: the two expensive runs
are built once in a module-scoped lazy cache and shared across tests.
"""
import hashlib
import json
import os
import shutil

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.blocksync import catchup as cu
from cometbft_tpu.blocksync.catchup import (
    CatchupEngine,
    CatchupLedger,
    HostCommitVerifier,
    StoreHistorySource,
)
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.simnet import Simnet
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import State, StateStore
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.statesync.chunks import ChunkQueue
from cometbft_tpu.statesync.snapshots import (
    ServeGate,
    SnapshotArchive,
    SnapshotServeOverloaded,
    proof_doc,
    verify_chunk,
)
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

pytestmark = pytest.mark.simnet


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


N_NODES = 4
SEED = 7718
H1 = 4  # phase-A history depth: enough for interval-2 app snapshots


class _PersistentKV(KVStoreApplication):
    """KVStore that survives its process: state persists to the node's
    home dir on every commit, so a simnet restart() reopens the app the
    bootstrap plane restored instead of a blank one. Snapshots every 2
    heights make every node a statesync donor."""

    def __init__(self, home=None):
        super().__init__()
        self._path = os.path.join(home, "app_state.json") if home else None
        self.enable_snapshots(2)
        if self._path and os.path.exists(self._path):
            with open(self._path) as f:
                doc = json.load(f)
            self.state = {bytes.fromhex(k): bytes.fromhex(v)
                          for k, v in doc["state"].items()}
            self.height = doc["height"]
            self.app_hash = bytes.fromhex(doc["app_hash"])
            self.staged = dict(self.state)

    def _save_disk(self):
        if self._path is None:
            return
        doc = {"height": self.height, "app_hash": self.app_hash.hex(),
               "state": {k.hex(): v.hex()
                         for k, v in self.state.items()}}
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self._path)

    def commit(self):
        rc = super().commit()
        self._save_disk()
        return rc

    def apply_snapshot_chunk(self, index, chunk, sender):
        rc = super().apply_snapshot_chunk(index, chunk, sender)
        if rc is True and getattr(self, "_restore", None) is None:
            self._save_disk()  # restore complete
        return rc


class _CountingVerifier(HostCommitVerifier):
    def __init__(self):
        self.heights = []

    def verify(self, jobs):
        self.heights.extend(j.height for j in jobs)
        return super().verify(jobs)


def _state_at(donor, s: int, app_hash: bytes) -> State:
    """stateprovider.go State from the donor's stores instead of a
    light client (syncer.LightStateProvider.state_at is the wire-level
    twin): valsets with their REAL proposer priorities from the
    per-height table, the commit's own BlockID (real PartSetHeader),
    and the restored app hash cross-checked below against header S+1."""
    bs, ss = donor.block_store, donor.state_store
    blk = bs.load_block(s)
    nxt = bs.load_block(s + 1)
    commit = bs.load_block_commit(s)
    cur = ss.load_validators(s + 1)
    live = ss.load()
    return State(
        chain_id=blk.header.chain_id,
        initial_height=live.initial_height,
        last_block_height=s,
        last_block_id=commit.block_id,
        last_block_time=blk.header.time,
        validators=cur.copy(),
        next_validators=ss.load_validators(s + 2).copy(),
        last_validators=ss.load_validators(s).copy(),
        last_height_validators_changed=live.last_height_validators_changed,
        consensus_params=live.consensus_params,
        app_hash=app_hash,
        last_results_hash=nxt.header.last_results_hash,
    )


def _bootstrap_node3(net, node3):
    """The archival plane end to end, on the still-installed virtual
    clock: gated merkle-chunked snapshot restore into node 3's wiped
    home, then the catch-up firehose (killed once mid-replay, resumed
    from the persisted cursor) through a real BlockExecutor into the
    node's own block/state stores."""
    donor = net.nodes[0].node
    donor_app = donor.app._app  # the raw application behind the conn
    donor_bs = donor.block_store
    tip = donor_bs.height()

    # deepest snapshot that leaves a real catch-up span behind it (the
    # archive case — the freshest snapshot would make catch-up trivial)
    snaps = [s for s in donor_app.list_snapshots()
             if s.height + 5 <= tip]
    assert snaps, "no snapshot deep enough below the donor tip"
    kv_snap = snaps[-1]
    s = kv_snap.height
    blob = b"".join(donor_app._snapshots[s])

    # -- serving: merkle archive behind the ServeGate -------------------
    archive = SnapshotArchive(chunk_size=128)
    snap2 = archive.generate(s, blob)
    gate = ServeGate(rate_per_s=200.0, burst=2, max_peers=8)
    sheds = []

    def fetch(idx: int) -> bytes:
        while True:
            try:
                gate.admit("boot-3", "chunk")
            except SnapshotServeOverloaded as e:
                # explicit retry-hinted verdict, honored on the sim clock
                sheds.append(round(e.retry_after_ms, 6))
                net.now += e.retry_after_ms / 1000.0
                continue
            chunk = archive.load_chunk(s, 2, idx)
            doc = proof_doc(archive.proof_for(s, 2, idx))
            assert verify_chunk(snap2.hash, chunk, doc), \
                "merkle proof rejected a donor chunk"
            ss_stats.bump("chunks_served")
            return chunk

    q = ChunkQueue(snap2.chunks,
                   cache_dir=os.path.join(node3.home, "ss-cache"))
    for i in range(snap2.chunks):
        if q.wait_for(i, 0.0) is None:
            q.add(i, fetch(i), "donor-0")
    chunks = [q.wait_for(i, 0.0) for i in range(snap2.chunks)]
    restored_blob_ok = b"".join(chunks) == blob

    # -- app restore (kvstore's own format-1 whole-blob contract) -------
    app3 = _PersistentKV(home=node3.home)
    offer = abci.Snapshot(height=s, format=1, chunks=snap2.chunks,
                          hash=hashlib.sha256(blob).digest())
    assert app3.offer_snapshot(offer)
    for i, c in enumerate(chunks):
        assert app3.apply_snapshot_chunk(i, c, "donor-0")
    nxt_hdr = donor_bs.load_block(s + 1).header
    checks = {
        "restored_blob_ok": restored_blob_ok,
        # kvstore's advertised format-1 hash is the same whole-blob
        # sha256 the offer used
        "kv_hash_match": kv_snap.hash == offer.hash,
        # syncer.go:458 VerifyApp — restored app hash against the
        # next header's AppHash
        "app_hash_vs_header": app3.app_hash == nxt_hdr.app_hash,
        "app_height_is_snap": app3.height == s,
    }

    # -- catch-up firehose into node 3's own home stores ----------------
    st = _state_at(donor, s, app3.app_hash)
    bs3 = BlockStore(os.path.join(node3.home, "blockstore.db"))
    ss3 = StateStore(os.path.join(node3.home, "state.db"))
    ss3.save(st)
    cursor_path = os.path.join(node3.home, "catchup-cursor.json")
    led = CatchupLedger()
    v1, v2 = _CountingVerifier(), _CountingVerifier()

    def engine(verifier):
        return CatchupEngine(
            StoreHistorySource(donor_bs), st.copy(),
            block_exec=BlockExecutor(app3, ss3), block_store=bs3,
            verifier=verifier, cursor_path=cursor_path,
            read_ahead=3, max_run=2, warm_ahead=False, ledger=led)

    old_g, old_l = cu._GLOBAL, cu._LAST
    try:
        # killed mid-replay at the 4th history read...
        fp.arm("catchup.read_ahead", "flake", 4, count=1)
        with pytest.raises(fp.FailpointError):
            engine(v1).run()
        fp.disarm("catchup.read_ahead")
        cursor_at_crash = json.loads(open(cursor_path).read())
        # ...and resumed from the persisted cursor. The resumed engine
        # seeds from the SAVED state (what a restarted process would
        # load), not the in-memory one the crash abandoned.
        st = ss3.load()
        final = engine(v2).run()
    finally:
        cu._GLOBAL, cu._LAST = old_g, old_l
        fp.disarm("catchup.read_ahead")

    donor_state = donor.state_store.load()
    state_match = {
        "height": final.last_block_height == tip,
        "app_hash": final.app_hash == donor_state.app_hash,
        "block_id": final.last_block_id == donor_state.last_block_id,
        "vals": final.validators.hash() == donor_state.validators.hash(),
        "results": (final.last_results_hash
                    == donor_state.last_results_hash),
    }
    bs3.close()
    ss3.close()
    return {
        "snap_height": s, "tip": tip, "chunks": snap2.chunks,
        "sheds": sheds, "gate_stats": gate.stats(),
        "checks": checks, "state_match": state_match,
        "cursor_at_crash": cursor_at_crash,
        "cursor": json.loads(open(cursor_path).read()),
        "verified_phase1": list(v1.heights),
        "verified_phase2": list(v2.heights),
        "ledger_records": led.records(),
        "counters": dict(led.counters),
    }


def _run_bootstrap(basedir, seed: int = SEED):
    ss_stats.reset()
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        with Simnet(N_NODES, seed=seed, basedir=str(basedir),
                    app_factory=_PersistentKV) as sim:
            net = sim.net
            # phase A: build history (and donor app snapshots). The
            # flood puts real key/value state behind the snapshots, so
            # the serving phase moves a blob worth chunking.
            assert sim.run(
                [{"at": 0.2, "op": "flood", "node": 0, "rate": 30.0,
                  "duration": 1.5, "signed": True, "size": 24}],
                until_height=H1, max_time=60.0), \
                "phase A never reached target height"
            node3 = net.nodes[3]
            node3.halt("machine lost")
            shutil.rmtree(node3.home)
            os.makedirs(node3.home)
            # phase B: donors advance under signed flood + a partition
            # that drops BOTH sides below quorum until the heal
            t0 = net.now
            chaos = [
                {"at": t0 + 0.2, "op": "flood", "node": 0, "rate": 20.0,
                 "duration": 2.0, "signed": True, "size": 24},
                {"at": t0 + 0.5, "op": "partition",
                 "groups": [[0, 1], [2], [3]]},
                {"at": t0 + 1.5, "op": "heal"},
            ]
            assert sim.run(chaos, until_height=H1 + 7, max_time=90.0), \
                "donors never recovered from phase-B chaos"
            # the bootstrap itself (virtual clock still installed)
            boot = _bootstrap_node3(net, node3)
            # phase C: rejoin live, with fresh flood riding the donors
            t1 = net.now
            target = boot["tip"] + 2
            assert sim.run(
                [{"at": t1, "op": "restart", "node": 3},
                 {"at": t1 + 0.2, "op": "flood", "node": 1,
                  "rate": 10.0, "duration": 1.0, "signed": True,
                  "size": 24}],
                until_height=target, max_time=120.0), \
                "restarted node never reached the live tip"
            sim.assert_safety()
            heights = [n.height() for n in net.nodes]
            hashes = sim.commit_hashes()
            flood_results = list(sim.flood_results)
            restarts = node3.restarts
    finally:
        set_global_plane(None)
        plane.stop()
    return {
        "boot": boot, "heights": heights, "target": target,
        "hashes": hashes, "flood_results": flood_results,
        "restarts": restarts, "plane_stats": plane.stats(),
    }


@pytest.fixture(scope="module")
def boot_runs(tmp_path_factory):
    cache = {}

    def get(tag: str):
        if tag not in cache:
            cache[tag] = _run_bootstrap(
                tmp_path_factory.mktemp(f"boot-{tag}"))
        return cache[tag]

    return get


def test_killed_node_bootstraps_to_live(boot_runs):
    """statesync -> catch-up -> live: the wiped node restores the
    donor snapshot through the merkle plane, replays to the donor tip
    through the real execution stack, and then COMMITS with the pack —
    its post-restart height clears the pre-bootstrap tip."""
    run = boot_runs("a")
    boot = run["boot"]
    assert all(boot["checks"].values()), boot["checks"]
    assert all(boot["state_match"].values()), boot["state_match"]
    assert boot["tip"] - boot["snap_height"] >= 3, \
        "catch-up span too short to mean anything"
    assert run["restarts"] == 1
    # every node, including the bootstrapped one, is at/past target
    assert all(h >= run["target"] for h in run["heights"]), \
        run["heights"]
    # the bootstrapped node COMMITTED live blocks past the catch-up
    # tip, and agrees with donor 0 wherever their histories overlap
    h0, h3 = run["hashes"][0], run["hashes"][3]
    assert any(h > boot["tip"] for h in h3), \
        "node 3 never committed a live block"
    common = set(h0) & set(h3)
    assert common and all(h0[h] == h3[h] for h in common)


def test_donor_serving_sheds_are_explicit_and_consensus_clean(
        boot_runs):
    """The overload contract on the serving plane: the bootstrap storm
    is shed with retry hints (which the peer honors and completes),
    while the donors' CONSENSUS lane records ZERO sheds and every
    flood verdict is an explicit code — nothing is silently dropped."""
    run = boot_runs("a")
    boot = run["boot"]
    assert boot["sheds"], "gate never shed: storm too small to prove " \
        "the contract"
    assert all(ms > 0 for ms in boot["sheds"])
    gs = boot["gate_stats"]
    assert gs["sheds"] == len(boot["sheds"])
    # every chunk was eventually served despite the sheds
    assert gs["served"] == boot["chunks"]
    assert run["plane_stats"]["sheds"]["consensus"] == 0
    assert run["flood_results"], "flood never fired"
    assert all(r["code"] is not None for r in run["flood_results"])


def test_catchup_resumes_mid_bootstrap_reverifying_zero(boot_runs):
    """The mid-replay kill left a persisted cursor; the resumed engine
    re-verified ZERO blocks the first pass already verified, and the
    ledger carries the resume."""
    run = boot_runs("a")
    boot = run["boot"]
    crash = boot["cursor_at_crash"]
    assert crash["verified"] > boot["snap_height"], \
        "crash landed before any verification — arm later"
    assert crash["verified"] < boot["tip"], "crash landed after the tip"
    overlap = set(boot["verified_phase1"]) & set(boot["verified_phase2"])
    assert overlap == set(), overlap
    assert boot["verified_phase2"], "resume verified nothing"
    assert min(boot["verified_phase2"]) == crash["verified"] + 1
    assert boot["counters"]["resumes"] == 1
    assert boot["cursor"]["applied"] == boot["tip"]
    # both passes record into ONE ledger, and together they applied
    # every post-snapshot block exactly once
    applied = sum(r["blocks"] for r in boot["ledger_records"])
    assert applied == boot["tip"] - boot["snap_height"]
    assert applied == boot["counters"]["blocks_applied"]


def test_bootstrap_replays_byte_identical(boot_runs):
    """Same (seed, schedule) -> the SAME run: commit hashes, flood
    verdicts, serve sheds, and the catch-up ledger — including its
    virtual-clock timestamps — are equal structure-for-structure."""
    a, b = boot_runs("a"), boot_runs("b")
    assert a["hashes"] == b["hashes"]
    assert a["heights"] == b["heights"]
    assert a["flood_results"] == b["flood_results"]
    assert a["boot"]["sheds"] == b["boot"]["sheds"]
    assert a["boot"]["gate_stats"] == b["boot"]["gate_stats"]
    assert a["boot"]["ledger_records"] == b["boot"]["ledger_records"]
    assert a["boot"]["counters"] == b["boot"]["counters"]
    assert a["boot"]["cursor_at_crash"] == b["boot"]["cursor_at_crash"]
    assert a["boot"]["verified_phase1"] == b["boot"]["verified_phase1"]
    assert a["boot"]["verified_phase2"] == b["boot"]["verified_phase2"]
