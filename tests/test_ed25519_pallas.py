"""Differential tests: Pallas fused ed25519 kernel vs oracle and XLA kernel.

Runs in Pallas interpret mode on the CPU test mesh (conftest forces
JAX_PLATFORMS=cpu); the same code path compiles to Mosaic on real TPU.
Covers the identical case matrix as tests/test_ed25519_kernel.py —
valid batches, the blame path, garbage inputs, and the ZIP-215 edge cases
whose CPU/TPU divergence would fork consensus.
"""
import os

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k
from cometbft_tpu.ops import ed25519_pallas as kp


def make_sigs(n, msg_fn=lambda i: b"msg-%d" % i):
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [msg_fn(i) for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


@pytest.mark.slow  # ~75 s interpret-mode run on the 1-core host;
# zip215_edges/blame_path keep the quick-gate Pallas coverage
def test_all_valid_batch():
    pubs, msgs, sigs = make_sigs(5)
    got = kp.verify_batch(pubs, msgs, sigs)
    assert got.shape == (5,)
    assert got.all()


@pytest.mark.slow  # ~77 s on the 1-core host under suite load; the
# garbage/zip215/pad siblings keep the kernel in the quick gate
def test_blame_path_mixed_batch():
    pubs, msgs, sigs = make_sigs(8)
    bad = dict()
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    bad[2] = True
    msgs[5] = msgs[5] + b"tampered"
    bad[5] = True
    sigs[6] = sigs[6][:32] + int.to_bytes(
        int.from_bytes(sigs[6][32:], "little") + ed.L, 32, "little"
    )  # S >= L: malleability reject in precheck
    bad[6] = True
    got = kp.verify_batch(pubs, msgs, sigs)
    for i in range(8):
        assert got[i] == (i not in bad), i
        assert got[i] == ed.verify(pubs[i], msgs[i], sigs[i]), i


@pytest.mark.slow  # ~115 s interpret-mode run on the 1-core host
# ([tier1-duration] flagged it past the 60 s line); zip215_edges keeps
# the quick-gate Pallas oracle-differential and the XLA twin
# (test_ed25519_kernel.py::test_matches_oracle_on_garbage) keeps the
# identical garbage matrix quick
def test_matches_oracle_on_garbage():
    rng = np.random.default_rng(3)
    pubs, msgs, sigs = [], [], []
    for i in range(16):
        pubs.append(rng.bytes(32))
        msgs.append(rng.bytes(i))
        sigs.append(rng.bytes(64))
    got = kp.verify_batch(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    np.testing.assert_array_equal(got, np.asarray(exp))


def test_zip215_edges():
    """Must match the oracle bit-for-bit on non-canonical encodings and
    small-order points — consensus forks otherwise."""
    ident = ed.pt_compress(ed.IDENT)
    cases = [(ident, b"m", ident + b"\x00" * 32)]
    for y in range(19):
        u, v = (y * y - 1) % ed.P, (ed.D * y * y + 1) % ed.P
        ok, x = ed._sqrt_ratio(u, v)
        if ok:
            enc_nc = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32, "little")
            break
    seed = bytes(32)
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, b"x")
    cases.append((pub, b"x", enc_nc + sig[32:]))
    cases.append((enc_nc, b"x", sig))
    neg_zero = int.to_bytes(1 | (1 << 255), 32, "little")
    cases.append((neg_zero, b"m", neg_zero + b"\x00" * 32))
    pubs, msgs, sigs = zip(*cases)
    got = kp.verify_batch(list(pubs), list(msgs), list(sigs))
    exp = [ed.verify(p, m, s) for p, m, s in cases]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert any(exp)


@pytest.mark.slow  # ~150 s interpret-mode cross-tile sweep
def test_matches_xla_kernel_cross_tile():
    """Pallas and XLA kernels agree on a batch spanning >1 tile (B=256)."""
    pubs, msgs, sigs = make_sigs(140)
    # corrupt a few spread across both tiles
    for i in (0, 63, 64, 127, 128, 139):
        sigs[i] = sigs[i][:8] + bytes([sigs[i][8] ^ 2]) + sigs[i][9:]
    got_p = kp.verify_batch(pubs, msgs, sigs)
    got_x = k.verify_batch(pubs, msgs, sigs)
    np.testing.assert_array_equal(got_p, got_x)
    exp = np.ones(140, bool)
    exp[[0, 63, 64, 127, 128, 139]] = False
    np.testing.assert_array_equal(got_p, exp)


def test_pad_to_tile():
    assert kp.pad_to_tile(1) == 128
    assert kp.pad_to_tile(64) == 128
    assert kp.pad_to_tile(129) == 256
    assert kp.pad_to_tile(257) == 1024


@pytest.mark.slow  # ~90 s interpret-mode multi-tile tally
def test_tally_multi_tile_with_invalid_and_quorum_miss():
    """verify_tally_rows across a >2-tile grid: invalid rows excluded
    from the tally, quorum-miss detected (round-2 verdict item 5 at a
    CPU-affordable 4-tile shape; the 10k shape runs on TPU below and in
    bench.py every round)."""
    n = 4 * kp.B_TILE  # 512 rows, 4 grid steps
    pubs, msgs, sigs = make_sigs(64)
    pubs, msgs, sigs = pubs * 8, msgs * 8, sigs * 8
    bad = [3, 130, 300, 511]
    for i in bad:
        sigs[i] = sigs[i][:20] + bytes([sigs[i][20] ^ 4]) + sigs[i][21:]

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
    powers = np.full((n,), 7, np.int64)
    power5 = k.power_limbs(powers)
    counted = np.ones((n,), np.bool_)
    cids = np.zeros((n,), np.int32)
    # commit 0: all rows; threshold just under the honest sum -> quorum
    honest = (n - len(bad)) * 7
    thresh_ok = k.threshold_limbs(honest - 1)
    rows = kp.pack_rows(pb, power5, counted, cids, thresh_ok)
    valid, tally, quorum = kp.verify_tally_rows(rows, 1)
    exp = np.ones(n, bool)
    exp[bad] = False
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp)
    assert k.tally_to_int(np.asarray(tally))[0] == honest
    assert bool(np.asarray(quorum)[0])
    # quorum-miss: threshold exactly the honest sum (needs MORE than)
    thresh_miss = k.threshold_limbs(honest)
    rows2 = kp.pack_rows(pb, power5, counted, cids, thresh_miss)
    _, _, q2 = kp.verify_tally_rows(rows2, 1)
    assert not bool(np.asarray(q2)[0])


@pytest.mark.skipif(
    not os.environ.get("CBT_TEST_ON_TPU"),
    reason="10,240-row grid is TPU-scale; CPU interpret takes minutes "
           "(bench.py asserts this shape on the real chip every round)",
)
def test_tally_10k_shape_vs_xla():
    n = 10_240
    pubs, msgs, sigs = make_sigs(64)
    reps = n // 64
    pubs, msgs, sigs = pubs * reps, msgs * reps, sigs * reps
    bad = [5, 5000, 10_239]
    for i in bad:
        sigs[i] = b"\x00" * 64
    pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
    powers = np.full((n,), 1000, np.int64)
    power5 = k.power_limbs(powers)
    counted = np.ones((n,), np.bool_)
    cids = np.zeros((n,), np.int32)
    thresh = k.threshold_limbs(int(powers.sum()) * 2 // 3)
    rows = kp.pack_rows(pb, power5, counted, cids, thresh)
    valid, tally, quorum = kp.verify_tally_rows(rows, 1)
    exp = np.ones(n, bool)
    exp[bad] = False
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp)
    # cross-check the fused tally against the XLA tally core on host data
    import jax.numpy as jnp

    ref_tally = k.tally_core(
        jnp.asarray(exp), jnp.asarray(power5), jnp.asarray(counted),
        jnp.asarray(cids), 1,
    )
    assert k.tally_to_int(np.asarray(ref_tally))[0] == k.tally_to_int(
        np.asarray(tally)
    )[0]
    assert bool(np.asarray(quorum)[0])
