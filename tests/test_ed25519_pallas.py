"""Differential tests: Pallas fused ed25519 kernel vs oracle and XLA kernel.

Runs in Pallas interpret mode on the CPU test mesh (conftest forces
JAX_PLATFORMS=cpu); the same code path compiles to Mosaic on real TPU.
Covers the identical case matrix as tests/test_ed25519_kernel.py —
valid batches, the blame path, garbage inputs, and the ZIP-215 edge cases
whose CPU/TPU divergence would fork consensus.
"""
import numpy as np

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k
from cometbft_tpu.ops import ed25519_pallas as kp


def make_sigs(n, msg_fn=lambda i: b"msg-%d" % i):
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [msg_fn(i) for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def test_all_valid_batch():
    pubs, msgs, sigs = make_sigs(5)
    got = kp.verify_batch(pubs, msgs, sigs)
    assert got.shape == (5,)
    assert got.all()


def test_blame_path_mixed_batch():
    pubs, msgs, sigs = make_sigs(8)
    bad = dict()
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    bad[2] = True
    msgs[5] = msgs[5] + b"tampered"
    bad[5] = True
    sigs[6] = sigs[6][:32] + int.to_bytes(
        int.from_bytes(sigs[6][32:], "little") + ed.L, 32, "little"
    )  # S >= L: malleability reject in precheck
    bad[6] = True
    got = kp.verify_batch(pubs, msgs, sigs)
    for i in range(8):
        assert got[i] == (i not in bad), i
        assert got[i] == ed.verify(pubs[i], msgs[i], sigs[i]), i


def test_matches_oracle_on_garbage():
    rng = np.random.default_rng(3)
    pubs, msgs, sigs = [], [], []
    for i in range(16):
        pubs.append(rng.bytes(32))
        msgs.append(rng.bytes(i))
        sigs.append(rng.bytes(64))
    got = kp.verify_batch(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    np.testing.assert_array_equal(got, np.asarray(exp))


def test_zip215_edges():
    """Must match the oracle bit-for-bit on non-canonical encodings and
    small-order points — consensus forks otherwise."""
    ident = ed.pt_compress(ed.IDENT)
    cases = [(ident, b"m", ident + b"\x00" * 32)]
    for y in range(19):
        u, v = (y * y - 1) % ed.P, (ed.D * y * y + 1) % ed.P
        ok, x = ed._sqrt_ratio(u, v)
        if ok:
            enc_nc = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32, "little")
            break
    seed = bytes(32)
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, b"x")
    cases.append((pub, b"x", enc_nc + sig[32:]))
    cases.append((enc_nc, b"x", sig))
    neg_zero = int.to_bytes(1 | (1 << 255), 32, "little")
    cases.append((neg_zero, b"m", neg_zero + b"\x00" * 32))
    pubs, msgs, sigs = zip(*cases)
    got = kp.verify_batch(list(pubs), list(msgs), list(sigs))
    exp = [ed.verify(p, m, s) for p, m, s in cases]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert any(exp)


def test_matches_xla_kernel_cross_tile():
    """Pallas and XLA kernels agree on a batch spanning >1 tile (B=256)."""
    pubs, msgs, sigs = make_sigs(140)
    # corrupt a few spread across both tiles
    for i in (0, 63, 64, 127, 128, 139):
        sigs[i] = sigs[i][:8] + bytes([sigs[i][8] ^ 2]) + sigs[i][9:]
    got_p = kp.verify_batch(pubs, msgs, sigs)
    got_x = k.verify_batch(pubs, msgs, sigs)
    np.testing.assert_array_equal(got_p, got_x)
    exp = np.ones(140, bool)
    exp[[0, 63, 64, 127, 128, 139]] = False
    np.testing.assert_array_equal(got_p, exp)


def test_pad_to_tile():
    assert kp.pad_to_tile(1) == 128
    assert kp.pad_to_tile(64) == 128
    assert kp.pad_to_tile(129) == 256
    assert kp.pad_to_tile(257) == 1024
