"""Differential tests: JAX batched ed25519 verifier vs the Python oracle.

This is the CPU-reference-vs-device differential harness SURVEY.md §4 calls
for, including the invalid-signature blame path and ZIP-215 edge cases.
"""
import os

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k


def make_sigs(n, msg_fn=lambda i: b"msg-%d" % i):
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [msg_fn(i) for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def test_all_valid_batch():
    pubs, msgs, sigs = make_sigs(5)
    got = k.verify_batch(pubs, msgs, sigs)
    assert got.shape == (5,)
    assert got.all()


def test_blame_path_mixed_batch():
    pubs, msgs, sigs = make_sigs(8)
    bad = dict()
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    bad[2] = True
    msgs[5] = msgs[5] + b"tampered"
    bad[5] = True
    sigs[6] = sigs[6][:32] + int.to_bytes(
        int.from_bytes(sigs[6][32:], "little") + ed.L, 32, "little"
    )  # S >= L: malleability reject in precheck
    bad[6] = True
    got = k.verify_batch(pubs, msgs, sigs)
    for i in range(8):
        assert got[i] == (i not in bad), i
        assert got[i] == ed.verify(pubs[i], msgs[i], sigs[i]), i


def test_matches_oracle_on_garbage():
    rng = np.random.default_rng(3)
    pubs, msgs, sigs = [], [], []
    for i in range(16):
        pubs.append(rng.bytes(32))
        msgs.append(rng.bytes(i))
        sigs.append(rng.bytes(64))
    got = k.verify_batch(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    np.testing.assert_array_equal(got, np.asarray(exp))


def test_zip215_edges():
    """Non-canonical encodings and small-order points must match the oracle
    bit-for-bit — consensus forks otherwise."""
    # identity-key / zero signature (valid under ZIP-215 cofactored eq)
    ident = ed.pt_compress(ed.IDENT)
    cases = [(ident, b"m", ident + b"\x00" * 32)]
    # non-canonical R: y + p for a small-y point on the curve
    for y in range(19):
        u, v = (y * y - 1) % ed.P, (ed.D * y * y + 1) % ed.P
        ok, x = ed._sqrt_ratio(u, v)
        if ok:
            enc_nc = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32, "little")
            break
    seed = bytes(32)
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, b"x")
    cases.append((pub, b"x", enc_nc + sig[32:]))  # valid-format, wrong R
    cases.append((enc_nc, b"x", sig))  # non-canonical pubkey
    # sign-bit edge: y=1 (x=0) with sign bit set
    neg_zero = int.to_bytes(1 | (1 << 255), 32, "little")
    cases.append((neg_zero, b"m", neg_zero + b"\x00" * 32))
    pubs, msgs, sigs = zip(*cases)
    got = k.verify_batch(list(pubs), list(msgs), list(sigs))
    exp = [ed.verify(p, m, s) for p, m, s in cases]
    np.testing.assert_array_equal(got, np.asarray(exp))
    # sanity: at least one of these exotic cases is actually valid
    assert any(exp)


def test_fused_tally_quorum():
    import jax.numpy as jnp

    pubs, msgs, sigs = make_sigs(12)
    sigs[3] = sigs[3][:20] + bytes([sigs[3][20] ^ 4]) + sigs[3][21:]
    pb = k.pack_batch(pubs, msgs, sigs)
    powers = np.array([10] * 6 + [5] * 6, dtype=np.int64)
    power5 = np.zeros((pb.padded, k.POWER_LIMBS), np.int32)
    power5[:12] = k.power_limbs(powers)
    counted = np.zeros((pb.padded,), np.bool_)
    counted[:12] = True
    counted[7] = False  # e.g. a nil-vote: verified but not tallied
    commit_ids = np.zeros((pb.padded,), np.int32)
    commit_ids[6:12] = 1  # two commits in one batch
    # commit 0: powers 10*6 minus invalid idx3 -> 50; commit 1: 5*6 minus
    # uncounted idx7 -> 25
    thresh = np.zeros((2, k.TALLY_LIMBS), np.int32)
    thresh[0, 0] = 49
    thresh[1, 0] = 25
    valid, tally, quorum = k.verify_tally_kernel(
        pb.ay, pb.asign, pb.ry, pb.rsign, pb.sdig, pb.hdig, pb.precheck,
        jnp.asarray(power5), jnp.asarray(counted), jnp.asarray(commit_ids),
        jnp.asarray(thresh), n_commits=2,
    )
    t = k.tally_to_int(np.asarray(tally))
    assert int(t[0]) == 50 and int(t[1]) == 25
    assert bool(quorum[0]) and not bool(quorum[1])
    exp_valid = [i != 3 for i in range(12)]
    np.testing.assert_array_equal(np.asarray(valid)[:12], exp_valid)


def test_large_power_tally_limbs():
    """Voting powers near MaxTotalVotingPower stay exact in limb arithmetic."""
    p = np.array([2**60, 2**60 - 1, 12345678901234567], dtype=np.int64)
    limbs = k.power_limbs(p)
    back = k.tally_to_int(limbs)
    assert [int(x) for x in back] == [int(v) for v in p]
