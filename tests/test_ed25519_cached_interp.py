"""Interpret-mode differential for the cached ZIP-215 kernel on CPU.

ADVICE r5 low: the cached-kernel differentials were gated behind
CBT_TEST_ON_TPU=1, so default CI never exercised the kernel math. This
file runs the REAL kernel (Pallas interpret mode) at the smallest legal
shape — one 128-lane tile, one 128-slot table block — with no env gate,
so `python -m pytest tests/` (the full default suite) enforces the
oracle differential on any box.

Measured on this 1-core CPU host: ~13.5 min cold (3.5 min XLA compile
of the table build + ~10 min kernel interpret compile), seconds when
the persistent compilation cache (conftest.py) is warm. That budget is
why it carries `slow`: tier-1's `-m 'not slow'` quick gate must not
spend its 870 s timeout here, while the full suite — and any TPU run —
still exercises it. The host-side bookkeeping is covered untimed in
test_ed25519_cached_host.py; full-shape kernel coverage stays in
test_ed25519_cached.py (TPU).
"""
import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_cached as ec

pytestmark = pytest.mark.slow


def test_cached_kernel_minimal_shape_vs_oracle():
    """One tile, one table block: valid rows, tampered sig/msg, S>=L
    malleability, bad pubkey, small-order identity, non-canonical
    encodings — all must match the pure-Python ZIP-215 oracle."""
    n = 12
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [b"interp-%d" % i for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]

    # adversarial rows
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    msgs[4] = msgs[4] + b"tampered"
    sigs[5] = sigs[5][:32] + int.to_bytes(
        int.from_bytes(sigs[5][32:], "little") + ed.L, 32, "little"
    )
    pubs[6] = b"\xff" * 32                       # undecompressable A
    ident = ed.pt_compress(ed.IDENT)
    pubs[7], msgs[7], sigs[7] = ident, b"m", ident + b"\x00" * 32
    neg_zero = int.to_bytes(1 | (1 << 255), 32, "little")
    pubs[8], msgs[8], sigs[8] = neg_zero, b"m", neg_zero + b"\x00" * 32

    got = ec.verify_batch_cached(pubs, msgs, sigs)
    exp = [ed.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert got[0] and got[1] and got[3]
    assert not got[2] and not got[4] and not got[5] and not got[6]
    # ZIP-215: small-order identity and -0 encodings ACCEPT
    assert exp[7] and exp[8]
