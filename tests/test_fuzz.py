"""Fuzz tests: adversarial bytes against the attack-surface parsers.

Reference: test/fuzz (mempool CheckTx, p2p SecretConnection read/write,
jsonrpc server) + p2p/fuzz.go's fault-injecting connection. Seeded RNG
throughout so failures reproduce.
"""
import json
import random
import socket
import threading
import time
import urllib.request
import urllib.error

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from cometbft_tpu.p2p.conn.secret_connection import (
    HandshakeError,
    SecretConnection,
)
from cometbft_tpu.p2p.fuzz import FuzzConnConfig, FuzzedSocket


def _sock_pair():
    a, b = socket.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    return a, b


def _handshake_pair(seed=1):
    """Two SecretConnections over a socketpair."""
    a, b = _sock_pair()
    ka = PrivKey.generate(bytes([seed]) * 32)
    kb = PrivKey.generate(bytes([seed + 1]) * 32)
    out = {}

    def srv():
        out["b"] = SecretConnection.handshake(b, kb)

    t = threading.Thread(target=srv, daemon=True)
    t.start()
    sca = SecretConnection.handshake(a, ka)
    t.join(timeout=5)
    return sca, out["b"]


def test_secret_connection_frame_corruption_never_panics():
    """Random bit flips in the ciphertext stream must surface as clean
    errors (auth tag failure), never hangs or silent acceptance."""
    rng = random.Random(1234)
    for trial in range(12):
        sca, scb = _handshake_pair(seed=40 + trial)
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 400)))
        raw = scb._stream  # the raw socket under b

        # a sends a frame, we corrupt bytes in flight b -> reads garbage:
        # emulate by writing a corrupted copy of a valid frame
        sca.write_msg(msg)
        frame = raw.recv(65536)
        pos = rng.randrange(len(frame))
        bad = bytearray(frame)
        bad[pos] ^= 0xFF
        # feed the corrupted frame back through a fresh pair's socket
        c, d = _sock_pair()
        c.sendall(bytes(bad))
        scb._stream = d
        with pytest.raises(Exception) as ei:
            scb.read_msg()
        assert not isinstance(ei.value, (SystemExit, KeyboardInterrupt))
        for s in (c, d):
            s.close()


def test_handshake_garbage_rejected():
    """Random garbage during the STS handshake must error out, not hang
    (test/fuzz p2p_secretconnection analog)."""
    rng = random.Random(99)
    for _ in range(8):
        a, b = _sock_pair()
        k = PrivKey.generate(bytes([7]) * 32)

        def attacker():
            try:
                n = rng.randrange(1, 200)
                b.sendall(bytes(rng.randrange(256) for _ in range(n)))
                b.shutdown(socket.SHUT_WR)
            except OSError:
                pass

        t = threading.Thread(target=attacker, daemon=True)
        t.start()
        with pytest.raises((HandshakeError, OSError, ValueError)):
            SecretConnection.handshake(a, k)
        t.join(timeout=5)
        for s in (a, b):
            s.close()


def test_mempool_checktx_fuzz():
    """Random tx bytes through CheckTx: no exceptions, cache stays
    bounded (test/fuzz mempool analog)."""
    mp = Mempool(KVStoreApplication())
    rng = random.Random(7)
    for _ in range(300):
        tx = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 300)))
        try:
            mp.check_tx(tx)
        except Exception as e:  # noqa: BLE001
            pytest.fail(f"CheckTx raised on fuzz input: {e!r}")


def test_fuzzed_socket_drops_are_survivable():
    """MConnection over a dropping FuzzedSocket: the connection either
    keeps delivering or dies via on_error — never hangs a thread or
    crashes the process (p2p/fuzz.go's purpose)."""
    sca, scb = _handshake_pair(seed=80)
    # fuzz a's underlying socket: 20% write drops after handshake
    sca._stream = FuzzedSocket(sca._stream, FuzzConnConfig(
        prob_drop_rw=0.2, seed=5,
    ))
    got, errs = [], []
    chans = [ChannelDescriptor(0x01, priority=1)]
    ma = MConnection(sca, chans, lambda c, m: None,
                     on_error=errs.append)
    mb = MConnection(scb, chans, lambda c, m: got.append(m),
                     on_error=errs.append)
    ma.start()
    mb.start()
    try:
        for i in range(60):
            ma.send(0x01, b"m%d" % i, block=False)
        deadline = time.time() + 8
        while time.time() < deadline and not got and not errs:
            time.sleep(0.05)
        # some messages made it through, or the connection failed clean
        assert got or errs
    finally:
        ma.stop()
        mb.stop()


def test_rpc_server_fuzz(tmp_path):
    """Garbage HTTP bodies and query strings against the JSON-RPC server
    return error responses, never hang or kill the server
    (test/fuzz rpc_jsonrpc_server analog)."""
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.rpc.server import RPCServer
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    priv = PrivKey.generate(bytes([3]) * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("fuzz-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"),
                timeouts=TimeoutParams(propose=0.4, propose_delta=0.1,
                                       prevote=0.2, prevote_delta=0.1,
                                       precommit=0.2, precommit_delta=0.1,
                                       commit=0.01))
    node.start()
    rpc = RPCServer(node, host="127.0.0.1", port=0)
    rpc.start()
    base = rpc.address
    rng = random.Random(11)
    try:
        bodies = [
            b"", b"{", b"[]", b"\x00\xff" * 50, b'{"jsonrpc":"2.0"}',
            json.dumps({"jsonrpc": "2.0", "method": "nope",
                        "id": 1}).encode(),
            json.dumps({"jsonrpc": "2.0", "method": "block",
                        "params": {"height": "NaN"}, "id": 2}).encode(),
            json.dumps({"jsonrpc": "2.0", "method": "block",
                        "params": {"height": -(2**70)}, "id": 3}).encode(),
        ] + [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
             for _ in range(10)]
        for body in bodies:
            req = urllib.request.Request(base + "/", data=body,
                                         method="POST")
            try:
                with urllib.request.urlopen(req, timeout=5) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                e.read()
            except urllib.error.URLError as e:
                pytest.fail(f"server hung/died on {body[:20]!r}: {e}")
        # server still sane after the abuse
        with urllib.request.urlopen(base + "/health", timeout=5) as r:
            assert r.status == 200
    finally:
        rpc.stop()
        node.stop()
