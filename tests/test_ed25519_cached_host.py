"""Host-side bookkeeping of the cached-valset path (runs on CPU).

The kernel itself is TPU-gated (see test_ed25519_cached.py); everything
here exercises the table-cache logic WITHOUT invoking the Pallas
kernel: cache-key injectivity, near-miss digest deltas, packed-row
layout, power bookkeeping, and the churn budget fallback.
"""
import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_cached as ec
from cometbft_tpu.ops import ed25519_kernel as ek


def pubs_n(n, tag=1):
    return [ed.pubkey_from_seed(bytes([tag, i % 251]) + b"\x13" * 30)
            for i in range(n)]


def test_cache_key_injective_over_lengths():
    a = ec._cache_key([b"", b"\x00" * 32], None)
    b = ec._cache_key([b"\x00" * 32, b""], None)
    assert a != b
    assert ec._cache_key([b"k"], [5]) != ec._cache_key([b"k"], [6])


def test_pubs_host_delta_detection():
    """The near-miss delta scan compares FULL pubkey bytes (the digest
    comparison it replaced was birthday-collidable at 2^32 work —
    round-5 advisory high)."""
    pubs = pubs_n(130)
    h1 = ec._pubs_host(pubs, 256)
    pubs2 = list(pubs)
    pubs2[77] = ed.pubkey_from_seed(b"\x99" * 32)
    h2 = ec._pubs_host(pubs2, 256)
    assert [i for i in range(256) if h1[i] != h2[i]] == [77]
    # padding slots are empty and equal
    assert h1[255] == b"" and len(h1) == 256


def test_pack_rows_layout():
    """The compact row layout round-trips: R limbs, s bytes, h nibbles,
    flags, thresholds land where the kernel expects them."""
    rng = np.random.default_rng(3)
    n, pad = 5, 128
    pubs = pubs_n(n)
    msgs = [b"m%d" % i for i in range(n)]
    seeds = [bytes([1, i % 251]) + b"\x13" * 30 for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    pb = ek.pack_batch(pubs, msgs, sigs, pad_to=pad)
    counted = np.zeros(pad, np.bool_)
    counted[:n] = True
    cids = np.zeros(pad, np.int32)
    thresh = ek.threshold_limbs(1234567890123, 1)
    rows = ec.pack_rows_cached(pb, counted, cids, thresh)
    assert rows.shape[0] == ec.V_THRESH + 1
    # R y limbs round-trip from the packed pairs
    ry = np.asarray(pb.ry, np.int64)
    packed = rows[ec.V_RY:ec.V_RY + 10]
    lo, hi = packed & ((1 << 13) - 1), packed >> 13
    np.testing.assert_array_equal(lo.T, ry[:, :10])
    np.testing.assert_array_equal(hi.T, ry[:, 10:])
    # flags: precheck bit set only for real rows; counted bit matches
    flags = rows[ec.V_FLAGS]
    assert ((flags[:n] >> 1) & 1).all()
    assert not ((flags[n:] >> 1) & 1).any()
    assert (((flags >> 2) & 1) == counted.astype(np.int32)).all()
    # threshold limbs recoverable
    tv = rows[ec.V_THRESH:].reshape(-1)[: ek.TALLY_LIMBS]
    assert ek.tally_to_int(tv) == 1234567890123


def test_update_table_budget_errors():
    """Deltas beyond UPDATE_PAD raise ValueError (table_for_pubs turns
    that into a full rebuild) and out-of-range indices are rejected."""
    t = ec.ValsetTable(None, None, None, 256,
                       ec._pubs_host([], 256),
                       np.zeros(256, np.int64))
    with pytest.raises(ValueError):
        ec.update_table(t, [(300, b"\x00" * 32)])
    too_many = [(i, b"\x00" * 32) for i in range(ec.UPDATE_PAD + 1)]
    with pytest.raises(ValueError):
        ec.update_table(t, too_many)
    # power-only deltas on top of key changes count against the budget
    changes = [(i, b"\x00" * 32) for i in range(ec.UPDATE_PAD)]
    with pytest.raises(ValueError):
        ec.update_table(t, changes, {ec.UPDATE_PAD + 1: 5})
    # no-op delta returns the same table object
    assert ec.update_table(t, [], None) is t


def test_pad_rows_buckets():
    assert ec.pad_rows(1) == 128
    assert ec.pad_rows(129) == 256
    assert ec.pad_rows(5000) == 6144
    assert ec.pad_rows(10000) == 10240
    with pytest.raises(ValueError):
        ec.pad_rows(70000)


# -- incremental warming (warm_incremental) --------------------------------
# Each test swaps in a private table cache so the shared process-global
# one (other test files may have populated it) can never donate or
# receive a near-miss base.

def _fake_table(pubs, padded=128):
    return ec.ValsetTable(None, None, None, padded,
                          ec._pubs_host(pubs, padded),
                          np.zeros(padded, np.int64))


def _private_cache(monkeypatch):
    from cometbft_tpu.ops import table_cache as tc

    cache = tc.BoundedLRU("tables", 8, size_fn=tc.default_size)
    monkeypatch.setattr(ec, "_TABLE_CACHE", cache)
    return cache


def test_warm_incremental_no_base_returns_false(monkeypatch):
    cache = _private_cache(monkeypatch)
    calls = []
    monkeypatch.setattr(ec, "update_table",
                        lambda *a, **k: calls.append(a))
    assert ec.warm_incremental(tuple(pubs_n(4, tag=101))) is False
    assert calls == [] and len(cache) == 0
    # a base of a DIFFERENT padded size is not eligible either
    with ec._TABLE_LOCK:
        cache.put(b"base256", _fake_table(pubs_n(200, tag=102), 256))
    assert ec.warm_incremental(tuple(pubs_n(4, tag=101))) is False
    assert calls == []


def test_warm_incremental_patches_eligible_base(monkeypatch):
    cache = _private_cache(monkeypatch)
    base_pubs = pubs_n(4, tag=103)
    target_pubs = tuple(pubs_n(4, tag=104))
    with ec._TABLE_LOCK:
        cache.put(b"base", _fake_table(base_pubs))
        h0 = dict(ec._TABLE_STATS)
    marker = _fake_table(target_pubs)
    seen = []

    def fake_update(cand, changes, pw_map=None):
        seen.append((len(changes), dict(pw_map or {})))
        return marker

    monkeypatch.setattr(ec, "update_table", fake_update)
    assert ec.warm_incremental(target_pubs) is True
    # the 4 changed slots (padding rows identical) rode the update,
    # with no power rewrites
    assert seen == [(4, {})]
    key = ec._memo_cache_key(target_pubs, None)
    with ec._TABLE_LOCK:
        assert cache.peek(key) is marker
        h1 = dict(ec._TABLE_STATS)
    # a warm is neither a hit nor a miss, but IS an incremental patch
    assert h1["hits"] == h0["hits"]
    assert h1["misses"] == h0["misses"]
    assert h1["incremental_patches"] == h0["incremental_patches"] + 1
    # second warm: already cached, no second update_table call
    assert ec.warm_incremental(target_pubs) is True
    assert len(seen) == 1


def test_warm_incremental_budget_overflow_returns_false(monkeypatch):
    cache = _private_cache(monkeypatch)
    with ec._TABLE_LOCK:
        cache.put(b"base", _fake_table(pubs_n(4, tag=105)))

    def refuse(*a, **k):
        raise ValueError("delta over budget")

    monkeypatch.setattr(ec, "update_table", refuse)
    with ec._TABLE_LOCK:
        h0 = dict(ec._TABLE_STATS)
    assert ec.warm_incremental(tuple(pubs_n(4, tag=106))) is False
    with ec._TABLE_LOCK:
        h1 = dict(ec._TABLE_STATS)
    assert h1["incremental_patches"] == h0["incremental_patches"]
