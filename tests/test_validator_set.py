"""ValidatorSet: sorting, lookup, proposer rotation, updates, hashing.

Mirrors types/validator_set_test.go case structure (proposer rotation
frequency proportional to power, update semantics, power cap).
"""
import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.types.validator import (
    MAX_TOTAL_VOTING_POWER,
    Validator,
    ValidatorSet,
    ValidatorSetError,
)


def mkvals(powers):
    out = []
    for i, p in enumerate(powers):
        priv = PrivKey.generate(bytes([i + 1]) * 32)
        out.append(Validator(priv.pub_key(), p))
    return out


def test_sorted_by_power_desc_then_address():
    """ValidatorsByVotingPower order (validator_set.go:752-763): power
    desc, address asc tiebreak — fixes the hash and index mapping."""
    vs = ValidatorSet(mkvals([10, 30, 20, 30]))
    powers = [v.voting_power for v in vs.validators]
    assert powers == [30, 30, 20, 10]
    tied = [v.address for v in vs.validators if v.voting_power == 30]
    assert tied == sorted(tied)
    for i, v in enumerate(vs.validators):
        j, got = vs.get_by_address(v.address)
        assert j == i and got is v
    assert vs.get_by_address(b"\x00" * 20) == (-1, None)
    assert vs.get_by_index(99) is None
    assert vs.total_voting_power() == 90


def test_duplicate_address_rejected():
    v = mkvals([5])[0]
    with pytest.raises(ValidatorSetError):
        ValidatorSet([v, Validator(v.pub_key, 7)])


def test_proposer_rotation_proportional():
    """Proposer frequency tracks voting power (validator_set.go docstring:
    priority-queue rotation)."""
    vs = ValidatorSet(mkvals([1, 2, 7]))
    by_addr = {v.address: 0 for v in vs.validators}
    power = {v.address: v.voting_power for v in vs.validators}
    for _ in range(1000):
        p = vs.get_proposer()
        by_addr[p.address] += 1
        vs.increment_proposer_priority(1)
    for a, count in by_addr.items():
        assert abs(count - 100 * power[a]) <= 10, (count, power[a])


def test_total_power_cap():
    with pytest.raises(ValidatorSetError):
        ValidatorSet(mkvals([MAX_TOTAL_VOTING_POWER, 1]))


def test_hash_changes_with_set():
    a = ValidatorSet(mkvals([10, 20]))
    b = ValidatorSet(mkvals([10, 21]))
    assert a.hash() != b.hash()
    assert a.hash() == ValidatorSet(mkvals([10, 20])).hash()
    assert len(a.hash()) == 32


def test_update_with_change_set():
    vals = mkvals([10, 20, 30])
    vs = ValidatorSet(vals)
    h0 = vs.hash()
    # update power of one, remove one, add one
    newv = mkvals([1, 1, 1, 40])[3]
    changes = [
        Validator(vals[0].pub_key, 15),   # update
        Validator(vals[1].pub_key, 0),    # remove
        newv,                              # add
    ]
    vs.update_with_change_set(changes)
    assert vs.total_voting_power() == 15 + 30 + 40
    assert not vs.has_address(vals[1].address)
    assert vs.has_address(newv.address)
    assert vs.hash() != h0
    # removing a non-member fails
    ghost = mkvals([1, 1, 1, 1, 9])[4]
    with pytest.raises(ValidatorSetError):
        vs.update_with_change_set([Validator(ghost.pub_key, 0)])


def test_copy_isolated():
    vs = ValidatorSet(mkvals([5, 5]))
    cp = vs.copy()
    before = [v.proposer_priority for v in cp.validators]
    vs.increment_proposer_priority(3)
    assert [v.proposer_priority for v in cp.validators] == before
    assert [v.proposer_priority for v in vs.validators] != before


def test_state_store_roundtrip_preserves_proposer(tmp_path):
    """ISSUE 3 (found by the simnet kill/restart schedules): the
    persisted valset must carry the SELECTED proposer. Selection
    decrements the winner's priority by the total power, so a reload
    that re-derives "max priority" elects a different validator than
    every live peer — the restarted node then signs proposals its peers
    reject as forged (and would disconnect it for, over real p2p)."""
    from cometbft_tpu.state.state import State, StateStore

    vs = ValidatorSet(mkvals([10, 10, 10, 10]))
    # a few rotation steps so the memoized proposer is NOT the
    # max-priority row
    vs.increment_proposer_priority(1)
    want = vs.get_proposer().address
    assert vs._find_proposer().address != want  # re-derivation differs

    state = State.make_genesis("prop-chain", ValidatorSet(mkvals([10] * 4)))
    from dataclasses import replace

    state = replace(state, validators=vs, next_validators=vs.copy())
    store = StateStore(str(tmp_path / "state.db"))
    store.save(state)
    loaded = store.load()
    assert loaded.validators.get_proposer().address == want
    # the per-height validator history restores it too
    hist = store.load_validators(state.last_block_height + 1)
    assert hist.get_proposer().address == want
    store.close()
