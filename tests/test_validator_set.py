"""ValidatorSet: sorting, lookup, proposer rotation, updates, hashing.

Mirrors types/validator_set_test.go case structure (proposer rotation
frequency proportional to power, update semantics, power cap).
"""
import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.types.validator import (
    MAX_TOTAL_VOTING_POWER,
    Validator,
    ValidatorSet,
    ValidatorSetError,
)


def mkvals(powers):
    out = []
    for i, p in enumerate(powers):
        priv = PrivKey.generate(bytes([i + 1]) * 32)
        out.append(Validator(priv.pub_key(), p))
    return out


def test_sorted_by_power_desc_then_address():
    """ValidatorsByVotingPower order (validator_set.go:752-763): power
    desc, address asc tiebreak — fixes the hash and index mapping."""
    vs = ValidatorSet(mkvals([10, 30, 20, 30]))
    powers = [v.voting_power for v in vs.validators]
    assert powers == [30, 30, 20, 10]
    tied = [v.address for v in vs.validators if v.voting_power == 30]
    assert tied == sorted(tied)
    for i, v in enumerate(vs.validators):
        j, got = vs.get_by_address(v.address)
        assert j == i and got is v
    assert vs.get_by_address(b"\x00" * 20) == (-1, None)
    assert vs.get_by_index(99) is None
    assert vs.total_voting_power() == 90


def test_duplicate_address_rejected():
    v = mkvals([5])[0]
    with pytest.raises(ValidatorSetError):
        ValidatorSet([v, Validator(v.pub_key, 7)])


def test_proposer_rotation_proportional():
    """Proposer frequency tracks voting power (validator_set.go docstring:
    priority-queue rotation)."""
    vs = ValidatorSet(mkvals([1, 2, 7]))
    by_addr = {v.address: 0 for v in vs.validators}
    power = {v.address: v.voting_power for v in vs.validators}
    for _ in range(1000):
        p = vs.get_proposer()
        by_addr[p.address] += 1
        vs.increment_proposer_priority(1)
    for a, count in by_addr.items():
        assert abs(count - 100 * power[a]) <= 10, (count, power[a])


def test_total_power_cap():
    with pytest.raises(ValidatorSetError):
        ValidatorSet(mkvals([MAX_TOTAL_VOTING_POWER, 1]))


def test_hash_changes_with_set():
    a = ValidatorSet(mkvals([10, 20]))
    b = ValidatorSet(mkvals([10, 21]))
    assert a.hash() != b.hash()
    assert a.hash() == ValidatorSet(mkvals([10, 20])).hash()
    assert len(a.hash()) == 32


def test_update_with_change_set():
    vals = mkvals([10, 20, 30])
    vs = ValidatorSet(vals)
    h0 = vs.hash()
    # update power of one, remove one, add one
    newv = mkvals([1, 1, 1, 40])[3]
    changes = [
        Validator(vals[0].pub_key, 15),   # update
        Validator(vals[1].pub_key, 0),    # remove
        newv,                              # add
    ]
    vs.update_with_change_set(changes)
    assert vs.total_voting_power() == 15 + 30 + 40
    assert not vs.has_address(vals[1].address)
    assert vs.has_address(newv.address)
    assert vs.hash() != h0
    # removing a non-member fails
    ghost = mkvals([1, 1, 1, 1, 9])[4]
    with pytest.raises(ValidatorSetError):
        vs.update_with_change_set([Validator(ghost.pub_key, 0)])


def test_copy_isolated():
    vs = ValidatorSet(mkvals([5, 5]))
    cp = vs.copy()
    before = [v.proposer_priority for v in cp.validators]
    vs.increment_proposer_priority(3)
    assert [v.proposer_priority for v in cp.validators] == before
    assert [v.proposer_priority for v in vs.validators] != before


def test_state_store_roundtrip_preserves_proposer(tmp_path):
    """ISSUE 3 (found by the simnet kill/restart schedules): the
    persisted valset must carry the SELECTED proposer. Selection
    decrements the winner's priority by the total power, so a reload
    that re-derives "max priority" elects a different validator than
    every live peer — the restarted node then signs proposals its peers
    reject as forged (and would disconnect it for, over real p2p)."""
    from cometbft_tpu.state.state import State, StateStore

    vs = ValidatorSet(mkvals([10, 10, 10, 10]))
    # a few rotation steps so the memoized proposer is NOT the
    # max-priority row
    vs.increment_proposer_priority(1)
    want = vs.get_proposer().address
    assert vs._find_proposer().address != want  # re-derivation differs

    state = State.make_genesis("prop-chain", ValidatorSet(mkvals([10] * 4)))
    from dataclasses import replace

    state = replace(state, validators=vs, next_validators=vs.copy())
    store = StateStore(str(tmp_path / "state.db"))
    store.save(state)
    loaded = store.load()
    assert loaded.validators.get_proposer().address == want
    # the per-height validator history restores it too
    hist = store.load_validators(state.last_block_height + 1)
    assert hist.get_proposer().address == want
    store.close()


# ---------------------------------------------------------------------------
# Epoch-rotation edges (ISSUE 12): the churn path's interaction with
# the proposer memo and the valset-table identity memo.
# ---------------------------------------------------------------------------


def test_proposer_persists_across_rotation_and_restart(tmp_path):
    """The PR 3 proposer-persistence fix, extended through a ROTATION:
    a committee re-election (update_with_change_set) immediately before
    a restart must reload the same selected proposer — rotation clears
    the proposer memo, selection re-runs, and the persisted row must
    carry the NEW selection, not a re-derivation."""
    from dataclasses import replace

    from cometbft_tpu.state.state import State, StateStore

    vals = mkvals([10, 10, 10, 10])
    vs = ValidatorSet(vals)
    # the rotation: one member out, one in, one repowered
    newv = mkvals([1, 1, 1, 1, 25])[4]
    vs.update_with_change_set([
        Validator(vals[2].pub_key, 0),
        Validator(vals[0].pub_key, 14),
        newv,
    ])
    vs.increment_proposer_priority(1)  # select post-rotation proposer
    want = vs.get_proposer().address
    assert vs.has_address(want)  # the selection is a current member

    state = State.make_genesis("rot-chain", ValidatorSet(mkvals([10] * 4)))
    state = replace(state, validators=vs, next_validators=vs.copy())
    store = StateStore(str(tmp_path / "state.db"))
    store.save(state)
    loaded = store.load()
    assert loaded.validators.get_proposer().address == want
    assert sorted(v.address for v in loaded.validators.validators) == \
        sorted(v.address for v in vs.validators)
    store.close()


def test_rotation_invalidates_table_identity_memo(monkeypatch):
    """table_for_valset memoizes by (set identity, validators-list
    identity). BOTH rotation shapes must invalidate it: a
    membership change AND a power-only change (each replaces the
    validators list wholesale in update_with_change_set) — a stale
    table would verify against retired keys or tally stale powers."""
    from cometbft_tpu.ops import ed25519_cached as ec

    tables = []

    def fake_table_for_pubs(pubs, powers=None):
        tables.append((pubs, powers))
        return object()

    monkeypatch.setattr(ec, "table_for_pubs", fake_table_for_pubs)
    ec._VALSET_MEMO.clear()

    vals = mkvals([10, 20, 30])
    vs = ValidatorSet(vals)
    t1 = ec.table_for_valset(vs)
    assert ec.table_for_valset(vs) is t1  # steady state: memo hit

    # power-only change: same membership, new power
    vs.update_with_change_set([Validator(vals[0].pub_key, 11)])
    t2 = ec.table_for_valset(vs)
    assert t2 is not t1
    assert tables[-1][1] != tables[0][1]  # the new powers reached it

    # membership change: one out, one in
    newv = mkvals([1, 1, 1, 40])[3]
    vs.update_with_change_set([Validator(vals[1].pub_key, 0), newv])
    t3 = ec.table_for_valset(vs)
    assert t3 is not t2
    assert newv.pub_key.data in tables[-1][0]


def test_rotated_out_valset_memo_entry_evictable(monkeypatch):
    """A retired epoch's table must be GC-able once the bounded caches
    evict it: neither the valset memo nor any QuorumGroup-tuple memo
    may keep a strong ref past eviction."""
    import gc
    import weakref

    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import table_cache as tc

    class _T:  # weakref-able stand-in (object() is not)
        pass

    monkeypatch.setattr(ec, "table_for_pubs",
                        lambda pubs, powers=None: _T())
    ec._VALSET_MEMO.clear()
    saved = tc.capacities()
    tc.set_capacities(valset_memo=2)
    try:
        vs = ValidatorSet(mkvals([10, 20]))
        old = ec.table_for_valset(vs)
        ref = weakref.ref(old)
        del old
        # two epochs of churn push the retired entry out of the memo
        for power in (11, 12):
            vs2 = ValidatorSet(mkvals([10, 20]))
            vs2.update_with_change_set(
                [Validator(vs2.validators[0].pub_key, power)])
            ec.table_for_valset(vs2)
        ec.table_for_valset(ValidatorSet(mkvals([5, 5, 5])))
        gc.collect()
        assert ref() is None, \
            "rotated-out epoch's table still strongly referenced"
    finally:
        tc.set_capacities(**saved)
