"""Tier-1 guard against tools/loadtime.py rot (ISSUE 7 satellite).

The full loadtime modes drive a live consensus net for tens of seconds;
`--smoke` is the tier-1-safe slice — mempool + admission + a host-path
verify plane only, no consensus, NO jax import, a couple of seconds.
This file (late in the alphabet on purpose, like test_zbench_smoke)
drives it through main() exactly like the CI invocation would, keeping
the overload-verdict path (explicit OVERLOADED codes with retry hints)
continuously exercised.
"""
import json
import sys

from tools import loadtime


def test_loadtime_smoke_cli(capsys):
    """`loadtime.py --smoke` exits 0, prints one JSON document with
    both outcomes populated (accepted AND explicitly overloaded), and
    never imports jax."""
    jax_loaded_before = "jax" in sys.modules
    rc = loadtime.main(["--smoke"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    # open-loop accounting: every offered tx got exactly one verdict
    assert rep["offered"] == rep["accepted"] + rep["overloaded"] \
        + rep["rejected_other"]
    assert rep["accepted"] > 0
    assert rep["overloaded"] > 0, "smoke never exercised overload"
    assert rep["rejected_other"] == 0, rep["codes"]
    # every overload verdict carries the backoff hint
    assert rep["overload_log_samples"]
    assert all("retry_after_ms=" in s
               for s in rep["overload_log_samples"])
    # the signed flood rode the BULK lane; consensus lane stayed empty
    # and was never shed (there IS no consensus traffic here)
    assert rep["plane"]["lane_rows"]["bulk"] > 0
    assert rep["plane"]["sheds"]["consensus"] == 0
    # admission accounting adds up
    adm = rep["admission"]
    assert adm["inflight"] == 0, "admission slots leaked"
    assert sum(adm["counts"].values()) >= rep["offered"]
    if not jax_loaded_before:
        assert "jax" not in sys.modules, "--smoke imported jax"
    assert rep["jax_imported"] is False


def test_open_loop_schedule_is_not_closed_loop():
    """The open-loop discipline itself: a submit path that stalls hard
    must not slow the offered schedule below its configured rate — the
    generator keeps injecting (queueing on workers) instead of politely
    waiting, which is the honesty property the ISSUE names."""
    import time

    run = loadtime.OpenLoopRun()
    calls = []

    def slow_submit(tx):
        calls.append(tx)
        time.sleep(0.05)  # 20/s per worker vs 200/s offered
        return 0, ""

    wall = loadtime.open_loop(200.0, 0.5, lambda k: b"x%d" % k,
                              slow_submit, run, workers=4)
    assert run.offered == 100
    # closed-loop would need 100 * 50ms / 4 workers = 1.25 s of
    # injection pacing; open-loop pacing finishes the schedule on time
    # and only then drains the queue
    assert wall < 2.5
    lat = run.report(wall)["checktx_latency"]
    # queueing delay is VISIBLE in the latencies (not hidden by pacing)
    assert lat["max_ms"] >= 50.0
