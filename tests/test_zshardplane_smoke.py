"""Tier-1 coverage for the multichip sharded verify plane (ISSUE 10)
and the pipelined flight deck (ISSUE 11) without TPU hardware: a
subprocess forced onto a 4-virtual-device CPU mesh runs
tests/_shardplane_prog.py, which stubs the two expensive device
programs (Pallas cached kernel, XLA table build) and drives the REAL
plane machinery — sharded plan/scatter, per-shard table assembly and
(valset, mesh) memoization, the psum-tally mesh step, ledger n_dev
attribution, breaker + PlaneOverloaded semantics under a faulting
sharded dispatch — asserting bit-identical verdicts/tallies/quorum vs
the single-device oracle. The deck phases then prove two flights
genuinely airborne on DISJOINT mesh halves (ledger dev0 0 vs 2 with
airborne=1), out-of-order landing when flight 2 finishes first, the
giant-flush drain-the-deck-then-full-mesh policy, and a breaker trip
mid-deck degrading every airborne flight to correct host verdicts.

Subprocess on purpose (late-alphabet, host-safe shapes): the device
count must be fixed BEFORE jax initializes, independently of the
suite's own 8-device conftest forcing, and the stubs must never leak
into other tests' modules.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_shardplane_prog.py")


def test_sharded_plane_matches_single_device_on_forced_4dev_host():
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("CBT_TEST_ON_TPU", None)
    env["PYTHONPATH"] = REPO
    out = subprocess.run(
        [sys.executable, PROG], env=env, cwd=REPO, timeout=300,
        capture_output=True, text=True,
    )
    assert out.returncode == 0, (
        f"stdout:\n{out.stdout[-4000:]}\nstderr:\n{out.stderr[-4000:]}"
    )
    last = [ln for ln in out.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    rep = json.loads(last)
    assert rep["ok"] and rep["devices"] == 4
    # 300 validators over stride-256 shards fill 2 devices; the flush
    # clamps to the 2-device sub-mesh (empty shards = dead work)
    assert rep["n_dev_max"] == 2
    assert rep["sharded_flushes"] >= 2
    assert rep["mesh_hits_gained"] > 0
    assert rep["shard_table_hits_gained"] > 0
    # ISSUE 11: the flight deck flew two concurrent flushes on
    # disjoint halves, landed them out of order, drained before a
    # full-mesh giant flush, and survived a mid-deck breaker trip
    deck = rep["deck"]
    assert deck["halves"] == [[0, 1], [2, 3]]
    assert deck["flight_dev0"] == [0, 2]  # disjoint sub-meshes
    assert deck["airborne_max"] == 1      # two flights at once
    assert deck["out_of_order_landing"] is True
    assert deck["rotation_window_ok"] is True  # staging-slot safety
    assert deck["drain_first_ok"] is True
    assert deck["mid_deck_fallbacks"] == 2
    # ISSUE 15: the device observatory caught a deliberately broken
    # mesh-step memo — steady-state recompiles recorded and attributed
    # to the flush that paid (comp_ms), the compile_storm incident
    # fired with the compile tail, and the sharded flushes measured a
    # real rows-x-cost utilization
    obs = rep["observatory"]
    assert obs["steady_recompiles"] >= 1
    assert obs["storm_fired"] >= 1
    assert obs["paid_flush_comp_ms"] > 0
    assert 0 < obs["sharded_util"] <= 1.0
    # ISSUE 19: each device stamped its own rows slice from per-row
    # deltas bit-identically to the single-device expansion
    assert rep["stamped_shards_ok"] is True
