"""wal_generator + loadtime tooling.

Reference: consensus/wal_generator.go:226, scripts/wal2json,
test/loadtime (load/main.go, report/report.go).
"""
import time

from cometbft_tpu.consensus.wal_generator import generate_wal, wal_to_json
from cometbft_tpu.tools import loadtime


def test_wal_generator_and_wal2json(tmp_path):
    dest = str(tmp_path / "gen.wal")
    assert generate_wal(3, dest) == dest
    recs = wal_to_json(dest)
    ends = [r for r in recs if r["kind"] == "end_height"]
    assert [r["height"] for r in ends][:2] == [1, 2]
    msgs = [r for r in recs if r["kind"] == "msg"]
    assert any(r["msg"].get("t") == "vote" for r in msgs)
    assert any(r["msg"].get("t") == "proposal" for r in msgs)


def test_payload_roundtrip():
    tx = loadtime.make_tx(7, size=100)
    assert len(tx) == 100
    seq, stamp = loadtime.parse_tx(tx)
    assert seq == 7
    assert abs(stamp - time.time_ns()) < 5 * 10**9
    assert loadtime.parse_tx(b"not a load tx") is None


def test_load_and_report(tmp_path):
    """Drive a live single-validator node with timestamped load and
    recompute per-tx latency from its block store."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.01)
    priv = PrivKey.generate(bytes([31]) * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("load-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=fast)
    node.start()
    try:
        assert node.consensus.wait_for_height(1, timeout=30)
        n = loadtime.run_load(node.broadcast_tx, rate=50,
                              duration_s=1.0, size=80)
        assert n >= 10
        assert node.consensus.wait_for_height(node.height() + 2,
                                              timeout=30)
        rep = loadtime.report_from_blockstore(node.block_store)
    finally:
        node.stop()
    assert rep is not None and rep.n_txs >= 1
    # block time is the BFT median with second granularity, so a tx can
    # land in a block "timestamped" earlier than its own stamp; bounds
    # are sanity, not sign
    assert rep.min_ms <= rep.p50_ms <= rep.max_ms
    assert rep.max_ms < 60_000


def test_wal_rotation_and_group_replay(tmp_path):
    """autofile.Group analog: the WAL rotates at height boundaries once
    the head exceeds its size limit; replay and ENDHEIGHT search span
    the whole group; old segments are pruned."""
    import os
    import struct

    from cometbft_tpu.consensus import wal as walmod

    path = str(tmp_path / "cs.wal")
    w = walmod.WAL(path, head_size_limit=2000, max_segments=3)
    for h in range(1, 30):
        for k in range(3):
            w.write_sync(walmod.MSG_INFO, b"h%02d-msg%d" % (h, k) * 20)
        w.write_end_height(h)
    w.close()
    segs = [f for f in os.listdir(tmp_path) if f.startswith("cs.wal.")]
    assert segs, "never rotated"
    assert len(segs) <= 3, f"pruning failed: {segs}"
    # replay spans segments: the most recent heights are intact
    recs = list(walmod.WAL.iter_records(path))
    ends = [struct.unpack(">q", r.data)[0] for r in recs
            if r.kind == walmod.END_HEIGHT]
    assert ends[-1] == 29 and len(ends) >= 5
    # ENDHEIGHT search across the group finds a recent height
    idx = walmod.WAL.search_for_end_height(path, ends[-2])
    assert idx is not None
    tail = list(walmod.WAL.iter_records(path))[idx:]
    assert any(r.kind == walmod.END_HEIGHT
               and struct.unpack(">q", r.data)[0] == 29 for r in tail)


def test_bench_history_renders_trajectory(tmp_path, capsys):
    """tools/bench_history: driver-shaped BENCH files (head-truncated
    tails included) line up per config across rounds; missing configs
    render as '—', never as a guessed value."""
    import json

    from tools import bench_history

    r1 = {"n": 1, "rc": 0, "tail": "\n".join([
        '{"metric": "cfg2 1000-validator commit batch verify", '
        '"value": 8.6, "unit": "ms", "vs_baseline": 10.0}',
        '{"metric": "10k-validator VerifyCommitLight fused p50", '
        '"value": 38.5, "unit": "ms", "vs_baseline": 33.0}',
    ])}
    # round 2's tail lost cfg2 to head truncation (first line cut mid-
    # object, exactly how the driver stores long stdouts)
    r2 = {"n": 2, "rc": 0, "tail": "\n".join([
        'alue": 15.2, "unit": "ms"}',
        '{"metric": "10k-validator VerifyCommitLight fused p50", '
        '"value": 29.0, "unit": "ms", "vs_baseline": 44.0}',
    ])}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(r1))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(r2))

    assert bench_history.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "headline" in out and "cfg2" in out and "—" in out
    # -24.7%: 38.5 -> 29.0
    assert "r01->r02: -24.7%" in out

    assert bench_history.main(["--dir", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rounds"] == ["r01", "r02"]
    cfg2 = {p["round"]: p["value"] for p in doc["series"]["cfg2"]}
    assert cfg2 == {"r01": 8.6, "r02": None}
    assert bench_history.main(
        ["--dir", str(tmp_path), "--glob", "NOPE*.json"]) == 2
