"""RPC server + EventBus: an external HTTP client drives a node, a light
client syncs over RPC, WebSocket subscriptions stream events.

Reference: rpc/core/routes.go route surface + rpc/jsonrpc server tests.
"""
import base64
import hashlib
import json
import socket
import struct
import time
import urllib.request

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.libs.pubsub import PubSub, Query
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.rpc.client import HTTPClient, light_provider
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def test_query_language():
    q = Query("tm.event='NewBlock' AND tx.height=5")
    assert q.matches({"tm.event": ["NewBlock"], "tx.height": ["5"]})
    assert not q.matches({"tm.event": ["NewBlock"]})
    assert not q.matches({"tm.event": ["Tx"], "tx.height": ["5"]})
    q2 = Query("tx.hash EXISTS")
    assert q2.matches({"tx.hash": ["AB"]})
    assert not q2.matches({})


def test_pubsub_drop_on_full():
    ps = PubSub()
    sub = ps.subscribe("s", "k='v'", capacity=2)
    for _ in range(5):
        ps.publish("x", {"k": ["v"]})
    got = 0
    while sub.next(timeout=0):
        got += 1
    assert got == 2  # dropped, not blocked


@pytest.fixture()
def rpc_node(tmp_path):
    priv = PrivKey.generate(b"\x09" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("rpc-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=FAST)
    node.start()
    url = node.rpc_listen()
    try:
        assert node.consensus.wait_for_height(2, timeout=60)
        yield node, url
    finally:
        node.stop()


def test_rpc_core_routes(rpc_node):
    node, url = rpc_node
    c = HTTPClient(url)

    st = c.status()
    assert st["sync_info"]["latest_block_height"] >= 2
    assert st["node_info"]["network"] == "rpc-chain"

    b = c.block(2)
    assert b["block"]["header"]["height"] == 2
    bh = c.call("block_by_hash", hash=b["block_id"]["hash"])
    assert bh["block"]["header"]["height"] == 2

    cm = c.commit(2)
    assert cm["signed_header"]["commit"]["height"] == 2

    v = c.validators(2)
    assert v["count"] == 1

    bc = c.call("blockchain")
    assert bc["last_height"] >= 2 and bc["block_metas"]

    assert c.call("health") == {}
    ni = c.call("net_info")
    assert ni["n_peers"] == 0

    ai = c.call("abci_info")
    assert "response" in ai

    # tx through the full pipeline
    res = c.broadcast_tx_commit(b"rpckey=rpcval")
    assert res["tx_result"]["code"] == 0 and res["height"] > 0
    q = c.abci_query(b"rpckey")
    assert base64.b64decode(q["response"]["value"]) == b"rpcval"

    # URI (GET) form
    with urllib.request.urlopen(f"{url}/status", timeout=5) as r:
        j = json.loads(r.read().decode())
    assert j["result"]["sync_info"]["latest_block_height"] >= 2

    # error path
    with pytest.raises(Exception):
        c.block(10_000)


def test_query_range_comparisons():
    q = Query("tx.height>2 AND tx.height<=5")
    assert q.matches({"tx.height": ["3"]})
    assert q.matches({"tx.height": ["5"]})
    assert not q.matches({"tx.height": ["2"]})
    assert not q.matches({"tx.height": ["6"]})
    assert not q.matches({"tx.height": ["zebra"]})
    with pytest.raises(Exception):
        Query("tx.height > banana")


def test_rpc_route_parity(rpc_node):
    """The round-3 route-gap list (VERDICT): block_results, header,
    header_by_hash, check_tx, consensus_params, consensus_state,
    dump_consensus_state, genesis_chunked, pagination."""
    node, url = rpc_node
    c = HTTPClient(url)

    res = c.broadcast_tx_commit(b"pk=pv")
    h = res["height"]

    hd = c.call("header", height=h)
    assert hd["header"]["height"] == h
    b = c.block(h)
    hbh = c.call("header_by_hash", hash=b["block_id"]["hash"])
    assert hbh["header"]["height"] == h

    br = c.call("block_results", height=h)
    assert br["height"] == h
    assert any(r["code"] == 0 for r in br["txs_results"])
    assert br["app_hash"]

    cp = c.call("consensus_params")
    assert "block" in cp["consensus_params"]

    cs = c.call("consensus_state")
    assert cs["round_state"]["height"] >= h
    dcs = c.call("dump_consensus_state")
    assert "peers" in dcs and dcs["round_state"]["height"] >= h

    gc = c.call("genesis_chunked")
    doc = json.loads(base64.b64decode(gc["data"]))
    assert doc["chain_id"] == "rpc-chain" and gc["total"] >= 1

    ct = c.call("check_tx", tx=base64.b64encode(b"x=y").decode())
    assert ct["code"] == 0
    # check_tx must NOT add to the mempool
    assert c.call("num_unconfirmed_txs")["n_txs"] == 0

    # validators pagination
    v = c.call("validators", height=h, page=1, per_page=1)
    assert v["count"] == 1 and v["total"] == 1
    with pytest.raises(Exception):
        c.call("validators", height=h, page=99)

    # tx_search pagination + order
    for i in range(3):
        c.broadcast_tx_commit(b"m%d=v" % i)
    ts = c.call("tx_search", query="tx.height EXISTS", per_page=2,
                page=1, order_by="desc")
    assert ts["total_count"] >= 4 and len(ts["txs"]) == 2
    hs = [t["height"] for t in ts["txs"]]
    assert hs == sorted(hs, reverse=True)
    # range query through the indexer
    ts2 = c.call("tx_search", query=f"tx.height>={h}")
    assert ts2["total_count"] >= 1
    ts3 = c.call("tx_search", query="tx.height<1")
    assert ts3["total_count"] == 0


def test_tx_prove_and_verified_abci_query(rpc_node):
    """tx(prove=true) returns a valid inclusion proof; abci_query with
    prove returns a kv proof chaining to the app hash."""
    from cometbft_tpu.crypto.proof_ops import (
        ProofError,
        ProofOp,
        default_runtime,
    )
    from cometbft_tpu.types.tx import TxProof

    node, url = rpc_node
    c = HTTPClient(url)
    res = c.broadcast_tx_commit(b"proofme=42")
    h, txhash = res["height"], res["hash"]

    t = c.call("tx", hash=txhash, prove=True)
    tp = TxProof.from_j(t["proof"])
    blk = node.block_store.load_block(h)
    assert tp.validate(blk.header.data_hash)
    assert tp.data == b"proofme=42"
    # tampered proof fails
    bad = TxProof.from_j(t["proof"])
    bad.data = b"proofme=43"
    assert not bad.validate(blk.header.data_hash)

    q = c.call("abci_query", data=b"proofme".hex(), prove=True)
    resp = q["response"]
    ops = [ProofOp.from_j(o) for o in resp["proof_ops"]["ops"]]
    # the proof chains to the app hash in the NEXT height's header
    assert node.consensus.wait_for_height(resp["height"] + 1, timeout=60)
    hdr = node.block_store.load_block(resp["height"] + 1).header
    rt = default_runtime()
    rt.verify_value(ops, hdr.app_hash, b"proofme", b"42")
    with pytest.raises(ProofError):
        rt.verify_value(ops, hdr.app_hash, b"proofme", b"43")


def test_light_client_syncs_over_rpc(rpc_node):
    node, url = rpc_node
    from cometbft_tpu.light import client as lc

    assert node.consensus.wait_for_height(4, timeout=60)
    provider = light_provider("rpc-chain", url)
    c = lc.Client("rpc-chain", provider, trusting_period=1e6)
    c.trust_light_block(provider.light_block(1))
    target = node.height()
    lb = c.verify_light_block_at_height(target)
    assert lb.signed_header.header.height == target
    # the verified header matches the node's own block hash
    assert lb.signed_header.header.hash() == \
        node.block_store.load_block(target).hash()


def _ws_handshake(host, port):
    s = socket.create_connection((host, port), timeout=10)
    key = base64.b64encode(b"0123456789abcdef").decode()
    s.sendall((
        f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
        "Upgrade: websocket\r\nConnection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
    ).encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    assert b"101" in buf.split(b"\r\n", 1)[0]
    return s


def _ws_send(s, text):
    data = text.encode()
    mask = b"\x01\x02\x03\x04"
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
    assert len(data) < 126
    s.sendall(bytes([0x81, 0x80 | len(data)]) + mask + masked)


def _ws_recv(s, timeout=20.0):
    s.settimeout(timeout)
    hdr = s.recv(2)
    ln = hdr[1] & 0x7F
    if ln == 126:
        ln = struct.unpack(">H", s.recv(2))[0]
    elif ln == 127:
        ln = struct.unpack(">Q", s.recv(8))[0]
    data = b""
    while len(data) < ln:
        data += s.recv(ln - len(data))
    return data.decode()


def test_websocket_subscription(rpc_node):
    node, url = rpc_node
    host, port = url[len("http://"):].split(":")
    s = _ws_handshake(host, int(port))
    try:
        _ws_send(s, json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "subscribe",
            "params": {"query": "tm.event='NewBlock'"},
        }))
        ack = json.loads(_ws_recv(s))
        assert ack["id"] == 1 and "result" in ack
        ev = json.loads(_ws_recv(s))
        assert ev["result"]["events"]["tm.event"] == ["NewBlock"]
        assert ev["result"]["data"]["block"]["header"]["height"] > 0
    finally:
        s.close()


def test_tx_indexer_and_search(rpc_node):
    node, url = rpc_node
    c = HTTPClient(url)
    res = c.broadcast_tx_commit(b"idx1=a")
    res2 = c.broadcast_tx_commit(b"idx2=b")
    time.sleep(0.3)  # indexer service drains the event bus
    got = c.call("tx", hash=res["hash"])
    assert got["height"] == res["height"]
    assert base64.b64decode(got["tx"]) == b"idx1=a"
    s = c.call("tx_search", query=f"tx.height={res['height']}")
    assert any(t["hash"] == res["hash"] for t in s["txs"])
    bs = c.call("block_search", query=f"block.height={res2['height']}")
    assert bs["total_count"] >= 1
    assert bs["blocks"][0]["block"]["header"]["height"] >= 1


def test_pruner_retention(rpc_node):
    node, url = rpc_node
    assert node.consensus.wait_for_height(4, timeout=60)
    node.pruner.set_retain_height(3)
    removed = node.pruner.prune_once()
    assert removed >= 1
    assert node.block_store.base() >= 3
    assert node.block_store.load_block(1) is None
    # validator history is NOT pruned: it stays loadable through the
    # evidence max-age window (evidence at old heights must still verify)
    assert node.state_store.load_validators(2) is not None
    # with a tight evidence window the cap follows it
    node.pruner.evidence_safe_height = lambda: 3
    node.pruner.prune_once()
    assert node.state_store.load_validators(2) is None
    assert node.state_store.load_validators(3) is not None
    # the chain keeps running after pruning
    h = node.height()
    assert node.consensus.wait_for_height(h + 2, timeout=60)


def test_unsafe_routes_gated_and_working(tmp_path):
    """dial_seeds/dial_peers/unsafe_flush_mempool + /debug/pprof only
    exist behind the unsafe flag (rpc/core/routes.go:58-63,
    rpc/core/dev.go); with it, they act on the node."""
    import json as _json
    import urllib.request

    priv = PrivKey.generate(b"\x0a" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("unsafe-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=FAST, p2p=True)
    node.listen()
    node.start()
    safe_url = node.rpc_listen()
    from cometbft_tpu.rpc.server import RPCServer

    unsafe_srv = RPCServer(node, unsafe=True)
    unsafe_srv.start()
    url = unsafe_srv.address
    try:
        assert node.consensus.wait_for_height(2, timeout=60)
        c_safe = HTTPClient(safe_url)
        c = HTTPClient(url)

        # gated on the safe server
        with pytest.raises(Exception) as ei:
            c_safe.call("unsafe_flush_mempool")
        assert "unsafe" in str(ei.value)

        # flush: park a tx in the mempool (consensus may race one
        # commit, so assert emptiness only after the flush)
        node.mempool.check_tx(b"zz=1")
        assert c.call("unsafe_flush_mempool") == {}
        assert c.call("num_unconfirmed_txs")["total"] == 0

        # dial_seeds/dial_peers accept id@host:port lists; a dead
        # target is fine — dialing is async and just fails later
        r = c.call("dial_seeds",
                   seeds=["ff" * 20 + "@127.0.0.1:1"])
        assert "dialing" in r["log"]
        r = c.call("dial_peers",
                   peers=["ee" * 20 + "@127.0.0.1:1"],
                   persistent=True)
        assert "dialing" in r["log"]

        # pprof-analog endpoints
        with urllib.request.urlopen(url + "/debug/pprof/goroutine",
                                    timeout=10) as resp:
            stacks = resp.read().decode()
        assert "thread" in stacks and "rpc-http" in stacks
        with urllib.request.urlopen(
                url + "/debug/pprof/profile?seconds=0.2",
                timeout=10) as resp:
            assert "statistical profile" in resp.read().decode()
        # gated on the safe server (403)
        try:
            urllib.request.urlopen(safe_url + "/debug/pprof/goroutine",
                                   timeout=10)
            assert False, "expected 403"
        except urllib.error.HTTPError as e:
            assert e.code == 403
    finally:
        unsafe_srv.stop()
        node.stop()
