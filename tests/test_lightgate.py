"""Light-client gateway: coalescer under contention, LRU + expiry
interplay, divergent-claim evidence, GATEWAY-lane QoS (ISSUE 8).

The contention tests drive K real threads through ONE gateway over a
deterministic in-process chain, with a host-path verify plane mounted
as the global plane — so "exactly one plane submission" is asserted
from the plane's always-on flush ledger, not inferred from counters.
"""
import threading

import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.light import client as lc
from cometbft_tpu.light import verifier as lv
from cometbft_tpu.lightgate import (
    GatewayError,
    GatewayOverloaded,
    LightGateway,
    VerifiedLRU,
)
from cometbft_tpu.lightgate.cache import CacheEntry
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import Header
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane
from cometbft_tpu.verifyplane.plane import FlushLedger

CHAIN_ID = "lightgate-chain"
T0 = 1_700_000_000
NOW = Timestamp(T0 + 1000, 0)


def _keys(tag, n):
    return [PrivKey.generate(bytes([tag, i + 1]) + b"\x0b" * 30)
            for i in range(n)]


class Chain:
    """Deterministic stable-valset light-block chain (the test_light
    LightChain shape, trimmed)."""

    def __init__(self, n_heights, keys):
        self.keys = keys
        vs = ValidatorSet([Validator(p.pub_key(), 10) for p in keys])
        self.valset = vs
        by_addr = {p.pub_key().address(): p for p in keys}
        self.blocks = {}
        prev_bid = BlockID()
        for h in range(1, n_heights + 1):
            header = Header(
                chain_id=CHAIN_ID, height=h, time=Timestamp(T0 + h, 0),
                last_block_id=prev_bid, validators_hash=vs.hash(),
                next_validators_hash=vs.hash(),
                proposer_address=vs.validators[0].address,
                app_hash=b"\x01" * 32,
            )
            bid = BlockID(header.hash(), PartSetHeader(1, header.hash()))
            sigs = []
            for v in vs.validators:
                ts = Timestamp(T0 + h, 42)
                sb = canonical.canonical_vote_bytes(
                    CHAIN_ID, canonical.PRECOMMIT_TYPE, h, 0, bid, ts
                )
                sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address,
                                      ts, by_addr[v.address].sign(sb)))
            self.blocks[h] = lv.LightBlock(
                lv.SignedHeader(header, Commit(h, 0, bid, sigs)), vs
            )
            prev_bid = bid

    def provider(self):
        return lc.Provider(CHAIN_ID, lambda h: self.blocks.get(h))


@pytest.fixture()
def host_plane():
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.ledger = FlushLedger(capacity=2048)
    plane.start()
    set_global_plane(plane)
    try:
        yield plane
    finally:
        set_global_plane(None)
        plane.stop()


def _gateway(chain, **kw):
    gw = LightGateway(CHAIN_ID, chain.provider(), **kw)
    gw.client.trust_light_block(chain.blocks[1])
    gw.start(register=False)
    return gw


def _ledger_subs(plane):
    return sum(r["subs"] for r in plane.dump_flushes()["flushes"])


def test_coalescer_one_submission_for_k_threads(host_plane):
    """K threads asking for the same (trusted, target) pair must cost
    exactly ONE verification — asserted from flush-ledger rows: the
    plane sees the same submission count a single solo sync produces,
    and every row rides the GATEWAY lane."""
    chain = Chain(16, _keys(1, 4))

    # solo baseline: one gateway, one request, on a fresh ledger
    gw_solo = _gateway(chain)
    gw_solo.verify(1, 16, now=NOW)
    solo_subs = _ledger_subs(host_plane)
    assert solo_subs > 0

    host_plane.ledger = FlushLedger(capacity=2048)  # reset the count
    gw = _gateway(chain)
    K = 8
    barrier = threading.Barrier(K)
    verdicts, errs = [], []
    lock = threading.Lock()

    def worker():
        try:
            barrier.wait()
            v = gw.verify(1, 16, now=NOW)
            with lock:
                verdicts.append(v)
        except Exception as e:  # noqa: BLE001 - asserted below
            with lock:
                errs.append(repr(e))

    threads = [threading.Thread(target=worker) for _ in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert len(verdicts) == K
    hashes = {v["target_hash"] for v in verdicts}
    assert len(hashes) == 1, "fan-out delivered divergent results"
    st = gw.stats()
    assert st["verifies"] == 1, st
    assert st["coalesced"] + st["cache"]["hits"] == K - 1, st
    # the ledger agrees: K threads cost what ONE sync costs
    recs = host_plane.dump_flushes()["flushes"]
    assert sum(r["subs"] for r in recs) == solo_subs, recs
    assert sum(r["g_rows"] for r in recs) > 0
    assert sum(r["c_rows"] for r in recs) == 0
    assert sum(r["b_rows"] for r in recs) == 0


def _forged_claim(chain, height):
    """A lying-primary view of `height`: different app_hash, commit
    sealed by the full (>= 1/3) coalition."""
    from cometbft_tpu.types import serde
    from cometbft_tpu.types.vote import Vote

    header = Header(
        chain_id=CHAIN_ID, height=height, time=Timestamp(T0 + height, 0),
        last_block_id=BlockID(), validators_hash=chain.valset.hash(),
        next_validators_hash=chain.valset.hash(),
        proposer_address=chain.valset.validators[0].address,
        app_hash=b"\x66" * 32,
    )
    hh = header.hash()
    bid = BlockID(hh, PartSetHeader(1, hh))
    sigs = [CommitSig.absent() for _ in range(len(chain.valset))]
    for priv in chain.keys:
        addr = priv.pub_key().address()
        vidx, _ = chain.valset.get_by_address(addr)
        v = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=height,
                 round=0, block_id=bid,
                 timestamp=Timestamp(T0 + height, 0),
                 validator_address=addr, validator_index=vidx)
        sigs[vidx] = CommitSig(BLOCK_ID_FLAG_COMMIT, addr,
                               Timestamp(T0 + height, 0),
                               priv.sign(v.sign_bytes(CHAIN_ID)))
    return {"header": serde.header_to_j(header),
            "commit": serde.commit_to_j(Commit(height, 0, bid, sigs))}


def test_mixed_valid_forged_fanout(host_plane):
    """K concurrent clients on one (trusted, target) pair, half fed a
    forged header by a lying primary: per-client verdicts — honest
    clients get "verified", deceived clients get "divergent" — and one
    (deduped) LightClientAttackEvidence lands in the pool."""
    from cometbft_tpu.evidence.pool import EvidencePool
    from cometbft_tpu.types.evidence import LightClientAttackEvidence

    chain = Chain(8, _keys(2, 4))
    pool = EvidencePool(CHAIN_ID, lambda h: chain.valset)
    pool.height = 8
    pool.time_s = T0 + 8
    gw = _gateway(chain, evidence_pool=pool)
    claim = _forged_claim(chain, 8)

    K = 8
    forged = {1, 3, 5, 7}
    barrier = threading.Barrier(K)
    results = {}
    lock = threading.Lock()

    def worker(k):
        barrier.wait()
        v = gw.verify(1, 8, claimed=claim if k in forged else None,
                      now=NOW)
        with lock:
            results[k] = v

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == K
    for k, v in results.items():
        if k in forged:
            assert v["status"] == "divergent", (k, v)
            assert v["evidence_hash"]
        else:
            assert v["status"] == "verified", (k, v)
    # one attack, deduped at the pool (the proof is malleable; it must
    # not re-enter under each client's resubmission)
    assert pool.size() == 1
    ev = pool.pending_evidence()[0]
    assert isinstance(ev, LightClientAttackEvidence)
    assert len(ev.byzantine_validators) == 4
    # still one coalesced verification for the whole storm
    assert gw.stats()["verifies"] == 1


def test_lru_eviction_refetches(host_plane):
    """Evicted pairs verify again (capacity bound is real), and repeat
    syncs over a cached pair cost zero client verifications."""
    chain = Chain(12, _keys(3, 3))
    gw = _gateway(chain, cache_size=2)
    gw.verify(1, 10, now=NOW)
    v2 = gw.verify(1, 10, now=NOW)
    assert v2["cached"] is True
    before = gw.client.verifications
    gw.verify(1, 10, now=NOW)
    assert gw.client.verifications == before  # pure cache hit
    # two more pairs evict (1, 10) from the 2-entry LRU
    gw.verify(1, 11, now=NOW)
    gw.verify(1, 12, now=NOW)
    assert gw.cache.stats()["evictions"] >= 1
    v = gw.verify(1, 10, now=NOW)
    # not served from the LRU anymore — but the shared trusted store
    # still has height 10, so the re-verify is a store hit (0 steps),
    # which is exactly the two-layer sharing the gateway promises
    assert v["cached"] is False
    assert v["verify_steps"] == 0


def test_expired_trust_never_served(host_plane):
    """The LRU + prune_expired interplay: a cached pair whose target
    aged past the trusting period is NOT served — the request fails
    loudly (expired trust) instead of returning stale verification."""
    chain = Chain(6, _keys(4, 3))
    gw = _gateway(chain, trusting_period=50.0)  # headers at T0+h
    fresh_now = Timestamp(T0 + 10, 0)
    v = gw.verify(1, 6, now=fresh_now)
    assert v["status"] == "verified"
    assert gw.cache.stats()["size"] == 1
    # a second sync inside the window is a pure cache hit
    assert gw.verify(1, 6, now=fresh_now)["cached"] is True

    late_now = Timestamp(T0 + 1000, 0)  # everything expired
    with pytest.raises((GatewayError, lv.LightClientError)):
        gw.verify(1, 6, now=late_now)
    st = gw.cache.stats()
    assert st["expired"] >= 1, st  # the hit was refused, not served
    # prune drops both layers together
    out = gw.prune_expired(now=late_now)
    assert out["store_dropped"] >= 1
    assert gw.cache.stats()["size"] == 0
    assert len(gw.client.store.heights()) == 0


def test_verified_lru_unit():
    """The LRU itself: hit/miss/eviction/expiry accounting."""
    lru = VerifiedLRU(capacity=2)

    def ent(h, exp):
        return CacheEntry(target_height=h, target_hash=b"%d" % h,
                          expires_ns=exp, verify_steps=1)

    lru.put((b"a", b"b"), ent(2, 100))
    lru.put((b"a", b"c"), ent(3, 100))
    assert lru.get((b"a", b"b"), now_ns=50).target_height == 2
    lru.put((b"a", b"d"), ent(4, 100))  # evicts (a, c): (a, b) is MRU
    assert lru.get((b"a", b"c"), now_ns=50) is None
    assert lru.get((b"a", b"b"), now_ns=50) is not None
    # expiry: at/after expires_ns the entry is dropped and counted
    assert lru.get((b"a", b"b"), now_ns=100) is None
    st = lru.stats()
    assert st["evictions"] == 1 and st["expired"] == 1
    assert st["hits"] == 2 and st["misses"] == 2
    assert lru.prune_expired(now_ns=1000) == 1  # (a, d) goes too
    assert len(lru) == 0


def test_overload_shed_fans_out_with_hint():
    """A GATEWAY-lane shed must surface to EVERY coalesced waiter as an
    explicit retry-hinted GatewayOverloaded — never a silent drop or a
    hang."""
    from cometbft_tpu.verifyplane import PlaneOverloaded

    class ShedPlane:
        """Duck-typed global plane whose gateway lane always sheds."""

        def is_running(self):
            return True

        def in_dispatcher(self):
            return False

        def submit_and_wait(self, pubs, msgs, sigs, timeout=None,
                            lane="consensus", chain_id=None):
            raise PlaneOverloaded("gateway lane full",
                                  retry_after_ms=123.0)

    chain = Chain(8, _keys(5, 3))
    # install the stub directly (NOT via set_global_plane): the stub
    # has no ledger, and set_global_plane would leave it as the
    # process-global _LAST that ledger readers dereference later
    from cometbft_tpu.verifyplane import plane as plane_mod

    saved = (plane_mod._GLOBAL, plane_mod._LAST)
    plane_mod._GLOBAL = ShedPlane()
    try:
        gw = _gateway(chain)
        K = 4
        barrier = threading.Barrier(K)
        outcomes = []
        lock = threading.Lock()

        def worker():
            barrier.wait()
            try:
                gw.verify(1, 8, now=NOW)
                with lock:
                    outcomes.append(("ok", None))
            except GatewayOverloaded as e:
                with lock:
                    outcomes.append(("overloaded", e.retry_after_ms))

        threads = [threading.Thread(target=worker) for _ in range(K)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(outcomes) == K
        assert all(kind == "overloaded" for kind, _ in outcomes)
        assert all(hint == 123.0 for _, hint in outcomes)
        assert gw.stats()["overloaded"] >= 1
    finally:
        plane_mod._GLOBAL, plane_mod._LAST = saved


def test_gateway_lane_queue_bound_sheds_nonblocking():
    """The plane-level lane contract: a non-blocking GATEWAY
    submission over the lane bound is answered with PlaneOverloaded
    (+ retry hint), not PlaneQueueFull, and the shed is counted per
    lane."""
    from cometbft_tpu.verifyplane import LANE_GATEWAY, PlaneOverloaded

    keys = _keys(6, 2)
    rows = [(k.pub_key(), b"m%d" % i, k.sign(b"m%d" % i))
            for i, k in enumerate(keys)]
    plane = VerifyPlane(window_ms=60.0, use_device=False,
                        gateway_max_queue=1, gateway_deadline_ms=0.0)
    plane.start()
    try:
        futs = [plane.submit_many([rows[0]], lane=LANE_GATEWAY)]
        with pytest.raises(PlaneOverloaded) as ei:
            for _ in range(64):
                futs.append(plane.submit_many(
                    [rows[1]], lane=LANE_GATEWAY, block=False))
        assert ei.value.retry_after_ms > 0
        assert plane.sheds[LANE_GATEWAY] >= 1
        assert plane.sheds["consensus"] == 0
    finally:
        plane.stop()
    # the queued submissions still resolved (stop-drain, real verdicts)
    assert all(f.result(5) == (True,) for f in futs)


def test_trust_root_pin_mismatch():
    """A client pinning a trusted hash from a different chain is an
    error — the gateway must not silently verify from OUR root as if
    the client's trust matched."""
    chain = Chain(6, _keys(7, 3))
    gw = _gateway(chain)
    with pytest.raises(GatewayError, match="trust root mismatch"):
        gw.verify(1, 6, trusted_hash=b"\x13" * 32, now=NOW)
    # and a correct pin passes
    pin = chain.blocks[1].signed_header.header.hash()
    assert gw.verify(1, 6, trusted_hash=pin,
                     now=NOW)["status"] == "verified"


def test_batched_headers_serving():
    chain = Chain(10, _keys(8, 3))
    gw = _gateway(chain, max_batch_headers=4)
    out = gw.headers([2, 4, 6, 99])
    assert [h["height"] for h in out["headers"]] == [2, 4, 6]
    assert out["missing"] == [99]
    assert not out["truncated"]
    out2 = gw.headers(list(range(1, 11)), with_validators=True)
    assert len(out2["headers"]) == 4  # capped at max_batch_headers
    assert out2["truncated"]
    assert len(out2["headers"][0]["validators"]) == 3
