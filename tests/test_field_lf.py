"""Differential tests: limbs-first (Pallas-dialect) field vs Python big ints.

Mirrors tests/test_field.py but in the (NLIMBS, B) transposed layout used
inside Pallas kernels (ops.field_lf).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cometbft_tpu.ops.field import F25519, FSECP, NLIMBS, limbs_to_int
from cometbft_tpu.ops.field_lf import FieldLF, const_col

RNG = np.random.default_rng(11)
LF25519 = FieldLF(F25519)
LFSECP = FieldLF(FSECP)
FIELDS = [LF25519, LFSECP]


def rand_elems(lf, n):
    vals = [int.from_bytes(RNG.bytes(40), "little") % lf.p for _ in range(n)]
    limbs = np.stack([lf.f.from_int(v) for v in vals], axis=1)  # (NLIMBS, n)
    return vals, jnp.asarray(limbs)


def check(lf, got_cols, expect_ints):
    got = limbs_to_int(np.asarray(got_cols).T)
    got = np.asarray([g % lf.p for g in got])
    exp = np.asarray([e % lf.p for e in expect_ints])
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("lf", FIELDS, ids=["ed25519", "secp256k1"])
def test_add_sub_mul(lf):
    a_int, a = rand_elems(lf, 32)
    b_int, b = rand_elems(lf, 32)
    check(lf, lf.add(a, b), [x + y for x, y in zip(a_int, b_int)])
    check(lf, lf.sub(a, b), [x - y for x, y in zip(a_int, b_int)])
    check(lf, lf.mul(a, b), [x * y for x, y in zip(a_int, b_int)])
    check(lf, lf.square(a), [x * x for x in a_int])
    check(lf, lf.neg(a), [-x for x in a_int])
    check(lf, lf.mul_small(a, 121666), [x * 121666 for x in a_int])


@pytest.mark.parametrize("lf", FIELDS, ids=["ed25519", "secp256k1"])
def test_deep_chain_no_canonical(lf):
    """Stress the lazy-limb invariant transposed: 50-op chains."""
    a_int, a = rand_elems(lf, 8)
    b_int, b = rand_elems(lf, 8)
    x, xi = a, list(a_int)
    for i in range(50):
        if i % 3 == 0:
            x, xi = lf.mul(x, b), [u * v for u, v in zip(xi, b_int)]
        elif i % 3 == 1:
            x, xi = lf.sub(lf.add(x, x), b), [2 * u - v for u, v in zip(xi, b_int)]
        else:
            x, xi = lf.square(x), [u * u for u in xi]
        xi = [u % lf.p for u in xi]
    check(lf, x, xi)
    # fast mode admits the wider B1 invariant (field_lf.FieldLF.__init__)
    assert int(np.abs(np.asarray(x)).max()) <= lf.bound1


@pytest.mark.parametrize("lf", FIELDS, ids=["ed25519", "secp256k1"])
def test_canonical_eq_parity(lf):
    a_int, a = rand_elems(lf, 8)
    canon = np.asarray(lf.canonical(lf.mul(a, a)))
    assert (canon >= 0).all() and (canon < 2**13).all()
    got = limbs_to_int(canon.T)
    np.testing.assert_array_equal(
        np.asarray([int(g) for g in got]),
        np.asarray([v * v % lf.p for v in a_int]),
    )
    par = np.asarray(lf.parity(a))
    assert par.shape == (1, 8)
    np.testing.assert_array_equal(par[0], np.asarray([v & 1 for v in a_int]))
    assert bool(np.all(np.asarray(lf.eq(a, a))))
    z = lf.sub(a, a)
    assert bool(np.all(np.asarray(lf.is_zero(z))))


def test_pow_p58():
    lf = LF25519
    a_int, a = rand_elems(lf, 8)
    got = limbs_to_int(np.asarray(lf.canonical(lf.pow_p58(a))).T)
    exp = [pow(v, (lf.p - 5) // 8, lf.p) for v in a_int]
    np.testing.assert_array_equal(
        np.asarray([int(g) for g in got]), np.asarray(exp)
    )


def test_const_col_matches_from_int():
    for lf in FIELDS:
        for v in [0, 1, 19, lf.p - 1, 2**200 + 12345]:
            t = lf.const_limbs(v)
            col = np.asarray(const_col(t, 4))
            expect = np.asarray(lf.f.from_int(v % lf.p))
            for lane in range(4):
                np.testing.assert_array_equal(col[:, lane], expect)


def test_edge_values_zero_detect():
    lf = LF25519
    vals = [0, 1, lf.p - 1, (lf.p - 1) // 2, 2**255 - 20]
    vals = [v % lf.p for v in vals]
    limbs = jnp.asarray(np.stack([lf.f.from_int(v) for v in vals], axis=1))
    one = const_col((1,) + (0,) * (NLIMBS - 1), len(vals))
    zp = np.asarray(lf.is_zero(lf.add(limbs, one)))[0]
    np.testing.assert_array_equal(
        zp, np.asarray([v == lf.p - 1 for v in vals])
    )
