"""Byzantine simnet: deterministic adversarial scenarios over real
node/consensus stacks (cometbft_tpu/simnet/).

Tier-1 scenarios are budgeted small (<= a few simulated heights, no
kernel compiles — everything is host-path crypto); the long randomized
schedules live in tools/simnet_fuzz.py. File named test_simnet.py so it
lands late in the alphabetical tier-1 order (ROADMAP timeout note).

Every scenario asserts safety (no conflicting commits) and, where the
schedule permits a quorum, liveness after heal. A failing assertion
raises SimnetFailure carrying the exact seed + schedule replay blob.
"""
import json

import pytest

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.simnet import (
    Simnet,
    SimnetFailure,
    schedule_to_json,
    validate_schedule,
)
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
)

pytestmark = pytest.mark.simnet


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


FAULTY_SCHEDULE = [
    {"at": 0.05, "op": "link", "drop": 0.08, "delay": 0.02,
     "jitter": 0.01, "dup": 0.05, "reorder": 0.05},
    {"at": 0.2, "op": "partition", "groups": [[0, 1, 2], [3]]},
    {"at": 0.3, "op": "tx", "node": 0, "data": b"sim=net".hex()},
    {"at": 1.0, "op": "heal"},
]


def test_quick_consensus_no_faults(tmp_path):
    """Baseline: 4 simulated validators reach height 3 and agree."""
    with Simnet(4, seed=1, basedir=str(tmp_path)) as sim:
        assert sim.run([], until_height=3, max_time=60.0)
        assert all(n.height() >= 3 for n in sim.net.nodes)
        sim.assert_safety()
        # all four committed the same block 2
        hashes = sim.commit_hashes()
        assert len({h[2] for h in hashes}) == 1


def test_determinism_same_seed_same_chain(tmp_path):
    """ISSUE 3 acceptance: the same (seed, schedule) twice yields
    identical commit hashes at every height on every node — drops,
    duplication, reordering, and a partition included."""

    def run_once(tag):
        with Simnet(4, seed=77, basedir=str(tmp_path / tag)) as sim:
            assert sim.run(FAULTY_SCHEDULE, until_height=4,
                           max_time=120.0)
            sim.assert_safety()
            return sim.commit_hashes()

    assert run_once("a") == run_once("b")


def test_template_packing_determinism_vs_legacy(tmp_path):
    """ISSUE 4 satellite (zero-copy hot path): the same (seed,
    schedule) with the template-packing path FORCED ON yields commit
    hashes byte-identical to the legacy per-vote packing path at every
    height on every node — a patching bug that rejected (or mis-built)
    any sign-bytes would wedge a round or fork the runs. Also checks a
    REAL committed commit's template rows against its per-vote
    sign-bytes, byte for byte."""
    from cometbft_tpu.types import validation as tv

    sched = [
        {"at": 0.05, "op": "link", "drop": 0.05, "delay": 0.01,
         "jitter": 0.005},
        {"at": 0.3, "op": "tx", "node": 1, "data": b"zero=copy".hex()},
    ]

    def run_once(tag, on):
        prev = tv.set_template_packing(on)
        try:
            assert tv.template_packing_enabled() == on
            with Simnet(4, seed=44, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(sched, until_height=2, max_time=120.0)
                sim.assert_safety()
                hashes = sim.commit_hashes()
                # byte-level guard on a commit the network produced
                store = sim.net.nodes[0].node.block_store
                commit = store.load_seen_commit(1)
                chain = sim.net.chain_id
                idxs = list(range(len(commit.signatures)))
                assert commit.sign_bytes_rows(chain, idxs) == [
                    commit.vote_sign_bytes(chain, i) for i in idxs
                ]
                return hashes
        finally:
            tv.set_template_packing(prev)

    assert run_once("tmpl", True) == run_once("legacy", False)


def test_device_stamping_toggle_determinism(tmp_path):
    """ISSUE 19 satellite: the same (seed, schedule) with device
    stamping enabled vs disabled yields commit hashes byte-identical
    at every height on every node, with a RUNNING verify plane
    mounted. The delta arm exercises the whole new seam — vote_set
    attaches per-row (template, secs, nanos) stamp metadata to every
    plane submission and requests a template prefetch — and on a
    host-path plane the flush must degrade to the host pack honestly
    (every ledger record's stamp column says "host"): metadata that
    perturbed packing, verdicts, or scheduling would fork the runs or
    wedge a round."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane
    from cometbft_tpu.verifyplane import fused as fz

    sched = [
        {"at": 0.05, "op": "link", "drop": 0.04, "delay": 0.01,
         "jitter": 0.005},
        {"at": 0.3, "op": "tx", "node": 1, "data": b"de=lta".hex()},
    ]

    def run_once(tag, on):
        prev = fz.DEVICE_STAMP
        fz.set_device_stamping(on)
        plane = VerifyPlane(window_ms=0.5, use_device=False)
        plane.start()
        set_global_plane(plane)
        try:
            with Simnet(4, seed=91, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(sched, until_height=2, max_time=120.0)
                sim.assert_safety()
                hashes = sim.commit_hashes()
        finally:
            set_global_plane(None)
            plane.stop()
            fz.set_device_stamping(prev)
        assert plane.rows_verified > 0  # votes really rode the plane
        recs = plane.dump_flushes()["flushes"]
        assert recs and all(r["stamp"] == "host" for r in recs), recs
        return hashes

    assert run_once("stamp", True) == run_once("legacy", False)


def test_partition_minority_stalls_then_catches_up(tmp_path):
    """A partitioned validator cannot commit (safety) while the 3/4
    majority keeps going; after heal the catch-up pushes restore it."""
    with Simnet(4, seed=5, basedir=str(tmp_path)) as sim:
        sim.run([], until_height=2, max_time=60.0)
        cut = sim.net.now
        sim.run([{"at": cut, "op": "partition",
                  "groups": [[0, 1, 2], [3]]}], max_time=0.1)
        victim = sim.net.nodes[3]
        h_cut = victim.height()
        majority_target = max(n.height() for n in sim.net.nodes) + 2
        assert sim.run(
            [],
            until=lambda: all(sim.net.nodes[i].height()
                              >= majority_target for i in (0, 1, 2)),
            max_time=60.0,
        )
        assert victim.height() <= h_cut + 1  # at most one in-flight commit
        sim.run([{"at": sim.net.now, "op": "heal"}], max_time=0.1)
        assert sim.run(
            [], until=lambda: victim.height() >= majority_target,
            max_time=60.0,
        ), f"victim stuck at {victim.height()}"
        sim.assert_safety()


def test_equivocator_lands_in_committed_evidence(tmp_path):
    """ISSUE 3 acceptance: a double-signing validator's conflicting
    prevotes surface as DuplicateVoteEvidence (height_vote_set conflict
    detection), flow through the pool, and end committed in a block on
    every node — chain stays safe and live throughout."""
    with Simnet(4, seed=11, basedir=str(tmp_path)) as sim:
        sim.run([{"at": 0.12, "op": "equivocate", "node": 3, "votes": 2}],
                until_height=2, max_time=60.0)
        ev = sim.assert_evidence_committed(
            predicate=lambda e: isinstance(e, DuplicateVoteEvidence)
        )
        assert ev.vote_a.validator_address == \
            sim.net.privs[3].pub_key().address()
        sim.assert_safety()
        sim.assert_liveness(min_new_heights=2, max_time=30.0)


def test_garbage_signer_does_not_poison_verify_plane(tmp_path):
    """ISSUE 3 acceptance: forged signatures coalesce through a RUNNING
    verify plane with honest votes; verdicts reject them, consensus
    proceeds, and the circuit breaker stays closed (no permanent host
    fallback) — an invalid signature is a verdict, not a device
    fault."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        with Simnet(4, seed=22, basedir=str(tmp_path)) as sim:
            assert sim.run(
                [{"at": 0.1, "op": "garbage", "node": 1, "votes": 4}],
                until_height=4, max_time=60.0,
            )
            sim.assert_safety()
        stats = plane.stats()
        assert stats["breaker_state"] == "closed", stats
        assert plane.rows_verified > 0  # votes really rode the plane
    finally:
        set_global_plane(None)
        plane.stop()


def test_flush_ledger_deterministic_under_simnet(tmp_path):
    """ISSUE 6 acceptance: the always-on flush ledger rides the virtual
    clock — the same (seed, schedule) with a verify plane running
    produces IDENTICAL ledger records (sequence, composition, paths,
    and every stage timing), because submissions are serialized by the
    single-threaded event loop and every stamp comes from
    tracing.monotonic_ns() (= Timestamp.now() under simnet). Also
    proves the ledger is on by default (no knob was touched) and
    survives plane.stop()."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    def run_once(tag):
        plane = VerifyPlane(window_ms=0.5, use_device=False)
        plane.start()
        set_global_plane(plane)
        try:
            with Simnet(3, seed=33, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(
                    [{"at": 0.1, "op": "link", "drop": 0.03,
                      "delay": 0.01}],
                    until_height=2, max_time=60.0,
                )
                sim.assert_safety()
        finally:
            set_global_plane(None)
            plane.stop()
        recs = plane.dump_flushes()["flushes"]
        assert recs, "plane saw no flushes — ledger not always-on?"
        return recs

    a = run_once("a")
    b = run_once("b")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # and the stamps really rode the virtual clock: inside the sim epoch
    from cometbft_tpu.simnet.core import SIM_EPOCH_SECONDS

    assert all(r["ts_ms"] >= SIM_EPOCH_SECONDS * 1e3 for r in a)


def test_flush_ledger_deterministic_with_deck_enabled(tmp_path):
    """ISSUE 11: the pipelined flight deck must not perturb simnet
    determinism — the same (seed, schedule) with pipeline_flights=2
    produces byte-identical ledgers INCLUDING the airborne counts.
    Host-path flushes are synchronous (the deck only ever holds device
    flights), so airborne must stay 0 here: a nonzero count would mean
    the deck's real-clock landing poll leaked onto the simnet path."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    def run_once(tag):
        plane = VerifyPlane(window_ms=0.5, use_device=False,
                            pipeline_flights=2)
        plane.start()
        set_global_plane(plane)
        try:
            with Simnet(3, seed=47, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(
                    [{"at": 0.1, "op": "link", "drop": 0.02,
                      "delay": 0.01}],
                    until_height=2, max_time=60.0,
                )
                sim.assert_safety()
        finally:
            set_global_plane(None)
            plane.stop()
        recs = plane.dump_flushes()["flushes"]
        assert recs, "plane saw no flushes"
        return recs

    a = run_once("a")
    b = run_once("b")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert all(r["airborne"] == 0 and r["n_host"] == 1
               and r["dev0"] == 0 for r in a)


def test_height_ledger_deterministic_under_simnet(tmp_path):
    """ISSUE 13 acceptance: the always-on height ledger rides the
    virtual clock — the same (seed, schedule) produces byte-identical
    per-height records on every node (stage timeline, rounds, late
    offsets, absent bitmaps — everything), with a verify plane RUNNING
    so the flush-seq join is exercised too. Also proves the ledger is
    on by default and that the plane join attributes real flushes."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    def run_once(tag):
        plane = VerifyPlane(window_ms=0.5, use_device=False)
        plane.start()
        set_global_plane(plane)
        try:
            with Simnet(3, seed=61, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(
                    [{"at": 0.1, "op": "link", "drop": 0.03,
                      "delay": 0.01}],
                    until_height=3, max_time=60.0,
                )
                sim.assert_safety()
                recs = [n.node.consensus.height_ledger.records()
                        for n in sim.net.nodes]
        finally:
            set_global_plane(None)
            plane.stop()
        for node_recs in recs:
            assert node_recs, "height ledger recorded nothing"
        return recs

    a = run_once("a")
    b = run_once("b")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # the stamps really rode the virtual clock, and the plane join
    # attributed at least one flush somewhere on the run
    from cometbft_tpu.simnet.core import SIM_EPOCH_SECONDS

    flat = [r for node_recs in a for r in node_recs]
    assert all(r["ts_ms"] >= SIM_EPOCH_SECONDS * 1e3 for r in flat)
    assert any(r["plane_flushes"] > 0 for r in flat), \
        "no height ever joined a verify-plane flush"
    assert all(r["apply_ms"] >= r["commit_ms"] >= 0 for r in flat)


def test_peer_ledger_partition_visible_and_deterministic(tmp_path):
    """ISSUE 14 acceptance: a scheduled partition is VISIBLE in the
    gossip observatory — messages lost on downed links are attributed
    to the partitioned peers (link_drops on exactly the cross-group
    records), injected drop faults attribute as inj_drops, vote
    first-seen routing is populated — and the same (seed, schedule)
    replays every node's peer ledger byte-identically (stamps on the
    virtual clock, traffic a pure function of the schedule), with a
    verify plane RUNNING so plane-era timing can't leak in."""
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    def run_once(tag):
        plane = VerifyPlane(window_ms=0.5, use_device=False)
        plane.start()
        set_global_plane(plane)
        try:
            with Simnet(4, seed=83, basedir=str(tmp_path / tag)) as sim:
                assert sim.run(
                    [{"at": 0.1, "op": "link", "drop": 0.05,
                      "delay": 0.01},
                     {"at": 0.5, "op": "partition",
                      "groups": [[0, 1], [2, 3]]},
                     {"at": 3.0, "op": "heal"}],
                    until_height=3, max_time=90.0,
                )
                sim.assert_safety()
                return [n.peer_ledger.dump() for n in sim.net.nodes]
        finally:
            set_global_plane(None)
            plane.stop()

    a = run_once("a")
    b = run_once("b")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # partition attribution: node 0's records for n2/n3 ate link
    # drops; its record for n1 (same side) never did
    n0 = {p["peer"]: p for p in a[0]["peers"]}
    assert n0["n2"]["link_drops"] + n0["n3"]["link_drops"] > 0, n0
    assert n0["n1"]["link_drops"] == 0, n0
    # the 5% drop fault attributed itself as injected, not network
    assert a[0]["summary"]["inj_drops"] > 0
    # real traffic flowed and votes were route-stamped on every node
    for dump in a:
        s = dump["summary"]
        assert s["msgs_tx"] > 0 and s["msgs_rx"] > 0
        assert s["votes"]["seen"] > 0
    for dump in a:
        for p in dump["peers"]:
            assert p["state"] in ("up", "dropped")


def test_incident_stream_deterministic_under_simnet(tmp_path):
    """ISSUE 13 acceptance: a partition-induced commit stall fires a
    commit_stall incident (plus round escalation), and the same (seed,
    schedule) freezes a byte-identical incident stream — the snapshot
    bundles (height/flush tails, counter samples, virtual timestamps)
    included."""
    from cometbft_tpu.libs import incidents

    def run_once(tag):
        rec = incidents.IncidentRecorder(
            commit_stall_s=3.0, round_limit=3, cooldown_s=5.0)
        old = incidents.install(rec)
        try:
            with Simnet(4, seed=71, basedir=str(tmp_path / tag)) as sim:
                sim.run([], until_height=2, max_time=60.0)
                cut = sim.net.now
                # 2/2 split: NO quorum anywhere — commits stop, rounds
                # escalate, and every step transition pokes the watchdog
                sim.run([{"at": cut, "op": "partition",
                          "groups": [[0, 1], [2, 3]]},
                         {"at": cut + 12.0, "op": "heal"}],
                        max_time=14.0)
                assert sim.run([], until_height=3, max_time=60.0), \
                    "chain did not recover after heal"
                sim.assert_safety()
                return rec.dump()
        finally:
            incidents.install(old)

    a = run_once("a")
    b = run_once("b")
    assert a["fired"].get("commit_stall", 0) >= 1, a["fired"]
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    snap = next(s for s in a["incidents"]
                if s["trigger"] == "commit_stall")
    assert snap["detail"]["stalled_s"] >= 3.0
    assert snap["height_tail"], "no height tail frozen in the snapshot"


def test_failure_blob_carries_incident_and_height_tails():
    """A SimnetFailure raised while incidents/heights were recorded
    attaches their tails ABOVE the replay blob (which must stay last
    and parseable) — the flush-ledger-tail contract extended to the
    flight recorder."""
    from cometbft_tpu.libs import incidents

    rec = incidents.IncidentRecorder(cooldown_s=0.0)
    old = incidents.install(rec)
    try:
        fp.registry().arm_from_spec("incidents.force=raise*1")
        incidents.poke(height=9, round_=2)
        msg = str(SimnetFailure("boom", 5, [{"at": 0.1, "op": "heal"}]))
    finally:
        incidents.install(old)
        fp.reset()
    assert "incidents: #0 forced h=9 r=2" in msg
    # the replay blob is still the LAST line and parses
    replay = msg.rsplit("replay: ", 1)[1]
    doc = json.loads(replay)
    assert doc["seed"] == 5


def test_light_client_attack_evidence_committed(tmp_path):
    """A >=1/3 coalition's forged header reaches one honest node as
    LightClientAttackEvidence (with its conflicting-commit proof),
    passes verify_light_client_attack, gossips, and is committed."""
    with Simnet(4, seed=12, basedir=str(tmp_path)) as sim:
        sim.run([], until_height=2, max_time=60.0)
        sim.run([{"at": sim.net.now + 0.05, "op": "light_attack",
                  "byz": [2, 3], "target": 0, "height": 1}],
                max_time=1.0)
        ev = sim.assert_evidence_committed(
            predicate=lambda e: isinstance(e, LightClientAttackEvidence)
        )
        assert len(ev.byzantine_validators) == 2
        assert ev.common_height == 1
        sim.assert_safety()


def test_failpoint_crash_and_wal_recovery(tmp_path):
    """A consensus.wal.post_vote crash failpoint armed on ONE node's
    private registry halts exactly that node; a later restart rebuilds
    it over the same home dir (WAL catchup replay + handshake replay)
    and it catches back up to the tip."""
    with Simnet(4, seed=21, basedir=str(tmp_path)) as sim:
        sim.run([
            {"at": 0.15, "op": "failpoint", "node": 2,
             "spec": "consensus.wal.post_vote=crash*1"},
            {"at": 2.0, "op": "restart", "node": 2},
        ], until_height=4, max_time=120.0)
        n2 = sim.net.nodes[2]
        # the crash fired on node 2's registry and nowhere else
        assert n2.registry.stats("consensus.wal.post_vote")["fires"] == 1
        for i in (0, 1, 3):
            st = sim.net.nodes[i].registry.stats(
                "consensus.wal.post_vote")
            assert st is None or st["fires"] == 0
        tip = max(n.height() for n in sim.net.nodes if n.alive)
        assert sim.run(
            [], until=lambda: n2.alive and n2.height() >= tip,
            max_time=60.0,
        ), (n2.alive, n2.height(), tip)
        assert n2.restarts == 1
        sim.assert_safety()


def test_failure_carries_replay_blob(tmp_path):
    """Every simnet assertion failure must print the reproducing seed +
    schedule: kill beyond quorum, then ask for liveness."""
    sched = [{"at": 0.2, "op": "kill", "node": 2},
             {"at": 0.25, "op": "kill", "node": 3}]
    with Simnet(4, seed=9, basedir=str(tmp_path)) as sim:
        sim.run(sched, max_time=0.5)
        with pytest.raises(SimnetFailure) as ei:
            sim.assert_liveness(min_new_heights=1, max_time=5.0)
        msg = str(ei.value)
        assert "replay:" in msg
        blob = json.loads(msg.split("replay:", 1)[1])
        assert blob["seed"] == 9
        assert blob["schedule"] == sched
        # the blob round-trips through the schedule validator
        validate_schedule(blob["schedule"], 4)
        assert schedule_to_json(9, sched) == json.dumps(
            blob, sort_keys=True)


def test_failure_carries_flush_ledger_tail():
    """ISSUE 6: when a verify plane ran, a SimnetFailure carries the
    ledger tail (the last flushes' stage costs) — and the replay blob
    stays the LAST line, still one parseable JSON document."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    plane = VerifyPlane(window_ms=0.2, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        k = PrivKey.generate(b"\x09" * 32)
        plane.submit(k.pub_key(), b"m", k.sign(b"m")).result(5)
    finally:
        set_global_plane(None)
        plane.stop()
    sched = [{"at": 0.1, "op": "heal"}]
    msg = str(SimnetFailure("boom", 7, sched))
    assert "flush ledger tail:" in msg
    blob = json.loads(msg.split("replay:", 1)[1])
    assert blob["seed"] == 7 and blob["schedule"] == sched


def test_stale_ledger_tail_skipped(tmp_path):
    """The module-global ledger survives unrelated earlier planes in
    the same process; a simulation during which the ledger never moved
    must not attach that stale history to its failure blob."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    plane = VerifyPlane(window_ms=0.2, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        k = PrivKey.generate(b"\x0c" * 32)
        plane.submit(k.pub_key(), b"m", k.sign(b"m")).result(5)
    finally:
        set_global_plane(None)
        plane.stop()
    # the stopped plane is still readable history (/dump_flushes), but
    # this sim never runs one — its blob must skip the foreign tail
    with Simnet(2, seed=13, basedir=str(tmp_path)) as sim:
        msg = str(sim._fail("boom"))
    assert "flush ledger tail:" not in msg
    blob = json.loads(msg.split("replay:", 1)[1])
    assert blob["seed"] == 13


def test_gateway_forged_header_scenario(tmp_path):
    """ISSUE 8: a light-client gateway mounted on a full node serves K
    clients while a lying primary feeds a SUBSET of them forged
    headers. The gateway answers the deceived clients with divergent
    verdicts, drives LightClientAttackEvidence through the existing
    pool -> gossip -> block pipeline, honest clients complete their
    sync untouched — and the whole verdict stream replays
    byte-identically for the same (seed, schedule)."""

    def run_once(tag):
        with Simnet(4, seed=37, basedir=str(tmp_path / tag)) as sim:
            sim.run([], until_height=2, max_time=60.0)
            sim.run([{"at": sim.net.now + 0.05, "op": "gateway_sync",
                      "node": 0, "clients": 6, "trusted": 1,
                      "target": 2, "forged": [1, 4], "byz": [2, 3]}],
                    max_time=2.0)
            assert len(sim.gateway_results) == 6
            ev = sim.assert_evidence_committed(
                predicate=lambda e: isinstance(
                    e, LightClientAttackEvidence)
            )
            assert ev.conflicting_height == 2
            assert ev.common_height == 1
            assert len(ev.byzantine_validators) == 2
            sim.assert_safety()
            return sim.gateway_results, ev.hash()

    results, ev_hash = run_once("a")
    by_seq = {r["seq"]: r for r in results}
    for k in range(6):
        if k in (1, 4):
            assert by_seq[k]["status"] == "divergent", by_seq[k]
        else:
            assert by_seq[k]["status"] == "verified", by_seq[k]
    # ONE attack entered the pool; the duplicate claim deduped there
    assert sum(1 for r in results if r.get("evidence_added")) == 1
    # honest clients all landed on the same (true) header
    honest = {r["target_hash"] for r in results
              if r["status"] == "verified"}
    assert len(honest) == 1

    # byte-identical replay: verdict stream AND committed evidence
    results2, ev_hash2 = run_once("b")
    assert json.dumps(results, sort_keys=True) == \
        json.dumps(results2, sort_keys=True)
    assert ev_hash == ev_hash2
