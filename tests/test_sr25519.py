"""sr25519 (schnorrkel): keccak/STROBE/merlin conformance, ristretto255
round trips, host sign/verify, and the batched device kernel.

Reference: crypto/sr25519/{batch.go,pubkey.go,privkey.go} — the protocol
itself lives in curve25519-voi; our ground truths are (a) hashlib for the
keccak permutation, (b) the published merlin conformance vector, (c) the
pure-host schnorrkel implementation as a differential oracle.
"""
import hashlib
import os

import numpy as np
import pytest

from cometbft_tpu.crypto import keccak, merlin
from cometbft_tpu.crypto import ristretto_ref as rist
from cometbft_tpu.crypto import sr25519_ref as sr
from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.crypto.keys import SR25519_KEY_TYPE, Sr25519PrivKey


def test_keccak_permutation_vs_hashlib():
    """Full SHA3-256 sponge built on our keccak-f must match hashlib —
    validates the derived round constants and rotation offsets."""
    for n in (0, 1, 135, 136, 137, 1000):
        d = os.urandom(n)
        assert keccak.sha3_256(d) == hashlib.sha3_256(d).digest()


def test_keccak_batched_matches_scalar():
    rng = np.random.default_rng(1)
    sts = rng.integers(0, 1 << 63, (5, 25), np.int64).astype(np.uint64)
    out = keccak.keccak_f1600_np(sts.copy())
    for i in range(5):
        assert [int(x) for x in out[i]] == keccak.keccak_f1600(
            [int(x) for x in sts[i]]
        )


def test_merlin_conformance_vector():
    """The published merlin transcript test vector
    (merlin/src/transcript.rs, test_transcript_equivalence_simple)."""
    t = merlin.Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    c = t.challenge_bytes(b"challenge", 32)
    assert c.hex() == (
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
    )


def test_merlin_batch_matches_scalar():
    prefix = merlin.Transcript(b"proto")
    prefix.append_message(b"ctx", b"shared")
    msgs = np.frombuffer(
        b"".join(bytes([i]) * 40 for i in range(4)), np.uint8
    ).reshape(4, 40)
    bt = merlin.BatchTranscript(4, prefix)
    bt.append_message_batch(b"m", msgs)
    out = bt.challenge_bytes_batch(b"c", 64)
    for i in range(4):
        ts = prefix.clone()
        ts.append_message(b"m", bytes(msgs[i]))
        assert bytes(out[i]) == ts.challenge_bytes(b"c", 64)


def test_ristretto_roundtrip():
    for k in (1, 2, 7, 123456, ed.L - 1):
        pt = ed.pt_mul(k, ed.BASE_EXT)
        b = rist.encode(pt)
        pt2 = rist.decode(b)
        assert pt2 is not None and rist.equals(pt, pt2)
        assert rist.encode(pt2) == b


def test_ristretto_rejects_noncanonical():
    assert rist.decode((rist.P + 2).to_bytes(32, "little")) is None  # >= p
    assert rist.decode((1).to_bytes(32, "little")) is None  # negative (odd)
    # sqrt-ratio failures must reject, and everything that DOES decode
    # must round-trip to the identical canonical bytes (decode is a
    # bijection onto its image — RFC 9496 §4.3.1); small even s values
    # split roughly half and half between the two cases
    rejected = 0
    for s in range(0, 60, 2):
        b = s.to_bytes(32, "little")
        pt = rist.decode(b)
        if pt is None:
            rejected += 1
        else:
            assert rist.encode(pt) == b
    assert rejected >= 10


def test_sign_verify_roundtrip():
    k = Sr25519PrivKey.generate(b"\x11" * 32)
    pk = k.pub_key()
    assert pk.key_type == SR25519_KEY_TYPE
    sig = k.sign(b"hello")
    assert sig[63] & 0x80
    assert pk.verify_signature(b"hello", sig)
    assert not pk.verify_signature(b"hellp", sig)
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not pk.verify_signature(b"hello", bytes(bad))
    # marker bit is mandatory (schnorrkel signature format)
    nomark = bytearray(sig)
    nomark[63] &= 0x7F
    assert not pk.verify_signature(b"hello", bytes(nomark))


def _fixture(n, bad=()):
    ks = [Sr25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(8)]
    msgs = [b"sr-%04d" % i for i in range(n)]
    pubs = [ks[i % 8].pub_key().data for i in range(n)]
    sigs = [ks[i % 8].sign(m) for i, m in enumerate(msgs)]
    for i in bad:
        sigs[i] = sigs[i][:5] + bytes([sigs[i][5] ^ 1]) + sigs[i][6:]
    return pubs, msgs, sigs


@pytest.mark.slow  # ~6 min sr25519 kernel compile+run on CPU;
# kernel_rejects_bad_encodings keeps a quick-gate kernel probe
def test_kernel_matches_oracle():
    from cometbft_tpu.ops import sr25519_kernel as srk

    pubs, msgs, sigs = _fixture(32, bad=(3, 17))
    got = srk.verify_batch(pubs, msgs, sigs)
    exp = np.asarray(
        [sr.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    )
    assert (got == exp).all()
    assert not exp[3] and not exp[17] and exp[0]


@pytest.mark.slow  # ~71 s on the 1-core host under suite load;
# ristretto_rejects_noncanonical + mixed_batch_dispatch stay quick
def test_kernel_rejects_bad_encodings():
    from cometbft_tpu.ops import sr25519_kernel as srk

    pubs, msgs, sigs = _fixture(8)
    sigs[1] = sigs[1][:63] + bytes([sigs[1][63] & 0x7F])  # no marker
    sigs[2] = b"\x01" + sigs[2][1:]  # R likely invalid/odd encoding
    pubs[4] = (rist.P + 2).to_bytes(32, "little")  # non-canonical pk
    got = srk.verify_batch(pubs, msgs, sigs)
    exp = np.asarray(
        [sr.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    )
    assert (got == exp).all()
    assert not exp[1] and not exp[2] and not exp[4]


def _mixed_fixture():
    from cometbft_tpu.crypto.keys import PrivKey

    eks = [PrivKey.generate(bytes([40 + i]) * 32) for i in range(4)]
    sks = [Sr25519PrivKey.generate(bytes([80 + i]) * 32) for i in range(4)]
    pubs, msgs, sigs = [], [], []
    for i in range(8):
        m = b"mixed-%d" % i
        if i % 2 == 0:
            k = eks[i // 2]
        else:
            k = sks[i // 2]
        pubs.append(k.pub_key())
        msgs.append(m)
        sigs.append(k.sign(m))
    sigs[5] = sigs[5][:8] + bytes([sigs[5][8] ^ 1]) + sigs[5][9:]
    exp = np.ones(8, bool)
    exp[5] = False
    return pubs, msgs, sigs, exp


@pytest.mark.slow  # ~143 s: the sr25519 group pays the kernel
# compile on CPU ([tier1-duration] flagged it past the 60 s line);
# test_mixed_batch_dispatch_grouping keeps the dispatch seam quick
def test_mixed_batch_dispatch():
    """ed25519 + sr25519 rows in one crypto/batch call (the BASELINE
    config #3 seam; goes beyond crypto/batch/batch.go:12 which can't mix
    key types in one verifier)."""
    from cometbft_tpu.crypto import batch as cbatch

    pubs, msgs, sigs, exp = _mixed_fixture()
    valid = cbatch.verify_batch(pubs, msgs, sigs)
    assert (valid == exp).all()


def test_mixed_batch_dispatch_grouping(monkeypatch):
    """The quick-gate sibling of test_mixed_batch_dispatch: same mixed
    fixture, same grouping/reassembly/blame logic in
    crypto/batch.verify_batch, but the per-key-type kernels are
    monkeypatched to the host oracles at the `_kernel_for` seam — so
    the DISPATCH layer (group by key type, one call per group, verdicts
    scattered back to input order) is proven without paying the
    sr25519 kernel compile the slow variant covers."""
    from cometbft_tpu.crypto import batch as cbatch
    from cometbft_tpu.crypto.keys import ED25519_KEY_TYPE

    routed = []

    def host_kernel_for(key_type):
        routed.append(key_type)
        if key_type == ED25519_KEY_TYPE:
            return lambda pubs, msgs, sigs: np.asarray(
                [ed.verify(p, m, s)
                 for p, m, s in zip(pubs, msgs, sigs)])
        if key_type == SR25519_KEY_TYPE:
            return lambda pubs, msgs, sigs: np.asarray(
                [sr.verify(p, m, s)
                 for p, m, s in zip(pubs, msgs, sigs)])
        raise ValueError(key_type)

    monkeypatch.setattr(cbatch, "_kernel_for", host_kernel_for)
    pubs, msgs, sigs, exp = _mixed_fixture()
    # a pinned fresh breaker keeps the test independent of global
    # breaker state (and of any mounted plane — pinning goes direct)
    valid = cbatch.verify_batch(pubs, msgs, sigs,
                                breaker=cbatch.CircuitBreaker())
    assert (valid == exp).all()
    # one kernel lookup per key-type group, both groups routed
    assert sorted(routed) == sorted([ED25519_KEY_TYPE,
                                     SR25519_KEY_TYPE])
