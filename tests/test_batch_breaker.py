"""Device-fault circuit breaker (crypto/batch.py): with the
`crypto.device_dispatch` failpoint armed, batch verification must trip
the breaker, return verdicts identical to the ed25519_ref host oracle,
and recover once the fault clears (ISSUE acceptance criterion)."""
import numpy as np
import pytest

from cometbft_tpu.crypto import batch as cbatch
from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.libs import failpoints as fp


@pytest.fixture(autouse=True)
def clean():
    fp.reset()
    cbatch.device_breaker().reset()
    yield
    fp.reset()
    cbatch.device_breaker().reset()
    cbatch.configure_breaker(2, 30.0)  # restore defaults


def make_batch(n=6):
    """Mixed valid/invalid ed25519 rows + the host-oracle expectation."""
    seeds = [bytes([i + 10]) * 32 for i in range(n)]
    privs = [PrivKey.generate(s) for s in seeds]
    pubs = [p.pub_key() for p in privs]
    msgs = [b"breaker-%d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    sigs[2] = b"\x01" * 64                      # garbage signature
    msgs_t = list(msgs)
    msgs_t[4] = msgs[4] + b"tampered"           # sig/msg mismatch
    exp = [ed.verify(p.data, m, s)
           for p, m, s in zip(pubs, msgs_t, sigs)]
    assert exp == [True, True, False, True, False, True]
    return pubs, msgs_t, sigs, exp


def oracle_kernel(pub_bytes, msgs, sigs):
    """Stand-in 'device' kernel: oracle semantics, zero compile cost.

    The breaker tests exercise dispatch/trip/probe/fallback control
    flow, which is independent of which kernel runs; using the real
    XLA kernel here would spend minutes of 1-core compile inside the
    alphabetically-early part of the tier-1 run. Kernel correctness
    itself is covered by the differential tests."""
    return np.asarray(
        [ed.verify(p, m, s) for p, m, s in zip(pub_bytes, msgs, sigs)]
    )


KERNELS = {"ed25519": oracle_kernel}


def test_device_fault_trips_breaker_host_path_correct():
    pubs, msgs, sigs, exp = make_batch()
    brk = cbatch.CircuitBreaker(failure_threshold=2, cooldown=0.2)

    fp.arm("crypto.device_dispatch", "raise")  # device is sick
    # 1st faulted batch: breaker still closed (threshold 2), host path
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert brk.state == "closed"
    # 2nd faulted batch: breaker trips
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert brk.state == "open" and brk.trips == 1

    # while open (cooldown not lapsed) the device is NOT dispatched:
    # the armed failpoint would raise, so correct results prove the
    # host path served the batch without even probing
    fires_before = fp.registry().stats("crypto.device_dispatch")["fires"]
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert fp.registry().stats("crypto.device_dispatch")["fires"] == \
        fires_before


def test_breaker_reprobes_and_recovers():
    pubs, msgs, sigs, exp = make_batch()
    brk = cbatch.CircuitBreaker(failure_threshold=1, cooldown=0.05)

    fp.arm("crypto.device_dispatch", "raise")
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert brk.state == "open"

    # fault clears; after the cooldown the next batch probes the device
    # and the breaker closes
    fp.reset()
    import time

    time.sleep(0.06)
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert brk.state == "closed" and brk.probes >= 1


def test_probe_failure_keeps_breaker_open():
    pubs, msgs, sigs, exp = make_batch()
    brk = cbatch.CircuitBreaker(failure_threshold=1, cooldown=0.05)
    fp.arm("crypto.device_dispatch", "raise")
    cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    assert brk.state == "open"
    import time

    time.sleep(0.06)
    # still faulted: the probe fails and the breaker stays open
    got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert brk.state == "open" and brk.probes >= 1


def test_flake_action_degrades_not_halts():
    """A flaky device (every 2nd dispatch faults) still returns correct
    verdicts on every call — consensus sees slowdown, never error."""
    pubs, msgs, sigs, exp = make_batch()
    brk = cbatch.CircuitBreaker(failure_threshold=10, cooldown=0.01)
    fp.arm("crypto.device_dispatch", "flake", arg=2)
    for _ in range(4):
        got = cbatch.verify_batch(pubs, msgs, sigs, kernels=KERNELS, breaker=brk)
        np.testing.assert_array_equal(got, np.asarray(exp))


def test_device_batch_fn_covered_by_breaker():
    """The TPU verify path (validation.device_batch_fn) dispatches
    through the same breaker-guarded chokepoint."""
    from cometbft_tpu.types import validation

    pubs, msgs, sigs, exp = make_batch()
    cbatch.configure_breaker(1, 30.0)
    fn = validation.device_batch_fn(use_pallas=False)
    fp.arm("crypto.device_dispatch", "raise")
    got = np.asarray(fn(pubs, msgs, sigs))
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert cbatch.device_breaker().state == "open"


def test_breaker_config_knobs():
    from cometbft_tpu.config.config import Config, ConfigError

    cfg = Config()
    cfg.crypto.breaker_failure_threshold = 7
    cfg.crypto.breaker_cooldown = 1.5
    cfg.validate_basic()
    cfg.crypto.batch_fn()  # applies the knobs to the global breaker
    assert cbatch.device_breaker().failure_threshold == 7
    assert cbatch.device_breaker().cooldown == 1.5
    cfg.crypto.breaker_failure_threshold = 0
    with pytest.raises(ConfigError):
        cfg.validate_basic()
