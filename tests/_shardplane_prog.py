"""Subprocess body for tests/test_zshardplane_smoke.py.

Runs under a FORCED 4-virtual-device CPU mesh (the parent sets
XLA_FLAGS=--xla_force_host_platform_device_count=4, JAX_PLATFORMS=cpu)
and proves the verify plane's cross-chip sharded fused path without a
TPU: the two expensive device programs are stubbed — the Pallas cached
kernel by a precheck&ok plumbing fake and the XLA table build by a
shape-faithful fake — so what executes is exactly the machinery ISSUE
10 added: plan_fused's sharded scatter layout, per-shard table
assembly + (valset, mesh) memoization, the sharded_fused_verify step
(psum tally, replicated thresholds), ledger n_dev attribution, and the
breaker/PlaneOverloaded semantics around a faulting sharded dispatch.

Asserts, then prints one JSON summary line the parent test parses:
  * sharded verdicts, per-group tallies, and quorum bits are
    BIT-IDENTICAL to the single-device oracle (same stubs, one chip);
  * the second sharded flush HITs the mesh step memo and the sharded
    table memo (no steady-state re-trace or re-upload);
  * a faulting sharded dispatch degrades that flush with correct
    verdicts, trips the breaker, and BULK-lane PlaneOverloaded
    shedding still carries its retry hint.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

assert len(jax.devices()) == 4, jax.devices()
assert jax.default_backend() == "cpu"

from cometbft_tpu.crypto import batch as cbatch  # noqa: E402
from cometbft_tpu.crypto.keys import PrivKey  # noqa: E402
from cometbft_tpu.ops import ed25519_cached as ec  # noqa: E402
from cometbft_tpu.parallel import mesh as pm  # noqa: E402
from cometbft_tpu.verifyplane import fused as fz  # noqa: E402
from cometbft_tpu.verifyplane import (  # noqa: E402
    PlaneOverloaded,
    QuorumGroup,
    VerifyPlane,
)

# ---- stubs: the minutes-of-compile device programs, not the plumbing ----

from _kernel_stubs import fake_verify_tally_cached  # noqa: E402

fz.ALLOW_CPU_FUSED = True
ec._BASE60_F32 = np.zeros((32 * 256, ec.ROWS_PER_ENT), np.float32)
ec._verify_tally_cached = fake_verify_tally_cached

real_pack_pubs = ec._pack_pub_arrays


def fake_build_table(pub_bytes, powers=None):
    padded = ec.table_pad(len(pub_bytes))
    ok = np.zeros((padded,), np.bool_)
    ok[: len(pub_bytes)] = [len(p) == 32 for p in pub_bytes]
    return ec.ValsetTable(
        jnp.zeros((padded // 128 * ec.ENT_BLOCK, 128), jnp.int16),
        jnp.asarray(ok), ec._power_dev(powers, padded), padded,
        ec._pubs_host(pub_bytes, padded),
        ec._powers_host(powers, padded),
        # the REAL pub_raw: the device stamping prologue (ISSUE 19) is
        # pure XLA — only the Pallas verify kernel is stubbed, so
        # delta-staged flushes through this table stamp for real
        ec._pub_raw(pub_bytes, padded))


ec.build_table = fake_build_table

# ---- fixture: a 300-validator valset spanning 2 of 4 table shards ----

# shard stride 256 (table_pad bucket) -> only 2 shards hold validators
# (the second one partially), so effective_mesh must CLAMP the flush to
# a 2-device sub-mesh — empty shards would stage + verify pure padding
# every flush
NVALS = 300
EXPECT_NDEV = 2

mesh4 = fz.plane_mesh(0)
assert mesh4 is not None and mesh4.devices.size == 4
m_eff, n_eff, m_s_eff = fz.effective_mesh(mesh4, NVALS)
assert (n_eff, m_s_eff) == (EXPECT_NDEV, 256), (n_eff, m_s_eff)
assert m_eff.devices.size == EXPECT_NDEV
# a valset filling every stride keeps the full fan-out...
assert fz.effective_mesh(mesh4, 1024)[1] == 4
# ...and one that fits a single stride is single-device business
assert fz.effective_mesh(mesh4, 100)[0] is None
privs = [PrivKey.generate((4200 + i).to_bytes(4, "big") + b"\x77" * 28)
         for i in range(NVALS)]
pubs_t = tuple(p.pub_key().data for p in privs)
powers_t = tuple((i % 9 + 1) * 100 for i in range(NVALS))
submitters = list(range(0, NVALS, 7))  # spread across the shards

BAD_SIG = b"\x5a" * 32 + b"\xff" * 32  # S >= L: precheck AND ref reject


def make_batch(groups):
    """(rows, vidx, group, power, expected_verdicts) per submission:
    vote + extension rows; every 5th vote forged, every 11th extension
    forged (valid vote + forged ext => power must NOT stand)."""
    subs = []
    for j, v in enumerate(submitters):
        pub = privs[v].pub_key()
        m1 = b"vote-%d" % v
        m2 = b"ext-%d" % v
        s1 = BAD_SIG if j % 5 == 0 else privs[v].sign(m1)
        s2 = BAD_SIG if j % 11 == 3 else privs[v].sign(m2)
        exp = (j % 5 != 0, j % 11 != 3)
        subs.append(([(pub, m1, s1), (pub, m2, s2)], (v, v),
                     groups[v % 2], powers_t[v], exp))
    return subs


def drive(plane, groups):
    futs = [plane.submit_many(rows, power=pw, group=g, counted=True,
                              vidx=vidx)
            for rows, vidx, g, pw, _ in make_batch(groups)]
    return [f.result(30.0) for f in futs]


def expected():
    exp_verdicts = [e for *_, e in make_batch([None, None])]
    tallies = [0, 0]
    for (rows, vidx, _g, pw, e) in make_batch([None, None]):
        if all(e):
            tallies[vidx[0] % 2] += pw
    return exp_verdicts, tallies


def new_groups(thr):
    return [QuorumGroup(thr[c], valset_pubs=pubs_t,
                        valset_powers=powers_t) for c in range(2)]


exp_verdicts, exp_tallies = expected()
# one group crosses its threshold, the other misses it
THR = [exp_tallies[0], exp_tallies[1] + 1]

# ---- phase A: single-device oracle --------------------------------------

plane_s = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True)
plane_s.start()
groups_s = new_groups(THR)
verd_s = drive(plane_s, groups_s)
plane_s.stop()
assert verd_s == exp_verdicts, (verd_s, exp_verdicts)
assert [g.tally for g in groups_s] == exp_tallies
assert [g.quorum_reached for g in groups_s] == [True, False]
led_s = plane_s.dump_flushes()["flushes"]
assert any(r["path"] == "fused" and r["n_dev"] == 1 for r in led_s), led_s

# ---- phase B: sharded across the 4-device mesh, bit-identical -----------

plane_m = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True,
                      mesh_devices=0, mesh_min_rows=1)
plane_m.start()
groups_m = new_groups(THR)
verd_m = drive(plane_m, groups_m)
assert verd_m == verd_s, "sharded verdicts diverged from single-device"
assert [g.tally for g in groups_m] == [g.tally for g in groups_s]
assert [g.quorum_reached for g in groups_m] == \
    [g.quorum_reached for g in groups_s]

# ---- phase C: steady state hits every memo (no re-trace, no re-upload) --

mesh_before = pm.cache_stats()
tbl_before = ec.table_cache_stats()
groups_m2 = new_groups(THR)
verd_m2 = drive(plane_m, groups_m2)
assert verd_m2 == verd_s
mesh_after = pm.cache_stats()
tbl_after = ec.table_cache_stats()
assert mesh_after["hits"] > mesh_before["hits"]
assert mesh_after["misses"] == mesh_before["misses"], \
    "second sharded flush re-traced a mesh step"
assert tbl_after["shard_hits"] > tbl_before["shard_hits"]
assert tbl_after["shard_misses"] == tbl_before["shard_misses"], \
    "second sharded flush rebuilt the sharded table"

recs = plane_m.dump_flushes()["flushes"]
shard_recs = [r for r in recs if r["path"] == "fused_sharded"]
assert shard_recs and all(r["n_dev"] == EXPECT_NDEV
                          for r in shard_recs), recs
# the ledger's warm column (ISSUE 12): the FIRST sharded flush paid
# the table build inline (cold, warm=0); the steady-state flush found
# it cached (warm=1) — exactly how /dump_flushes attributes a
# post-rotation stall
assert shard_recs[0]["warm"] == 0, shard_recs
assert shard_recs[-1]["warm"] == 1, shard_recs
summary = plane_m.dump_flushes()["summary"]
assert summary["tables"]["cold"] >= 1
assert summary["tables"]["warm"] >= 1
assert summary["shard"]["flushes"] >= 2
assert summary["shard"]["n_dev_max"] == EXPECT_NDEV
stats = plane_m.stats()
# mesh_ndev reports the RESOLVED configured mesh; the ledger column
# reports the clamped per-flush fan-out
assert stats["mesh_ndev"] == 4 and stats["shard_flushes"] >= 2
plane_m.stop()

# ---- phase C2: an IN-FLIGHT sharded fault must not claim cross-chip -----
# (JAX async dispatch surfaces most device faults at collect, not
# dispatch: the record must repair to n_dev=1 host attribution and the
# shard counters must only ever count COMPLETED cross-chip passes)

real_collect = fz.collect_fused
fault = {"armed": True}


def faulty_collect(plan):
    if fault["armed"]:
        fault["armed"] = False
        raise RuntimeError("injected in-flight device fault")
    return real_collect(plan)


fz.collect_fused = faulty_collect
plane_c = VerifyPlane(
    window_ms=40.0, max_batch=4096, use_device=True, mesh_devices=0,
    mesh_min_rows=1,
    breaker=cbatch.CircuitBreaker(failure_threshold=3, cooldown=60.0))
plane_c.start()
groups_c = new_groups(THR)
verd_c = drive(plane_c, groups_c)
plane_c.stop()
fz.collect_fused = real_collect
assert verd_c == exp_verdicts, "in-flight fault changed verdicts"
assert [g.tally for g in groups_c] == exp_tallies
recs_c = plane_c.dump_flushes()["flushes"]
fallbacks = [r for r in recs_c if r["path"] == "fused_host_fallback"]
assert fallbacks and all(r["n_dev"] == 1 for r in fallbacks), recs_c
completed = [r for r in recs_c if r["path"] == "fused_sharded"]
assert plane_c.stats()["shard_flushes"] == len(completed), recs_c

# ---- phase D: a faulting sharded dispatch degrades, breaker + sheds -----


def host_direct(pubs, msgs, sigs, kernels=None, breaker=None):
    out = []
    for p, m, s in zip(pubs, msgs, sigs):
        try:
            out.append(bool(p.verify_signature(m, s)))
        except ValueError:
            out.append(False)
    return np.asarray(out, np.bool_)


cbatch.verify_batch_direct = host_direct
real_dispatch = fz.dispatch_fused


def faulting_dispatch(plan):
    raise RuntimeError("injected sharded device fault")


fz.dispatch_fused = faulting_dispatch
brk = cbatch.CircuitBreaker(failure_threshold=1, cooldown=60.0)
plane_f = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True,
                      mesh_devices=0, mesh_min_rows=1, breaker=brk,
                      bulk_max_queue=2, bulk_window_ms=10_000.0)
plane_f.start()
groups_f = new_groups(THR)
verd_f = drive(plane_f, groups_f)
# verdicts still correct (host fallback), tallies still land host-side
assert verd_f == exp_verdicts
assert [g.tally for g in groups_f] == exp_tallies
assert brk.state == "open", "sharded dispatch fault must trip the breaker"
recs_f = plane_f.dump_flushes()["flushes"]
assert any(r["path"] == "grouped" for r in recs_f), recs_f
assert not any(r["path"] == "fused_sharded" for r in recs_f)

# BULK shedding semantics are unchanged with a mesh configured: the
# lane bound still answers with an explicit retry-hinted verdict
# (bulk_window is 10s, so the queued row cannot drain underneath us)
p0 = privs[0]
row = (p0.pub_key(), b"bulk-0", p0.sign(b"bulk-0"))
plane_f.submit_many([row], lane="bulk")
try:
    plane_f.submit_many([row, row, row], lane="bulk", block=False)
    raise AssertionError("over-bound BULK submit was not shed")
except PlaneOverloaded as e:
    assert e.retry_after_ms > 0
assert plane_f.sheds["bulk"] >= 1
plane_f.stop()
fz.dispatch_fused = real_dispatch

# ==========================================================================
# ISSUE 11: the pipelined flight deck — phases E (two flights airborne
# on DISJOINT halves + out-of-order landing), F (giant flush takes the
# full mesh and drains the deck FIRST), G (breaker trip mid-deck
# degrades every airborne flight to correct host verdicts).
# ==========================================================================

import threading
import time as _time

mesh4b = fz.plane_mesh(0)
halves = fz.half_meshes(mesh4b)
assert len(halves) == 2
ids_lo = tuple(int(d.id) for d in halves[0].devices.flat)
ids_hi = tuple(int(d.id) for d in halves[1].devices.flat)
assert ids_lo == (0, 1) and ids_hi == (2, 3), (ids_lo, ids_hi)
# a 300-validator valset fills both devices of either half, and the
# half memo rides the same sub-mesh seam effective_mesh clamps through
assert fz.effective_mesh(halves[0], NVALS)[1] == 2
assert fz.effective_mesh(halves[1], NVALS)[1] == 2
assert fz.half_meshes(mesh4b)[0] is halves[0]
# meshes under 4 devices offer no halves (deck degrades single-flight)
assert fz.half_meshes(halves[0]) == []

# the deck's 42-submission fixture: 21-sub waves of the standard
# vote+ext shape (the 43rd submitter is dropped so both waves drain as
# exactly one max_batch=42-row flush each)
E_N = 42
probe42 = make_batch([None, None])[:E_N]
exp42 = [e for *_, e in probe42]
tallies42 = [0, 0]
for _rows, _vidx, _g, _pw, _e in probe42:
    if all(_e):
        tallies42[_vidx[0] % 2] += _pw
THR42 = [tallies42[0], tallies42[1] + 1]


def drive_waves(plane, groups, on_wave1=None):
    subs = make_batch(groups)[:E_N]
    futs = []
    for rows, vidx, g, pw, _ in subs[:E_N // 2]:
        futs.append(plane.submit_many(rows, power=pw, group=g,
                                      counted=True, vidx=vidx))
    if on_wave1 is not None:
        on_wave1()
    for rows, vidx, g, pw, _ in subs[E_N // 2:]:
        futs.append(plane.submit_many(rows, power=pw, group=g,
                                      counted=True, vidx=vidx))
    return futs


def wait_until(cond, timeout=30.0, what="condition"):
    t0 = _time.monotonic()
    while not cond():
        if _time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {what}")
        _time.sleep(0.002)


# ---- phase E oracle: the same 42 submissions, single-flight ----------

plane_o = VerifyPlane(window_ms=40.0, max_batch=E_N, use_device=True,
                      mesh_devices=0, mesh_min_rows=1)
plane_o.start()
groups_o = new_groups(THR42)
futs_o = drive_waves(plane_o, groups_o)
verd_o = [f.result(30.0) for f in futs_o]
plane_o.stop()
assert verd_o == exp42, "single-flight oracle verdicts wrong"
assert [g.tally for g in groups_o] == tallies42
assert [g.quorum_reached for g in groups_o] == [True, False]

# ---- gates: hold staging, block collects, fake the readiness probe ----

real_dispatch_e = fz.dispatch_fused
real_collect_e = fz.collect_fused
real_ready_e = fz.plan_ready
dispatched = []
release = {}
collect_entered = {}
fault_ids = set()
gate_hold = {"fn": None}


def gated_dispatch(plan):
    real_dispatch_e(plan)
    release[id(plan)] = threading.Event()
    collect_entered[id(plan)] = threading.Event()
    dispatched.append(plan)
    h = gate_hold["fn"]
    if h is not None:
        h(plan)


def gated_collect(plan):
    ev = release.get(id(plan))
    if ev is not None:
        collect_entered[id(plan)].set()
        assert ev.wait(60.0), "collect gate timed out"
    if id(plan) in fault_ids:
        raise RuntimeError("injected mid-deck device fault")
    return real_collect_e(plan)


def gated_ready(plan):
    ev = release.get(id(plan))
    return ev.is_set() if ev is not None else real_ready_e(plan)


fz.dispatch_fused = gated_dispatch
fz.collect_fused = gated_collect
fz.plan_ready = gated_ready

# ---- phase E: disjoint halves, out-of-order landing -------------------

e_hold = threading.Event()
e_base = len(dispatched)
gate_hold["fn"] = lambda plan: (
    e_hold.wait(30.0) if len(dispatched) == e_base + 1 else None)

# window >> test: flushes trigger on max_batch rows, never the clock;
# holding flush 1's staging (the dispatch gate) until wave 2 is FULLY
# queued makes the two-flush split deterministic (with the deck
# airborne the dispatcher drains without waiting the window)
plane_p = VerifyPlane(window_ms=30_000.0, max_batch=E_N,
                      use_device=True, mesh_devices=0, mesh_min_rows=1,
                      pipeline_flights=2)
plane_p.start()
groups_p = new_groups(THR42)
futs_p = drive_waves(
    plane_p, groups_p,
    on_wave1=lambda: wait_until(
        lambda: len(dispatched) == e_base + 1,
        what="flight 1 dispatch"))
e_hold.set()  # wave 2 fully queued: let flight 1's staging finish
wait_until(lambda: len(dispatched) == e_base + 2,
           what="flight 2 dispatch")
p1, p2 = dispatched[e_base], dispatched[e_base + 1]
# the two flights fly DISJOINT halves
assert tuple(p1.devs) == ids_lo, p1.devs
assert tuple(p2.devs) == ids_hi, p2.devs
wait_until(lambda: plane_p.deck_airborne == 2, what="deck depth 2")
assert plane_p.stats()["halves"] == 2

# out-of-order landing: flight 2 is released FIRST and must settle
# while flight 1 is still airborne (no head-of-line blocking)
release[id(p2)].set()
verd_w2 = [f.result(30.0) for f in futs_p[E_N // 2:]]
assert not futs_p[0].done(), "flight 1 settled before its release"
wait_until(lambda: plane_p.deck_airborne == 1, what="flight 2 landed")

# ---- phase E2: the rotation-window bound on out-of-order landing ----
# The staging pool rotates flights+1 slots round-robin, so pack 4
# would hand out the very buffers still pinned under flight 1 (packed
# as pack 1, still airborne after flight 2 landed out of order). The
# dispatcher must force-land flight 1 FIFO *before* staging pack 4.


def uncounted_wave(tag):
    rows, vidx = [], []
    for v in range(0, E_N, 2):
        m = b"%s-%d" % (tag, v)
        rows.append((privs[v].pub_key(), m, privs[v].sign(m)))
        vidx.append(v)
    return rows, vidx


g_e2 = QuorumGroup(1, valset_pubs=pubs_t, valset_powers=powers_t)
rows3, vidx3 = uncounted_wave(b"w3")
fut_w3 = plane_p.submit_many(rows3, group=g_e2, counted=False,
                             vidx=vidx3)
wait_until(lambda: len(dispatched) == e_base + 3,
           what="flight 3 dispatch")
p3 = dispatched[e_base + 2]
# flight 3 flies the half flight 2 freed; flight 1 (pack 1) is still
# airborne on the other — within the rotation window, so no force-land
assert tuple(p3.devs) == ids_hi, p3.devs
assert not futs_p[0].done()
rows4, vidx4 = uncounted_wave(b"w4")
fut_w4 = plane_p.submit_many(rows4, group=g_e2, counted=False,
                             vidx=vidx4)
# pack 4 reuses pack 1's buffers: flight 1 must be force-landed (its
# collect entered) while pack 4 is still UNstaged
wait_until(lambda: collect_entered[id(p1)].is_set(),
           what="rotation-window force-land of flight 1")
assert len(dispatched) == e_base + 3, \
    "pack 4 staged while flight 1 still pinned its buffers"
release[id(p1)].set()
verd_w1 = [f.result(30.0) for f in futs_p[:E_N // 2]]
wait_until(lambda: len(dispatched) == e_base + 4,
           what="flight 4 dispatch")
p4 = dispatched[e_base + 3]
assert tuple(p4.devs) == ids_lo, p4.devs  # back on the freed half
release[id(p3)].set()
release[id(p4)].set()
assert all(fut_w3.result(30.0)) and all(fut_w4.result(30.0))
plane_p.stop()
verd_p = verd_w1 + verd_w2
assert verd_p == verd_o, "deck verdicts diverged from the oracle"
assert [g.tally for g in groups_p] == [g.tally for g in groups_o]
assert [g.quorum_reached for g in groups_p] == \
    [g.quorum_reached for g in groups_o]

recs_p = plane_p.dump_flushes()["flushes"]
sh_p = [r for r in recs_p if r["path"] == "fused_sharded"]
assert len(sh_p) == 4, recs_p
f1r, f2r, f3r, f4r = sorted(sh_p, key=lambda r: r["seq"])
# ledger evidence of two flights genuinely airborne on disjoint halves
assert f1r["airborne"] == 0 and f2r["airborne"] == 1, (f1r, f2r)
assert (f1r["dev0"], f2r["dev0"]) == (0, 2), (f1r, f2r)
assert f1r["n_dev"] == 2 and f2r["n_dev"] == 2
assert all(r["n_host"] == 1 for r in sh_p)
assert f2r["overlapped"] is True and f1r["overlapped"] is False
# flights 3/4 each packed with one flight airborne (E2)
assert (f3r["airborne"], f4r["airborne"]) == (1, 1)
assert (f3r["dev0"], f4r["dev0"]) == (2, 0)
# landing order: flight 2 out of order first, then the force-landed
# flight 1 (rotation window), then 3 and 4
assert [r["seq"] for r in sh_p] == \
    [f2r["seq"], f1r["seq"], f3r["seq"], f4r["seq"]], sh_p
sum_p = plane_p.dump_flushes()["summary"]
assert sum_p["deck"]["airborne_max"] == 1
assert sum_p["deck"]["overlapped_flushes"] == 3
assert plane_p.stats()["deck_peak"] == 2

# ---- phase F: a giant flush takes the FULL mesh and drains first ------

gate_hold["fn"] = None
f_base = len(dispatched)
plane_f2 = VerifyPlane(window_ms=30_000.0, max_batch=E_N,
                       use_device=True, mesh_devices=0, mesh_min_rows=1,
                       pipeline_flights=2, half_mesh_rows=E_N)
plane_f2.start()
groups_f2 = new_groups(THR42)
subs_f = make_batch(groups_f2)[:E_N]
futs_f1 = [plane_f2.submit_many(rows, power=pw, group=g, counted=True,
                                vidx=vidx)
           for rows, vidx, g, pw, _ in subs_f[:E_N // 2]]
wait_until(lambda: len(dispatched) == f_base + 1,
           what="phase F flight 1 dispatch")
pf1 = dispatched[f_base]
assert tuple(pf1.devs) == ids_lo
# one oversized submission: 60 rows > half_mesh_rows (42) -> the
# policy must take the full mesh and land the airborne deck FIRST
big_vals = list(range(0, 60, 2))
big_rows = []
big_vidx = []
for v in big_vals:
    m1, m2 = b"big-%d" % v, b"bigext-%d" % v
    big_rows += [(privs[v].pub_key(), m1, privs[v].sign(m1)),
                 (privs[v].pub_key(), m2, privs[v].sign(m2))]
    big_vidx += [v, v]
fut_big = plane_f2.submit_many(
    big_rows, group=QuorumGroup(1, valset_pubs=pubs_t,
                                valset_powers=powers_t),
    counted=False, vidx=big_vidx)
# drain-before-dispatch, observed: the dispatcher enters flight 1's
# collect (the drain) while the big flush is STILL undispatched
wait_until(lambda: collect_entered[id(pf1)].is_set(),
           what="deck drain before the full-mesh dispatch")
assert len(dispatched) == f_base + 1, \
    "full-mesh flush dispatched before the deck drained"
release[id(pf1)].set()
wait_until(lambda: len(dispatched) == f_base + 2,
           what="full-mesh dispatch after the drain")
pf2 = dispatched[f_base + 1]
assert pf2.drain_first is True
# NVALS=300 clamps the full mesh to its 2-device prefix — the policy
# passed over the FREE upper half because the flush was over the knob
assert tuple(pf2.devs) == ids_lo, pf2.devs
release[id(pf2)].set()
assert all(fut_big.result(30.0)), "big-flush verdicts wrong"
verd_f1 = [f.result(30.0) for f in futs_f1]
plane_f2.stop()
assert verd_f1 == exp42[:E_N // 2]
big_rec = [r for r in plane_f2.dump_flushes()["flushes"]
           if r["rows"] == len(big_rows)]
assert big_rec and big_rec[0]["path"] == "fused_sharded"
assert big_rec[0]["airborne"] == 0  # the deck was drained first

# ---- phase G: breaker trip mid-deck degrades ALL airborne flights -----

g_base = len(dispatched)
g_hold = threading.Event()
gate_hold["fn"] = lambda plan: (
    g_hold.wait(30.0) if len(dispatched) == g_base + 1 else None)
brk_g = cbatch.CircuitBreaker(failure_threshold=1, cooldown=60.0)
plane_g = VerifyPlane(window_ms=30_000.0, max_batch=E_N,
                      use_device=True, mesh_devices=0, mesh_min_rows=1,
                      pipeline_flights=2, breaker=brk_g)
plane_g.start()
groups_g = new_groups(THR42)
futs_g = drive_waves(
    plane_g, groups_g,
    on_wave1=lambda: wait_until(
        lambda: len(dispatched) == g_base + 1,
        what="phase G flight 1 dispatch"))
g_hold.set()  # wave 2 queued: let phase G flight 1's staging finish
wait_until(lambda: len(dispatched) == g_base + 2,
           what="phase G flight 2 dispatch")
wait_until(lambda: plane_g.deck_airborne == 2,
           what="phase G deck depth 2")
pg1, pg2 = dispatched[g_base], dispatched[g_base + 1]
assert set(pg1.devs).isdisjoint(pg2.devs)
# both collects fault: every airborne flight must degrade to host
# verdicts (and the breaker must trip)
fault_ids.update((id(pg1), id(pg2)))
release[id(pg1)].set()
release[id(pg2)].set()
verd_g = [f.result(60.0) for f in futs_g]
plane_g.stop()
assert verd_g == exp42, "mid-deck fault changed verdicts"
assert [g.tally for g in groups_g] == tallies42
assert brk_g.state == "open", "mid-deck faults must trip the breaker"
recs_g = plane_g.dump_flushes()["flushes"]
fb_g = [r for r in recs_g if r["path"] == "fused_host_fallback"]
assert len(fb_g) == 2, recs_g
assert all(r["n_dev"] == 1 and r["dev0"] == 0 for r in fb_g)

fz.dispatch_fused = real_dispatch_e
fz.collect_fused = real_collect_e
fz.plan_ready = real_ready_e

# ---- phase H: the device observatory catches a broken step memo ---------
# The round-5 bug class END TO END on the real dispatcher: the jitted
# sharded steps in this subprocess compile for REAL (only the Pallas
# kernel inside them is stubbed), so clearing parallel.mesh's step memo
# — exactly what the round-5 per-call shard_map rebuild regression did
# — makes the next flush re-trace and re-compile. The compile ledger
# must record the recompiles STEADY, attribute them to the flush that
# paid (site=plane.flush, flush_seq joining /dump_flushes' comp_ms
# column), and the compile_storm incident must fire with the compile
# tail frozen in its snapshot.

from cometbft_tpu.libs import deviceledger, incidents  # noqa: E402

assert deviceledger.arm_compile_listener(), "jax is live here"
old_rec = incidents.install(incidents.IncidentRecorder(
    compile_storm=1, window_s=600.0, cooldown_s=0.0))
# the plane already declared steady itself (two successful fused
# collects back in the early phases) — assert that, then watermark the
# compile ring so the joins below only see phase-H records
assert deviceledger.is_steady(), \
    "the plane's own steady declaration never fired"
steady_before = deviceledger.counters()["steady_compiles"]
_pre = deviceledger.ledger().records()
watermark = _pre[-1]["seq"] if _pre else -1

pm._STEP_CACHE.clear()  # the round-5 regression, deliberately

plane_h = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True,
                      mesh_devices=0, mesh_min_rows=1,
                      breaker=cbatch.CircuitBreaker(failure_threshold=3,
                                                    cooldown=60.0))
plane_h.start()
groups_h = new_groups(THR)
verd_h = drive(plane_h, groups_h)
plane_h.stop()
incidents.poke()  # anchor the storm window
incidents.poke()  # evaluate it
assert verd_h == exp_verdicts, "memo break must not change verdicts"
steady_recompiles = \
    deviceledger.counters()["steady_compiles"] - steady_before
assert steady_recompiles >= 1, "broken memo never recompiled?"
comp_recs = [r for r in deviceledger.ledger().records()
             if r["seq"] > watermark and r["steady"]
             and r["site"] == "plane.flush"]
assert comp_recs, deviceledger.ledger().records()[-8:]
# the flush that paid: the compile record's flush_seq joins the flush
# ledger's comp_ms column (and the sharded flush measured util/dev_ms)
recs_h = {r["seq"]: r for r in plane_h.dump_flushes()["flushes"]}
paid = recs_h[comp_recs[0]["flush_seq"]]
assert paid["comp_ms"] > 0, paid
shard_h = [r for r in recs_h.values() if r["path"] == "fused_sharded"]
assert shard_h and all(r["util"] > 0 for r in shard_h), shard_h
assert all(r["dev_ms"] >= 0 for r in shard_h)
storm_snaps = [s for s in incidents.recorder().incidents()
               if s["trigger"] == "compile_storm"]
assert storm_snaps, incidents.recorder().incidents()
assert any("STEADY" in ln for ln in storm_snaps[0]["device_tail"]), \
    storm_snaps[0]["device_tail"]
incidents.install(old_rec)

# ---- phase I: stamped delta flush shards bit-identically ----------------
# (ISSUE 19) The stamping prologue is REAL here — pure XLA, no Pallas
# stub in its path: each device stamps its OWN rows slice from the
# per-row deltas against its OWN (M_s, 32) pub_raw shard, and the
# gathered matrix must be BIT-IDENTICAL to the single-device
# expansion. B == M (one row per table slot) so the oracle's
# `row mod M` validator gather and the shard-local `row mod M_s`
# gather address the same keys — the layout fused.shard_positions
# ships.

from cometbft_tpu.ops import ed25519_kernel as ek  # noqa: E402
from cometbft_tpu.types import canonical  # noqa: E402
from cometbft_tpu.types.block_id import (  # noqa: E402
    BlockID,
    PartSetHeader,
)
from cometbft_tpu.types.timestamp import Timestamp  # noqa: E402

M_I = 1024  # 4 shards x 256 stride
FUZZ_S = [0, 1, 127, 128, 16383, 16384, 1_700_000_000, 2 ** 31 - 1,
          2 ** 31, 2 ** 40, 2 ** 62, -1, -2 ** 33]
privs_i = [PrivKey.generate((7000 + i).to_bytes(4, "big") + b"\x33" * 28)
           for i in range(M_I // 16)]  # a live row every 16 slots
pubs_i = [b""] * M_I
for k, p in enumerate(privs_i):
    pubs_i[k * 16] = p.pub_key().data
bid_i = BlockID(b"\x19" * 32, PartSetHeader(4, b"\x91" * 32))
tmpl_i = canonical.VoteRowTemplate(
    "shard-chain", canonical.PRECOMMIT_TYPE, 5150, 0, bid_i)
ent_i = ec.template_entry([tmpl_i.stamp_site()])
sig_i = np.zeros((M_I, 64), np.uint8)
dts_i = np.zeros((M_I, 3), np.int32)
dfl_i = np.zeros((M_I,), np.int32)
for k, p in enumerate(privs_i):
    row = k * 16
    s = FUZZ_S[k % len(FUZZ_S)]
    nn = (k * 131) % 1_000_000_000
    msg = canonical.canonical_vote_bytes(
        "shard-chain", canonical.PRECOMMIT_TYPE, 5150, 0, bid_i,
        Timestamp(s, nn))
    sig_i[row] = np.frombuffer(p.sign(msg), np.uint8)
    dts_i[row, 0] = np.uint32(s & 0xFFFFFFFF).view(np.int32)
    dts_i[row, 1] = np.int32(s >> 32)
    dts_i[row, 2] = nn
    dfl_i[row] = 3  # live | counted
pub_raw_i = ec._pub_raw(pubs_i, M_I)
thr0_i = np.zeros((1, ek.TALLY_LIMBS), np.int32)
oracle_rows = np.asarray(ec._stamp_rows_jit(
    jnp.asarray(sig_i), jnp.asarray(dts_i), jnp.asarray(dfl_i),
    ent_i.pre_mat, ent_i.pre_len, ent_i.suf_mat, ent_i.suf_len,
    ent_i.ts_tag, pub_raw_i, jnp.asarray(thr0_i),
    msg_max=ent_i.msg_max, t_rows=1))
step_i = pm.sharded_stamp_rows(mesh4b, ent_i.msg_max)
shard_rows = np.asarray(step_i(
    sig_i, dts_i, dfl_i,
    np.asarray(ent_i.pre_mat), np.asarray(ent_i.pre_len),
    np.asarray(ent_i.suf_mat), np.asarray(ent_i.suf_len),
    np.asarray(ent_i.ts_tag), np.asarray(pub_raw_i)))
np.testing.assert_array_equal(shard_rows, oracle_rows)
assert shard_rows.any(), "stamped phase produced all-zero rows"

print(json.dumps({
    "ok": True,
    "stamped_shards_ok": True,
    "devices": len(jax.devices()),
    "verdicts": len(verd_m),
    "sharded_flushes": summary["shard"]["flushes"],
    "n_dev_max": summary["shard"]["n_dev_max"],
    "mesh_hits_gained": mesh_after["hits"] - mesh_before["hits"],
    "shard_table_hits_gained":
        tbl_after["shard_hits"] - tbl_before["shard_hits"],
    "deck": {
        "halves": [list(ids_lo), list(ids_hi)],
        "flight_dev0": [f1r["dev0"], f2r["dev0"]],
        "airborne_max": sum_p["deck"]["airborne_max"],
        "out_of_order_landing": True,
        "rotation_window_ok": True,
        "drain_first_ok": True,
        "mid_deck_fallbacks": len(fb_g),
    },
    "observatory": {
        "steady_recompiles": steady_recompiles,
        "storm_fired": len(storm_snaps),
        "paid_flush_comp_ms": paid["comp_ms"],
        "sharded_util": shard_h[0]["util"],
    },
}))
