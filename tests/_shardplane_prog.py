"""Subprocess body for tests/test_zshardplane_smoke.py.

Runs under a FORCED 4-virtual-device CPU mesh (the parent sets
XLA_FLAGS=--xla_force_host_platform_device_count=4, JAX_PLATFORMS=cpu)
and proves the verify plane's cross-chip sharded fused path without a
TPU: the two expensive device programs are stubbed — the Pallas cached
kernel by a precheck&ok plumbing fake and the XLA table build by a
shape-faithful fake — so what executes is exactly the machinery ISSUE
10 added: plan_fused's sharded scatter layout, per-shard table
assembly + (valset, mesh) memoization, the sharded_fused_verify step
(psum tally, replicated thresholds), ledger n_dev attribution, and the
breaker/PlaneOverloaded semantics around a faulting sharded dispatch.

Asserts, then prints one JSON summary line the parent test parses:
  * sharded verdicts, per-group tallies, and quorum bits are
    BIT-IDENTICAL to the single-device oracle (same stubs, one chip);
  * the second sharded flush HITs the mesh step memo and the sharded
    table memo (no steady-state re-trace or re-upload);
  * a faulting sharded dispatch degrades that flush with correct
    verdicts, trips the breaker, and BULK-lane PlaneOverloaded
    shedding still carries its retry hint.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

assert len(jax.devices()) == 4, jax.devices()
assert jax.default_backend() == "cpu"

from cometbft_tpu.crypto import batch as cbatch  # noqa: E402
from cometbft_tpu.crypto.keys import PrivKey  # noqa: E402
from cometbft_tpu.ops import ed25519_cached as ec  # noqa: E402
from cometbft_tpu.parallel import mesh as pm  # noqa: E402
from cometbft_tpu.verifyplane import fused as fz  # noqa: E402
from cometbft_tpu.verifyplane import (  # noqa: E402
    PlaneOverloaded,
    QuorumGroup,
    VerifyPlane,
)

# ---- stubs: the minutes-of-compile device programs, not the plumbing ----

from _kernel_stubs import fake_verify_tally_cached  # noqa: E402

fz.ALLOW_CPU_FUSED = True
ec._BASE60_F32 = np.zeros((32 * 256, ec.ROWS_PER_ENT), np.float32)
ec._verify_tally_cached = fake_verify_tally_cached

real_pack_pubs = ec._pack_pub_arrays


def fake_build_table(pub_bytes, powers=None):
    padded = ec.table_pad(len(pub_bytes))
    ok = np.zeros((padded,), np.bool_)
    ok[: len(pub_bytes)] = [len(p) == 32 for p in pub_bytes]
    return ec.ValsetTable(
        jnp.zeros((padded // 128 * ec.ENT_BLOCK, 128), jnp.int16),
        jnp.asarray(ok), ec._power_dev(powers, padded), padded,
        ec._pubs_host(pub_bytes, padded),
        ec._powers_host(powers, padded))


ec.build_table = fake_build_table

# ---- fixture: a 300-validator valset spanning 2 of 4 table shards ----

# shard stride 256 (table_pad bucket) -> only 2 shards hold validators
# (the second one partially), so effective_mesh must CLAMP the flush to
# a 2-device sub-mesh — empty shards would stage + verify pure padding
# every flush
NVALS = 300
EXPECT_NDEV = 2

mesh4 = fz.plane_mesh(0)
assert mesh4 is not None and mesh4.devices.size == 4
m_eff, n_eff, m_s_eff = fz.effective_mesh(mesh4, NVALS)
assert (n_eff, m_s_eff) == (EXPECT_NDEV, 256), (n_eff, m_s_eff)
assert m_eff.devices.size == EXPECT_NDEV
# a valset filling every stride keeps the full fan-out...
assert fz.effective_mesh(mesh4, 1024)[1] == 4
# ...and one that fits a single stride is single-device business
assert fz.effective_mesh(mesh4, 100)[0] is None
privs = [PrivKey.generate((4200 + i).to_bytes(4, "big") + b"\x77" * 28)
         for i in range(NVALS)]
pubs_t = tuple(p.pub_key().data for p in privs)
powers_t = tuple((i % 9 + 1) * 100 for i in range(NVALS))
submitters = list(range(0, NVALS, 7))  # spread across the shards

BAD_SIG = b"\x5a" * 32 + b"\xff" * 32  # S >= L: precheck AND ref reject


def make_batch(groups):
    """(rows, vidx, group, power, expected_verdicts) per submission:
    vote + extension rows; every 5th vote forged, every 11th extension
    forged (valid vote + forged ext => power must NOT stand)."""
    subs = []
    for j, v in enumerate(submitters):
        pub = privs[v].pub_key()
        m1 = b"vote-%d" % v
        m2 = b"ext-%d" % v
        s1 = BAD_SIG if j % 5 == 0 else privs[v].sign(m1)
        s2 = BAD_SIG if j % 11 == 3 else privs[v].sign(m2)
        exp = (j % 5 != 0, j % 11 != 3)
        subs.append(([(pub, m1, s1), (pub, m2, s2)], (v, v),
                     groups[v % 2], powers_t[v], exp))
    return subs


def drive(plane, groups):
    futs = [plane.submit_many(rows, power=pw, group=g, counted=True,
                              vidx=vidx)
            for rows, vidx, g, pw, _ in make_batch(groups)]
    return [f.result(30.0) for f in futs]


def expected():
    exp_verdicts = [e for *_, e in make_batch([None, None])]
    tallies = [0, 0]
    for (rows, vidx, _g, pw, e) in make_batch([None, None]):
        if all(e):
            tallies[vidx[0] % 2] += pw
    return exp_verdicts, tallies


def new_groups(thr):
    return [QuorumGroup(thr[c], valset_pubs=pubs_t,
                        valset_powers=powers_t) for c in range(2)]


exp_verdicts, exp_tallies = expected()
# one group crosses its threshold, the other misses it
THR = [exp_tallies[0], exp_tallies[1] + 1]

# ---- phase A: single-device oracle --------------------------------------

plane_s = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True)
plane_s.start()
groups_s = new_groups(THR)
verd_s = drive(plane_s, groups_s)
plane_s.stop()
assert verd_s == exp_verdicts, (verd_s, exp_verdicts)
assert [g.tally for g in groups_s] == exp_tallies
assert [g.quorum_reached for g in groups_s] == [True, False]
led_s = plane_s.dump_flushes()["flushes"]
assert any(r["path"] == "fused" and r["n_dev"] == 1 for r in led_s), led_s

# ---- phase B: sharded across the 4-device mesh, bit-identical -----------

plane_m = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True,
                      mesh_devices=0, mesh_min_rows=1)
plane_m.start()
groups_m = new_groups(THR)
verd_m = drive(plane_m, groups_m)
assert verd_m == verd_s, "sharded verdicts diverged from single-device"
assert [g.tally for g in groups_m] == [g.tally for g in groups_s]
assert [g.quorum_reached for g in groups_m] == \
    [g.quorum_reached for g in groups_s]

# ---- phase C: steady state hits every memo (no re-trace, no re-upload) --

mesh_before = pm.cache_stats()
tbl_before = ec.table_cache_stats()
groups_m2 = new_groups(THR)
verd_m2 = drive(plane_m, groups_m2)
assert verd_m2 == verd_s
mesh_after = pm.cache_stats()
tbl_after = ec.table_cache_stats()
assert mesh_after["hits"] > mesh_before["hits"]
assert mesh_after["misses"] == mesh_before["misses"], \
    "second sharded flush re-traced a mesh step"
assert tbl_after["shard_hits"] > tbl_before["shard_hits"]
assert tbl_after["shard_misses"] == tbl_before["shard_misses"], \
    "second sharded flush rebuilt the sharded table"

recs = plane_m.dump_flushes()["flushes"]
shard_recs = [r for r in recs if r["path"] == "fused_sharded"]
assert shard_recs and all(r["n_dev"] == EXPECT_NDEV
                          for r in shard_recs), recs
summary = plane_m.dump_flushes()["summary"]
assert summary["shard"]["flushes"] >= 2
assert summary["shard"]["n_dev_max"] == EXPECT_NDEV
stats = plane_m.stats()
# mesh_ndev reports the RESOLVED configured mesh; the ledger column
# reports the clamped per-flush fan-out
assert stats["mesh_ndev"] == 4 and stats["shard_flushes"] >= 2
plane_m.stop()

# ---- phase C2: an IN-FLIGHT sharded fault must not claim cross-chip -----
# (JAX async dispatch surfaces most device faults at collect, not
# dispatch: the record must repair to n_dev=1 host attribution and the
# shard counters must only ever count COMPLETED cross-chip passes)

real_collect = fz.collect_fused
fault = {"armed": True}


def faulty_collect(plan):
    if fault["armed"]:
        fault["armed"] = False
        raise RuntimeError("injected in-flight device fault")
    return real_collect(plan)


fz.collect_fused = faulty_collect
plane_c = VerifyPlane(
    window_ms=40.0, max_batch=4096, use_device=True, mesh_devices=0,
    mesh_min_rows=1,
    breaker=cbatch.CircuitBreaker(failure_threshold=3, cooldown=60.0))
plane_c.start()
groups_c = new_groups(THR)
verd_c = drive(plane_c, groups_c)
plane_c.stop()
fz.collect_fused = real_collect
assert verd_c == exp_verdicts, "in-flight fault changed verdicts"
assert [g.tally for g in groups_c] == exp_tallies
recs_c = plane_c.dump_flushes()["flushes"]
fallbacks = [r for r in recs_c if r["path"] == "fused_host_fallback"]
assert fallbacks and all(r["n_dev"] == 1 for r in fallbacks), recs_c
completed = [r for r in recs_c if r["path"] == "fused_sharded"]
assert plane_c.stats()["shard_flushes"] == len(completed), recs_c

# ---- phase D: a faulting sharded dispatch degrades, breaker + sheds -----


def host_direct(pubs, msgs, sigs, kernels=None, breaker=None):
    out = []
    for p, m, s in zip(pubs, msgs, sigs):
        try:
            out.append(bool(p.verify_signature(m, s)))
        except ValueError:
            out.append(False)
    return np.asarray(out, np.bool_)


cbatch.verify_batch_direct = host_direct
real_dispatch = fz.dispatch_fused


def faulting_dispatch(plan):
    raise RuntimeError("injected sharded device fault")


fz.dispatch_fused = faulting_dispatch
brk = cbatch.CircuitBreaker(failure_threshold=1, cooldown=60.0)
plane_f = VerifyPlane(window_ms=40.0, max_batch=4096, use_device=True,
                      mesh_devices=0, mesh_min_rows=1, breaker=brk,
                      bulk_max_queue=2, bulk_window_ms=10_000.0)
plane_f.start()
groups_f = new_groups(THR)
verd_f = drive(plane_f, groups_f)
# verdicts still correct (host fallback), tallies still land host-side
assert verd_f == exp_verdicts
assert [g.tally for g in groups_f] == exp_tallies
assert brk.state == "open", "sharded dispatch fault must trip the breaker"
recs_f = plane_f.dump_flushes()["flushes"]
assert any(r["path"] == "grouped" for r in recs_f), recs_f
assert not any(r["path"] == "fused_sharded" for r in recs_f)

# BULK shedding semantics are unchanged with a mesh configured: the
# lane bound still answers with an explicit retry-hinted verdict
# (bulk_window is 10s, so the queued row cannot drain underneath us)
p0 = privs[0]
row = (p0.pub_key(), b"bulk-0", p0.sign(b"bulk-0"))
plane_f.submit_many([row], lane="bulk")
try:
    plane_f.submit_many([row, row, row], lane="bulk", block=False)
    raise AssertionError("over-bound BULK submit was not shed")
except PlaneOverloaded as e:
    assert e.retry_after_ms > 0
assert plane_f.sheds["bulk"] >= 1
plane_f.stop()
fz.dispatch_fused = real_dispatch

print(json.dumps({
    "ok": True,
    "devices": len(jax.devices()),
    "verdicts": len(verd_m),
    "sharded_flushes": summary["shard"]["flushes"],
    "n_dev_max": summary["shard"]["n_dev_max"],
    "mesh_hits_gained": mesh_after["hits"] - mesh_before["hits"],
    "shard_table_hits_gained":
        tbl_after["shard_hits"] - tbl_before["shard_hits"],
}))
