"""Statesync end-to-end: snapshot restore + light verification + blocksync
handoff over real TCP p2p.

Reference: statesync/syncer_test.go case structure + the node start
sequencing of node/node.go:527.
"""
import time

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.statesync.syncer import StateSyncError, Syncer
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def test_syncer_rejects_tampered_snapshot():
    """A snapshot whose chunks don't hash to the advertised snapshot hash
    (or whose restored app hash disagrees with the trusted header) must be
    rejected (syncer.go verifyApp)."""
    src = KVStoreApplication()
    src.enable_snapshots(2)
    for h in range(1, 3):
        src.finalize_block(abci.RequestFinalizeBlock(
            txs=[b"a%d=%d" % (h, h)], height=h, hash=b"",
            proposer_address=b"", time_seconds=0))
        src.commit()
    snap = src.list_snapshots()[-1]

    class FakeProvider:
        def state_at(self, height):
            from cometbft_tpu.state.state import State as S

            st = S.make_genesis("x", ValidatorSet(
                [Validator(PrivKey.generate(b"\x01" * 32).pub_key(), 1)]
            ))
            from dataclasses import replace

            return replace(st, last_block_height=height,
                           app_hash=b"\xde\xad" * 16)  # wrong on purpose

    dst = KVStoreApplication()
    syncer = Syncer(dst, FakeProvider())
    syncer.add_snapshot(snap, lambda i: src.load_snapshot_chunk(
        snap.height, 1, i))
    with pytest.raises(StateSyncError):
        syncer.sync_any(discovery_time=0.1)


def _snapshot_source(n_blocks=4, period=2):
    src = KVStoreApplication()
    src.enable_snapshots(period)
    for h in range(1, n_blocks + 1):
        src.finalize_block(abci.RequestFinalizeBlock(
            txs=[b"k%d=%d" % (h, h)], height=h, hash=b"",
            proposer_address=b"", time_seconds=0))
        src.commit()
    return src, src.list_snapshots()[-1]


class _TrustingProvider:
    """state_at that trusts the source app (chunk-engine unit tests)."""

    def __init__(self, src):
        self.src = src

    def state_at(self, height):
        from dataclasses import replace

        st = State.make_genesis("x", ValidatorSet(
            [Validator(PrivKey.generate(b"\x01" * 32).pub_key(), 1)]
        ))
        info = self.src.info(abci.RequestInfo())
        return replace(st, last_block_height=height,
                       app_hash=info.last_block_app_hash)


def test_chunk_engine_corrupt_and_slow_providers():
    """Sync completes although one provider serves corrupt chunks (app
    rejects -> punished -> dropped) and another stalls past the chunk
    timeout; the honest provider fills every reclaimed slot."""
    src, snap = _snapshot_source()
    assert snap.chunks >= 1
    fetch_counts = {"evil": 0, "slow": 0, "good": 0}

    def evil(i):
        fetch_counts["evil"] += 1
        return b"\x00garbage"  # wrong hash -> app rejects

    def slow(i):
        fetch_counts["slow"] += 1
        time.sleep(5.0)
        return None

    def good(i):
        fetch_counts["good"] += 1
        return src.load_snapshot_chunk(snap.height, snap.format, i)

    dst = KVStoreApplication()
    syncer = Syncer(dst, _TrustingProvider(src), chunk_timeout=0.5)
    syncer.add_snapshot(snap, evil, provider_id="evil")
    syncer.add_snapshot(snap, slow, provider_id="slow")
    syncer.add_snapshot(snap, good, provider_id="good")
    state = syncer.sync_any(discovery_time=0.1)
    assert state.last_block_height == snap.height
    assert fetch_counts["good"] >= snap.chunks
    info = dst.info(abci.RequestInfo())
    assert info.last_block_app_hash == \
        src.info(abci.RequestInfo()).last_block_app_hash


def test_chunk_engine_all_providers_dead():
    src, snap = _snapshot_source()
    dst = KVStoreApplication()
    syncer = Syncer(dst, _TrustingProvider(src), chunk_timeout=0.2)
    syncer.add_snapshot(snap, lambda i: None, provider_id="dead")
    with pytest.raises(StateSyncError):
        syncer.sync_any(discovery_time=0.1)


def test_chunk_cache_survives_restart(tmp_path):
    """Chunks fetched before a crash are NOT refetched after restart:
    the cache dir re-seeds the queue (chunks.go load-from-disk)."""
    from cometbft_tpu.statesync.chunks import ChunkQueue

    src, snap = _snapshot_source(n_blocks=6, period=2)
    cache = str(tmp_path / "chunks")
    q1 = ChunkQueue(snap.chunks, cache_dir=f"{cache}/{snap.height}-1")
    i = q1.allocate()
    q1.add(i, src.load_snapshot_chunk(snap.height, snap.format, i), "p")
    # "crash": new queue over the same dir sees the chunk as received
    q2 = ChunkQueue(snap.chunks, cache_dir=f"{cache}/{snap.height}-1")
    assert q2.wait_for(i, 0.1) is not None
    assert q2.sender_of(i) == "cache"
    # and a full sync with the cache dir only fetches the missing ones
    fetches = []

    def good(j):
        fetches.append(j)
        return src.load_snapshot_chunk(snap.height, snap.format, j)

    dst = KVStoreApplication()
    syncer = Syncer(dst, _TrustingProvider(src), chunk_timeout=1.0,
                    cache_dir=cache)
    syncer.add_snapshot(snap, good, provider_id="good")
    syncer.sync_any(discovery_time=0.1)
    assert i not in fetches, "cached chunk was refetched"


def test_statesync_node_joins_over_p2p(tmp_path):
    """A fresh node statesyncs from a running net: snapshot restore at the
    snapshot height (NO early blocks fetched), blocksync for the tail,
    then live consensus (round-2 missing item 3)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("ss-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        app = KVStoreApplication()
        app.enable_snapshots(4)
        n = Node(app, state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x30 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    late = None
    try:
        nodes[0].dial(addrs[1])
        assert nodes[0].consensus.wait_for_height(2, timeout=120)
        nodes[0].broadcast_tx(b"a1=x1")
        # run past a snapshot height + the 2 extra light blocks the
        # state provider needs
        assert nodes[0].consensus.wait_for_height(8, timeout=120)

        # trusted light client over node0's RPC (the operator's trust root)
        from cometbft_tpu.light import client as lc
        from cometbft_tpu.rpc.client import light_provider

        url = nodes[0].rpc_listen()
        provider = light_provider("ss-chain", url)
        light = lc.Client("ss-chain", provider, trusting_period=1e6)
        light.trust_light_block(provider.light_block(1))

        late = Node(KVStoreApplication(), state.copy(),
                    home=str(tmp_path / "late"), timeouts=FAST, p2p=True,
                    blocksync=True, statesync_light_client=light,
                    node_key=NodeKey(PrivKey.generate(b"\x66" * 32)))
        late.listen()
        late.start()
        for a in addrs:
            late.dial(a)
        target = nodes[0].height() + 2
        deadline = time.time() + 120
        while time.time() < deadline and late.height() < target:
            time.sleep(0.2)
        assert late.height() >= target, \
            f"statesync node stuck at {late.height()} (target {target})"
        # proof it STATE-synced: no early blocks in its store (blocksync
        # from genesis would have block 2)
        assert late.block_store.load_block(2) is None
        # restored app state matches the network's
        assert late.query(b"a1").value == nodes[0].query(b"a1").value
    finally:
        for n in nodes:
            n.stop()
        if late is not None:
            late.stop()
