"""End-to-end consensus: single node, 4-validator in-process network,
crash + WAL replay.

Mirrors the reference's consensus test strategy (SURVEY.md §4):
in-process multi-validator networks (consensus/common_test.go
randConsensusNet analog = Node + LocalNetwork), deterministic-enough
timeouts, and replay tests (consensus/replay_test.go) that kill a node
and restart it from its WAL + stores.
"""
import threading

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def make_genesis(n_vals, chain_id="test-chain"):
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n_vals)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis(chain_id, vals)
    return state, privs


def test_single_node_produces_blocks(tmp_path):
    """One validator proposes and commits blocks through the kvstore ABCI
    app (BASELINE config #1 shape, n=1)."""
    state, privs = make_genesis(1)
    app = KVStoreApplication()
    node = Node(app, state, privval=FilePV(privs[0]),
                home=str(tmp_path / "n0"), timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(3, timeout=30)
        node.broadcast_tx(b"alpha=1")
        assert node.consensus.wait_for_height(node.height() + 2, timeout=30)
        assert node.query(b"alpha").value == b"1"
        # app hash advances and is persisted into state
        assert node.consensus.state.app_hash != b""
    finally:
        node.stop()


def test_four_validator_network(tmp_path):
    """4 validators over the in-memory hub: all reach height 5 and agree on
    the app state (the consensus/common_test.go randConsensusNet shape)."""
    state, privs = make_genesis(4)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        app = KVStoreApplication()
        node = Node(app, state.copy(), privval=FilePV(priv),
                    home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    try:
        nodes[0].broadcast_tx(b"k=v")
        for n in nodes:
            assert n.consensus.wait_for_height(5, timeout=60), \
                f"node stuck at {n.height()}"
        # all block stores agree on block 3's hash
        h3 = {n.block_store.load_block(3).hash() for n in nodes}
        assert len(h3) == 1
    finally:
        for n in nodes:
            n.stop()


def test_wal_crash_replay(tmp_path):
    """Kill a node mid-run; a fresh Node over the same home dir must
    resume from its persisted state + WAL and keep committing
    (consensus/replay_test.go crash/restart sims; replay.go:94)."""
    state, privs = make_genesis(1)
    home = str(tmp_path / "n0")
    app = KVStoreApplication()
    node = Node(app, state, privval=FilePV(privs[0]), home=home,
                timeouts=FAST)
    node.start()
    assert node.consensus.wait_for_height(3, timeout=30)
    node.broadcast_tx(b"persist=me")
    assert node.consensus.wait_for_height(node.height() + 2, timeout=30)
    crash_height = node.height()
    # abrupt stop: no graceful anything beyond thread teardown
    node.stop()

    # fresh app instance (lost its in-memory state) — handshake replays
    # stored blocks into it (node.py replay loop / consensus/replay.go:285)
    app2 = KVStoreApplication()
    node2 = Node(app2, state, privval=FilePV(privs[0]), home=home,
                 timeouts=FAST)
    assert node2.height() >= crash_height
    node2.start()
    try:
        assert node2.query(b"persist").value == b"me"
        assert node2.consensus.wait_for_height(crash_height + 2, timeout=30)
    finally:
        node2.stop()


class CommitInfoApp(KVStoreApplication):
    """An app whose state depends on FinalizeBlock's CommitInfo +
    misbehavior — the class of app that exposes replay divergence
    (fee distribution / slashing logic; consensus/replay.go:285)."""

    def finalize_block(self, req):
        import hashlib
        import json as _json

        resp = super().finalize_block(req)
        dlc = req.decided_last_commit
        blob = _json.dumps({
            "votes": [
                (v.validator_address.hex(), v.power, v.block_id_flag)
                for v in dlc.votes
            ] if dlc else None,
            "round": dlc.round if dlc else -1,
            "misbehavior": [
                (m.type, m.validator_address.hex(), m.height)
                for m in (req.misbehavior or [])
            ],
        }, sort_keys=True).encode()
        self.staged[b"ci:%08d" % req.height] = \
            hashlib.sha256(blob).hexdigest()[:16].encode()
        self._pending_hash = self._computed_staged_hash(req.height)
        resp.app_hash = self._pending_hash
        return resp


def test_replay_feeds_identical_commit_info(tmp_path):
    """Crash + handshake replay must hand the app the SAME
    decided_last_commit/misbehavior the live path did: an app that
    hashes CommitInfo reaches an identical app hash after replay
    (consensus/replay.go:285-360; round-3 weak item 6)."""
    state, privs = make_genesis(1)
    home = str(tmp_path / "n0")
    app = CommitInfoApp()
    node = Node(app, state, privval=FilePV(privs[0]), home=home,
                timeouts=FAST)
    node.start()
    assert node.consensus.wait_for_height(4, timeout=30)
    node.broadcast_tx(b"ci=live")
    assert node.consensus.wait_for_height(node.height() + 2, timeout=30)
    crash_height = node.height()
    live_hash = app.app_hash
    live_state_hash = node.consensus.state.app_hash
    node.stop()

    # fresh app: handshake replays every stored block into it
    app2 = CommitInfoApp()
    node2 = Node(app2, state, privval=FilePV(privs[0]), home=home,
                 timeouts=FAST)
    assert app2.height >= crash_height
    assert app2.app_hash == live_hash, \
        "replay diverged: app saw different CommitInfo than live"
    assert node2.consensus.state.app_hash == live_state_hash
    node2.start()
    try:
        # and the chain keeps committing on the replayed state
        assert node2.consensus.wait_for_height(crash_height + 2,
                                               timeout=30)
    finally:
        node2.stop()


@pytest.mark.slow
def test_hundred_blocks(tmp_path):
    """VERDICT item 6 acceptance: 100 blocks through ABCI, persisted."""
    state, privs = make_genesis(1)
    app = KVStoreApplication()
    node = Node(app, state, privval=FilePV(privs[0]),
                home=str(tmp_path / "n0"), timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(100, timeout=300)
        assert node.block_store.load_block(100) is not None
        assert node.block_store.load_seen_commit(100) is not None
    finally:
        node.stop()
