"""Bootstrap-plane recovery + serving-contract tests (ISSUE 18).

The restore side: a donor cut down mid-restore (the statesync.fetch
failpoint, kill-at-every-position matrix in test_wal_recovery.py's
style) leaves its chunks in the cache dir, and the restarted sync
refetches ONLY what the cache is missing. The serving side: the
ServeGate sheds over-budget peers with explicit retry-hinted verdicts
on the ledger clock, the p2p reactor turns those verdicts into
``chunk_shed`` messages the fetching peer honors as backoff (not
punishment), served chunks carry merkle inclusion proofs the client
verifies on arrival, and the snapshot.serve failpoint faults the
serving seam without touching anything else.
"""
import json
import os
import threading

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.statesync.p2p_reactor import (
    CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StatesyncP2PReactor)
from cometbft_tpu.statesync.snapshots import (
    ServeGate, SnapshotArchive, SnapshotServeOverloaded, proof_doc,
    verify_chunk)
from cometbft_tpu.statesync.syncer import StateSyncError, Syncer

N_CHUNKS = 6


class _ChunkApp:
    """Minimal restoring app: accepts chunks in order; info() reports
    the snapshot height + blob hash only once every chunk landed."""

    def __init__(self, snapshot, blob_hash):
        self.snapshot = snapshot
        self.blob_hash = blob_hash
        self.applied = []

    def offer_snapshot(self, snapshot):
        return True

    def apply_snapshot_chunk(self, idx, chunk, sender):
        self.applied.append(idx)
        return True

    def info(self, req):
        done = len(self.applied) >= self.snapshot.chunks
        return abci.ResponseInfo(
            last_block_height=self.snapshot.height if done else 0,
            last_block_app_hash=self.blob_hash if done else b"",
        )


class _Provider:
    def __init__(self, app_hash, height):
        self.app_hash = app_hash
        self.height = height

    def state_at(self, height):
        class _St:
            pass

        st = _St()
        st.app_hash = self.app_hash
        st.last_block_height = height
        return st


def _archive_snapshot(n_chunks=N_CHUNKS):
    """A merkle-rooted archive snapshot with n distinct 1KiB chunks."""
    arch = SnapshotArchive(chunk_size=1024)
    blob = b"".join(bytes([i]) * 1024 for i in range(n_chunks))
    snap = arch.generate(7, blob)
    assert snap.chunks == n_chunks
    return arch, snap, blob


def _restore(snap, fetch, cache_dir, chunk_timeout=0.3):
    app = _ChunkApp(snap, b"blob-ok")
    syncer = Syncer(app, _Provider(b"blob-ok", snap.height),
                    chunk_timeout=chunk_timeout, cache_dir=cache_dir)
    syncer.add_snapshot(snap, fetch, provider_id="donor")
    return syncer.sync_any(discovery_time=0.1), app


def test_kill_at_every_fetch_resumes_from_cache(tmp_path, monkeypatch):
    """Matrix over the statesync.fetch seam: kill the donor at EVERY
    fetch position (drop limit 1, so the k-th fetch is lethal and
    exactly k-1 chunks made it to the cache), then restart the restore
    over the same cache dir — the second run must refetch ONLY the
    chunks the first run never cached."""
    from cometbft_tpu.statesync import chunks as chunks_mod

    arch, snap, _ = _archive_snapshot()
    for k in range(1, N_CHUNKS + 1):
        cache = str(tmp_path / f"cache-{k}")
        served1 = []

        def fetch1(i):
            data = arch.load_chunk(snap.height, snap.format, i)
            served1.append(i)
            return data

        monkeypatch.setattr(chunks_mod, "MAX_PROVIDER_FAILURES", 1)
        fp.arm("statesync.fetch", "flake", k, count=1)
        try:
            with pytest.raises(StateSyncError):
                _restore(snap, fetch1, cache)
        finally:
            fp.disarm("statesync.fetch")
            monkeypatch.undo()
        assert len(served1) == k - 1, f"k={k}: died at the wrong fetch"
        cached = set()
        for sub in os.listdir(cache):
            for f in os.listdir(os.path.join(cache, sub)):
                cached.add(int(f.split("-")[1]))
        assert cached == set(served1), f"k={k}: cache != served"
        assert len(cached) < N_CHUNKS  # it really did die mid-restore

        fetched2 = []

        def fetch2(i):
            fetched2.append(i)
            return arch.load_chunk(snap.height, snap.format, i)

        state, app = _restore(snap, fetch2, cache)
        assert state.last_block_height == snap.height
        assert set(app.applied) == set(range(N_CHUNKS))
        refetched = set(fetched2) & cached
        assert not refetched, \
            f"k={k}: refetched cached chunks {sorted(refetched)}"
        assert set(fetched2) == set(range(N_CHUNKS)) - cached, f"k={k}"


def test_serve_gate_sheds_with_exact_retry_hint():
    """Over-budget admits raise SnapshotServeOverloaded whose
    retry_after_ms names the exact wait until the next token — on the
    virtual clock, waiting precisely that long readmits."""
    now = [10 ** 12]
    tracing.set_clock(lambda: now[0])
    try:
        ss_stats.reset()
        gate = ServeGate(rate_per_s=10.0, burst=2)
        gate.admit("peer-a")
        gate.admit("peer-a")
        with pytest.raises(SnapshotServeOverloaded) as ei:
            gate.admit("peer-a")
        hint_ms = ei.value.retry_after_ms
        assert hint_ms == pytest.approx(100.0)  # 1 token at 10/s
        gate.admit("peer-b", kind="snapshot")  # other peers unaffected
        # waiting 1ms short of the hint still sheds; the hint readmits
        now[0] += int((hint_ms - 1.0) * 1e6)
        with pytest.raises(SnapshotServeOverloaded):
            gate.admit("peer-a")
        now[0] += int(1e6 + (hint_ms - 1.0) * 1e6)
        gate.admit("peer-a")
        st = gate.stats()
        assert st["served"] == 4 and st["sheds"] == 2
        c = ss_stats.stats()
        assert c["chunks_shed"] == 2 and c["snapshots_shed"] == 0
    finally:
        tracing.set_clock(None)


def test_serve_gate_peer_table_is_bounded():
    gate = ServeGate(max_peers=8)
    for i in range(50):
        gate.admit(f"sybil-{i}")
    assert gate.stats()["peers"] <= 8


class _FakePeer:
    def __init__(self, node_id):
        self.node_id = node_id
        self.sent = []

    def send(self, chan, msg):
        self.sent.append((chan, json.loads(msg.decode())))


class _FakeSwitch:
    def __init__(self):
        self.stopped = []

    def stop_peer_for_error(self, peer, err):
        self.stopped.append((peer.node_id, str(err)))


def _donor_reactor(gate=None):
    arch, snap, blob = _archive_snapshot()

    class _NoSnapApp:
        def list_snapshots(self):
            return []

    r = StatesyncP2PReactor(_NoSnapApp(), gate=gate, archive=arch)
    r.switch = _FakeSwitch()
    return r, snap, blob


def test_reactor_serves_proofs_then_sheds_with_hint():
    """Within budget a chunk_req is answered with data + a merkle
    proof that verifies against the offer root; over budget it is
    answered with an explicit chunk_shed carrying the retry hint —
    never silence, never a stopped peer."""
    r, snap, _ = _donor_reactor(gate=ServeGate(rate_per_s=8.0, burst=2))
    peer = _FakePeer("bootstrapper")
    for i in range(2):
        r.receive(CHUNK_CHANNEL, peer, json.dumps(
            {"t": "chunk_req", "h": snap.height, "f": snap.format,
             "i": i}).encode())
    import base64 as b64
    for i, (chan, msg) in enumerate(peer.sent):
        assert (chan, msg["t"]) == (CHUNK_CHANNEL, "chunk")
        data = b64.b64decode(msg["data"])
        assert data == bytes([i]) * 1024
        assert verify_chunk(snap.hash, data, msg["proof"])
    r.receive(CHUNK_CHANNEL, peer, json.dumps(
        {"t": "chunk_req", "h": snap.height, "f": snap.format,
         "i": 2}).encode())
    chan, shed = peer.sent[-1]
    assert shed["t"] == "chunk_shed" and shed["i"] == 2
    assert shed["retry_after_ms"] > 0
    assert r.switch.stopped == []  # a shed is a verdict, not an error


def test_reactor_snapshot_offers_carry_merkle_root():
    r, snap, _ = _donor_reactor()
    peer = _FakePeer("asker")
    r.receive(SNAPSHOT_CHANNEL, peer,
              json.dumps({"t": "snapshots_req"}).encode())
    offers = [m for c, m in peer.sent if m["t"] == "snapshot"]
    assert len(offers) == 1
    assert bytes.fromhex(offers[0]["root"]) == snap.hash
    assert offers[0]["c"] == N_CHUNKS


def test_snapshot_serve_failpoint_faults_the_serving_seam():
    """snapshot.serve raising after gate admission rides the reactor's
    malformed-message path: the requesting peer is stopped, nothing
    else breaks, and the next request (failpoint disarmed) serves."""
    r, snap, _ = _donor_reactor()
    peer = _FakePeer("victim")
    req = json.dumps({"t": "chunk_req", "h": snap.height,
                      "f": snap.format, "i": 0}).encode()
    fp.arm("snapshot.serve", "raise", count=1)
    try:
        r.receive(CHUNK_CHANNEL, peer, req)
    finally:
        fp.disarm("snapshot.serve")
    assert [m["t"] for c, m in peer.sent] == []  # nothing served
    assert len(r.switch.stopped) == 1
    r.receive(CHUNK_CHANNEL, peer, req)
    assert [m["t"] for c, m in peer.sent] == ["chunk"]


def test_fetch_chunk_honors_shed_hint_then_succeeds():
    """The client side of the shed contract: a chunk_shed answer makes
    _fetch_chunk wait the hinted backoff and RE-REQUEST from the same
    donor (no punish), and the retried chunk verifies against the
    root."""
    arch, snap, _ = _archive_snapshot()
    r = StatesyncP2PReactor(app=None)
    r.switch = _FakeSwitch()
    peer = _FakePeer("donor")
    result = []

    def run():
        result.append(r._fetch_chunk(peer, snap, 0, timeout=5.0,
                                     root=snap.hash))

    th = threading.Thread(target=run, daemon=True)
    th.start()
    deadline = 50
    while len(peer.sent) < 1 and deadline:
        threading.Event().wait(0.02)
        deadline -= 1
    assert peer.sent[0][1]["t"] == "chunk_req"
    r.receive(CHUNK_CHANNEL, peer, json.dumps(
        {"t": "chunk_shed", "h": snap.height, "f": snap.format,
         "i": 0, "retry_after_ms": 5.0}).encode())
    deadline = 100
    while len(peer.sent) < 2 and deadline:
        threading.Event().wait(0.02)
        deadline -= 1
    assert len(peer.sent) == 2, "shed hint did not trigger a retry"
    import base64 as b64
    data = arch.load_chunk(snap.height, snap.format, 0)
    r.receive(CHUNK_CHANNEL, peer, json.dumps(
        {"t": "chunk", "h": snap.height, "f": snap.format, "i": 0,
         "data": b64.b64encode(data).decode(),
         "proof": proof_doc(arch.proof_for(snap.height, snap.format,
                                           0))}).encode())
    th.join(timeout=5.0)
    assert result == [data]


def test_fetch_chunk_rejects_bad_proof():
    """A chunk that fails merkle verification against the offer root
    returns None — the fetcher punishes exactly this sender."""
    arch, snap, _ = _archive_snapshot()
    r = StatesyncP2PReactor(app=None)
    r.switch = _FakeSwitch()
    peer = _FakePeer("liar")
    result = []

    def run():
        result.append(r._fetch_chunk(peer, snap, 1, timeout=5.0,
                                     root=snap.hash))

    th = threading.Thread(target=run, daemon=True)
    th.start()
    deadline = 50
    while len(peer.sent) < 1 and deadline:
        threading.Event().wait(0.02)
        deadline -= 1
    import base64 as b64
    r.receive(CHUNK_CHANNEL, peer, json.dumps(
        {"t": "chunk", "h": snap.height, "f": snap.format, "i": 1,
         "data": b64.b64encode(b"poison").decode(),
         "proof": proof_doc(arch.proof_for(snap.height, snap.format,
                                           1))}).encode())
    th.join(timeout=5.0)
    assert result == [None]


def test_archive_retention_is_bounded():
    arch = SnapshotArchive(keep=3, chunk_size=512)
    for h in range(1, 6):
        arch.generate(h, bytes([h]) * 2048)
    snaps = arch.list_snapshots()
    assert [s.height for s in snaps] == [3, 4, 5]
    # evicted snapshots serve nothing; retained ones round-trip
    assert arch.load_chunk(1, 2, 0) == b""
    assert arch.proof_for(1, 2, 0) is None
    s5 = snaps[-1]
    blob = b"".join(arch.load_chunk(5, s5.format, i)
                    for i in range(s5.chunks))
    assert blob == bytes([5]) * 2048
