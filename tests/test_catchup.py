"""Catch-up firehose engine tests (ISSUE 18 tentpole).

Pins the archival replay contracts directly against a real-signed
in-memory history: fused segments never pack across a valset
boundary, warm-ahead hands the NEXT epoch's valset to the warmer
BEFORE the replay cursor reaches the boundary, and — the
crash-resume heart of the thing — a kill at EVERY read-ahead
position (the catchup.read_ahead failpoint, test_wal_recovery.py's
kill-at-every-failpoint style) resumes from the persisted cursor
re-verifying ZERO already-verified blocks. Plus the cursor's
corrupt/torn-file conservatism, the bounded always-on ledger and its
/dump_catchup document, and the catchup_stall incident on a frozen
ledger.
"""
import json

import pytest

from cometbft_tpu.blocksync import catchup as cu
from cometbft_tpu.blocksync.catchup import (
    CatchupCursor, CatchupEngine, CatchupError, CatchupLedger,
    HostCommitVerifier, StoreHistorySource)
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import incidents, tracing
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import Block, Data, Header
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_COMMIT, Commit, CommitSig)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet

CHAIN = "catchup-chain"
N_BLOCKS = 10
EPOCH_LEN = 4


def make_history(n_blocks=N_BLOCKS, n_vals=3, epoch_len=EPOCH_LEN,
                 chain_id=CHAIN):
    """Real ed25519-signed history with per-epoch valset rotation;
    returns (items={h: (block, commit)}, vals_at)."""
    n_epochs = n_blocks // epoch_len + 2
    epochs = []
    for e in range(n_epochs):
        privs = [PrivKey.generate(bytes([60 + e, i + 1]) + b"\x19" * 30)
                 for i in range(n_vals)]
        vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
        epochs.append((vs, {p.pub_key().address(): p for p in privs}))

    def vals_at(h):
        return epochs[min((h - 1) // epoch_len, n_epochs - 1)][0]

    items = {}
    last_bid = None
    for h in range(1, n_blocks + 1):
        vs, by_addr = epochs[min((h - 1) // epoch_len, n_epochs - 1)]
        hdr = Header(chain_id=chain_id, height=h,
                     time=Timestamp(1700000000 + h, 0),
                     validators_hash=vs.hash(),
                     next_validators_hash=vals_at(h + 1).hash(),
                     proposer_address=vs.validators[0].address)
        if last_bid is not None:
            hdr.last_block_id = last_bid
        blk = Block(hdr, Data())
        blk.fill_header()
        bid = blk.block_id()
        sigs = []
        for v in vs.validators:
            ts = Timestamp(1700000000 + h, 1)
            sb = canonical.canonical_vote_bytes(
                chain_id, canonical.PRECOMMIT_TYPE, h, 0, bid, ts)
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                  by_addr[v.address].sign(sb)))
        items[h] = (blk, Commit(h, 0, bid, sigs))
        last_bid = bid
    return items, vals_at


@pytest.fixture(scope="module")
def history():
    return make_history()


class _Source:
    def __init__(self, items):
        self.items = items

    def base(self):
        return min(self.items)

    def tip(self):
        return max(self.items)

    def load(self, h):
        if h not in self.items:
            raise CatchupError(f"history missing block {h}")
        return self.items[h]


class _State:
    __slots__ = ("chain_id", "last_block_height", "validators",
                 "next_validators")

    def __init__(self, chain_id, h, validators, next_validators):
        self.chain_id = chain_id
        self.last_block_height = h
        self.validators = validators
        self.next_validators = next_validators


class _Warmer:
    def __init__(self):
        self.requests = []  # (valset_hash, chain_id)

    def request_valset(self, vals, chain_id=None):
        self.requests.append((vals.hash(), chain_id))


class _CountingVerifier(HostCommitVerifier):
    def __init__(self):
        self.heights = []

    def verify(self, jobs):
        self.heights.extend(j.height for j in jobs)
        return super().verify(jobs)


def _engine(items, vals_at, *, start=0, cursor_path=None,
            read_ahead=3, max_run=3, verifier=None, warmer=None,
            warm_ahead=True, on_apply=None):
    state = _State(CHAIN, start, vals_at(start + 1), vals_at(start + 2))

    def apply_fn(st, blk, commit):
        h = blk.header.height
        if on_apply is not None:
            on_apply(h)
        return _State(st.chain_id, h, vals_at(h + 1), vals_at(h + 2))

    return CatchupEngine(
        _Source(items), state, apply_fn=apply_fn,
        verifier=verifier or HostCommitVerifier(),
        cursor_path=cursor_path, read_ahead=read_ahead,
        max_run=max_run, warm_ahead=warm_ahead,
        warmer=warmer or _Warmer())


def test_replays_history_to_tip(history):
    items, vals_at = history
    eng = _engine(items, vals_at)
    final = eng.run()
    assert final.last_block_height == N_BLOCKS
    c = eng.ledger.counters
    assert c["blocks_applied"] == N_BLOCKS
    assert c["blocks_verified"] == N_BLOCKS
    assert c["blocks_skipped"] == 0
    assert c["sigs_verified"] == N_BLOCKS * 3  # every val signed
    assert eng.cursor.verified == eng.cursor.applied == N_BLOCKS


def test_segments_never_cross_valset_boundaries(history):
    """The pre-scan bounds every fused flush at the first
    validators_hash change: record (first, last) always lies inside
    one epoch, and the flush that hit the wall carries boundary=True."""
    items, vals_at = history
    eng = _engine(items, vals_at, read_ahead=8, max_run=8)
    eng.run()
    recs = eng.ledger.records()
    for r in recs:
        assert (r["first"] - 1) // EPOCH_LEN == \
            (r["last"] - 1) // EPOCH_LEN, r
    walls = [r for r in recs if r["boundary"]]
    # epochs end inside the history at 4 and 8
    assert sorted(r["last"] for r in walls) == [4, 8]
    assert eng.ledger.counters["boundaries"] == 2


def test_warm_ahead_fires_before_the_boundary(history):
    """The next epoch's valset reaches the warmer while the replay
    cursor is still BELOW the boundary — the table builds ahead."""
    items, vals_at = history
    cursor_h = [0]
    warmer = _Warmer()
    # record the replay height at which each warm request landed
    orig = warmer.request_valset

    def stamped(vals, chain_id=None):
        warmer.requests.append((vals.hash(), cursor_h[0]))
    warmer.request_valset = stamped
    eng = _engine(items, vals_at, warmer=warmer,
                  on_apply=lambda h: cursor_h.__setitem__(0, h))
    eng.run()
    del orig
    by_hash = {h: at for h, at in warmer.requests}
    # boundary into epoch 1 is at height 5; its valset warmed earlier
    assert by_hash[vals_at(5).hash()] < 5
    assert by_hash[vals_at(9).hash()] < 9
    assert eng.ledger.counters["warm_requests"] >= 2


def test_warm_ahead_off_means_no_requests(history):
    items, vals_at = history
    warmer = _Warmer()
    eng = _engine(items, vals_at, warmer=warmer, warm_ahead=False)
    eng.run()
    assert warmer.requests == []
    assert eng.ledger.counters["warm_requests"] == 0


def test_kill_at_every_read_resumes_reverifying_zero(history, tmp_path):
    """The matrix: crash at read-ahead position K for EVERY K, resume
    from the persisted cursor, and prove the second run re-verifies
    not one block at or below the crash-time verified mark."""
    items, vals_at = history
    for k in range(1, N_BLOCKS + 1):
        cpath = str(tmp_path / f"cursor-{k}.json")
        eng1 = _engine(items, vals_at, cursor_path=cpath)
        fp.arm("catchup.read_ahead", "flake", k, count=1)
        try:
            with pytest.raises(fp.FailpointError):
                eng1.run()
        finally:
            fp.disarm("catchup.read_ahead")
        verified1, applied1 = eng1.cursor.verified, eng1.cursor.applied
        assert applied1 <= verified1 < N_BLOCKS

        v2 = _CountingVerifier()
        eng2 = _engine(items, vals_at, start=applied1,
                       cursor_path=cpath, verifier=v2)
        assert eng2.cursor.resumed, f"k={k}: cursor did not resume"
        assert eng2.ledger.counters["resumes"] == 1
        final = eng2.run()
        assert final.last_block_height == N_BLOCKS
        reverified = [h for h in v2.heights if h <= verified1]
        assert reverified == [], \
            f"k={k}: resume re-verified {reverified}"
        # heights in (applied, verified] replay WITHOUT verification
        assert eng2.ledger.counters["blocks_skipped"] == \
            verified1 - applied1, f"k={k}"
        assert eng2.ledger.counters["blocks_applied"] == \
            N_BLOCKS - applied1, f"k={k}"


def test_bad_signature_raises_with_height():
    items, vals_at = make_history(n_blocks=6, epoch_len=100)
    sig = items[4][1].signatures[0]
    sig.signature = sig.signature[:10] + \
        bytes([sig.signature[10] ^ 1]) + sig.signature[11:]
    eng = _engine(items, vals_at)
    with pytest.raises(CatchupError, match="height 4"):
        eng.run()
    # verified mark never advanced past the poisoned flush
    assert eng.cursor.verified < 4


def test_wrong_resume_state_is_corrupt_history(history):
    """A resume state whose valset does not match the next block's
    validators_hash must fail loudly, not verify against the wrong
    keys."""
    items, vals_at = history
    state = _State(CHAIN, 2, vals_at(99), vals_at(99))
    eng = CatchupEngine(_Source(items), state,
                        apply_fn=lambda s, b, c: s,
                        verifier=HostCommitVerifier(),
                        warmer=_Warmer())
    with pytest.raises(CatchupError, match="corrupt history"):
        eng.run()


def test_history_gap_raises(history):
    items, vals_at = history
    gappy = dict(items)
    del gappy[7]
    eng = _engine(gappy, vals_at)
    with pytest.raises(CatchupError, match="missing block 7"):
        eng.run()


def test_store_history_source_contract():
    class _EmptyStore:
        def base(self):
            return 1

        def height(self):
            return 3

        def load_block(self, h):
            return None

        def load_block_commit(self, h):
            return None

    src = StoreHistorySource(_EmptyStore())
    assert src.tip() == 3
    with pytest.raises(CatchupError, match="missing block 1"):
        src.load(1)


def test_cursor_roundtrip_and_corrupt_file(tmp_path):
    path = str(tmp_path / "cursor.json")
    c = CatchupCursor(path)
    assert (c.verified, c.applied, c.resumed) == (0, 0, False)
    c.verified, c.applied = 42, 40
    c.save()
    c2 = CatchupCursor(path)
    assert (c2.verified, c2.applied, c2.resumed) == (42, 40, True)
    # torn/corrupt file: resume conservatively from zero, never crash
    with open(path, "w") as f:
        f.write("{not json")
    c3 = CatchupCursor(path)
    assert (c3.verified, c3.applied, c3.resumed) == (0, 0, False)
    # pathless cursor is inert
    CatchupCursor(None).save()


def test_ledger_ring_bounded_and_summary():
    led = CatchupLedger(capacity=8)
    for i in range(20):
        led.record(first=i, last=i, blocks=1, sigs=3, skipped=0,
                   read_ms=1.0, verify_ms=2.0, apply_ms=0.5,
                   boundary=(i % 5 == 0), warmed=False)
    assert len(led) == 8  # ring bounded; counters cumulative
    assert led.counters["flushes"] == 20
    assert led.counters["blocks_applied"] == 20
    assert led.counters["boundaries"] == 4
    s = led.summary()
    assert s["window_flushes"] == 8
    assert s["verify_ms_total"] == pytest.approx(16.0)
    assert [r["seq"] for r in led.tail(3)] == [17, 18, 19]
    m = led.mark()
    assert not led.advanced(m)
    led.record(first=99, last=99, blocks=1, sigs=0, skipped=0,
               read_ms=0, verify_ms=0, apply_ms=0,
               boundary=False, warmed=False)
    assert led.advanced(m)


def test_dump_catchup_document(history):
    items, vals_at = history
    old_g, old_l = cu._GLOBAL, cu._LAST
    try:
        cu.set_global_ledger(None)
        cu._LAST = None
        assert cu.dump_catchup() == {"records": [], "summary": {},
                                     "counters": {}}
        eng = _engine(items, vals_at)
        eng.run()  # run() installs its ledger as the process-global
        doc = cu.dump_catchup()
        assert doc["counters"]["blocks_applied"] == N_BLOCKS
        assert doc["records"] and doc["summary"]["flushes"] >= 1
        json.dumps(doc)  # the /dump_catchup body must serialize
        assert cu.ledger_tail(2) == doc["records"][-2:]
    finally:
        cu._GLOBAL, cu._LAST = old_g, old_l


def test_catchup_stall_incident_fires_on_frozen_ledger():
    """Catch-up ACTIVE + no ledger advance past catchup_stall_s fires
    catchup_stall (with the ledger tail in the snapshot); progress
    notes and deactivation both re-arm the window. Driven entirely on
    a virtual clock — the satellite-1 contract that stall detection
    works under simnet."""
    now = [10 ** 12]
    tracing.set_clock(lambda: now[0])
    old_g, old_l = cu._GLOBAL, cu._LAST
    try:
        led = CatchupLedger()
        led.record(first=1, last=2, blocks=2, sigs=6, skipped=0,
                   read_ms=0, verify_ms=0, apply_ms=0,
                   boundary=False, warmed=False)
        cu.set_global_ledger(led)
        rec = incidents.IncidentRecorder(catchup_stall_s=5.0)
        rec.poke()  # clock-domain change: re-arms every window
        rec.note_catchup(True)
        now[0] += int(4e9)
        rec.poke()
        assert rec.fired.get("catchup_stall") is None  # within limit
        now[0] += int(2e9)  # 6s since the last note: stalled
        rec.poke()
        assert rec.fired.get("catchup_stall") == 1
        snap = rec.incidents()[-1]
        assert snap["trigger"] == "catchup_stall"
        assert snap["detail"]["stalled_s"] == pytest.approx(6.0)
        assert snap["catchup_tail"], "ledger tail missing from snapshot"
        # progress re-arms; inactive never fires however stale
        rec.note_catchup(True)
        now[0] += int(3e9)
        rec.poke()
        assert rec.fired.get("catchup_stall") == 1
        rec.note_catchup(False)
        now[0] += int(60e9)
        rec.poke()
        assert rec.fired.get("catchup_stall") == 1
    finally:
        tracing.set_clock(None)
        cu._GLOBAL, cu._LAST = old_g, old_l
