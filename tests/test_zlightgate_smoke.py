"""Tier-1 guard for the light-client gateway RPC surface (ISSUE 8
satellite): the lightgate_* routes end-to-end against an in-process
node — host paths only, NO jax import, seconds not minutes. Late in
the alphabet like test_zloadtime_smoke/test_zbench_smoke: by the time
this runs, the unit tests have localized any real breakage.
"""
import json
import sys
import urllib.request

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config.config import LightGateConfig
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.rpc.client import HTTPClient
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


@pytest.fixture()
def gateway_node(tmp_path):
    priv = PrivKey.generate(b"\x5a" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("lightgate-rpc-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=FAST,
                lightgate=LightGateConfig(enable=True, cache_size=64))
    node.start()
    url = node.rpc_listen()
    try:
        assert node.consensus.wait_for_height(3, timeout=60)
        yield node, url, priv
    finally:
        node.stop()


def test_lightgate_rpc_end_to_end(gateway_node):
    jax_loaded_before = "jax" in sys.modules
    node, url, priv = gateway_node
    c = HTTPClient(url)

    # the gateway mounted with the node and registered globally
    from cometbft_tpu.lightgate import global_gateway

    assert node.lightgate is not None
    assert global_gateway() is node.lightgate

    # verify: client trusts height 1, wants the tip
    tip = node.block_store.height()
    v = c.call("lightgate_verify", trusted_height=1, target_height=tip)
    assert v["status"] == "verified"
    assert v["height"] == tip
    assert v["target"]["signed_header"]["header"]["height"] == tip

    # repeat sync over the popular pair: pure cache hit
    v2 = c.call("lightgate_verify", trusted_height=1, target_height=tip)
    assert v2["cached"] is True
    assert v2["target_hash"] == v["target_hash"]

    # batched header serving, range form + explicit list + cap
    hs = c.call("lightgate_headers", min_height=1, max_height=tip)
    assert [h["height"] for h in hs["headers"]] == list(range(1, tip + 1))
    hs2 = c.call("lightgate_headers", heights=[1, tip, 999_999],
                 with_validators=True)
    assert hs2["missing"] == [999_999]
    assert len(hs2["headers"][0]["validators"]) == 1

    # a forged claim (lying primary) yields a divergent verdict and
    # LightClientAttackEvidence in the node's pool
    from cometbft_tpu.simnet.actors import forged_claim
    from cometbft_tpu.types.evidence import LightClientAttackEvidence
    from cometbft_tpu.types.timestamp import Timestamp

    claim = forged_claim([priv], node.consensus.state.validators,
                         "lightgate-rpc-chain", [0], tip,
                         Timestamp.now())
    dv = c.call("lightgate_verify", trusted_height=1, target_height=tip,
                claimed=claim)
    assert dv["status"] == "divergent"
    assert dv["evidence_added"] is True
    evs = node.evidence_pool.pending_evidence()
    assert any(isinstance(e, LightClientAttackEvidence) for e in evs)

    # status + scrape-time metrics
    st = c.call("lightgate_status")
    assert st["requests"] >= 3 and st["verifies"] >= 1
    assert st["cache"]["hits"] >= 1
    with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
        metrics = r.read().decode()
    assert 'cometbft_lightgate_cache_total{kind="hits"}' in metrics
    assert 'cometbft_lightgate_requests_total{kind="verifies"}' in metrics

    # GET (URI) form works too
    with urllib.request.urlopen(
        f"{url}/lightgate_verify?trusted_height=1&target_height={tip}",
        timeout=5,
    ) as r:
        j = json.loads(r.read().decode())
    assert j["result"]["status"] == "verified"

    # host-only contract: serving light clients must never pull in jax
    if not jax_loaded_before:
        assert "jax" not in sys.modules, "lightgate smoke imported jax"


def test_lightgate_routes_error_without_gateway(tmp_path):
    """A node without [lightgate] answers the routes with a clear
    error instead of AttributeError soup."""
    priv = PrivKey.generate(b"\x5b" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("nogw-chain", vals)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n1"), timeouts=FAST)
    node.start()
    url = node.rpc_listen()
    try:
        assert node.consensus.wait_for_height(1, timeout=60)
        c = HTTPClient(url)
        with pytest.raises(Exception, match="no light-client gateway"):
            c.call("lightgate_status")
    finally:
        node.stop()
