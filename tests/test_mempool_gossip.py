"""Mempool gossip send-state, gas-aware reap, and AppConns.

Reference: mempool/reactor.go (per-peer send state — no echo to the
sender, each tx at most once per peer), clist_mempool.go:519
ReapMaxBytesMaxGas, proxy/multi_app_conn.go (four logical conns).
"""
import threading

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.proxy import AppConns
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.mempool.reactor import MempoolReactor


class GasApp(KVStoreApplication):
    def check_tx(self, req):
        r = super().check_tx(req)
        r.gas_wanted = 10
        return r


class _FakePeer:
    def __init__(self, name):
        self.name = name
        self.got = []

    def send(self, chan_id, msg):
        self.got.append(msg)
        return True


class _FakeSwitch:
    def __init__(self, peers):
        self.peers = {p.name: p for p in peers}
        self._peers_lock = threading.Lock()


def test_reap_max_gas():
    mp = Mempool(GasApp())
    for i in range(10):
        assert mp.check_tx(b"k%d=v" % i).code == 0
    assert len(mp.reap()) == 10
    # 10 gas per tx: a 35-gas budget admits exactly 3
    assert len(mp.reap(max_gas=35)) == 3
    assert mp.reap(max_gas=0) == []
    assert len(mp.reap(max_bytes=11)) == 2  # byte cap still applies


def test_no_echo_and_once_per_peer():
    mp = Mempool(KVStoreApplication())
    r = MempoolReactor(mp)
    a, b, c = _FakePeer("a"), _FakePeer("b"), _FakePeer("c")
    r.switch = _FakeSwitch([a, b, c])
    for p in (a, b, c):
        r.add_peer(p)
    # tx arrives from a: relayed to b and c, never echoed to a
    r.receive(0x30, a, b"x=1")
    assert a.got == []
    assert b.got == [b"x=1"] and c.got == [b"x=1"]
    # duplicate delivery from another peer: no re-send anywhere
    r.receive(0x30, b, b"x=1")
    assert b.got == [b"x=1"] and c.got == [b"x=1"] and a.got == []
    # local broadcast of a second tx reaches everyone exactly once
    assert mp.check_tx(b"y=2").code == 0
    r.broadcast_tx(b"y=2")
    r.broadcast_tx(b"y=2")
    assert a.got == [b"y=2"] and b.got.count(b"y=2") == 1


def test_new_peer_gets_existing_pool():
    mp = Mempool(KVStoreApplication())
    r = MempoolReactor(mp)
    assert mp.check_tx(b"old=1").code == 0
    late = _FakePeer("late")
    r.switch = _FakeSwitch([late])
    r.add_peer(late)
    assert late.got == [b"old=1"]


def test_app_conns_in_process_serializes():
    """Four conns over one app share one mutex — concurrent calls on
    different conns never interleave inside the app."""
    inside = []

    class Probe(KVStoreApplication):
        def check_tx(self, req):
            inside.append(1)
            try:
                assert inside.count(1) - inside.count(-1) == 1, \
                    "concurrent entry into non-thread-safe app"
                return super().check_tx(req)
            finally:
                inside.append(-1)

    conns = AppConns.in_process(Probe())
    errs = []

    def hammer(conn):
        try:
            for i in range(50):
                conn.check_tx(abci.RequestCheckTx(tx=b"a=b"))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=hammer, args=(c,))
          for c in (conns.consensus, conns.mempool, conns.query,
                    conns.snapshot)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs


def test_app_conns_socket_four_connections():
    from cometbft_tpu.abci.server import ABCISocketServer

    srv = ABCISocketServer(KVStoreApplication())
    srv.start()
    try:
        host, port = srv.addr[:2]
        conns = AppConns.socket(host, port)
        # each logical conn works independently, incl. the snapshot family
        assert conns.query.info(abci.RequestInfo()).last_block_height == 0
        assert conns.mempool.check_tx(
            abci.RequestCheckTx(tx=b"s=1")
        ).code == 0
        assert conns.snapshot.list_snapshots() == []
        assert conns.snapshot.load_snapshot_chunk(1, 1, 0) == b""
        assert conns.consensus.extend_vote(
            abci.RequestExtendVote(height=1)
        ).vote_extension == b""
        conns.close()
    finally:
        srv.stop()
