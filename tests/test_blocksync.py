"""Blocksync: fused multi-commit stream verification, catch-up from a
peer's block store, bad-peer banning.

Mirrors blocksync/reactor_test.go + pool_test.go structure: a real chain
is produced by a single-validator node, then fresh nodes catch up from
peers serving that store."""
import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.blocksync.pipeline import CommitJob, StreamVerifier
from cometbft_tpu.blocksync.reactor import BlocksyncReactor
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import State, StateStore
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types import validation
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                     prevote_delta=0.1, precommit=0.2, precommit_delta=0.1,
                     commit=0.01)
CHAIN_HEIGHT = 24


@pytest.fixture(scope="module")
def chain(tmp_path_factory):
    """A real 24-block chain + its genesis state, produced by one node."""
    home = str(tmp_path_factory.mktemp("chain") / "n0")
    priv = PrivKey.generate(b"\x55" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    genesis = State.make_genesis("sync-chain", vals)
    node = Node(KVStoreApplication(), genesis, privval=FilePV(priv),
                home=home, timeouts=FAST)
    node.start()
    assert node.consensus.wait_for_height(CHAIN_HEIGHT + 1, timeout=60)
    node.stop()
    store = BlockStore(home + "/blockstore.db")
    return genesis, store


def serve_from(store, reactor, peer_id, height):
    """Wire a BlockStore up as a peer: requests are served synchronously."""
    def request(h):
        blk = store.load_block(h)
        if blk is not None:
            reactor.receive_block(peer_id, blk)

    reactor.add_peer(peer_id, height, request)


def fresh_reactor(chain, tmp_path, name="sync"):
    from dataclasses import replace

    from cometbft_tpu.abci.types import RequestInitChain

    genesis, _ = chain
    app = KVStoreApplication()
    ri = app.init_chain(RequestInitChain(chain_id=genesis.chain_id))
    state = genesis.copy()
    if ri.app_hash:
        state = replace(state, app_hash=ri.app_hash)
    state_store = StateStore(str(tmp_path / f"{name}-state.db"))
    block_store = BlockStore(str(tmp_path / f"{name}-blocks.db"))
    block_exec = BlockExecutor(app, state_store, mempool=Mempool(app))
    return BlocksyncReactor(state, block_exec, block_store,
                            StreamVerifier(use_pallas=False))


def test_stream_verifier_multi_commit(chain):
    """Many commits fused into one device pass: per-commit quorum bits and
    exact blame indices."""
    genesis, store = chain
    jobs = []
    for h in range(1, 9):
        blk = store.load_block(h)
        commit = store.load_seen_commit(h)
        jobs.append(CommitJob(genesis.validators, blk.block_id(), h, commit,
                              genesis.chain_id))
    sv = StreamVerifier(use_pallas=False)
    assert sv.verify(jobs) == [None] * 8

    # tamper job 3's signature; truncate job 5's quorum (absent-ify)
    import copy

    bad = copy.deepcopy(jobs)
    sig = bytearray(bad[3].commit.signatures[0].signature)
    sig[7] ^= 1
    bad[3].commit.signatures[0].signature = bytes(sig)
    bad[5].commit.signatures[0].flag = 1  # BLOCK_ID_FLAG_ABSENT
    bad[5].commit.signatures[0].signature = b""
    res = sv.verify(bad)
    assert res[0] is None and res[7] is None
    assert isinstance(res[3], validation.InvalidSignatureError)
    assert res[3].idx == 0
    assert isinstance(res[5], validation.NotEnoughPowerError)


def test_catchup_from_one_peer(chain, tmp_path):
    genesis, store = chain
    reactor = fresh_reactor(chain, tmp_path, "one")
    caught = []
    reactor.on_caught_up = lambda st: caught.append(st.last_block_height)
    serve_from(store, reactor, "peer-a", CHAIN_HEIGHT)
    reactor.start()
    try:
        assert reactor.wait_caught_up(30)
        # blocksync applies up to maxPeerHeight-1; consensus takes over for
        # the tip (pool.go IsCaughtUp semantics)
        assert reactor.height() == CHAIN_HEIGHT - 1
        assert caught and caught[0] == CHAIN_HEIGHT - 1
        assert reactor.block_store.load_block(CHAIN_HEIGHT - 1) is not None
    finally:
        reactor.stop()


@pytest.fixture(autouse=True)
def clean_failpoints():
    fp.reset()
    yield
    fp.reset()


def test_pool_request_timeout_reassigns_and_drops_peer():
    """A peer that never answers times out: its heights are released
    with backoff and go to another peer; after PEER_TIMEOUT_LIMIT
    consecutive timeouts the dead peer leaves the pool entirely
    (pool.go requestRetrySeconds/redo analog)."""
    import time as _t

    from cometbft_tpu.blocksync import pool as poolmod

    pool = poolmod.BlockPool(1, request_timeout=0.03)
    dead_reqs = []
    pool.set_peer_range("dead", 10, lambda h: dead_reqs.append(h))
    assert pool.make_requests() > 0
    assert pool.peer_of(1) == "dead"

    # keep sweeping until the unresponsive peer is evicted: a strike
    # lands at most once per sweep, and between strikes the requester
    # must wait out its backoff and get re-assigned to the dead peer
    deadline = _t.time() + 10
    while pool.num_peers() > 0:
        assert _t.time() < deadline, "dead peer never evicted"
        _t.sleep(0.04)
        pool.make_requests()
    assert pool.num_peers() == 0

    # a live peer picks the heights up once their backoff lapses
    served = []

    def serve(h):
        served.append(h)

    pool.set_peer_range("live", 10, serve)
    deadline = _t.time() + 5
    while 1 not in served and _t.time() < deadline:
        pool.make_requests()
        _t.sleep(0.02)
    assert 1 in served
    assert pool.peer_of(1) == "live"


def test_sync_completes_with_flaky_requests_and_deliveries(
        chain, tmp_path, monkeypatch):
    """Failpoint-injected request loss (every 2nd request never sent)
    AND delivery loss (every 3rd arriving block dropped) must only slow
    the sync down — the timeout/backoff machinery re-requests until the
    chain is complete. This is the blocksync arm of the ISSUE's
    'survive each injection' requirement. (Peer eviction is pinned off:
    in production, periodic status messages re-register dropped peers;
    this test has no status stream, and eviction is unit-covered in
    test_pool_request_timeout_reassigns_and_drops_peer.)"""
    from cometbft_tpu.blocksync import pool as poolmod

    monkeypatch.setattr(poolmod, "PEER_TIMEOUT_LIMIT", 10 ** 9)
    genesis, store = chain
    reactor = fresh_reactor(chain, tmp_path, "flaky")
    reactor.pool.request_timeout = 0.05
    fp.arm("blocksync.request", "flake", arg=2)
    fp.arm("blocksync.deliver", "flake", arg=3)
    serve_from(store, reactor, "peer-a", CHAIN_HEIGHT)
    reactor.start()
    try:
        assert reactor.wait_caught_up(60), \
            f"flaky sync wedged at {reactor.height()}"
        assert reactor.height() == CHAIN_HEIGHT - 1
    finally:
        fp.reset()
        reactor.stop()


def test_transient_local_process_fault_retries_without_ban(chain,
                                                           tmp_path):
    """blocksync.process raising (injected local verify/apply fault)
    must retry the run without banning the serving peer."""
    genesis, store = chain
    reactor = fresh_reactor(chain, tmp_path, "transient")
    fp.arm("blocksync.process", "raise", count=2)
    serve_from(store, reactor, "peer-a", CHAIN_HEIGHT)
    reactor.start()
    try:
        assert reactor.wait_caught_up(60)
        assert reactor.height() == CHAIN_HEIGHT - 1
        assert "peer-a" not in reactor.banned_peers
    finally:
        fp.reset()
        reactor.stop()


def test_bad_peer_banned_good_peer_completes(chain, tmp_path):
    genesis, store = chain
    reactor = fresh_reactor(chain, tmp_path, "ban")

    class EvilStore:
        """Serves block 5 with a corrupted LastCommit for block 4."""

        def load_block(self, h):
            blk = store.load_block(h)
            if blk is not None and h == 5 and blk.last_commit.signatures:
                import copy

                blk = copy.deepcopy(blk)
                sig = bytearray(blk.last_commit.signatures[0].signature)
                sig[3] ^= 0xFF
                blk.last_commit.signatures[0].signature = bytes(sig)
            return blk

    serve_from(EvilStore(), reactor, "evil", CHAIN_HEIGHT)
    reactor.start()
    try:
        # evil is the only peer: the corrupted LastCommit must get it banned
        import time

        deadline = time.time() + 20
        while "evil" not in reactor.banned_peers:
            assert time.time() < deadline, "evil peer never banned"
            time.sleep(0.02)
        # an honest peer then completes the sync
        serve_from(store, reactor, "good", CHAIN_HEIGHT)
        assert reactor.wait_caught_up(30)
        assert reactor.height() == CHAIN_HEIGHT - 1
        assert "evil" in reactor.banned_peers
    finally:
        reactor.stop()
