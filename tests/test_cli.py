"""CLI + config: init/start/testnet drive real validators from home dirs.

Reference: cmd/cometbft/commands (init.go, run_node.go, testnet.go) and
config/config.go ValidateBasic.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from cometbft_tpu.config.config import (
    Config,
    ConfigError,
    load_config,
    save_config,
)
from cometbft_tpu.cmd import cli


def test_config_roundtrip(tmp_path):
    cfg = Config()
    cfg.base.chain_id = "roundtrip"
    cfg.crypto.verifier = "cpu"
    cfg.consensus.timeout_propose = 1.5
    p = str(tmp_path / "config.toml")
    save_config(cfg, p)
    got = load_config(p)
    assert got.base.chain_id == "roundtrip"
    assert got.crypto.verifier == "cpu"
    assert got.consensus.timeout_propose == 1.5

    cfg.crypto.verifier = "gpu"
    with pytest.raises(ConfigError):
        cfg.validate_basic()


def test_init_start_rpc(tmp_path):
    """`init` then `start`: the validator commits blocks and serves RPC
    (the round-2 verdict item 8 done-condition)."""
    home = str(tmp_path / "node")
    assert cli.main(["init", "--home", home, "--chain-id", "cli-chain",
                     "--verifier", "cpu"]) == 0
    # speed up consensus + pick free ports for the test
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    cfg.consensus.timeout_propose = 0.4
    cfg.consensus.timeout_commit = 0.01
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.base.blocksync = False
    save_config(cfg, os.path.join(home, "config", "config.toml"))

    node, cfg = cli.build_node(home)
    node.start()
    try:
        url = node.rpc_listen()
        assert node.consensus.wait_for_height(2, timeout=60)
        with urllib.request.urlopen(f"{url}/status", timeout=5) as r:
            j = json.loads(r.read().decode())
        assert j["result"]["sync_info"]["latest_block_height"] >= 2
        assert j["result"]["node_info"]["network"] == "cli-chain"
    finally:
        node.stop()


def test_testnet_generation(tmp_path):
    out = str(tmp_path / "net")
    assert cli.main(["testnet", "--v", "3", "--output", out,
                     "--chain-id", "net-chain"]) == 0
    geneses = set()
    for i in range(3):
        home = os.path.join(out, f"node{i}")
        cfg = load_config(os.path.join(home, "config", "config.toml"))
        assert cfg.base.chain_id == "net-chain"
        peers = cfg.p2p.persistent_peers.split(",")
        assert len(peers) == 2  # wired to the other two
        with open(os.path.join(home, "config", "genesis.json")) as f:
            geneses.add(f.read())
    assert len(geneses) == 1  # identical genesis everywhere
    from cometbft_tpu.types.genesis import GenesisDoc

    doc = GenesisDoc.from_file(
        os.path.join(out, "node0", "config", "genesis.json"))
    assert len(doc.validators) == 3
