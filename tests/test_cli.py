"""CLI + config: init/start/testnet drive real validators from home dirs.

Reference: cmd/cometbft/commands (init.go, run_node.go, testnet.go) and
config/config.go ValidateBasic.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from cometbft_tpu.config.config import (
    Config,
    ConfigError,
    load_config,
    save_config,
)
from cometbft_tpu.cmd import cli


def test_config_roundtrip(tmp_path):
    cfg = Config()
    cfg.base.chain_id = "roundtrip"
    cfg.crypto.verifier = "cpu"
    cfg.consensus.timeout_propose = 1.5
    p = str(tmp_path / "config.toml")
    save_config(cfg, p)
    got = load_config(p)
    assert got.base.chain_id == "roundtrip"
    assert got.crypto.verifier == "cpu"
    assert got.consensus.timeout_propose == 1.5

    cfg.crypto.verifier = "gpu"
    with pytest.raises(ConfigError):
        cfg.validate_basic()


def test_init_start_rpc(tmp_path):
    """`init` then `start`: the validator commits blocks and serves RPC
    (the round-2 verdict item 8 done-condition)."""
    home = str(tmp_path / "node")
    assert cli.main(["init", "--home", home, "--chain-id", "cli-chain",
                     "--verifier", "cpu"]) == 0
    # speed up consensus + pick free ports for the test
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    cfg.consensus.timeout_propose = 0.4
    cfg.consensus.timeout_commit = 0.01
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.base.blocksync = False
    save_config(cfg, os.path.join(home, "config", "config.toml"))

    node, cfg = cli.build_node(home)
    node.start()
    try:
        url = node.rpc_listen()
        assert node.consensus.wait_for_height(2, timeout=60)
        with urllib.request.urlopen(f"{url}/status", timeout=5) as r:
            j = json.loads(r.read().decode())
        assert j["result"]["sync_info"]["latest_block_height"] >= 2
        assert j["result"]["node_info"]["network"] == "cli-chain"
    finally:
        node.stop()


def test_testnet_generation(tmp_path):
    out = str(tmp_path / "net")
    assert cli.main(["testnet", "--v", "3", "--output", out,
                     "--chain-id", "net-chain"]) == 0
    geneses = set()
    for i in range(3):
        home = os.path.join(out, f"node{i}")
        cfg = load_config(os.path.join(home, "config", "config.toml"))
        assert cfg.base.chain_id == "net-chain"
        peers = cfg.p2p.persistent_peers.split(",")
        assert len(peers) == 2  # wired to the other two
        with open(os.path.join(home, "config", "genesis.json")) as f:
            geneses.add(f.read())
    assert len(geneses) == 1  # identical genesis everywhere
    from cometbft_tpu.types.genesis import GenesisDoc

    doc = GenesisDoc.from_file(
        os.path.join(out, "node0", "config", "genesis.json"))
    assert len(doc.validators) == 3


def test_reindex_event_rebuilds_indexes(tmp_path):
    """reindex_event.go: wipe tx_index.db + block_index.db, reindex
    from the block store + stored FinalizeBlock responses, and
    tx_search/the tx route serve the same answers as before."""
    import os

    home = str(tmp_path / "n0")
    assert cli.main(["init", "--home", home, "--chain-id", "ri-chain",
                     "--verifier", "cpu"]) == 0
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    cfg.consensus.timeout_propose = 0.4
    cfg.consensus.timeout_commit = 0.01
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.base.blocksync = False
    save_config(cfg, os.path.join(home, "config", "config.toml"))
    node, _ = cli.build_node(home)
    node.start()
    try:
        node.broadcast_tx(b"ri=1")
        assert node.consensus.wait_for_height(node.height() + 2,
                                              timeout=60)
        import hashlib

        txh = hashlib.sha256(b"ri=1").hexdigest().upper()
        got = node.tx_indexer.get(bytes.fromhex(txh))
        assert got is not None
        h_indexed = got["height"]
    finally:
        node.stop()

    # wipe the indexes, then reindex from stores
    data = os.path.join(home, "data")
    for n in ("tx_index.db", "block_index.db"):
        os.remove(os.path.join(data, n))
    assert cli.main(["reindex-event", "--home", home]) == 0

    from cometbft_tpu.state.indexer import BlockIndexer, TxIndexer

    txi = TxIndexer(os.path.join(data, "tx_index.db"))
    got = txi.get(bytes.fromhex(txh))
    assert got is not None and got["height"] == h_indexed
    assert txi.search(f"tx.height={h_indexed}")
    bli = BlockIndexer(os.path.join(data, "block_index.db"))
    assert h_indexed in bli.search(f"block.height={h_indexed}")
    txi.close(); bli.close()


def test_debug_dump_and_kill(tmp_path):
    """debug.go: dump collects status/net_info/consensus/stacks from a
    live node's (unsafe) RPC; kill writes the zip and signals the pid
    (we hand it a throwaway child process)."""
    import json as _json
    import os
    import subprocess
    import sys as _sys
    import zipfile

    home = str(tmp_path / "nd")
    assert cli.main(["init", "--home", home, "--chain-id", "dbg-chain",
                     "--verifier", "cpu"]) == 0
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    cfg.consensus.timeout_propose = 0.4
    cfg.consensus.timeout_commit = 0.01
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.base.blocksync = False
    save_config(cfg, os.path.join(home, "config", "config.toml"))
    node, _ = cli.build_node(home)
    node.start()
    url = node.rpc_listen(unsafe=True)
    try:
        assert node.consensus.wait_for_height(2, timeout=60)
        out = str(tmp_path / "snaps")
        assert cli.main(["debug", "dump", out, "--home", home,
                         "--rpc-laddr", url, "--frequency", "0.1",
                         "--count", "1"]) == 0
        snaps = os.listdir(out)
        assert len(snaps) == 1
        files = set(os.listdir(os.path.join(out, snaps[0])))
        assert {"status.json", "consensus_state.json",
                "stacks.txt", "config.toml"} <= files
        st = _json.load(open(os.path.join(out, snaps[0],
                                          "status.json")))
        assert st["result"]["node_info"]["network"] == "dbg-chain"

        child = subprocess.Popen([_sys.executable, "-c",
                                  "import time; time.sleep(60)"])
        zpath = str(tmp_path / "dump.zip")
        assert cli.main(["debug", "kill", str(child.pid), zpath,
                         "--home", home, "--rpc-laddr", url]) == 0
        assert child.wait(timeout=10) != 0  # SIGTERM'd
        with zipfile.ZipFile(zpath) as z:
            assert "status.json" in z.namelist()
    finally:
        node.stop()
