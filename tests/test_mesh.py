"""Sharded verify+tally over the virtual 8-device CPU mesh."""
import pytest
import numpy as np

import jax

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k
from cometbft_tpu.parallel import mesh as pm


"""Both CPU cases are slow-marked: with the jax<0.5 shard_map shim these
now actually COMPILE on old containers (they used to fail fast on the
missing jax.shard_map attribute), and an 8-virtual-device compile of the
full verify graph costs multi-minute wall on a 1-core host. The driver's
dryrun_multichip covers the sharded paths in the quick gate."""


def test_rows_builders_memoized_and_share_verify_program():
    """ISSUE 3 satellite (round-5 MULTICHIP regression): repeated
    builder calls return the SAME compiled closure, and every tally
    width reuses ONE Pallas verify step per mesh — no per-call
    shard_map rebuilds. Pure cache identity, no compiles."""
    mesh = pm.make_mesh()
    assert pm.sharded_verify_tally_rows(mesh, 1) is \
        pm.sharded_verify_tally_rows(mesh, 1)
    assert pm.sharded_verify_tally(mesh, 2) is \
        pm.sharded_verify_tally(mesh, 2)
    assert pm.sharded_stream_verify(mesh, 4) is \
        pm.sharded_stream_verify(mesh, 4)
    # an equivalent mesh (same devices/axes) hits the same entries
    assert pm.sharded_verify_tally_rows(pm.make_mesh(), 1) is \
        pm.sharded_verify_tally_rows(mesh, 1)
    # n_commits=1 and n_commits=16 share the expensive verify program
    pm.sharded_verify_tally_rows(mesh, 16)
    assert pm._STEP_CACHE[("rows", pm._mesh_key(mesh), 1)] is not \
        pm._STEP_CACHE[("rows", pm._mesh_key(mesh), 16)]
    assert pm._sharded_verify_rows_step(mesh) is \
        pm._sharded_verify_rows_step(mesh)
    assert sum(1 for key in pm._STEP_CACHE
               if key[0] == "pallas-verify") == 1


def test_step_cache_hit_counters():
    """ISSUE 4 satellite (MULTICHIP_r05 rc=124 guard): the memoized
    builders expose hit/miss counters, and REPEATED builder calls are
    observable HITS — a regression back to per-call shard_map rebuilds
    would show up as misses here (and as minutes of recompile on the
    harness). Pure cache identity, no compiles."""
    mesh = pm.make_mesh()
    pm.sharded_verify_tally(mesh, 3)  # ensure the entry exists
    before = pm.cache_stats()
    for _ in range(4):
        pm.sharded_verify_tally(mesh, 3)
    after = pm.cache_stats()
    assert after["hits"] >= before["hits"] + 4
    assert after["misses"] == before["misses"]
    # a NEW width is one miss (the cheap tally step), then hits
    pm.sharded_verify_tally(mesh, 5)
    mid = pm.cache_stats()
    assert mid["misses"] == after["misses"] + 1
    pm.sharded_verify_tally(mesh, 5)
    assert pm.cache_stats()["hits"] == mid["hits"] + 1


def test_rows_split_plumbing_with_stub_kernel(monkeypatch):
    """Execute the split verify->tally pipeline over the 8-device mesh
    with a STUB verify kernel (the real Pallas program costs minutes of
    interpret compile on CPU): the per-device column extraction, psum,
    limb carry, and quorum plumbing must tally exactly."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_pallas as kp

    def fake_verify(rows, base):
        return (rows[kp.C_CID] & 1) == 0  # even commits "verify"

    fake_verify.__wrapped__ = fake_verify
    monkeypatch.setattr(kp, "_verify_rows", fake_verify)
    pm._STEP_CACHE.clear()
    try:
        mesh = pm.make_mesh()
        n_dev = len(jax.devices())
        n_commits = 4
        n = n_dev * kp.B_TILE
        keys = [PrivKey.generate(i.to_bytes(4, "big") + b"\x33" * 28)
                for i in range(8)]
        pubs = [keys[i % 8].pub_key().data for i in range(n)]
        msgs = [b"stub-%d" % i for i in range(n)]
        sigs = [b"\x00" * 64] * n  # content is irrelevant to the stub
        pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
        powers = np.full((n,), 7, np.int64)
        power5 = k.power_limbs(powers)
        counted = np.ones((n,), np.bool_)
        cids = (np.arange(n, dtype=np.int32) % n_commits)
        thresh = k.threshold_limbs(1, n_commits)
        rows = kp.pack_rows(pb, power5, counted, cids, thresh)
        rows[kp.C_THRESH:] = 0
        from jax.sharding import NamedSharding, PartitionSpec as P

        step = pm.sharded_verify_tally_rows(mesh, n_commits)
        rows_d = jax.device_put(
            rows, NamedSharding(mesh, P(None, mesh.axis_names[0])))
        valid, tally, quorum = jax.block_until_ready(
            step(rows_d, kp.base_f32(), thresh))
        v = np.asarray(valid)[:n]
        np.testing.assert_array_equal(v, cids % 2 == 0)
        t = k.tally_to_int(np.asarray(tally))
        per_commit = n // n_commits * 7
        assert [int(x) for x in t] == [
            per_commit if c % 2 == 0 else 0 for c in range(n_commits)
        ]
        q = np.asarray(quorum)
        assert list(q) == [c % 2 == 0 for c in range(n_commits)]
    finally:
        pm._STEP_CACHE.clear()  # stub-compiled steps must not leak


@pytest.mark.slow
def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    n = 24
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [b"commit-sig-%d" % i for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    sigs[4] = sigs[4][:8] + bytes([sigs[4][8] ^ 2]) + sigs[4][9:]

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=64)
    powers = np.arange(1, n + 1, dtype=np.int64) * 1000
    power5 = np.zeros((pb.padded, k.POWER_LIMBS), np.int32)
    power5[:n] = k.power_limbs(powers)
    counted = np.zeros((pb.padded,), np.bool_)
    counted[:n] = True
    commit_ids = np.zeros((pb.padded,), np.int32)
    commit_ids[n // 2 :] = 1
    thresh = np.zeros((2, k.TALLY_LIMBS), np.int32)
    thresh[0, 0] = 1
    thresh[1, 0] = 2

    mesh = pm.make_mesh()
    step = pm.sharded_verify_tally(mesh, n_commits=2)
    pb2, args = pm.shard_batch_arrays(mesh, pb, power5, counted, commit_ids)
    valid, tally, quorum = step(*args, thresh)

    exp_valid = np.array([i != 4 for i in range(n)])
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp_valid)
    t = k.tally_to_int(np.asarray(tally))
    exp0 = sum(int(powers[i]) for i in range(n // 2) if i != 4)
    exp1 = sum(int(powers[i]) for i in range(n // 2, n))
    assert int(t[0]) == exp0 and int(t[1]) == exp1
    assert bool(quorum[0]) and bool(quorum[1])


@pytest.mark.slow
def test_sharded_pallas_rows():
    """The flagship Mosaic kernel under shard_map: a 1024-row packed
    batch lane-sharded over the 8-device mesh, per-device Pallas tiles,
    psum tally (round-2 verdict item 7)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_pallas as kp

    n_dev = len(jax.devices())
    n = n_dev * kp.B_TILE
    keys = [PrivKey.generate(i.to_bytes(4, "big") + b"\x19" * 28)
            for i in range(n)]
    pubs = [q.pub_key().data for q in keys]
    msgs = [b"sharded-%d" % i for i in range(n)]
    sigs = [q.sign(m) for q, m in zip(keys, msgs)]
    sigs[7] = sigs[7][:12] + bytes([sigs[7][12] ^ 1]) + sigs[7][13:]
    sigs[900 % n] = b"\x00" * 64

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
    powers = np.full((n,), 10, np.int64)
    power5 = k.power_limbs(powers)
    counted = np.ones((n,), np.bool_)
    cids = np.zeros((n,), np.int32)
    thresh = k.threshold_limbs(int(powers.sum()) * 2 // 3)
    rows = kp.pack_rows(pb, power5, counted, cids, thresh)
    rows[kp.C_THRESH:] = 0  # thresholds ride separately when sharded

    mesh = pm.make_mesh()
    step = pm.sharded_verify_tally_rows(mesh, n_commits=1)
    rows_d = jax.device_put(
        rows, NamedSharding(mesh, P(None, mesh.axis_names[0]))
    )
    valid, tally, quorum = jax.block_until_ready(
        step(rows_d, kp.base_f32(), thresh)
    )
    exp = np.ones(n, bool)
    exp[[7, 900 % n]] = False
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp)
    assert k.tally_to_int(np.asarray(tally))[0] == int(powers.sum()) - 20
    assert bool(np.asarray(quorum)[0])


@pytest.mark.skipif(
    not __import__("os").environ.get("CBT_TEST_ON_TPU"),
    reason="cached kernel under shard_map: pallas-interpret compile "
           "takes hours on CPU (see test_ed25519_cached.py); the "
           "8-device CPU dryrun covers it via __graft_entry__."
)
def test_sharded_stream_cached_multi_commit():
    """The blocksync streaming shape multi-device: a 16-commit chunk of
    one 128-validator valset through the cached-table kernel, sharded
    2 commits/device over the 8-mesh, per-commit psum tallies; one bad
    signature flips exactly its commit's row and no quorum bit (each
    commit has 128/128 power, so one loss still clears 2/3)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.parallel import mesh as pm

    mesh = pm.make_mesh(jax.devices()[:8])
    n_commits = 16
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(128)]
    pubs = [k.pub_key().data for k in keys]
    table = ec.build_table(pubs, [10] * 128)
    M = table.n_vals
    B = n_commits * M
    spubs, smsgs, ssigs = [], [], []
    for c in range(n_commits):
        for i, k in enumerate(keys):
            m = b"mesh-stream-%d-%d" % (c, i)
            spubs.append(pubs[i])
            smsgs.append(m)
            ssigs.append(k.sign(m))
    bad = 5 * M + 17  # commit 5, validator 17
    ssigs[bad] = b"\x01" * 64
    pb = ek.pack_batch(spubs, smsgs, ssigs, pad_to=B)
    counted = np.ones((B,), np.bool_)
    cids = np.repeat(np.arange(n_commits, dtype=np.int32), M)
    thresh = ek.threshold_limbs(128 * 10 * 2 // 3, n_commits)
    rows = ec.pack_rows_cached(pb, counted, cids, thresh)
    step = pm.sharded_stream_verify(mesh, n_commits)
    rows_d = jax.device_put(
        rows, NamedSharding(mesh, P(None, mesh.axis_names[0])))
    valid, tally, quorum = jax.block_until_ready(
        step(rows_d, table.tab, table.ok, table.power5,
             ec.base60_f32(), thresh))
    v = np.asarray(valid)
    assert not v[bad] and v.sum() == B - 1
    t = ek.tally_to_int(np.asarray(tally))
    assert int(t[5]) == 127 * 10
    assert all(int(t[c]) == 128 * 10 for c in range(n_commits) if c != 5)
    assert np.asarray(quorum).all()
