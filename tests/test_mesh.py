"""Sharded verify+tally over the virtual 8-device CPU mesh."""
import numpy as np

import jax

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k
from cometbft_tpu.parallel import mesh as pm


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    n = 24
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [b"commit-sig-%d" % i for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    sigs[4] = sigs[4][:8] + bytes([sigs[4][8] ^ 2]) + sigs[4][9:]

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=64)
    powers = np.arange(1, n + 1, dtype=np.int64) * 1000
    power5 = np.zeros((pb.padded, k.POWER_LIMBS), np.int32)
    power5[:n] = k.power_limbs(powers)
    counted = np.zeros((pb.padded,), np.bool_)
    counted[:n] = True
    commit_ids = np.zeros((pb.padded,), np.int32)
    commit_ids[n // 2 :] = 1
    thresh = np.zeros((2, k.TALLY_LIMBS), np.int32)
    thresh[0, 0] = 1
    thresh[1, 0] = 2

    mesh = pm.make_mesh()
    step = pm.sharded_verify_tally(mesh, n_commits=2)
    pb2, args = pm.shard_batch_arrays(mesh, pb, power5, counted, commit_ids)
    valid, tally, quorum = step(*args, thresh)

    exp_valid = np.array([i != 4 for i in range(n)])
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp_valid)
    t = k.tally_to_int(np.asarray(tally))
    exp0 = sum(int(powers[i]) for i in range(n // 2) if i != 4)
    exp1 = sum(int(powers[i]) for i in range(n // 2, n))
    assert int(t[0]) == exp0 and int(t[1]) == exp1
    assert bool(quorum[0]) and bool(quorum[1])
