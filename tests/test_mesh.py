"""Sharded verify+tally over the virtual 8-device CPU mesh."""
import sys
import threading

import pytest
import numpy as np

import jax

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.ops import ed25519_kernel as k
from cometbft_tpu.parallel import mesh as pm


"""Both CPU cases are slow-marked: with the jax<0.5 shard_map shim these
now actually COMPILE on old containers (they used to fail fast on the
missing jax.shard_map attribute), and an 8-virtual-device compile of the
full verify graph costs multi-minute wall on a 1-core host. The driver's
dryrun_multichip covers the sharded paths in the quick gate."""


def test_rows_builders_memoized_and_share_verify_program():
    """ISSUE 3 satellite (round-5 MULTICHIP regression): repeated
    builder calls return the SAME compiled closure, and every tally
    width reuses ONE Pallas verify step per mesh — no per-call
    shard_map rebuilds. Pure cache identity, no compiles."""
    mesh = pm.make_mesh()
    assert pm.sharded_verify_tally_rows(mesh, 1) is \
        pm.sharded_verify_tally_rows(mesh, 1)
    assert pm.sharded_verify_tally(mesh, 2) is \
        pm.sharded_verify_tally(mesh, 2)
    assert pm.sharded_stream_verify(mesh, 4) is \
        pm.sharded_stream_verify(mesh, 4)
    # an equivalent mesh (same devices/axes) hits the same entries
    assert pm.sharded_verify_tally_rows(pm.make_mesh(), 1) is \
        pm.sharded_verify_tally_rows(mesh, 1)
    # n_commits=1 and n_commits=16 share the expensive verify program
    pm.sharded_verify_tally_rows(mesh, 16)
    assert pm._STEP_CACHE[("rows", pm._mesh_key(mesh), 1)] is not \
        pm._STEP_CACHE[("rows", pm._mesh_key(mesh), 16)]
    assert pm._sharded_verify_rows_step(mesh) is \
        pm._sharded_verify_rows_step(mesh)
    assert sum(1 for key in pm._STEP_CACHE
               if key[0] == "pallas-verify") == 1


def test_step_cache_hit_counters():
    """ISSUE 4 satellite (MULTICHIP_r05 rc=124 guard): the memoized
    builders expose hit/miss counters, and REPEATED builder calls are
    observable HITS — a regression back to per-call shard_map rebuilds
    would show up as misses here (and as minutes of recompile on the
    harness). Pure cache identity, no compiles."""
    mesh = pm.make_mesh()
    pm.sharded_verify_tally(mesh, 3)  # ensure the entry exists
    before = pm.cache_stats()
    for _ in range(4):
        pm.sharded_verify_tally(mesh, 3)
    after = pm.cache_stats()
    assert after["hits"] >= before["hits"] + 4
    assert after["misses"] == before["misses"]
    # a NEW width is one miss (the cheap tally step), then hits
    pm.sharded_verify_tally(mesh, 5)
    mid = pm.cache_stats()
    assert mid["misses"] == after["misses"] + 1
    pm.sharded_verify_tally(mesh, 5)
    assert pm.cache_stats()["hits"] == mid["hits"] + 1


def test_cache_stats_exact_under_two_threads():
    """ISSUE 10 satellite: the memo counters are mutated by the verify
    plane's dispatcher thread AND test/bench/scrape probes concurrently
    — increments ride one module lock, so two hammering threads land
    EXACTLY 2N hits (an unguarded += loses counts under preemption,
    the same race the sheds counter fixed in PR 7)."""
    mesh = pm.make_mesh()
    pm.sharded_verify_tally(mesh, 7)  # ensure the entry exists (1 miss)
    before = pm.cache_stats()
    n_iter = 2000
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)  # make preemption aggressive
    try:
        def worker():
            for _ in range(n_iter):
                pm.sharded_verify_tally(mesh, 7)

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old_si)
    after = pm.cache_stats()
    assert after["hits"] - before["hits"] == 2 * n_iter
    assert after["misses"] == before["misses"]


def test_rows_split_plumbing_with_stub_kernel(monkeypatch):
    """Execute the split verify->tally pipeline over the 8-device mesh
    with a STUB verify kernel (the real Pallas program costs minutes of
    interpret compile on CPU): the per-device column extraction, psum,
    limb carry, and quorum plumbing must tally exactly."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_pallas as kp

    def fake_verify(rows, base):
        return (rows[kp.C_CID] & 1) == 0  # even commits "verify"

    fake_verify.__wrapped__ = fake_verify
    monkeypatch.setattr(kp, "_verify_rows", fake_verify)
    pm._STEP_CACHE.clear()
    try:
        mesh = pm.make_mesh()
        n_dev = len(jax.devices())
        n_commits = 4
        n = n_dev * kp.B_TILE
        keys = [PrivKey.generate(i.to_bytes(4, "big") + b"\x33" * 28)
                for i in range(8)]
        pubs = [keys[i % 8].pub_key().data for i in range(n)]
        msgs = [b"stub-%d" % i for i in range(n)]
        sigs = [b"\x00" * 64] * n  # content is irrelevant to the stub
        pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
        powers = np.full((n,), 7, np.int64)
        power5 = k.power_limbs(powers)
        counted = np.ones((n,), np.bool_)
        cids = (np.arange(n, dtype=np.int32) % n_commits)
        thresh = k.threshold_limbs(1, n_commits)
        rows = kp.pack_rows(pb, power5, counted, cids, thresh)
        rows[kp.C_THRESH:] = 0
        from jax.sharding import NamedSharding, PartitionSpec as P

        step = pm.sharded_verify_tally_rows(mesh, n_commits)
        rows_d = jax.device_put(
            rows, NamedSharding(mesh, P(None, mesh.axis_names[0])))
        valid, tally, quorum = jax.block_until_ready(
            step(rows_d, kp.base_f32(), thresh))
        v = np.asarray(valid)[:n]
        np.testing.assert_array_equal(v, cids % 2 == 0)
        t = k.tally_to_int(np.asarray(tally))
        per_commit = n // n_commits * 7
        assert [int(x) for x in t] == [
            per_commit if c % 2 == 0 else 0 for c in range(n_commits)
        ]
        q = np.asarray(quorum)
        assert list(q) == [c % 2 == 0 for c in range(n_commits)]
    finally:
        pm._STEP_CACHE.clear()  # stub-compiled steps must not leak


def test_padded_sharded_tally_matches_unpadded():
    """ISSUE 10 satellite: shard_batch_arrays' mesh padding rows carry
    counted=False EXPLICITLY (bool-cast, zeroed past the original
    padding). Padding rows necessarily claim commit_id=0, so a counted
    leak would inflate exactly commit 0's tally — the padded sharded
    tally must bit-match the unpadded single-device tally. valid is
    forced all-True so ONLY the counted mask keeps padding out (the
    regression this guards)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n, pad = 24, 60  # 60 % 8 devices != 0: forces the padding path
    pubs = [b"\x01" * 32] * n
    msgs = [b"pad-%d" % i for i in range(n)]
    sigs = [b"\x00" * 64] * n
    pb = k.pack_batch(pubs, msgs, sigs, pad_to=pad)
    powers = np.arange(1, n + 1, dtype=np.int64) * 111
    power5 = np.zeros((pad, k.POWER_LIMBS), np.int32)
    power5[:n] = k.power_limbs(powers)
    counted = np.zeros((pad,), np.int64)  # hostile dtype: must be cast
    counted[:n] = 1
    cids = np.zeros((pad,), np.int32)
    cids[n // 2:n] = 1

    mesh = pm.make_mesh()
    pb2, args = pm.shard_batch_arrays(mesh, pb, power5, counted, cids)
    assert pb2.padded == 64
    power5_d, counted_d, cids_d = args[7], args[8], args[9]
    assert np.asarray(counted_d).dtype == np.bool_
    assert not np.asarray(counted_d)[pad:].any()
    assert not np.asarray(args[6])[pad:].any()  # precheck pads False too

    thresh = k.threshold_limbs(1, 2)
    step = pm._sharded_tally_step(mesh, 2)
    axis = mesh.axis_names[0]
    valid = jax.device_put(np.ones((pb2.padded,), np.bool_),
                           NamedSharding(mesh, P(axis)))
    tally, _ = step(valid, power5_d, counted_d, cids_d, thresh)
    exp = k.tally_core(jnp.ones((pad,), bool), jnp.asarray(power5),
                       jnp.asarray(counted.astype(np.bool_)),
                       jnp.asarray(cids), 2)
    np.testing.assert_array_equal(np.asarray(tally), np.asarray(exp))
    # and in ints: commit 0 is exactly the first half's power sum
    t = k.tally_to_int(np.asarray(tally))
    assert int(t[0]) == int(powers[: n // 2].sum())
    assert int(t[1]) == int(powers[n // 2:].sum())


def test_sharded_fused_layout_with_stub_kernel(monkeypatch):
    """ISSUE 10 tentpole plumbing: the verify plane's cross-chip fused
    step (sharded_fused_verify) over the 8-device mesh with a STUB
    cached kernel — proves the layout contract between
    fused.shard_positions and the kernel's local `row mod M ->
    validator` map, the per-shard ok/power table wiring, global commit
    ids through the psum tally, and the replicated-threshold quorum.
    The real Pallas program costs minutes of interpret compile on CPU;
    the stub keeps validity = precheck & ok[vidx], which exercises
    every sharded seam."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from _kernel_stubs import fake_verify_tally_cached
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.verifyplane.fused import shard_positions

    monkeypatch.setattr(ec, "_verify_tally_cached",
                        fake_verify_tally_cached)
    pm._STEP_CACHE.clear()
    try:
        mesh = pm.make_mesh()
        n_dev = len(jax.devices())
        m_s = 128                      # one table block per device
        nvals = n_dev * m_s
        n_strides = 2                  # the vote + extension shape
        b_loc = n_strides * m_s
        B = n_dev * b_loc
        n_commits = 2

        # position-ordered fixture: position p holds validator v of
        # stride s per the sharded layout; assert the layout helper
        # agrees before driving the device
        v_of = np.empty(B, np.int64)
        s_of = np.empty(B, np.int64)
        for p in range(B):
            d, q = divmod(p, b_loc)
            s_of[p], v_of[p] = divmod(q, m_s)
            v_of[p] += d * m_s
        np.testing.assert_array_equal(
            shard_positions(v_of, s_of, m_s, n_strides), np.arange(B))

        precheck_ok = (v_of * 7 + s_of) % 5 != 0
        ok_host = np.asarray([v % 3 != 0 for v in range(nvals)])
        powers = np.arange(1, nvals + 1, dtype=np.int64)
        counted = s_of == 0
        cids = (v_of % n_commits).astype(np.int32)

        pubs = [b"\x02" * 32] * B
        msgs = [b"fx-%d" % p for p in range(B)]
        sigs = [b"\x00" * 64] * B
        pb = k.pack_batch(pubs, msgs, sigs, pad_to=B)
        pb = pb._replace(precheck=np.asarray(precheck_ok, np.bool_))
        rows = ec.pack_rows_cached(pb, counted, cids)

        axis = mesh.axis_names[0]
        tab = jax.device_put(
            np.zeros((nvals // 128 * ec.ENT_BLOCK, 128), np.int16),
            NamedSharding(mesh, P(axis, None)))
        ok_d = jax.device_put(ok_host, NamedSharding(mesh, P(axis)))
        p5 = jax.device_put(k.power_limbs(powers),
                            NamedSharding(mesh, P(axis, None)))
        exp_tally = []
        for c in range(n_commits):
            sel = [v for v in range(nvals)
                   if v % n_commits == c and ok_host[v]
                   and (v * 7) % 5 != 0]
            exp_tally.append(int(powers[sel].sum()))
        thresh = np.zeros((n_commits, k.TALLY_LIMBS), np.int32)
        thresh[0] = k.threshold_limbs(exp_tally[0] - 1)[0]  # quorum True
        thresh[1] = k.threshold_limbs(exp_tally[1])[0]      # quorum False

        step = pm.sharded_fused_verify(mesh, n_commits)
        rows_d = jax.device_put(rows,
                                NamedSharding(mesh, P(None, axis)))
        valid, tally, quorum = jax.block_until_ready(
            step(rows_d, tab, ok_d, p5, ec.base60_f32(), thresh))
        exp_valid = precheck_ok & ok_host[v_of]
        np.testing.assert_array_equal(np.asarray(valid), exp_valid)
        t = k.tally_to_int(np.asarray(tally))
        assert [int(x) for x in t] == exp_tally
        assert list(np.asarray(quorum)) == [True, False]
        # memoized: the second build is the same closure, observably
        before = pm.cache_stats()
        assert pm.sharded_fused_verify(mesh, n_commits) is step
        assert pm.cache_stats()["hits"] == before["hits"] + 1
    finally:
        pm._STEP_CACHE.clear()  # stub-compiled steps must not leak


def test_effective_mesh_clamps_empty_shards():
    """Review fix: coarse table_pad buckets can leave trailing shards
    EMPTY (10k validators over 8 devices -> 4096-slot stride -> 3
    shards used); the flush must clamp to a sub-mesh instead of
    staging/verifying pure padding on 5 chips."""
    from cometbft_tpu.verifyplane import fused as fz

    mesh = pm.make_mesh()
    assert mesh.devices.size == 8
    m_eff, n_dev, m_s = fz.effective_mesh(mesh, 10_000)
    assert (n_dev, m_s) == (3, 4096)
    assert m_eff.devices.size == 3
    assert tuple(m_eff.devices.flat) == tuple(mesh.devices.flat)[:3]
    # sub-meshes are memoized: identity feeds the step/table memos
    assert fz.effective_mesh(mesh, 10_000)[0] is m_eff
    # a valset filling every stride keeps the full mesh object
    full = fz.effective_mesh(mesh, 2048)
    assert full[0] is mesh and full[1] == 8 and full[2] == 256
    # one that fits a single stride is single-device business
    assert fz.effective_mesh(mesh, 100) == (None, 1, 256)
    assert fz.effective_mesh(None, 100) == (None, 1, 256)
    # past even the full mesh's table budget: loud, not wrong
    with pytest.raises(ValueError):
        fz.effective_mesh(mesh, 8 * 65536 + 1)


def test_thresh_from_rows_pads_short_sharded_slice():
    """Review fix: a lane-sharded flush packs ONE zero threshold row,
    so a device's local slice can hold fewer than n_commits *
    TALLY_LIMBS elements when a flush carries many commit groups —
    the kernel's threshold read must zero-pad instead of crashing at
    trace time (which would falsely trip the device breaker)."""
    import jax.numpy as jnp

    from cometbft_tpu.ops import ed25519_cached as ec

    # 40 commits * 6 limbs = 240 > the 128 elements one zero row holds
    short = jnp.zeros((ec.V_THRESH + 1, 128), jnp.int32)
    t = ec._thresh_from_rows(short, 40)
    assert t.shape == (40, k.TALLY_LIMBS)
    assert not np.asarray(t).any()
    # the single-device path still reads its packed values back
    thresh = np.arange(3 * k.TALLY_LIMBS, dtype=np.int32).reshape(3, -1)
    pubs = [b"\x01" * 32] * 8
    pb = k.pack_batch(pubs, [b"m"] * 8, [b"\x00" * 64] * 8, pad_to=128)
    rows = ec.pack_rows_cached(pb, thresh=thresh)
    got = ec._thresh_from_rows(jnp.asarray(rows), 3)
    np.testing.assert_array_equal(np.asarray(got), thresh)


@pytest.mark.slow
def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    n = 24
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pubs = [ed.pubkey_from_seed(s) for s in seeds]
    msgs = [b"commit-sig-%d" % i for i in range(n)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    sigs[4] = sigs[4][:8] + bytes([sigs[4][8] ^ 2]) + sigs[4][9:]

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=64)
    powers = np.arange(1, n + 1, dtype=np.int64) * 1000
    power5 = np.zeros((pb.padded, k.POWER_LIMBS), np.int32)
    power5[:n] = k.power_limbs(powers)
    counted = np.zeros((pb.padded,), np.bool_)
    counted[:n] = True
    commit_ids = np.zeros((pb.padded,), np.int32)
    commit_ids[n // 2 :] = 1
    thresh = np.zeros((2, k.TALLY_LIMBS), np.int32)
    thresh[0, 0] = 1
    thresh[1, 0] = 2

    mesh = pm.make_mesh()
    step = pm.sharded_verify_tally(mesh, n_commits=2)
    pb2, args = pm.shard_batch_arrays(mesh, pb, power5, counted, commit_ids)
    valid, tally, quorum = step(*args, thresh)

    exp_valid = np.array([i != 4 for i in range(n)])
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp_valid)
    t = k.tally_to_int(np.asarray(tally))
    exp0 = sum(int(powers[i]) for i in range(n // 2) if i != 4)
    exp1 = sum(int(powers[i]) for i in range(n // 2, n))
    assert int(t[0]) == exp0 and int(t[1]) == exp1
    assert bool(quorum[0]) and bool(quorum[1])


@pytest.mark.slow
def test_sharded_pallas_rows():
    """The flagship Mosaic kernel under shard_map: a 1024-row packed
    batch lane-sharded over the 8-device mesh, per-device Pallas tiles,
    psum tally (round-2 verdict item 7)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_pallas as kp

    n_dev = len(jax.devices())
    n = n_dev * kp.B_TILE
    keys = [PrivKey.generate(i.to_bytes(4, "big") + b"\x19" * 28)
            for i in range(n)]
    pubs = [q.pub_key().data for q in keys]
    msgs = [b"sharded-%d" % i for i in range(n)]
    sigs = [q.sign(m) for q, m in zip(keys, msgs)]
    sigs[7] = sigs[7][:12] + bytes([sigs[7][12] ^ 1]) + sigs[7][13:]
    sigs[900 % n] = b"\x00" * 64

    pb = k.pack_batch(pubs, msgs, sigs, pad_to=n)
    powers = np.full((n,), 10, np.int64)
    power5 = k.power_limbs(powers)
    counted = np.ones((n,), np.bool_)
    cids = np.zeros((n,), np.int32)
    thresh = k.threshold_limbs(int(powers.sum()) * 2 // 3)
    rows = kp.pack_rows(pb, power5, counted, cids, thresh)
    rows[kp.C_THRESH:] = 0  # thresholds ride separately when sharded

    mesh = pm.make_mesh()
    step = pm.sharded_verify_tally_rows(mesh, n_commits=1)
    rows_d = jax.device_put(
        rows, NamedSharding(mesh, P(None, mesh.axis_names[0]))
    )
    valid, tally, quorum = jax.block_until_ready(
        step(rows_d, kp.base_f32(), thresh)
    )
    exp = np.ones(n, bool)
    exp[[7, 900 % n]] = False
    np.testing.assert_array_equal(np.asarray(valid)[:n], exp)
    assert k.tally_to_int(np.asarray(tally))[0] == int(powers.sum()) - 20
    assert bool(np.asarray(quorum)[0])


@pytest.mark.skipif(
    not __import__("os").environ.get("CBT_TEST_ON_TPU"),
    reason="cached kernel under shard_map: pallas-interpret compile "
           "takes hours on CPU (see test_ed25519_cached.py); the "
           "8-device CPU dryrun covers it via __graft_entry__."
)
def test_sharded_stream_cached_multi_commit():
    """The blocksync streaming shape multi-device: a 16-commit chunk of
    one 128-validator valset through the cached-table kernel, sharded
    2 commits/device over the 8-mesh, per-commit psum tallies; one bad
    signature flips exactly its commit's row and no quorum bit (each
    commit has 128/128 power, so one loss still clears 2/3)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.parallel import mesh as pm

    mesh = pm.make_mesh(jax.devices()[:8])
    n_commits = 16
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(128)]
    pubs = [k.pub_key().data for k in keys]
    table = ec.build_table(pubs, [10] * 128)
    M = table.n_vals
    B = n_commits * M
    spubs, smsgs, ssigs = [], [], []
    for c in range(n_commits):
        for i, k in enumerate(keys):
            m = b"mesh-stream-%d-%d" % (c, i)
            spubs.append(pubs[i])
            smsgs.append(m)
            ssigs.append(k.sign(m))
    bad = 5 * M + 17  # commit 5, validator 17
    ssigs[bad] = b"\x01" * 64
    pb = ek.pack_batch(spubs, smsgs, ssigs, pad_to=B)
    counted = np.ones((B,), np.bool_)
    cids = np.repeat(np.arange(n_commits, dtype=np.int32), M)
    thresh = ek.threshold_limbs(128 * 10 * 2 // 3, n_commits)
    rows = ec.pack_rows_cached(pb, counted, cids, thresh)
    step = pm.sharded_stream_verify(mesh, n_commits)
    rows_d = jax.device_put(
        rows, NamedSharding(mesh, P(None, mesh.axis_names[0])))
    valid, tally, quorum = jax.block_until_ready(
        step(rows_d, table.tab, table.ok, table.power5,
             ec.base60_f32(), thresh))
    v = np.asarray(valid)
    assert not v[bad] and v.sum() == B - 1
    t = ek.tally_to_int(np.asarray(tally))
    assert int(t[5]) == 127 * 10
    assert all(int(t[c]) == 128 * 10 for c in range(n_commits) if c != 5)
    assert np.asarray(quorum).all()
