"""Device observatory tier-1 wiring (ISSUE 15): compile-ledger record
shape + attribution stack (innermost frame names the site/flush, ms
bubbles to every frame), the steady-state flag feeding the
compile_storm incident (burst fires with the compile tail frozen;
a drip past the window does not), the exact-accounting HBM residency
cross-check under 50 churn epochs (zero drift vs the cache truth),
GET+JSON-RPC /dump_devices (post-stop history — the ledger is
process-global), the device_report --diff regression detector, the
flush ledger's comp/h2d/dev/util columns on the host path, and the
< 10 us/flush hook budget.

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP).
Host-only: the whole file must run with NO jax import (asserted).
"""
import copy
import json
import sys
import urllib.request

import pytest

from cometbft_tpu.libs import deviceledger, incidents, tracing

_JAX_LOADED_BEFORE = "jax" in sys.modules


@pytest.fixture()
def fresh_ledger():
    led = deviceledger.CompileLedger()
    old = deviceledger.install(led)
    yield led
    deviceledger.install(old)


def test_compile_record_shape_and_attribution(fresh_ledger):
    """Innermost frame names the record's site/flush_seq; accumulated
    ms bubbles to every frame on the stack (a bench config sees its
    nested plane flushes' compiles); the fallback frame only engages
    on an empty stack; the ring is bounded."""
    led = fresh_ledger
    outer = deviceledger.attr_begin("bench.cfg2")
    inner = deviceledger.attr_begin("plane.flush", 7)
    deviceledger.record_compile(0.05)
    deviceledger.attr_end(inner)
    deviceledger.record_compile(0.01)
    deviceledger.attr_end(outer)
    recs = led.records()
    assert set(recs[0]) == set(deviceledger.CompileLedger.FIELDS)
    assert recs[0]["site"] == "plane.flush"
    assert recs[0]["flush_seq"] == 7 and recs[0]["dur_ms"] == 50.0
    assert recs[1]["site"] == "bench.cfg2" and recs[1]["flush_seq"] == -1
    assert inner.ms == 50.0 and inner.n == 1
    # ms bubbles to every frame; n counts only innermost-attributed
    assert outer.ms == 60.0 and outer.n == 1
    # fallback frames engage only with no richer frame active
    fb = deviceledger.attr_begin_fallback("mesh.step:fused")
    assert fb is not None
    deviceledger.record_compile(0.002)
    deviceledger.attr_end(fb)
    with deviceledger.attr_context("plane.flush", 1):
        assert deviceledger.attr_begin_fallback("mesh.step:fused") is None
    assert led.records()[-1]["site"] == "mesh.step:fused"
    # no frame: site is empty, never a guess
    deviceledger.record_compile(0.001)
    assert led.records()[-1]["site"] == ""
    # double attr_end never pops an outer caller's frame
    o2 = deviceledger.attr_begin("outer2")
    i2 = deviceledger.attr_begin("inner2")
    deviceledger.attr_end(i2)
    deviceledger.attr_end(i2)  # no-op, must not pop outer2
    deviceledger.record_compile(0.001)
    assert led.records()[-1]["site"] == "outer2"
    deviceledger.attr_end(o2)
    # bounded ring
    small = deviceledger.CompileLedger(capacity=16)
    for i in range(50):
        small.record(0.001, False, "s", i)
    assert len(small) == 16
    assert small.counters()["compiles"] == 50  # counters stay monotone


def test_steady_burst_fires_compile_storm_drip_does_not(fresh_ledger):
    """The round-5 guard: steady-state recompiles in a burst fire ONE
    compile_storm whose snapshot freezes the compile tail; the same
    count dripped out over longer than the window is reported as a
    drip (expiry checked BEFORE the threshold — the shed-storm
    semantics)."""
    now = [1_000_000_000]
    tracing.set_clock(lambda: now[0])
    # commit_stall_s=0 disables the stall trigger: the fake clock
    # jumps 20 s per drip step, which would otherwise read as a stall
    rec_obj = incidents.IncidentRecorder(compile_storm=3, window_s=10.0,
                                         cooldown_s=0.0,
                                         commit_stall_s=0.0)
    old = incidents.install(rec_obj)
    try:
        deviceledger.mark_steady()
        # drip: 3 steady compiles spread over 40 s > the 10 s window
        for _ in range(3):
            with deviceledger.attr_context("drip.site"):
                deviceledger.record_compile(0.004)
            incidents.poke()
            now[0] += int(20e9)
        incidents.poke()  # expire the last drip's window
        assert len(rec_obj) == 0, rec_obj.incidents()
        # burst: 3 steady compiles inside one window
        with deviceledger.attr_context("storm.site", 42):
            for _ in range(3):
                deviceledger.record_compile(0.004)
        incidents.poke()            # anchor
        now[0] += int(1e9)
        incidents.poke()            # evaluate
        snaps = rec_obj.incidents()
        assert [s["trigger"] for s in snaps] == ["compile_storm"]
        assert snaps[0]["detail"]["steady_compiles"] == 3
        tail = snaps[0]["device_tail"]
        assert any("storm.site" in ln and "STEADY" in ln
                   and "flush=42" in ln for ln in tail), tail
        # cold (pre-steady) compiles never feed the window
        fresh2 = deviceledger.CompileLedger()
        old2 = deviceledger.install(fresh2)
        try:
            for _ in range(5):
                deviceledger.record_compile(0.004)
            incidents.poke()
            now[0] += int(1e9)
            incidents.poke()
            assert len(rec_obj) == 1  # still just the one storm
        finally:
            deviceledger.install(old2)
        assert rec_obj.thresholds()["compile_storm"] == 3
    finally:
        incidents.install(old)
        tracing.set_clock(None)


class _FakeTable:
    """Duck-typed stand-in sized by table_cache.default_size via
    ``nbytes`` — exactly how the real sampler sizes real tables."""

    def __init__(self, nbytes, n_vals=0, m_shard=0, devs=None):
        self.nbytes = nbytes
        self.n_vals = n_vals
        self.m_shard = m_shard
        if devs is not None:
            self.devs = devs


def test_residency_exact_accounting_50_churn_epochs():
    """ISSUE 15 satellite: device_resident_bytes must reconcile with
    the caches' own resident_bytes EXACTLY — 50 churn epochs of
    inserts and LRU evictions, zero drift after every one."""
    from cometbft_tpu.ops import table_cache as tc

    inserted = []
    ev_before = tc.stats()["evictions_tables"]
    try:
        for epoch in range(50):
            key = b"zdev-epoch-%d" % epoch
            with tc.LOCK:
                tc.TABLES.put(key, _FakeTable(4096 + epoch,
                                              n_vals=2048))
                tc.SHARDS.put((key, "mesh"),
                              _FakeTable(8192 + epoch, m_shard=1024,
                                         devs=[0, 1]))
            inserted.append(key)
            rec = deviceledger.reconcile()
            assert rec["table_drift"] == 0, (epoch, rec)
            assert rec["staging_drift"] == 0, (epoch, rec)
            # the split itself is per-device-exact (odd bytes too)
            fams = deviceledger.residency()
            sh_total = sum(s["bytes"]
                           for s in fams["shard_tables"].values())
            assert sh_total == tc.SHARDS.resident_bytes()
        # churn pressure actually evicted (bounded caches)
        assert tc.stats()["evictions_tables"] > ev_before
        with tc.LOCK:
            assert len(tc.TABLES) <= tc.TABLES.capacity
        # headroom math over the live window
        fams = deviceledger.residency()
        head = deviceledger.headroom_rows(fams)
        assert all(isinstance(d, int) for d in head)
        for dev, n in head.items():
            assert n <= deviceledger.HBM_SLOT_BUDGET
    finally:
        with tc.LOCK:
            for key in inserted:
                tc.TABLES.pop(key)
                tc.SHARDS.pop((key, "mesh"))
    assert deviceledger.reconcile()["table_drift"] == 0


def test_staging_pools_attributed_to_host():
    """Every live StagingPool's pinned bytes land in the staging
    family under dev='host' — including pools no metrics sampler knew
    about (the weakref registry)."""
    import numpy as np

    from cometbft_tpu.libs.staging import StagingPool

    pool = StagingPool(slots=2)
    pool.get("zdev.buf", (64, 8), np.int32)
    fams = deviceledger.residency(tables=[], shards=[])
    assert fams["staging"]["host"]["bytes"] >= 64 * 8 * 4
    assert deviceledger.reconcile(fams)["staging_drift"] == 0


def _mini_net(n_nodes=2):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([90 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("zdevice-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    return nodes


def test_dump_devices_over_real_rpc(fresh_ledger):
    """GET /dump_devices and the JSON-RPC form over a live server (the
    curl surface), /metrics device families sampled from the jax-free
    core, and post-stop history (the ledger is process-global — the
    _LAST property for free)."""
    with deviceledger.attr_context("rpc.test", 3):
        deviceledger.record_compile(0.025)
    nodes = _mini_net(2)
    try:
        for n in nodes:
            n.start()
        url = nodes[0].rpc_listen("127.0.0.1", 0)
        assert nodes[0].consensus.wait_for_height(1, timeout=30.0)
        with urllib.request.urlopen(url + "/dump_devices",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["summary"]["compiles"] == 1
        assert doc["compiles"][0]["site"] == "rpc.test"
        assert doc["compiles"][0]["flush_seq"] == 3
        assert doc["hbm_slot_budget"] == 65536
        assert doc["reconcile"]["table_drift"] == 0
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_devices",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["summary"]["compiles"] == 1
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("cometbft_device_compiles_total",
                    "cometbft_device_compile_seconds_total",
                    "cometbft_device_compile_pcache_hits_total",
                    "cometbft_device_resident_bytes",
                    "cometbft_device_hbm_headroom_rows",
                    "cometbft_device_compile_ledger_records"):
            assert fam in text, fam
        line = next(ln for ln in text.splitlines()
                    if ln.startswith(
                        'cometbft_device_compiles_total{phase="cold"}'))
        assert float(line.split()[-1]) == 1.0
    finally:
        for n in nodes:
            n.stop()
    # history after the nodes stopped: the module core still serves
    post = deviceledger.dump_devices()
    assert post["summary"]["compiles"] == 1
    assert post["compiles"][0]["site"] == "rpc.test"


def test_device_report_diff_detects_synthetic_regression(
        fresh_ledger, tmp_path, capsys):
    """The --diff CLI path flags injected compile/steady/residency
    regressions (exit 1 under --fail-on-regression), stays quiet on
    identical dumps, and errors on a miswired gate
    (--fail-on-regression without --diff)."""
    from tools import device_report

    with deviceledger.attr_context("base.site"):
        for _ in range(4):
            deviceledger.record_compile(0.01)
    dump = deviceledger.dump_devices()
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump))
    doctored = copy.deepcopy(dump)
    s = doctored["summary"]
    s["compiles"] += 60
    s["compile_s"] += 12.0
    s["steady_compiles"] += 4
    s["resident_bytes"] += 1 << 22
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(doctored))

    rc = device_report.main([str(a_path), str(a_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = device_report.main([str(a_path), str(b_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "steady_compiles" in out and "compiles" in out
    assert "resident_bytes" in out
    # ANY steady-recompile growth flags — the relative threshold must
    # not excuse one new round-5-class recompile on a big baseline
    big = copy.deepcopy(dump)
    big["summary"]["steady_compiles"] = 8
    one_more = copy.deepcopy(big)
    one_more["summary"]["steady_compiles"] = 9
    (tmp_path / "big.json").write_text(json.dumps(big))
    (tmp_path / "one_more.json").write_text(json.dumps(one_more))
    capsys.readouterr()
    rc = device_report.main([str(tmp_path / "big.json"),
                             str(tmp_path / "one_more.json"),
                             "--diff", "--fail-on-regression"])
    assert rc == 1
    with pytest.raises(SystemExit):
        device_report.main([str(a_path), "--fail-on-regression"])
    # the single-dump report renders the site table
    capsys.readouterr()
    assert device_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "base.site" in out and "compiles:" in out


def test_flush_ledger_device_columns_host_path():
    """The widened flush ledger on the host path: every record carries
    the comp_ms/h2d_ms/dev_ms/util columns (zeros — nothing compiled,
    nothing fused), the summary grows the device block, and
    /dump_flushes keeps its shape."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.verifyplane import VerifyPlane

    priv = PrivKey.generate(b"\x2d" * 32)
    plane = VerifyPlane(window_ms=1.0, use_device=False)
    plane.start()
    try:
        fut = plane.submit(priv.pub_key(), b"zdev-msg",
                           priv.sign(b"zdev-msg"))
        assert fut.result(30.0) == (True,)
    finally:
        plane.stop()
    recs = plane.dump_flushes()["flushes"]
    assert recs, "no flush recorded"
    r = recs[0]
    for col in ("comp_ms", "h2d_ms", "dev_ms", "util"):
        assert col in r, r
        assert r[col] == 0.0
    dev = plane.dump_flushes()["summary"]["device"]
    assert dev["comp_ms"] == 0.0 and dev["fused_flushes"] == 0
    assert dev["util"]["p50"] == 0.0


def test_cross_dump_hammer_during_node_stop(fresh_ledger):
    """ISSUE 20 satellite: dump readers racing the write side AND a
    node teardown — one thread feeds the cost surfaces + compile ring
    at full rate while readers hammer dump_devices() (the
    /dump_devices body) across a live node's start/stop window. No
    dump may raise or fail to serialize, every served cost_surfaces
    row must be internally consistent (p50 <= p95, bounded samples),
    and the final document accounts for every observation."""
    import threading
    import time

    surf = deviceledger.CostSurfaces()
    old_surf = deviceledger.install_surfaces(surf)
    stop_evt = threading.Event()
    errors = []
    wrote = [0]

    def writer():
        i = 0
        while not stop_evt.is_set():
            stamp = "device" if i % 2 else "host"
            deviceledger.observe_flush("hammer", stamp, 8 << (i % 4),
                                       1, 0.01, 0.02, 0.5 + i % 7)
            with deviceledger.attr_context("hammer.site", i):
                deviceledger.record_compile(0.0001)
            i += 1
            wrote[0] = i
            time.sleep(0.001)

    def reader():
        while not stop_evt.is_set():
            try:
                doc = deviceledger.dump_devices()
                json.dumps(doc)
                for row in doc["cost_surfaces"]:
                    assert row["n"] >= 1
                    assert row["dev_ms_p50"] <= row["dev_ms_p95"]
                cm = deviceledger.cost_model()
                cm.estimate_dev_ms("hammer", 64)
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(repr(e))
                return
            time.sleep(0.002)  # 1-core host: leave the nodes air

    threads = [threading.Thread(target=writer, daemon=True),
               threading.Thread(target=reader, daemon=True),
               threading.Thread(target=reader, daemon=True)]
    nodes = _mini_net(2)
    try:
        for t in threads:
            t.start()
        for n in nodes:
            n.start()
        assert nodes[0].consensus.wait_for_height(1, timeout=30.0)
        # the teardown races the readers — the satellite's point
        for n in nodes:
            n.stop()
        time.sleep(0.05)  # post-stop dumps land under the hammer too
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10.0)
        for n in nodes:
            n.stop()
        deviceledger.install_surfaces(old_surf)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    assert wrote[0] >= 4, "writer barely ran"
    # the final document accounts for everything the writer observed
    # (>= because live node flushes feed the same global surfaces)
    final = surf.counters()
    assert final["observed"] >= wrote[0], (final, wrote[0])
    fams = {r["family"] for r in surf.surfaces()}
    assert {"hammer", "hammer:stamped"} <= fams, fams
    assert fresh_ledger.counters()["compiles"] >= wrote[0]


def test_device_hook_budget():
    """ISSUE 15 acceptance: < 10 us per flush for the observatory's
    always-on hooks with tracing OFF (best of 3 to dodge 1-core
    scheduler spikes; typical is ~1-2 us)."""
    import bench

    rows = [bench.device_ledger_bookkeeping_us(k=5_000)
            for _ in range(3)]
    best = min(r["flush_hook_us_per_flush"] for r in rows)
    assert best < 10.0, f"flush hooks {best} us"
    assert min(r["compile_record_us"] for r in rows) < 50.0


def test_no_jax_import():
    """Host-only contract: nothing in this file (the observatory core,
    residency sampling, RPC, device_report, the bench helper) may pull
    jax into the process."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
