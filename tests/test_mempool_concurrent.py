"""Concurrent CheckTx hammer: the mempool's overload seams under real
thread contention (ISSUE 7 satellite).

Every scenario here is a race that a single-threaded test cannot see:
cache TOCTOU on duplicate submissions, full-pool drop/un-cache
semantics under interleaved update() commits, plane-routed sigtx
verification racing the dispatcher, and BULK-lane sheds surfacing as
explicit non-OK codes while honest txs keep flowing.
"""
import threading

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.mempool import sigtx
from cometbft_tpu.mempool.admission import AdmissionController
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.verifyplane import (
    VerifyPlane,
    set_global_plane,
)

N_THREADS = 8


def _hammer(fn, n_threads=N_THREADS):
    """Run fn(thread_index) on n_threads, re-raising any failure."""
    errs = []

    def run(k):
        try:
            fn(k)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run, args=(k,))
          for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs[:3]


@pytest.fixture
def host_plane():
    """A running host-path plane registered as the process global —
    the mempool routes sigtx checks through its BULK lane."""
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    yield plane
    set_global_plane(None)
    plane.stop()


def test_concurrent_duplicate_tx_admitted_once():
    """Cache TOCTOU: N threads racing the SAME tx — exactly one body
    runs CheckTx through to the pool, the rest dedup; the pool and the
    gas table hold exactly one entry."""
    mp = Mempool(KVStoreApplication(), max_txs=64, verify_sigs=False)
    codes = []
    lock = threading.Lock()

    def submit(_k):
        for _ in range(50):
            resp = mp.check_tx(b"dup-tx=1")
            with lock:
                codes.append(resp.code)

    _hammer(submit)
    assert codes.count(abci.CODE_TYPE_OK) == 1, (
        "duplicate tx admitted more than once (cache TOCTOU)"
    )
    assert mp.size() == 1
    assert mp.gas_entries() == 1


def test_concurrent_full_pool_drop_and_uncache():
    """Full-queue semantics under contention: overflow txs get an
    explicit 'mempool is full', leave the cache (resubmittable), never
    leak gas entries — and after update() frees space, a previously
    dropped tx IS re-admittable."""
    cap = 16
    mp = Mempool(KVStoreApplication(), max_txs=cap, verify_sigs=False)
    results = {}
    lock = threading.Lock()

    def submit(k):
        for i in range(cap):
            tx = b"tx-%d-%d=v" % (k, i)
            resp = mp.check_tx(tx)
            with lock:
                results[tx] = resp

    _hammer(submit)
    oks = [tx for tx, r in results.items()
           if r.code == abci.CODE_TYPE_OK]
    fulls = [tx for tx, r in results.items()
             if r.code != abci.CODE_TYPE_OK]
    assert len(oks) == cap
    assert fulls and all("full" in results[tx].log for tx in fulls)
    assert mp.size() == cap
    assert mp.gas_entries() == cap, "gas table leaked dropped txs"
    # commit everything; a full-dropped tx must now be re-admittable
    # (the drop un-cached it — dedup must not swallow the retry)
    mp.update(1, oks)
    assert mp.size() == 0 and mp.gas_entries() == 0
    retry = fulls[0]
    assert mp.check_tx(retry).code == abci.CODE_TYPE_OK
    assert mp.size() == 1 and mp.gas_entries() == 1


def test_concurrent_checktx_races_update_no_gas_leak():
    """The hygiene invariant under the nastiest interleaving: CheckTx
    admissions racing update() commits/rechecks must end with _tx_gas
    tracking the pool EXACTLY (any excess is the leak the ISSUE
    names)."""
    mp = Mempool(KVStoreApplication(), max_txs=128, verify_sigs=False)
    stop = threading.Event()

    def committer():
        h = 0
        while not stop.is_set():
            h += 1
            mp.update(h, mp.reap(max_txs=16))

    ct = threading.Thread(target=committer)
    ct.start()
    try:
        _hammer(lambda k: [mp.check_tx(b"race-%d-%d=v" % (k, i))
                           for i in range(200)])
    finally:
        stop.set()
        ct.join()
    mp.update(9999, mp.reap(max_txs=-1))
    assert mp.size() == 0
    assert mp.gas_entries() == 0, "gas entries leaked across update()"


def test_plane_routed_verify_matches_host_oracle(host_plane):
    """Correctness under concurrency: valid/corrupted/malformed sigtx
    envelopes and unsigned txs hammered through the BULK lane must land
    exactly where the host oracle says — no cross-contamination between
    interleaved verdicts."""
    mp = Mempool(KVStoreApplication(), max_txs=4096, verify_sigs=True)
    privs = [PrivKey.generate(bytes([40 + k]) * 32)
             for k in range(N_THREADS)]
    expected = {}  # tx -> expected CheckTx code
    per_thread = []
    for k in range(N_THREADS):
        txs = []
        for i in range(25):
            payload = b"oracle-%d-%d=v" % (k, i)
            kind = i % 4
            if kind == 0:  # valid envelope
                tx = sigtx.wrap(privs[k], payload)
                code = abci.CODE_TYPE_OK
            elif kind == 1:  # corrupted signature
                good = bytearray(sigtx.wrap(privs[k], payload))
                good[len(sigtx.MAGIC) + sigtx.PUB_LEN] ^= 0xFF
                tx, code = bytes(good), abci.CODE_TYPE_BAD_SIGNATURE
            elif kind == 2:  # magic present, frame too short
                tx = sigtx.MAGIC + payload
                code = abci.CODE_TYPE_BAD_SIGNATURE
            else:  # unsigned: app-level auth applies, kvstore accepts
                tx, code = payload, abci.CODE_TYPE_OK
            txs.append(tx)
            expected[tx] = code
        per_thread.append(txs)
    got = {}
    lock = threading.Lock()

    def submit(k):
        for tx in per_thread[k]:
            resp = mp.check_tx(tx)
            with lock:
                got[tx] = resp.code

    _hammer(submit)
    mismatches = {tx: (got[tx], code) for tx, code in expected.items()
                  if got[tx] != code}
    assert not mismatches, f"{len(mismatches)} verdicts diverged " \
                           f"from the host oracle: " \
                           f"{list(mismatches.items())[:3]}"
    n_ok = sum(1 for c in expected.values()
               if c == abci.CODE_TYPE_OK)
    assert mp.size() == n_ok
    assert mp.gas_entries() == n_ok
    # the signed txs really rode the BULK lane of the shared plane
    assert host_plane.stats()["lane_rows"]["bulk"] > 0


def test_bulk_shed_surfaces_as_overloaded_code():
    """Sheds are EXPLICIT: a bulk lane squeezed to 1 row with a long
    coalescing window must reject overflow submissions with
    CODE_TYPE_OVERLOADED + a retry hint (never a silent drop or a
    false OK), and a shed tx must stay resubmittable."""
    # deadline > window: the tx that DID win the 1-row queue flushes
    # before it can age out (this test isolates queue-bound sheds; the
    # deadline-shed path gets its own test below)
    plane = VerifyPlane(window_ms=60.0, use_device=False,
                        bulk_window_ms=60.0, bulk_max_queue=1,
                        bulk_deadline_ms=500.0)
    plane.start()
    set_global_plane(plane)
    mp = Mempool(KVStoreApplication(), max_txs=4096, verify_sigs=True)
    priv = PrivKey.generate(b"\x51" * 32)
    txs = [sigtx.wrap(priv, b"shed-%d-%d=v" % (k, i))
           for k in range(N_THREADS) for i in range(20)]
    responses = {}
    lock = threading.Lock()
    try:
        def submit(k):
            for tx in txs[k::N_THREADS]:
                resp = mp.check_tx(tx)
                with lock:
                    responses[tx] = resp

        _hammer(submit)
        shed = [r for r in responses.values()
                if r.code == abci.CODE_TYPE_OVERLOADED]
        ok = [r for r in responses.values()
              if r.code == abci.CODE_TYPE_OK]
        assert len(shed) + len(ok) == len(txs), \
            f"unexpected codes: {set(r.code for r in responses.values())}"
        assert shed, "squeezed bulk lane never shed"
        assert ok, "every tx shed — lane never drained"
        for r in shed:
            assert "retry_after_ms=" in r.log, r
        stats = plane.stats()
        assert stats["sheds"]["bulk"] >= len(shed)
        assert stats["sheds"]["consensus"] == 0
        # a shed tx was un-cached: resubmitting it alone (no contention)
        # must verify and land
        shed_tx = next(tx for tx, r in responses.items()
                       if r.code == abci.CODE_TYPE_OVERLOADED)
        retry = mp.check_tx(shed_tx)
        assert retry.code == abci.CODE_TYPE_OK, retry
    finally:
        set_global_plane(None)
        plane.stop()


def test_deadline_shed_surfaces_as_overloaded_code():
    """The OTHER shed path: submissions that ENTER the bulk queue but
    age past bulk_deadline_ms are failed by the DISPATCHER via the
    future (not the submit-time raise) — VerifyFuture.result() must
    preserve the PlaneOverloaded type so the mempool answers OVERLOADED
    instead of silently host-verifying the shed tx."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_window_ms=150.0, bulk_max_queue=100_000,
                        bulk_deadline_ms=5.0)
    plane.start()
    set_global_plane(plane)
    mp = Mempool(KVStoreApplication(), max_txs=4096, verify_sigs=True)
    priv = PrivKey.generate(b"\x52" * 32)
    responses = []
    lock = threading.Lock()
    try:
        def submit(k):
            mine = [mp.check_tx(sigtx.wrap(priv, b"dl-%d-%d=v" % (k, i)))
                    for i in range(6)]
            with lock:
                responses.extend(mine)

        _hammer(submit)
        codes = {r.code for r in responses}
        assert codes <= {abci.CODE_TYPE_OK, abci.CODE_TYPE_OVERLOADED}, \
            codes
        shed = [r for r in responses
                if r.code == abci.CODE_TYPE_OVERLOADED]
        assert shed, "nothing aged past the 5ms bulk deadline"
        for r in shed:
            assert "retry_after_ms=" in r.log, r
        assert plane.stats()["sheds"]["bulk"] >= len(shed)
    finally:
        set_global_plane(None)
        plane.stop()


def test_admission_inflight_bound_under_hammer():
    """The admission gate keeps its inflight invariant under a thread
    storm: concurrent admitted CheckTx never exceeds the bound, every
    rejection is an explicit OVERLOADED with the hint, and the gate
    fully releases afterward."""
    seen_max = [0]
    lock = threading.Lock()

    class SlowApp(KVStoreApplication):
        def __init__(self, adm):
            super().__init__()
            self._adm = adm

        def check_tx(self, req):
            with lock:
                seen_max[0] = max(seen_max[0], self._adm.inflight)
            return super().check_tx(req)

    adm = AdmissionController(max_inflight=4, retry_after_ms=123.0)
    mp = Mempool(SlowApp(adm), max_txs=4096, verify_sigs=False,
                 admission=adm)
    adm._fill_fn = mp.fill_fraction
    responses = []

    def submit(k):
        mine = []
        for i in range(100):
            mine.append(mp.check_tx(b"adm-%d-%d=v" % (k, i)))
        with lock:
            responses.extend(mine)

    _hammer(submit)
    assert seen_max[0] <= 4, "inflight bound violated under contention"
    rejected = [r for r in responses
                if r.code == abci.CODE_TYPE_OVERLOADED]
    for r in rejected:
        assert "retry_after_ms=123.0" in r.log, r
    st = adm.stats()
    assert st["inflight"] == 0, "admission slots leaked"
    assert st["counts"]["admitted"] == len(responses) - len(rejected)


def test_update_recheck_drops_invalidated_txs():
    """Recheck semantics (clist_mempool.go:577): a tx the new state
    invalidates is dropped by update(), leaves the cache (resubmittable
    once valid again) and the gas table; with the config flag off the
    pool keeps it."""

    class FlagApp(KVStoreApplication):
        def __init__(self):
            super().__init__()
            self.reject = set()

        def check_tx(self, req):
            if req.tx in self.reject:
                return abci.ResponseCheckTx(code=9, log="stale")
            return super().check_tx(req)

    for flag in (True, False):
        app = FlagApp()
        mp = Mempool(app, max_txs=64, verify_sigs=False, recheck=flag)
        txs = [b"rc-%d=v" % i for i in range(8)]
        for tx in txs:
            assert mp.check_tx(tx).code == abci.CODE_TYPE_OK
        # the block invalidates the odd txs and commits the first two
        app.reject = set(txs[3::2])
        mp.update(1, txs[:2])
        survivors = set(mp.reap())
        if flag:
            assert survivors == set(txs[2:]) - app.reject
            # dropped txs re-admit once valid again (cache hygiene)
            app.reject = set()
            stale = txs[3]
            assert mp.check_tx(stale).code == abci.CODE_TYPE_OK
        else:
            assert survivors == set(txs[2:]), \
                "recheck=False must keep survivors untouched"
        assert mp.gas_entries() == mp.size(), "gas/pool divergence"
