"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Tests never touch the real TPU chip; multi-chip sharding is validated on a
virtual CPU mesh per the driver contract (see __graft_entry__.dryrun_multichip).
This must run before any test module imports jax.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
