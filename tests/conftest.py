"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Tests must not depend on — or hog — the single real TPU chip; multi-chip
sharding is validated on a virtual CPU mesh per the driver contract (see
__graft_entry__.dryrun_multichip). The image pins jax_platforms to
"axon,cpu" at import time (the TPU tunnel) and ignores JAX_PLATFORMS, so we
override via jax.config after import. XLA_FLAGS must still be set before
jax initializes its CPU client.

Set CBT_TEST_ON_TPU=1 to deliberately run the suite against the real chip.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if not os.environ.get("CBT_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the ed25519 verify kernel takes minutes to
# compile on CPU; cache it across test runs (cache key includes backend +
# jax version, so TPU runs are unaffected). Shared knobs with
# __graft_entry__ so the suite and the driver hit ONE cache.
from cometbft_tpu.libs.jax_cache import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache()

# ---------------------------------------------------------------------------
# Tier-1 duration report: the suite runs under a hard 870 s timeout on a
# 1-core host (ROADMAP note), so any NON-slow-marked test that takes more
# than 60 s is a budget hazard — flag it loudly in the terminal summary
# so it gets a `slow` marker (with a fast sibling) before it breaks the
# quick gate.
import pytest  # noqa: E402

_DURATION_FLAG_SECS = 60.0
_over_budget = []


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    outcome.get_result()
    if (call.when == "call"
            and call.duration is not None
            and call.duration > _DURATION_FLAG_SECS
            and item.get_closest_marker("slow") is None):
        _over_budget.append((item.nodeid, call.duration))


def pytest_terminal_summary(terminalreporter):
    for nodeid, dur in _over_budget:
        terminalreporter.write_line(
            f"[tier1-duration] non-slow test over {_DURATION_FLAG_SECS:.0f}s:"
            f" {nodeid} took {dur:.1f}s — mark it slow (keep a fast"
            " sibling) or shrink it"
        )
