"""Merkle tree differential tests against reference golden vectors.

Vectors: crypto/merkle/tree_test.go:22-40 (HashFromByteSlices),
rfc6962_test.go (leaf/inner/empty hashes).
"""
import hashlib

import pytest

from cometbft_tpu.crypto import merkle

GOLDEN = [
    ([], "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"),
    ([bytes([1, 2, 3])],
     "054edec1d0211f624fed0cbca9d4f9400b0e491c43742af2c5b0abebf0c990d8"),
    ([b""],
     "6e340b9cffb37a989ca544e6bb780a2c78901d3fb33738768511a30617afa01d"),
    ([bytes([1, 2, 3]), bytes([4, 5, 6])],
     "82e6cfce00453804379b53962939eaa7906b39904be0813fcadd31b100773c4b"),
    ([bytes([1, 2]), bytes([3, 4]), bytes([5, 6]), bytes([7, 8]),
      bytes([9, 10])],
     "f326493eceab4f2d9ffbc78c59432a0a005d6ea98392045c74df5d14a113be18"),
]


@pytest.mark.parametrize("items,expect", GOLDEN)
def test_hash_from_byte_slices_golden(items, expect):
    assert merkle.hash_from_byte_slices(items).hex() == expect


def test_rfc6962_primitives():
    assert (merkle.leaf_hash(b"L123456").hex()
            == "395aa064aa4c29f7010acfe3f25db9485bbd4b91897b6ad7ad547639252b4d56")
    assert (merkle.inner_hash(b"N123", b"N456").hex()
            == "aa217fe888e47007fa15edab33c2b492a722cb106c64667fc2b044444de66bbb")
    assert merkle.empty_hash() == hashlib.sha256(b"").digest()


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33, 100])
def test_proofs_roundtrip(n):
    items = [b"item-%d" % i for i in range(n)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, (item, p) in enumerate(zip(items, proofs)):
        assert p.index == i and p.total == n
        assert p.verify(root, item)
        assert not p.verify(root, item + b"x")
        bad_root = bytes([root[0] ^ 1]) + root[1:]
        assert not p.verify(bad_root, item)


def test_proof_wrong_position():
    items = [b"a", b"b", b"c", b"d"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    # proof for index 0 must not verify item at index 1
    assert not proofs[0].verify(root, items[1])
