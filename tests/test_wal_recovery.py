"""Kill-at-every-failpoint WAL recovery matrix.

Reference: consensus/replay_test.go crashWALandCheckpointer — arm each
libs/fail point in the WAL/consensus write path, kill the node there,
restart over the same home, and require the replay to land on the same
height/app-hash. Crashes here are SimulatedCrash (failpoints.py crash
handler override): the consensus receive routine halts dead in place,
pytest survives to restart the node.

Also covers the corrupt-tail repair: a torn/garbage WAL tail must be
truncated on reopen so post-restart appends stay reachable by the next
replay (wal.py repair_tail), swept across truncation offsets with a
wal_generator-produced real WAL.
"""
import os
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus import wal as walmod
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)

# every crash point of the WAL/consensus write path (ISSUE acceptance:
# kill at each, restart, same height/app-hash)
CRASH_POINTS = [
    "wal.pre_write",
    "wal.post_write",
    "wal.pre_fsync",
    "consensus.wal.pre_vote",
    "consensus.wal.post_vote",
    "consensus.wal.pre_proposal",
    "consensus.wal.post_proposal",
    "consensus.pre_finalize",
    "consensus.post_block_save",
]


@pytest.fixture(autouse=True)
def clean_failpoints():
    fp.reset()
    fp.set_crash_handler(fp.simulated_crash)
    yield
    fp.reset()
    fp.set_crash_handler(None)


def make_genesis(chain_id="crash-chain"):
    priv = PrivKey.generate(b"\x77" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    return State.make_genesis(chain_id, vals), priv


@pytest.mark.parametrize("point", CRASH_POINTS)
def test_kill_at_failpoint_then_recover(tmp_path, point):
    state, priv = make_genesis()
    home = str(tmp_path / "n0")
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=home, timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(2, timeout=30)
        fp.arm(point, "crash", count=1)
        deadline = time.time() + 30
        while not node.consensus.crashed:
            assert time.time() < deadline, \
                f"failpoint {point} never fired"
            time.sleep(0.01)
    finally:
        fp.reset()
        node.stop()

    # restart over the same home: handshake + WAL replay must produce a
    # state whose app hash the replayed app agrees with, then keep
    # committing from wherever the crash left off
    app2 = KVStoreApplication()
    node2 = Node(app2, state, privval=FilePV(priv), home=home,
                 timeouts=FAST)
    persisted_h = node2.height()
    assert app2.app_hash == node2.consensus.state.app_hash, \
        f"replay diverged after crash at {point}"
    node2.start()
    try:
        assert node2.consensus.wait_for_height(persisted_h + 2,
                                               timeout=30), \
            f"node wedged after crash at {point}"
    finally:
        node2.stop()


def test_crash_mid_rotation_recovers(tmp_path):
    """wal.mid_rotate: head already renamed to a segment, new head not
    yet open. On reopen the group must still replay every record and
    accept new writes."""
    path = str(tmp_path / "cs.wal")
    w = walmod.WAL(path, head_size_limit=64)
    w.write_sync(walmod.MSG_INFO, b"m" * 64)
    w.write_end_height(1)  # over the tiny limit: rotates here
    w.write_sync(walmod.MSG_INFO, b"n" * 64)
    fp.arm("wal.mid_rotate", "crash", count=1)
    with pytest.raises(fp.SimulatedCrash):
        w.write_end_height(2)  # crashes between rename and reopen
    fp.reset()
    assert not os.path.exists(path)  # head is gone: crash was mid-move

    w2 = walmod.WAL(path, head_size_limit=64)
    recs = list(walmod.WAL.iter_records(path))
    kinds = [r.kind for r in recs]
    assert kinds.count(walmod.END_HEIGHT) == 2  # both survived rotation
    assert walmod.WAL.search_for_end_height(path, 2) is not None
    w2.write_sync(walmod.MSG_INFO, b"post-crash")
    w2.close()
    assert any(r.data == b"post-crash"
               for r in walmod.WAL.iter_records(path))


def test_corrupt_tail_repaired_on_reopen(tmp_path):
    """Garbage appended after valid records (fsync'd torn write) is
    truncated on reopen, so post-restart appends are REACHABLE — without
    the repair the decoder stops at the garbage forever."""
    path = str(tmp_path / "cs.wal")
    w = walmod.WAL(path)
    for i in range(5):
        w.write_sync(walmod.MSG_INFO, b"rec-%d" % i)
    w.close()
    good_size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\xde\xad\xbe\xef" * 7)  # torn frame garbage

    w2 = walmod.WAL(path)
    assert os.path.getsize(path) == good_size  # tail dropped
    w2.write_sync(walmod.MSG_INFO, b"after-repair")
    w2.close()
    recs = [r.data for r in walmod.WAL.iter_records(path)]
    assert recs == [b"rec-%d" % i for i in range(5)] + [b"after-repair"]


def test_truncation_sweep_on_generated_wal(tmp_path):
    """wal_generator-driven: take a REAL consensus WAL, truncate it at
    every offset across the final records (torn-write simulation), and
    require (a) the decoder never raises, (b) repair_tail leaves a
    byte-exact valid prefix, (c) a node restarted on the truncated WAL
    resumes committing."""
    from cometbft_tpu.consensus.wal_generator import generate_wal

    src = str(tmp_path / "gen.wal")
    generate_wal(3, src, chain_id="walgen-sweep")
    blob = open(src, "rb").read()
    recs_full = list(walmod.WAL.iter_records(src))
    assert len(recs_full) >= 4

    path = str(tmp_path / "t.wal")
    # sweep the last ~2 records' worth of offsets plus a few deep cuts
    offsets = list(range(max(0, len(blob) - 160), len(blob))) + [
        len(blob) // 3, len(blob) // 2,
    ]
    for cut in offsets:
        with open(path, "wb") as f:
            f.write(blob[:cut])
        recs = list(walmod.WAL.iter_records(path))  # never raises
        assert len(recs) <= len(recs_full)
        dropped = walmod.WAL.repair_tail(path)
        assert dropped >= 0
        # after repair the file is exactly the valid prefix
        again = list(walmod.WAL.iter_records(path))
        assert len(again) == len(recs)
        assert os.path.getsize(path) == \
            walmod.WAL._scan_valid_prefix(path)


def test_node_resumes_on_truncated_wal(tmp_path):
    """End-to-end: crash-truncate the WAL mid-record, restart the node,
    and require it to repair + resume committing."""
    state, priv = make_genesis("trunc-chain")
    home = str(tmp_path / "n0")
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=home, timeouts=FAST)
    node.start()
    assert node.consensus.wait_for_height(3, timeout=30)
    node.stop()
    h_before = node.height()

    wal_path = os.path.join(home, "cs.wal")
    size = os.path.getsize(wal_path)
    with open(wal_path, "r+b") as f:
        f.truncate(size - 11)        # torn mid-record
        f.seek(0, os.SEEK_END)
        f.write(b"\x00" * 64)        # plus a zero-filled fsync tail

    app2 = KVStoreApplication()
    node2 = Node(app2, state, privval=FilePV(priv), home=home,
                 timeouts=FAST)
    assert app2.app_hash == node2.consensus.state.app_hash
    node2.start()
    try:
        assert node2.consensus.wait_for_height(h_before + 2, timeout=30)
    finally:
        node2.stop()
