"""Consensus flight recorder tier-1 wiring (ISSUE 13): height-ledger
record shape over a REAL committing LocalNetwork, the /dump_heights +
/dump_incidents RPC surfaces (including the stopping-node concurrency
hammer — the _LAST pattern), incident trigger + snapshot freeze via
the registered failpoint, the height_report --diff regression
detector, and the <10 us step-transition bookkeeping budget.

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP): by
the time this runs the cheap unit tests have localized real breakage.
Host-only: the whole file must run with NO jax import (asserted).
"""
import copy
import json
import sys
import threading
import urllib.request

import pytest

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import incidents

_JAX_LOADED_BEFORE = "jax" in sys.modules


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


def _mini_net(n_nodes=3):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([90 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("zheight-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    return nodes


@pytest.fixture(scope="module")
def committed_net():
    """ONE LocalNetwork run to height 4, shared read-only across the
    module (the suite sits near the tier-1 ceiling); yields the
    stopped nodes + node 0's height dump."""
    nodes = _mini_net()
    for n in nodes:
        n.start()
    assert nodes[0].consensus.wait_for_height(4, timeout=30.0)
    for n in nodes:
        n.stop()
    yield nodes, nodes[0].consensus.height_ledger.dump()


def test_height_ledger_record_shape(committed_net):
    """Every record carries the full FIELDS surface with a monotone
    cumulative stage timeline, the proposer, and the via path; the
    summary decomposes commit latency per stage."""
    from cometbft_tpu.consensus.heightledger import HeightLedger

    _, dump = committed_net
    recs = dump["heights"]
    assert len(recs) >= 4
    heights = [r["height"] for r in recs]
    assert heights == sorted(heights)
    for r in recs:
        assert set(r) == set(HeightLedger.FIELDS)
        assert r["via"] == "consensus"
        assert len(r["proposer"]) == 12
        # cumulative timeline: each stage at or after the previous
        stages = [r["proposal_ms"], r["prevote_quorum_ms"],
                  r["precommit_quorum_ms"], r["commit_ms"],
                  r["apply_ms"]]
        assert all(s > 0 for s in stages), r
        assert stages == sorted(stages), r
        assert r["rounds"] >= 0 and r["txs"] == 0
        assert isinstance(r["late"], list)
    s = dump["summary"]
    assert s["heights"] == len(recs)
    assert s["commit_latency_ms"]["p50"] > 0
    assert set(s["stage_ms"]) == {"proposal", "prevote_quorum",
                                  "precommit_quorum", "commit", "apply"}


def test_dump_routes_serve_after_stop(committed_net):
    """The _LAST pattern: /dump_heights (node-attached AND module
    fallback), /dump_flushes, /dump_incidents all serve history from a
    STOPPED node, and /metrics carries the new height/incident
    families."""
    from cometbft_tpu.consensus import heightledger
    from cometbft_tpu.rpc.server import Routes

    nodes, dump = committed_net
    routes = Routes(nodes[0])
    served = routes.dump_heights()
    assert served["summary"]["heights"] == dump["summary"]["heights"]
    # the module-global fallback serves the LAST registered ledger
    assert heightledger.dump_heights()["summary"]["heights"] >= 1
    inc = routes.dump_incidents()
    assert set(inc) == {"incidents", "fired", "thresholds"}
    assert routes.dump_flushes()["summary"] is not None
    text = nodes[0].metrics.expose_text()
    for fam in ("cometbft_consensus_height_stage_ms",
                "cometbft_consensus_height_ledger_records",
                "cometbft_consensus_late_signer_heights_total",
                "cometbft_incidents_fired_total",
                "cometbft_incidents_ring_records"):
        assert fam in text, fam
    # the stage percentiles really sampled from the ledger
    line = next(ln for ln in text.splitlines()
                if ln.startswith("cometbft_consensus_height_ledger_"))
    assert float(line.split()[-1]) >= 4


def test_dump_routes_concurrent_with_stop():
    """ISSUE 13 satellite: hammer /dump_flushes, /dump_heights and
    /dump_incidents from threads WHILE the plane and node stop — no
    crash, every response well-formed, and post-stop history still
    served."""
    from cometbft_tpu.rpc.server import Routes
    from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane

    nodes = _mini_net(2)
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    nodes[0].verify_plane = plane  # the node-attached dump path
    stop_ev = threading.Event()
    try:
        for n in nodes:
            n.start()
        assert nodes[0].consensus.wait_for_height(2, timeout=30.0)
        routes = Routes(nodes[0])
        errors = []
        responses = [0]

        def hammer():
            while not stop_ev.is_set():
                try:
                    for fn in (routes.dump_heights, routes.dump_flushes,
                               routes.dump_incidents):
                        doc = fn()
                        json.dumps(doc)  # well-formed, serializable
                        responses[0] += 1
                except Exception as e:  # noqa: BLE001 - the assertion
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        # stop everything WHILE the hammer runs
        for n in nodes:
            n.stop()
        set_global_plane(None)
        plane.stop()
        stop_ev.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:3]
        assert responses[0] > 0
    finally:
        stop_ev.set()
        set_global_plane(None)
        if plane.is_running():
            plane.stop()
        for n in nodes:
            if n.is_running():
                n.stop()
    # post-stop: history still served through every layer
    post = routes.dump_heights()
    assert post["summary"]["heights"] >= 2
    assert routes.dump_flushes()["summary"]["flushes"] >= 0
    assert routes.dump_incidents()["thresholds"]


def test_dump_heights_over_real_rpc():
    """GET /dump_heights and /dump_incidents over a live JSON-RPC
    server (the curl path operators actually use)."""
    nodes = _mini_net(2)
    try:
        for n in nodes:
            n.start()
        url = nodes[0].rpc_listen("127.0.0.1", 0)
        assert nodes[0].consensus.wait_for_height(2, timeout=30.0)
        with urllib.request.urlopen(url + "/dump_heights",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["summary"]["heights"] >= 1
        assert doc["heights"][0]["apply_ms"] > 0
        with urllib.request.urlopen(url + "/dump_incidents",
                                    timeout=10) as r:
            inc = json.loads(r.read().decode())
        assert "thresholds" in inc
        # the JSON-RPC form of the same route
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_heights",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["summary"]["heights"] >= 1
    finally:
        for n in nodes:
            n.stop()


def test_incident_failpoint_trigger_freezes_snapshot(committed_net):
    """Arming ``incidents.force=raise*1`` forces ONE snapshot at the
    next watchdog poke: the frozen bundle carries the height-ledger
    tail, the counter sample and the fingerprint, and /dump_incidents
    serves it; the cooldown keeps a re-poke from double-firing."""
    rec = incidents.IncidentRecorder(cooldown_s=60.0)
    rec.set_fingerprint({"chain_id": "zheight-chain", "drill": True})
    old = incidents.install(rec)
    try:
        fp.registry().arm_from_spec("incidents.force=raise*1")
        incidents.poke(height=7, round_=1)
        incidents.poke(height=7, round_=1)  # armed *1: no re-fire
        dump = incidents.dump_incidents()
    finally:
        incidents.install(old)
    assert dump["fired"] == {"forced": 1}
    snap = dump["incidents"][0]
    assert snap["trigger"] == "forced"
    assert snap["height"] == 7 and snap["round"] == 1
    # the committed_net fixture registered a height ledger: its tail
    # was frozen into the black box at trigger time
    assert snap["height_tail"], snap
    assert snap["fingerprint"]["drill"] is True
    assert "heights_recorded" in snap["counters"]


def test_incident_commit_stall_and_round_escalation_triggers():
    """The watchdog's threshold arms, driven directly: a commit gap
    past commit_stall_s fires commit_stall; a poke at round >= the
    limit fires round_escalation; cooldown suppresses same-kind
    refires."""
    from cometbft_tpu.libs import tracing

    now = [1_000_000_000_000]
    tracing.set_clock(lambda: now[0])
    try:
        rec = incidents.IncidentRecorder(
            commit_stall_s=5.0, round_limit=3, cooldown_s=100.0)
        rec.note_commit(10)
        now[0] += int(2e9)
        rec.poke(11, 0)
        assert not rec.fired  # 2s < 5s: quiet
        now[0] += int(4e9)
        rec.poke(11, 0)
        assert rec.fired == {"commit_stall": 1}
        now[0] += int(1e9)
        rec.poke(11, 0)  # cooldown holds
        assert rec.fired == {"commit_stall": 1}
        rec.poke(11, 3)  # round escalation is its own kind
        assert rec.fired == {"commit_stall": 1, "round_escalation": 1}
        snaps = rec.incidents()
        assert [s["trigger"] for s in snaps] == ["commit_stall",
                                                 "round_escalation"]
        assert snaps[0]["detail"]["stalled_s"] >= 5.0
    finally:
        tracing.set_clock(None)


def test_shed_storm_window_semantics():
    """Review regression: sheds that accumulated over LONGER than
    window_s (a wedged poker waking up after a quorumless partition)
    are a drip, not a storm — the expired window resets BEFORE the
    threshold check. A genuine in-window burst still fires."""
    from cometbft_tpu.libs import tracing

    now = [10 ** 15]
    tracing.set_clock(lambda: now[0])
    try:
        rec = incidents.IncidentRecorder(shed_storm=10, window_s=2.0,
                                         commit_stall_s=0.0)
        rec.note_commit(1)
        rec.note_shed(5)
        rec.poke(1, 0)          # anchors the storm window
        now[0] += int(60e9)     # a minute wedged, sheds dripping
        rec.note_shed(20)
        rec.poke(1, 0)          # expired window: 25 sheds, no storm
        assert "shed_storm" not in rec.fired, rec.fired
        rec.note_shed(15)       # burst INSIDE the fresh window
        now[0] += int(1e9)
        rec.poke(1, 0)
        assert rec.fired.get("shed_storm") == 1, rec.fired
        snap = rec.incidents()[-1]
        assert snap["detail"]["sheds"] == 15
    finally:
        tracing.set_clock(None)


def test_watchdog_ticker_detects_total_wedge():
    """The production half of stall detection: with NO pokes arriving
    at all (a quorumless partition produces zero step transitions),
    the refcounted real-clock ticker thread still fires commit_stall —
    and stop_watchdog tears the thread down when the last node
    releases it."""
    import time

    rec = incidents.IncidentRecorder(commit_stall_s=0.4,
                                     cooldown_s=60.0)
    rec.note_commit(3)
    rec.start_watchdog()
    rec.start_watchdog()  # second node's reference
    try:
        deadline = time.time() + 5.0
        while time.time() < deadline and not rec.fired:
            time.sleep(0.05)
        assert rec.fired.get("commit_stall") == 1, rec.fired
    finally:
        rec.stop_watchdog()
        assert rec._watch_thread is not None  # one ref still held
        rec.stop_watchdog()
    assert rec._watch_thread is None


def test_height_report_diff_detects_synthetic_regression(
        committed_net, tmp_path, capsys):
    """The --diff CLI path flags an injected +500 ms prevote-quorum
    regression (exit 1 under --fail-on-regression) and stays quiet on
    identical dumps (exit 0)."""
    from tools import height_report

    _, dump = committed_net
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump))
    doctored = copy.deepcopy(dump)
    for r in doctored["heights"]:
        for k in ("prevote_quorum_ms", "precommit_quorum_ms",
                  "commit_ms", "apply_ms"):
            r[k] += 500.0
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(doctored))

    rc = height_report.main([str(a_path), str(a_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = height_report.main([str(a_path), str(b_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "prevote_quorum" in out
    # the miswired-CI-gate guard mirrors trace_report's
    with pytest.raises(SystemExit):
        height_report.main([str(a_path), "--fail-on-regression"])
    # and the single-dump report renders the late-signer-aware table
    capsys.readouterr()
    assert height_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "commit latency p50/p99" in out


def test_late_signer_attribution_math():
    """Driven on a fake clock: offsets are measured against the
    precommit-quorum instant (only AFTER-quorum arrivals are late),
    each late row splits into net_ms (in-flight, from the vote's own
    signing stamp) vs sign_ms (signed late), the gossip-observatory
    join names the delivering hop, absent precommits land in the
    bitmap + count, and repeat offenders accumulate net/sign sums in
    the chronically-late table /dump_heights ranks."""
    from cometbft_tpu.consensus.heightledger import HeightLedger
    from cometbft_tpu.libs import tracing
    from cometbft_tpu.p2p.peerledger import PeerLedger

    class _Sig:
        def __init__(self, absent):
            self._a = absent

        def is_absent(self):
            return self._a

    now = [5_000_000_000_000]
    tracing.set_clock(lambda: now[0])
    try:
        led = HeightLedger()
        pled = PeerLedger()
        led.peer_ledger = pled
        for h in (1, 2):
            led.on_step(h, 0, 2)          # new_round opens the height
            now[0] += 10_000_000
            led.on_step(h, 0, 4)          # prevote entry
            led.note_vote(0, 0)           # val 0: before quorum
            now[0] += 5_000_000
            led.on_step(h, 0, 6)          # precommit entry
            led.note_vote(0, 1)           # val 1: AT quorum crossing
            now[0] += 2_000_000
            led.on_step(h, 0, 8)          # commit: quorum instant
            now[0] += 7_500_000
            # val 2: 7.5 ms LATE, of which 3 ms was flight time; the
            # peer ledger saw the vote arrive from n1 (+1 duplicate)
            pled.note_vote_seen((h, 0, 2, 2), "n1")
            pled.note_vote_seen((h, 0, 2, 2), "n0")
            led.note_vote(0, 2, net_ns=3_000_000)
            now[0] += 1_000_000
            led.on_commit(h)
            now[0] += 3_000_000
            led.record_height(
                h, 0, "aabbccddeeff", n_txs=2, block_bytes=64,
                commit_sigs=[_Sig(False), _Sig(False), _Sig(False),
                             _Sig(True)])
        # pruning lags ONE height so straggler joins still resolve:
        # finalizing h=2 pruned h=1's routes, h=2's survive
        assert pled.vote_route(1, 0, 2, 2) is None
        assert pled.vote_route(2, 0, 2, 2) is not None
        # post-commit straggler: a verified precommit for the JUST-
        # finalized height arrives 4 ms later (2 ms of it in flight)
        # and folds into the finalized record with the same split
        assert led.wants_straggler(2, 0, 1)
        assert not led.wants_straggler(2, 0, 2)  # already late
        assert not led.wants_straggler(1, 0, 1)  # older height
        now[0] += 4_000_000
        pled.note_vote_seen((2, 0, 2, 1), "n3")
        led.note_straggler(2, 0, 1, net_ns=2_000_000)
        led.note_straggler(2, 0, 1, net_ns=2_000_000)  # dedup
        recs = led.records()
    finally:
        tracing.set_clock(None)
    # the straggler row landed in height 2's FINALIZED record: offset
    # measured against its quorum instant (4 ms since finalize + the
    # 1+3 ms between quorum and finalize = 8 ms), net/sign split, hop
    straggler_rows = [row for row in recs[1]["late"] if row[0] == 1]
    assert straggler_rows == [[1, 15.5, 2.0, 13.5, "n3"]], \
        recs[1]["late"]
    r = recs[0]
    # vals 0/1 arrived at or before the quorum instant (not late);
    # val 2's stamp is 7.5 ms past it: 3 ms network, 4.5 ms sign-late,
    # delivered via n1 with one duplicate receipt
    assert r["late"] == [[2, 7.5, 3.0, 4.5, "n1+1dup"]], r["late"]
    assert r["absent"] == 1
    # bitmap: index 3 absent -> bit 3 of byte 0 -> 0x08
    assert r["absent_bitmap"] == "08"
    assert r["txs"] == 2 and r["block_bytes"] == 64
    # two heights of the same offenders -> chronic table ranks them,
    # accumulating the net-vs-sign decomposition
    top = led.top_late_signers()
    by_val = {t["val"]: t for t in top}
    assert by_val[2]["late_heights"] == 2
    assert by_val[2]["net_ms"] == 6.0
    assert by_val[2]["sign_ms"] == 9.0
    assert by_val[3]["absent_heights"] == 2
    # the straggler folded into val 1's chronic row too
    assert by_val[1]["late_heights"] == 1
    assert by_val[1]["net_ms"] == 2.0 and by_val[1]["sign_ms"] == 13.5
    assert top[0]["total"] == 2
    dump = led.dump()
    assert dump["late_signers"] == top
    assert dump["summary"]["late_votes"] == 3  # incl. the straggler
    assert dump["summary"]["late_net_ms"] == 8.0
    assert dump["summary"]["late_sign_ms"] == 22.5
    assert dump["summary"]["absent_votes"] == 2


def test_late_signer_split_on_live_network():
    """ISSUE 14 acceptance: a REAL committing multi-node network with
    one chronically slow signer produces late-signer rows carrying the
    net_ms vs sign_ms split — through the post-commit straggler path
    (finalize is atomic with quorum here, so the slow validator's
    precommit always loses the height race; the reference folds those
    into LastCommit, this ledger attributes them post-hoc)."""
    import time

    import cometbft_tpu.types.canonical as canonical
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)

    class SlowPV(FilePV):
        def sign_vote(self, chain_id, vote, **kw):
            if vote.vote_type == canonical.PRECOMMIT_TYPE:
                time.sleep(0.08)
            return super().sign_vote(chain_id, vote, **kw)

    privs = [PrivKey.generate(bytes([110 + i]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("zlate-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        pv = SlowPV(priv) if i == 3 else FilePV(priv)
        node = Node(KVStoreApplication(), state.copy(), privval=pv,
                    broadcast=net.broadcaster(i), timeouts=fast)
        net.add(node)
        nodes.append(node)
    try:
        for n in nodes:
            n.start()
        assert nodes[0].consensus.wait_for_height(6, timeout=60.0)
    finally:
        for n in nodes:
            n.stop()
    dumps = [n.consensus.height_ledger.dump() for n in nodes]
    rows = [row for d in dumps for r in d["heights"]
            for row in r["late"]]
    # on a 1-core host WHICH validator loses the height race varies
    # (GIL contention competes with the injected sleep), but the
    # straggler path must attribute SOMEBODY with the full split
    assert rows, "no late-signer rows on a live multi-node run"
    for row in rows:
        assert len(row) == 5 and row[1] > 0
        assert abs(row[1] - (row[2] + row[3])) < 0.011, row
    # real in-flight time measured (signing stamp -> arrival)
    assert any(row[2] > 0 for row in rows), rows
    split_dumps = [d for d in dumps
                   if d["summary"]["late_net_ms"] > 0]
    assert split_dumps, "summary never carried the net split"
    tops = [t for d in split_dumps for t in d["late_signers"]
            if t["late_heights"]]
    assert tops and all("net_ms" in t and "sign_ms" in t for t in tops)


def test_height_ledger_step_bookkeeping_budget():
    """ISSUE 13 acceptance: < 10 us per step transition with tracing
    OFF (best of 3 to dodge 1-core scheduler spikes; the typical
    number is < 1 us)."""
    import bench

    rows = [bench.height_ledger_bookkeeping_us(k=5_000)
            for _ in range(3)]
    best = min(r["step_transition_us"] for r in rows)
    assert best < 10.0, f"step bookkeeping {best} us >= 10 us budget"
    # allocation-free in the FlushLedger sense: steady-state step
    # transitions hold the process block count flat (< 1 block/2 steps
    # tolerates freelist jitter; the real number is ~0.004)
    assert min(r["steady_alloc_blocks_per_step"] for r in rows) < 0.5


def test_no_jax_import():
    """Host-only contract: nothing in this file (LocalNetwork
    consensus, ledgers, incidents, RPC, height_report, the bench
    helper) may pull jax into the process."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
