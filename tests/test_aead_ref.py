"""Pure-Python crypto fallbacks (crypto/aead_ref.py + the gated paths
in keys.py / secp256k1_ref.py): RFC-vector pinned so the no-OpenSSL
degraded mode stays byte-compatible with the OpenSSL-backed one."""
import pytest

from cometbft_tpu.crypto import aead_ref


def test_x25519_rfc7748_vectors():
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd"
        "62144c0ac1fc5a18506a2244ba449ac4")
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c"
        "726624ec26b3353b10a903a6d0ab1c4c")
    assert aead_ref._x25519_scalarmult(k, u).hex() == (
        "c3da55379de9c6908e94ea4df28d084f"
        "32eccf03491c71f754b4075577a28552")
    # DH agreement (RFC 7748 §6.1): Alice's key pair + the published
    # Bob PUBLIC key pin the shared secret K
    ka = bytes.fromhex("77076d0a7318a57d3c16c17251b26645"
                       "df4c2f87ebc0992ab177fba51db92c2a")
    pa = aead_ref.X25519PrivateKey(ka).public_key()
    assert pa.public_bytes_raw().hex() == (
        "8520f0098930a754748b7ddcb43ef75a"
        "0dbf3a0d26381af4eba4a98eaa9b4e6a")
    pb = aead_ref.X25519PublicKey.from_public_bytes(bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece43537"
        "3f8343c85b78674dadfc7e146f882b4f"))
    sa = aead_ref.X25519PrivateKey(ka).exchange(pb)
    assert sa.hex() == ("4a5d9d5ba4ce2de1728e3bf480350f25"
                        "e07e21c947d19e3376f09b3c1e161742")
    # fresh-keypair agreement property
    x, y = (aead_ref.X25519PrivateKey.generate() for _ in range(2))
    assert x.exchange(y.public_key()) == y.exchange(x.public_key())


def test_chacha20poly1305_rfc8439_vector():
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (b"Ladies and Gentlemen of the class of '99: If I could "
          b"offer you only one tip for the future, sunscreen would "
          b"be it.")
    a = aead_ref.ChaCha20Poly1305(key)
    ct = a.encrypt(nonce, pt, aad)
    assert ct[-16:].hex() == "1ae10b594f09e26a7e902ecbd0600691"
    assert a.decrypt(nonce, ct, aad) == pt
    with pytest.raises(aead_ref.InvalidTag):
        a.decrypt(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), aad)
    with pytest.raises(aead_ref.InvalidTag):
        a.decrypt(nonce, ct, b"wrong-aad")


def test_hkdf_rfc5869_case1():
    okm = aead_ref.hkdf_sha256(
        ikm=b"\x0b" * 22,
        salt=bytes.fromhex("000102030405060708090a0b0c"),
        info=bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"),
        length=42,
    )
    assert okm.hex() == (
        "3cb25f25faacd57a90434f64d0362f2a"
        "2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865")


def test_ed25519_sign_fallback_matches_ref():
    """PrivKey signing (whatever backend) must equal the RFC 8032
    reference implementation bit-for-bit."""
    from cometbft_tpu.crypto import ed25519_ref as ed
    from cometbft_tpu.crypto.keys import PrivKey

    seed = b"\x3c" * 32
    pk = PrivKey.generate(seed)
    assert pk.pub_key().data == ed.pubkey_from_seed(seed)
    for msg in (b"", b"x", b"hello world" * 100):
        assert pk.sign(msg) == ed.sign(seed, msg)


def test_secp256k1_sign_verify_roundtrip():
    """The host signer (OpenSSL or RFC 6979 fallback) produces low-S
    signatures the pure oracle accepts."""
    from cometbft_tpu.crypto import secp256k1_ref as s

    d = 0x1234_5678_9ABC_DEF0_1111
    pub = s.pubkey_from_secret(d)
    assert len(pub) == 33 and pub[0] in (2, 3)
    sig = s.sign(d, b"fallback")
    assert int.from_bytes(sig[32:], "big") <= s.HALF_N
    assert s.verify(pub, b"fallback", sig)
    assert s.verify_py(pub, b"fallback", sig)
    assert not s.verify(pub, b"other", sig)


def test_secret_connection_over_fallback_or_openssl():
    """The STS handshake works with whichever AEAD backend is loaded
    (socketpair round trip incl. multi-frame messages + tamper)."""
    import socket
    import threading

    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.p2p.conn.secret_connection import SecretConnection

    a, b = socket.socketpair()
    pva = PrivKey.generate(b"\x01" * 32)
    pvb = PrivKey.generate(b"\x02" * 32)
    res = {}
    t = threading.Thread(
        target=lambda: res.update(s=SecretConnection.handshake(b, pvb))
    )
    t.start()
    ca = SecretConnection.handshake(a, pva)
    t.join(timeout=10)
    cb = res["s"]
    assert ca.remote_pub.data == pvb.pub_key().data
    assert cb.remote_pub.data == pva.pub_key().data
    msg = b"ping" * 700  # > 2 frames
    ca.write_msg(msg)
    assert cb.read_msg() == msg
    cb.write_msg(b"")
    assert ca.read_msg() == b""
    a.close()
    b.close()
