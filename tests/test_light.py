"""Light client: adjacent/non-adjacent verification, bisection across
validator-set churn, witness divergence, trusting-period expiry.

Mirrors light/client_test.go + light/verifier_test.go case structure with
an in-process chain generator standing in for the RPC providers.
"""
import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.light import client as lc
from cometbft_tpu.light import verifier as lv
from cometbft_tpu.types import canonical, validation
from cometbft_tpu.types.block import Header
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet

CHAIN_ID = "light-chain"
T0 = 1_700_000_000


def keys_for(tag, n):
    return [
        PrivKey.generate(bytes([tag, i + 1]) + b"\x07" * 30)
        for i in range(n)
    ]


class LightChain:
    """Deterministic chain builder: vals_plan[h] is the key list whose set
    signs height h; headers carry correct validators/next_validators
    hashes so adjacent links and bisection behave like the real chain."""

    def __init__(self, vals_plan):
        self.plan = vals_plan  # dict height -> list[PrivKey]
        self.max_height = max(vals_plan)
        self.blocks = {}
        prev_bid = BlockID()
        for h in range(1, self.max_height + 1):
            privs = self.plan[h]
            nxt = self.plan.get(h + 1, privs)
            vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
            nvs = ValidatorSet([Validator(p.pub_key(), 10) for p in nxt])
            header = Header(
                chain_id=CHAIN_ID, height=h,
                time=Timestamp(T0 + h, 0),
                last_block_id=prev_bid,
                validators_hash=vs.hash(),
                next_validators_hash=nvs.hash(),
                proposer_address=vs.validators[0].address,
                app_hash=b"\x01" * 32,
            )
            bid = BlockID(header.hash(), PartSetHeader(1, header.hash()))
            by_addr = {p.pub_key().address(): p for p in privs}
            sigs = []
            for v in vs.validators:
                ts = Timestamp(T0 + h, 42)
                sb = canonical.canonical_vote_bytes(
                    CHAIN_ID, canonical.PRECOMMIT_TYPE, h, 0, bid, ts
                )
                sigs.append(CommitSig(
                    BLOCK_ID_FLAG_COMMIT, v.address, ts,
                    by_addr[v.address].sign(sb),
                ))
            self.blocks[h] = lv.LightBlock(
                lv.SignedHeader(header, Commit(h, 0, bid, sigs)), vs
            )
            prev_bid = bid

    def provider(self):
        return lc.Provider(CHAIN_ID, lambda h: self.blocks.get(h))


NOW = Timestamp(T0 + 1000, 0)


def make_client(chain, **kw):
    kw.setdefault("trusting_period", 1e6)
    kw.setdefault("batch_fn", validation.oracle_batch_fn())
    c = lc.Client(CHAIN_ID, chain.provider(), **kw)
    c.trust_light_block(chain.blocks[1])
    return c


def test_skipping_one_jump_stable_valset():
    """Stable validator set: one non-adjacent verification reaches the
    target (the whole point of skipping mode)."""
    keys = keys_for(1, 4)
    chain = LightChain({h: keys for h in range(1, 21)})
    c = make_client(chain)
    lb = c.verify_light_block_at_height(20, now=NOW)
    assert lb.height == 20
    assert c.verifications == 1


def test_sequential_walks_every_height():
    keys = keys_for(1, 4)
    chain = LightChain({h: keys for h in range(1, 11)})
    c = make_client(chain, skipping=False)
    c.verify_light_block_at_height(10, now=NOW)
    assert c.verifications == 9
    assert c.store.heights() == list(range(1, 11))


def test_bisection_across_full_valset_rotation():
    """Heights 1-10 signed by era A, 11-20 by a disjoint era B: a direct
    jump fails the 1/3-trust check and bisection + the adjacent
    next-validators link must carry the client across (client.go:706)."""
    a, b = keys_for(1, 4), keys_for(2, 4)
    plan = {h: (a if h <= 10 else b) for h in range(1, 21)}
    chain = LightChain(plan)
    c = make_client(chain)
    lb = c.verify_light_block_at_height(20, now=NOW)
    assert lb.height == 20
    # must have passed through the era boundary via the adjacent link
    assert 11 in c.store.heights()
    assert c.verifications > 2


def test_gradual_churn_skips_far():
    """Replacing one of 6 validators every 3 heights keeps >1/3 overlap on
    moderate jumps — skipping should NOT need every height."""
    base = keys_for(3, 8)
    plan = {}
    cur = list(base)
    for h in range(1, 31):
        if h % 3 == 0:
            cur = cur[1:] + [keys_for(10 + h, 1)[0]]
        plan[h] = list(cur)
    chain = LightChain(plan)
    c = make_client(chain)
    c.verify_light_block_at_height(30, now=NOW)
    assert c.verifications < 29  # strictly better than sequential


def test_expired_trusted_header_rejected():
    keys = keys_for(1, 4)
    chain = LightChain({h: keys for h in range(1, 6)})
    c = make_client(chain, trusting_period=10.0)
    with pytest.raises(lv.ErrOldHeaderExpired):
        c.verify_light_block_at_height(5, now=Timestamp(T0 + 1000, 0))


def test_witness_divergence_detected():
    keys = keys_for(1, 4)
    chain = LightChain({h: keys for h in range(1, 6)})
    forged = LightChain({h: keys_for(9, 4) for h in range(1, 6)})
    c = lc.Client(
        CHAIN_ID, chain.provider(),
        witnesses=[forged.provider()],
        trusting_period=1e6, batch_fn=validation.oracle_batch_fn(),
    )
    c.trust_light_block(chain.blocks[1])
    with pytest.raises(lc.DivergenceError):
        c.verify_light_block_at_height(5, now=NOW)


def test_tampered_target_rejected():
    keys = keys_for(1, 4)
    chain = LightChain({h: keys for h in range(1, 6)})
    # swap height 5's commit sigs for garbage
    lb = chain.blocks[5]
    bad_sigs = [
        CommitSig(cs.flag, cs.validator_address, cs.timestamp, bytes(64))
        for cs in lb.signed_header.commit.signatures
    ]
    chain.blocks[5] = lv.LightBlock(
        lv.SignedHeader(
            lb.signed_header.header,
            Commit(5, 0, lb.signed_header.commit.block_id, bad_sigs),
        ),
        lb.validator_set,
    )
    c = make_client(chain)
    with pytest.raises(lv.ErrInvalidHeader):
        c.verify_light_block_at_height(5, now=NOW)


def test_backwards_verification():
    """Heights below the trust root verify via the last_block_id hash
    chain (light/client.go:734)."""
    keys = keys_for(7, 4)
    chain = LightChain({h: keys for h in range(1, 9)})
    c = lc.Client(CHAIN_ID, chain.provider(), trusting_period=1e6,
                  batch_fn=validation.oracle_batch_fn())
    c.trust_light_block(chain.blocks[6])
    lb = c.verify_light_block_at_height(2, now=NOW)
    assert lb.signed_header.header.height == 2
    assert lb.signed_header.header.hash() == \
        chain.blocks[2].signed_header.header.hash()
    # a tampered intermediate header breaks the chain walk
    import copy

    chain2 = LightChain({h: keys for h in range(1, 9)})
    bad = copy.deepcopy(chain2.blocks[3])
    bad.signed_header.header.app_hash = b"\x99" * 32
    chain2.blocks[3] = bad
    c2 = lc.Client(CHAIN_ID, chain2.provider(), trusting_period=1e6,
                   batch_fn=validation.oracle_batch_fn())
    c2.trust_light_block(chain2.blocks[6])
    with pytest.raises(lc.LightClientError):
        c2.verify_light_block_at_height(2, now=NOW)


def test_divergence_produces_attack_evidence():
    """A forged witness fork yields LightClientAttackEvidence naming the
    byzantine signers (detector.go -> types/evidence.go:193)."""
    keys = keys_for(9, 4)
    chain = LightChain({h: keys for h in range(1, 6)})
    # witness serves a conflicting chain signed by the SAME validators
    fork = LightChain({h: keys for h in range(1, 6)})
    fork.blocks[4].signed_header.header.app_hash = b"\x66" * 32
    # re-sign the forged header so the commit is internally consistent
    hdr = fork.blocks[4].signed_header.header
    hdr_hash = hdr.hash()
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    bid = BlockID(hdr_hash, PartSetHeader(1, hdr_hash))
    by_addr = {p.pub_key().address(): p for p in keys}
    sigs = []
    vs = fork.blocks[4].validator_set
    for v in vs.validators:
        ts = Timestamp(T0 + 4, 42)
        sb = canonical.canonical_vote_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, 4, 0, bid, ts
        )
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                              by_addr[v.address].sign(sb)))
    fork.blocks[4] = lv.LightBlock(
        lv.SignedHeader(hdr, Commit(4, 0, bid, sigs)), vs
    )

    collected = []
    c = make_client(chain)
    c.witnesses = [fork.provider()]
    c.on_attack_evidence = collected.append
    with pytest.raises(lc.DivergenceError) as ei:
        c.verify_light_block_at_height(4, now=NOW)
    ev = ei.value.evidence
    assert ev is not None and ev.conflicting_height == 4
    assert len(ev.byzantine_validators) == 4  # all signed the fork
    assert collected and collected[0] is ev
    ev.validate_basic()


def test_persistent_store_roundtrip(tmp_path):
    """light/store/db/db.go: save/get/latest/first/prune/size survive a
    store reopen."""
    from cometbft_tpu.light.store import DBStore

    keys = keys_for(9, 4)
    chain = LightChain({h: keys for h in range(1, 8)})
    path = str(tmp_path / "light.db")
    st = DBStore(path)
    for h in (1, 3, 5, 7):
        st.save(chain.blocks[h])
    assert st.size() == 4
    assert st.first_height() == 1
    assert st.latest().height == 7
    st.close()

    st2 = DBStore(path)
    assert st2.heights() == [1, 3, 5, 7]
    lb = st2.get(3)
    assert lb.signed_header.header.hash() == \
        chain.blocks[3].signed_header.header.hash()
    assert lb.validator_set.hash() == chain.blocks[3].validator_set.hash()
    # commit sigs survive byte-exact (they re-verify)
    lb.validate_basic(CHAIN_ID)
    st2.prune(2)
    assert st2.heights() == [5, 7]
    st2.delete(5)
    assert st2.heights() == [7]
    st2.close()


def test_client_resumes_from_persisted_trust(tmp_path):
    """Restarting a client on the same DB keeps the trust root: no
    trust_light_block call needed, bisection proceeds from the stored
    latest (the VERDICT r4 gap: volatile trust defeats the trust-period
    model across restarts)."""
    from cometbft_tpu.light.store import DBStore

    keys = keys_for(11, 4)
    chain = LightChain({h: keys for h in range(1, 31)})
    path = str(tmp_path / "light.db")

    c1 = lc.Client(CHAIN_ID, chain.provider(), trusting_period=1e6,
                   batch_fn=validation.oracle_batch_fn(),
                   store=DBStore(path))
    c1.trust_light_block(chain.blocks[1])
    c1.verify_light_block_at_height(15, now=NOW)
    c1.store.close()

    # "restart": fresh client, same db, NO trust bootstrap
    c2 = lc.Client(CHAIN_ID, chain.provider(), trusting_period=1e6,
                   batch_fn=validation.oracle_batch_fn(),
                   store=DBStore(path))
    assert c2.store.latest().height == 15
    lb = c2.verify_light_block_at_height(30, now=NOW)
    assert lb.height == 30
    # and the new verification persisted too
    c2.store.close()
    assert DBStore(path).latest().height == 30


def test_proxy_refuses_expired_root_without_pinned_hash(tmp_path):
    """ADVICE r5 low: a light proxy whose PERSISTED trust root has aged
    past the trusting period must refuse to silently re-root on the
    primary (trust-on-first-use) unless the operator explicitly opted
    into the insecure mode or pinned a hash."""
    from cometbft_tpu.light.proxy import LightProxy, LightProxyError
    from cometbft_tpu.light.store import DBStore

    keys = keys_for(21, 3)
    chain = LightChain({h: keys for h in range(1, 6)})
    path = str(tmp_path / "light.db")
    st = DBStore(path)
    st.save(chain.blocks[3])  # T0-era root: years older than 14 days
    st.close()

    proxy = LightProxy(
        CHAIN_ID, "http://127.0.0.1:1",  # never contacted
        db_path=path,
    )
    try:
        with pytest.raises(LightProxyError, match="trusting period"):
            proxy._ensure_trust()
    finally:
        proxy.httpd.server_close()


def test_proxy_reroots_expired_root_when_explicitly_insecure(tmp_path):
    """The escape hatch: insecure_allow_reroot=True restores the old
    TOFU-with-warning behavior for dev setups."""
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.light.store import DBStore

    keys = keys_for(22, 3)
    chain = LightChain({h: keys for h in range(1, 6)})
    path = str(tmp_path / "light.db")
    st = DBStore(path)
    st.save(chain.blocks[3])
    st.close()

    proxy = LightProxy(
        CHAIN_ID, "http://127.0.0.1:1",
        trusted_height=5,
        db_path=path,
        insecure_allow_reroot=True,
    )
    try:
        # serve the "primary" from the in-process chain: the proxy
        # re-roots on its height-5 block without raising
        proxy.client.primary = chain.provider()
        proxy._ensure_trust()
        assert proxy.client.store.latest().height == 5
    finally:
        proxy.httpd.server_close()


def test_proxy_accepts_pinned_hash_reroot(tmp_path):
    """An operator-pinned --trusted-hash re-roots an expired store
    securely (and a WRONG pin is rejected)."""
    from cometbft_tpu.light.proxy import LightProxy, LightProxyError
    from cometbft_tpu.light.store import DBStore

    keys = keys_for(23, 3)
    chain = LightChain({h: keys for h in range(1, 6)})
    path = str(tmp_path / "light.db")
    st = DBStore(path)
    st.save(chain.blocks[2])
    st.close()

    good = chain.blocks[4].signed_header.header.hash()
    proxy = LightProxy(
        CHAIN_ID, "http://127.0.0.1:1",
        trusted_height=4, trusted_hash=good, db_path=path,
    )
    try:
        proxy.client.primary = chain.provider()
        proxy._ensure_trust()
        assert proxy.client.store.latest().height == 4
    finally:
        proxy.httpd.server_close()

    proxy2 = LightProxy(
        CHAIN_ID, "http://127.0.0.1:1",
        trusted_height=4, trusted_hash=b"\x13" * 32,
        db_path=str(tmp_path / "light2.db"),
    )
    try:
        proxy2.client.primary = chain.provider()
        with pytest.raises(LightProxyError, match="mismatch"):
            proxy2._ensure_trust()
    finally:
        proxy2.httpd.server_close()


def test_client_concurrent_access_hammer():
    """ISSUE 8 satellite: the gateway shares ONE Client across serving
    threads — hammer it: K threads bisecting random targets while
    another thread prunes, with no lost verification counts, no
    exceptions, and a store whose every block still matches the chain.
    The device-verify wait runs unlocked (coalesced flushes overlap),
    so this is exactly the concurrency shape the gateway produces."""
    import random
    import threading

    keys = keys_for(31, 3)
    chain = LightChain({h: keys for h in range(1, 25)})
    c = make_client(chain)
    targets = [6, 12, 18, 24]
    errs = []
    lock = threading.Lock()
    K = 8
    barrier = threading.Barrier(K + 1)

    def worker(seed):
        rng = random.Random(seed)
        try:
            barrier.wait()
            for t in rng.sample(targets, len(targets)):
                lb = c.verify_light_block_at_height(t, now=NOW)
                assert lb.height == t
                assert lb.signed_header.header.hash() == \
                    chain.blocks[t].signed_header.header.hash()
        except Exception as e:  # noqa: BLE001 - asserted below
            with lock:
                errs.append(repr(e))

    def pruner():
        barrier.wait()
        for _ in range(20):
            c.prune_expired(now=NOW)  # nothing expired: exercises the
            # heights()/get()/delete() walk against concurrent saves

    threads = [threading.Thread(target=worker, args=(1000 + k,))
               for k in range(K)] + [threading.Thread(target=pruner)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:3]
    # every stored block is byte-honest chain state
    for h in c.store.heights():
        assert c.store.get(h).signed_header.header.hash() == \
            chain.blocks[h].signed_header.header.hash()
    # the locked counter lost no increments: every verification that
    # saved a NEW height counted at least once, and the counter is at
    # least the number of distinct verified heights
    assert c.verifications >= len([h for h in c.store.heights()
                                   if h > 1])
    # atomic anchor scan used by backwards verification
    assert c.store.lowest_at_or_above(7).height in c.store.heights()


def test_proxy_rides_mounted_gateway():
    """ISSUE 8 satellite: with a light-client gateway mounted, the
    proxy's verify path routes through the SHARED gateway verifier —
    one TrustedStore for both — and trust bookkeeping is the
    gateway's. The legacy standalone path stays available behind the
    gateway=False flag."""
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.lightgate import LightGateway, set_global_gateway

    keys = keys_for(33, 3)
    chain = LightChain({h: keys for h in range(1, 11)})
    gw = LightGateway(CHAIN_ID, chain.provider(), trusting_period=1e9,
                      batch_fn=validation.oracle_batch_fn())
    gw.client.trust_light_block(chain.blocks[1])
    gw.start()
    proxy = LightProxy(CHAIN_ID, "http://127.0.0.1:1")  # never dialed
    try:
        # shared verifier: the proxy's client IS the gateway's client
        assert proxy.client is gw.client
        out = proxy.commit(height=7)
        assert out["verified"] is True
        # the verification landed in the ONE shared store — a gateway
        # request for the same height is now a pure store hit
        assert 7 in gw.client.store.heights()
        v = gw.verify(1, 7)
        assert v["verify_steps"] == 0
        # _ensure_trust with a pin re-checks against the shared view
        proxy._trusted_height = 3
        proxy._trusted_hash = b"\x13" * 32
        from cometbft_tpu.light.proxy import LightProxyError

        with pytest.raises(LightProxyError, match="mismatch"):
            proxy._ensure_trust()
        proxy._trusted_hash = \
            chain.blocks[3].signed_header.header.hash()
        proxy._ensure_trust()  # correct pin passes
    finally:
        gw.stop()
        set_global_gateway(None)
        proxy.httpd.server_close()

    # unmounted again: the proxy is back on its own standalone client
    assert proxy.client is proxy._own_client

    # and the legacy flag pins standalone even WITH a gateway mounted
    gw2 = LightGateway(CHAIN_ID, chain.provider(), trusting_period=1e9,
                       batch_fn=validation.oracle_batch_fn())
    gw2.client.trust_light_block(chain.blocks[1])
    gw2.start()
    legacy = LightProxy(CHAIN_ID, "http://127.0.0.1:1", gateway=False)
    try:
        assert legacy.client is legacy._own_client
    finally:
        gw2.stop()
        set_global_gateway(None)
        legacy.httpd.server_close()
