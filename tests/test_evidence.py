"""Evidence end-to-end: equivocation -> pool -> block -> committed.

Reference strategy: evidence/pool_test.go + e2e evidence injection
(test/e2e/runner/evidence.go) — a byzantine double-signer's conflicting
votes must end up as DuplicateVoteEvidence inside a committed block.
"""
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.evidence.pool import EvidencePool
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def _mk_vote(priv, vals, height, round_, bid, chain_id):
    addr = priv.pub_key().address()
    idx, _ = vals.get_by_address(addr)
    v = Vote(
        vote_type=canonical.PREVOTE_TYPE, height=height, round=round_,
        block_id=bid, timestamp=Timestamp(1_700_000_100, 0),
        validator_address=addr, validator_index=idx,
    )
    v.signature = priv.sign(v.sign_bytes(chain_id))
    return v


def _mk_evidence(priv, vals, height, chain_id, power=10):
    bid_a = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xaa" * 32))
    bid_b = BlockID(b"\xbb" * 32, PartSetHeader(1, b"\xbb" * 32))
    va = _mk_vote(priv, vals, height, 0, bid_a, chain_id)
    vb = _mk_vote(priv, vals, height, 0, bid_b, chain_id)
    return DuplicateVoteEvidence.from_votes(
        va, vb, Timestamp(1_700_000_000, 0),
        vals.total_voting_power(), power,
    ), va, vb


def test_pool_verify_and_lifecycle():
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    pool = EvidencePool("ev-chain", lambda h: vals)
    ev, va, vb = _mk_evidence(privs[0], vals, 5, "ev-chain")
    assert pool.add_evidence(ev)
    assert not pool.add_evidence(ev)  # dedupe
    assert pool.pending_evidence() == [ev]
    pool.check_evidence([ev])  # proposed-block check passes
    pool.mark_committed(6, 1_700_000_010, [ev])
    assert pool.pending_evidence() == []
    with pytest.raises(EvidenceError):
        pool.check_evidence([ev])  # already committed

    # forged power snapshot rejected
    bad, _, _ = _mk_evidence(privs[1], vals, 5, "ev-chain", power=99)
    with pytest.raises(EvidenceError):
        pool.add_evidence(bad)


def _mk_lca(privs, vals, byz_idxs, height, chain_id="ev-chain"):
    """A verifiable light-client-attack evidence via the simnet actor
    (forged header + commit signed by the byzantine coalition)."""
    from cometbft_tpu.simnet.actors import build_light_attack

    return build_light_attack(privs, vals, chain_id, byz_idxs, height,
                              Timestamp(1_700_000_100, 0))


def test_lca_pool_lifecycle():
    """LightClientAttackEvidence mirrors the duplicate-vote pool cases:
    add/verify, dedupe, pending, proposed-block check, commit, expiry
    (ISSUE 3 satellite)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    pool = EvidencePool("ev-chain", lambda h: vals)
    ev = _mk_lca(privs, vals, [1, 2], 5)
    assert pool.add_evidence(ev)
    assert not pool.add_evidence(ev)  # dedupe
    assert pool.pending_evidence() == [ev]
    pool.check_evidence([ev])  # proposed-block check passes
    pool.mark_committed(6, 1_700_000_110, [ev])
    assert pool.pending_evidence() == []
    with pytest.raises(EvidenceError):
        pool.check_evidence([ev])  # already committed

    # expiry: both age bounds exceeded -> silently refused
    pool2 = EvidencePool("ev-chain", lambda h: vals,
                         max_age_blocks=10, max_age_seconds=100)
    pool2.mark_committed(500, 1_800_000_000, [])
    old = _mk_lca(privs, vals, [1, 2], 3)
    assert not pool2.add_evidence(old)


def test_lca_verification_rejects_forgeries():
    """Invalid attacks must not enter the pool: wrong power snapshot,
    innocent validators named byzantine, sub-1/3 coalitions, and
    proof-less evidence."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    pool = EvidencePool("ev-chain", lambda h: vals)

    bad_power = _mk_lca(privs, vals, [1, 2], 5)
    bad_power.total_voting_power = 99
    with pytest.raises(EvidenceError, match="total power"):
        pool.add_evidence(bad_power)

    innocent = _mk_lca(privs, vals, [1, 2], 5)
    innocent.byzantine_validators.append(
        privs[0].pub_key().address())  # did not sign the fork
    with pytest.raises(EvidenceError, match="did not sign"):
        pool.add_evidence(innocent)

    weak = _mk_lca(privs, vals, [1], 5)  # 10/40 < 1/3
    with pytest.raises(EvidenceError, match="trusting"):
        pool.add_evidence(weak)

    proofless = _mk_lca(privs, vals, [1, 2], 5)
    proofless.conflicting_commit = None
    with pytest.raises(EvidenceError, match="no conflicting commit"):
        pool.add_evidence(proofless)

    # an INNOCENT validator framed via an appended FORGED commit row:
    # the named-byzantine check must verify that row's signature itself
    # (the 1/3-trusting pass early-exits and would never reach it)
    from cometbft_tpu.types.commit import BLOCK_ID_FLAG_COMMIT, CommitSig

    framed = _mk_lca(privs, vals, [1, 2], 5)
    victim_addr = privs[0].pub_key().address()
    vidx, _ = vals.get_by_address(victim_addr)
    framed.conflicting_commit.signatures[vidx] = CommitSig(
        BLOCK_ID_FLAG_COMMIT, victim_addr,
        framed.timestamp, b"\x13" * 64,
    )
    framed.byzantine_validators.append(victim_addr)
    with pytest.raises(EvidenceError, match="FORGED"):
        pool.add_evidence(framed)


def test_lca_attack_level_dedup():
    """The proof commit is malleable (signer subsets, rows past the 1/3
    early-exit), so pool dedup keys on the ATTACK
    (conflicting_header_hash, common_height) — one misbehavior must not
    re-enter pending/committed under a second proof hash."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    pool = EvidencePool("ev-chain", lambda h: vals)
    ev = _mk_lca(privs, vals, [1, 2], 5)
    assert pool.add_evidence(ev)
    # same attack, different (also valid) proof: a 3-signer commit
    variant = _mk_lca(privs, vals, [1, 2, 3], 5)
    variant.byzantine_validators = list(ev.byzantine_validators)
    assert variant.hash() != ev.hash()
    assert not pool.add_evidence(variant)  # deduped at attack level
    # after committing one proof, any variant is "already committed"
    pool.mark_committed(6, 1_700_000_110, [ev])
    assert pool.size() == 0
    assert not pool.add_evidence(variant)
    with pytest.raises(EvidenceError, match="already committed"):
        pool.check_evidence([variant])


def test_lca_serde_roundtrip_keeps_proof():
    """evidence_to_j/from_j (the gossip + block wire form) must carry
    the conflicting-commit proof, and the hash must COVER it — a
    relayer stripping the proof must change the evidence identity (and
    so the enclosing block's evidence_hash), not produce a same-hash
    copy that verifies on some nodes and not others."""
    from cometbft_tpu.types.evidence import (
        LightClientAttackEvidence,
        evidence_from_j,
        evidence_to_j,
    )

    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    ev = _mk_lca(privs, vals, [0, 3], 7)
    back = evidence_from_j(evidence_to_j(ev))
    assert isinstance(back, LightClientAttackEvidence)
    assert back.hash() == ev.hash()
    assert back.conflicting_commit is not None
    assert back.conflicting_commit.block_id.hash == \
        ev.conflicting_header_hash
    # a pool on the other side of the wire verifies the round-tripped form
    pool = EvidencePool("ev-chain", lambda h: vals)
    assert pool.add_evidence(back)
    # identity COVERS the proof: a stripped copy is different evidence
    stripped = evidence_from_j(
        {k: v for k, v in evidence_to_j(ev).items() if k != "commit"}
    )
    assert stripped.hash() != ev.hash()
    assert stripped.conflicting_commit is None
    with pytest.raises(EvidenceError, match="no conflicting commit"):
        pool.check_evidence([stripped])


def test_double_signer_evidence_committed(tmp_path):
    """A byzantine validator's conflicting prevotes are detected by the
    honest nodes, pooled, proposed, and committed into a block whose
    evidence_hash seals them (round-2 verdict item 5)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("byz-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    try:
        byz = privs[3]
        # wait until the net is mid-flight, then double-sign the current
        # height at round 0 with two different block IDs
        assert nodes[0].consensus.wait_for_height(2, timeout=60)
        h = nodes[0].consensus.height
        ev, va, vb = _mk_evidence(byz, vals, h, "byz-chain")
        for n in nodes:
            n.consensus.receive_vote(va)
            n.consensus.receive_vote(vb)
        # some committed block must carry the evidence
        deadline = time.time() + 60
        found = None
        while time.time() < deadline and found is None:
            time.sleep(0.2)
            tip = nodes[0].height()
            for hh in range(max(1, h - 1), tip + 1):
                blk = nodes[0].block_store.load_block(hh)
                if blk is not None and blk.evidence:
                    found = (hh, blk)
                    break
        assert found is not None, "no block carried the evidence"
        hh, blk = found
        from cometbft_tpu.types.block import evidence_hash

        assert blk.header.evidence_hash == evidence_hash(blk.evidence)
        assert blk.evidence[0].vote_a.validator_address == \
            byz.pub_key().address()
        # every node committed the same evidence block and marked the
        # pool accordingly (no re-proposal)
        for n in nodes:
            assert n.consensus.wait_for_height(hh, timeout=60)
            b2 = n.block_store.load_block(hh)
            assert b2 is not None and b2.evidence
            assert b2.header.evidence_hash == blk.header.evidence_hash
    finally:
        for n in nodes:
            n.stop()
