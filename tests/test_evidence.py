"""Evidence end-to-end: equivocation -> pool -> block -> committed.

Reference strategy: evidence/pool_test.go + e2e evidence injection
(test/e2e/runner/evidence.go) — a byzantine double-signer's conflicting
votes must end up as DuplicateVoteEvidence inside a committed block.
"""
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.evidence.pool import EvidencePool
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def _mk_vote(priv, vals, height, round_, bid, chain_id):
    addr = priv.pub_key().address()
    idx, _ = vals.get_by_address(addr)
    v = Vote(
        vote_type=canonical.PREVOTE_TYPE, height=height, round=round_,
        block_id=bid, timestamp=Timestamp(1_700_000_100, 0),
        validator_address=addr, validator_index=idx,
    )
    v.signature = priv.sign(v.sign_bytes(chain_id))
    return v


def _mk_evidence(priv, vals, height, chain_id, power=10):
    bid_a = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xaa" * 32))
    bid_b = BlockID(b"\xbb" * 32, PartSetHeader(1, b"\xbb" * 32))
    va = _mk_vote(priv, vals, height, 0, bid_a, chain_id)
    vb = _mk_vote(priv, vals, height, 0, bid_b, chain_id)
    return DuplicateVoteEvidence.from_votes(
        va, vb, Timestamp(1_700_000_000, 0),
        vals.total_voting_power(), power,
    ), va, vb


def test_pool_verify_and_lifecycle():
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    pool = EvidencePool("ev-chain", lambda h: vals)
    ev, va, vb = _mk_evidence(privs[0], vals, 5, "ev-chain")
    assert pool.add_evidence(ev)
    assert not pool.add_evidence(ev)  # dedupe
    assert pool.pending_evidence() == [ev]
    pool.check_evidence([ev])  # proposed-block check passes
    pool.mark_committed(6, 1_700_000_010, [ev])
    assert pool.pending_evidence() == []
    with pytest.raises(EvidenceError):
        pool.check_evidence([ev])  # already committed

    # forged power snapshot rejected
    bad, _, _ = _mk_evidence(privs[1], vals, 5, "ev-chain", power=99)
    with pytest.raises(EvidenceError):
        pool.add_evidence(bad)


def test_double_signer_evidence_committed(tmp_path):
    """A byzantine validator's conflicting prevotes are detected by the
    honest nodes, pooled, proposed, and committed into a block whose
    evidence_hash seals them (round-2 verdict item 5)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("byz-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    try:
        byz = privs[3]
        # wait until the net is mid-flight, then double-sign the current
        # height at round 0 with two different block IDs
        assert nodes[0].consensus.wait_for_height(2, timeout=60)
        h = nodes[0].consensus.height
        ev, va, vb = _mk_evidence(byz, vals, h, "byz-chain")
        for n in nodes:
            n.consensus.receive_vote(va)
            n.consensus.receive_vote(vb)
        # some committed block must carry the evidence
        deadline = time.time() + 60
        found = None
        while time.time() < deadline and found is None:
            time.sleep(0.2)
            tip = nodes[0].height()
            for hh in range(max(1, h - 1), tip + 1):
                blk = nodes[0].block_store.load_block(hh)
                if blk is not None and blk.evidence:
                    found = (hh, blk)
                    break
        assert found is not None, "no block carried the evidence"
        hh, blk = found
        from cometbft_tpu.types.block import evidence_hash

        assert blk.header.evidence_hash == evidence_hash(blk.evidence)
        assert blk.evidence[0].vote_a.validator_address == \
            byz.pub_key().address()
        # every node committed the same evidence block and marked the
        # pool accordingly (no re-proposal)
        for n in nodes:
            assert n.consensus.wait_for_height(hh, timeout=60)
            b2 = n.block_store.load_block(hh)
            assert b2 is not None and b2.evidence
            assert b2.header.evidence_hash == blk.header.evidence_hash
    finally:
        for n in nodes:
            n.stop()
