"""Verify plane (cometbft_tpu.verifyplane): cross-caller continuous
batching on CPU — coalescing across submitter threads, per-future
verdict correctness against the ed25519_ref oracle, deadline flush,
breaker-open host fallback, queue-overflow backpressure, the
`verifyplane.dispatch` failpoint, and VoteSet quorum through the fused
tally path (ISSUE 2 acceptance criteria). All host-path and fast: the
CPU plane never touches the minutes-to-compile kernels."""
import threading
import time

import numpy as np
import pytest

from cometbft_tpu.crypto import batch as cbatch
from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.verifyplane import (
    PlaneError,
    PlaneQueueFull,
    QuorumGroup,
    VerifyPlane,
    global_plane,
    plane_batch_fn,
    set_global_plane,
)

WINDOW_MS = 25.0


@pytest.fixture(autouse=True)
def clean():
    fp.reset()
    set_global_plane(None)
    cbatch.device_breaker().reset()
    yield
    fp.reset()
    set_global_plane(None)
    cbatch.device_breaker().reset()


@pytest.fixture()
def plane():
    p = VerifyPlane(window_ms=WINDOW_MS, max_batch=256, max_queue=1024)
    p.start()
    yield p
    p.stop()


def make_rows(n=12, seed=40):
    """n ed25519 rows, every 4th signature corrupted; oracle verdicts."""
    privs = [PrivKey.generate(bytes([seed + i]) * 32) for i in range(n)]
    pubs = [p.pub_key() for p in privs]
    msgs = [b"plane-%d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    for i in range(0, n, 4):
        sigs[i] = b"\x5a" * 64
    exp = [ed.verify(p.data, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    assert True in exp and False in exp
    return pubs, msgs, sigs, exp


# -- coalescing + correctness ----------------------------------------------


def test_multithread_coalescing_correctness(plane):
    """Items from >= 2 distinct submitter threads land in ONE dispatched
    batch, and every future resolves to the oracle verdict even with
    valid/invalid rows interleaved."""
    pubs, msgs, sigs, exp = make_rows(12)
    results = {}
    start = threading.Barrier(3)

    def worker(lo, hi):
        start.wait()
        futs = [(i, plane.submit(pubs[i], msgs[i], sigs[i]))
                for i in range(lo, hi)]
        for i, f in futs:
            results[i] = f.result(10.0)[0]

    threads = [threading.Thread(target=worker, args=(k * 4, k * 4 + 4))
               for k in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert [results[i] for i in range(12)] == exp
    # the barrier releases all three submitters inside one window, so at
    # least one flush must have coalesced across threads
    assert any(len(d["tids"]) >= 2 for d in plane.dispatch_log), \
        list(plane.dispatch_log)


def test_deadline_flush_lone_item(plane):
    """A lone submission with no other traffic flushes on the window
    deadline, not never."""
    pubs, msgs, sigs, exp = make_rows(2)
    t0 = time.perf_counter()
    fut = plane.submit(pubs[1], msgs[1], sigs[1])
    got = fut.result(5.0)
    elapsed = time.perf_counter() - t0
    assert got == (exp[1],)
    assert elapsed < 5.0
    assert any(d["rows"] == 1 for d in plane.dispatch_log)


def test_submit_and_wait_batch(plane):
    pubs, msgs, sigs, exp = make_rows(9)
    got = plane.submit_and_wait(pubs, msgs, sigs)
    np.testing.assert_array_equal(got, np.asarray(exp))


# -- breaker interaction ---------------------------------------------------


def oracle_kernel(pub_bytes, msgs, sigs):
    return np.asarray(
        [ed.verify(p, m, s) for p, m, s in zip(pub_bytes, msgs, sigs)]
    )


def test_breaker_open_falls_back_to_host():
    """A device-mode plane whose kernel faults trips the shared breaker;
    verdicts stay oracle-correct throughout, and an OPEN breaker stops
    device dispatch entirely (the armed failpoint would raise)."""
    brk = cbatch.CircuitBreaker(failure_threshold=1, cooldown=30.0)
    p = VerifyPlane(window_ms=5.0, kernels={"ed25519": oracle_kernel},
                    breaker=brk)
    p.start()
    try:
        pubs, msgs, sigs, exp = make_rows(8)
        fp.arm("crypto.device_dispatch", "raise")
        got = p.submit_and_wait(pubs, msgs, sigs)
        np.testing.assert_array_equal(got, np.asarray(exp))
        assert brk.state == "open"
        fires = fp.registry().stats("crypto.device_dispatch")["fires"]
        got = p.submit_and_wait(pubs, msgs, sigs)
        np.testing.assert_array_equal(got, np.asarray(exp))
        # no new device dispatch while open: host path served the flush
        assert fp.registry().stats("crypto.device_dispatch")["fires"] == \
            fires
        assert p.stats()["breaker_state"] == "open"
    finally:
        p.stop()


# -- failpoint + backpressure ----------------------------------------------


def test_dispatch_failpoint_degrades_to_host(plane):
    """An armed verifyplane.dispatch fault degrades the flush to the
    inline host path: futures still resolve with correct verdicts."""
    pubs, msgs, sigs, exp = make_rows(6)
    fp.arm("verifyplane.dispatch", "raise")
    got = plane.submit_and_wait(pubs, msgs, sigs)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert fp.registry().stats("verifyplane.dispatch")["fires"] >= 1


def test_queue_overflow_backpressure():
    """max_queue rows pending -> non-blocking submits raise
    PlaneQueueFull; once the dispatcher drains, everything resolves."""
    p = VerifyPlane(window_ms=1.0, max_batch=1000, max_queue=8)
    p.start()
    try:
        pubs, msgs, sigs, exp = make_rows(10)
        # stall the dispatcher inside a flush so the queue can fill
        fp.arm("verifyplane.dispatch", "delay", arg=1.0, count=1)
        first = p.submit(pubs[9], msgs[9], sigs[9])
        time.sleep(0.2)  # dispatcher is now sleeping in the failpoint
        futs = [p.submit(pubs[i], msgs[i], sigs[i], block=False)
                for i in range(8)]
        with pytest.raises(PlaneQueueFull):
            p.submit(pubs[8], msgs[8], sigs[8], block=False)
        # blocking submit rides out the backpressure instead of raising
        blocked = p.submit(pubs[8], msgs[8], sigs[8], block=True)
        assert blocked.result(10.0) == (exp[8],)
        assert first.result(10.0) == (exp[9],)
        for i, f in enumerate(futs):
            assert f.result(10.0) == (exp[i],)
    finally:
        p.stop()


def test_stop_drains_pending_futures():
    """stop() drains queued submissions (graceful) — a submitter never
    hangs on a stopping plane, and post-stop submits are refused."""
    p = VerifyPlane(window_ms=10_000.0)  # deadline far away: items queue
    p.start()
    pubs, msgs, sigs, exp = make_rows(2)
    fut = p.submit(pubs[1], msgs[1], sigs[1])
    p.stop()
    assert fut.result(1.0) == (exp[1],)
    with pytest.raises(PlaneError):
        p.submit(pubs[0], msgs[0], sigs[0])


def test_stop_under_load_resolves_every_future():
    """ISSUE 3 satellite: stop() racing a crowd of submitters (queued +
    in-flight + backpressure-blocked) must leave NO future unresolved —
    every submitter either gets verdicts or a PlaneError from submit(),
    within a bounded wait. A mid-flush delay failpoint forces the
    in-flight case."""
    p = VerifyPlane(window_ms=1.0, max_batch=64, max_queue=16)
    p.start()
    pubs, msgs, sigs, exp = make_rows(12)
    fp.arm("verifyplane.dispatch", "delay", arg=0.5, count=1)
    outcomes = {}
    start = threading.Barrier(5)

    def worker(k):
        start.wait()
        for i in range(12):
            try:
                fut = p.submit(pubs[i], msgs[i], sigs[i])
            except PlaneError:
                outcomes[(k, i)] = "refused"
                continue
            try:
                outcomes[(k, i)] = fut.result(10.0)[0]
            except PlaneError:
                outcomes[(k, i)] = "failed"

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    start.wait()  # all four submitters racing...
    time.sleep(0.05)
    p.stop()      # ...and the plane stops under them
    for t in threads:
        t.join(timeout=20.0)
    assert not any(t.is_alive() for t in threads), "submitter hung"
    # every accepted submission RESOLVED (verdict or error — no hang),
    # and every verdict that came back matches the oracle
    for (k, i), got in outcomes.items():
        if isinstance(got, bool):
            assert got == exp[i], (k, i)
    assert len(outcomes) == 4 * 12


def test_stop_leftovers_resolve_with_host_verdicts():
    """The leftovers path (plane.py stop()): submissions the dispatcher
    never drained — dead dispatcher simulated by a running plane with no
    thread — resolve via the inline host path with REAL verdicts, and
    counted group tallies still land."""
    p = VerifyPlane(window_ms=1.0)
    # a "running" plane whose dispatcher never existed: everything
    # submitted stays queued — exactly the state stop() must clean up
    p._running = True
    pubs, msgs, sigs, exp = make_rows(6)
    g = QuorumGroup(threshold=15)
    futs = [p.submit(pubs[i], msgs[i], sigs[i], power=10, group=g,
                     counted=True) for i in range(6)]
    assert not any(f.done() for f in futs)
    p.stop()
    for i, f in enumerate(futs):
        assert f.result(5.0) == (exp[i],)
    assert g.tally == 10 * sum(exp)
    assert g.quorum_reached == (g.tally >= 15)


# -- fused quorum tally ----------------------------------------------------


def test_quorum_group_fused_tally(plane):
    """Counted submissions credit the group inside the flush; an
    invalid row keeps its submission's power out of the tally."""
    pubs, msgs, sigs, exp = make_rows(8)
    g = QuorumGroup(threshold=41)
    futs = [plane.submit(pubs[i], msgs[i], sigs[i], power=10, group=g,
                         counted=True) for i in range(8)]
    for f in futs:
        f.result(10.0)
    assert g.tally == 10 * sum(exp)
    assert g.quorum_reached == (g.tally >= 41)


def test_quorum_retract_clears_transient_crossing():
    """A retraction (admission found the vote inadmissible) that drops
    the tally back below threshold clears the quorum event — a
    transient double-count must not leave a phantom 2/3 signal."""
    g = QuorumGroup(threshold=21)
    g.add(10)
    g.add(10)
    assert not g.quorum_reached
    g.add(10)  # duplicate raced in: 30 >= 21, event fires
    assert g.quorum_reached
    g.retract(10)  # admission rejects the duplicate: 20 < 21
    assert not g.quorum_reached and g.tally == 20
    g.add(10)  # a genuine third vote re-crosses
    assert g.quorum_reached


def test_voteset_reaches_quorum_through_plane(plane):
    """Gossiped precommits (vote + extension signatures as ONE
    submission each) coalesce through the plane; the VoteSet's 2/3
    quorum comes out of the fused group tally, and a forged extension
    is rejected without its power standing."""
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.types.vote_set import VoteSet, VoteSetError

    chain = "plane-chain"
    privs = [PrivKey.generate(bytes([i + 61]) * 32) for i in range(4)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    bid = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))

    def mk(i):
        priv = privs[i]
        idx, _ = vs.get_by_address(priv.pub_key().address())
        v = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=5, round=0,
                 block_id=bid, timestamp=Timestamp(1_700_000_000, 0),
                 validator_address=priv.pub_key().address(),
                 validator_index=idx, extension=b"ext")
        v.signature = priv.sign(v.sign_bytes(chain))
        v.extension_signature = priv.sign(v.extension_sign_bytes(chain))
        return v

    set_global_plane(plane)
    vset = VoteSet(chain, 5, 0, canonical.PRECOMMIT_TYPE, vs,
                   ext_enabled=True)
    errs = []
    start = threading.Barrier(3)

    def add(i):
        start.wait()
        try:
            vset.add_vote(mk(i))
        except Exception as e:  # noqa: BLE001 - assert below
            errs.append((i, e))

    threads = [threading.Thread(target=add, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    group = vset._plane_groups[bid.key()]
    assert group.quorum_reached and group.tally == 30
    assert vset.two_thirds_majority() == bid
    # vote + extension rode as one 2-row submission
    assert any(d["rows"] == 2 * d["submissions"]
               for d in plane.dispatch_log), list(plane.dispatch_log)
    # forged extension: rejected, no power credited
    bad = mk(3)
    bad.extension_signature = b"\x01" * 64
    with pytest.raises(VoteSetError, match="extension"):
        vset.add_vote(bad)
    assert group.tally == 30
    # duplicate still returns False (no plane round trip needed)
    assert vset.add_vote(mk(0)) is False
    assert vset.sum == 30


def test_voteset_serial_path_single_pass_when_plane_off():
    """Plane off: vote + extension verify in ONE host pass
    (verify_with_extension), semantics unchanged."""
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.types.vote_set import VoteSet, VoteSetError

    chain = "serial-chain"
    priv = PrivKey.generate(bytes([77]) * 32)
    vs = ValidatorSet([Validator(priv.pub_key(), 10)])
    bid = BlockID(b"\xcc" * 32, PartSetHeader(1, b"\xdd" * 32))
    v = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=3, round=0,
             block_id=bid, timestamp=Timestamp(1_700_000_000, 0),
             validator_address=priv.pub_key().address(),
             validator_index=0, extension=b"e")
    v.signature = priv.sign(v.sign_bytes(chain))
    v.extension_signature = priv.sign(v.extension_sign_bytes(chain))
    vset = VoteSet(chain, 3, 0, canonical.PRECOMMIT_TYPE, vs,
                   ext_enabled=True)
    assert global_plane() is None
    assert vset.add_vote(v)
    assert vset.two_thirds_majority() == bid
    # bad vote signature reported as the vote, not the extension
    v2 = Vote(vote_type=canonical.PRECOMMIT_TYPE, height=3, round=0,
              block_id=BlockID(b"\xee" * 32,
                               PartSetHeader(1, b"\xff" * 32)),
              timestamp=Timestamp(1_700_000_000, 0),
              validator_address=priv.pub_key().address(),
              validator_index=0, extension=b"e",
              signature=b"\x02" * 64,
              extension_signature=b"\x02" * 64)
    vset2 = VoteSet(chain, 3, 0, canonical.PRECOMMIT_TYPE, vs,
                    ext_enabled=True)
    with pytest.raises(VoteSetError, match="invalid vote:"):
        vset2.add_vote(v2)


# -- wiring: crypto.batch, light verifier, config, metrics -----------------


def test_crypto_batch_routes_through_plane(plane):
    pubs, msgs, sigs, exp = make_rows(7)
    set_global_plane(plane)
    before = plane.batches
    got = cbatch.verify_batch(pubs, msgs, sigs)
    np.testing.assert_array_equal(got, np.asarray(exp))
    assert plane.batches > before
    # pinned kernels/breaker stay on the direct path (tests, dispatcher)
    brk = cbatch.CircuitBreaker()
    direct = cbatch.verify_batch(pubs, msgs, sigs,
                                 kernels={"ed25519": oracle_kernel},
                                 breaker=brk)
    np.testing.assert_array_equal(direct, np.asarray(exp))


def test_plane_batch_fn_for_light_verifier(plane):
    assert plane_batch_fn() is None  # no global plane registered
    set_global_plane(plane)
    fn = plane_batch_fn()
    assert fn is not None
    pubs, msgs, sigs, exp = make_rows(5)
    np.testing.assert_array_equal(np.asarray(fn(pubs, msgs, sigs)),
                                  np.asarray(exp))


def test_config_section_and_validation(tmp_path):
    from cometbft_tpu.config.config import (
        Config,
        ConfigError,
        load_config,
        save_config,
    )

    cfg = Config()
    assert cfg.verify_plane.build() is None  # disabled by default
    cfg.verify_plane.enable = True
    cfg.verify_plane.window_ms = 2.5
    cfg.verify_plane.max_batch = 64
    cfg.verify_plane.max_queue = 128
    cfg.validate_basic()
    path = str(tmp_path / "config.toml")
    save_config(cfg, path)
    loaded = load_config(path)
    assert loaded.verify_plane.enable is True
    assert loaded.verify_plane.window_ms == 2.5
    assert loaded.verify_plane.max_queue == 128
    p = loaded.verify_plane.build()
    try:
        assert p is not None and p.window == pytest.approx(0.0025)
    finally:
        p.stop()
    cfg.verify_plane.max_queue = 1  # < max_batch
    with pytest.raises(ConfigError, match="max_queue"):
        cfg.validate_basic()


def test_config_mesh_knobs_roundtrip_and_validation(tmp_path):
    """ISSUE 10: the [verify_plane] mesh knobs load/save/validate and
    reach the plane — a host plane with no mesh configured stays
    single-device (mesh_ndev 0, every ledger record n_dev 1)."""
    from cometbft_tpu.config.config import (
        Config,
        ConfigError,
        load_config,
        save_config,
    )

    cfg = Config()
    cfg.verify_plane.enable = True
    cfg.verify_plane.mesh = True
    cfg.verify_plane.mesh_devices = 4
    cfg.verify_plane.mesh_min_rows = 32
    cfg.validate_basic()
    path = str(tmp_path / "config.toml")
    save_config(cfg, path)
    loaded = load_config(path)
    assert loaded.verify_plane.mesh is True
    assert loaded.verify_plane.mesh_devices == 4
    assert loaded.verify_plane.mesh_min_rows == 32
    p = loaded.verify_plane.build()
    try:
        assert p._mesh_devices == 4
        assert p.mesh_min_rows == 32
    finally:
        p.stop()
    # mesh off: the knob must not reach the plane
    loaded.verify_plane.mesh = False
    p2 = loaded.verify_plane.build()
    try:
        assert p2._mesh_devices is None
    finally:
        p2.stop()
    cfg.verify_plane.mesh_devices = 1
    with pytest.raises(ConfigError, match="mesh_devices"):
        cfg.validate_basic()
    cfg.verify_plane.mesh_devices = 0
    cfg.verify_plane.mesh_min_rows = -1
    with pytest.raises(ConfigError, match="mesh_min_rows"):
        cfg.validate_basic()


def test_config_deck_knobs_roundtrip_and_validation(tmp_path):
    """ISSUE 11: the [verify_plane] flight-deck knobs load/save/
    validate and reach the plane — pipeline_flights sizes the private
    staging pool (flights+1 slots) and half_mesh_rows rides along; a
    host plane has no halves and the deck stays empty."""
    from cometbft_tpu.config.config import (
        Config,
        ConfigError,
        load_config,
        save_config,
    )

    cfg = Config()
    cfg.verify_plane.enable = True
    cfg.verify_plane.pipeline_flights = 2
    cfg.verify_plane.half_mesh_rows = 1024
    cfg.validate_basic()
    path = str(tmp_path / "config.toml")
    save_config(cfg, path)
    loaded = load_config(path)
    assert loaded.verify_plane.pipeline_flights == 2
    assert loaded.verify_plane.half_mesh_rows == 1024
    p = loaded.verify_plane.build()
    try:
        assert p.flights == 2
        assert p.half_mesh_rows == 1024
        assert p._staging.slots == 3  # flights + 1
    finally:
        p.stop()
    cfg.verify_plane.pipeline_flights = 0
    with pytest.raises(ConfigError, match="pipeline_flights"):
        cfg.validate_basic()
    cfg.verify_plane.pipeline_flights = 1
    cfg.verify_plane.half_mesh_rows = -1
    with pytest.raises(ConfigError, match="half_mesh_rows"):
        cfg.validate_basic()


def test_deck_stats_and_ledger_columns_on_host_plane():
    """Host flushes are synchronous, so the deck never fills — but
    every surface the TPU deck writes must exist and stay consistent:
    the ledger's airborne/n_host/dev0 columns (with the legacy
    overlapped bool derived at read time), the summary deck block, and
    the stats() deck gauges."""
    from cometbft_tpu.verifyplane import VerifyPlane

    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        pipeline_flights=2)
    plane.start()
    try:
        pubs, msgs, sigs, _ = make_rows(4)
        plane.submit_and_wait(pubs, msgs, sigs)
    finally:
        plane.stop()
    dump = plane.dump_flushes()
    recs = dump["flushes"]
    assert recs
    for r in recs:
        assert r["airborne"] == 0
        assert r["overlapped"] is False  # derived legacy bool
        assert r["n_host"] == 1 and r["dev0"] == 0
    assert dump["summary"]["deck"] == {"airborne_max": 0,
                                       "overlapped_flushes": 0}
    st = plane.stats()
    assert st["flights"] == 2
    assert st["deck_airborne"] == 0 and st["deck_peak"] == 0
    assert st["halves"] == 0


def test_ledger_n_dev_column_on_host_flushes(plane):
    """Every flush record carries the device fan-out column; host/
    single-device flushes stamp n_dev=1 and the summary's shard block
    stays empty — the surfaces /dump_flushes uses to attribute
    cross-chip flushes (the sharded stamping itself is proven in
    tests/test_zshardplane_smoke.py on a forced 4-device host)."""
    pubs, msgs, sigs, _ = make_rows(5)
    plane.submit_and_wait(pubs, msgs, sigs)
    dump = plane.dump_flushes()
    recs = dump["flushes"]
    assert recs and all(r["n_dev"] == 1 for r in recs)
    shard = dump["summary"]["shard"]
    assert shard == {"flushes": 0, "rows": 0, "n_dev_max": 1}
    st = plane.stats()
    assert st["mesh_ndev"] == 0
    assert st["shard_flushes"] == 0 and st["shard_rows"] == 0


def test_plane_metrics_exposed(plane):
    from cometbft_tpu.libs.metrics import NodeMetrics

    m = NodeMetrics()
    plane.metrics = m
    pubs, msgs, sigs, _ = make_rows(4)
    plane.submit_and_wait(pubs, msgs, sigs)
    text = m.expose_text()
    for name in (
        "cometbft_verifyplane_queue_depth",
        "cometbft_verifyplane_batch_rows",
        "cometbft_verifyplane_submit_to_result_seconds",
        "cometbft_verifyplane_padding_waste_total",
        "cometbft_verifyplane_pack_seconds",
        "cometbft_verifyplane_h2d_bytes_total",
        "cometbft_crypto_breaker_open",
    ):
        assert name in text, name
    # the flush recorded a batch and a latency observation
    assert "cometbft_verifyplane_batch_rows_count" in text


def test_plane_pack_metrics_and_overlap_counters(plane):
    """ISSUE 4 satellite: every flush observes its host staging time
    (verifyplane_pack_seconds) and stats() carries the zero-copy
    counters; on the CPU host path nothing is uploaded, so the H2D
    byte counter stays zero."""
    from cometbft_tpu.libs.metrics import NodeMetrics

    m = NodeMetrics()
    plane.metrics = m
    pubs, msgs, sigs, _ = make_rows(6)
    plane.submit_and_wait(pubs, msgs, sigs)
    st = plane.stats()
    assert st["pack_seconds"] > 0.0
    assert st["h2d_bytes"] == 0  # host path: no device staging
    assert st["overlapped"] >= 0
    text = m.expose_text()
    assert "cometbft_verifyplane_pack_seconds_count" in text
    # at least one pack observation landed in the histogram
    count_line = [ln for ln in text.splitlines()
                  if ln.startswith("cometbft_verifyplane_pack_seconds_count")]
    assert count_line and float(count_line[0].split()[-1]) >= 1
