"""Differential tests for the pure-Python ed25519 oracle.

Cross-checked against the `cryptography` (OpenSSL) implementation and the
RFC 8032 test vector, plus the ZIP-215 edge cases that are consensus-critical
(reference: crypto/ed25519/ed25519.go:40-42 verification options).
"""
import os

import pytest

from cometbft_tpu.crypto import ed25519_ref as ed

# Only the OpenSSL cross-check needs the cryptography wheel; the RFC
# 8032 vector and ZIP-215 edge cases below run everywhere — ed25519_ref
# is the consensus-critical verifier AND the breaker's host fallback,
# so its oracle tests must not vanish in wheel-less containers.


RFC8032_SEED = bytes.fromhex(
    "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
)
RFC8032_PUB = bytes.fromhex(
    "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
)
RFC8032_SIG = bytes.fromhex(
    "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
    "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
)


def test_point_double_matches_add():
    B = (ed.BASE[0], ed.BASE[1], 1, ed.BASE[0] * ed.BASE[1] % ed.P)
    assert ed.pt_equal(ed.pt_add(B, B), ed.pt_double(B))


def test_base_point_order():
    B = (ed.BASE[0], ed.BASE[1], 1, ed.BASE[0] * ed.BASE[1] % ed.P)
    assert ed.pt_equal(ed.pt_mul(ed.L, B), ed.IDENT)
    assert not ed.pt_equal(ed.pt_mul(ed.L - 1, B), ed.IDENT)


def test_rfc8032_vector1():
    assert ed.pubkey_from_seed(RFC8032_SEED) == RFC8032_PUB
    assert ed.sign(RFC8032_SEED, b"") == RFC8032_SIG
    assert ed.verify(RFC8032_PUB, b"", RFC8032_SIG)
    assert ed.verify(RFC8032_PUB, b"", RFC8032_SIG, zip215=False)


def test_sign_verify_roundtrip_vs_openssl():
    pytest.importorskip(
        "cryptography",
        reason="OpenSSL differential needs the cryptography wheel",
    )
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    for i in range(20):
        seed = os.urandom(32)
        msg = os.urandom(i * 7)
        pub = ed.pubkey_from_seed(seed)
        sig = ed.sign(seed, msg)
        # our signature verifies under OpenSSL
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        assert (
            sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw) == pub
        )
        sk.public_key().verify(sig, msg)  # raises on failure
        # OpenSSL's signature verifies under ours
        sig2 = sk.sign(msg)
        assert ed.verify(pub, msg, sig2)
        assert ed.verify(pub, msg, sig)


def test_reject_corrupted():
    seed = os.urandom(32)
    msg = b"cometbft tpu"
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, msg)
    for pos in (0, 31, 32, 63):
        bad = bytearray(sig)
        bad[pos] ^= 1
        assert not ed.verify(pub, msg, bytes(bad))
    assert not ed.verify(pub, msg + b"x", sig)
    bad_pub = bytearray(pub)
    bad_pub[5] ^= 1
    # either decompression fails or the equation fails; both must reject
    assert not ed.verify(bytes(bad_pub), msg, sig)


def test_reject_s_out_of_range():
    seed = os.urandom(32)
    msg = b"m"
    pub = ed.pubkey_from_seed(seed)
    sig = ed.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    bad = sig[:32] + int.to_bytes(s + ed.L, 32, "little")
    assert not ed.verify(pub, msg, bad)
    assert not ed.verify(pub, msg, bad, zip215=False)


def test_zip215_noncanonical_y_accepted():
    """An R encoding with y >= p must verify under ZIP-215, not RFC 8032.

    Construct a signature whose R has y in [0, 19) so y + p is a valid
    non-canonical encoding of the same point.
    """
    # point with small y: search a y < 19 that is on the curve
    found = None
    for y in range(19):
        u = (y * y - 1) % ed.P
        v = (ed.D * y * y + 1) % ed.P
        ok, x = ed._sqrt_ratio(u, v)
        if ok:
            found = (x, y)
            break
    assert found is not None
    x, y = found
    enc_canon = int.to_bytes(y | ((x & 1) << 255), 32, "little")
    enc_noncanon = int.to_bytes((y + ed.P) | ((x & 1) << 255), 32, "little")
    p1, c1 = ed.pt_decompress(enc_canon)
    p2, c2 = ed.pt_decompress(enc_noncanon)
    assert p1 is not None and p2 is not None
    assert c1 and not c2
    assert ed.pt_equal(p1, p2)
    p3, _ = ed.pt_decompress(enc_noncanon, zip215=False)
    assert p3 is None


def test_small_order_pubkey_zip215():
    """ZIP-215 accepts signatures under small-order keys when the cofactored
    equation holds; strict mode can differ. We only assert determinism of our
    oracle here: the identity-key signature (R=identity, S=0) verifies in
    ZIP-215 because 8*(0*B - h*A - R) = identity for small-order A, R."""
    ident_enc = ed.pt_compress(ed.IDENT)
    sig = ident_enc + b"\x00" * 32
    assert ed.verify(ident_enc, b"any message", sig)
